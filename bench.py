"""Benchmark harness — prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, "detail": {...}}

Headline (BASELINE.md north star): ResNet-18 / CIFAR10-shape training through
the define-then-run Executor on the real chip, samples/sec/chip, best over
{f32, bf16} x {bs 128, 256} plus bf16 x bs 512 (f32 falls behind well
before bs 512, so that cell is skipped). Round-3 changes: bf16 conv backward
fixed, device-resident dataset slicing (zero per-step H2D), rng folded into
the jit, hard host syncs (block_until_ready reports early on the tunnel).
``detail`` carries each config's samples/s + step ms + MFU (XLA cost-analysis
flops over an assumed peak), the flagship transformer tokens/s, and a
WDL-Criteo run through a real local PS cluster (scheduler + 2 servers,
Hybrid mode) with the prefetch on/off A/B.

Syncs once per timed window: host<->device roundtrips on the tunneled chip
cost ~64ms and must not be counted per step.

vs_baseline: the reference publishes no numbers (BASELINE.md); recorded
baseline = our round-1 f32 measurement (4929.1 samples/s on v5e-1).
"""
import contextlib
import glob
import json
import os
import re
import signal
import sys
import tempfile
import time

import numpy as np

BASELINE_SAMPLES_PER_SEC = 4929.1

# MFU denominator. The bench chip is tunneled (device_kind is opaque), so the
# peak is an assumption, reported alongside: v5e bf16 ~197 TFLOPs/chip.
PEAK_TFLOPS = float(os.environ.get("HETU_PEAK_TFLOPS", "197"))


def _mfu(flops_per_step, step_s):
    if not flops_per_step or not step_s:
        return None
    return flops_per_step / step_s / (PEAK_TFLOPS * 1e12)


_PROFILER = None


def _profiler():
    """``hetu_tpu/telemetry/profiler.py`` loaded by FILE PATH (shared with
    bin/hetuprof): the driver parent must stay jax-free and importing the
    ``hetu_tpu`` package pulls jax. The module is stdlib-only by
    contract."""
    global _PROFILER
    if _PROFILER is None:
        import importlib.util
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "hetu_tpu", "telemetry", "profiler.py")
        spec = importlib.util.spec_from_file_location("_hetuprof", path)
        mod = importlib.util.module_from_spec(spec)
        sys.modules.setdefault("_hetuprof", mod)   # dataclasses need this
        spec.loader.exec_module(sys.modules["_hetuprof"])
        _PROFILER = sys.modules["_hetuprof"]
    return _PROFILER


def _attn_flops(batch, seq, n_layers, d_model, causal):
    """Attention-score matmul FLOPs (the 6ND rule excludes them) — the
    formula lives in hetu_tpu.telemetry.profiler.attn_flops now so hetutop
    reports the same two denominators (docs/ROOFLINE.md)."""
    return _profiler().attn_flops(batch, seq, n_layers, d_model, causal)


def _import_models(suite):
    """Import examples/<suite>/models fresh — the cnn and ctr suites both
    name their package ``models``, so the cached module must be dropped."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "examples", suite)
    if path in sys.path:
        sys.path.remove(path)
    sys.path.insert(0, path)
    for mod in [m for m in sys.modules
                if m == "models" or m.startswith("models.")]:
        del sys.modules[mod]
    import models
    return models


def bench_resnet18(batch_size=128, warmup=5, iters=30, dtype=None):
    # stdout must stay clean: the driver's contract is ONE JSON line, and
    # the example model zoo prints "Building ..." banners
    with contextlib.redirect_stdout(sys.stderr):
        return _bench_resnet18(batch_size, warmup, iters, dtype)


def _bench_resnet18(batch_size, warmup, iters, dtype):
    import hetu_tpu as ht
    models = _import_models("cnn")

    rng = np.random.RandomState(0)
    n = batch_size * 4
    data_x = rng.randn(n, 3, 32, 32).astype(np.float32)
    data_y = np.eye(10, dtype=np.float32)[rng.randint(0, 10, n)]
    x = ht.dataloader_op([ht.Dataloader(data_x, batch_size, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(data_y, batch_size, "train")])
    loss, y = models.resnet18(x, y_, 10)
    opt = ht.optim.MomentumOptimizer(learning_rate=0.1)
    train_op = opt.minimize(loss)
    kwargs = {} if dtype is None else {"dtype": dtype}
    ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.tpu(0), seed=0,
                     **kwargs)

    for _ in range(warmup):
        ex.run("train")
    float(np.mean(ex.run("train")[0].asnumpy()))  # drain the queue

    t0 = time.time()
    for _ in range(iters - 1):
        ex.run("train")
    last = ex.run("train")[0]
    float(np.mean(last.asnumpy()))  # one sync for the whole window
    dt = (time.time() - t0) / iters

    cost = ex.subexecutors["train"].last_cost_analysis() or {}
    flops = cost.get("flops")
    return batch_size / dt, dt * 1000, _mfu(flops, dt)



def bench_introspect_overhead(width=512, batch=512, warmup=None, iters=60,
                              cadence=None):
    """Measured hetuscope introspection overhead (docs/OBSERVABILITY.md
    acceptance: <5% of step time at the default cadence) — two identical
    MLP trainers, introspect off vs on, same shapes/seed, timed back to
    back on CPU (a framework-overhead measurement, so the SECTION_ENV pin
    keeps it off the tunneled chip and deterministic). The on-window pays
    the real amortized cost: 1-in-cadence steps run the stats variant and
    its one extra device fetch."""
    import hetu_tpu as ht
    from hetu_tpu.telemetry import scope as scope_mod

    cadence = cadence or scope_mod.DEFAULT_CADENCE
    if warmup is None:
        warmup = cadence + 5   # must compile BOTH variants of the on-step

    def build(introspect):
        x = ht.Variable(name="x", trainable=False)
        y_ = ht.Variable(name="y_", trainable=False)
        h = x
        for i in range(3):
            w = ht.init.random_normal((width, width), stddev=0.05,
                                      name=f"w{i}")
            h = ht.relu_op(ht.matmul_op(h, w))
        wo = ht.init.random_normal((width, 8), stddev=0.05, name="wo")
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(h, wo), y_), [0])
        train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         seed=0, introspect=introspect)
        rng = np.random.RandomState(0)
        bx = rng.randn(batch, width).astype(np.float32)
        by = np.eye(8, dtype=np.float32)[rng.randint(0, 8, batch)]
        return ex, {x: bx, y_: by}

    def window(introspect):
        ex, feeds = build(introspect)
        for _ in range(warmup):
            ex.run("train", feed_dict=feeds)
        loss = ex.run("train", feed_dict=feeds)[0]
        float(np.mean(loss.asnumpy()))   # drain before the window
        t0 = time.time()
        for _ in range(iters - 1):
            ex.run("train", feed_dict=feeds)
        last = ex.run("train", feed_dict=feeds)[0]
        float(np.mean(last.asnumpy()))   # one sync for the whole window
        return (time.time() - t0) / iters * 1000

    ms_off = window(0)
    scope_mod.shutdown()   # detach the recorder between the A/B arms
    ms_on = window(cadence)
    scope_mod.shutdown()
    return {"step_ms_off": round(ms_off, 4), "step_ms_on": round(ms_on, 4),
            "introspect_overhead_pct": round(
                (ms_on - ms_off) / ms_off * 100, 2),
            "cadence": cadence}


def bench_trail_overhead(batch_size=128, iters=40, rows=5000, width=16,
                         warmup=10, windows=3):
    """hetutrail always-on cost (docs/OBSERVABILITY.md pillar 5 acceptance:
    < 2%/step with the ring enabled): the SAME PS-mode embedding trainer
    against one live cluster, client span ring disarmed vs armed (SetTrail
    A/B on the singleton worker + per-boundary span drain). Interleaved
    best-of-N windows (off/on alternating, min per leg) — run-to-run noise
    on this container (±6%) exceeds the cost being measured, and a
    sequential A/B would land any load drift entirely in the delta.

    Scope caveat: the SERVER-side rings stay armed in both legs (they arm
    from HETU_TRAIL_DIR at spawn; there is no runtime toggle), so the
    delta measures the client ring + drain — the only trail cost on the
    worker's critical path. The server's on-request cost before the
    response is two clock reads (~40 ns); its record+flush run after
    send_msg, off the caller's path."""
    import glob as _glob
    import shutil
    import tempfile
    from hetu_tpu.ps.local_cluster import local_cluster
    tdir = tempfile.mkdtemp(prefix="hetu_trail_bench_")
    saved = os.environ.get("HETU_TRAIL_DIR")
    os.environ["HETU_TRAIL_DIR"] = tdir
    try:
        with local_cluster(n_servers=2, n_workers=1):
            import hetu_tpu as ht

            def build(leg):
                # disjoint server tensor ids per leg (see bench_wdl_ps)
                os.environ["HETU_PS_ID_BASE"] = str(leg * 1000)
                embed = ht.init.random_normal((rows, width), stddev=0.05,
                                              name=f"embed{leg}",
                                              is_embed=True)
                idx = ht.Variable(name="idx", trainable=False)
                y_ = ht.Variable(name="y_", trainable=False)
                vec = ht.embedding_lookup_op(embed, idx)
                flat = ht.array_reshape_op(vec, (-1, 4 * width))
                w = ht.init.random_normal((4 * width, 1), stddev=0.1,
                                          name=f"w{leg}")
                prob = ht.sigmoid_op(ht.matmul_op(flat, w))
                loss = ht.reduce_mean_op(
                    ht.binarycrossentropy_op(prob, y_), [0])
                train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
                ex = ht.Executor({"train": [loss, train_op]},
                                 ctx=ht.cpu(0), comm_mode="Hybrid", seed=0)
                rng = np.random.RandomState(7)
                feeds = {idx: rng.randint(0, rows, (batch_size, 4))
                         .astype(np.float32),
                         y_: rng.randint(0, 2, (batch_size, 1))
                         .astype(np.float32)}
                return ex, feeds

            # leg 1 (env set at build) gets the trail writer; leg 0 is
            # built with the env hidden so its runtime never drains
            os.environ.pop("HETU_TRAIL_DIR", None)
            ex_off, feeds_off = build(0)
            os.environ["HETU_TRAIL_DIR"] = tdir
            ex_on, feeds_on = build(1)

            def window(ex, feeds, armed):
                # re-arm per window: SetTrail state is per-worker (a
                # process singleton), not per-executor. Disarming CLEARS
                # the native ring, so the on-leg's undrained tail must hit
                # its file first or client_spans undercounts.
                if not armed:
                    from hetu_tpu.telemetry import trail as _trail
                    rt = ex_on.ps_runtime
                    if rt.trail_writer is not None:
                        with rt._rpc_lock:
                            _trail.drain_client_spans(rt.comm,
                                                      rt.trail_writer)
                ex.ps_runtime.comm.SetTrail(armed)
                for _ in range(warmup):
                    ex.run("train", feed_dict=feeds)
                t0 = time.time()
                for _ in range(iters - 1):
                    ex.run("train", feed_dict=feeds)
                float(np.mean(ex.run("train",
                                     feed_dict=feeds)[0].asnumpy()))
                return (time.time() - t0) / iters * 1000

            off_windows, on_windows = [], []
            for _ in range(windows):   # interleaved: drift hits both legs
                off_windows.append(window(ex_off, feeds_off, False))
                on_windows.append(window(ex_on, feeds_on, True))
            ms_off, ms_on = min(off_windows), min(on_windows)
            ex_off.close()
            ex_on.close()   # shutdown() drains the ring's tail into the file
            spans = 0
            for p in _glob.glob(os.path.join(tdir,
                                             "trail-client-r*.jsonl")):
                with open(p) as f:
                    spans += sum(1 for line in f if '"kind":"rpc"' in line)
        os.environ.pop("HETU_PS_ID_BASE", None)
        return {"step_ms_off": round(ms_off, 4),
                "step_ms_on": round(ms_on, 4),
                "trail_overhead_pct": round(
                    (ms_on - ms_off) / ms_off * 100, 2),
                "client_spans": spans, "windows": windows}
    finally:
        if saved is None:
            os.environ.pop("HETU_TRAIL_DIR", None)
        else:
            os.environ["HETU_TRAIL_DIR"] = saved
        shutil.rmtree(tdir, ignore_errors=True)


def bench_watch_overhead(width=256, batch=256, iters=40, warmup=None,
                         windows=3, cadence=None):
    """hetuwatch armed cost (docs/OBSERVABILITY.md pillar 6 acceptance:
    <= 2%/step at the default cadence): two identical MLP trainers with
    telemetry AND plan adoption in BOTH arms — the sentinel disarmed vs
    armed — so the delta isolates hetuwatch itself (the residual fold,
    gauge export, SLO latches and the kind:"watch" JSONL row on
    1-in-cadence steps), not the telemetry baseline it rides on.
    Interleaved best-of-N windows (the bench_trail_overhead discipline):
    container noise exceeds the cost being measured, and a sequential A/B
    would land any load drift entirely in the delta. CPU-pinned via
    SECTION_ENV for the same reason."""
    import shutil
    import tempfile
    import hetu_tpu as ht
    from hetu_tpu import telemetry as tel_mod
    from hetu_tpu.graph import executor as ex_mod
    from hetu_tpu.telemetry import watch as watch_mod

    cadence = cadence or watch_mod.DEFAULT_CADENCE
    if warmup is None:
        warmup = cadence + 5   # both arms past compile + one full cadence
    tdir = tempfile.mkdtemp(prefix="hetu_watch_bench_")
    saved = os.environ.get("HETU_TELEMETRY_DIR")
    os.environ["HETU_TELEMETRY_DIR"] = tdir
    try:
        def build(watch):
            x = ht.Variable(name="x", trainable=False)
            y_ = ht.Variable(name="y_", trainable=False)
            h = x
            for i in range(3):
                w = ht.init.random_normal((width, width), stddev=0.05,
                                          name=f"w{i}")
                h = ht.relu_op(ht.matmul_op(h, w))
            wo = ht.init.random_normal((width, 8), stddev=0.05, name="wo")
            loss = ht.reduce_mean_op(
                ht.softmaxcrossentropy_op(ht.matmul_op(h, wo), y_), [0])
            train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
            ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                             seed=0, telemetry="metrics", plan="auto",
                             watch=watch,
                             slo="step_ms<100000" if watch else None)
            rng = np.random.RandomState(0)
            bx = rng.randn(batch, width).astype(np.float32)
            by = np.eye(8, dtype=np.float32)[rng.randint(0, 8, batch)]
            return ex, {x: bx, y_: by}

        ex_off, feeds_off = build(0)
        ex_on, feeds_on = build(cadence)
        assert ex_off.plan_watch is None and ex_on.plan_watch is not None

        def window(ex, feeds):
            for _ in range(warmup):
                ex.run("train", feed_dict=feeds)
            t0 = time.time()
            for _ in range(iters - 1):
                ex.run("train", feed_dict=feeds)
            float(np.mean(ex.run("train",
                                 feed_dict=feeds)[0].asnumpy()))
            return (time.time() - t0) / iters * 1000

        # Direct per-observation stopwatch alongside the A/B: the hook's
        # cost (~0.2 ms) amortized over the cadence is ~0.5% of this
        # container's ~3.7 ms step, BELOW the run-to-run noise an
        # interleaved A/B can resolve here — so record both, headline
        # the amortized number, and keep the A/B as the noise-floor
        # cross-check (the trail cell's 1.3 ms step could resolve its
        # delta; this one cannot).
        observe_ms = []
        orig_observe = ex_mod.SubExecutor._watch_observe

        def timed_observe(self, *a, **k):
            t0 = time.time()
            r = orig_observe(self, *a, **k)
            observe_ms.append((time.time() - t0) * 1000)
            return r

        ex_mod.SubExecutor._watch_observe = timed_observe
        try:
            off_windows, on_windows = [], []
            for _ in range(windows):   # interleaved: drift hits both legs
                off_windows.append(window(ex_off, feeds_off))
                on_windows.append(window(ex_on, feeds_on))
        finally:
            ex_mod.SubExecutor._watch_observe = orig_observe
        ms_off, ms_on = min(off_windows), min(on_windows)
        obs_ms = (sorted(observe_ms)[len(observe_ms) // 2]
                  if observe_ms else 0.0)
        return {"step_ms_off": round(ms_off, 4),
                "step_ms_on": round(ms_on, 4),
                "watch_overhead_pct": round(
                    (ms_on - ms_off) / ms_off * 100, 2),
                "watch_observe_ms": round(obs_ms, 4),
                "watch_amortized_pct": round(
                    obs_ms / cadence / ms_off * 100, 2),
                "cadence": cadence, "windows": windows,
                "observations": ex_on.plan_watch.observations}
    finally:
        tel_mod.shutdown()
        if saved is None:
            os.environ.pop("HETU_TELEMETRY_DIR", None)
        else:
            os.environ["HETU_TELEMETRY_DIR"] = saved
        shutil.rmtree(tdir, ignore_errors=True)


def bench_pilot_overhead(width=64, batch=128, iters=60, warmup=10,
                         windows=4):
    """hetupilot armed-idle cost (docs/FAULT_TOLERANCE.md "Self-tuning
    with guardrails" acceptance: < 1%/step while idle): two identical
    PS-mode dense trainers against ONE live cluster, hetuwatch armed in
    BOTH arms (an SLO the job can never trip, so no recommendation ever
    reaches the controller) — the controller disarmed vs armed — so the
    delta isolates the pilot's steady-state tax: the residual-row feed
    and the per-step boundary walk (governor/pending/verdict checks that
    all fall through). Actuation-era cost is NOT this cell's subject;
    the eras are deliberate, rare, operator-audited events measured by
    tests/test_pilot.py. Interleaved best-of-N windows plus a direct
    stopwatch on Pilot.step_boundary (the watch cell's discipline: the
    cost sits below container noise, so headline the direct reading and
    keep the A/B as the noise-floor cross-check)."""
    import shutil
    import tempfile
    import hetu_tpu as ht
    from hetu_tpu import telemetry as tel_mod
    from hetu_tpu import pilot as pilot_mod
    tdir = tempfile.mkdtemp(prefix="hetu_pilot_bench_")
    saved = {k: os.environ.get(k)
             for k in ("HETU_TELEMETRY_DIR", "HETU_PILOT",
                       "HETU_PILOT_DIR")}
    os.environ["HETU_TELEMETRY_DIR"] = tdir
    os.environ["HETU_PILOT_DIR"] = os.path.join(tdir, "pilot")
    try:
        from hetu_tpu.ps.local_cluster import local_cluster
        with local_cluster(n_servers=1, n_workers=1):
            def build(tag, pilot_on):
                if pilot_on:
                    os.environ["HETU_PILOT"] = "1"
                else:
                    os.environ.pop("HETU_PILOT", None)
                os.environ["HETU_PS_ID_BASE"] = str(tag * 1000)
                x = ht.Variable(name="x", trainable=False)
                y_ = ht.Variable(name="y_", trainable=False)
                w = ht.init.random_normal((width, 8), stddev=0.05,
                                          name=f"w{tag}")
                loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
                    ht.matmul_op(x, w), y_), [0])
                train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
                ex = ht.Executor({"train": [loss, train_op]},
                                 ctx=ht.cpu(0), comm_mode="PS", bsp=True,
                                 prefetch=False, seed=0,
                                 telemetry="metrics", watch=1,
                                 slo="step_ms<100000")
                rng = np.random.RandomState(0)
                bx = rng.randn(batch, width).astype(np.float32)
                by = np.eye(8, dtype=np.float32)[rng.randint(0, 8, batch)]
                return ex, {x: bx, y_: by}

            ex_off, feeds_off = build(1, False)
            ex_on, feeds_on = build(2, True)
            assert ex_off.pilot is None and ex_on.pilot is not None

            def window(ex, feeds):
                for _ in range(warmup):
                    ex.run("train", feed_dict=feeds)
                t0 = time.time()
                for _ in range(iters):
                    ex.run("train", feed_dict=feeds)
                return (time.time() - t0) / iters * 1000

            boundary_ms = []
            orig_boundary = pilot_mod.Pilot.step_boundary

            def timed_boundary(self, *a, **k):
                t0 = time.time()
                r = orig_boundary(self, *a, **k)
                boundary_ms.append((time.time() - t0) * 1000)
                return r

            pilot_mod.Pilot.step_boundary = timed_boundary
            try:
                off_w, on_w = [], []
                for _ in range(windows):   # interleaved: drift hits both
                    off_w.append(window(ex_off, feeds_off))
                    on_w.append(window(ex_on, feeds_on))
            finally:
                pilot_mod.Pilot.step_boundary = orig_boundary
            ms_off, ms_on = min(off_w), min(on_w)
            bd_ms = (sorted(boundary_ms)[len(boundary_ms) // 2]
                     if boundary_ms else 0.0)
            s = pilot_mod.summarize_dir(os.environ["HETU_PILOT_DIR"])
            ex_off.close()
            ex_on.close()
            return {"step_ms_off": round(ms_off, 4),
                    "step_ms_on": round(ms_on, 4),
                    "pilot_overhead_pct": round(
                        (ms_on - ms_off) / ms_off * 100, 2),
                    "pilot_boundary_ms": round(bd_ms, 4),
                    "pilot_amortized_pct": round(bd_ms / ms_off * 100, 2),
                    "eras": (s or {}).get("eras", 0),   # must stay 0
                    "windows": windows}
    finally:
        tel_mod.shutdown()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tdir, ignore_errors=True)


def bench_story_overhead(width=64, batch=128, iters=4000, warmup=400,
                         windows=6, step_iters=40, step_warmup=8):
    """hetustory run-identity stamping cost (docs/OBSERVABILITY.md pillar
    7 acceptance: < 0.5%/step): every JSONL row a heturun job writes now
    carries (run_id, inc). The pair is merged into the sink's
    PRESERIALIZED base-field prefix at Telemetry construction, so the
    per-record cost is writing ~30 extra bytes, not serializing two extra
    fields per step. A/B on the hot step-record path itself — two
    Telemetry instances, stamped vs not, interleaved best-of-N windows
    (the watch/pilot cell discipline: the cost sits far below container
    noise, so headline the direct per-record reading) — then amortized
    against a real dense training step measured in-process."""
    import shutil
    import tempfile
    import hetu_tpu as ht
    from hetu_tpu import telemetry as tel_mod
    tdir = tempfile.mkdtemp(prefix="hetu_story_bench_")
    saved = {k: os.environ.get(k)
             for k in ("HETU_RUN_ID", "HETU_RUN_INCARNATION")}
    phases = {"compute": 1.1, "ps_pull": 0.2, "ps_push": 0.2}
    try:
        os.environ.pop("HETU_RUN_ID", None)
        tel_off = tel_mod.Telemetry(
            "metrics", os.path.join(tdir, "off"), 0)
        os.environ["HETU_RUN_ID"] = "bench-20260101-000000-1"
        os.environ["HETU_RUN_INCARNATION"] = "1"
        tel_on = tel_mod.Telemetry(
            "metrics", os.path.join(tdir, "on"), 0)

        def window(tel, base):
            for i in range(warmup):
                tel.step_record("train", base + i, 1.234, phases=phases)
            tel.sink.flush()
            t0 = time.time()
            for i in range(iters):
                tel.step_record("train", base + warmup + i, 1.234,
                                phases=phases)
            tel.sink.flush()
            return (time.time() - t0) / iters * 1e6   # us/record

        off_w, on_w = [], []
        for k in range(windows):   # interleaved: drift hits both arms
            base = k * (warmup + iters)
            off_w.append(window(tel_off, base))
            on_w.append(window(tel_on, base))
        us_off, us_on = min(off_w), min(on_w)
        with open(os.path.join(tdir, "off", "metrics-r0.jsonl")) as f:
            row_off = len(f.readline())
        with open(os.path.join(tdir, "on", "metrics-r0.jsonl")) as f:
            row_on = len(f.readline())
        tel_off.close()
        tel_on.close()

        # amortize against a real dense training step on this host
        x = ht.Variable(name="x", trainable=False)
        y_ = ht.Variable(name="y_", trainable=False)
        w = ht.init.random_normal((width, 8), stddev=0.05, name="w_story")
        loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(
            ht.matmul_op(x, w), y_), [0])
        train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         seed=0)
        rng = np.random.RandomState(0)
        feeds = {x: rng.randn(batch, width).astype(np.float32),
                 y_: np.eye(8, dtype=np.float32)[
                     rng.randint(0, 8, batch)]}
        for _ in range(step_warmup):
            ex.run("train", feed_dict=feeds)
        t0 = time.time()
        for _ in range(step_iters):
            ex.run("train", feed_dict=feeds)
        ref_step_ms = (time.time() - t0) / step_iters * 1000
        ex.close()
        return {"record_us_off": round(us_off, 3),
                "record_us_on": round(us_on, 3),
                "row_bytes_off": row_off, "row_bytes_on": row_on,
                "ref_step_ms": round(ref_step_ms, 4),
                "story_overhead_pct": round(
                    max(0.0, us_on - us_off) / 1000 / ref_step_ms * 100,
                    4),
                "windows": windows}
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        shutil.rmtree(tdir, ignore_errors=True)


def bench_chaos_hardening(batch_size=128, iters=60, rows=5000, width=16,
                          warmup=10, windows=8):
    """hetuchaos transport-hardening cost (docs/FAULT_TOLERANCE.md
    acceptance: retry/CRC hardening <= 2%/step): the SAME PS-mode
    embedding trainer against one live cluster, CRC32C payload checksums
    off vs on (SetPsCrc A/B on the singleton worker — the kFlagCrc
    negotiation means one client-side toggle flips BOTH legs: request
    verify on the server and response checksum back). Interleaved
    best-of-N windows, min per leg — same noise reasoning as the trail
    cell. The retry/backoff machinery itself costs nothing on a clean
    wire (it only runs after a failure), so CRC compute IS the
    hardening's steady-state price; the cell also records that zero
    retries/rejects happened, pinning that the measured delta is pure
    checksum arithmetic."""
    from hetu_tpu.ps.local_cluster import local_cluster
    with local_cluster(n_servers=2, n_workers=1):
        import hetu_tpu as ht
        embed = ht.init.random_normal((rows, width), stddev=0.05,
                                      name="embed_crc", is_embed=True)
        idx = ht.Variable(name="idx", trainable=False)
        y_ = ht.Variable(name="y_", trainable=False)
        vec = ht.embedding_lookup_op(embed, idx)
        flat = ht.array_reshape_op(vec, (-1, 4 * width))
        w = ht.init.random_normal((4 * width, 1), stddev=0.1, name="w_crc")
        prob = ht.sigmoid_op(ht.matmul_op(flat, w))
        loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_), [0])
        train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         comm_mode="Hybrid", seed=0)
        rng = np.random.RandomState(7)
        feeds = {idx: rng.randint(0, rows, (batch_size, 4))
                 .astype(np.float32),
                 y_: rng.randint(0, 2, (batch_size, 1)).astype(np.float32)}
        comm = ex.ps_runtime.comm

        def window(crc_on):
            comm.SetPsCrc(crc_on)
            for _ in range(warmup):
                ex.run("train", feed_dict=feeds)
            t0 = time.time()
            for _ in range(iters - 1):
                ex.run("train", feed_dict=feeds)
            float(np.mean(ex.run("train", feed_dict=feeds)[0].asnumpy()))
            return (time.time() - t0) / iters * 1000

        off_w, on_w = [], []
        for _ in range(windows):   # interleaved: drift hits both legs
            off_w.append(window(False))
            on_w.append(window(True))
        ms_off, ms_on = min(off_w), min(on_w)
        cs = comm.ClientStats()
        ex.close()
        return {"step_ms_off": round(ms_off, 4),
                "step_ms_on": round(ms_on, 4),
                "crc_overhead_pct": round((ms_on - ms_off) / ms_off * 100,
                                          2),
                # a clean wire: the delta above is checksum math, not
                # retry noise (nonzero here would invalidate the A/B)
                "retries": cs["retries"], "crc_rejects": cs["crc_rejects"],
                "windows": windows}


def bench_snapshot_overhead(batch_size=128, iters=200, rows=5000, width=16,
                            warmup=10, windows=4, snap_every=200):
    """hetusave coordinated-snapshot cost (docs/FAULT_TOLERANCE.md
    acceptance: snapshot stall < 5%/step amortized at the measured
    cadence): the SAME PS-mode embedding trainer against one live
    cluster, with leg B taking a full coordinated job snapshot (quiesce
    barrier + per-server kSnapshotNow + worker pickle + manifest commit)
    every ``snap_every`` steps — the stall is the AMORTIZED per-step
    delta, the number an operator actually pays. Interleaved best-of-N
    windows, min per leg, same noise reasoning as the trail/chaos cells.
    The raw wall time of one snapshot is also reported (from the last
    committed manifest), so the amortization arithmetic is auditable:
    stall% ~= snapshot_wall_ms / (snap_every * step_ms)."""
    import shutil
    import tempfile
    from hetu_tpu.recovery import latest_committed_manifest, \
        take_job_snapshot
    snaproot = tempfile.mkdtemp(prefix="bench_snap_")
    jobdir = tempfile.mkdtemp(prefix="bench_snapjob_")
    saved = os.environ.get("DMLC_PS_SNAPSHOT_DIR")
    os.environ["DMLC_PS_SNAPSHOT_DIR"] = snaproot
    try:
        from hetu_tpu.ps.local_cluster import local_cluster
        with local_cluster(n_servers=2, n_workers=1):
            import hetu_tpu as ht
            embed = ht.init.random_normal((rows, width), stddev=0.05,
                                          name="embed_snap", is_embed=True)
            idx = ht.Variable(name="idx", trainable=False)
            y_ = ht.Variable(name="y_", trainable=False)
            vec = ht.embedding_lookup_op(embed, idx)
            flat = ht.array_reshape_op(vec, (-1, 4 * width))
            w = ht.init.random_normal((4 * width, 1), stddev=0.1,
                                      name="w_snap")
            prob = ht.sigmoid_op(ht.matmul_op(flat, w))
            loss = ht.reduce_mean_op(ht.binarycrossentropy_op(prob, y_),
                                     [0])
            train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
            ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                             comm_mode="PS", seed=0, prefetch=False)
            rng = np.random.RandomState(7)
            feeds = {idx: rng.randint(0, rows, (batch_size, 4))
                     .astype(np.float32),
                     y_: rng.randint(0, 2, (batch_size, 1))
                     .astype(np.float32)}

            def window(snap_on):
                for _ in range(warmup):
                    ex.run("train", feed_dict=feeds)
                n = 0
                t0 = time.time()
                for i in range(iters):
                    ex.run("train", feed_dict=feeds)
                    if snap_on and (i + 1) % snap_every == 0:
                        take_job_snapshot(ex, jobdir)
                        n += 1
                return (time.time() - t0) / iters * 1000, n

            off_w, on_w, n_snaps = [], [], 0
            for _ in range(windows):   # interleaved: drift hits both legs
                off_w.append(window(False)[0])
                ms, n = window(True)
                on_w.append(ms)
                n_snaps += n
            ms_off, ms_on = min(off_w), min(on_w)
            got = latest_committed_manifest(jobdir)
            snap_ms = float(got[0].get("wall_ms", -1)) if got else -1.0
            ex.close()
            return {"step_ms_off": round(ms_off, 4),
                    "step_ms_on": round(ms_on, 4),
                    "snapshot_stall_pct": round(
                        (ms_on - ms_off) / ms_off * 100, 2),
                    "snapshot_wall_ms": round(snap_ms, 3),
                    "snap_every": snap_every, "snapshots": n_snaps,
                    "windows": windows}
    finally:
        if saved is None:
            os.environ.pop("DMLC_PS_SNAPSHOT_DIR", None)
        else:
            os.environ["DMLC_PS_SNAPSHOT_DIR"] = saved
        shutil.rmtree(snaproot, ignore_errors=True)
        shutil.rmtree(jobdir, ignore_errors=True)


def _capture_trace(out, step_twice, trace_dir, label):
    """Post-window jax.profiler capture shared by the LM cells (bert,
    transformer/350): runs AFTER the timed window so tracing overhead
    never pollutes the reported step time. An explicit ``trace_dir`` is
    used as-is; the HETU_BENCH_TRACE env dir gains a per-section
    ``label`` subdir so each cell's flame graph stays attributable."""
    if not trace_dir:
        env = os.environ.get("HETU_BENCH_TRACE")
        trace_dir = os.path.join(env, label) if env else None
    if not trace_dir:
        return
    import jax.profiler
    with jax.profiler.trace(trace_dir):
        step_twice()
    out["trace"] = trace_dir
    # counted in-child: smoke trace dirs are TemporaryDirectories deleted
    # when the section exits, so "did the trace land" must be recorded
    # before cleanup (tests/test_bench_sections.py asserts on it)
    out["trace_files"] = sum(len(fs) for _, _, fs in os.walk(trace_dir))


def bench_bert(batch_size=32, seq_len=512, warmup=3, iters=15, cfg=None,
               trace_dir=None, **cfg_overrides):
    """BERT-base MLM+NSP pretrain step (BASELINE.md north star: 'BERT-base
    pretrain (Pallas attention)'). Dense packed batches -> the fused
    bidirectional flash kernel; tokens/s with BOTH the 6ND and the
    attention-inclusive MFU."""
    import jax
    from hetu_tpu.models import bert

    if cfg is None:
        cfg = bert.BERT_BASE
    if cfg_overrides:
        import dataclasses
        cfg = dataclasses.replace(cfg, **cfg_overrides)
    params = bert.init_params(jax.random.PRNGKey(0), cfg)
    n_params = bert.count_params(params)
    opt = bert.init_opt_state(params)
    step = bert.make_pretrain_step(cfg, mesh=None, lr=1e-4)
    rng = np.random.RandomState(0)
    P = 76  # ~15% of 512
    batch = {
        "input_ids": rng.randint(0, cfg.vocab_size,
                                 (batch_size, seq_len)).astype(np.int32),
        "segment_ids": (rng.rand(batch_size, seq_len) > 0.5).astype(np.int32),
        "mlm_positions": np.sort(rng.randint(
            1, seq_len, (batch_size, P)).astype(np.int32), axis=1),
        "mlm_ids": rng.randint(0, cfg.vocab_size,
                               (batch_size, P)).astype(np.int32),
        "mlm_weights": np.ones((batch_size, P), np.float32),
        "nsp_label": rng.randint(0, 2, (batch_size,)).astype(np.int32),
    }
    def timed(params, opt, batch, n_warm):
        """Warmup then one hard-synced timing window. The float(np.asarray)
        sync matters: block_until_ready does not wait for remote execution
        on the tunneled chip; one transfer per window, not per step."""
        loss = None
        for _ in range(n_warm):
            loss, _, params, opt = step(params, opt, batch)
        float(np.asarray(loss))
        t0 = time.time()
        for _ in range(iters):
            loss, _, params, opt = step(params, opt, batch)
        float(np.asarray(loss))
        return (time.time() - t0) / iters, params, opt

    dt, params, opt = timed(params, opt, batch, warmup)
    tokens = batch_size * seq_len
    flops_6nd = 6.0 * n_params * tokens
    flops_attn = _attn_flops(batch_size, seq_len, cfg.n_layers, cfg.d_model,
                             causal=False)
    from hetu_tpu.models import transformer as tfm
    impl = tfm._resolve_attn_impl(cfg.trunk(), None, seq_len)
    from hetu_tpu.kernels.fused_ce import should_fuse
    fused_ce = should_fuse(cfg.fused_mlm_ce, None)
    out = {"tokens_per_sec": round(tokens / dt, 0),
           "step_ms": round(dt * 1000, 2),
           "mfu_6nd": round(_mfu(flops_6nd, dt), 4),
           "mfu_attn_incl": round(_mfu(flops_6nd + flops_attn, dt), 4),
           "attn_impl": impl,
           "mlm_ce": "fused" if fused_ce else "einsum",
           "n_params": n_params}

    def _two_steps():
        nonlocal params, opt
        loss = None
        for _ in range(2):
            loss, _, params, opt = step(params, opt, batch)
        float(np.asarray(loss))

    _capture_trace(out, _two_steps, trace_dir, "bert")

    # masked A/B: padded batches keep the fused kernel via the key-padding
    # bias (before round 4 a mask forced the unfused (B,nh,T,T) path)
    batch["input_mask"] = (
        np.arange(seq_len)[None, :]
        < rng.randint(seq_len // 2, seq_len + 1, (batch_size, 1))
    ).astype(np.int32)
    dtm, params, opt = timed(params, opt, batch, max(1, warmup - 1))
    bias = jax.numpy.zeros((batch_size, 1, 1, seq_len))
    out["masked"] = {
        "tokens_per_sec": round(tokens / dtm, 0),
        "step_ms": round(dtm * 1000, 2),
        "attn_impl": tfm._resolve_attn_impl(cfg.trunk(), None, seq_len, bias),
    }
    return out


def bench_flash_attention(b=4, h=8, s=4096, d=64, iters=10):
    """Pallas flash kernels vs the unfused reference form at seq 4096
    (fwd and full grad, bf16, hard-synced) — the long-context headline."""
    import jax
    import jax.numpy as jnp
    from hetu_tpu.kernels.flash_attention import flash_attention, mha_reference

    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(b, h, s, d), jnp.bfloat16)
               for _ in range(3))

    out = {}
    for name, fn in (("flash", flash_attention), ("unfused", mha_reference)):
        fwd = jax.jit(lambda q, k, v, f=fn: f(q, k, v, True))
        grad = jax.jit(jax.grad(
            lambda q, k, v, f=fn: jnp.sum(f(q, k, v, True)
                                          .astype(jnp.float32)),
            argnums=(0, 1, 2)))
        float(np.asarray(fwd(q, k, v)[0, 0, 0, 0]))   # compile + sync
        t0 = time.time()
        for _ in range(iters):
            o = fwd(q, k, v)
        float(np.asarray(o[0, 0, 0, 0]))
        fwd_ms = (time.time() - t0) / iters * 1000
        g = grad(q, k, v)
        float(np.asarray(g[0][0, 0, 0, 0]))           # compile + sync
        t0 = time.time()
        for _ in range(iters):
            g = grad(q, k, v)
        float(np.asarray(g[0][0, 0, 0, 0]))
        out[name] = {"fwd_ms": round(fwd_ms, 2),
                     "grad_ms": round((time.time() - t0) / iters * 1000, 2)}
    out["fwd_speedup"] = round(
        out["unfused"]["fwd_ms"] / out["flash"]["fwd_ms"], 2)
    out["grad_speedup"] = round(
        out["unfused"]["grad_ms"] / out["flash"]["grad_ms"], 2)
    return out


def bench_decode(batch=8, prompt_len=16, max_len=256):
    """KV-cache greedy decode throughput on the 38M flagship (inference
    side of the north star; one compiled scan, hard-synced)."""
    import jax
    import jax.numpy as jnp
    from hetu_tpu.models import transformer as tfm
    from hetu_tpu.models import generate as gen

    cfg = tfm.TransformerConfig(vocab_size=8192, d_model=512, n_heads=8,
                                n_layers=8, d_ff=2048, max_seq_len=512)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    fn = gen.make_generate_fn(cfg, max_len=max_len)
    prompt = jnp.asarray(np.random.RandomState(0).randint(
        0, cfg.vocab_size, (batch, prompt_len)), jnp.int32)
    toks, _ = fn(params, prompt, jax.random.PRNGKey(0))   # compile
    np.asarray(toks)
    t0 = time.time()
    toks, _ = fn(params, prompt, jax.random.PRNGKey(1))
    np.asarray(toks)
    dt = time.time() - t0
    new_tokens = batch * (max_len - prompt_len)
    return new_tokens / dt, dt / (max_len - prompt_len) * 1000


def bench_transformer(cfg=None, batch=16, seq=512, warmup=3, iters=20,
                      trace_dir=None, trace_label="transformer",
                      **cfg_overrides):
    import jax
    import jax.numpy as jnp
    from hetu_tpu.models import transformer as tfm

    if cfg is None:
        cfg = tfm.TransformerConfig(vocab_size=8192, d_model=512, n_heads=8,
                                    n_layers=8, d_ff=2048, max_seq_len=512)
    if cfg_overrides:
        import dataclasses
        cfg = dataclasses.replace(cfg, **cfg_overrides)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    n_params = sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
    opt = tfm.init_opt_state(params)
    step = tfm.make_train_step(cfg, mesh=None, lr=3e-4)
    rng = np.random.RandomState(0)
    tok = jnp.asarray(rng.randint(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    tgt = jnp.roll(tok, -1, axis=1)
    for _ in range(warmup):
        loss, params, opt = step(params, opt, tok, tgt)
    float(np.asarray(loss))   # hard sync (see bench_bert)
    t0 = time.time()
    for _ in range(iters):
        loss, params, opt = step(params, opt, tok, tgt)
    float(np.asarray(loss))
    dt = (time.time() - t0) / iters
    tokens = batch * seq
    # 6ND: fwd+bwd matmul flops for a decoder-only transformer; the
    # attention-inclusive denominator adds the T^2-scaling score matmuls
    flops_6nd = 6.0 * n_params * tokens
    flops_attn = _attn_flops(batch, seq, cfg.n_layers, cfg.d_model,
                             causal=True)
    out = {"tokens_per_sec": round(tokens / dt, 0),
           "step_ms": round(dt * 1000, 2),
           "mfu_6nd": round(_mfu(flops_6nd, dt), 4),
           "mfu_attn_incl": round(_mfu(flops_6nd + flops_attn, dt), 4),
           "attn_impl": tfm._resolve_attn_impl(cfg, None, seq),
           "n_params": n_params}
    def _two_steps():
        nonlocal params, opt
        loss = None
        for _ in range(2):
            loss, params, opt = step(params, opt, tok, tgt)
        float(np.asarray(loss))

    _capture_trace(out, _two_steps, trace_dir, trace_label)
    return out


# ---------------------------------------------------------------------------
# WDL-Criteo through a real local PS cluster (BASELINE.md sparse north star):
# scheduler + 2 server processes over loopback, this process as the worker,
# comm_mode='Hybrid' (dense grads on-device, embedding rows through the PS).
# ---------------------------------------------------------------------------

def bench_wdl_ps(batch_size=128, warmup=5, iters=40, feature_dim=100000):
    """Returns {prefetch_on: (sps, ms, perf), prefetch_off: (sps, ms)} — the
    overlap A/B the reference's prefetch x ASP matrix is about."""
    from hetu_tpu.ps.local_cluster import local_cluster
    with local_cluster(n_servers=2, n_workers=1):
        import hetu_tpu as ht
        models = _import_models("ctr")
        from models.load_data import load_criteo_data

        (tr_dense, tr_sparse, tr_y), _ = load_criteo_data(
            feature_dimension=feature_dim, n_train=batch_size * 8, n_test=64)

        out = {}
        for leg, prefetch in enumerate((True, False)):
            # disjoint server tensor ids per leg: the servers are live across
            # both legs and ParamInit is idempotent, so reusing ids would
            # resume from the first leg's trained values
            os.environ["HETU_PS_ID_BASE"] = str(leg * 1000)
            dense = ht.dataloader_op([ht.Dataloader(tr_dense, batch_size,
                                                    "train")])
            sparse = ht.dataloader_op([ht.Dataloader(tr_sparse, batch_size,
                                                     "train")])
            y_ = ht.dataloader_op([ht.Dataloader(tr_y, batch_size, "train")])
            loss, y, labels, train_op = models.wdl_criteo(
                dense, sparse, y_, feature_dimension=feature_dim,
                embedding_size=16)
            ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.tpu(0),
                             comm_mode="Hybrid", seed=0, prefetch=prefetch)
            for _ in range(warmup):
                ex.run("train")
            float(np.mean(ex.run("train")[0].asnumpy()))
            t0 = time.time()
            for _ in range(iters - 1):
                ex.run("train")
            float(np.mean(ex.run("train")[0].asnumpy()))
            dt = (time.time() - t0) / iters
            key = "prefetch_on" if prefetch else "prefetch_off"
            out[key] = {"samples_per_sec": round(batch_size / dt, 1),
                        "step_ms": round(dt * 1000, 2)}
            if prefetch:
                ex.ps_runtime.drain()
                out[key]["ps_perf"] = dict(ex.ps_runtime.perf)
            ex.close()
        os.environ.pop("HETU_PS_ID_BASE", None)
        return out


# ---------------------------------------------------------------------------
# hetuq (docs/COMM_QUANT.md): quantized-communication A/B cells. Both are
# framework-relative measurements pinned to the CPU backend (SECTION_ENV) —
# the PS cell's bytes-on-wire counters and AUC delta and the DP cell's
# loss deltas are device-independent, and determinism beats tunnel jitter.
# ---------------------------------------------------------------------------

def bench_comm_quant_ps(batch_size=128, steps=1000, feature_dim=10000,
                        embedding_size=32, n_test=1024, warmup=5,
                        n_train=8192, learning_rate=0.02, stddev=0.1):
    """WDL-Criteo under comm_mode='PS' (dense AND sparse params PS-hosted),
    quant off vs int8: bytes-on-wire from the worker's raw/wire counters
    (client_stats), step time, and final test AUC per leg. The acceptance
    claim — >=3x wire reduction at AUC within 0.002 — is measured here.
    lr/stddev are tuned so BOTH legs converge well clear of the synthetic
    task's steep learning-curve transition — reading AUC mid-transition
    would measure noise-shifted timing, not quality."""
    from hetu_tpu.ps.local_cluster import local_cluster
    with local_cluster(n_servers=2, n_workers=1):
        import hetu_tpu as ht
        from hetu_tpu import metrics as ht_metrics
        models = _import_models("ctr")
        from models.load_data import load_criteo_data

        (tr_dense, tr_sparse, tr_y), (te_dense, te_sparse, te_y) = \
            load_criteo_data(feature_dimension=feature_dim,
                             n_train=n_train, n_test=n_test)
        out = {}
        for leg, mode in enumerate(("off", "int8")):
            # disjoint server tensor ids per leg (see bench_wdl_ps)
            os.environ["HETU_PS_ID_BASE"] = str(leg * 1000)
            dense = ht.dataloader_op([
                ht.Dataloader(tr_dense, batch_size, "train"),
                ht.Dataloader(te_dense, batch_size, "validate")])
            sparse = ht.dataloader_op([
                ht.Dataloader(tr_sparse, batch_size, "train"),
                ht.Dataloader(te_sparse, batch_size, "validate")])
            y_ = ht.dataloader_op([
                ht.Dataloader(tr_y, batch_size, "train"),
                ht.Dataloader(te_y, batch_size, "validate")])
            loss, y, labels, train_op = models.wdl_criteo(
                dense, sparse, y_, feature_dimension=feature_dim,
                embedding_size=embedding_size, learning_rate=learning_rate,
                stddev=stddev)
            ex = ht.Executor({"train": [loss, train_op],
                              "validate": [loss, y, y_]}, ctx=ht.cpu(0),
                             comm_mode="PS", seed=0, comm_quant=mode)
            comm = ex.ps_runtime.comm
            for _ in range(warmup):
                ex.run("train")
            float(np.mean(ex.run("train")[0].asnumpy()))  # drain
            cs0 = comm.ClientStats()
            t0 = time.time()
            for _ in range(steps - 1):
                ex.run("train")
            float(np.mean(ex.run("train")[0].asnumpy()))
            dt = (time.time() - t0) / steps
            ex.ps_runtime.drain()
            cs1 = comm.ClientStats()
            preds, labs = [], []
            for _ in range(n_test // batch_size):
                _, yv, lv = ex.run("validate", convert_to_numpy_ret_vals=True)
                preds.append(yv)
                labs.append(lv)
            auc = float(ht_metrics.auc(np.concatenate(labs),
                                       np.concatenate(preds)))
            out[mode] = {
                "step_ms": round(dt * 1000, 2),
                "auc": round(auc, 4),
                "raw_bytes": cs1["quant_raw_bytes"] - cs0["quant_raw_bytes"],
                "wire_bytes": (cs1["quant_wire_bytes"]
                               - cs0["quant_wire_bytes"]),
            }
            ex.close()
        os.environ.pop("HETU_PS_ID_BASE", None)
        # wire reduction = identical logical traffic (same model, steps,
        # batches, seed) at each leg's wire encoding
        out["bytes_wire_ratio"] = round(
            out["off"]["wire_bytes"] / max(1, out["int8"]["wire_bytes"]), 2)
        out["auc_off"] = out["off"]["auc"]
        out["auc_int8"] = out["int8"]["auc"]
        out["auc_delta"] = round(abs(out["off"]["auc"]
                                     - out["int8"]["auc"]), 4)
        return out


def bench_comm_quant_dp(width=512, batch=512, steps=40, warmup=5):
    """DP AllReduce on the 8-device virtual mesh: off vs int8 vs fp8 (same
    seed/feeds), step time + final loss per mode, plus the analytic
    raw-vs-wire ratio of the quantized decomposition (the executor's
    comm_quant_report; the reduce-scatter half stays f32 by construction —
    docs/COMM_QUANT.md)."""
    import hetu_tpu as ht
    from hetu_tpu.comm_quant import fp8_dtype
    from hetu_tpu.utils import ensure_devices

    ensure_devices(8)
    rng = np.random.RandomState(0)
    bx = rng.randn(batch, width).astype(np.float32)
    by = np.eye(8, dtype=np.float32)[rng.randint(0, 8, batch)]

    def run(mode):
        x = ht.Variable(name="x", trainable=False)
        y_ = ht.Variable(name="y_", trainable=False)
        h = x
        for i in range(3):
            w = ht.init.random_normal((width, width), stddev=0.05,
                                      name=f"w{i}")
            h = ht.relu_op(ht.matmul_op(h, w))
        wo = ht.init.random_normal((width, 8), stddev=0.05, name="wo")
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(h, wo), y_), [0])
        train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
        ex = ht.Executor({"train": [loss, train_op]}, ctx=ht.cpu(0),
                         comm_mode="AllReduce", seed=0, comm_quant=mode)
        feeds = {x: bx, y_: by}
        for _ in range(warmup):
            ex.run("train", feed_dict=feeds)
        float(np.mean(ex.run("train", feed_dict=feeds)[0].asnumpy()))
        t0 = time.time()
        for _ in range(steps - 1):
            ex.run("train", feed_dict=feeds)
        last = ex.run("train", feed_dict=feeds)[0]
        final = float(np.mean(last.asnumpy()))
        dt = (time.time() - t0) / steps
        return {"step_ms": round(dt * 1000, 2),
                "final_loss": round(final, 6)}, ex.comm_quant_report

    out = {}
    report = None
    modes = ["off", "int8"] + (["fp8"] if fp8_dtype() is not None else [])
    for mode in modes:
        out[mode], rep = run(mode)
        report = rep or report
    if fp8_dtype() is None:
        out["fp8"] = {"error": "float8_e4m3fn unavailable in this jax build"}
    if report:
        out["wire_report"] = report
    out["final_loss_off"] = out["off"]["final_loss"]
    out["loss_delta_int8"] = round(
        abs(out["int8"]["final_loss"] - out["off"]["final_loss"]), 6)
    if "final_loss" in out.get("fp8", {}):
        out["loss_delta_fp8"] = round(
            abs(out["fp8"]["final_loss"] - out["off"]["final_loss"]), 6)
    return out


def bench_planner(width=256, target_width=512, batch=256, warmup=8,
                  iters=40):
    """hetuplan cell (docs/ANALYSIS.md "Tier C: planning"): predicted vs
    measured step time — the acceptance check that the cost model's
    numbers mean something. A CALIBRATION MLP (``width``) trains on CPU
    with telemetry=metrics; its telemetry dir calibrates the planner
    (measured critical-path legs → compute residual + host term, exactly
    what ``hetulint --plan --calibrate`` does). The calibrated model then
    predicts a DIFFERENT graph — the ``target_width`` MLP it has never
    seen — and that graph is trained and measured for the residual. Same-
    graph prediction would be circular (the calibration reproduces its own
    run by construction); cross-size is the real claim. The uncalibrated
    prediction is recorded too — against TPU-assumed peaks on a CPU host
    it is orders of magnitude off BY DESIGN (docs/ROOFLINE.md:
    assumptions, not readings). SECTION_ENV pins the cell to CPU."""
    import tempfile
    import hetu_tpu as ht
    from hetu_tpu import analysis
    from hetu_tpu import telemetry as tel_mod
    from hetu_tpu.telemetry import profiler as prof_mod

    def build(w):
        x = ht.Variable(name="x", trainable=False)
        y_ = ht.Variable(name="y_", trainable=False)
        h = x
        for i in range(3):
            wt = ht.init.random_normal((w, w), stddev=0.05,
                                       name=f"pw{i}_{w}")
            h = ht.relu_op(ht.matmul_op(h, wt))
        wo = ht.init.random_normal((w, 8), stddev=0.05, name=f"pwo_{w}")
        loss = ht.reduce_mean_op(
            ht.softmaxcrossentropy_op(ht.matmul_op(h, wo), y_), [0])
        train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
        rng = np.random.RandomState(0)
        feeds = {x: rng.randn(batch, w).astype(np.float32),
                 y_: np.eye(8, dtype=np.float32)[rng.randint(0, 8, batch)]}
        return {"train": [loss, train_op]}, feeds

    def run_measured(graph, feeds, tel_dir):
        os.environ["HETU_TELEMETRY_DIR"] = tel_dir
        ex = ht.Executor(graph, ctx=ht.cpu(0), seed=0, telemetry="metrics")
        for _ in range(warmup):
            ex.run("train", feed_dict=feeds)
        t0 = time.time()
        for _ in range(iters - 1):
            ex.run("train", feed_dict=feeds)
        last = ex.run("train", feed_dict=feeds)[0]
        float(np.mean(last.asnumpy()))   # one sync closes the window
        wall_ms = (time.time() - t0) / iters * 1000
        tel_mod.shutdown()               # flush the step records
        means = prof_mod.step_phase_means(
            prof_mod.read_metrics_records(tel_dir))
        return means.get("step_ms", wall_ms), means

    # calibration run (width) -> measured legs + residuals
    cal_graph, cal_feeds = build(width)
    cal_dir = tempfile.mkdtemp(prefix="hetu_plan_cal_")
    _cal_ms, _ = run_measured(cal_graph, cal_feeds, cal_dir)

    # target run (target_width): predict FIRST, measure after. The
    # calibration carries the CALIBRATION graph's own predicted compute
    # as the residual baseline, so the correction is a true ratio that
    # extrapolates across sizes instead of echoing the measured step.
    cal = analysis.load_calibration(cal_dir)
    cal_baseline = analysis.plan_graph(cal_graph, devices=1,
                                       feed_meta=dict(cal_feeds))
    cal.baseline_compute_ms = cal_baseline.breakdown.get("compute_ms")
    tgt_graph, tgt_feeds = build(target_width)
    feed_meta = dict(tgt_feeds)
    plan_uncal = analysis.plan_graph(tgt_graph, devices=1,
                                     feed_meta=feed_meta)
    plan = analysis.plan_graph(tgt_graph, devices=1, calibrate=cal,
                               feed_meta=feed_meta)
    predicted = plan.predicted_step_ms
    tgt_dir = tempfile.mkdtemp(prefix="hetu_plan_tgt_")
    measured_ms, means = run_measured(tgt_graph, tgt_feeds, tgt_dir)
    err_pct = abs(predicted - measured_ms) / measured_ms * 100 \
        if measured_ms else None
    return {
        "calib_width": width, "target_width": target_width,
        "calib_step_ms": round(_cal_ms, 4),
        "measured_step_ms": round(measured_ms, 4),
        "predicted_step_ms": round(predicted, 4),
        "predicted_uncal_ms": round(plan_uncal.predicted_step_ms, 6),
        "plan_err_pct": round(err_pct, 2) if err_pct is not None else None,
        "plan_comm_mode": plan.comm_mode or "none",
        "plan_mesh": plan.mesh,
        "steps_measured": int(means.get("n_steps", iters)),
    }


def bench_kernels(vocab=1_000_000, dim=32, batch=4096, lookups=4,
                  warmup=5, iters=30):
    """hetukern cell (docs/KERNELS.md): (a) the per-kernel interpret-mode
    equality smoke — force-mode Pallas vs the XLA fallback through the
    real registry dispatch, under jit so both sides compile — and (b) the
    fused-embed-grad A/B on the CTR shape: the pre-hetukern dense
    ``(vocab, dim)`` zeros-table scatter vs the compact rows path
    (sort/unique + segment-sum), step time AND compiled peak HBM from the
    same executable handles hetuprof reads. The structural win (no
    table-sized intermediate) is backend-independent; SECTION_ENV pins the
    cell to CPU so the number is deterministic."""
    import jax
    import jax.numpy as jnp
    from hetu_tpu.kernels import registry, embed_grad, csr_spmm, \
        quant_comm, fused_opt
    from hetu_tpu import comm_quant

    rng = np.random.RandomState(0)
    out = {"equality": {}}

    # -- (a) registry dispatch + one equality check per kernel -------------
    def check(name, force_fn, oracle_fn, *args, exact=False, atol=1e-4):
        @jax.jit
        def _force(*a):
            with registry.active("force"):
                return force_fn(*a)

        @jax.jit
        def _off(*a):
            with registry.active("off"):
                return oracle_fn(*a)

        got = jax.tree.map(np.asarray, _force(*args))
        want = jax.tree.map(np.asarray, _off(*args))
        flat_g = jax.tree.leaves(got)
        flat_w = jax.tree.leaves(want)
        # structure must match too — zip would silently truncate a
        # mismatched tree and report a never-checked equivalence
        ok = len(flat_g) == len(flat_w) and all(
            (np.array_equal(a, b) if exact
             else np.allclose(a, b, atol=atol))
            for a, b in zip(flat_g, flat_w))
        out["equality"][name] = "ok" if ok else "MISMATCH"
        return ok

    ev = jnp.asarray(rng.randn(256, 128).astype(np.float32))
    ei = jnp.asarray(rng.randint(0, 40, 256))
    check("fused_embed_grad",
          lambda v, i: embed_grad.embed_grad_rows(v, i, 1000),
          lambda v, i: embed_grad.embed_grad_rows(v, i, 1000), ev, ei)
    sv = jnp.asarray(rng.randn(300).astype(np.float32))
    sr = jnp.asarray(rng.randint(0, 8, 300).astype(np.int32))
    sc = jnp.asarray(rng.randint(0, 16, 300).astype(np.int32))
    sb = jnp.asarray(rng.randn(16, 128).astype(np.float32))
    check("csr_spmm",
          lambda v, r, c, b: csr_spmm.coo_matmat(v, r, c, 8, b),
          lambda v, r, c, b: csr_spmm.coo_matmat(v, r, c, 8, b),
          sv, sr, sc, sb)
    qx = jnp.asarray(rng.randn(4096).astype(np.float32))
    check("quant_blocks",
          lambda x: quant_comm.quantize_blocks(x, 256, "int8"),
          lambda x: comm_quant.quantize_blocks(x, 256, "int8"),
          qx, exact=True)   # wire payloads must be bit-identical
    qq, qs, qn = comm_quant.quantize_blocks(qx, 256, "int8")
    check("dequant_blocks",
          lambda q, s: quant_comm.dequantize_blocks(q, s, 4096, 256),
          lambda q, s: comm_quant.dequantize_blocks(q, s, 4096, 256),
          qq, qs, exact=True)

    class _O:
        beta1, beta2, epsilon, weight_decay, l2reg = 0.9, 0.999, 1e-7, 0.0, 0.0

    op_ = jnp.asarray(rng.randn(8, 128).astype(np.float32))
    og = jnp.asarray(rng.randn(8, 128).astype(np.float32))
    slot = {"m": jnp.zeros((8, 128), jnp.float32),
            "v": jnp.zeros((8, 128), jnp.float32),
            "t": jnp.zeros((), jnp.float32)}
    check("fused_adam",
          lambda p, g: fused_opt.adam_step(_O, p, g, slot, 0.01),
          lambda p, g: fused_opt.adam_step(_O, p, g, slot, 0.01),
          op_, og, exact=True)
    check("fused_sgd",
          lambda p, g: fused_opt.sgd_step(_O, p, g, 0.01),
          lambda p, g: fused_opt.sgd_step(_O, p, g, 0.01),
          op_, og, exact=True)

    # -- (b) fused embed-grad A/B on the CTR shape -------------------------
    # lookups-per-example x batch row grads into a (vocab, dim) table: the
    # dense path writes the whole table per step to carry ~batch live rows
    vec = jnp.asarray(rng.randn(batch, lookups, dim).astype(np.float32))
    idx = jnp.asarray(
        # duplicate-heavy, like CTR hash features (power-law-ish)
        (rng.zipf(1.3, size=(batch, lookups)) % vocab).astype(np.int64))

    dense_fn = jax.jit(
        lambda v, i: embed_grad.embed_grad_dense_xla(v, i, (vocab, dim)))
    rows_fn = jax.jit(
        lambda v, i: embed_grad.embed_grad_rows(v, i, vocab))

    def timed(fn):
        # AOT: compile ONCE and reuse the executable for both the timing
        # loop and memory_analysis (a fresh .lower().compile() after the
        # timed calls would recompile the whole program a second time)
        exe = fn.lower(vec, idx).compile()
        jax.block_until_ready(exe(vec, idx))
        for _ in range(warmup):
            jax.block_until_ready(exe(vec, idx))
        t0 = time.time()
        for _ in range(iters):
            r = exe(vec, idx)
        jax.block_until_ready(r)
        ms = (time.time() - t0) / iters * 1000
        mem = None
        try:
            ma = exe.memory_analysis()
            mem = (int(ma.argument_size_in_bytes)
                   + int(ma.output_size_in_bytes)
                   + int(ma.temp_size_in_bytes)
                   - int(getattr(ma, "alias_size_in_bytes", 0) or 0))
        except Exception:  # noqa: BLE001 — backend may expose no analysis
            pass
        return ms, mem

    ms_dense, mem_dense = timed(dense_fn)
    ms_rows, mem_rows = timed(rows_fn)
    out["embed_grad"] = {
        "vocab": vocab, "dim": dim, "rows_pushed": batch * lookups,
        "dense_step_ms": round(ms_dense, 3),
        "rows_step_ms": round(ms_rows, 3),
        "speedup_rows": round(ms_dense / ms_rows, 2) if ms_rows else None,
    }
    if mem_dense and mem_rows:
        out["embed_grad"]["dense_peak_mib"] = round(mem_dense / 2**20, 2)
        out["embed_grad"]["rows_peak_mib"] = round(mem_rows / 2**20, 2)
        out["embed_grad"]["hbm_ratio"] = round(mem_dense / mem_rows, 2)
    # headline copies for the telemetry line / gate
    out["dense_step_ms"] = out["embed_grad"]["dense_step_ms"]
    out["rows_step_ms"] = out["embed_grad"]["rows_step_ms"]
    out["speedup_rows"] = out["embed_grad"]["speedup_rows"]
    out["equality_ok"] = all(v == "ok" for v in out["equality"].values())
    return out


def bench_vit(batch=64, warmup=3, iters=15, **cfg_overrides):
    """ViT-base/16 image-classification fine-tune step (the vision side of
    the flagship trunk; same 6ND + attention-inclusive MFU accounting as
    the LM cells, with T = n_patches + 1)."""
    import jax
    import jax.numpy as jnp
    from hetu_tpu.models import vit as hvit

    kw = dict(n_classes=1000, dtype=jnp.bfloat16, remat=True)
    kw.update(cfg_overrides)
    cfg = hvit.ViTConfig(**kw)
    params = hvit.init_params(jax.random.PRNGKey(0), cfg)
    n_params = hvit.count_params(params)
    opt = hvit.init_opt_state(params)
    step = hvit.make_train_step(cfg, lr=1e-4)
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(batch, cfg.n_channels, cfg.image_size,
                              cfg.image_size), jnp.float32)
    y = jnp.asarray(rng.randint(0, cfg.n_classes, batch), jnp.int32)
    loss = None
    for _ in range(warmup):
        loss, _, params, opt = step(params, opt, x, y)
    float(np.asarray(loss))   # hard sync (see bench_bert)
    t0 = time.time()
    for _ in range(iters):
        loss, _, params, opt = step(params, opt, x, y)
    float(np.asarray(loss))
    dt = (time.time() - t0) / iters
    T = cfg.seq_len
    flops_6nd = 6.0 * n_params * batch * T
    flops_attn = _attn_flops(batch, T, cfg.n_layers, cfg.d_model,
                             causal=False)
    return {"images_per_sec": round(batch / dt, 1),
            "step_ms": round(dt * 1000, 2),
            "mfu_6nd": round(_mfu(flops_6nd, dt), 4),
            "mfu_attn_incl": round(_mfu(flops_6nd + flops_attn, dt), 4),
            "n_params": n_params}


def bench_pipeline_ab(d_model=512, n_layers=8, d_ff=2048, vocab_size=8192,
                      seq=256, mb=4, microbatches=16, pp=4):
    """GPipe vs 1F1B (both window endpoints) on a pp4/dp2 virtual mesh:
    per-stage bubble accounting (host schedule table) and AOT-compiled
    per-device memory for THREE cases — gpipe, 1f1b (default 2pp
    window), 1f1b_minmem (classic pp window: least stash, half-rate
    steady state). The 1F1B selling point is the stash: O(pp) instead
    of O(M). No wall-clock — a CPU mesh says nothing about ICI timing;
    memory and schedule structure are backend-independent. The cell's
    timeout budget covers the three AOT compiles (~30s total on the
    bench host's CPU)."""
    import jax
    from hetu_tpu.models import transformer as tfm
    from hetu_tpu.parallel import mesh as meshlib
    from hetu_tpu.parallel import pipeline as pplib
    from hetu_tpu.utils import ensure_devices

    ensure_devices(8)
    cfg = tfm.TransformerConfig(
        vocab_size=vocab_size, d_model=d_model, n_heads=d_model // 64,
        n_layers=n_layers, d_ff=d_ff, max_seq_len=seq,
        dtype=jax.numpy.float32, remat=False)
    mesh = meshlib.make_mesh(dp=8 // pp, pp=pp,
                             devices=jax.devices()[:8])
    M = microbatches
    p_sds = jax.eval_shape(
        lambda: pplib.init_pipeline_params(jax.random.PRNGKey(0), cfg, mesh))
    o_sds = jax.eval_shape(tfm.init_opt_state, p_sds)
    tok = jax.ShapeDtypeStruct((M, mb, seq), jax.numpy.int32)

    out = {"config": {"d_model": d_model, "n_layers": n_layers, "pp": pp,
                      "microbatches": M, "seq": seq, "mb": mb},
           "schedule": pplib.schedule_stats(pp, M),
           # the memory/duty tradeoff's other endpoint: classic 1F1B
           # window (stash <= pp, half-rate steady state)
           "schedule_minmem": pplib.schedule_stats(pp, M,
                                                   max_inflight=pp)["1f1b"]}
    cases = (("gpipe", pplib.make_pipeline_train_step, {}),
             ("1f1b", pplib.make_pipeline_train_step_1f1b, {}),
             ("1f1b_minmem", pplib.make_pipeline_train_step_1f1b,
              {"max_inflight": pp}))
    for label, make, kw in cases:
        step = make(cfg, mesh, num_microbatches=M, lr=1e-3, **kw)
        ma = step.lower(p_sds, o_sds, tok, tok).compile().memory_analysis()
        peak = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                + ma.temp_size_in_bytes - ma.alias_size_in_bytes)
        out[label] = {
            "per_device_mib": round(peak / 2**20, 1),
            "temp_mib": round(ma.temp_size_in_bytes / 2**20, 1),
        }
    out["temp_ratio_gpipe_over_1f1b"] = round(
        out["gpipe"]["temp_mib"] / max(out["1f1b"]["temp_mib"], 0.1), 2)
    return out


def _with_fused_fallback(fn, flag_name="fused_lm_ce"):
    """The fused-CE kernel's compiled (non-interpret) path first executes
    on the DRIVER's chip — if Mosaic rejects it there, retry the cell with
    the materializing einsum form instead of losing the cell, and record
    the failure for diagnosis."""
    try:
        return fn()
    except Exception as e:  # noqa: BLE001 — any compile/runtime failure
        out = fn(**{flag_name: False})
        out["fused_ce_fallback"] = f"{type(e).__name__}: {e}"[:300]
        return out


@contextlib.contextmanager
def _smoke_trace_dir(smoke):
    """Trace dir for smoke runs, DELETED on exit — smoke only exercises the
    capture path, and the former bare mkdtemp leaked a hetu_bench_* dir per
    run. Yields None outside smoke (or when the driver exported
    HETU_BENCH_TRACE: real runs get their per-section dir from
    _capture_trace and must keep it)."""
    if smoke and not os.environ.get("HETU_BENCH_TRACE"):
        with tempfile.TemporaryDirectory(prefix="hetu_bench_") as td:
            yield os.path.join(td, "trace")
    else:
        yield None


def _run_section(name):
    """Child mode: compute ONE section, print one JSON object, exit.
    Runs in its own process so a hung compile (degraded tunnel) can be
    killed from outside — SIGALRM cannot interrupt a stuck C call.

    HETU_BENCH_SMOKE=1 shrinks every section to seconds-scale configs so
    the whole section surface can execute on the CPU backend in tests —
    the driver's one hardware run must never be the first time a
    section's Python path executes (tests/test_bench_sections.py)."""
    smoke = os.environ.get("HETU_BENCH_SMOKE") == "1"
    # tiny-but-structurally-identical transformer dialect for smoke runs
    tiny = dict(vocab_size=512, d_model=64, n_heads=4, n_layers=2,
                d_ff=128, max_seq_len=64)
    out = {}
    if name.startswith("resnet:"):
        _, bs, tag = name.split(":")
        dtype = None if tag == "f32" else "bfloat16"
        kw = dict(batch_size=8, warmup=1, iters=2) if smoke else \
            dict(batch_size=int(bs))
        sps, ms, mfu = bench_resnet18(dtype=dtype, **kw)
        out = {"samples_per_sec": round(sps, 1), "step_ms": round(ms, 2),
               "mfu": round(mfu, 4) if mfu else None}
    elif name == "twin":
        _import_models("cnn")
        import jax_twin
        kw = dict(batch_size=8, warmup=1, iters=2) if smoke else \
            dict(batch_size=512)
        tsps, tms = jax_twin.bench(dtype="bf16", **kw)
        out = {"samples_per_sec": round(tsps, 1), "step_ms": round(tms, 2)}
    elif name == "transformer":
        if smoke:
            out = _with_fused_fallback(
                lambda **kw: bench_transformer(batch=2, seq=64, warmup=1,
                                               iters=2, **tiny, **kw))
        else:
            out = _with_fused_fallback(bench_transformer)
    elif name == "transformer350":
        # flagship-scale proof point (~350M params): MFU must rise with
        # model size if the 38M config is shape-bound, as claimed
        from hetu_tpu.models import transformer as tfm

        def cfg350(**kw):
            big = dict(vocab_size=32768, d_model=1024, n_heads=16,
                       n_layers=24, d_ff=4096, max_seq_len=512)
            return tfm.TransformerConfig(remat=True,
                                         **(tiny if smoke else big), **kw)

        # smoke exercises the trace path like the bert cell does (env
        # runs get their per-section subdir from _capture_trace)
        with _smoke_trace_dir(smoke) as tdir350:
            out = _with_fused_fallback(
                lambda **kw: bench_transformer(
                    cfg=cfg350(**kw), batch=2 if smoke else 8,
                    seq=64 if smoke else 512, warmup=1 if smoke else 2,
                    iters=2 if smoke else 8, trace_dir=tdir350,
                    trace_label="transformer350"),
                flag_name="fused_lm_ce")
    elif name == "decode":
        kw = dict(batch=2, prompt_len=4, max_len=16) if smoke else {}
        dtoks, dms = bench_decode(**kw)
        out = {"tokens_per_sec": round(dtoks, 0),
               "ms_per_token": round(dms, 3)}
    elif name == "flash4k":
        kw = dict(b=1, h=2, s=256, d=64, iters=2) if smoke else {}
        out = bench_flash_attention(**kw)
    elif name == "bert":
        if smoke:
            # smoke exercises the trace-capture path too (the real cell
            # only traces when the driver exports HETU_BENCH_TRACE)
            with _smoke_trace_dir(smoke) as tdir:
                out = _with_fused_fallback(
                    lambda **kw: bench_bert(batch_size=2, seq_len=64,
                                            warmup=1, iters=2,
                                            trace_dir=tdir, **tiny, **kw),
                    flag_name="fused_mlm_ce")
        else:
            out = _with_fused_fallback(bench_bert, flag_name="fused_mlm_ce")
    elif name == "vit":
        kw = (dict(batch=2, warmup=1, iters=2, image_size=32, patch_size=8,
                   d_model=64, n_heads=4, n_layers=2, d_ff=128,
                   n_classes=10) if smoke else {})
        out = bench_vit(**kw)
    elif name == "pipeline":
        # GPipe vs 1F1B (x2 windows) on an 8-device VIRTUAL CPU mesh (cell
        # measures the schedules' memory law and bubble accounting, which
        # need pp>1 — the bench host has one chip; _run_section pins the
        # child to the CPU backend for exactly this section)
        # smoke keeps microbatches > 2*pp so the minmem window actually
        # binds (at M <= pp both windows yield the same table/ring)
        out = bench_pipeline_ab(**(dict(d_model=64, n_layers=4, d_ff=128,
                                        vocab_size=512, seq=32, mb=2,
                                        microbatches=12) if smoke else {}))
    elif name == "introspect":
        # hetuscope overhead cell (docs/OBSERVABILITY.md): the <5%-at-
        # default-cadence claim is MEASURED here, not asserted
        kw = (dict(width=32, batch=16, iters=12, warmup=4)
              if smoke else {})
        out = bench_introspect_overhead(**kw)
    elif name == "watch":
        # hetuwatch overhead cell (docs/OBSERVABILITY.md pillar 6): the
        # <=2%-armed claim is MEASURED here, not asserted
        kw = (dict(width=32, batch=16, iters=12, warmup=4, windows=2)
              if smoke else {})
        out = bench_watch_overhead(**kw)
    elif name == "pilot":
        # hetupilot armed-idle cell (docs/FAULT_TOLERANCE.md): the
        # <1%-idle claim is MEASURED here, not asserted
        kw = (dict(width=32, batch=16, iters=10, warmup=3, windows=2)
              if smoke else {})
        out = bench_pilot_overhead(**kw)
        out["servers"] = 1
    elif name == "story":
        # hetustory run-identity stamping cell (docs/OBSERVABILITY.md
        # pillar 7): the <0.5%/step claim is MEASURED here, not asserted
        kw = (dict(iters=500, warmup=50, windows=2, step_iters=8,
                   step_warmup=2) if smoke else {})
        out = bench_story_overhead(**kw)
    elif name == "probe":
        import jax
        import jax.numpy as jnp
        # liveness first: a dead tunnel backend hangs (or raises) in
        # jax.devices() itself, before any compile is paid — the bounded
        # child turns that into a clean timeout the parent can triage
        devs = jax.devices()
        x = jnp.ones((512, 512))
        out = {"ok": float(jnp.sum(jax.jit(lambda a: a @ a)(x))) > 0,
               "devices": len(devs)}
    elif name == "wdl":
        kw = dict(batch_size=16, warmup=1, iters=4,
                  feature_dim=1000) if smoke else {}
        out = bench_wdl_ps(**kw)
        out["servers"] = 2
    elif name == "comm_quant_ps":
        kw = (dict(batch_size=32, steps=12, feature_dim=1000, n_test=128,
                   warmup=2, n_train=256) if smoke else {})
        out = bench_comm_quant_ps(**kw)
        out["servers"] = 2
    elif name == "comm_quant_dp":
        kw = (dict(width=64, batch=32, steps=8, warmup=2) if smoke else {})
        out = bench_comm_quant_dp(**kw)
    elif name == "trail":
        # hetutrail overhead cell (docs/OBSERVABILITY.md pillar 5): the
        # <2%-with-ring-enabled claim is MEASURED here, not asserted
        kw = (dict(batch_size=32, iters=6, rows=500, warmup=2, windows=2)
              if smoke else {})
        out = bench_trail_overhead(**kw)
        out["servers"] = 2
    elif name == "chaos":
        # hetuchaos hardening cell (docs/FAULT_TOLERANCE.md): the
        # retry/CRC <= 2%/step claim is MEASURED here, not asserted
        kw = (dict(batch_size=32, iters=6, rows=500, warmup=2, windows=2)
              if smoke else {})
        out = bench_chaos_hardening(**kw)
        out["servers"] = 2
    elif name == "snapshot":
        # hetusave coordinated-snapshot cell (docs/FAULT_TOLERANCE.md):
        # the <5%/step amortized stall claim is MEASURED here, not
        # asserted
        kw = (dict(batch_size=32, iters=10, rows=500, warmup=2,
                   windows=2, snap_every=5) if smoke else {})
        out = bench_snapshot_overhead(**kw)
        out["servers"] = 2
    elif name == "kernels":
        kw = (dict(vocab=5000, dim=32, batch=512, lookups=2, warmup=1,
                   iters=3) if smoke else {})
        out = bench_kernels(**kw)
    elif name == "planner":
        # hetuplan predicted-vs-measured cell (docs/ANALYSIS.md Tier C):
        # the 30%-of-measured acceptance for the calibrated prediction
        kw = (dict(width=64, target_width=128, batch=64, warmup=3,
                   iters=8) if smoke else {})
        out = bench_planner(**kw)
    else:
        raise SystemExit(f"unknown section {name}")
    import jax
    out["_device"] = str(jax.devices()[0].device_kind)
    print(json.dumps(out))


# sections that must run on the virtual CPU mesh regardless of the host's
# backend: the pipeline A/B needs 8 devices (pp>1), which the 1-chip bench
# host cannot provide. PYTHONPATH is blanked so the image's sitecustomize
# cannot re-pin the axon backend; bench.py's cwd keeps the repo importable.
SECTION_ENV = {
    "pipeline": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": "",
                 "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
    # framework-overhead A/B: pinned off the tunneled chip so the delta
    # measures hetuscope, not tunnel jitter
    "introspect": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
    # hetuq A/Bs (docs/COMM_QUANT.md): bytes-on-wire and AUC/loss deltas
    # are device-independent; determinism beats the tunneled chip. The DP
    # cell additionally needs an 8-device mesh for a real dp axis.
    "comm_quant_ps": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
    "comm_quant_dp": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": "",
                      "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
    # hetukern cell (docs/KERNELS.md): the dense-vs-rows embed-grad A/B is
    # a structural HBM/step-time claim, deterministic on CPU; the equality
    # smoke drives interpret-mode Pallas, which the tunneled chip only
    # slows down
    "kernels": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
    # hetutrail overhead A/B: framework-relative, PS-cluster-bound —
    # deterministic on CPU, and the tunneled chip would add 60-85ms RTTs
    # that drown the cost being measured
    "trail": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
    # hetuwatch overhead A/B: same reasoning — the sentinel's per-step
    # cost is host-side dict arithmetic, far below tunnel jitter
    "watch": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
    # hetupilot armed-idle A/B: the boundary walk being measured is
    # host-side dict arithmetic, far below tunnel jitter
    "pilot": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
    # hetustory base-field stamping A/B: pure host-side serialization,
    # far below tunnel jitter
    "story": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
    # hetuchaos CRC-hardening A/B: same reasoning as trail — the checksum
    # cost being measured is host-side and far below tunnel jitter
    "chaos": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
    # hetusave coordinated-snapshot A/B: the quiesce barrier + shard
    # write being measured are host/disk-side; tunnel jitter would drown
    # a single-digit-percent stall
    "snapshot": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
    # hetuplan predicted-vs-measured (docs/ANALYSIS.md Tier C): the
    # calibration round-trip is framework-relative and must be
    # deterministic; the tunnel's RTT jitter would drown the residual
    "planner": {"JAX_PLATFORMS": "cpu", "PYTHONPATH": ""},
}


# pgid of the in-flight section child: the SIGTERM emergency emitter kills
# it so a driver-terminated bench leaves no orphaned PS cluster behind
_CURRENT_CHILD_PGID = [None]


def _section_subprocess(name, timeout):
    """Run one section in a child process group with a hard timeout. The
    whole GROUP is killed on timeout — the wdl section spawns a PS
    scheduler/server that must not outlive a killed child (and whose open
    pipes would otherwise stall communicate() after a child crash)."""
    import subprocess
    cmd = [sys.executable, os.path.abspath(__file__), "--run-section", name]
    # Persistent XLA compilation cache shared by every section subprocess
    # (and by repeat bench runs on the same machine): each section is a
    # fresh process, so without this every section pays the full ~20-40s+
    # axon compile — the dominant share of its timeout window. Degrades to
    # a no-op warning on backends that can't serialize executables.
    env = os.environ.copy()
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.expanduser("~/.cache/hetu_tpu_xla_cache"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "2")
    env.update(SECTION_ENV.get(name, {}))
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True,
                            cwd=os.path.dirname(os.path.abspath(__file__)),
                            env=env, start_new_session=True)
    _CURRENT_CHILD_PGID[0] = proc.pid
    try:
        stdout, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
        proc.communicate()
        # "hang" is the structured marker every triage path keys on — an
        # rc!=0 crash whose stderr merely CONTAINS "timed out" must not be
        # classified as a backend hang
        return {"error": f"timed out after {timeout}s (hung compile?)",
                "hang": True}
    finally:
        _CURRENT_CHILD_PGID[0] = None
        if proc.poll() is None:
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
    if proc.returncode != 0:
        tail = (stderr or "").strip().splitlines()[-3:]
        return {"error": f"rc={proc.returncode}: " + " | ".join(tail)[:300]}
    for line in reversed(stdout.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue   # progress noise that merely looks like JSON
    return {"error": "no JSON line from section"}


def _git_sha():
    import subprocess
    try:
        return subprocess.check_output(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            text=True, stderr=subprocess.DEVNULL).strip()
    except Exception:  # noqa: BLE001 — not a git checkout / no git
        return None


class _Ledger:
    """Durable per-cell scoreboard (BENCH_PARTIAL.json).

    Every completed cell is written to disk the moment it finishes, so a
    tunnel death mid-run (it has happened three rounds straight) loses
    nothing: the next invocation — self-run or driver-run — reuses the
    recorded cells and spends its hardware minutes only on the missing
    ones. The final JSON line merges ledger + fresh; entries recorded at
    a different git sha are re-measured, not served (HETU_BENCH_REUSE_STALE
    opts in, flagged). Smoke runs never open a
    ledger at all (main() passes an empty path): smoke exists to validate
    the section pipeline, and serving cached cells would defeat that.
    Reference analogue: PS load recording persists to log_path
    (/root/reference/python/hetu/gpu_ops/executor.py:292-295); this is the
    same durability idea applied to the round scoreboard."""

    def __init__(self, path):
        self.path = path or None
        self.sha = _git_sha()
        self.cells = {}
        if self.path and os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    data = json.load(f)
                self.cells = data["cells"] if isinstance(data, dict) else {}
            except (KeyError, ValueError, OSError) as e:
                print(f"# bench ledger unreadable ({e}); starting fresh",
                      file=sys.stderr)

    def reuse(self, key):
        """A reusable entry is a SUCCESS recorded at THIS git sha; errors
        and hangs are always re-attempted, and a cell from a different
        commit is re-measured rather than fed into the merged headline
        (HETU_BENCH_REUSE_STALE=1 opts back into serving it, flagged
        ``stale`` — for triage runs on a dead backend, where an old number
        beats none). Returns the result dict with an ``_ledger``
        provenance stamp, or None."""
        ent = self.cells.get(key)
        if not isinstance(ent, dict):
            return None
        result = ent.get("result")
        if not isinstance(result, dict) or "error" in result:
            return None
        out = dict(result)
        prov = {"ts": ent.get("ts")}
        if ent.get("sha") != self.sha:
            # resilience.env_truthy's convention, re-inlined because this
            # driver must stay jax-free (importing hetu_tpu pulls jax):
            # REUSE_STALE=false means what it says
            if os.environ.get("HETU_BENCH_REUSE_STALE",
                              "").strip().lower() not in ("1", "true",
                                                          "yes", "on"):
                return None
            prov["stale"] = f"recorded at {ent.get('sha')}, HEAD is {self.sha}"
        out["_ledger"] = prov
        return out

    def record(self, key, result, device=None):
        self.cells[key] = {
            "result": result, "sha": self.sha,
            "ts": time.strftime("%Y-%m-%dT%H:%M:%S"),
        }
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"cells": self.cells}, f, indent=1, sort_keys=True)
        os.replace(tmp, self.path)   # atomic: a kill never corrupts it
        self._telemetry_line(key, result, device)

    def _telemetry_line(self, key, result, device):
        """One JSONL line per completed cell, appended next to the ledger
        (BENCH_TELEMETRY.jsonl): records the cell's headline numbers PLUS
        device_kind and the ASSUMED peak, so docs/ROOFLINE.md's
        "assumption, not a reading" caveat is auditable per run — an MFU
        without the peak it was computed against is not a measurement."""
        path = os.path.join(os.path.dirname(os.path.abspath(self.path)),
                            "BENCH_TELEMETRY.jsonl")
        rec = {"ts": time.strftime("%Y-%m-%dT%H:%M:%S"), "cell": key,
               "sha": self.sha, "device_kind": device,
               "peak_tflops_assumed": PEAK_TFLOPS}
        if isinstance(result, dict):
            for k in ("samples_per_sec", "step_ms", "mfu", "mfu_6nd",
                      "mfu_attn_incl", "tokens_per_sec",
                      "introspect_overhead_pct", "trail_overhead_pct",
                      "watch_overhead_pct", "watch_observe_ms",
                      "watch_amortized_pct", "observations",
                      "pilot_overhead_pct", "pilot_boundary_ms",
                      "pilot_amortized_pct",
                      "story_overhead_pct", "record_us_off",
                      "record_us_on",
                      "client_spans", "step_ms_off",
                      "step_ms_on", "bytes_wire_ratio", "auc_off",
                      "auc_int8", "auc_delta", "final_loss_off",
                      "loss_delta_int8", "loss_delta_fp8",
                      "dense_step_ms", "rows_step_ms", "speedup_rows",
                      "equality_ok", "measured_step_ms",
                      "predicted_step_ms", "plan_err_pct",
                      "plan_comm_mode", "crc_overhead_pct", "crc_rejects",
                      "snapshot_stall_pct", "snapshot_wall_ms"):
                if result.get(k) is not None:
                    rec[k] = result[k]
        try:
            with open(path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError as e:
            print(f"# bench telemetry line skipped ({e})", file=sys.stderr)


def _wait_for_backend(budget, detail):
    """Probe-wait loop for a tunnel outage the caller JUST observed (so it
    sleeps before the first probe instead of re-confirming the hang).
    Spends up to ``budget[0]`` seconds (a single-element list so the spend
    is SHARED across every outage in the run) probing every 240s. Returns
    True when a probe succeeds; False when the budget is gone. Observed
    behavior of the axon tunnel (rounds 3-4): outages are intermittent — it
    can die 20 minutes into a green run and return minutes later, so
    mid-run recovery matters as much as the at-start wait."""
    while True:
        if budget[0] < 240 + 180:
            return False
        print(f"# backend down; retrying probe in 240s "
              f"({int(budget[0])}s shared wait budget left)",
              file=sys.stderr, flush=True)
        time.sleep(240)
        budget[0] -= 240
        t0 = time.time()
        out = _section_subprocess("probe", 180)
        budget[0] -= time.time() - t0
        if "error" not in out:
            detail["outage_recoveries"] = detail.get("outage_recoveries", 0) + 1
            if out.get("_device"):
                detail.setdefault("device", out["_device"])
            return True
        if not out.get("hang"):
            # the probe CRASHED (backend alive enough to run python):
            # treat as recovered so sections get their chance
            detail.setdefault("_probe_crashes", []).append(out["error"])
            return True


def _assemble_final(detail, section_keys, error=None):
    """The ONE final JSON line, from whatever cells exist so far.

    Factored out of main() so the SIGTERM emergency path emits the same
    structure: completed cells keep their numbers, the headline comes from
    whichever resnet cells finished, and ``incomplete_cells`` names every
    section that has no measurement — so a cut-short run yields a partial
    trajectory point that SAYS it is partial (the BENCH_r05 rc=124 hole,
    where the driver's cap left no JSON line at all) instead of reading as
    a win, a loss, or nothing."""
    headline = 0.0
    for k, v in detail.items():
        if k.startswith("resnet18_") and isinstance(v, dict):
            headline = max(headline, v.get("samples_per_sec") or 0.0)
    incomplete = [k for k in section_keys
                  if not isinstance(detail.get(k), dict)
                  or "error" in detail[k]]
    line = {
        "metric": "resnet18_cifar10_train_samples_per_sec_per_chip",
        "value": round(headline, 1) if headline else None,
        "unit": "samples/sec/chip",
        "vs_baseline": (round(headline / BASELINE_SAMPLES_PER_SEC, 3)
                        if headline and BASELINE_SAMPLES_PER_SEC else None),
        "detail": detail,
    }
    if error:
        line["error"] = error
    if incomplete:
        line["incomplete_cells"] = incomplete
    return line


def _install_emergency_emit(detail, section_keys):
    """SIGTERM handler (installed BEFORE the first timed window): the
    driver kills a over-budget bench with ``timeout -k 10``, which sends
    SIGTERM then SIGKILL 10 s later — enough room to print the final line
    with every completed cell, kill the in-flight section child's process
    group, and exit 75 (EX_TEMPFAIL, the repo's preemption convention)."""
    def _emergency(signum, frame):
        line = _assemble_final(
            detail, section_keys,
            error=f"terminated by signal {signum} before completion")
        print(json.dumps(line), flush=True)
        pgid = _CURRENT_CHILD_PGID[0]
        if pgid:
            try:
                os.killpg(pgid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
        os._exit(75)
    signal.signal(signal.SIGTERM, _emergency)


def _latest_good_round(here):
    """Newest BENCH round artifact with at least one gateable measurement
    (BENCH_rNN.json driver wrappers and BENCH_SELF_rNN_partial.json
    ledgers both qualify) — the default --gate baseline. BENCH_r05's
    parsed-null wrapper is exactly what this must skip."""
    prof = _profiler()
    candidates = []
    for path in glob.glob(os.path.join(here, "BENCH_*r[0-9]*.json")):
        m = re.search(r"r(\d+)", os.path.basename(path))
        if m:
            candidates.append((int(m.group(1)), path))
    for _, path in sorted(candidates, reverse=True):
        try:
            cells, _meta = prof.load_summary(path)
        except (OSError, ValueError):
            continue
        if prof.summary_has_measurement(cells):
            return path
    return None


def main():
    # the parent NEVER touches jax: a hung backend must not stall the
    # driver's one-JSON-line contract
    detail = {"assumed_peak_tflops": PEAK_TFLOPS}
    backend_dead = False
    # durable scoreboard: HETU_BENCH_LEDGER overrides the path; empty
    # string disables (the scripted driver tests run ledger-less). Smoke
    # mode NEVER opens a ledger — a smoke run must execute every section
    # (that's what it validates), and its toy numbers must never be
    # served to (or shadow) a real run.
    lpath = os.environ.get("HETU_BENCH_LEDGER")
    if lpath is None:
        lpath = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_PARTIAL.json")
    if os.environ.get("HETU_BENCH_SMOKE") == "1":
        lpath = ""
    ledger = _Ledger(lpath)
    alive_hangs = 0   # consecutive section hangs while probes still answer
    # one shared wait budget for every outage in the run (at-start AND
    # mid-run), so an intermittent tunnel can't stretch the bench unboundedly
    wait_budget = [float(os.environ.get("HETU_BENCH_PROBE_WAIT_S", "2700"))]

    # cheap canary first: a dead tunnel is detected in one 180s probe
    # instead of burning two full section timeouts
    # ordered by value-per-minute under an intermittent tunnel: the headline
    # candidates first, then the BERT MFU story, then the rest — a late
    # outage with an exhausted wait budget costs the least-important cells.
    # resnet bf16 bs>=256 runs LAST and is never retried: in two separate
    # hardware sessions (2026-07-30/31) exactly those cells hung AND left
    # the backend unresponsive to probes afterwards, while bf16 bs128 and
    # f32 bs128/256 completed green around them — the observed signature of
    # a workload that wedges the tunnel backend, not of a random outage.
    # Putting them after every other section caps the blast radius at the
    # two least-important cells.
    sections = [("_probe", "probe", 180),
                ("resnet18_bf16_bs128", "resnet:128:bf16", 420),
                ("resnet18_f32_bs128", "resnet:128:f32", 420),
                ("resnet18_f32_bs256", "resnet:256:f32", 420)]
    if "--fast" not in sys.argv:
        sections += [("bert_base_pretrain_seq512", "bert", 600),
                     ("transformer_38M_seq512", "transformer", 420),
                     ("transformer_350M_seq512", "transformer350", 600),
                     ("jax_native_twin_bf16_bs512", "twin", 420),
                     ("decode_38M_greedy", "decode", 420),
                     ("flash_attention_seq4096", "flash4k", 420),
                     ("vit_base_finetune", "vit", 600),
                     ("pipeline_gpipe_vs_1f1b", "pipeline", 600),
                     ("wdl_criteo_hybrid_ps", "wdl", 600),
                     ("comm_quant_ps_wdl", "comm_quant_ps", 600),
                     ("comm_quant_dp_mlp", "comm_quant_dp", 600),
                     ("introspect_overhead", "introspect", 420),
                     ("trail_overhead", "trail", 600),
                     ("watch_overhead", "watch", 420),
                     ("pilot_overhead", "pilot", 420),
                     ("story_overhead", "story", 420),
                     ("chaos_overhead", "chaos", 600),
                     ("snapshot_overhead", "snapshot", 600),
                     ("kernels_tier", "kernels", 600),
                     ("planner_residual", "planner", 420)]
    # 900s not 420s: these cells DID run green in a round-3 session (30.8k
    # samples/s at bf16 bs512), so the hang signature is most consistent
    # with a cold compile that outlives a killed client server-side and
    # blocks probes until it finishes — being last, a longer window costs
    # nothing, and one green completion lands in the persistent cache.
    sections += [("resnet18_bf16_bs256", "resnet:256:bf16", 900),
                 ("resnet18_bf16_bs512", "resnet:512:bf16", 900)]
    risky = {"resnet18_bf16_bs256", "resnet18_bf16_bs512"}
    # tools/wedge_bisect.py closes the loop: a green bisect verdict
    # (the STRUCTURED verdict.green flag) lifts the quarantine, so the
    # cells get normal outage-retry treatment without a hand edit; any
    # other verdict (compile/execute-side, inconclusive) keeps it.
    wpath = os.environ.get("HETU_WEDGE_REPORT") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "WEDGE_BISECT.json")
    try:
        with open(wpath) as f:
            wverdict = json.load(f).get("verdict", {})
        lift = wverdict.get("green") is True
        wtext = wverdict.get("text", "")
    except Exception:  # noqa: BLE001 — a malformed report must not break
        lift, wtext = False, ""  # the driver's one-JSON-line contract
    if lift:
        risky = set()
        detail["wedge_verdict"] = wtext

    # emergency emitter BEFORE the first timed window: a driver kill from
    # here on still produces the final line with every completed cell
    section_keys = [k for k, n, _t in sections if n != "probe"]
    _install_emergency_emit(detail, section_keys)

    # Global wall-clock budget (HETU_BENCH_DEADLINE_S, 0 = off): the
    # driver wraps the whole bench in `timeout -k`, and a run whose
    # section timeouts SUM past that cap is killed rc=124 — the
    # BENCH_r03-r05 no-trajectory-point hole the emergency line only
    # partially fixed (SIGTERM still loses the in-flight cell and any
    # stdout race loses the line entirely). With a deadline set, each
    # cell's timeout is clamped to the time actually remaining and a
    # cell that no longer fits is SKIPPED with a named reason — the
    # bench always finishes inside the cap and emits its own final line.
    deadline_s = float(os.environ.get("HETU_BENCH_DEADLINE_S", "0") or 0)
    bench_t0 = time.monotonic()
    # leave room after the last cell for the gate + final-line emit
    _DEADLINE_MARGIN_S, _MIN_CELL_S = 30.0, 60.0

    for key, name, timeout in sections:
        if deadline_s > 0:
            remaining = deadline_s - (time.monotonic() - bench_t0) \
                - _DEADLINE_MARGIN_S
            if remaining < _MIN_CELL_S:
                if name != "probe":
                    detail[key] = {"error": "skipped: global deadline "
                                   f"(HETU_BENCH_DEADLINE_S={deadline_s:g})"
                                   " exhausted"}
                continue
            timeout = min(timeout, int(remaining))
            # the outage wait budget must also fit inside the deadline: a
            # _wait_for_backend sleep past the cap turns a named-skip
            # round into a driver rc=124 kill with no final line (the
            # r04/r05 hole). HETU_BENCH_PROBE_WAIT_S semantics unchanged
            # when no deadline is set.
            wait_budget[0] = min(wait_budget[0], remaining)
        if name == "probe":
            # At-start wait-and-retry: a tunnel outage at driver-run time
            # should not null the round if the backend comes back within the
            # shared budget (HETU_BENCH_PROBE_WAIT_S, default 45 min). Only
            # probe TIMEOUTS mean "backend dead" — an rc!=0 probe crash
            # proves the child ran, so the sections still get their chance.
            out = _section_subprocess(name, timeout)
            if "error" not in out:
                dev = out.pop("_device", None)
                if dev:
                    detail["device"] = dev
            elif out.get("hang"):
                wait_budget[0] -= timeout   # the observed hang IS attempt 1
                if not _wait_for_backend(wait_budget, detail):
                    backend_dead = True
                    detail["_probe"] = out
                # on recovery: nothing stale recorded — outage_recoveries
                # carries the "started down, came back" signal
            else:
                detail["_probe"] = out   # crash, not a hang: run sections
            continue
        cached = ledger.reuse(key)
        if cached is not None:
            # ledger reuse comes BEFORE the dead-backend/backstop skips:
            # a cell captured by an earlier invocation must survive a run
            # whose own hardware window is gone
            detail[key] = cached
            detail.setdefault("from_ledger", []).append(key)
            continue
        if backend_dead:
            # wait budget exhausted with the tunnel still down: a NAMED
            # per-cell skip (machine-readable "skip" key) instead of
            # burning each cell's timeout into an rc=124 no-data round
            detail[key] = {"error": "skipped: backend unresponsive",
                           "skip": "backend_dead"}
            continue
        if alive_hangs >= 2:
            # backstop: probes answer but sections keep hanging (a systemic
            # compile-path hang, not an outage) — don't burn timeout+probe
            # on every remaining section
            detail[key] = {"error": "skipped: sections hanging with live "
                                    "backend"}
            continue
        out = _section_subprocess(name, timeout)
        # hang_kind: None = section completed (possibly rc!=0);
        # "alive" = hung while probes answer; "outage" = tunnel's fault
        hang_kind = None
        if out.get("hang") and key in risky:
            # suspected backend-wedging cell: never retried (a second
            # attempt risks re-wedging for zero upside). One probe triages;
            # if the backend is unresponsive, spend the remaining wait
            # budget on recovery — the risky cells run LAST, so the budget
            # has no other claimant and a recovery lets the next risky cell
            # still get its window (the observed hang model is a server-side
            # compile that outlives the killed client and finishes minutes
            # later).
            t0 = time.time()
            probe = _section_subprocess("probe", 180)
            wait_budget[0] -= time.time() - t0
            if probe.get("hang"):
                detail[key] = {"error": "hung and left the backend "
                                        "unresponsive (known-risky cell; "
                                        "not retried)"}
                wait_budget[0] -= timeout
                if not _wait_for_backend(wait_budget, detail):
                    backend_dead = True
            else:
                detail[key] = {"error": out["error"] + " (known-risky cell;"
                                        " backend still alive; not retried)"}
                alive_hangs += 1
            continue
        if out.get("hang"):
            # a hung section is EITHER a dead tunnel or a genuinely hung
            # compile — a 180s probe tells them apart. Backend alive →
            # record the section failure and move on; backend down → wait
            # it out and retry this section ONCE (rounds 3-4 showed the
            # tunnel can drop mid-run and return minutes later).
            t0 = time.time()
            probe = _section_subprocess("probe", 180)
            wait_budget[0] -= time.time() - t0
            if probe.get("hang"):
                # outage: the section's burned timeout counts against the
                # shared budget — an intermittent tunnel must not stretch
                # the run unboundedly via un-charged section hangs
                wait_budget[0] -= timeout
                detail.setdefault("mid_run_outages", []).append(key)
                if _wait_for_backend(wait_budget, detail):
                    out = _section_subprocess(name, timeout)
                    if out.get("hang"):
                        # retry hung too — triage AGAIN before blaming the
                        # section: a flapping tunnel is not an alive-hang
                        t0 = time.time()
                        p2 = _section_subprocess("probe", 180)
                        wait_budget[0] -= time.time() - t0
                        if p2.get("hang"):
                            hang_kind = "outage"
                            out = {"error": "hung across outage retry "
                                            "(tunnel flapping)"}
                        else:
                            hang_kind = "alive"
                else:
                    backend_dead = True
                    detail[key] = {"error": "backend lost mid-run; wait "
                                            "budget exhausted",
                                   "skip": "backend_dead"}
                    continue
            else:
                hang_kind = "alive"
        # consecutive-hang bookkeeping: alive-hangs count toward the
        # backstop, completed sections reset, outage-attributed hangs
        # leave the counter untouched
        if hang_kind == "alive":
            alive_hangs += 1
        elif hang_kind is None:
            alive_hangs = 0
        if "error" not in out:
            dev = out.pop("_device", None)
            if dev and "device" not in detail:
                detail["device"] = dev
            ledger.record(key, out, device=dev)
        detail[key] = out

    # final line over the MERGED detail (fresh + ledger): a resnet cell
    # captured by a killed earlier invocation still counts; a value of None
    # is unmistakably a failure, not a catastrophic-regression-shaped
    # measurement, and incomplete_cells names what was not measured
    line = _assemble_final(detail, section_keys)

    if "--gate" in sys.argv:
        # self-report regression vs the last good trajectory round: the
        # verdict rides INSIDE the line (detailed in docs/PROFILING.md);
        # the driver's exit-code contract is untouched
        here = os.path.dirname(os.path.abspath(__file__))
        idx = sys.argv.index("--gate")
        baseline = (sys.argv[idx + 1]
                    if idx + 1 < len(sys.argv)
                    and not sys.argv[idx + 1].startswith("-") else None)
        baseline = baseline or _latest_good_round(here)
        if baseline is None:
            line["gate"] = {"error": "no usable baseline round found"}
        else:
            res = _profiler().gate_files(baseline, current_data=line)
            line["gate"] = {"baseline": os.path.basename(baseline),
                            "verdict": res.verdict, "status": res.status,
                            "regressions": res.regressions,
                            "incomplete": res.incomplete}
            print(f"# gate vs {baseline}: {res.verdict}", file=sys.stderr)

    print(json.dumps(line))
    if line["value"] is None:
        sys.exit(1)


if __name__ == "__main__":
    if "--run-section" in sys.argv:
        _run_section(sys.argv[sys.argv.index("--run-section") + 1])
    else:
        main()
