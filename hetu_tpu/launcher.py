"""Yaml-driven local PS-cluster launcher.

Capability parity with the reference's ``python/hetu/launcher.py``: a yaml
file carries the shared DMLC_* env block plus a ``launch`` section with
scheduler/server/worker counts; roles run as local processes
(``python -m hetu_tpu.launcher cfg.yml -n 2 --sched`` starts PS roles only,
``launch(target, args)`` also forks workers running ``target``).

Uses the ``spawn`` start method: worker targets import JAX, and forking a
JAX-threaded parent deadlocks.
"""
from __future__ import annotations

import argparse
import multiprocessing
import os
import signal
import sys

import yaml

_procs: list = []


def _signal_handler(sig, frame):
    print("SIGINT caught, stopping cluster")
    for proc in _procs:
        proc.terminate()
    sys.exit(0)


def _apply_shared_env(settings):
    for k, v in settings.get("shared", {}).items():
        os.environ[k] = str(v)


def start_sched(env=None):
    os.environ.update(env or {})
    os.environ["DMLC_ROLE"] = "scheduler"
    from hetu_tpu.ps import server as srv
    srv.start_scheduler_from_env()
    try:
        srv.scheduler_wait()
    except RuntimeError as e:
        # bounded teardown wait timed out: print the diagnostic naming the
        # ranks that never checked out, still Finalize, and exit nonzero
        # (same contract as ps/_light_main.py's scheduler body)
        print(f"[hetu ps scheduler] {e}", file=sys.stderr)
        srv.stop_scheduler()
        sys.exit(1)
    srv.stop_scheduler()


def start_server(server_id=0, env=None):
    os.environ.update(env or {})
    os.environ["DMLC_ROLE"] = "server"
    os.environ.setdefault("SERVER_ID", str(server_id))
    # no DMLC_PS_SERVER_PORT -> the native server binds an OS-assigned port
    # itself (race-free) and registers the actual number with the scheduler
    import signal as _signal
    import threading
    from hetu_tpu.ps import server as srv
    srv.start_server_from_env()
    stop = threading.Event()
    _signal.signal(_signal.SIGTERM, lambda *_: stop.set())
    _signal.signal(_signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    srv.stop_server()


def start_worker(target, args, worker_id=0, env=None):
    os.environ.update(env or {})
    os.environ["DMLC_ROLE"] = "worker"
    os.environ.setdefault("WORKER_ID", str(worker_id))
    import hetu_tpu as ht
    ht.worker_init()
    try:
        target(args)
    finally:
        ht.worker_finish()


def launch(target, args):
    """Launch the yaml-described local cluster and run ``target(args)`` in
    every worker process (reference launcher.py:18-38).

    PS high availability: a ``ps_max_respawns`` count in the yaml's
    ``launch`` section (or env ``HETU_PS_MAX_RESPAWNS``) turns on continuous
    server snapshots + supervised auto-respawn + worker failover, with the
    same env knobs as ``heturun --ps-max-respawns`` (docs/FAULT_TOLERANCE.md).
    """
    settings = yaml.safe_load(open(args.config).read())
    _apply_shared_env(settings)
    n_servers = int(settings["launch"]["server"])
    max_respawns = int(settings["launch"].get(
        "ps_max_respawns", os.environ.get("HETU_PS_MAX_RESPAWNS", 0)))
    ps_ha = n_servers > 0 and max_respawns > 0
    env = dict(os.environ)
    ps_snap_created = None
    if ps_ha:
        # defaults land in the CHILD env only — the launcher parent's
        # environment is left alone
        from hetu_tpu.ps.supervisor import apply_ha_env_defaults
        ps_snap_created = apply_ha_env_defaults(env)
    ctx = multiprocessing.get_context("spawn")
    n_workers = int(settings["launch"]["worker"])
    args.num_local_worker = n_workers
    if settings["launch"].get("scheduler", 0):
        _procs.append(ctx.Process(target=start_sched, args=(env,)))
    server_procs = {}
    for i in range(n_servers):
        server_procs[i] = ctx.Process(target=start_server, args=(i, env))
        _procs.append(server_procs[i])
    workers = []
    for i in range(n_workers):
        p = ctx.Process(target=start_worker, args=(target, args, i, env))
        _procs.append(p)
        workers.append(p)
    signal.signal(signal.SIGINT, _signal_handler)
    for proc in _procs:
        proc.start()
    sup = None
    if ps_ha:
        from hetu_tpu.ps.supervisor import start_mp_supervisor
        sup = start_mp_supervisor(ctx, start_server, env, server_procs,
                                  _procs.append, max_respawns=max_respawns)
    fatal_reported = False
    for proc in workers:
        while True:
            proc.join(timeout=0.5 if sup is not None else None)
            if not proc.is_alive():
                break
            if sup is not None and sup.fatal and not fatal_reported:
                # PS tier permanently down: fail fast instead of letting
                # every worker grind through its failover deadline
                fatal_reported = True
                print(f"# hetu launcher: PS supervisor fatal: {sup.fatal}; "
                      "terminating workers", file=sys.stderr)
                for w in workers:
                    if w.is_alive():
                        w.terminate()
    # workers done: tear down PS roles
    if sup is not None:
        sup.stop()  # before terminate(): teardown is not a death
    for proc in _procs:
        if proc not in workers:
            proc.terminate()
            proc.join(timeout=10)
    if ps_snap_created:
        from hetu_tpu.ps.supervisor import cleanup_snapshot_root
        cleanup_snapshot_root(ps_snap_created)
    if fatal_reported:
        # workers were killed because the PS tier was permanently down —
        # a caller (or CI) must not see this run as a success
        raise RuntimeError(f"PS supervisor fatal: {sup.fatal}")


def main():
    signal.signal(signal.SIGINT, _signal_handler)
    parser = argparse.ArgumentParser(
        description="launch PS roles (scheduler/servers) from a yaml config")
    parser.add_argument("config")
    parser.add_argument("-n", type=int, default=1, help="number of servers")
    parser.add_argument("--sched", action="store_true",
                        help="also launch the scheduler")
    args = parser.parse_args()
    settings = yaml.safe_load(open(args.config).read())
    _apply_shared_env(settings)
    env = dict(os.environ)
    ctx = multiprocessing.get_context("spawn")
    if args.sched:
        _procs.append(ctx.Process(target=start_sched, args=(env,)))
    for i in range(args.n):
        _procs.append(ctx.Process(target=start_server, args=(i, env)))
    for proc in _procs:
        proc.start()
    for proc in _procs:
        proc.join()


if __name__ == "__main__":
    main()
