"""Tier B: static analysis of the *lowered* program.

Tier A sees the Op graph; Tier B sees what XLA will actually run, through the
hooks every ``SubExecutor`` already carries: ``_lowered()`` (StableHLO of the
latest executed step), ``dump_hlo`` and ``last_cost_analysis``. These checks
need at least one executed step — they answer "is the step program the step
program you meant to compile", which only exists after a run:

- **Recompilation detector** — each distinct feed/batch signature compiles a
  fresh XLA program. Signature churn (one python-int shape per step, an
  unpadded last batch, a host-side lr baked as a constant) silently turns a
  training loop into a compile loop. Budget is per-subexecutor.
- **Donation/aliasing check** — the training step donates params/slots/state
  buffers; if the lowered text carries no aliasing attributes the program
  double-buffers every parameter.
- **Host-transfer check** — host callbacks (``io_callback``, debug prints)
  inside the step serialize the device on the host round-trip every step.
- **Replicated-large-tensor lint** — a parameter replicated across a dp>1
  mesh spends ``dp * nbytes`` of HBM; cost-analysis byte counts put the
  program's total traffic next to the worst offenders (the GSPMD-style
  sharded-weight-update work in PAPERS.md is the fix this lint motivates).
"""
from __future__ import annotations

import os
from typing import Optional

import numpy as np

from .findings import Finding, WARN, NOTE

# replicated-large-tensor default threshold; see resolve_replicated_threshold
DEFAULT_REPLICATED_THRESHOLD = 64 << 20


def resolve_replicated_threshold(config=None) -> int:
    """Threshold for the replicated-large-tensor lint, resolved the usual
    way: an explicit ``AnalysisConfig(replicated_threshold_bytes=...)`` (or
    any config carrying that attribute) wins, then the
    ``HETU_REPLICATED_THRESHOLD_BYTES`` env (how CI tightens it for
    planner-chosen tp layouts), then the 64 MiB default."""
    t = getattr(config, "replicated_threshold_bytes", None)
    if t is None:
        t = os.environ.get("HETU_REPLICATED_THRESHOLD_BYTES")
    return DEFAULT_REPLICATED_THRESHOLD if t in (None, "") else int(t)


def _fmt_bytes(n) -> str:
    return f"{n / 1e6:.1f} MB" if n >= 1e6 else f"{n / 1e3:.1f} KB"

HOST_CALLBACK_MARKERS = (
    "xla_python_cpu_callback", "xla_ffi_python_cpu_callback",
    "xla_python_gpu_callback", "infeed", "outfeed",
)
DONATION_MARKERS = ("tf.aliasing_output", "jax.buffer_donor")

_SIG_PARTS = ("feed signature", "dataloader-batch signature",
              "optimizer host token", "PS staged-row shapes",
              "introspection cadence", "poisoned op")


def _sub_finding(sub, lint, severity, message) -> Finding:
    f = Finding(lint=lint, severity=severity, message=message,
                op_name=sub.name, op_type="SubExecutor",
                pass_name="lowered")
    f.op = sub
    return f


def _lowered_text(sub) -> Optional[str]:
    try:
        low = sub._lowered()
        return None if low is None else low.as_text()
    except Exception:  # noqa: BLE001 — diagnostics only
        return None


def _describe_sig_change(prev, cur) -> str:
    """Human-readable diff of two compile-cache keys."""
    changed = [name for name, a, b in zip(_SIG_PARTS, prev, cur) if a != b]
    if not changed:
        return "signatures differ in an unnamed component"
    detail = []
    for name, a, b in zip(_SIG_PARTS, prev, cur):
        if a != b:
            detail.append(f"{name}: {a!r} -> {b!r}")
    return "; ".join(detail)


def recompile_findings(sub, budget: int = 3) -> list[Finding]:
    """Flag a subexecutor whose compile cache outgrew ``budget`` distinct
    step signatures — the signature churn that turns steps into compiles.
    Counted over SHAPE signatures (``_base_sigs``) when available: the
    hetuscope cadence/poison variants of one signature are deliberate
    extra compiles, not churn."""
    cache = getattr(sub, "_compiled", None)
    if cache is None:
        return []
    # collapse the hetuscope cadence/poison variants (2 trailing key
    # components) onto their shape signature, preserving first-seen order:
    # both the count and the churn diff must describe SHAPE churn, not a
    # deliberate variant switch
    sigs = list(dict.fromkeys(
        k[:len(_SIG_PARTS) - 2] if len(k) > len(_SIG_PARTS) - 2 else k
        for k in cache))
    n = len(sigs)
    if n <= budget:
        return []
    churn = (f"; last change: {_describe_sig_change(sigs[-2], sigs[-1])}"
             if len(sigs) >= 2 else "")
    return [_sub_finding(
        sub, "recompile-budget", WARN,
        f"{n} distinct step programs compiled (budget {budget}) — "
        "the step signature churns across steps, so steps pay compile "
        f"latency instead of running{churn}. Pad batches (drop_last), fix "
        "feed shapes, or hoist host-side optimizer state")]


def donation_findings(sub) -> list[Finding]:
    """Training steps donate params/slots/op-state; a lowered program with no
    aliasing attribute re-allocates every buffer each step."""
    if not getattr(sub, "training", False):
        return []
    ex = sub.executor
    has_state = (bool(ex.param_nodes) or bool(sub.optimizer_nodes)
                 or bool(sub.stateful_nodes))
    if not has_state:
        return []
    txt = _lowered_text(sub)
    if txt is None:
        return []
    if not any(m in txt for m in DONATION_MARKERS):
        return [_sub_finding(
            sub, "donation-missing", WARN,
            "training step program carries no input/output buffer aliasing "
            "— params and optimizer state are double-buffered every step "
            "(HETU_NO_DONATE set, or donation lost in lowering)")]
    return []


def host_transfer_findings(sub) -> list[Finding]:
    """Host callbacks compiled INTO the step serialize the device on a
    host round-trip per step."""
    txt = _lowered_text(sub)
    if txt is None:
        return []
    out = []
    for marker in HOST_CALLBACK_MARKERS:
        if marker in txt:
            out.append(_sub_finding(
                sub, "host-transfer", WARN,
                f"compiled step program contains a host transfer "
                f"({marker!r}, {txt.count(marker)} site(s)) — every step "
                "blocks on a host round-trip; move the callback out of the "
                "step or gate it off the hot path"))
    return out


def cost_analysis_of(sub) -> Optional[dict]:
    """Cost analysis dict of the latest executed step, or None.
    ``SubExecutor.last_cost_analysis`` owns the jax-version normalization
    (0.4.x wraps the dict in a list); this is the analysis-side alias."""
    return sub.last_cost_analysis()


def replicated_tensor_findings(sub, threshold_bytes: Optional[int] = None
                               ) -> list[Finding]:
    """Parameters replicated (PartitionSpec ``P()``) across a dp>1 mesh with
    ``nbytes >= threshold`` — each replica burns a full copy of HBM and the
    update is recomputed everywhere (see PAPERS.md: automatic cross-replica
    sharding of the weight update). ``threshold_bytes=None`` resolves via
    :func:`resolve_replicated_threshold` (config attr → env → 64 MiB)."""
    cfg = sub.config
    if threshold_bytes is None:
        threshold_bytes = resolve_replicated_threshold(cfg)
    mesh = getattr(cfg, "mesh", None)
    dp = getattr(cfg, "dp_size", 1)
    if mesh is None or dp <= 1:
        return []
    ex = sub.executor
    topo_ids = {id(n) for n in sub.topo}
    cost = cost_analysis_of(sub) or {}
    prog_bytes = cost.get("bytes accessed")
    out = []
    for node in ex.param_nodes:
        if id(node) not in topo_ids:
            continue
        spec = cfg.param_specs.get(id(node))
        if spec is not None and any(s is not None for s in spec):
            continue  # sharded over some axis
        arr = ex.state["params"].get(id(node))
        nbytes = getattr(arr, "nbytes", 0)
        if nbytes >= threshold_bytes:
            extra = (f"; the step program moves "
                     f"{_fmt_bytes(prog_bytes)} total"
                     if prog_bytes else "")
            f = Finding.at(
                node, "replicated-large-tensor", WARN,
                f"parameter ({_fmt_bytes(nbytes)}) is fully replicated "
                f"across the {dp}-way dp axis — {dp}x HBM and a redundant "
                f"update on every replica{extra}; shard it with "
                "ht.dispatch or a param spec", "lowered")
            out.append(f)
    return out


def analyze_executor(executor, budget: int = 3,
                     large_tensor_bytes: Optional[int] = None
                     ) -> list[Finding]:
    """All Tier B checks over every subexecutor that has run at least one
    step. Gpipe subexecutors (their own per-stage programs) are skipped."""
    out: list[Finding] = []
    for sub in executor.subexecutors.values():
        if not hasattr(sub, "_compiled"):
            continue
        out.extend(recompile_findings(sub, budget))
        if getattr(sub, "_last_call", None) is not None:
            out.extend(donation_findings(sub))
            out.extend(host_transfer_findings(sub))
            out.extend(replicated_tensor_findings(sub, large_tensor_bytes))
    return out


class RecompileMonitor:
    """Per-subexecutor recompilation budget you can poll inside a training
    loop: ``monitor.check()`` returns NEW findings (a sub is re-reported only
    when its compile count grows past the last reported value)."""

    def __init__(self, executor, budget: int = 3):
        self.executor = executor
        self.budget = int(budget)
        self._reported: dict[str, int] = {}

    def check(self) -> list[Finding]:
        out = []
        for name, sub in self.executor.subexecutors.items():
            cache = getattr(sub, "_compiled", None)
            if cache is None:
                continue
            base = getattr(sub, "_base_sigs", None)
            n = len(base) if base else len(cache)
            if n > self.budget and n > self._reported.get(name, 0):
                self._reported[name] = n
                out.extend(recompile_findings(sub, self.budget))
        return out
