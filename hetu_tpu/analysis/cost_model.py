"""hetuplan cost model: prices for a layout candidate (docs/ANALYSIS.md
"Tier C: planning").

The planner (:mod:`planner`) searches layouts; this module prices them.
Three families of cost terms, all derived from define-time information:

- **Compute** — the hetuprof roofline formulas (``profiler.roofline_rows``)
  over hetulint's abstract shapes vs the assumed peaks: per op family,
  ``max(flops/peak_tflops, bytes/peak_gbs)``. Same math as
  ``hetuprof --roofline`` so a measured residual from one surface calibrates
  the other.
- **Communication** — analytic wire-byte formulas per leg: ring AllReduce
  (reduce-scatter + all-gather, the hetuq quantized decomposition priced
  exactly as ``comm_quant.allreduce_wire_report`` so planner claims and the
  exported ``hetu_comm_quant_*`` gauges agree), PS dense push/pull and PS
  sparse row traffic with the ``kQI8`` container's per-row scale overhead
  (EQuARX-style wire ratios, docs/COMM_QUANT.md), and the pipeline bubble
  fraction.
- **Memory** — per-device HBM projection in the AOT memory-gate
  decomposition (``peak = args + out + temp − alias``, the
  ``last_memory_analysis`` / ``__graft_entry__.aot_memory_check`` formula)
  so "would this candidate fit" is answered by the same algebra the gate
  enforces. ZeRO-1 shards optimizer slots over dp; remat scales the saved
  activations by ``remat_factor``.

Every number here is a MODEL against ASSUMED peaks (docs/ROOFLINE.md:
assumptions, not readings). :class:`Calibration` folds measured data back
in: per-family roofline residuals (the ``hetuprof --roofline --json``
table) and measured critical-path legs from a telemetry dir (PR 13's
``cp_legs`` machinery) — ``hetulint --plan --calibrate TEL_DIR``.
"""
from __future__ import annotations

import glob
import json
import math
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from ..telemetry import profiler as _prof
from ..comm_quant import DEFAULT_BLOCK, DEFAULT_MIN_SIZE

# assumed interconnect peaks, same env convention as the roofline peaks
# (docs/ROOFLINE.md): collective fabric (ICI-class) and the PS/host link
# (NIC-class) are different orders of magnitude, which is most of why the
# dense/sparse comm-mode split exists at all
DEFAULT_NET_GBS = float(os.environ.get("HETU_PEAK_NET_GBS", "45"))
DEFAULT_PS_GBS = float(os.environ.get("HETU_PEAK_PS_GBS", "12.5"))
# same env as the AOT memory gate (__graft_entry__.aot_memory_check)
DEFAULT_HBM_GB = float(os.environ.get("HETU_HBM_BUDGET_GB", "16"))


@dataclass
class CostModelConfig:
    """Assumed peaks + model knobs. All overridable per call; the defaults
    come from the same envs the roofline and the AOT gate read."""

    peak_tflops: float = None
    peak_gbs: float = None
    net_gbs: float = None          # collective fabric, per device
    ps_gbs: float = None           # PS/host link, per server
    ps_servers: int = 1
    hbm_budget_gb: float = None
    quant_block: int = DEFAULT_BLOCK
    quant_min_size: int = DEFAULT_MIN_SIZE
    # fraction of saved activations remat keeps live (stage boundaries)
    remat_factor: float = 0.3
    # pipeline microbatch count for the bubble model (config.gpipe_microbatches
    # overrides when declared)
    microbatches: int = 4

    def __post_init__(self):
        if self.peak_tflops is None:
            self.peak_tflops = _prof.DEFAULT_PEAK_TFLOPS
        if self.peak_gbs is None:
            self.peak_gbs = _prof.DEFAULT_PEAK_GBS
        if self.net_gbs is None:
            self.net_gbs = DEFAULT_NET_GBS
        if self.ps_gbs is None:
            self.ps_gbs = DEFAULT_PS_GBS
        if self.hbm_budget_gb is None:
            self.hbm_budget_gb = DEFAULT_HBM_GB


# ---------------------------------------------------------------------------
# comm-leg algebra (pure, unit-tested against hand-computed formulas)
# ---------------------------------------------------------------------------

def ring_allreduce_bytes(n_elems: int, dp: int, quant: Optional[str] = None,
                         block: int = DEFAULT_BLOCK) -> Dict[str, float]:
    """Per-device wire bytes of one ring all-reduce of ``n_elems`` f32.

    The ring moves ``(dp-1)/dp`` of the payload per leg; the two legs are
    reduce-scatter + all-gather. The hetuq decomposition keeps the
    reduce-scatter exact (f32 — the accumulation never sees quantization
    error) and compresses only the all-gather leg to 1 byte/elem + one f32
    scale per ``block`` (comm_quant.quantized_allreduce). Returns
    ``{"raw", "wire", "ratio"}`` — raw is the all-f32 wire, wire the one
    this quant choice actually moves."""
    if dp <= 1:
        return {"raw": 0.0, "wire": 0.0, "ratio": 1.0}
    frac = (dp - 1) / dp
    rs = 4.0 * n_elems * frac
    ag_raw = 4.0 * n_elems * frac
    raw = rs + ag_raw
    if quant in ("int8", "fp8"):
        nb = -(-n_elems // block)
        wire = rs + (n_elems + 4.0 * nb) * frac
    else:
        wire = raw
    return {"raw": raw, "wire": wire,
            "ratio": raw / wire if wire else 1.0}


def ps_dense_bytes(n_elems: int, quant: Optional[str] = None,
                   block: int = DEFAULT_BLOCK) -> Dict[str, float]:
    """Per-worker per-step PS wire bytes for a dense param: one gradient
    push + one value pull, each ``4n`` raw or the ``kQI8`` container
    (1 byte/elem + one f32 scale per 256-elem block) when quantized —
    csrc/ps/net.h's dense layout."""
    leg_raw = 4.0 * n_elems
    if quant in ("int8", "kQI8"):
        nb = -(-n_elems // block)
        leg = float(n_elems) + 4.0 * nb
    else:
        leg = leg_raw
    raw = 2.0 * leg_raw
    wire = 2.0 * leg
    return {"raw": raw, "wire": wire,
            "ratio": raw / wire if wire else 1.0}


def ps_sparse_bytes(rows: float, dim: int, quant: Optional[str] = None
                    ) -> Dict[str, float]:
    """Per-worker per-step PS wire bytes for a lookup-accessed table:
    ``rows`` touched rows of width ``dim`` move twice (pull the rows, push
    the row gradients), each with an int64 row id. The ``kQI8`` sparse
    layout is row-wise: 1 byte/elem + ONE f32 scale per row
    (csrc/ps/net.h), so the ratio approaches 4x as ``dim`` grows."""
    ids = 8.0 * rows
    leg_raw = 4.0 * rows * dim + ids
    if quant in ("int8", "kQI8"):
        leg = rows * dim + 4.0 * rows + ids
    else:
        leg = leg_raw
    return {"raw": 2.0 * leg_raw, "wire": 2.0 * leg,
            "ratio": leg_raw / leg if leg else 1.0}


def expected_unique(vocab: int, lookups: float) -> float:
    """Expected distinct rows touched by ``lookups`` uniform draws from a
    ``vocab``-row table: ``V·(1 − (1−1/V)^L)``. Uniform is the coarse
    prior — real CTR streams are zipfian (fewer uniques); the planner only
    needs the order of magnitude, and calibration absorbs the rest."""
    if vocab <= 0 or lookups <= 0:
        return 0.0
    return float(vocab) * (1.0 - (1.0 - 1.0 / vocab) ** float(lookups))


def pipeline_bubble(pp: int, microbatches: int) -> float:
    """GPipe bubble fraction: ``(pp−1)/(m+pp−1)`` of the step is idle
    ramp-up/drain."""
    if pp <= 1:
        return 0.0
    m = max(1, int(microbatches))
    return (pp - 1) / (m + pp - 1)


# ---------------------------------------------------------------------------
# calibration — measured data folded back into the model
# ---------------------------------------------------------------------------

@dataclass
class Calibration:
    """Measured corrections for the analytic model.

    - ``family_residual``: op family -> measured/predicted multiplier, the
      residual column of ``hetuprof --roofline --json``.
    - ``legs_ms``: mean measured critical-path legs (feed/ps_pull/compute/
      ps_push/poststep) from a telemetry dir — PR 13's ``cp_legs``.
    - ``step_ms``: mean measured steady-state step time.

    The compute residual is leg-level: measured compute leg over the
    model's single-device compute prediction for the SAME graph (so
    calibrate with a run of the graph being planned). Host overhead
    (feed + poststep legs) is additive and layout-invariant in the model.
    """

    family_residual: Dict[str, float] = field(default_factory=dict)
    legs_ms: Dict[str, float] = field(default_factory=dict)
    step_ms: Optional[float] = None
    source: str = ""
    # single-device uncalibrated compute prediction for the GRAPH THE
    # MEASUREMENT CAME FROM — makes the compute residual a true
    # graph-independent ratio (the bench cell's cross-size prediction
    # sets it). Unset, the residual is taken against the planned graph's
    # own baseline — correct under the documented same-graph contract of
    # ``hetulint --plan --calibrate``.
    baseline_compute_ms: Optional[float] = None

    @property
    def host_ms(self) -> float:
        """Measured feed + poststep wall time per step (additive,
        layout-invariant in the model)."""
        return (self.legs_ms.get("feed", 0.0)
                + self.legs_ms.get("poststep", 0.0))

    @property
    def measured_work_ms(self) -> Optional[float]:
        """Measured per-step device-work window: the wall step minus the
        host legs and the PS waits. NOT the dispatch stamp — the executor
        dispatches asynchronously, so the compute leg alone undercounts
        the device time that drains between stamps; the wall remainder is
        what the work actually cost."""
        if self.step_ms:
            work = (float(self.step_ms) - self.host_ms
                    - self.legs_ms.get("ps_pull", 0.0)
                    - self.legs_ms.get("ps_push", 0.0))
            if work > 0:
                return work
        v = self.legs_ms.get("compute")
        return float(v) if v else None

    @property
    def measured_ps_ms(self) -> Optional[float]:
        v = (self.legs_ms.get("ps_pull", 0.0)
             + self.legs_ms.get("ps_push", 0.0))
        return float(v) if v else None

    def as_dict(self) -> dict:
        return {"source": self.source, "step_ms": self.step_ms,
                "legs_ms": {k: round(v, 4)
                            for k, v in self.legs_ms.items()},
                "family_residual": {k: round(v, 4) for k, v
                                    in self.family_residual.items()}}


def _residuals_from_roofline_doc(doc) -> Dict[str, float]:
    """Family residuals out of a ``hetuprof --roofline --json`` document —
    either the structured ``{"kind": "roofline", "rows": [...]}`` form or
    the bare row list."""
    rows = doc.get("rows", []) if isinstance(doc, dict) else doc
    out: Dict[str, float] = {}
    for r in rows if isinstance(rows, list) else []:
        if not isinstance(r, dict):
            continue
        fam, resid = r.get("family"), r.get("residual")
        if fam and isinstance(resid, (int, float)) and resid > 0 \
                and math.isfinite(resid):
            out[fam] = float(resid)
    return out


def load_calibration(path: str) -> Calibration:
    """Build a :class:`Calibration` from measured artifacts.

    ``path`` may be a telemetry directory (metrics-r*.jsonl step records →
    mean critical-path legs + step time; any ``roofline*.json`` files in it
    → family residuals) or a single roofline-JSON file. Missing pieces
    degrade silently — a calibration of nothing is the uncalibrated model.
    """
    cal = Calibration(source=path)
    if os.path.isfile(path):
        try:
            with open(path) as f:
                cal.family_residual = _residuals_from_roofline_doc(
                    json.load(f))
        except (OSError, ValueError):
            pass
        return cal
    if not os.path.isdir(path):
        return cal
    records = _prof.read_metrics_records(path)
    means = _prof.step_phase_means(records)
    if means:
        cal.step_ms = means.get("step_ms")
        cal.legs_ms = {k: float(v)
                       for k, v in _prof.cp_legs(means).items()}
    # live hetuwatch stream (docs/OBSERVABILITY.md pillar 6): a watched
    # run's kind:"watch" rows carry per-family EWMA residuals and measured
    # legs continuously — calibration no longer needs a dedicated offline
    # run. The last (most-converged) row wins; rows from a stale elastic
    # era abstain and carry no residuals, so they contribute nothing.
    watch_rows = [r for r in records
                  if r.get("kind") == "watch" and "abstain" not in r]
    if watch_rows:
        last = watch_rows[-1]
        fams = last.get("families")
        if isinstance(fams, dict):
            for fam, resid in fams.items():
                if isinstance(resid, (int, float)) and resid > 0 \
                        and math.isfinite(resid):
                    cal.family_residual.setdefault(fam, float(resid))
        if not cal.legs_ms:
            # no step records in the dir (e.g. a pruned watch-only
            # stream): the watch rows themselves supply the legs
            legs_sum: Dict[str, float] = {}
            for r in watch_rows:
                for leg, v in (r.get("legs") or {}).items():
                    legs_sum[leg] = legs_sum.get(leg, 0.0) + float(v)
            cal.legs_ms = {k: v / len(watch_rows)
                           for k, v in legs_sum.items()}
            cal.step_ms = sum(float(r.get("step_ms", 0.0))
                              for r in watch_rows) / len(watch_rows)
    # explicit roofline docs override the watch stream's leg-level prior
    for p in sorted(glob.glob(os.path.join(path, "roofline*.json"))):
        try:
            with open(p) as f:
                cal.family_residual.update(
                    _residuals_from_roofline_doc(json.load(f)))
        except (OSError, ValueError):
            continue
    return cal


# ---------------------------------------------------------------------------
# per-parameter profiles
# ---------------------------------------------------------------------------

@dataclass
class ParamProfile:
    """What the comm-mode decision needs to know about one trainable var."""

    name: str
    size: int                      # elements
    nbytes: int
    dim: int                       # trailing dim (row width for tables)
    sparse: bool                   # read through an embedding lookup
    touched_rows: float = 0.0      # expected distinct rows per step
    density: float = 1.0           # touched_rows / vocab
    tp_sharded: bool = False       # a dispatch marker pins its layout
    slot_factor: int = 0           # optimizer state copies (Adam=2, SGD=0)
    forced_ps: bool = False        # an explicit PS push pins it to PS
    node: object = None            # live PlaceholderOp handle

    @property
    def vocab(self) -> int:
        return self.size // max(1, self.dim)


_SLOT_FACTORS = {"AdamOptimizer": 2, "AdamWOptimizer": 2,
                 "MomentumOptimizer": 1, "AdaGradOptimizer": 1,
                 "SGDOptimizer": 0}


def param_profiles(topo, abstract, ps_embed_ids=frozenset()
                   ) -> List[ParamProfile]:
    """Profiles for every optimizer-managed trainable variable.

    Sparse classification is STRUCTURAL, no hand hints: any variable read
    through an embedding lookup (``embed_node``) is sparse — the same rule
    the executor applies at build. Touched rows come from the lookup
    index shapes under the uniform-draw expectation; an explicit
    ``embedding_lookup_gradient_op`` routed to a PS push (the PR-12 rows
    route) counts through its own index input.
    """
    from ..graph.node import PlaceholderOp
    from ..graph.ops.comm import DispatchOp

    lookup_elems: Dict[int, float] = {}
    # (table id, index-node id) pairs already counted: a lookup and the
    # explicit rows-route grad op share ONE index tensor — the grad push
    # covers the same rows the lookup pulled, not an additional batch
    counted: set = set()
    sparse_ids: set = set(ps_embed_ids)
    by_name: Dict[str, object] = {}

    def count_lookup(var, idx_node):
        idx_shape = abstract.shape_of(idx_node)
        if not idx_shape or (id(var), id(idx_node)) in counted:
            return
        counted.add((id(var), id(idx_node)))
        lookup_elems[id(var)] = (lookup_elems.get(id(var), 0.0)
                                 + float(np.prod(idx_shape)))

    for node in topo:
        if isinstance(node, PlaceholderOp) and node.trainable:
            by_name.setdefault(node.name, node)
        embed = getattr(node, "embed_node", None)
        if embed is not None and getattr(embed, "trainable", False):
            sparse_ids.add(id(embed))
            if len(node.inputs) > 1:
                count_lookup(embed, node.inputs[1])
        # PR-12 rows route: an explicit embed-grad op names its table via
        # the consuming push's ps_id; its index input sizes the traffic
        if getattr(node, "opname", None) == "EmbeddingLookUpGradient":
            for consumer in topo:
                if getattr(consumer, "ps_id", None) is not None \
                        and node in consumer.inputs:
                    var = by_name.get(consumer.ps_id)
                    if var is not None and len(node.inputs) > 1:
                        sparse_ids.add(id(var))
                        count_lookup(var, node.inputs[1])

    tp_pinned: set = set()
    for node in topo:
        if isinstance(node, DispatchOp) \
                and getattr(node.inputs[0], "trainable", False):
            tp_pinned.add(id(node.inputs[0]))

    out: List[ParamProfile] = []
    seen: set = set()

    def profile(var, slot_factor, forced_ps=False):
        if id(var) in seen:
            return
        seen.add(id(var))
        shape = (abstract.shape_of(var)
                 or tuple(getattr(var, "shape", ()) or ()))
        if not shape:
            return
        size = int(np.prod(shape))
        dim = int(shape[-1]) if len(shape) > 1 else 1
        itemsize = np.dtype(getattr(var, "dtype", np.float32)).itemsize
        sparse = id(var) in sparse_ids
        touched = 0.0
        density = 1.0
        if sparse:
            vocab = size // max(1, dim)
            touched = expected_unique(vocab,
                                      lookup_elems.get(id(var), 0.0))
            density = touched / vocab if vocab else 1.0
        out.append(ParamProfile(
            name=var.name, size=size, nbytes=size * itemsize, dim=dim,
            sparse=sparse, touched_rows=touched, density=density,
            tp_sharded=id(var) in tp_pinned, slot_factor=slot_factor,
            forced_ps=forced_ps, node=var))

    for node in topo:
        if not node.is_optimizer:
            continue
        slot_factor = _SLOT_FACTORS.get(type(node.optimizer).__name__, 1)
        for var in getattr(node, "vars", ()):
            profile(var, slot_factor)
    # params synced only through an explicit PS push (the rows-route
    # pattern): no OptimizerOp manages them worker-side — the server owns
    # the update, and the push op is a structural commitment to PS the
    # planner must respect (removing it would change the graph, not just
    # the layout)
    for node in topo:
        ps_id = getattr(node, "ps_id", None)
        if ps_id is not None and ps_id in by_name:
            profile(by_name[ps_id], 0, forced_ps=True)
    return out


# ---------------------------------------------------------------------------
# the cost model proper
# ---------------------------------------------------------------------------

class CostModel:
    """Prices one graph's compute/comm/memory for any layout candidate.

    Built once per planning run from the topo + abstract shapes; the
    planner then queries it per (dp, tp, pp, zero1, remat, per-param comm
    assignment) candidate. ``calibration`` (optional) folds measured
    residuals in — see :class:`Calibration`.
    """

    def __init__(self, topo, abstract, cmc: Optional[CostModelConfig] = None,
                 calibration: Optional[Calibration] = None,
                 training: bool = True, config=None,
                 ps_embed_ids=frozenset()):
        self.topo = list(topo)
        self.abstract = abstract
        self.cmc = cmc or CostModelConfig()
        self.calibration = calibration
        self.training = training
        self.config = config          # HetuConfig / AnalysisConfig or None
        # roofline families over the same abstract shapes hetuprof uses —
        # one source of truth for the compute prediction
        self.roofline = _prof.roofline_rows(
            self.topo, training=training,
            peak_tflops=self.cmc.peak_tflops, peak_gbs=self.cmc.peak_gbs)
        self.params = param_profiles(self.topo, abstract,
                                     ps_embed_ids=ps_embed_ids)
        self._act_bytes = self._activation_bytes()
        self._feed_bytes = self._feed_input_bytes()

    # -- structural capabilities ---------------------------------------
    @property
    def tp_able(self) -> bool:
        from ..graph.ops.comm import DispatchOp
        return any(isinstance(n, DispatchOp) for n in self.topo)

    @property
    def pp_able(self) -> bool:
        from ..graph.ops.comm import PipelineSendOp
        return (any(isinstance(n, PipelineSendOp) for n in self.topo)
                or bool(getattr(self.config, "gpipe", False)))

    # -- compute -------------------------------------------------------
    def base_compute_ms(self, calibrated: bool = True) -> float:
        """Single-device per-step compute prediction: sum of per-family
        roofline times, each scaled by its measured residual when the
        calibration carries one."""
        total_us = 0.0
        fr = (self.calibration.family_residual
              if calibrated and self.calibration else {})
        for r in self.roofline:
            total_us += r.predicted_us * fr.get(r.family, 1.0)
        return total_us / 1e3

    def compute_ms(self, dp: int, tp: int = 1, remat: bool = False) -> float:
        """Per-step compute for a candidate: batch-linear work divides by
        dp (each replica computes its shard) and matmul-class work by tp;
        the optimizer update is per-parameter and does not shrink with dp.
        Remat re-runs the forward inside backward: +1 forward on the 3x
        fwd+bwd+bwd training multiplier (~+33% matmul compute)."""
        fr = (self.calibration.family_residual if self.calibration else {})
        opt_us = 0.0
        rest_us = 0.0
        mm_us = 0.0
        for r in self.roofline:
            us = r.predicted_us * fr.get(r.family, 1.0)
            if r.family.startswith("Optimizer"):
                opt_us += us
            elif r.family in _prof._MATMUL_FAMILIES \
                    or r.family in _prof._CONV_FAMILIES:
                mm_us += us
            else:
                rest_us += us
        if remat and self.training:
            mm_us *= 4.0 / 3.0
            rest_us *= 1.5
        ms = (opt_us + (mm_us / max(1, tp) + rest_us) / max(1, dp)) / 1e3
        # leg-level residual: measured work window over the calibration
        # run's predicted compute — a RATIO, so it corrects everything the
        # family residuals missed (real vs assumed peaks, fusion, runtime
        # drain) and transfers across graph sizes. The baseline is the
        # measured graph's own prediction when the calibration carries it
        # (bench's cross-size cell); otherwise this graph's — the
        # documented same-graph --calibrate contract.
        if self.calibration and self.calibration.measured_work_ms:
            base = (self.calibration.baseline_compute_ms
                    or self.base_compute_ms(calibrated=True))
            if base > 0:
                ms *= self.calibration.measured_work_ms / base
        return ms

    # -- communication -------------------------------------------------
    def allreduce_ms(self, decisions, dp: int) -> float:
        """Ring-AllReduce time for every param assigned AllReduce."""
        if dp <= 1:
            return 0.0
        wire = 0.0
        for d in decisions:
            if d.mode != "AllReduce":
                continue
            wire += ring_allreduce_bytes(
                d.size_elems, dp, quant=d.quant,
                block=self.cmc.quant_block)["wire"]
        return wire / (self.cmc.net_gbs * 1e9) * 1e3

    def ps_ms(self, decisions, dp: int) -> float:
        """PS traffic time: every worker's push+pull bytes land on the
        server links (``ps_servers`` × ``ps_gbs``) — the PS tier's
        bottleneck is the server side once dp grows."""
        per_worker_ms = self._uncal_ps_ms_single(decisions)
        ms = per_worker_ms * max(1, dp)
        if ms > 0 and self.calibration \
                and self.calibration.measured_ps_ms \
                and per_worker_ms > 0:
            # leg residual only when the measured run exercised the PS
            # path; the single-worker prediction is the residual baseline
            ms *= self.calibration.measured_ps_ms / per_worker_ms
        return ms

    def _uncal_ps_ms_single(self, decisions) -> float:
        """One worker's PS push+pull time — ONE copy of the per-decision
        wire pricing (ps_ms scales and residual-corrects it)."""
        per_worker = 0.0
        for d in decisions:
            if d.mode != "PS":
                continue
            if d.sparse:
                per_worker += ps_sparse_bytes(
                    d.touched_rows, d.dim, quant=d.quant)["wire"]
            else:
                per_worker += ps_dense_bytes(
                    d.size_elems, quant=d.quant,
                    block=self.cmc.quant_block)["wire"]
        return per_worker / (self.cmc.ps_servers * self.cmc.ps_gbs * 1e9) \
            * 1e3

    def host_ms(self) -> float:
        """Measured feed/poststep overhead (layout-invariant additive term);
        zero without calibration — the analytic model cannot see it."""
        return self.calibration.host_ms if self.calibration else 0.0

    # -- memory (the AOT-gate decomposition) ---------------------------
    def _activation_bytes(self) -> int:
        total = 0
        for node in self.topo:
            if node.is_placeholder or node.is_dataloader \
                    or node.is_optimizer or node.is_gradient:
                continue
            m = self.abstract.meta.get(id(node))
            total += _prof._nbytes(m) if m is not None else 0
        return total

    def _feed_input_bytes(self) -> int:
        total = 0
        for node in self.topo:
            if not (node.is_dataloader
                    or (node.is_placeholder
                        and getattr(node, "is_feed", False))):
                continue
            m = self.abstract.meta.get(id(node))
            total += _prof._nbytes(m) if m is not None else 0
        return total

    def memory(self, dp: int, tp: int = 1, pp: int = 1,
               ps_resident=frozenset(), zero1: bool = False,
               remat: bool = False) -> Dict[str, float]:
        """Projected per-device HBM in the AOT-gate decomposition.

        ``ps_resident``: param ids hosted server-side (they cost the
        device nothing). Params replicate over dp (the lint this planner
        automates away is exactly that cost); tp-pinned params shard over
        tp; ZeRO-1 shards optimizer slots over dp; remat keeps
        ``remat_factor`` of the saved activations. peak = args + out +
        temp − alias, alias = donated params + slots.
        """
        param_b = slot_b = grad_b = 0.0
        for p in self.params:
            if id(p.node) in ps_resident:
                continue
            local = p.nbytes / (tp if p.tp_sharded else 1) / max(1, pp)
            param_b += local
            slot_b += local * p.slot_factor / (dp if zero1 else 1)
            grad_b += local
        act = self._act_bytes / max(1, dp) / max(1, pp)
        if self.training:
            act *= 2.0              # forward values saved for backward
            if remat:
                act *= self.cmc.remat_factor
        feeds = self._feed_bytes / max(1, dp)
        args = param_b + slot_b + feeds
        out_b = param_b + slot_b    # next-step state (aliased)
        alias = param_b + slot_b
        temp = act + (grad_b if self.training else 0.0)
        peak = args + out_b + temp - alias
        return {"argument_bytes": args, "output_bytes": out_b,
                "temp_bytes": temp, "alias_bytes": alias,
                "peak_bytes": peak,
                "peak_gib": peak / 2**30,
                "budget_gib": self.cmc.hbm_budget_gb,
                "feasible": peak / 2**30 <= self.cmc.hbm_budget_gb}
