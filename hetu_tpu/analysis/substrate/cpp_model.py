"""A micro-parser for the csrc/ps headers: just enough C++ to do lock-order
and protocol analysis, and not a token more.

This is NOT a C++ frontend. It is a purpose-built recognizer for the idioms
the PS runtime actually uses (docs/ANALYSIS.md "Tier D"): single-header
classes, ``std::lock_guard``/``unique_lock``/``shared_lock`` RAII guards
(including the deferred ``std::unique_lock<std::mutex> g;`` + later
``g = std::unique_lock<std::mutex>(m)`` re-bind pattern), manual
``mu.lock()/unlock()``, and plain-name intra-file calls. Anything fancier
(templates with dependent lock types, lock adoption, ``std::lock``) would
need new cases here — the seeded-defect tests in tests/test_substrate.py
pin the idioms that must keep parsing.

Straight-line release convention: a conditional unlock
(``if (cond) g.unlock();``) is modeled as an unconditional release at that
point, and the matching conditional re-lock as an unconditional re-acquire.
That is exactly the release-across-call shape the PR 16 deadlock fix
introduced (server.h serve_conn drops the client slot around ``handle()``),
so the shipped tree analyzes clean while the pre-fix fixture — which has no
release at all — still produces the ABBA cycle.
"""
from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# statement keywords that can never open a function definition
_STMT_KEYWORDS = frozenset((
    "if", "for", "while", "switch", "return", "else", "case", "catch",
    "do", "throw", "new", "delete", "sizeof", "static_assert", "using",
    "typedef", "goto", "break", "continue", "default",
))

# member-call names never linked as intra-file call edges: std containers,
# atomics, and condition variables share these names with nothing we model
_CALL_NOISE = frozenset((
    "wait", "wait_for", "wait_until", "notify_all", "notify_one",
    "lock", "unlock", "try_lock", "owns_lock",
    "load", "store", "exchange", "fetch_add", "fetch_sub",
    "size", "empty", "clear", "resize", "reserve", "assign",
    "push_back", "emplace_back", "pop_front", "push", "pop",
    "front", "back", "begin", "end", "at", "count", "find", "insert",
    "erase", "emplace", "get", "reset", "data", "c_str", "str", "substr",
    "append", "join", "detach", "open", "close", "swap", "min", "max",
    "move", "to_string", "make_shared", "make_pair", "string",
))


@dataclass
class LockEvent:
    """One lock-relevant statement, in source order inside a function."""

    kind: str       # "acquire" | "release" | "call" | "atomic_write"
    name: str       # resolved mutex label / callee name / atomic label
    line: int       # 1-based line in the source file
    depth: int      # brace depth at the statement (for scope-exit release)
    scoped: bool = False   # acquire only: released automatically at scope exit


@dataclass
class CppFunction:
    name: str
    cls: Optional[str]          # enclosing class, None for free functions
    file: str                   # basename, e.g. "server.h"
    start: int
    end: int
    events: List[LockEvent] = field(default_factory=list)

    @property
    def qualname(self) -> str:
        return f"{self.cls}::{self.name}" if self.cls else self.name


@dataclass
class CppClass:
    name: str
    file: str
    mutexes: set = field(default_factory=set)
    atomics: set = field(default_factory=set)
    cvs: set = field(default_factory=set)


@dataclass
class CppSource:
    """One parsed header: classes, functions, and a var-name -> class map."""

    path: str
    name: str                   # basename
    text: str                   # comment/string-stripped, line-preserving
    classes: Dict[str, CppClass] = field(default_factory=dict)
    functions: List[CppFunction] = field(default_factory=list)
    var_types: Dict[str, str] = field(default_factory=dict)


def strip_noise(text: str) -> str:
    """Blank out comments, string and char literals — preserving every
    newline so line numbers survive — then return the cleaned text."""
    out = []
    i, n = 0, len(text)
    while i < n:
        c = text[i]
        nxt = text[i + 1] if i + 1 < n else ""
        if c == "/" and nxt == "/":
            j = text.find("\n", i)
            if j < 0:
                break
            out.append("\n")
            i = j + 1
        elif c == "/" and nxt == "*":
            j = text.find("*/", i + 2)
            j = n if j < 0 else j + 2
            out.append("\n" * text.count("\n", i, j))
            i = j
        elif c in "\"'":
            q = c
            j = i + 1
            while j < n and text[j] != q:
                j += 2 if text[j] == "\\" else 1
            out.append(q + q)
            i = j + 1
        else:
            out.append(c)
            i += 1
    return "".join(out)


_RE_CLASS = re.compile(r"^\s*(?:class|struct)\s+([A-Za-z_]\w*)\b(?!.*;\s*$)")
_RE_MUTEX_MEMBER = re.compile(
    r"^\s*(?:mutable\s+)?(?:std::)?(?:shared_)?mutex\s+"
    r"([A-Za-z_]\w*(?:\s*,\s*[A-Za-z_]\w*)*)\s*(?:\[[^;]*\])?\s*;")
_RE_CV_MEMBER = re.compile(
    r"^\s*(?:std::)?condition_variable(?:_any)?\s+"
    r"([A-Za-z_]\w*(?:\s*,\s*[A-Za-z_]\w*)*)\s*;")
_RE_ATOMIC_MEMBER = re.compile(
    r"^\s*(?:std::)?atomic<[^>]*>\s+([A-Za-z_]\w*)\s*[;{]")
_RE_FUNC_NAME = re.compile(r"([A-Za-z_]\w*)\s*\($")
_RE_GUARD_DECL = re.compile(
    r"(?:std::)?(lock_guard|unique_lock|shared_lock|scoped_lock)"
    r"\s*<[^>]*>\s+([A-Za-z_]\w*)\s*[({]([^;]*?)[)}]\s*;")
_RE_GUARD_DEFER = re.compile(
    r"(?:std::)?(unique_lock|shared_lock)\s*<[^>]*>\s+([A-Za-z_]\w*)\s*;")
_RE_GUARD_ASSIGN = re.compile(
    r"\b([A-Za-z_]\w*)\s*=\s*(?:std::)?(?:unique_lock|shared_lock)"
    r"\s*<[^>]*>\s*\(([^;]*?)\)\s*;")
_RE_LOCK_OP = re.compile(r"\b([A-Za-z_]\w*)\s*(?:\.|->)\s*(lock|unlock)\s*\(\s*\)")
_RE_CALL = re.compile(r"(?<![\w.])([A-Za-z_]\w*)\s*\(")
_RE_MEMBER_CALL = re.compile(r"[\w)\]]\s*(?:\.|->)\s*([A-Za-z_]\w*)\s*\(")
_RE_VAR_PTR = re.compile(r"\b([A-Z]\w*)\s*\*\s*(?:const\s+)?([a-z_]\w*)\b")
_RE_VAR_REF = re.compile(r"\b([A-Z]\w*)\s*&\s*([a-z_]\w*)\b")
_RE_MUTEX_EXPR = re.compile(
    r"^\s*\*?\s*(?:([A-Za-z_]\w*)\s*(?:\.|->)\s*)?([A-Za-z_]\w*)")


def _join_header(lines: List[str], start: int, max_span: int = 10):
    """Join a candidate function-definition header until its parens balance
    and a ``{`` or ``;`` decides it. Returns (joined, end_index, opener)."""
    buf = ""
    for j in range(start, min(start + max_span, len(lines))):
        buf += " " + lines[j]
        bal = buf.count("(") - buf.count(")")
        if bal <= 0:
            body = buf
            # past the closing paren of the arg list: ctor init lists and
            # const/noexcept qualifiers may precede the brace
            brace = body.find("{", body.rfind(")"))
            semi = body.find(";", body.rfind(")"))
            if brace >= 0 and (semi < 0 or brace < semi):
                return buf, j, "{"
            if semi >= 0:
                return buf, j, ";"
            if j + 1 < len(lines) and "{" not in buf and ";" not in buf:
                continue  # init list on following lines
    return buf, start, None


class CppModel:
    """All parsed sources plus the cross-file class map, so ``slot->mu``
    in server.h resolves against ``Param``/``ClientSlot`` wherever they
    were declared."""

    def __init__(self, sources: List[CppSource]):
        self.sources = sources
        self.classes: Dict[str, CppClass] = {}
        for src in sources:
            self.classes.update(src.classes)
        self.functions: Dict[Tuple[str, str], CppFunction] = {}
        for src in sources:
            for fn in src.functions:
                self.functions.setdefault((src.name, fn.name), fn)

    def resolve_mutex(self, expr: str, src: CppSource,
                      cls: Optional[str]) -> Optional[str]:
        """Mutex expression -> stable label. ``snap_mu_`` inside PsServer
        -> ``PsServer::snap_mu_``; ``slot->mu`` with ``ClientSlot* slot``
        in scope -> ``ClientSlot::mu``; an indexed ``server_mu_[i][j]``
        resolves by its base name. Unresolvable exprs get a per-variable
        label (conservative: never merges two locks that might differ)."""
        expr = expr.split(",")[0].strip()
        expr = re.sub(r"\[[^\]]*\]", "", expr)       # strip indexing
        m = _RE_MUTEX_EXPR.match(expr)
        if not m:
            return None
        recv, member = m.group(1), m.group(2)
        if recv is None:
            # bare name: enclosing-class member, else treat as local/global
            if cls and member in self.classes.get(cls, CppClass("", "")).mutexes:
                return f"{cls}::{member}"
            return member
        vcls = src.var_types.get(recv)
        if vcls and member in self.classes.get(vcls, CppClass("", "")).mutexes:
            return f"{vcls}::{member}"
        return f"{member}@{recv}"


def parse_source(path: str, text: Optional[str] = None) -> CppSource:
    """Parse one header. ``text`` overrides the file contents (fixtures)."""
    if text is None:
        with open(path, "r", encoding="utf-8", errors="replace") as f:
            text = f.read()
    stripped = strip_noise(text)
    lines = stripped.split("\n")
    src = CppSource(path=path, name=os.path.basename(path), text=stripped)

    # ---- pass 1: class extents + members, function extents --------------
    depth = 0
    # stack of (kind, name, body_depth); kind in {"class", "func", "other"}
    stack: List[Tuple[str, Optional[str], int]] = []
    i = 0
    while i < len(lines):
        line = lines[i]
        cur_class = next((n for k, n, _ in reversed(stack) if k == "class"),
                         None)
        in_func = any(k == "func" for k, _, _ in stack)

        handled_span = i
        if not in_func:
            mc = _RE_CLASS.match(line)
            opens_here = "{" in line
            if mc and (opens_here or (i + 1 < len(lines)
                                      and "{" in lines[i + 1])):
                name = mc.group(1)
                src.classes.setdefault(name, CppClass(name, src.name))
                stack.append(("class", name, depth + 1))
            elif cur_class is not None:
                mm = _RE_MUTEX_MEMBER.match(line)
                if mm:
                    for nm in re.split(r"\s*,\s*", mm.group(1)):
                        src.classes[cur_class].mutexes.add(nm)
                mv = _RE_CV_MEMBER.match(line)
                if mv:
                    for nm in re.split(r"\s*,\s*", mv.group(1)):
                        src.classes[cur_class].cvs.add(nm)
                ma = _RE_ATOMIC_MEMBER.match(line)
                if ma:
                    src.classes[cur_class].atomics.add(ma.group(1))
            if (not mc and "(" in line):
                first = re.match(r"\s*([A-Za-z_]\w*)", line)
                if first and first.group(1) not in _STMT_KEYWORDS \
                        and not line.lstrip().startswith("#"):
                    header, j, opener = _join_header(lines, i)
                    if opener == "{":
                        paren = header.find("(")
                        mname = re.search(r"([A-Za-z_~]\w*)\s*$",
                                          header[:paren])
                        if mname and mname.group(1) not in _STMT_KEYWORDS:
                            fn = CppFunction(name=mname.group(1),
                                             cls=cur_class, file=src.name,
                                             start=i + 1, end=i + 1)
                            src.functions.append(fn)
                            stack.append(("func", fn.name,
                                          depth + 1))
                            handled_span = j

        # advance depth over the full span we consumed
        for j in range(i, handled_span + 1):
            depth += lines[j].count("{") - lines[j].count("}")
        # close scopes whose body depth is now above current depth
        while stack and depth < stack[-1][2]:
            kind, name, _ = stack.pop()
            if kind == "func":
                for fn in reversed(src.functions):
                    if fn.name == name and fn.end == fn.start:
                        fn.end = handled_span + 1
                        break
        i = handled_span + 1

    # ---- pass 2: file-wide var-name -> class map -------------------------
    for regex in (_RE_VAR_PTR, _RE_VAR_REF):
        for m in regex.finditer(stripped):
            src.var_types.setdefault(m.group(2), m.group(1))
    return src


def extract_events(src: CppSource, model: CppModel) -> None:
    """Pass 3: per-function lock/call/atomic event streams, in source
    order, with straight-line release semantics (module docstring)."""
    lines = src.text.split("\n")
    for fn in src.functions:
        guards: Dict[str, Optional[str]] = {}     # guard var -> mutex label
        guard_depth: Dict[str, int] = {}
        scoped_at: List[Tuple[int, str]] = []     # (depth, label) lock_guard
        depth = 0
        events = fn.events
        atomics_here = set()
        for c in model.classes.values():
            atomics_here |= {(a, c.name) for a in c.atomics}
        atomic_names = {a: c for a, c in atomics_here}

        for ln in range(fn.start - 1, min(fn.end, len(lines))):
            line = lines[ln]
            lineno = ln + 1
            consumed_spans: List[Tuple[int, int]] = []

            for m in _RE_GUARD_DECL.finditer(line):
                style, gvar, args = m.group(1), m.group(2), m.group(3)
                consumed_spans.append(m.span())
                mutex_args = ([a for a in args.split(",")]
                              if style == "scoped_lock" else [args])
                for a in mutex_args:
                    label = model.resolve_mutex(a, src, fn.cls)
                    if not label:
                        continue
                    events.append(LockEvent("acquire", label, lineno, depth,
                                            scoped=True))
                    if style in ("unique_lock", "shared_lock"):
                        guards[gvar] = label
                        guard_depth[gvar] = depth
                    else:
                        scoped_at.append((depth, label))
            for m in _RE_GUARD_DEFER.finditer(line):
                consumed_spans.append(m.span())
                guards[m.group(2)] = None
                guard_depth[m.group(2)] = depth
            for m in _RE_GUARD_ASSIGN.finditer(line):
                gvar, arg = m.group(1), m.group(2)
                if gvar not in guards:
                    continue
                consumed_spans.append(m.span())
                if guards[gvar]:
                    events.append(LockEvent("release", guards[gvar],
                                            lineno, depth))
                label = model.resolve_mutex(arg, src, fn.cls)
                if label:
                    events.append(LockEvent("acquire", label, lineno, depth,
                                            scoped=True))
                    guards[gvar] = label
            for m in _RE_LOCK_OP.finditer(line):
                recv, op = m.group(1), m.group(2)
                consumed_spans.append(m.span())
                if recv in guards:
                    label = guards[recv]
                    if label is None:
                        continue
                    events.append(LockEvent(
                        "release" if op == "unlock" else "acquire",
                        label, lineno, depth, scoped=(op == "lock")))
                else:
                    label = model.resolve_mutex(recv, src, fn.cls)
                    if label and _is_known_mutex(label, model):
                        events.append(LockEvent(
                            "release" if op == "unlock" else "acquire",
                            label, lineno, depth, scoped=False))

            # atomic writes (only class-member atomics we parsed)
            for an, acls in atomic_names.items():
                if re.search(rf"\b{an}\s*(?:\.\s*(?:store|fetch_add|"
                             rf"fetch_sub|exchange)\s*\(|=(?!=)|\+\+)", line):
                    events.append(LockEvent("atomic_write",
                                            f"{acls}::{an}", lineno, depth))

            # calls (plain or member), minus std/cv noise. All are
            # recorded; lock_order propagates through same-file callees
            # and warns on the blocking set wherever it is defined.
            seen_calls = set()
            for m in list(_RE_CALL.finditer(line)) \
                    + list(_RE_MEMBER_CALL.finditer(line)):
                name = m.group(1)
                if name in _CALL_NOISE or name == fn.name \
                        or name in _STMT_KEYWORDS:
                    continue
                if any(a <= m.start(1) < b for a, b in consumed_spans):
                    continue
                if (name, m.start(1)) in seen_calls:
                    continue
                seen_calls.add((name, m.start(1)))
                events.append(LockEvent("call", name, lineno, depth))

            depth += line.count("{") - line.count("}")
            # scope exits release lock_guards and in-scope unique_locks: a
            # guard declared at statement depth d dies when depth sinks
            # BELOW d (its enclosing block's closing brace)
            still = []
            for d, label in scoped_at:
                if depth < d:
                    events.append(LockEvent("release", label, lineno, depth))
                else:
                    still.append((d, label))
            scoped_at = still
            for gvar in list(guards):
                if depth < guard_depth[gvar]:
                    if guards[gvar]:
                        events.append(LockEvent("release", guards[gvar],
                                                lineno, depth))
                    del guards[gvar], guard_depth[gvar]


def _is_known_mutex(label: str, model: CppModel) -> bool:
    if "::" in label:
        cls, member = label.split("::", 1)
        return member in model.classes.get(cls, CppClass("", "")).mutexes
    return label.endswith("mu_") or label.endswith("mu") \
        or "mutex" in label.lower()


def build_model(paths_or_texts) -> CppModel:
    """Parse a set of headers into one model. Items are either paths or
    ``(virtual_path, text)`` tuples (fixtures). Event extraction runs after
    all files parse so cross-file class lookups (Param in store.h, used in
    server.h) resolve."""
    sources = []
    for item in paths_or_texts:
        if isinstance(item, tuple):
            sources.append(parse_source(item[0], text=item[1]))
        else:
            sources.append(parse_source(item))
    model = CppModel(sources)
    for src in sources:
        extract_events(src, model)
    return model
