"""Tier D: jax-free static analysis of the PS runtime substrate.

Where Tiers A-C (docs/ANALYSIS.md) analyze the *graph*, Tier D analyzes the
*runtime underneath it*: the C++ parameter-server headers in
``hetu_tpu/csrc/ps``, the Python coordinators that speak their wire
protocol, and the docs that promise knobs/gauges/fault kinds. Three check
families, all pure-CPython text analysis (CI runs them on every commit
without a jax import or a compiled library):

- :mod:`lock_order` — parse mutex declarations and lock/unlock sites out of
  the headers, build per-function acquisition-order graphs with call-edge
  propagation, and report order cycles (the ABBA class of deadlock PR 16
  shipped a fix for), locks held across blocking calls, and atomics written
  under inconsistent guards.
- :mod:`drift` — diff ``hetu_tpu/ps/wire_constants.py`` (the ONE Python
  wire mirror) against the parsed C++ truth: PsfType/ArgType/ChaosKind/
  OptType enums, MsgHeader/ArgHeader layouts and field-reuse slots, every
  reply slot count, dispatch coverage, the ctypes C-API surface, and the
  registered cross-language mirror pairs (quantizer, backoff schedule).
- :mod:`surface` — diff what the code *does* against what the docs *say*:
  HETU_*/DMLC_* knobs read vs documented, hetu_* gauges emitted vs the
  OBSERVABILITY.md table, fault kinds in the registry vs the
  FAULT_TOLERANCE.md catalogue.

Entry point: ``bin/hetucheck [--json] [--check]`` (:mod:`cli`), reusing the
hetulint Finding/severity/suppression machinery and exit-code contract.
"""
from .cpp_model import CppModel, build_model, parse_source
from .drift import analyze_drift
from .lock_order import analyze_locks
from .surface import analyze_surface
