"""Lock-order analysis over the parsed header model (docs/ANALYSIS.md
"Tier D: substrate").

Per function we simulate the straight-line event stream from
:mod:`cpp_model`: every acquisition taken while another lock is held adds a
directed edge ``held -> new`` to the file's acquisition-order graph, and
every intra-file call is expanded through the callee's (memoized,
transitive) acquisition summary so the release-across-call pattern is
modeled exactly — a lock dropped before ``handle()`` contributes no edge, a
lock still held does. Graphs are per source file: server, worker, and
scheduler are separate processes, so a server-side mutex can never deadlock
against a worker-side one.

Findings:

- ``lock-order-cycle`` (error) — a cycle among distinct mutexes, reported
  with a witness acquisition stack (function + file:line for each leg).
  This is the ABBA that PR 16's pre-fix server shipped: dispatch held
  ``ClientSlot::mu`` across ``handle()`` into ``take_snapshot`` (which
  takes ``snap_take_mu_`` then walks slots) while the periodic
  ``snapshot_loop`` took ``snap_take_mu_`` first.
- ``lock-same-class-pair`` (note) — two locks with the same class label
  held at once (``p->mu`` + ``lp->mu``). Not provably a deadlock (distinct
  instances may be consistently ordered), so a note, not an error.
- ``lock-across-blocking`` (warn) — a lock held across a known blocking
  call (request dispatch, socket send/recv, snapshot IO).
- ``atomic-mixed-guard`` (note) — an atomic member written both under a
  lock and lock-free (or under different locks): either the lock is
  superfluous or the lock-free site is a race with the guarded invariant.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ..findings import ERROR, NOTE, WARN, Finding
from .cpp_model import CppFunction, CppModel

PASS = "lock_order"

# calls that block: request dispatch, socket IO, snapshot/trail file IO
BLOCKING_CALLS = frozenset((
    "handle", "send_msg", "recv_msg", "read_exact", "recv_exact",
    "read_all", "write_all", "rpc", "rpc_once", "connect_fd",
    "take_snapshot", "save_param_file", "trail_flush",
))


@dataclass(frozen=True)
class Acq:
    """One acquisition a function performs (transitively through calls)."""

    label: str
    site: str           # "file:line"
    func: str           # qualified function name
    chain: Tuple[str, ...] = ()   # call path, outermost first


@dataclass
class Edge:
    held: Acq
    taken: Acq

    def stack(self) -> str:
        via = "".join(f" -> {c}" for c in self.taken.chain)
        return (f"{self.held.func} acquires {self.held.label} at "
                f"{self.held.site}, then{via or ''} acquires "
                f"{self.taken.label} at {self.taken.site}")


def _summaries(model: CppModel, file: str) -> Dict[str, List[Acq]]:
    """func name -> every acquisition it performs, transitively through
    intra-file calls (recursion-guarded, memoized)."""
    memo: Dict[str, List[Acq]] = {}
    in_progress: Set[str] = set()

    def summary(fn: CppFunction) -> List[Acq]:
        if fn.name in memo:
            return memo[fn.name]
        if fn.name in in_progress:      # recursion: no new info on this path
            return []
        in_progress.add(fn.name)
        acqs: List[Acq] = []
        for ev in fn.events:
            if ev.kind == "acquire":
                acqs.append(Acq(ev.name, f"{file}:{ev.line}", fn.qualname))
            elif ev.kind == "call":
                callee = model.functions.get((file, ev.name))
                if callee is None:
                    continue
                frame = f"{callee.qualname}() [called at {file}:{ev.line}]"
                for a in summary(callee):
                    acqs.append(Acq(a.label, a.site, a.func,
                                    (frame,) + a.chain))
        in_progress.discard(fn.name)
        memo[fn.name] = acqs
        return acqs

    for fn in model.functions.values():
        if fn.file == file:
            summary(fn)
    return memo


def _simulate(model: CppModel, file: str,
              summaries: Dict[str, List[Acq]]):
    """Walk every function with an empty entry lock set; produce order
    edges, blocking-call warns, and atomic write-site guard sets."""
    edges: Dict[Tuple[str, str], List[Edge]] = {}
    blocking: List[Tuple[Acq, str, str]] = []      # (held, callee, site)
    atomic_writes: Dict[str, Set[frozenset]] = {}

    for fn in model.functions.values():
        if fn.file != file:
            continue
        held: List[Acq] = []
        for ev in fn.events:
            if ev.kind == "acquire":
                new = Acq(ev.name, f"{file}:{ev.line}", fn.qualname)
                for h in held:
                    # same-label edges kept: they feed lock-same-class-pair
                    edges.setdefault((h.label, new.label), []).append(
                        Edge(h, new))
                held.append(new)
            elif ev.kind == "release":
                for idx in range(len(held) - 1, -1, -1):
                    if held[idx].label == ev.name:
                        held.pop(idx)
                        break
            elif ev.kind == "call":
                if held and ev.name in BLOCKING_CALLS:
                    for h in held:
                        blocking.append((h, ev.name, f"{file}:{ev.line}"))
                if (file, ev.name) not in model.functions:
                    continue
                for a in summaries.get(ev.name, []):
                    callee = model.functions.get((file, ev.name))
                    frame = (f"{callee.qualname}() [called at "
                             f"{file}:{ev.line}]") if callee else ev.name
                    taken = Acq(a.label, a.site, a.func,
                                (frame,) + a.chain)
                    for h in held:
                        edges.setdefault((h.label, a.label), []).append(
                            Edge(h, taken))
            elif ev.kind == "atomic_write":
                key = frozenset(h.label for h in held)
                atomic_writes.setdefault(ev.name, set()).add(key)
    return edges, blocking, atomic_writes


def _find_cycles(labels: Set[str],
                 edges: Dict[Tuple[str, str], List[Edge]]):
    """Tarjan SCCs over distinct-label edges; one representative cycle per
    non-trivial SCC (DFS inside the component)."""
    adj: Dict[str, Set[str]] = {l: set() for l in labels}
    for (a, b) in edges:
        if a != b:
            adj.setdefault(a, set()).add(b)
            adj.setdefault(b, set())

    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    stack: List[str] = []
    on_stack: Set[str] = set()
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str):
        work = [(v, iter(sorted(adj[v])))]
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        while work:
            node, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj[w]))))
                    advanced = True
                    break
                elif w in on_stack:
                    low[node] = min(low[node], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                sccs.append(comp)

    for v in sorted(adj):
        if v not in index:
            strongconnect(v)

    cycles: List[List[str]] = []
    for comp in sccs:
        if len(comp) < 2:
            continue
        comp_set = set(comp)
        start = sorted(comp)[0]
        # DFS for one simple cycle through `start` within the SCC
        path = [start]
        seen = {start}

        def dfs(v: str) -> Optional[List[str]]:
            for w in sorted(adj[v]):
                if w == start and len(path) >= 2:
                    return list(path)
                if w in comp_set and w not in seen:
                    seen.add(w)
                    path.append(w)
                    got = dfs(w)
                    if got:
                        return got
                    path.pop()
                    seen.discard(w)
            return None

        cyc = dfs(start)
        if cyc:
            cycles.append(cyc)
        else:   # 2-cycle fallback
            for w in sorted(adj[start]):
                if w in comp_set and start in adj[w]:
                    cycles.append([start, w])
                    break
    return cycles


def analyze_locks(model: CppModel) -> List[Finding]:
    findings: List[Finding] = []
    files = sorted({fn.file for fn in model.functions.values()})
    for file in files:
        summaries = _summaries(model, file)
        edges, blocking, atomic_writes = _simulate(model, file, summaries)
        labels = {l for pair in edges for l in pair}

        # distinct-mutex order cycles -> error, with both witness stacks
        for cyc in _find_cycles(labels, edges):
            legs = []
            for i, a in enumerate(cyc):
                b = cyc[(i + 1) % len(cyc)]
                wit = edges.get((a, b))
                if wit:
                    legs.append(wit[0].stack())
            order = " -> ".join(cyc + [cyc[0]])
            findings.append(Finding(
                lint="lock-order-cycle", severity=ERROR,
                message=(f"lock acquisition-order cycle {order}; "
                         + "; meanwhile ".join(legs)
                         + " — two threads interleaving these paths "
                           "deadlock (ABBA)"),
                op_name=file, pass_name=PASS))

        # same-class pairs (p->mu with lp->mu) -> note
        seen_pairs = set()
        for (a, b), wits in sorted(edges.items()):
            if a == b and (file, a) not in seen_pairs:
                seen_pairs.add((file, a))
                findings.append(Finding(
                    lint="lock-same-class-pair", severity=NOTE,
                    message=(f"two {a} instances held at once "
                             f"({wits[0].stack()}) — safe only if every "
                             "such site orders the instances consistently"),
                    op_name=wits[0].taken.site, pass_name=PASS))

        seen_block = set()
        for h, callee, site in blocking:
            key = (h.label, callee, h.func)
            if key in seen_block:
                continue
            seen_block.add(key)
            findings.append(Finding(
                lint="lock-across-blocking", severity=WARN,
                message=(f"{h.func} holds {h.label} (acquired "
                         f"{h.site}) across blocking call {callee}() at "
                         f"{site} — a stalled peer extends the critical "
                         "section indefinitely"),
                op_name=site, pass_name=PASS))

        for label, guard_sets in sorted(atomic_writes.items()):
            if len(guard_sets) > 1 and frozenset() in guard_sets:
                locked = sorted(", ".join(sorted(s))
                                for s in guard_sets if s)
                findings.append(Finding(
                    lint="atomic-mixed-guard", severity=NOTE,
                    message=(f"atomic {label} written both lock-free and "
                             f"under {{{locked[0]}}} — if the guarded site "
                             "maintains an invariant with other state, the "
                             "lock-free write races it"),
                    op_name=file, pass_name=PASS))
    return findings
