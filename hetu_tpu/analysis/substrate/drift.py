"""Cross-language protocol-drift lint (docs/ANALYSIS.md "Tier D").

The PS runtime speaks one wire protocol from two languages: C++ defines it
(csrc/ps/net.h and friends) and Python mirrors it
(:mod:`hetu_tpu.ps.wire_constants`, the ONE mirror every coordinator
imports). Nothing at runtime checks the two agree — a C++ slot added
without the Python mirror silently mis-unpacks every later slot. This pass
re-parses the C++ truth on every run and diffs it against the mirror:

- ``enum-drift`` (error) — PsfType / ArgType / ChaosKind / OptType entries
  missing on either side or bound to different values.
- ``wire-header-drift`` (error) — MsgHeader/ArgHeader member count, byte
  size, or names out of step with ``MSG_HDR``/``ARG_HDR`` (field-reuse
  slots: C++ ``pad`` may be Python ``crc_or_pad``/``world_ver``).
- ``wire-const-drift`` (error) — kFlagQuantRsp/kFlagCrc/kQuantWireBlock/
  kShardMagicV2/kTrailCols/kEventCols value drift.
- ``slot-count-drift`` (error) — every fixed reply layout (kServerStats,
  kSnapshotNow, kResizeState, world replies, client_stats, kListParams
  stride, shard meta, optimizer aux-slot counts) vs the mirror's field
  tuples.
- ``psf-dispatch-drift`` (error) — a PsfType no handler dispatches (and is
  not a known reply-only type), or a worker-sent PSF nothing handles.
- ``capi-unbound`` (error) / ``capi-dead`` (note) — ctypes calls into the
  ``extern "C"`` surface that don't exist, and exports nothing calls.
- ``wire-import-drift`` (error) / ``magic-number`` (warn) — a raw-socket
  unpacker that stopped importing the mirror, or a consumer that grew a
  bare slot-count literal back.
- ``mirror-pair-drift`` (error) / ``mirror-pair-untested`` (warn) — the
  registered bit-equality mirrors (quantizer, backoff schedule) missing a
  side, or missing the test that pins them together.
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Tuple

from ...ps import wire_constants as wire
from ..findings import ERROR, NOTE, WARN, Finding

PASS = "drift"
CSRC = os.path.join("hetu_tpu", "csrc", "ps")

_CTYPE_SIZE = {"int8_t": 1, "uint8_t": 1, "int16_t": 2, "uint16_t": 2,
               "int32_t": 4, "uint32_t": 4, "int": 4, "unsigned": 4,
               "float": 4, "int64_t": 8, "uint64_t": 8, "double": 8,
               "size_t": 8}

# C++ member name -> acceptable Python mirror names (documented slot reuse)
_FIELD_ALIASES = {"pad": ("crc_or_pad", "world_ver")}

# PsfTypes that only ever appear as response types — no dispatch case owed
_REPLY_ONLY = ("kAck", "kAddressBook")

# Python files that unpack raw i64 reply slots and therefore must import
# the mirror, plus the dict-consumer files checked for magic re-growth
_RAW_UNPACKERS = ("hetu_tpu/elastic.py", "hetu_tpu/ps/client.py",
                  "hetu_tpu/ps/supervisor.py", "hetu_tpu/chaos.py")
_ALL_CONSUMERS = _RAW_UNPACKERS + ("hetu_tpu/recovery.py",
                                   "hetu_tpu/runner.py",
                                   "hetu_tpu/resilience.py")

# (python symbol, python file, c++ symbol, c++ file, pinning test file,
#  acceptable test anchors — any one present pins the pair)
_MIRROR_PAIRS = (
    ("np_quantize_blocks", "hetu_tpu/comm_quant.py",
     "make_qi8_arg", "hetu_tpu/csrc/ps/net.h", "tests/test_comm_quant.py",
     ("np_quantize_blocks", "np_roundtrip")),
    ("backoff_ms", "hetu_tpu/chaos.py",
     "backoff_ms", "hetu_tpu/csrc/ps/chaos.h", "tests/test_chaos.py",
     ("backoff_ms",)),
    ("splitmix64", "hetu_tpu/chaos.py",
     "splitmix64", "hetu_tpu/csrc/ps/chaos.h", "tests/test_chaos.py",
     ("splitmix64", "backoff_ms")),
)


def _read(root: str, rel: str, overlay: Optional[dict] = None) -> str:
    if overlay and rel in overlay:
        return overlay[rel]
    with open(os.path.join(root, rel), "r", encoding="utf-8",
              errors="replace") as f:
        return f.read()


def _strip(text: str) -> str:
    from .cpp_model import strip_noise
    return strip_noise(text)


def parse_enum(text: str, name: str) -> Dict[str, int]:
    """``enum [class] Name [: type] { kA = 0, kB, ... };`` -> dict."""
    m = re.search(rf"enum\s+(?:class\s+)?{name}\b[^{{]*\{{", text)
    if not m:
        return {}
    body = text[m.end():text.index("}", m.end())]
    out: Dict[str, int] = {}
    nxt = 0
    for entry in body.split(","):
        em = re.match(r"\s*([A-Za-z_]\w*)\s*(?:=\s*(-?\d+))?\s*$", entry)
        if not em:
            continue
        val = int(em.group(2)) if em.group(2) is not None else nxt
        out[em.group(1)] = val
        nxt = val + 1
    return out


def parse_struct_members(text: str, name: str) -> List[Tuple[str, str]]:
    """Plain-old-data struct members as (ctype, name), declaration order."""
    m = re.search(rf"struct\s+{name}\s*\{{", text)
    if not m:
        return []
    body = text[m.end():text.index("}", m.end())]
    out = []
    for line in body.split(";"):
        mm = re.match(r"\s*([A-Za-z_]\w*)\s+([A-Za-z_]\w*)\s*(?:=.*)?$",
                      line.strip())
        if mm and mm.group(1) in _CTYPE_SIZE:
            out.append((mm.group(1), mm.group(2)))
    return out


def parse_const(text: str, name: str) -> Optional[int]:
    m = re.search(rf"\b{name}\s*=\s*(-?\d+)", text)
    return int(m.group(1)) if m else None


def case_block(text: str, psf: str) -> str:
    """The statement span of one ``case PsfType::kX:`` (to the next case/
    default or an unindented close)."""
    m = re.search(rf"case\s+PsfType::{psf}\s*:", text)
    if not m:
        return ""
    rest = text[m.end():]
    stop = re.search(r"\n\s*(?:case\s+PsfType::|default\s*:)", rest)
    return rest[:stop.start()] if stop else rest[:4000]


def func_block(text: str, name: str) -> str:
    """Body of the first function definition named ``name`` (brace-matched)."""
    m = re.search(rf"\b{name}\s*\([^;{{]*\)[^;{{]*\{{", text)
    if not m:
        return ""
    depth, i = 1, m.end()
    while i < len(text) and depth:
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
        i += 1
    return text[m.end():i]


def _err(findings, lint, where, msg, severity=ERROR):
    findings.append(Finding(lint=lint, severity=severity, message=msg,
                            op_name=where, pass_name=PASS))


def _diff_enum(findings, where, cpp: Dict[str, int], py: Dict[str, int],
               enum_name: str):
    for k in sorted(set(cpp) | set(py)):
        if k not in py:
            _err(findings, "enum-drift", where,
                 f"{enum_name}::{k} = {cpp[k]} has no entry in "
                 "hetu_tpu/ps/wire_constants.py — Python cannot name it")
        elif k not in cpp:
            _err(findings, "enum-drift", where,
                 f"wire_constants mirrors {enum_name}::{k} = {py[k]} but "
                 "the C++ enum has no such entry — stale mirror")
        elif cpp[k] != py[k]:
            _err(findings, "enum-drift", where,
                 f"{enum_name}::{k} is {cpp[k]} in C++ but {py[k]} in "
                 "wire_constants — value drift corrupts every message "
                 "carrying it")


def _check_header_struct(findings, where, text, struct: str,
                         fields: tuple, pystruct) -> None:
    members = parse_struct_members(text, struct)
    if not members:
        _err(findings, "wire-header-drift", where,
             f"could not parse struct {struct} out of net.h — the parser "
             "or the header moved; fix whichever drifted")
        return
    if len(members) != len(fields):
        _err(findings, "wire-header-drift", where,
             f"{struct} has {len(members)} members but wire_constants "
             f"names {len(fields)} fields {fields} — slot-layout drift")
        return
    size = sum(_CTYPE_SIZE[t] for t, _ in members)
    if size != pystruct.size:
        _err(findings, "wire-header-drift", where,
             f"{struct} is {size} bytes in C++ but wire_constants packs "
             f"{pystruct.size} ({pystruct.format!r})")
    for (ctype, cname), pyname in zip(members, fields):
        ok = (cname == pyname
              or pyname in _FIELD_ALIASES.get(cname, ())
              or cname in _FIELD_ALIASES and pyname in _FIELD_ALIASES[cname])
        if not ok and cname != pyname:
            _err(findings, "wire-header-drift", where,
                 f"{struct}.{cname} is mirrored as {pyname!r} — if the "
                 "slot was renamed/reused, add it to the documented "
                 "field-reuse aliases; otherwise the layouts disagree")


def _check_slot_counts(findings, root, overlay):
    server = _strip(_read(root, f"{CSRC}/server.h", overlay))
    sched = _strip(_read(root, f"{CSRC}/scheduler.h", overlay))
    workr = _strip(_read(root, f"{CSRC}/worker.h", overlay))
    chaos = _strip(_read(root, f"{CSRC}/chaos.h", overlay))
    store = _strip(_read(root, f"{CSRC}/store.h", overlay))

    def arr_size(block: str, arr: str) -> Optional[int]:
        m = re.search(rf"\b{arr}\s*\[\s*(\d+)\s*\]", block)
        return int(m.group(1)) if m else None

    def expect(where, what, got, want):
        if got is None:
            _err(findings, "slot-count-drift", where,
                 f"could not locate the {what} slot-count anchor — the "
                 "handler moved; update the Tier D extractor")
        elif got != want:
            _err(findings, "slot-count-drift", where,
                 f"{what} is {got} slots in C++ but wire_constants "
                 f"declares {want} — every unpacker reading the mirror "
                 "now mis-slices the reply")

    expect("server.h:kServerStats", "kServerStats reply",
           arr_size(case_block(server, "kServerStats"), "stats"),
           wire.SERVER_STATS_SLOTS)
    expect("server.h:kSnapshotNow", "kSnapshotNow reply",
           arr_size(case_block(server, "kSnapshotNow"), "out"),
           wire.SNAPSHOT_NOW_SLOTS)
    expect("scheduler.h:kResizeState", "kResizeState reply",
           arr_size(case_block(sched, "kResizeState"), "vals"),
           wire.RESIZE_STATE_SLOTS)
    expect("scheduler.h:world_reply_locked", "world reply",
           arr_size(func_block(sched, "world_reply_locked"), "vals"),
           wire.WORLD_REPLY_SLOTS)
    expect("server.h:save_param_file", "v2 shard meta header",
           arr_size(func_block(server, "save_param_file"), "meta"),
           wire.SHARD_META_LEN)

    cs = func_block(workr, "client_stats")
    n = len(re.findall(r"static_cast<int64_t>", cs)) if cs else None
    expect("worker.h:client_stats", "client_stats vector", n,
           wire.CLIENT_STATS_SLOTS)

    lp = case_block(server, "kListParams")
    n = len(re.findall(r"\bflat\s*\.\s*push_back", lp)) if lp else None
    expect("server.h:kListParams", "kListParams row stride", n,
           wire.LIST_PARAMS_STRIDE)

    for cname, cfile, ctext, want in (
            ("kTrailCols", "worker.h", workr, wire.TRAIL_COLS),
            ("kEventCols", "chaos.h", chaos, wire.CHAOS_EVENT_COLS),
            ("kShardMagicV2", "server.h", server, wire.SHARD_MAGIC_V2),
            ("kQuantWireBlock", "net.h",
             _strip(_read(root, f"{CSRC}/net.h", overlay)),
             wire.QUANT_WIRE_BLOCK),):
        got = parse_const(ctext, cname)
        if got is None:
            _err(findings, "wire-const-drift", cfile,
                 f"constant {cname} not found in {cfile}")
        elif got != want:
            _err(findings, "wire-const-drift", cfile,
                 f"{cname} is {got} in {cfile} but wire_constants says "
                 f"{want}")

    # optimizer aux-slot counts: store.h alloc_slots switch vs the mirror
    ab = func_block(store, "alloc_slots")
    opt = parse_enum(store, "OptType")
    if ab and opt:
        counts: Dict[int, int] = {}
        pending: List[str] = []
        for line in ab.split("\n"):
            cm = re.search(r"case\s+OptType::(\w+)\s*:", line)
            if cm:
                pending.append(cm.group(1))
            if ".assign(" in line:
                for p in pending:
                    counts[opt[p]] = counts.get(opt[p], 0) + 1
            if "break" in line:
                for p in pending:
                    counts.setdefault(opt[p], 0)
                pending = []
        if counts != wire.OPT_SLOT_COUNTS:
            _err(findings, "slot-count-drift", "store.h:alloc_slots",
                 f"optimizer aux-slot counts are {counts} in C++ but "
                 f"wire_constants.OPT_SLOT_COUNTS says "
                 f"{wire.OPT_SLOT_COUNTS} — v2 shard re-splits will "
                 "mis-shape optimizer state")


def _check_dispatch(findings, root, overlay):
    server = _strip(_read(root, f"{CSRC}/server.h", overlay))
    sched = _strip(_read(root, f"{CSRC}/scheduler.h", overlay))
    workr = _strip(_read(root, f"{CSRC}/worker.h", overlay))
    handled = set(re.findall(r"case\s+PsfType::(\w+)\s*:", server)) \
        | set(re.findall(r"case\s+PsfType::(\w+)\s*:", sched))
    for k in sorted(wire.PSF):
        if k not in handled and k not in _REPLY_ONLY:
            _err(findings, "psf-dispatch-drift", "server.h/scheduler.h",
                 f"PsfType::{k} has no dispatch case in server.h or "
                 "scheduler.h and is not a known reply-only type — "
                 "requests of this type hang or error at every peer")
    sent = set(re.findall(r"PsfType::(\w+)", workr))
    for k in sorted(sent - handled - set(_REPLY_ONLY)):
        _err(findings, "psf-dispatch-drift", "worker.h",
             f"worker.h builds PsfType::{k} requests but no server/"
             "scheduler case handles them")


_CAPI_FILES = (f"{CSRC}/capi.cc", "hetu_tpu/csrc/cache/cache_capi.cc")
# extern "C" definitions sit at column 0; a type prefix then the name
_RE_CAPI_DEF = re.compile(
    r"^(?:(?:static|inline|extern|const|unsigned|struct)\s+)*"
    r"(?:[A-Za-z_][\w:<>]*[*&\s]+)+([A-Za-z_]\w*)\s*\(", re.M)


def _extern_c_spans(text: str) -> List[str]:
    """The brace-matched bodies of every ``extern "C" { ... }`` block
    (string literals are blanked by the strip pass, hence ``""``)."""
    spans = []
    for m in re.finditer(r'extern\s+""\s*\{', text):
        depth, i = 1, m.end()
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        spans.append(text[m.end():i])
    return spans


def _check_capi(findings, root, overlay):
    exports = set()
    for rel in _CAPI_FILES:
        try:
            text = _strip(_read(root, rel, overlay))
        except OSError:
            continue
        for span in _extern_c_spans(text) or [text]:
            for fm in _RE_CAPI_DEF.finditer(span):
                exports.add(fm.group(1))
    exports -= {"if", "for", "while", "switch", "return", "sizeof",
                "throw", "delete", "new"}

    refs: Dict[str, str] = {}
    for dirpath, _, files in os.walk(os.path.join(root, "hetu_tpu")):
        if "csrc" in dirpath:
            continue
        for f in files:
            if not f.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, f), root)
            text = _read(root, rel, overlay)
            for rm in re.finditer(r"\b_?lib\.([A-Za-z_]\w*)", text):
                refs.setdefault(rm.group(1), rel)
    for name in sorted(set(refs) - exports - {"restype", "argtypes"}):
        _err(findings, "capi-unbound", refs[name],
             f"{refs[name]} calls lib.{name} but no C-API file exports "
             "such a symbol — AttributeError (or worse) at first use")
    for name in sorted(exports - set(refs)):
        _err(findings, "capi-dead", "capi.cc",
             f"the C API exports {name} but no Python code references "
             "it — dead surface or a binding went missing",
             severity=NOTE)


def _check_unpackers(findings, root, overlay):
    for rel in _RAW_UNPACKERS:
        text = _read(root, rel, overlay)
        if "wire_constants" not in text:
            _err(findings, "wire-import-drift", rel,
                 f"{rel} unpacks raw wire replies but no longer imports "
                 "hetu_tpu/ps/wire_constants.py — its slot layout can "
                 "drift silently")
    magic = sorted({wire.SERVER_STATS_SLOTS, wire.CLIENT_STATS_SLOTS,
                    wire.RESIZE_STATE_SLOTS, wire.WORLD_REPLY_SLOTS,
                    wire.TRAIL_COLS, wire.CHAOS_EVENT_COLS,
                    wire.SNAPSHOT_NOW_SLOTS})
    pat = re.compile(
        r"np\.zeros\(\s*(\d+)\s*,|np\.zeros\(\(\s*\w+\s*,\s*(\d+)\s*\)")
    for rel in _ALL_CONSUMERS:
        for i, line in enumerate(_read(root, rel, overlay).split("\n"), 1):
            for m in pat.finditer(line):
                n = int(m.group(1) or m.group(2))
                if n in magic:
                    _err(findings, "magic-number", f"{rel}:{i}",
                         f"bare wire slot count {n} — size buffers from "
                         "wire_constants field tuples so hetucheck can "
                         "see drift", severity=WARN)


def _check_mirror_pairs(findings, root, overlay):
    for pysym, pyfile, cppsym, cppfile, testfile, anchors in _MIRROR_PAIRS:
        pair = f"{pyfile}:{pysym} <-> {cppfile}:{cppsym}"
        try:
            pysrc = _read(root, pyfile, overlay)
        except OSError:
            pysrc = ""
        try:
            cppsrc = _read(root, cppfile, overlay)
        except OSError:
            cppsrc = ""
        if not re.search(rf"def\s+{pysym}\s*\(", pysrc):
            _err(findings, "mirror-pair-drift", pyfile,
                 f"registered mirror pair {pair}: Python side "
                 f"{pysym}() is gone — the C++ wire format has no "
                 "bit-equality twin")
            continue
        if cppsym not in cppsrc:
            _err(findings, "mirror-pair-drift", cppfile,
                 f"registered mirror pair {pair}: C++ side {cppsym} is "
                 "gone — the Python twin mirrors nothing")
            continue
        try:
            tsrc = _read(root, testfile, overlay)
        except OSError:
            tsrc = ""
        if not any(a in tsrc for a in anchors):
            _err(findings, "mirror-pair-untested", testfile,
                 f"mirror pair {pair} has no pinning reference (any of "
                 f"{anchors}) in {testfile} — bit-equality can rot "
                 "unseen", severity=WARN)


def analyze_drift(root: str = ".", overlay: Optional[dict] = None
                  ) -> List[Finding]:
    """Run every drift check. ``overlay`` maps repo-relative paths to
    replacement text (seeded-defect fixtures and tests)."""
    findings: List[Finding] = []
    net = _strip(_read(root, f"{CSRC}/net.h", overlay))
    chaos = _strip(_read(root, f"{CSRC}/chaos.h", overlay))
    store = _strip(_read(root, f"{CSRC}/store.h", overlay))

    _diff_enum(findings, "net.h", parse_enum(net, "PsfType"), wire.PSF,
               "PsfType")
    at_names = ("kF32", "kI64", "kF64", "kBytes", "kI32", "kU64", "kQI8")
    at_py = dict(zip(at_names, (wire.AT_F32, wire.AT_I64, wire.AT_F64,
                                wire.AT_BYTES, wire.AT_I32, wire.AT_U64,
                                wire.AT_QI8)))
    _diff_enum(findings, "net.h", parse_enum(net, "ArgType"), at_py,
               "ArgType")
    _diff_enum(findings, "chaos.h", parse_enum(chaos, "ChaosKind"),
               wire.CHAOS_KINDS, "ChaosKind")
    _diff_enum(findings, "store.h", parse_enum(store, "OptType"),
               wire.OPT_TYPES, "OptType")

    _check_header_struct(findings, "net.h", net, "MsgHeader",
                         wire.MSG_HDR_FIELDS, wire.MSG_HDR)
    _check_header_struct(findings, "net.h", net, "ArgHeader",
                         wire.ARG_HDR_FIELDS, wire.ARG_HDR)

    for cname, want in (("kFlagQuantRsp", wire.FLAG_QUANT_RSP),
                        ("kFlagCrc", wire.FLAG_CRC)):
        got = parse_const(net, cname)
        if got != want:
            _err(findings, "wire-const-drift", "net.h",
                 f"{cname} is {got} in net.h but wire_constants says "
                 f"{want}")

    _check_slot_counts(findings, root, overlay)
    _check_dispatch(findings, root, overlay)
    _check_capi(findings, root, overlay)
    _check_unpackers(findings, root, overlay)
    _check_mirror_pairs(findings, root, overlay)
    return findings
