"""``bin/hetucheck`` — Tier D CLI (docs/ANALYSIS.md "Tier D: substrate").

Same contract as ``bin/hetulint``: human or ``--json`` output, lint
suppression, ``--fail-on {error,warn,never}``, exit 0 on a clean tree,
1 when findings at or above the threshold exist, 2 on usage/load errors.
``--check`` runs the self-test: the three analyzers against seeded-defect
fixtures (including PR 16's pre-fix ABBA deadlock and a slot-count drift)
plus a clean-baseline assertion over the working tree.

jax-free: ``bin/hetucheck`` installs a synthetic ``hetu_tpu`` package so
this module loads without executing ``hetu_tpu/__init__`` (which imports
jax); CI runs it on every commit under plain CPython.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ..findings import (count_by_severity, format_findings, is_suppressed,
                        sort_findings)
from .cpp_model import build_model
from .drift import analyze_drift
from .lock_order import analyze_locks
from .surface import analyze_surface

# the substrate under analysis: every header the PS runtime is built from
HEADERS = ("hetu_tpu/csrc/ps/net.h", "hetu_tpu/csrc/ps/store.h",
           "hetu_tpu/csrc/ps/server.h", "hetu_tpu/csrc/ps/worker.h",
           "hetu_tpu/csrc/ps/scheduler.h", "hetu_tpu/csrc/ps/chaos.h")


def repo_root() -> str:
    here = os.path.abspath(__file__)
    for _ in range(4):      # substrate -> analysis -> hetu_tpu -> repo
        here = os.path.dirname(here)
    return here


def analyze(root: str) -> List:
    """All three Tier D families over one tree."""
    paths = [os.path.join(root, h) for h in HEADERS
             if os.path.exists(os.path.join(root, h))]
    findings = list(analyze_locks(build_model(paths)))
    findings += analyze_drift(root)
    findings += analyze_surface(root)
    return sort_findings(findings)


# --------------------------------------------------------------------------
# --check fixtures. The ABBA pair reproduces PR 16's pre-fix server:
# dispatch holds ClientSlot::mu across handle() into take_snapshot (which
# takes PsServer::snap_take_mu_ then walks the slot table re-locking each
# slot), while the snapshot path takes snap_take_mu_ first — the two
# acquisition orders deadlock. The FIXED variant drops the slot lock
# before dispatch (the shipped release-across-call), so no cycle.

_ABBA_FIXTURE = """
#pragma once
#include <mutex>

struct ClientSlot {
  std::mutex mu;
  int fd = -1;
};

class PsServer {
 public:
  void serve_conn(ClientSlot* slot) {
    std::unique_lock<std::mutex> slot_g(slot->mu);
    handle(slot);
  }

  void handle(ClientSlot* slot) {
    take_snapshot();
  }

  void take_snapshot() {
    std::lock_guard<std::mutex> g(snap_take_mu_);
    for (size_t i = 0; i < n_; ++i) {
      ClientSlot* s = slots_[i];
      std::unique_lock<std::mutex> sg(s->mu);
    }
  }

 private:
  std::mutex snap_take_mu_;
  ClientSlot* slots_[64];
  size_t n_ = 0;
};
"""

_FIXED_FIXTURE = _ABBA_FIXTURE.replace(
    "    std::unique_lock<std::mutex> slot_g(slot->mu);\n    handle(slot);",
    "    std::unique_lock<std::mutex> slot_g(slot->mu);\n"
    "    slot_g.unlock();\n    handle(slot);")


def self_check(root: str) -> int:
    failures: List[str] = []

    def expect(cond: bool, what: str):
        print(("ok   " if cond else "FAIL ") + what)
        if not cond:
            failures.append(what)

    # 1. seeded ABBA must be detected, naming both mutexes + both sites
    model = build_model([("fixture/server_prefix.h", _ABBA_FIXTURE)])
    cycles = [f for f in analyze_locks(model) if f.lint == "lock-order-cycle"]
    expect(bool(cycles), "seeded pre-fix ABBA fixture yields a "
                         "lock-order-cycle error")
    msg = cycles[0].message if cycles else ""
    expect("ClientSlot::mu" in msg and "PsServer::snap_take_mu_" in msg,
           "cycle names both mutexes (ClientSlot::mu, "
           "PsServer::snap_take_mu_)")
    expect(msg.count("server_prefix.h:") >= 2,
           "cycle reports both acquisition sites")

    # 2. the shipped release-across-call shape must NOT be flagged
    model = build_model([("fixture/server_fixed.h", _FIXED_FIXTURE)])
    fixed = [f for f in analyze_locks(model) if f.lint == "lock-order-cycle"]
    expect(not fixed, "release-across-call (post-fix) fixture is clean")

    # 3. seeded slot-count drift must be caught
    server = os.path.join(root, "hetu_tpu/csrc/ps/server.h")
    with open(server, "r", encoding="utf-8") as f:
        text = f.read()
    overlay = {"hetu_tpu/csrc/ps/server.h":
               text.replace("int64_t stats[11]", "int64_t stats[12]")}
    drifted = [f for f in analyze_drift(root, overlay=overlay)
               if f.lint == "slot-count-drift"]
    expect(bool(drifted), "seeded kServerStats slot-count drift (11 -> 12) "
                          "yields a slot-count-drift error")

    # 3b. seeded kResizeState era-counter drift (the hetupilot actuation
    # tags widened the reply 11 -> 13; a further native-side widening
    # without the wire_constants.py counterpart must be caught)
    sched = os.path.join(root, "hetu_tpu/csrc/ps/scheduler.h")
    with open(sched, "r", encoding="utf-8") as f:
        stext = f.read()
    overlay = {"hetu_tpu/csrc/ps/scheduler.h":
               stext.replace("int64_t vals[13]", "int64_t vals[14]")}
    drifted = [f for f in analyze_drift(root, overlay=overlay)
               if f.lint == "slot-count-drift"]
    expect(bool(drifted), "seeded kResizeState slot-count drift (13 -> 14) "
                          "yields a slot-count-drift error")

    # 3c. seeded PlanDelta registry/consumer drift: a pilot that grew its
    # own kind list (no DELTA_KINDS reference) must be caught, and a new
    # registry kind without a docs catalogue row must be caught
    pilot_rel = "hetu_tpu/pilot.py"
    drifted = [f for f in analyze_surface(
                   root, overlay={pilot_rel: "# pilot with a private "
                                  "catalogue\nKINDS = ['comm_quant']\n"})
               if f.lint == "delta-parser-drift"]
    expect(bool(drifted), "pilot without a DELTA_KINDS reference yields a "
                          "delta-parser-drift error")
    watch_rel = "hetu_tpu/telemetry/watch.py"
    with open(os.path.join(root, watch_rel), "r", encoding="utf-8") as f:
        wtext = f.read()
    overlay = {watch_rel: wtext.replace(
        '    "comm_quant":     {"arg": "mode",',
        '    "zero_stage":     {"arg": "stage", "reversible": True,'
        ' "scope": "program"},\n'
        '    "comm_quant":     {"arg": "mode",')}
    drifted = [f for f in analyze_surface(root, overlay=overlay)
               if f.lint == "delta-kind-undocumented"]
    expect(bool(drifted), "seeded undocumented plan-delta kind yields a "
                          "delta-kind-undocumented error")

    # 3d. seeded ledger-kind drift, both directions: a producer emitting a
    # kind the story registry never heard of, and a registry kind the
    # docs/OBSERVABILITY.md ledger catalogue does not list
    exec_rel = "hetu_tpu/graph/executor.py"
    with open(os.path.join(root, exec_rel), "r", encoding="utf-8") as f:
        etext = f.read()
    overlay = {exec_rel: etext + '\n_ROGUE = {"kind": "rogue_kind"}\n'}
    drifted = [f for f in analyze_surface(root, overlay=overlay)
               if f.lint == "ledger-kind-drift"
               and f.op_name == "rogue_kind"]
    expect(bool(drifted), "seeded unregistered record kind yields a "
                          "ledger-kind-drift error")
    obs_rel = "docs/OBSERVABILITY.md"
    with open(os.path.join(root, obs_rel), "r", encoding="utf-8") as f:
        otext = f.read()
    drifted = [f for f in analyze_surface(
                   root, overlay={obs_rel: otext.replace("`finding`", "")})
               if f.lint == "ledger-kind-drift"]
    expect(bool(drifted), "record kind dropped from the OBSERVABILITY.md "
                          "ledger catalogue yields a ledger-kind-drift "
                          "error")

    # 4. gutting the fault catalogue doc must trip the surface lint
    gutted = [f for f in analyze_surface(
                  root, overlay={"docs/FAULT_TOLERANCE.md": "# empty\n"})
              if f.lint == "fault-kind-undocumented"]
    expect(bool(gutted), "emptied FAULT_TOLERANCE.md yields "
                         "fault-kind-undocumented errors")

    # 5. the working tree itself must be error-free
    errors = [f for f in analyze(root) if f.severity == "error"]
    for f in errors[:5]:
        print(f"     baseline error: [{f.lint}] {f.message}")
    expect(not errors, "working tree has no Tier D errors")

    print(("hetucheck self-test: PASS" if not failures
           else f"hetucheck self-test: {len(failures)} FAILURE(S)"))
    return 0 if not failures else 1


# --------------------------------------------------------------------------

def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="hetucheck",
        description="Tier D substrate analysis: lock-order deadlock "
                    "detection + cross-language protocol/surface drift "
                    "lint (docs/ANALYSIS.md)")
    ap.add_argument("root", nargs="?", default=None,
                    help="repo root to analyze (default: this checkout)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable findings")
    ap.add_argument("--suppress", action="append", default=[],
                    metavar="LINT", help="suppress a lint globally")
    ap.add_argument("--fail-on", choices=("error", "warn", "never"),
                    default="error",
                    help="exit 1 at/above this severity (default: error)")
    ap.add_argument("--check", action="store_true",
                    help="run the seeded-fixture self-test and exit")
    try:
        args = ap.parse_args(argv)
    except SystemExit as e:
        return 0 if e.code == 0 else 2

    root = args.root or repo_root()
    if not os.path.isdir(os.path.join(root, "hetu_tpu")):
        print(f"hetucheck: {root} is not a hetu-tpu checkout",
              file=sys.stderr)
        return 2

    if args.check:
        return self_check(root)

    findings = [f for f in analyze(root)
                if not is_suppressed(f, args.suppress)]
    counts = count_by_severity(findings)

    if args.fail_on == "never":
        ok = True
    elif args.fail_on == "warn":
        ok = counts.get("error", 0) + counts.get("warn", 0) == 0
    else:
        ok = counts.get("error", 0) == 0

    if args.as_json:
        print(json.dumps({"findings": [f.as_dict() for f in findings],
                          "counts": counts, "ok": ok}, indent=2))
    else:
        if findings:
            print(format_findings(findings))
        print(f"hetucheck: {counts.get('error', 0)} error(s), "
              f"{counts.get('warn', 0)} warn(s), "
              f"{counts.get('note', 0)} note(s) — "
              + ("ok" if ok else f"failing on {args.fail_on}"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
