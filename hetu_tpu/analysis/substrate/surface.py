"""Surface-consistency lint: what the code *does* vs what the docs *say*
(docs/ANALYSIS.md "Tier D: substrate").

Three promise surfaces, each diffed in both directions:

- **Knobs** — every quoted ``HETU_*`` / ``DMLC_*`` environment variable the
  Python layer or the C++ substrate reads must appear in the docs
  (``knob-undocumented``, warn), and every knob the docs promise must still
  be read somewhere (``knob-dead``, note: the doc row outlived the code).
- **Gauges** — every ``hetu_*`` metric name the telemetry layer emits must
  have a row in docs/OBSERVABILITY.md (``gauge-undocumented``, warn);
  documented names nothing emits or reads are stale (``gauge-stale-doc``,
  note); names a consumer (hetutop / hetuwatch / plan watch) reads but no
  producer ever emits are broken panels (``gauge-consumer-drift``, warn).
- **Fault kinds** — the :mod:`hetu_tpu.faults` registry, the
  docs/FAULT_TOLERANCE.md catalogue, the three parsers that consume the
  registry, and the C++ chaos grammar in csrc/ps/chaos.h must all agree
  (``fault-kind-undocumented`` / ``fault-kind-unknown-doc`` /
  ``fault-parser-drift`` / ``chaos-grammar-drift``, all errors: a fault
  kind that exists in one layer only is a silent no-op in the layer that
  was supposed to exercise it).
- **Plan-delta kinds** — the ``watch.DELTA_KINDS`` registry (the bounded
  deltas hetuwatch recommends and hetupilot actuates) must be catalogued
  in docs/FAULT_TOLERANCE.md (``delta-kind-undocumented``, error) and the
  pilot must consume the registry symbol rather than a private kind list
  (``delta-parser-drift``, error) — the same discipline as fault kinds: a
  kind the recommender emits but the actuator or docs never heard of is a
  recommendation that silently goes nowhere.
- **Ledger record kinds** — every ``kind`` a JSONL producer emits (Python
  dict literals, hot-path raw-JSON fragments, C++ escaped rows,
  ``tel.record(...)`` call sites) must be registered in
  ``story.LEDGER_KINDS`` and catalogued in the docs/OBSERVABILITY.md
  ledger table, and vice versa (``ledger-kind-drift``, error both
  directions; a registered-but-never-emitted kind is a warn) — a row
  hetustory cannot classify is invisible to every timeline, audit, and
  incident report built on the unified ledger.

Pure text analysis over the working tree; ``overlay`` maps repo-relative
paths to replacement text so the seeded-defect tests and ``--check`` can
analyze counterfactual trees without touching disk.
"""
from __future__ import annotations

import os
import re
from typing import Dict, List, Optional, Set, Tuple

from ... import faults
from ..findings import ERROR, NOTE, WARN, Finding

PASS = "surface"

# Doc set that constitutes "the promise surface". ROADMAP/ISSUE/CHANGES are
# planning artifacts, not promises, and would drown the diff in noise.
_DOC_FILES = (
    "README.md", "docs/API.md", "docs/ANALYSIS.md", "docs/COMM_QUANT.md",
    "docs/FAULT_TOLERANCE.md", "docs/KERNELS.md", "docs/MIGRATING.md",
    "docs/OBSERVABILITY.md", "docs/PROFILING.md", "docs/ROOFLINE.md",
)

# a doc knob token ending in `_` came from a wildcard row (`HETU_X_*`):
# it documents the whole prefix family
_RE_KNOB = re.compile(r"\b((?:HETU|DMLC)_[A-Z][A-Z0-9_]*_?)")
_RE_KNOB_QUOTED = re.compile(r"\"((?:HETU|DMLC)_[A-Z][A-Z0-9_]*)\"")
# metric names at emission sites only: registry method calls, or the
# conventional one-letter local binding of registry.gauge (`g("hetu_x")`).
# An f-string placeholder marks a dynamic prefix family (hetu_hbm_{k}).
_RE_GAUGE_EMIT = re.compile(
    r"\b(?:gauge|counter|histogram|g)\(\s*f?\"(hetu_[a-z0-9_]*)(\{)?")
# consumers read names anywhere (registry-dump lookups, startswith probes)
_RE_GAUGE_ANY = re.compile(r"[\"'](hetu_[a-z0-9_]*)")
_RE_DOC_GAUGE = re.compile(r"`(hetu_[a-z0-9_]+)(\{|\*)?")
_RE_DOC_FAULT = re.compile(r"`([a-z_]+)@S")

# hetu_* strings that are not metric names (paths, module prefixes)
_GAUGE_DENY = ("hetu_tpu", "hetu_telemetry", "hetu_ckpt", "hetu_elastic",
               "hetu_job_snap")

# names the registry dump derives from a histogram (hetutop reads
# hetu_ps_pull_ms_p50 off the emitted hetu_ps_pull_ms)
_HIST_SUFFIXES = ("_p50", "_p90", "_p99", "_count", "_sum", "_mean")

# gauge consumers: files that only *read* metric names from the registry
# dump (watch.py/hetuwatch both read AND emit, so they stay producers)
_CONSUMER_FILES = ("hetu_tpu/telemetry/hetutop.py",)

# the three parsers that must consume the faults registry, and the
# symbol(s) each one has no business reimplementing (any one suffices)
_FAULT_PARSERS = (
    ("hetu_tpu/resilience.py", ("parse_step_entry", "STEP_FAULT")),
    ("hetu_tpu/chaos.py", ("CHAOS_SPEC_KEYS", "CHAOS_PROB_KEYS",
                           "chaos_catalogue")),
    ("hetu_tpu/recovery.py", ("JOB_KILL_PHASES",)),
)

_CHAOS_HDR = "hetu_tpu/csrc/ps/chaos.h"

# the PlanDelta registry (producer) and its actuating consumer. Parsed as
# TEXT, not imported: watch.py is stdlib-only but this tier must analyze
# counterfactual overlay trees, and a registry literal is a surface too.
_DELTA_REGISTRY = "hetu_tpu/telemetry/watch.py"
_DELTA_CONSUMER = "hetu_tpu/pilot.py"
_RE_DELTA_KIND = re.compile(r"^\s*\"([a-z_]+)\":\s*\{\"arg\":", re.M)

# the hetustory ledger-kind registry (story.LEDGER_KINDS) — the contract
# every JSONL producer and the docs/OBSERVABILITY.md ledger catalogue must
# agree with. The registry file (and its jax-free bin loader) is excluded
# from the emission scan: it quotes every kind as data, plus fixtures.
_LEDGER_REGISTRY = "hetu_tpu/telemetry/story.py"
_LEDGER_SCAN_EXCLUDE = (_LEDGER_REGISTRY, "bin/hetustory")
# emission sites: Python dict literals ({"kind": "step"}), the hot-path
# raw-JSON fragments ('"kind":"step"'), C++ escaped JSON (\"kind\":\"srv\"),
# and the tel.record("<kind>", ...) free-form API
_RE_KIND_EMITS = (
    re.compile(r"\"kind\"\s*:\s*\"([a-z_0-9]+)\""),
    re.compile(r"\"kind\":\"([a-z_0-9]+)\""),
    re.compile(r"\\\"kind\\\":\\\"([a-z_0-9]+)"),
    re.compile(r"\.record\(\s*\"([a-z_0-9]+)\""),
)


def _read(root: str, rel: str, overlay: Optional[Dict[str, str]]) -> str:
    if overlay and rel in overlay:
        return overlay[rel]
    path = os.path.join(root, rel)
    if not os.path.exists(path):
        return ""
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        return f.read()


def _code_files(root: str) -> List[str]:
    """Repo-relative paths of everything that can read a knob or emit a
    gauge: the Python package, the bin/ entry points, the C++ substrate."""
    out: List[str] = []
    for base, exts in (("hetu_tpu", (".py", ".h", ".cc", ".c")),
                       ("bin", None), ("tools", (".py",))):
        top = os.path.join(root, base)
        if not os.path.isdir(top):
            continue
        for dirpath, dirnames, filenames in os.walk(top):
            # the analysis tier quotes knob/gauge names as *data*; scanning
            # it would make every lint string look like a live read
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "substrate")]
            for fn in sorted(filenames):
                if exts is not None and not fn.endswith(exts):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, fn), root)
                out.append(rel.replace(os.sep, "/"))
    # top-level entry points (bench.py, conftest.py) read knobs too
    for fn in sorted(os.listdir(root)):
        if fn.endswith(".py") and os.path.isfile(os.path.join(root, fn)):
            out.append(fn)
    return out


def _doc_text(root: str, overlay: Optional[Dict[str, str]]) -> str:
    return "\n".join(_read(root, rel, overlay) for rel in _DOC_FILES)


# --------------------------------------------------------------------------
# knobs

def _check_knobs(root: str, files: List[str], doc: str,
                 overlay: Optional[Dict[str, str]]) -> List[Finding]:
    findings: List[Finding] = []
    raw = set(_RE_KNOB.findall(doc))
    doc_prefixes = {k for k in raw if k.endswith("_")}
    doc_knobs = {k for k in raw if not k.endswith("_")}

    code_knobs: Dict[str, str] = {}     # knob -> first file that reads it
    all_code = set()
    for rel in files:
        text = _read(root, rel, overlay)
        for m in _RE_KNOB_QUOTED.finditer(text):
            code_knobs.setdefault(m.group(1), rel)
        all_code.update(k.rstrip("_") for k in _RE_KNOB.findall(text))

    for knob in sorted(set(code_knobs) - doc_knobs):
        if any(knob.startswith(p) for p in doc_prefixes):
            continue                    # covered by a wildcard doc row
        findings.append(Finding(
            lint="knob-undocumented", severity=WARN,
            message=(f"{knob} is read by {code_knobs[knob]} but appears in "
                     "no doc — an operator cannot discover it; add it to "
                     "the owning knob table"),
            op_name=knob, pass_name=PASS))

    # dead the other way: the doc promises a knob nothing reads (quoted OR
    # bare — generated names like HETU_FAULT_SPEC built from f-strings
    # still show up bare somewhere in code). A wildcard row is dead only
    # if NO code knob carries its prefix.
    for knob in sorted(doc_knobs - all_code):
        findings.append(Finding(
            lint="knob-dead", severity=NOTE,
            message=(f"{knob} is documented but no code under hetu_tpu/, "
                     "bin/ or csrc/ references it — stale doc row or a "
                     "renamed knob"),
            op_name=knob, pass_name=PASS))
    for prefix in sorted(doc_prefixes):
        if not any(k.startswith(prefix) for k in all_code):
            findings.append(Finding(
                lint="knob-dead", severity=NOTE,
                message=(f"wildcard doc row {prefix}* matches no knob any "
                         "code reads — stale family"),
                op_name=prefix + "*", pass_name=PASS))
    return findings


# --------------------------------------------------------------------------
# gauges

def _deny(name: str) -> bool:
    return any(name == d or name.startswith(d + "_") or d.startswith(name)
               for d in _GAUGE_DENY)


def _emitted_names(text: str) -> Tuple[Set[str], Set[str]]:
    """(exact names, dynamic prefixes) at gauge/counter/histogram sites."""
    names: Set[str] = set()
    prefixes: Set[str] = set()
    for m in _RE_GAUGE_EMIT.finditer(text):
        name, dynamic = m.group(1), m.group(2)
        if dynamic or name.endswith("_"):
            prefixes.add(name.rstrip("_") + "_")
        elif not _deny(name) and name != "hetu":
            names.add(name)
    return names, prefixes


def _covered(name: str, names: Set[str], prefixes: Set[str]) -> bool:
    if name in names or any(name.startswith(p) or p.startswith(name + "_")
                            for p in prefixes):
        return True
    for suf in _HIST_SUFFIXES:          # registry-derived histogram stats
        if name.endswith(suf) and name[:-len(suf)] in names:
            return True
    return False


def _check_gauges(root: str, files: List[str], overlay) -> List[Finding]:
    findings: List[Finding] = []
    doc = _read(root, "docs/OBSERVABILITY.md", overlay) + _read(
        root, "docs/FAULT_TOLERANCE.md", overlay)
    doc_names: Set[str] = set()
    doc_prefixes: Set[str] = set()
    for m in _RE_DOC_GAUGE.finditer(doc):
        name, wild = m.group(1), m.group(2)
        if _deny(name):
            continue
        if wild == "*" or name.endswith("_"):
            doc_prefixes.add(name.rstrip("_") + "_")
        else:
            doc_names.add(name)

    code_names: Dict[str, str] = {}     # emitted name -> first file
    code_prefixes: Set[str] = set()
    consumer_names: Dict[str, str] = {}
    for rel in files:
        if not rel.endswith(".py") and not rel.startswith("bin/"):
            continue                    # csrc emits no Python gauges
        text = _read(root, rel, overlay)
        if rel in _CONSUMER_FILES:
            for m in _RE_GAUGE_ANY.finditer(text):
                n = m.group(1)
                if not _deny(n) and n != "hetu":
                    consumer_names.setdefault(n.rstrip("_"), rel)
            continue
        names, prefixes = _emitted_names(text)
        for n in names:
            code_names.setdefault(n, rel)
        code_prefixes.update(prefixes)

    for name in sorted(code_names):
        if not _covered(name, doc_names, doc_prefixes):
            findings.append(Finding(
                lint="gauge-undocumented", severity=WARN,
                message=(f"metric {name} is emitted by {code_names[name]} "
                         "but has no row in docs/OBSERVABILITY.md — "
                         "dashboards cannot be built from the doc"),
                op_name=name, pass_name=PASS))

    emitted = set(code_names)
    for name in sorted(doc_names):
        if not _covered(name, emitted, code_prefixes) \
                and name not in consumer_names:
            findings.append(Finding(
                lint="gauge-stale-doc", severity=NOTE,
                message=(f"docs promise metric {name} but nothing under "
                         "hetu_tpu/ or bin/ emits or reads it — stale row "
                         "or renamed metric"),
                op_name=name, pass_name=PASS))

    for name in sorted(consumer_names):
        if _covered(name, emitted, code_prefixes):
            continue
        findings.append(Finding(
            lint="gauge-consumer-drift", severity=WARN,
            message=(f"{consumer_names[name]} reads metric {name} but no "
                     "producer emits it — the panel renders blank forever"),
            op_name=name, pass_name=PASS))
    return findings


# --------------------------------------------------------------------------
# fault kinds

def _check_faults(root: str, overlay) -> List[Finding]:
    findings: List[Finding] = []
    doc = _read(root, "docs/FAULT_TOLERANCE.md", overlay)
    doc_kinds = set(_RE_DOC_FAULT.findall(doc))

    for kind in faults.STEP_FAULT_NAMES:
        if kind not in doc_kinds:
            findings.append(Finding(
                lint="fault-kind-undocumented", severity=ERROR,
                message=(f"fault kind {kind} is in the faults registry but "
                         "the docs/FAULT_TOLERANCE.md catalogue has no "
                         f"`{kind}@S` row — undiscoverable, so untested "
                         "by operators"),
                op_name=kind, pass_name=PASS))
    for kind in sorted(doc_kinds - set(faults.STEP_FAULT_NAMES)):
        findings.append(Finding(
            lint="fault-kind-unknown-doc", severity=ERROR,
            message=(f"docs/FAULT_TOLERANCE.md catalogues fault kind "
                     f"{kind} but the faults registry does not know it — "
                     "the documented spec is rejected at parse time"),
            op_name=kind, pass_name=PASS))

    for phase in faults.JOB_KILL_PHASES:
        if phase not in doc:
            findings.append(Finding(
                lint="fault-kind-undocumented", severity=ERROR,
                message=(f"job_kill phase {phase} is in the registry but "
                         "not in the docs/FAULT_TOLERANCE.md job_kill row"),
                op_name=phase, pass_name=PASS))

    # the three parsers must consume the registry, not a private copy
    for rel, symbols in _FAULT_PARSERS:
        text = _read(root, rel, overlay)
        if text and not any(s in text for s in symbols):
            findings.append(Finding(
                lint="fault-parser-drift", severity=ERROR,
                message=(f"{rel} no longer references faults."
                         f"{'/'.join(symbols)} — a parser with a private "
                         "catalogue is exactly the three-copies drift the "
                         "registry was built to end"),
                op_name=rel, pass_name=PASS))

    # the C++ chaos grammar must accept every registry spec key
    chaos_h = _read(root, _CHAOS_HDR, overlay)
    if chaos_h:
        for key in faults.CHAOS_SPEC_KEYS:
            if f'"{key}"' not in chaos_h:
                findings.append(Finding(
                    lint="chaos-grammar-drift", severity=ERROR,
                    message=(f"chaos spec key {key!r} is in the registry "
                             f"(and the Python parser) but {_CHAOS_HDR} "
                             "never matches it — HETU_CHAOS_SPEC parses "
                             "differently per language"),
                    op_name=key, pass_name=PASS))
        for key in faults.CHAOS_SPEC_KEYS:
            if key not in doc:
                findings.append(Finding(
                    lint="fault-kind-undocumented", severity=ERROR,
                    message=(f"chaos spec key {key!r} has no row in the "
                             "docs/FAULT_TOLERANCE.md chaos table"),
                    op_name=key, pass_name=PASS))
    return findings


# --------------------------------------------------------------------------
# plan-delta kinds

def _delta_kinds(text: str) -> List[str]:
    """Registry keys from the ``DELTA_KINDS = {...}`` literal (text parse:
    overlay trees must be analyzable without importing them)."""
    m = re.search(r"^DELTA_KINDS\s*=\s*\{", text, re.M)
    if not m:
        return []
    block = text[m.end():]
    end = block.find("\n}")
    if end >= 0:
        block = block[:end]
    return _RE_DELTA_KIND.findall(block)


def _check_deltas(root: str, overlay) -> List[Finding]:
    findings: List[Finding] = []
    reg_text = _read(root, _DELTA_REGISTRY, overlay)
    if not reg_text:
        return findings
    kinds = _delta_kinds(reg_text)
    if not kinds:
        findings.append(Finding(
            lint="delta-parser-drift", severity=ERROR,
            message=(f"{_DELTA_REGISTRY} has no parseable DELTA_KINDS "
                     "registry literal — the plan-delta surface lint lost "
                     "its source of truth"),
            op_name=_DELTA_REGISTRY, pass_name=PASS))
        return findings

    doc = _read(root, "docs/FAULT_TOLERANCE.md", overlay)
    doc_kinds = set(re.findall(r"`([a-z_]+)`", doc))
    for kind in kinds:
        if kind not in doc_kinds:
            findings.append(Finding(
                lint="delta-kind-undocumented", severity=ERROR,
                message=(f"plan-delta kind {kind} is in watch.DELTA_KINDS "
                         "but the docs/FAULT_TOLERANCE.md delta catalogue "
                         f"has no `{kind}` row — an operator cannot know "
                         "what the pilot is allowed to change"),
                op_name=kind, pass_name=PASS))

    pilot = _read(root, _DELTA_CONSUMER, overlay)
    if pilot and "DELTA_KINDS" not in pilot:
        findings.append(Finding(
            lint="delta-parser-drift", severity=ERROR,
            message=(f"{_DELTA_CONSUMER} no longer references "
                     "watch.DELTA_KINDS — an actuator with a private kind "
                     "catalogue is exactly the recommender/actuator drift "
                     "the registry was built to end"),
            op_name=_DELTA_CONSUMER, pass_name=PASS))
    return findings


# --------------------------------------------------------------------------
# ledger record kinds (hetustory)

def _ledger_kinds(text: str) -> Dict[str, Set[str]]:
    """Family -> kinds from the ``LEDGER_KINDS = {...}`` literal (text
    parse, same discipline as :func:`_delta_kinds`)."""
    m = re.search(r"^LEDGER_KINDS\s*=\s*\{", text, re.M)
    if not m:
        return {}
    block = text[m.end():]
    end = block.find("\n}")
    if end >= 0:
        block = block[:end]
    out: Dict[str, Set[str]] = {}
    for fam, inner in re.findall(r"\"([a-z_]+)\":\s*\(([^)]*)\)", block,
                                 re.S):
        out[fam] = set(re.findall(r"\"([a-z_0-9]+)\"", inner))
    return out


def _doc_ledger_rows(doc: str) -> Dict[str, Set[str]]:
    """Family -> kinds from the docs/OBSERVABILITY.md ledger catalogue
    table (the section under the "Ledger catalogue" heading)."""
    m = re.search(r"^#+.*Ledger catalogue.*$", doc, re.M)
    if not m:
        return {}
    section = doc[m.end():]
    nxt = re.search(r"^#+ ", section, re.M)
    if nxt:
        section = section[:nxt.start()]
    out: Dict[str, Set[str]] = {}
    for line in section.splitlines():
        mm = re.match(r"^\|\s*`([a-z_]+)`\s*\|", line)
        if not mm:
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        # first cell = family; record kinds are the backticked lowercase
        # tokens of the THIRD cell (family | files | kinds | ...)
        kinds = set(re.findall(r"`([a-z_0-9]+)`", cells[2])) \
            if len(cells) >= 3 else set()
        kinds.discard("none")
        out[mm.group(1)] = kinds
    return out


def _check_ledgers(root: str, files: List[str], overlay) -> List[Finding]:
    findings: List[Finding] = []
    reg_text = _read(root, _LEDGER_REGISTRY, overlay)
    if not reg_text:
        return findings
    registry = _ledger_kinds(reg_text)
    if not registry:
        findings.append(Finding(
            lint="ledger-kind-drift", severity=ERROR,
            message=(f"{_LEDGER_REGISTRY} has no parseable LEDGER_KINDS "
                     "registry literal — the run-ledger surface lint lost "
                     "its source of truth"),
            op_name=_LEDGER_REGISTRY, pass_name=PASS))
        return findings
    known: Set[str] = set()
    for kinds in registry.values():
        known |= kinds

    # code -> registry: every emitted kind must be one hetustory's
    # timeline/audit can classify; a kind the registry never heard of is
    # invisible to every post-mortem built on the ledger
    emitted: Dict[str, Set[str]] = {}
    for rel in files:
        if rel in _LEDGER_SCAN_EXCLUDE:
            continue
        text = _read(root, rel, overlay)
        for rx in _RE_KIND_EMITS:
            for kind in rx.findall(text):
                emitted.setdefault(kind, set()).add(rel)
    for kind in sorted(set(emitted) - known):
        findings.append(Finding(
            lint="ledger-kind-drift", severity=ERROR,
            message=(f"record kind {kind!r} is emitted by "
                     f"{sorted(emitted[kind])[0]} but story.LEDGER_KINDS "
                     "has no entry for it — hetustory's timeline and "
                     "audit cannot classify the row"),
            op_name=kind, pass_name=PASS))
    # registry -> code: a registered kind nothing emits is a stale row
    for kind in sorted(known - set(emitted)):
        findings.append(Finding(
            lint="ledger-kind-drift", severity=WARN,
            message=(f"record kind {kind!r} is in story.LEDGER_KINDS but "
                     "no code path emits it — stale registry entry"),
            op_name=kind, pass_name=PASS))

    # registry <-> docs: the OBSERVABILITY.md ledger catalogue must list
    # every family with exactly the registry's kinds, both directions
    doc = _read(root, "docs/OBSERVABILITY.md", overlay)
    doc_rows = _doc_ledger_rows(doc)
    if not doc_rows:
        findings.append(Finding(
            lint="ledger-kind-drift", severity=ERROR,
            message=("docs/OBSERVABILITY.md has no parseable ledger "
                     "catalogue table (\"Ledger catalogue\" heading) — "
                     "the ledger contract is undocumented"),
            op_name="docs/OBSERVABILITY.md", pass_name=PASS))
        return findings
    for fam in sorted(set(registry) - set(doc_rows)):
        findings.append(Finding(
            lint="ledger-kind-drift", severity=ERROR,
            message=(f"ledger family {fam!r} is in story.LEDGER_KINDS but "
                     "the docs/OBSERVABILITY.md ledger catalogue has no "
                     f"`{fam}` row"),
            op_name=fam, pass_name=PASS))
    for fam in sorted(set(doc_rows) - set(registry)):
        findings.append(Finding(
            lint="ledger-kind-drift", severity=ERROR,
            message=(f"the docs/OBSERVABILITY.md ledger catalogue lists "
                     f"family {fam!r} that story.LEDGER_KINDS does not "
                     "register — doc row outlived the code"),
            op_name=fam, pass_name=PASS))
    for fam in sorted(set(registry) & set(doc_rows)):
        for kind in sorted(registry[fam] - doc_rows[fam]):
            findings.append(Finding(
                lint="ledger-kind-drift", severity=ERROR,
                message=(f"record kind {kind!r} of family {fam!r} is "
                         "registered but missing from its "
                         "docs/OBSERVABILITY.md catalogue row"),
                op_name=f"{fam}.{kind}", pass_name=PASS))
        for kind in sorted(doc_rows[fam] - registry[fam]):
            findings.append(Finding(
                lint="ledger-kind-drift", severity=ERROR,
                message=(f"the docs/OBSERVABILITY.md catalogue row for "
                         f"{fam!r} lists kind {kind!r} that "
                         "story.LEDGER_KINDS does not register"),
                op_name=f"{fam}.{kind}", pass_name=PASS))
    return findings


# --------------------------------------------------------------------------

def analyze_surface(root: str = ".",
                    overlay: Optional[Dict[str, str]] = None
                    ) -> List[Finding]:
    files = _code_files(root)
    doc = _doc_text(root, overlay)
    findings: List[Finding] = []
    findings += _check_knobs(root, files, doc, overlay)
    findings += _check_gauges(root, files, overlay)
    findings += _check_faults(root, overlay)
    findings += _check_deltas(root, overlay)
    findings += _check_ledgers(root, files, overlay)
    return findings
