"""Whole-graph abstract interpretation: shapes and dtypes for every node.

Walks the topo order once, inferring each op's output
``jax.ShapeDtypeStruct`` from its inputs' structs via ``Op.infer_meta`` —
no arrays are materialized and no XLA program is built. The result is the
substrate the Tier A passes read: shape-mismatch localization (the *op* whose
abstract evaluation raised, not a jit traceback 40 frames deep), dtype
promotion lints, and comm-op placement checks that need ranks.

Sources of truth for leaves:

- ``PlaceholderOp`` with a known shape (Variables with values/initializers):
  ``(shape, dtype)`` as declared.
- Dataloader nodes: ``(batch_size, *data.shape[1:])`` with the loaded data's
  dtype (``Dataloader.get_cur_shape``).
- Fed placeholders without a declared shape are *unknown roots*: their
  downstream cone is skipped silently (one ``shape-unknown`` note each, so a
  CI lint of a feed-dict graph says why coverage is partial). ``feed_meta``
  lets callers (tests, hetulint wrappers) pin shapes for exactly this case.
"""
from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np
import jax

from ..graph.node import _as_struct


def _shape_desc(m):
    """Shape of one abstract meta for diagnostics — tolerant of pytree
    metas (the IndexedRows rows-route pair has no ``.shape`` itself)."""
    if hasattr(m, "shape"):
        return tuple(m.shape)
    if isinstance(m, tuple):
        return tuple(_shape_desc(e) for e in m)
    return type(m).__name__


class AbstractGraph:
    """Abstract shapes/dtypes of one topo-sorted graph.

    After ``evaluate()``:

    - ``meta[id(node)]`` -> ``ShapeDtypeStruct`` | ``None`` (op yields no
      in-graph value: optimizer, PS push) — present only for resolved nodes.
    - ``failures[id(node)]`` -> ``(kind, message)`` with ``kind`` in
      ``{"shape-mismatch", "abstract-eval-failed"}``.
    - ``unknown_roots`` -> leaf nodes whose shape could not be determined.
    """

    def __init__(self, topo, config=None, target: Optional[str] = None,
                 feed_meta: Optional[dict] = None):
        self.topo = list(topo)
        self.config = config
        self.target = target
        self.meta: Dict[int, Any] = {}
        self.failures: Dict[int, tuple] = {}
        self.unknown_roots: list = []
        self._skipped: set = set()
        if feed_meta:
            for node, val in feed_meta.items():
                self.meta[id(node)] = _as_struct(val)

    # ------------------------------------------------------------------
    def _leaf_meta(self, node):
        if node.is_placeholder:
            shape = getattr(node, "shape", None)
            if shape is None:
                return None
            dtype = getattr(node, "dtype", np.float32)
            return jax.ShapeDtypeStruct(tuple(shape), dtype)
        if node.is_dataloader:
            dls = getattr(node, "dataloaders", None)
            if not dls:
                return None  # GNN loaders produce host-driven shapes
            dl = dls.get(self.target) if self.target in dls else \
                next(iter(dls.values()))
            try:
                shape = dl.get_cur_shape()
                return jax.ShapeDtypeStruct(tuple(shape), dl._data.dtype)
            except Exception:  # noqa: BLE001 — diagnostics must not throw
                return None
        return None

    def evaluate(self) -> "AbstractGraph":
        for node in self.topo:
            if id(node) in self.meta:
                continue
            if node.is_optimizer:
                self.meta[id(node)] = None  # applied by the executor
                continue
            if node.is_placeholder or node.is_dataloader:
                m = self._leaf_meta(node)
                if m is None:
                    self.unknown_roots.append(node)
                else:
                    self.meta[id(node)] = m
                continue
            if node.is_gradient:
                # d(loss)/dx has x's shape/dtype; with multi_x (PS shared
                # table rewiring) the op yields a host-consumed tuple
                multi = getattr(node, "multi_x", None)
                if multi:
                    self.meta[id(node)] = None
                    continue
                xm = self.meta.get(id(node.x))
                if xm is not None:
                    self.meta[id(node)] = xm
                continue
            # unresolved or valueless input: skip the whole downstream cone
            # silently — only its unknown root / failing op gets a finding
            if any(self.meta.get(id(i)) is None for i in node.inputs):
                continue
            in_metas = [self.meta[id(i)] for i in node.inputs]
            try:
                # may legitimately be None (PS push yields no in-graph value)
                self.meta[id(node)] = node.infer_meta(in_metas)
            except TypeError as e:
                shapes = [_shape_desc(m) for m in in_metas]
                self.failures[id(node)] = (
                    "shape-mismatch", f"{e} (input shapes {shapes})")
            except Exception as e:  # noqa: BLE001 — classify, don't crash
                self.failures[id(node)] = (
                    "abstract-eval-failed", f"{type(e).__name__}: {e}")
        return self

    # ------------------------------------------------------------------
    def shape_of(self, node) -> Optional[tuple]:
        m = self.meta.get(id(node))
        return tuple(m.shape) if m is not None and hasattr(m, "shape") else None

    def dtype_of(self, node):
        m = self.meta.get(id(node))
        return m.dtype if m is not None and hasattr(m, "dtype") else None
