"""hetulint: define-time graph validation + lowered-program static analysis
+ the hetuplan layout planner.

Three tiers:

- **Tier A** (:mod:`graph_passes`) runs over the Op graph before the executor
  builds: whole-graph abstract shape/dtype inference with op-level mismatch
  localization, structure checks, comm-op placement lints, dtype-promotion
  lints, dead-subgraph and common-subexpression reporting. Entry points:
  :func:`analyze_graph` / :class:`GraphAnalyzer`,
  ``Executor(..., lint="error"|"warn")``, and the ``bin/hetulint`` CLI.
- **Tier B** (:mod:`lowered`) analyzes the lowered/compiled step program via
  the ``SubExecutor._lowered``/``dump_hlo``/``last_cost_analysis`` hooks:
  recompilation detection, donation/aliasing and host-transfer checks, and
  the replicated-large-tensor lint. Entry points: :func:`analyze_executor`,
  :class:`RecompileMonitor`.
- **Tier C** (:mod:`planner` + :mod:`cost_model`) *chooses* a layout instead
  of linting one: per-parameter AllReduce/PS/Hybrid + comm_quant from
  analytic wire costs, (dp, tp, pp) mesh search under the AOT HBM gate with
  ZeRO-1/remat fallback, calibrated by measured roofline residuals and
  critical-path legs. Entry points: :func:`plan_graph` -> :class:`Plan`,
  ``hetulint --plan``, ``Executor(..., plan="auto")``.

See docs/ANALYSIS.md for the lint catalogue with examples and suppression.
"""
from .findings import (
    Finding, GraphValidationError, ERROR, WARN, NOTE, SEVERITIES,
    suppress, sort_findings, count_by_severity, format_findings,
)
from .abstract import AbstractGraph
from .graph_passes import (
    TIER_A_PASSES, structure_pass, shapes_pass, comm_pass, comm_quant_pass,
    kernels_pass, dce_pass,
)
from .analyzer import (
    AnalysisConfig, AnalysisContext, GraphAnalyzer, analyze_graph,
    record_graph,
)
from .lowered import (
    analyze_executor, recompile_findings, donation_findings,
    host_transfer_findings, replicated_tensor_findings, cost_analysis_of,
    RecompileMonitor, resolve_replicated_threshold,
)
from .cost_model import (
    Calibration, CostModel, CostModelConfig, load_calibration,
)
from .planner import Plan, ParamDecision, MeshCandidate, plan_graph

__all__ = [
    "Finding", "GraphValidationError", "ERROR", "WARN", "NOTE", "SEVERITIES",
    "suppress", "sort_findings", "count_by_severity", "format_findings",
    "AbstractGraph", "TIER_A_PASSES", "structure_pass", "shapes_pass",
    "comm_pass", "comm_quant_pass", "kernels_pass", "dce_pass",
    "AnalysisConfig", "AnalysisContext",
    "GraphAnalyzer", "analyze_graph", "record_graph", "analyze_executor",
    "recompile_findings", "donation_findings", "host_transfer_findings",
    "replicated_tensor_findings", "cost_analysis_of", "RecompileMonitor",
    "resolve_replicated_threshold",
    "Calibration", "CostModel", "CostModelConfig", "load_calibration",
    "Plan", "ParamDecision", "MeshCandidate", "plan_graph",
]
