"""hetulint: define-time graph validation + lowered-program static analysis.

Two tiers:

- **Tier A** (:mod:`graph_passes`) runs over the Op graph before the executor
  builds: whole-graph abstract shape/dtype inference with op-level mismatch
  localization, structure checks, comm-op placement lints, dtype-promotion
  lints, dead-subgraph and common-subexpression reporting. Entry points:
  :func:`analyze_graph` / :class:`GraphAnalyzer`,
  ``Executor(..., lint="error"|"warn")``, and the ``bin/hetulint`` CLI.
- **Tier B** (:mod:`lowered`) analyzes the lowered/compiled step program via
  the ``SubExecutor._lowered``/``dump_hlo``/``last_cost_analysis`` hooks:
  recompilation detection, donation/aliasing and host-transfer checks, and
  the replicated-large-tensor lint. Entry points: :func:`analyze_executor`,
  :class:`RecompileMonitor`.

See docs/ANALYSIS.md for the lint catalogue with examples and suppression.
"""
from .findings import (
    Finding, GraphValidationError, ERROR, WARN, NOTE, SEVERITIES,
    suppress, sort_findings, count_by_severity, format_findings,
)
from .abstract import AbstractGraph
from .graph_passes import (
    TIER_A_PASSES, structure_pass, shapes_pass, comm_pass, comm_quant_pass,
    kernels_pass, dce_pass,
)
from .analyzer import (
    AnalysisConfig, AnalysisContext, GraphAnalyzer, analyze_graph,
    record_graph,
)
from .lowered import (
    analyze_executor, recompile_findings, donation_findings,
    host_transfer_findings, replicated_tensor_findings, cost_analysis_of,
    RecompileMonitor,
)

__all__ = [
    "Finding", "GraphValidationError", "ERROR", "WARN", "NOTE", "SEVERITIES",
    "suppress", "sort_findings", "count_by_severity", "format_findings",
    "AbstractGraph", "TIER_A_PASSES", "structure_pass", "shapes_pass",
    "comm_pass", "comm_quant_pass", "kernels_pass", "dce_pass",
    "AnalysisConfig", "AnalysisContext",
    "GraphAnalyzer", "analyze_graph", "record_graph", "analyze_executor",
    "recompile_findings", "donation_findings", "host_transfer_findings",
    "replicated_tensor_findings", "cost_analysis_of", "RecompileMonitor",
]
