"""Bundled graph builders for ``bin/hetulint`` — CI smoke targets.

Each builder returns ``(graph, config_kwargs)`` where ``graph`` is an
Executor-style ``{target: [eval nodes]}`` dict and ``config_kwargs`` feed
:class:`~hetu_tpu.analysis.analyzer.AnalysisConfig` (declared comm strategy —
no devices are touched and no PS servers are spawned by linting).

They intentionally mirror the repo's three main workload shapes: the
examples/cnn MLP, the examples/nlp graph-API transformer block, and the
examples/ctr Wide&Deep-style PS embedding model.

    bin/hetulint --json hetu_tpu.analysis.examples:build_mlp \\
        hetu_tpu.analysis.examples:build_transformer \\
        hetu_tpu.analysis.examples:build_ctr_ps
"""
from __future__ import annotations

import numpy as np


def _synthetic(n, shape, num_classes, seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, *shape).astype(np.float32)
    y = rng.randint(0, num_classes, size=(n,))
    onehot = np.zeros((n, num_classes), np.float32)
    onehot[np.arange(n), y] = 1.0
    return x, onehot, y


def build_mlp():
    """3-layer MLP over dataloaders (the tests/test_mlp.py pattern)."""
    import hetu_tpu as ht
    from hetu_tpu import init

    train_x, train_y, _ = _synthetic(256, (32,), 10, seed=0)
    x = ht.dataloader_op([ht.Dataloader(train_x, 64, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(train_y, 64, "train")])

    h = x
    for i, (fan_in, fan_out) in enumerate([(32, 64), (64, 64), (64, 10)]):
        w = init.random_normal((fan_in, fan_out), stddev=0.1, name=f"w{i}")
        b = init.zeros((fan_out,), name=f"b{i}")
        mm = ht.matmul_op(h, w)
        h = mm + ht.broadcastto_op(b, mm)
        if i < 2:
            h = ht.relu_op(h)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(h, y_), [0])
    train_op = ht.optim.SGDOptimizer(0.1).minimize(loss)
    return {"train": [loss, train_op]}, {}


def build_transformer():
    """One causal self-attention block + FFN on the graph API (the
    examples/nlp/hetu_transformer.py pattern, miniaturized)."""
    import hetu_tpu as ht
    from hetu_tpu import init

    batch, seq_len, d_model, n_heads, vocab = 4, 8, 16, 2, 32
    hd = d_model // n_heads
    rng = np.random.RandomState(1)
    tokens = rng.randint(0, vocab, size=(64, seq_len)).astype(np.int32)
    targets = np.zeros((64, seq_len, vocab), np.float32)
    targets[np.arange(64)[:, None], np.arange(seq_len)[None, :],
            rng.randint(0, vocab, size=(64, seq_len))] = 1.0

    tok = ht.dataloader_op([ht.Dataloader(tokens, batch, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(targets, batch, "train")])

    table = init.xavier_normal((vocab, d_model), name="tok_embed")
    h = ht.embedding_lookup_op(table, tok)          # (B, T, D)

    def dense(x, fan_in, fan_out, name):
        w = init.xavier_normal((fan_in, fan_out), name=name + "_w")
        b = init.zeros((fan_out,), name=name + "_b")
        y = ht.matmul_op(ht.array_reshape_op(x, (-1, fan_in)), w)
        return y + ht.broadcastto_op(b, y)

    def split_heads(t):
        t = ht.array_reshape_op(t, (batch, seq_len, n_heads, hd))
        return ht.transpose_op(t, (0, 2, 1, 3))

    q, k, v = (split_heads(ht.array_reshape_op(
        dense(h, d_model, d_model, nm), (batch, seq_len, d_model)))
        for nm in ("q", "k", "v"))
    scores = ht.mul_byconst_op(ht.batch_matmul_op(q, k, trans_B=True),
                               1.0 / np.sqrt(hd))
    causal = np.triu(np.full((seq_len, seq_len), -1e9, np.float32), k=1)
    mask = ht.Variable(name="causal_mask", value=causal, trainable=False,
                       batch=False)
    scores = scores + ht.broadcastto_op(mask, scores)
    attn = ht.softmax_op(scores)
    ctxv = ht.transpose_op(ht.batch_matmul_op(attn, v), (0, 2, 1, 3))
    ctxv = ht.array_reshape_op(ctxv, (batch, seq_len, d_model))
    h = layer = ht.layer_normalization_op(
        h + ht.array_reshape_op(dense(ctxv, d_model, d_model, "proj"),
                                (batch, seq_len, d_model)),
        init.ones((d_model,), name="ln1_s"),
        init.zeros((d_model,), name="ln1_b"))
    ffn = dense(ht.gelu_op(dense(layer, d_model, 4 * d_model, "ffn1")),
                4 * d_model, d_model, "ffn2")
    h = ht.layer_normalization_op(
        layer + ht.array_reshape_op(ffn, (batch, seq_len, d_model)),
        init.ones((d_model,), name="ln2_s"),
        init.zeros((d_model,), name="ln2_b"))

    logits = ht.array_reshape_op(dense(h, d_model, vocab, "lm_head"),
                                 (batch, seq_len, vocab))
    loss = ht.reduce_mean_op(
        ht.softmaxcrossentropy_op(
            ht.array_reshape_op(logits, (-1, vocab)),
            ht.array_reshape_op(y_, (-1, vocab))), [0])
    train_op = ht.optim.AdamOptimizer(1e-3).minimize(loss)
    return {"train": [loss, train_op]}, {}


def build_ctr_ps():
    """Wide&Deep-style CTR model with PS-hosted embedding tables (the
    examples/ctr/models/wdl_adult.py pattern, miniaturized). Declares
    ``comm_mode='PS'`` so the analyzer replays the executor's PS comm-op
    insertion and checks the staging contract. The vocab stays CTR-shaped
    (10k rows against 128 lookups/step, ~1% density) so the hetuplan
    density × size rule sees the workload the example stands for — the
    table is only ever an initializer shape here, nothing materializes
    at lint/plan time."""
    import hetu_tpu as ht
    from hetu_tpu import init

    n_cat, embed_rows, embed_dim, n_num = 4, 10000, 8, 3
    rng = np.random.RandomState(2)
    cat = rng.randint(0, embed_rows, size=(128, n_cat)).astype(np.int64)
    num = rng.randn(128, n_num).astype(np.float32)
    _, y1h, _ = _synthetic(128, (1,), 2, seed=3)

    cat_dl = ht.dataloader_op([ht.Dataloader(cat, 32, "train")])
    num_dl = ht.dataloader_op([ht.Dataloader(num, 32, "train")])
    y_ = ht.dataloader_op([ht.Dataloader(y1h, 32, "train")])

    table = init.random_normal((embed_rows, embed_dim), stddev=0.1,
                               name="ctr_embed", is_embed=True)
    emb = ht.array_reshape_op(ht.embedding_lookup_op(table, cat_dl),
                              (-1, n_cat * embed_dim))
    deep = ht.concat_op(emb, num_dl, 1)
    w1 = init.random_normal((n_cat * embed_dim + n_num, 16), stddev=0.1,
                            name="ctr_w1")
    h = ht.relu_op(ht.matmul_op(deep, w1))
    w2 = init.random_normal((16, 2), stddev=0.1, name="ctr_w2")
    logits = ht.matmul_op(h, w2)
    loss = ht.reduce_mean_op(ht.softmaxcrossentropy_op(logits, y_), [0])
    train_op = ht.optim.SGDOptimizer(0.05).minimize(loss)
    return {"train": [loss, train_op]}, {"comm_mode": "PS"}


def build_ctr_ps_rows():
    """The PR-12 explicit rows route (docs/KERNELS.md): an
    ``embedding_lookup_gradient_op`` whose sole consumer is a PS gradient
    push — the executor flips it to compact ``IndexedRows`` mode at build
    so the ``(vocab, dim)`` zeros table never materializes. Bundled so CI
    lint/plan covers the route's abstract tracing end to end (the
    ``infer_meta`` identity keeps the whole cone evaluable)."""
    import hetu_tpu as ht
    from hetu_tpu import init

    embed_rows, embed_dim = 10000, 8
    rng = np.random.RandomState(4)
    cat = rng.randint(0, embed_rows, size=(128, 4)).astype(np.int64)
    idx = ht.dataloader_op([ht.Dataloader(cat, 32, "train")])
    table = init.random_normal((embed_rows, embed_dim), stddev=0.1,
                               name="rows_embed", is_embed=True)
    lk = ht.embedding_lookup_op(table, idx)
    loss = ht.reduce_mean_op(lk, [0, 1, 2])
    grad = ht.embedding_lookup_gradient_op(lk, idx,
                                           (embed_rows, embed_dim))
    push = ht.parameterServerCommunicate_op(grad, ps_id="rows_embed")
    return {"train": [loss, push]}, {"comm_mode": "PS"}
