"""Findings: the unit of output of every analysis pass.

A :class:`Finding` pins one diagnosed condition to one graph node (op-level
provenance via ``node.name``/``node.id``) or, for lowered-program (Tier B)
checks, to a subexecutor. Severities:

- ``error`` — the graph/program is wrong: it will crash at trace time or
  silently train incorrectly (e.g. a PS push op without a PS runtime).
- ``warn``  — a correctness or performance hazard that deserves a human
  decision (silent f64 downcast, per-step recompilation, missing donation).
- ``note``  — informational (common subexpressions, degenerate collectives).

Suppression: per-op via ``suppress(node, "lint-id", ...)`` (or a
``lint_suppress`` iterable attribute on the node), or analyzer-wide via
``GraphAnalyzer(..., suppress=["lint-id"])``. ``hetulint --suppress`` maps to
the latter.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional

ERROR = "error"
WARN = "warn"
NOTE = "note"

SEVERITIES = (ERROR, WARN, NOTE)
_SEVERITY_RANK = {ERROR: 0, WARN: 1, NOTE: 2}


def severity_rank(sev: str) -> int:
    """0 = most severe. Unknown severities sort last."""
    return _SEVERITY_RANK.get(sev, len(SEVERITIES))


@dataclass
class Finding:
    """One diagnosed condition with op-level provenance."""

    lint: str                       # stable id, e.g. "shape-mismatch"
    severity: str                   # "error" | "warn" | "note"
    message: str
    op_name: Optional[str] = None   # node.name (or subexecutor name, Tier B)
    op_id: Optional[int] = None     # node.id
    op_type: Optional[str] = None   # type(node).__name__
    pass_name: Optional[str] = None
    # live node handle for suppression filtering; never serialized
    op: Any = field(default=None, repr=False, compare=False)

    @classmethod
    def at(cls, node, lint: str, severity: str, message: str,
           pass_name: Optional[str] = None) -> "Finding":
        """Finding pinned to a graph node."""
        return cls(lint=lint, severity=severity, message=message,
                   op_name=getattr(node, "name", None),
                   op_id=getattr(node, "id", None),
                   op_type=type(node).__name__ if node is not None else None,
                   pass_name=pass_name, op=node)

    def as_dict(self) -> dict:
        return {"lint": self.lint, "severity": self.severity,
                "message": self.message, "op": self.op_name,
                "op_id": self.op_id, "op_type": self.op_type,
                "pass": self.pass_name}

    def __str__(self) -> str:
        where = ""
        if self.op_name is not None:
            where = (f" {self.op_name}"
                     + (f" ({self.op_type})" if self.op_type else "")) + ":"
        return f"{self.severity}[{self.lint}]{where} {self.message}"


def suppress(node, *lints: str):
    """Mark ``node`` so the listed lint ids are not reported against it
    (``"*"`` suppresses everything). Returns ``node`` for chaining."""
    cur = set(getattr(node, "lint_suppress", ()) or ())
    cur.update(lints)
    node.lint_suppress = cur
    return node


def is_suppressed(finding: Finding, global_suppress=()) -> bool:
    if finding.lint in global_suppress or "*" in global_suppress:
        return True
    node_sup = getattr(finding.op, "lint_suppress", None)
    if node_sup and (finding.lint in node_sup or "*" in node_sup):
        return True
    return False


def sort_findings(findings) -> list:
    """Stable order: severity first, then graph position (op id)."""
    return sorted(findings, key=lambda f: (severity_rank(f.severity),
                                           f.op_id if f.op_id is not None
                                           else 1 << 30))


def count_by_severity(findings) -> dict:
    counts = {s: 0 for s in SEVERITIES}
    for f in findings:
        counts[f.severity] = counts.get(f.severity, 0) + 1
    return counts


def format_findings(findings, indent: str = "  ") -> str:
    return "\n".join(indent + str(f) for f in sort_findings(findings))


class GraphValidationError(ValueError):
    """Raised by ``Executor(..., lint="error")`` when the graph has
    error-severity findings. Carries the full finding list."""

    def __init__(self, findings):
        self.findings = list(findings)
        errors = [f for f in self.findings if f.severity == ERROR]
        super().__init__(
            f"graph validation failed with {len(errors)} error(s):\n"
            + format_findings(errors))
