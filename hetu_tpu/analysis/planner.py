"""hetuplan: the Tier C auto-parallelism planner pass (docs/ANALYSIS.md
"Tier C: planning").

Tier A lints a declared layout; this pass *chooses* one. Over the same
``GraphAnalyzer`` op graph and abstract shapes, :func:`plan_graph` prices
layout candidates with :mod:`cost_model` and returns a :class:`Plan`:

- **Per-parameter comm mode** — AllReduce vs PS by density × size, the
  reference's hand-tuned Hybrid heuristic automated (Automatic
  Cross-Replica Sharding, PAPERS.md arXiv:2004.13336, mechanizes exactly
  this kind of weight-update placement from a static cost model). Sparse
  (lookup-accessed) params prefer PS unless AllReduce is *meaningfully*
  cheaper: at equal wire cost the sparse route still avoids materializing
  the dense ``(vocab, dim)`` table gradient on-device (the 7.7x/19.7x
  dense-vs-rows cost PR 12 measured) and keeps the server-side update
  sparse.
- **Per-tensor comm quantization** — on/off from the analytic wire ratios
  (EQuARX, arXiv:2506.17615; PR 8's validated formulas): dense AllReduce
  tensors follow the hetuq size exemption (small/sensitive params stay
  exact), PS sparse rows quantize whenever the row-wise ``kQI8`` ratio
  clears the threshold (one f32 scale per row — worth it from tiny row
  widths up, independent of table size).
- **Mesh-shape search** — every (dp, tp, pp) factorization of the device
  budget the graph can actually realize (tp needs dispatch markers, pp
  needs pipeline ops/gpipe), each checked for HBM feasibility via the AOT
  memory-gate formula. An infeasible candidate first escalates to ZeRO-1
  (slots shard over dp), then remat, then PS-offload of sparse tables; a
  candidate that still fails the gate is NEVER the chosen plan.

Surfaces: ``hetulint --plan [--devices N] [--calibrate TEL_DIR] [--json]``
(CLI, findings are note-severity and suppressible like every pass),
``Plan.apply(config)`` / ``HetuConfig(plan="auto")`` (executor adoption at
build), and the ``bench.py`` ``planner`` section (predicted vs measured).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .findings import Finding, ERROR, WARN, NOTE
from .cost_model import (
    Calibration, CostModel, CostModelConfig, load_calibration,
    pipeline_bubble, ps_dense_bytes, ps_sparse_bytes, ring_allreduce_bytes,
)

# AllReduce must beat PS by this factor to claim a SPARSE param: at parity
# the sparse route wins on the costs the wire model can't see (no dense
# table-grad materialization, sparse server-side update)
SPARSE_AR_MARGIN = 1.2
# minimum analytic wire ratio before quantization is worth switching on
QUANT_RATIO_MIN = 1.2


@dataclass
class ParamDecision:
    """One parameter's planned communication treatment."""

    name: str
    size_elems: int
    nbytes: int
    dim: int
    sparse: bool
    density: float
    touched_rows: float
    mode: str                     # "AllReduce" | "PS" | "local"
    quant: Optional[str] = None   # None | "int8" | "kQI8"
    wire_ratio: float = 1.0
    reason: str = ""
    node: object = None

    def as_dict(self) -> dict:
        return {"param": self.name, "size": self.size_elems,
                "sparse": self.sparse,
                "density": round(self.density, 4) if self.sparse else None,
                "mode": self.mode, "quant": self.quant,
                "wire_ratio": round(self.wire_ratio, 3),
                "reason": self.reason}


@dataclass
class MeshCandidate:
    """One evaluated (dp, tp, pp) point of the search."""

    dp: int
    tp: int
    pp: int
    feasible: bool = False
    zero1: bool = False
    remat: bool = False
    ps_offload: bool = False
    predicted_step_ms: Optional[float] = None
    peak_gib: Optional[float] = None
    why: str = ""

    def as_dict(self) -> dict:
        return {"dp": self.dp, "tp": self.tp, "pp": self.pp,
                "feasible": self.feasible, "zero1": self.zero1,
                "remat": self.remat, "ps_offload": self.ps_offload,
                "predicted_step_ms": (round(self.predicted_step_ms, 4)
                                      if self.predicted_step_ms is not None
                                      else None),
                "peak_gib": (round(self.peak_gib, 3)
                             if self.peak_gib is not None else None),
                "why": self.why}


@dataclass
class Plan:
    """The planner's verdict: a full layout choice with priced rationale.

    ``mesh`` is ``None`` when NO candidate passed the HBM gate — an
    infeasible layout is never emitted as the choice (the gate's whole
    point). ``zero1``/``remat`` are advisory for the Op-graph executor
    (which has no in-graph ZeRO-1) and directly consumable by the
    functional models' ``zero1=``/``remat=`` knobs.
    """

    devices: int
    mesh: Optional[Dict[str, int]]          # {"dp", "tp", "pp"} | None
    comm_mode: Optional[str]                # None/AllReduce/PS/Hybrid
    comm_quant: str                         # "off" | "int8"
    zero1: bool
    remat: bool
    predicted_step_ms: Optional[float]
    breakdown: Dict[str, float]
    memory: Dict[str, float]
    params: List[ParamDecision]
    candidates: List[MeshCandidate]
    calibration: Optional[Calibration] = None
    anchor: object = field(default=None, repr=False)

    # ------------------------------------------------------------------
    def as_dict(self) -> dict:
        return {
            "devices": self.devices,
            "mesh": dict(self.mesh) if self.mesh else None,
            "comm_mode": self.comm_mode,
            "comm_quant": self.comm_quant,
            "zero1": self.zero1,
            "remat": self.remat,
            "predicted_step_ms": (round(self.predicted_step_ms, 4)
                                  if self.predicted_step_ms is not None
                                  else None),
            "breakdown": {k: round(v, 4) for k, v in self.breakdown.items()},
            "memory": {k: (round(v, 4) if isinstance(v, float) else v)
                       for k, v in self.memory.items()},
            "params": [d.as_dict() for d in self.params],
            "candidates": [c.as_dict() for c in self.candidates],
            "calibration": (self.calibration.as_dict()
                            if self.calibration else None),
        }

    def summary(self) -> str:
        if self.mesh is None:
            return ("plan: NO feasible layout for the device budget "
                    f"({self.devices} device(s)) — every mesh candidate "
                    "fails the HBM gate even with ZeRO-1/remat")
        m = self.mesh
        lines = [
            f"plan: dp{m['dp']}/tp{m['tp']}/pp{m['pp']} over "
            f"{self.devices} device(s), comm_mode="
            f"{self.comm_mode or 'none'}, comm_quant={self.comm_quant}"
            + (", zero1" if self.zero1 else "")
            + (", remat" if self.remat else ""),
            f"predicted step {self.predicted_step_ms:.3f} ms ("
            + ", ".join(f"{k} {v:.3f}" for k, v in self.breakdown.items())
            + ")",
            f"projected HBM {self.memory['peak_gib']:.3f} GiB / "
            f"{self.memory['budget_gib']:g} GiB budget",
        ]
        for d in self.params:
            lines.append(f"  {d.name}: {d.mode}"
                         + (f" + {d.quant}" if d.quant else "")
                         + f" — {d.reason}")
        return "\n".join(lines)

    # ------------------------------------------------------------------
    def findings(self, config=None) -> List[Finding]:
        """The plan as structured findings — note severity, per-decision
        rationale, suppressible like every other pass (``plan-*`` ids);
        ``plan-infeasible`` is the one error. ``config`` (the running /
        declared config) adds ``plan-divergence`` warnings where it
        contradicts the choice."""
        out: List[Finding] = []
        if self.mesh is None:
            out.append(Finding.at(
                self.anchor, "plan-infeasible", ERROR,
                f"no (dp, tp, pp) factorization of {self.devices} device(s) "
                f"fits the {self.memory.get('budget_gib', 0):g} GiB HBM "
                "budget, even with ZeRO-1 + remat + PS offload — shrink the "
                "model, raise the budget, or add devices "
                f"(best candidate peaked at "
                f"{self.memory.get('peak_gib', 0):.2f} GiB)", "planner"))
        else:
            m = self.mesh
            rejected = sum(1 for c in self.candidates if not c.feasible)
            out.append(Finding.at(
                self.anchor, "plan-mesh", NOTE,
                f"chose dp{m['dp']}/tp{m['tp']}/pp{m['pp']} of "
                f"{len(self.candidates)} candidate(s) ({rejected} HBM-"
                f"rejected): predicted step {self.predicted_step_ms:.3f} ms, "
                f"projected HBM {self.memory['peak_gib']:.3f}/"
                f"{self.memory['budget_gib']:g} GiB", "planner"))
            if self.zero1 or self.remat:
                knobs = " + ".join(k for k, on in
                                   (("ZeRO-1", self.zero1),
                                    ("remat", self.remat)) if on)
                out.append(Finding.at(
                    self.anchor, "plan-memory", NOTE,
                    f"{knobs} adopted: the plain layout overflows the HBM "
                    f"gate; with it the candidate fits at "
                    f"{self.memory['peak_gib']:.3f} GiB", "planner"))
        for d in self.params:
            out.append(Finding.at(
                d.node, "plan-comm-mode", NOTE,
                f"{d.mode}" + (f" + {d.quant}" if d.quant else "")
                + f": {d.reason}", "planner"))
        quantized = [d for d in self.params if d.quant]
        if quantized:
            raw = wire = 0.0
            for d in quantized:
                if d.mode == "PS" and d.sparse:
                    b = ps_sparse_bytes(d.touched_rows, d.dim, quant=d.quant)
                elif d.mode == "PS":
                    b = ps_dense_bytes(d.size_elems, quant=d.quant)
                else:
                    b = ring_allreduce_bytes(d.size_elems,
                                             max(2, self.mesh["dp"])
                                             if self.mesh else 2,
                                             quant=d.quant)
                raw += b["raw"]
                wire += b["wire"]
            out.append(Finding.at(
                self.anchor, "plan-comm-quant", NOTE,
                f"{len(quantized)} tensor(s) quantized: analytic wire "
                f"{raw / 1e3:.1f} KB -> {wire / 1e3:.1f} KB per step "
                f"({raw / wire if wire else 1:.2f}x)", "planner"))
        out.extend(self.divergence_findings(config))
        return out

    def divergence_findings(self, config=None) -> List[Finding]:
        """``plan-divergence`` warnings: the running/declared config
        contradicts the planner's choice (a hand-picked layout the cost
        model disagrees with deserves a human look, not silence)."""
        out: List[Finding] = []
        if config is None:
            return out
        declared = getattr(config, "comm_mode", None)
        if declared is not None and self.comm_mode is not None \
                and declared != self.comm_mode:
            out.append(Finding.at(
                self.anchor, "plan-divergence", WARN,
                f"running config declares comm_mode={declared!r} but the "
                f"cost model chose {self.comm_mode!r} for this graph — "
                "hand-picked layout contradicts the planner; re-examine or "
                "suppress", "planner"))
        pol = getattr(config, "comm_quant_policy", None)
        declared_q = getattr(pol, "mode", None) if pol is not None else None
        if declared_q is not None and declared_q != "off" \
                and self.comm_quant == "off":
            out.append(Finding.at(
                self.anchor, "plan-divergence", WARN,
                f"running config arms comm_quant={declared_q!r} but the "
                "planner found no tensor worth quantizing (all below the "
                "exemption threshold or no comm legs)", "planner"))
        return out

    # ------------------------------------------------------------------
    def apply(self, config):
        """Adopt this plan on a ``HetuConfig``/``AnalysisConfig``: fills
        comm_mode and the comm_quant policy where the config left them
        unset (an explicitly declared value is never overridden — hetulint
        reports the divergence instead), re-deduces the mesh under the new
        comm_mode, and records zero1/remat advisories. Returns ``config``.
        """
        config.plan_adopted = self
        if getattr(config, "comm_mode", None) is None \
                and self.comm_mode is not None:
            if getattr(config, "anomaly_guard", False) \
                    and self.comm_mode in ("PS", "Hybrid"):
                raise ValueError(
                    "plan adoption chose comm_mode "
                    f"{self.comm_mode!r} but anomaly_guard is armed — PS-"
                    "hosted updates cannot be rolled back; disable the "
                    "guard or pass comm_mode explicitly")
            config.comm_mode = self.comm_mode
            # HetuConfig deduced its mesh before the plan existed (under
            # comm_mode=None); re-deduce now that a strategy is set
            if getattr(config, "mesh", None) is None \
                    and hasattr(config, "_deduce_mesh"):
                config.mesh = config._deduce_mesh()
        pol = getattr(config, "comm_quant_policy", None)
        if self.comm_quant != "off" \
                and not getattr(config, "gpipe", False) \
                and (pol is None or not getattr(pol, "active", False)):
            from ..comm_quant import resolve_policy
            config.comm_quant_policy = resolve_policy(self.comm_quant)
            config.comm_quant = self.comm_quant
        # advisory for the functional-model knobs (transformer/pipeline
        # zero1=, TransformerConfig.remat) — the Op-graph executor carries
        # them as metadata only
        config.plan_zero1 = self.zero1
        config.plan_remat = self.remat
        return config

    def device_group(self, device: str = "tpu"):
        """The chosen (dp, tp) mesh as a DeviceGroup literal for
        ``Executor(ctx=...)`` — ``context.mesh_device_group``'s tuple
        syntax carries the tp axis. None when no feasible layout exists
        or the layout is single-device."""
        if self.mesh is None or self.mesh["dp"] * self.mesh["tp"] <= 1:
            return None
        from ..context import mesh_device_group
        return mesh_device_group(self.mesh["dp"], self.mesh["tp"],
                                 device=device)


# ---------------------------------------------------------------------------
# decision rules
# ---------------------------------------------------------------------------

def decide_params(model: CostModel, dp: int,
                  ps_offload: bool = False) -> List[ParamDecision]:
    """Per-parameter comm-mode + quantization assignment at a given dp.

    dp == 1: no replication, nothing to synchronize — every param is
    ``local`` (unless ``ps_offload`` pushes sparse tables server-side for
    HBM). dp > 1: dense params price ring-AllReduce vs PS dense push/pull
    (AllReduce wins on the fabric); sparse params price PS row traffic vs
    dense-ifying the table grad for AllReduce — PS keeps the param unless
    AllReduce is ≥``SPARSE_AR_MARGIN``× cheaper, because the wire model
    undercounts the dense route (table-grad materialization, dense update).
    """
    cmc = model.cmc
    out: List[ParamDecision] = []
    for p in model.params:
        quant = None
        ratio = 1.0
        if p.forced_ps:
            mode = "PS"
            reason = ("explicit PS push in the graph pins this param to "
                      "the server (the rows route) — a layout choice "
                      "cannot remove a graph op")
            if p.sparse:
                qs = ps_sparse_bytes(p.touched_rows, p.dim, quant="kQI8")
                if qs["ratio"] >= QUANT_RATIO_MIN:
                    quant, ratio = "kQI8", qs["ratio"]
            elif p.size >= cmc.quant_min_size:
                qd = ps_dense_bytes(p.size, quant="kQI8",
                                    block=cmc.quant_block)
                if qd["ratio"] >= QUANT_RATIO_MIN:
                    quant, ratio = "kQI8", qd["ratio"]
        elif dp <= 1 and not (ps_offload and p.sparse):
            mode = "local"
            reason = "single replica: no gradient synchronization needed"
        elif p.sparse:
            ps = ps_sparse_bytes(p.touched_rows, p.dim, quant=None)
            ar = ring_allreduce_bytes(p.size, max(2, dp))
            # the AllReduce route must also build + move the dense table
            # grad through HBM (3 passes over table bytes: zeros, scatter,
            # read) — the PR-12 measured cost the wire bytes don't show
            ps_ms = (ps["wire"] * max(1, dp)
                     / (cmc.ps_servers * cmc.ps_gbs * 1e9) * 1e3)
            ar_ms = (ar["wire"] / (cmc.net_gbs * 1e9) * 1e3
                     + 3.0 * p.nbytes / (cmc.peak_gbs * 1e9) * 1e3)
            # ps_offload overrides the wire comparison: the table must
            # leave the device for the candidate to fit the HBM gate
            if not ps_offload and dp > 1 \
                    and ar_ms * SPARSE_AR_MARGIN < ps_ms:
                mode = "AllReduce"
                reason = (f"density {p.density:.2f} high enough that a "
                          f"dense all-reduce ({ar_ms:.4f} ms) beats PS row "
                          f"traffic ({ps_ms:.4f} ms) by >"
                          f"{SPARSE_AR_MARGIN}x")
            else:
                mode = "PS"
                qs = ps_sparse_bytes(p.touched_rows, p.dim, quant="kQI8")
                if qs["ratio"] >= QUANT_RATIO_MIN:
                    quant, ratio = "kQI8", qs["ratio"]
                if ps_offload:
                    reason = ("sparse table offloaded to PS for HBM "
                              "headroom (the layout overflows the gate "
                              "with it device-resident)")
                elif dp > 1:
                    reason = (
                        f"sparse table, density {p.density:.2f} "
                        f"(~{p.touched_rows:.0f}/{p.vocab} rows/step): "
                        f"PS moves {ps['wire'] / 1e3:.1f} KB of rows vs "
                        f"{ar['wire'] / 1e3:.1f} KB dense all-reduce + "
                        "a table-shaped grad materialization")
                else:
                    reason = "sparse table offloaded to PS for HBM headroom"
        else:
            ar = ring_allreduce_bytes(p.size, dp)
            psd = ps_dense_bytes(p.size)
            ar_ms = ar["wire"] / (cmc.net_gbs * 1e9) * 1e3
            ps_ms = (psd["wire"] * dp
                     / (cmc.ps_servers * cmc.ps_gbs * 1e9) * 1e3)
            if ps_ms < ar_ms:
                mode = "PS"
                reason = (f"dense but PS cheaper here: {ps_ms:.4f} ms vs "
                          f"ring {ar_ms:.4f} ms")
                qd = ps_dense_bytes(p.size, quant="kQI8",
                                    block=cmc.quant_block)
                if p.size >= cmc.quant_min_size \
                        and qd["ratio"] >= QUANT_RATIO_MIN:
                    quant, ratio = "kQI8", qd["ratio"]
            else:
                mode = "AllReduce"
                reason = (f"dense grad: ring all-reduce "
                          f"{ar['wire'] / 1e3:.1f} KB ({ar_ms:.4f} ms) vs "
                          f"PS {psd['wire'] * dp / 1e3:.1f} KB "
                          f"({ps_ms:.4f} ms)")
                qa = ring_allreduce_bytes(p.size, dp, quant="int8",
                                          block=cmc.quant_block)
                if p.tp_sharded:
                    # the executor exempts tp-sharded params from hetuq
                    # (their sync is not a pure-DP all-reduce) — mirror it
                    reason += "; quant off (tp-sharded, hetuq-exempt)"
                elif p.size >= cmc.quant_min_size \
                        and qa["ratio"] >= QUANT_RATIO_MIN:
                    quant, ratio = "int8", qa["ratio"]
                elif p.size < cmc.quant_min_size:
                    reason += (f"; quant off ({p.size} elems below the "
                               f"{cmc.quant_min_size}-elem exemption)")
        out.append(ParamDecision(
            name=p.name, size_elems=p.size, nbytes=p.nbytes, dim=p.dim,
            sparse=p.sparse, density=p.density,
            touched_rows=p.touched_rows, mode=mode, quant=quant,
            wire_ratio=ratio, reason=reason, node=p.node))
    return out


def _mesh_candidates(devices: int, tp_able: bool, pp_able: bool):
    """Every (dp, tp, pp) factorization of the device budget the graph
    can realize. tp needs dispatch markers; pp needs pipeline structure."""
    def divisors(n):
        return [d for d in range(1, n + 1) if n % d == 0]

    out = []
    for tp in (divisors(devices) if tp_able else [1]):
        for pp in (divisors(devices // tp) if pp_able else [1]):
            if devices % (tp * pp):
                continue
            dp = devices // (tp * pp)
            out.append((dp, tp, pp))
    return sorted(set(out))


def evaluate_candidate(model: CostModel, dp: int, tp: int, pp: int,
                       microbatches: int) -> tuple:
    """Price one mesh point, escalating through the memory fallbacks.

    Returns ``(MeshCandidate, decisions, memory_dict)``. Escalation
    order when the AOT-gate formula projects an overflow: ZeRO-1 (slots
    shard over dp), then remat (saved activations scaled by
    ``remat_factor``), then PS-offload of sparse tables. A candidate
    that still overflows is marked infeasible and can never be chosen.
    """
    decisions = decide_params(model, dp)
    ps_ids = frozenset(id(d.node) for d in decisions if d.mode == "PS")
    zero1 = remat = ps_off = False
    has_slots = any(p.slot_factor for p in model.params)
    while True:
        mem = model.memory(dp, tp, pp, ps_resident=ps_ids,
                           zero1=zero1, remat=remat)
        if mem["feasible"]:
            break
        if not zero1 and dp > 1 and has_slots:
            zero1 = True
            continue
        if not remat and model.training:
            remat = True
            continue
        if not ps_off and any(p.sparse for p in model.params) \
                and not all(d.mode == "PS" for d in decisions
                            if d.sparse):
            ps_off = True
            decisions = decide_params(model, dp, ps_offload=True)
            ps_ids = frozenset(id(d.node) for d in decisions
                               if d.mode == "PS")
            continue
        cand = MeshCandidate(
            dp=dp, tp=tp, pp=pp, feasible=False, zero1=zero1,
            remat=remat, ps_offload=ps_off, peak_gib=mem["peak_gib"],
            why=(f"HBM gate: {mem['peak_gib']:.2f} GiB > "
                 f"{mem['budget_gib']:g} GiB budget even with "
                 "ZeRO-1/remat/PS-offload"))
        return cand, decisions, mem
    bubble = pipeline_bubble(pp, microbatches)
    compute = model.compute_ms(dp, tp, remat=remat) / max(1, pp)
    if bubble:
        compute /= (1.0 - bubble)
    ar_ms = model.allreduce_ms(decisions, dp)
    ps_ms = model.ps_ms(decisions, dp)
    host = model.host_ms()
    step = compute + ar_ms + ps_ms + host
    cand = MeshCandidate(
        dp=dp, tp=tp, pp=pp, feasible=True, zero1=zero1, remat=remat,
        ps_offload=ps_off, predicted_step_ms=step,
        peak_gib=mem["peak_gib"], why="")
    breakdown = {"compute_ms": compute, "allreduce_ms": ar_ms,
                 "ps_ms": ps_ms, "host_ms": host,
                 "bubble_frac": bubble}
    return cand, decisions, {"mem": mem, "breakdown": breakdown}


# ---------------------------------------------------------------------------
# the entry point
# ---------------------------------------------------------------------------

def plan_graph(graph, config=None, devices: Optional[int] = None,
               calibrate=None, cost_config: Optional[CostModelConfig] = None,
               feed_meta: Optional[dict] = None,
               target: Optional[str] = None) -> Plan:
    """Plan a layout for ``graph`` (an Op, list, or ``{target: [ops]}``
    dict — the Executor eval spec).

    ``devices``: the device budget to lay out over (default: the local
    jax device count). ``calibrate``: a telemetry dir / roofline-JSON
    path (str) or a prebuilt :class:`Calibration`. ``config`` supplies
    dataloader/feed context and is diffed for ``plan-divergence`` — the
    planner never reads its comm_mode as a hint.
    """
    from .analyzer import GraphAnalyzer

    if devices is None:
        try:
            import jax
            devices = max(1, len(jax.devices()))
        except Exception:  # noqa: BLE001 — planning must not need devices
            devices = 1
    devices = max(1, int(devices))
    analyzer = GraphAnalyzer(graph, config=config, target=target,
                             feed_meta=feed_meta)
    from .analyzer import AnalysisContext
    ctx = AnalysisContext(analyzer.eval_nodes, analyzer.topo, config=config,
                          target=analyzer.target, feed_meta=feed_meta,
                          ps_embed_ids=analyzer.ps_embed_ids)
    calibration = None
    if isinstance(calibrate, Calibration):
        calibration = calibrate
    elif calibrate:
        calibration = load_calibration(str(calibrate))
    model = CostModel(analyzer.topo, ctx.abstract, cmc=cost_config,
                      calibration=calibration, training=True, config=config,
                      ps_embed_ids=analyzer.ps_embed_ids)
    microbatches = (getattr(config, "gpipe_microbatches", None)
                    or model.cmc.microbatches)

    candidates: List[MeshCandidate] = []
    best = None   # (cand, decisions, extras)
    for dp, tp, pp in _mesh_candidates(devices, model.tp_able,
                                       model.pp_able):
        cand, decisions, extras = evaluate_candidate(
            model, dp, tp, pp, microbatches)
        candidates.append(cand)
        if cand.feasible and (best is None
                              or cand.predicted_step_ms
                              < best[0].predicted_step_ms):
            best = (cand, decisions, extras)

    anchor = next((n for n in analyzer.topo if n.is_optimizer),
                  next(iter(analyzer.topo), None))
    if best is None:
        worst_peak = min((c.peak_gib for c in candidates
                          if c.peak_gib is not None), default=0.0)
        cmc = model.cmc
        return Plan(devices=devices, mesh=None, comm_mode=None,
                    comm_quant="off", zero1=False, remat=False,
                    predicted_step_ms=None, breakdown={},
                    memory={"peak_gib": worst_peak,
                            "budget_gib": cmc.hbm_budget_gb},
                    params=[], candidates=candidates,
                    calibration=calibration, anchor=anchor)

    cand, decisions, extras = best
    modes = {d.mode for d in decisions if d.mode != "local"}
    if modes == {"AllReduce"}:
        comm_mode = "AllReduce"
    elif modes == {"PS"}:
        comm_mode = "PS"
    elif modes:
        comm_mode = "Hybrid"
    else:
        comm_mode = None
    comm_quant = ("int8" if any(d.quant for d in decisions) else "off")
    return Plan(
        devices=devices,
        mesh={"dp": cand.dp, "tp": cand.tp, "pp": cand.pp},
        comm_mode=comm_mode, comm_quant=comm_quant,
        zero1=cand.zero1, remat=cand.remat,
        predicted_step_ms=cand.predicted_step_ms,
        breakdown=extras["breakdown"], memory=extras["mem"],
        params=decisions, candidates=candidates,
        calibration=calibration, anchor=anchor)


# ---------------------------------------------------------------------------
# CI self-test (hetulint --plan --check)
# ---------------------------------------------------------------------------

def _overflow_graph():
    """A graph whose dp-replicated layout overflows a ~3 GiB budget but
    whose ZeRO-1 variant fits: one 1.07 GiB Adam-managed weight (param
    1.07 + slots 2.15 + grad 1.07 GiB plain; slots/dp under ZeRO-1).
    Nothing materializes — initializers carry shapes only."""
    import numpy as np
    import hetu_tpu as ht

    x = ht.Variable(name="plan_big_x",
                    value=np.zeros((32, 4096), np.float32),
                    trainable=False)
    w = ht.init.random_normal((4096, 65536), stddev=0.02, name="plan_big_w")
    loss = ht.reduce_mean_op(ht.matmul_op(x, w), [0, 1])
    train = ht.optim.AdamOptimizer(1e-3).minimize(loss)
    return {"train": [loss, train]}


def plan_self_check(out=None) -> int:
    """Tier-1-safe smoke of the planning contract over the bundled
    builders + a synthetic HBM-overflow graph. Returns 0 when every
    claim holds — the verify-skill/CI hook (docs/ANALYSIS.md)."""
    import sys

    out = out or sys.stdout
    from . import examples
    from .analyzer import AnalysisConfig
    from .cli import _builder_result

    ok = True

    def check(label, cond):
        nonlocal ok
        state = "ok" if cond else "FAIL"
        if not cond:
            ok = False
        print(f"hetulint --plan --check: {label} -> {state}", file=out)

    # 1. CTR-PS: Hybrid with quantized sparse rows, no hand hints
    graph, cfg_kwargs = _builder_result(examples.build_ctr_ps)
    plan = plan_graph(graph, config=AnalysisConfig(), devices=8)
    table = next((d for d in plan.params if d.sparse), None)
    dense = [d for d in plan.params if not d.sparse]
    check("ctr_ps plans Hybrid", plan.comm_mode == "Hybrid")
    check("ctr_ps sparse table -> PS + kQI8",
          table is not None and table.mode == "PS"
          and table.quant == "kQI8")
    check("ctr_ps dense params -> AllReduce",
          bool(dense) and all(d.mode == "AllReduce" for d in dense))

    # 2. MLP: pure dense -> AllReduce dp8, feasible, quant obeys exemption
    graph, _ = _builder_result(examples.build_mlp)
    plan = plan_graph(graph, devices=8)
    check("mlp plans AllReduce dp8",
          plan.comm_mode == "AllReduce" and plan.mesh == {"dp": 8, "tp": 1,
                                                          "pp": 1})
    small = [d for d in plan.params if d.size_elems < 2048]
    check("mlp small params keep exact wire (exemption)",
          all(d.quant is None for d in small))

    # 3. HBM gate: a graph whose plain layout overflows adopts ZeRO-1;
    # one no budget can hold is never emitted as a chosen plan
    big = _overflow_graph()
    plan = plan_graph(big, devices=8,
                      cost_config=CostModelConfig(hbm_budget_gb=3.0))
    check("overflowing layout adopts ZeRO-1, fits the gate",
          plan.mesh is not None and plan.zero1
          and plan.memory.get("feasible") is True)
    plan = plan_graph(big, devices=8,
                      cost_config=CostModelConfig(hbm_budget_gb=0.5))
    check("impossible budget -> no plan + plan-infeasible error",
          plan.mesh is None
          and any(f.lint == "plan-infeasible" and f.severity == ERROR
                  for f in plan.findings()))

    # 4. calibration shifts the prediction in the measured direction
    graph, _ = _builder_result(examples.build_mlp)
    base = plan_graph(graph, devices=1)
    cal = Calibration(legs_ms={
        "compute": (base.breakdown.get("compute_ms", 0.0) or 1e-3) * 2.0,
        "feed": 0.05, "poststep": 0.05})
    shifted = plan_graph(graph, devices=1, calibrate=cal)
    check("calibration shifts prediction toward measured",
          shifted.predicted_step_ms > base.predicted_step_ms)

    return 0 if ok else 1
