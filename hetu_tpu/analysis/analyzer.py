"""GraphAnalyzer: the Tier A pass driver.

Three entry points share it:

- ``Executor(..., lint="error"|"warn")`` runs it at build over the real
  post-comm-insertion graph with the real ``HetuConfig``.
- ``bin/hetulint`` imports a graph-builder callable, records the op universe
  while building, and analyzes with a lightweight :class:`AnalysisConfig`
  (no devices touched, no PS servers spawned).
- ``graphboard.render(..., lint=True)`` annotates the topology drawing.
"""
from __future__ import annotations

import contextlib
from typing import Iterable, Optional, Sequence

import numpy as np

from ..graph.node import Op, _graph_recorders


def _tolerant_topo(node_list) -> list:
    """``find_topo_sort`` that survives malformed graphs: non-Op inputs are
    skipped (the structure pass reports them) and cycles terminate (the
    visited set breaks them; the structure pass reports those too). On a
    valid graph the order is identical to ``find_topo_sort``."""
    visited: set = set()
    order: list = []

    def children(n):
        return iter([c for c in getattr(n, "inputs", [])
                     if isinstance(c, Op)])

    for root in node_list:
        if not isinstance(root, Op) or id(root) in visited:
            continue
        visited.add(id(root))
        stack = [(root, children(root))]
        while stack:
            cur, it = stack[-1]
            advanced = False
            for child in it:
                if id(child) not in visited:
                    visited.add(id(child))
                    stack.append((child, children(child)))
                    advanced = True
                    break
            if not advanced:
                order.append(cur)
                stack.pop()
    return order
from .abstract import AbstractGraph
from .findings import (
    Finding, is_suppressed, sort_findings, ERROR, WARN, NOTE,
)
from .graph_passes import TIER_A_PASSES


@contextlib.contextmanager
def record_graph():
    """Record every Op constructed inside the block.

    The recorded list is the *universe* for dead-subgraph reporting: ops a
    builder constructed that ended up unreachable from its eval targets.
    ``hetulint`` wraps each builder call in one of these.
    """
    rec: list[Op] = []
    _graph_recorders.append(rec)
    try:
        yield rec
    finally:
        _graph_recorders.remove(rec)


class AnalysisConfig:
    """Duck-typed stand-in for ``HetuConfig`` carrying only what the passes
    read — lets ``hetulint`` lint a PS/AllReduce graph without spawning
    servers or touching devices."""

    def __init__(self, comm_mode=None, mesh=None, dp_size=None,
                 dp_axis="dp", mp_axis="tp", compute_dtype=np.float32,
                 gpipe=False, comm_quant_policy=None, kernels=None,
                 replicated_threshold_bytes=None):
        self.comm_mode = comm_mode
        self.mesh = mesh
        self._dp_size = dp_size
        self.dp_axis = dp_axis
        self.mp_axis = mp_axis
        self.compute_dtype = np.dtype(compute_dtype)
        self.gpipe = gpipe
        # hetuq policy for the comm_quant lints (a comm_quant.QuantPolicy);
        # None = quantization off, the lints are skipped
        self.comm_quant_policy = comm_quant_policy
        # hetukern mode for the kernels_pass lints ("off"|"auto"|"force");
        # None = skip the pass (the hetulint CLI default)
        self.kernels = kernels
        # replicated-large-tensor lint threshold; None defers to the
        # HETU_REPLICATED_THRESHOLD_BYTES env, then the 64 MiB default
        # (lowered.resolve_replicated_threshold)
        self.replicated_threshold_bytes = replicated_threshold_bytes

    @property
    def dp_size(self) -> int:
        if self._dp_size is not None:
            return int(self._dp_size)
        if self.mesh is not None and self.dp_axis in self.mesh.axis_names:
            return self.mesh.shape[self.dp_axis]
        return 1


class AnalysisContext:
    """What a pass sees: topo, eval targets, config, options, and the lazily
    computed abstract shape/dtype map."""

    def __init__(self, eval_nodes, topo, config=None, universe=None,
                 options=None, target=None, feed_meta=None,
                 ps_embed_ids=frozenset()):
        self.eval_nodes = list(eval_nodes)
        self.topo = list(topo)
        self.config = config
        self.universe = list(universe) if universe else None
        self.options = dict(options or {})
        self.target = target
        # tables the PS runtime WOULD classify as sparse-resident: the union
        # of explicitly marked is_embed vars and those the comm-insertion
        # replay inferred (the replay's attribute marks are rolled back so
        # the graph stays pristine — the inference survives here)
        self.ps_embed_ids = frozenset(ps_embed_ids)
        self._feed_meta = feed_meta
        self._abstract: Optional[AbstractGraph] = None

    @property
    def abstract(self) -> AbstractGraph:
        if self._abstract is None:
            self._abstract = AbstractGraph(
                self.topo, config=self.config, target=self.target,
                feed_meta=self._feed_meta).evaluate()
        return self._abstract


def _flatten_graph(graph) -> tuple[list, Optional[str]]:
    """Accept an Op, a list of Ops, or a ``{target: [Op, ...]}`` dict (the
    Executor's eval_node_dict form). Returns (eval nodes, first target)."""
    if isinstance(graph, Op):
        return [graph], None
    if isinstance(graph, dict):
        nodes = [n for ns in graph.values() for n in ns]
        first = next(iter(graph), None)
        return nodes, first
    return list(graph), None


class GraphAnalyzer:
    """Run Tier A passes over a graph: ``GraphAnalyzer(graph).run()``.

    ``graph``: an Op, list of Ops, or ``{target: [ops]}`` dict.
    ``config``: a ``HetuConfig`` or :class:`AnalysisConfig` (optional — comm
    placement lints that need a declared strategy are skipped without one).
    ``universe``: ops recorded by :func:`record_graph` for dead-subgraph
    reporting. ``suppress``: lint ids silenced analyzer-wide. ``options``:
    per-pass knobs. ``insert_comm=True`` replays the executor's comm-op
    insertion (AllReduce/PS markers on optimizer gradients) against
    ``config.comm_mode`` so a define-time lint sees the graph the executor
    would actually build — hetulint's default when a comm_mode is declared.
    """

    def __init__(self, graph, config=None, universe=None,
                 suppress: Sequence[str] = (), options: Optional[dict] = None,
                 target: Optional[str] = None, feed_meta: Optional[dict] = None,
                 insert_comm: bool = False):
        self.eval_nodes, first_target = _flatten_graph(graph)
        self.config = config
        self.suppress = tuple(suppress)
        self.options = dict(options or {})
        self.universe = universe
        self.target = target if target is not None else first_target
        self.feed_meta = feed_meta
        self._undo: list = []
        self.ps_embed_ids: set = set()
        if insert_comm and getattr(config, "comm_mode", None) is not None:
            self._insert_comm_ops()
        self.topo = _tolerant_topo(self.eval_nodes)
        # the topo snapshot keeps the inserted comm ops alive for the passes;
        # the *graph* must come back untouched — a later real Executor on the
        # same nodes has to run its own insertion against its own config.
        # (Inferred is_embed marks live on in ps_embed_ids for the passes.)
        self._restore_graph()

    def _insert_comm_ops(self):
        """Replay Executor.__init__'s strategy rewrite (executor.py): mark
        lookup-read embeddings, then let each optimizer wrap its gradient
        inputs in AllReduce/PS comm ops. Every mutation is recorded and
        undone by ``_restore_graph`` once the topo snapshot is taken."""
        topo = _tolerant_topo(self.eval_nodes)
        if self.config.comm_mode in ("PS", "Hybrid"):
            for node in topo:
                embed = getattr(node, "embed_node", None)
                if embed is not None and getattr(embed, "trainable", False):
                    self.ps_embed_ids.add(id(embed))
                    if not getattr(embed, "is_embed", False):
                        embed.is_embed = True
                        self._undo.append(("embed", embed))
        for node in topo:
            if node.is_optimizer:
                self._undo.append(("opt", node, list(node.inputs),
                                   node._comm_inserted))
                node.insert_comm_ops(self.config)

    def _restore_graph(self):
        for entry in reversed(self._undo):
            if entry[0] == "embed":
                entry[1].is_embed = False
            else:
                _, node, inputs, flag = entry
                node.inputs = inputs
                node._comm_inserted = flag
        self._undo = []

    def run(self, passes: Optional[Iterable] = None) -> list[Finding]:
        ctx = AnalysisContext(self.eval_nodes, self.topo, config=self.config,
                              universe=self.universe, options=self.options,
                              target=self.target, feed_meta=self.feed_meta,
                              ps_embed_ids=self.ps_embed_ids)
        findings: list[Finding] = []
        for p in (TIER_A_PASSES if passes is None else passes):
            findings.extend(p(ctx))
        findings = [f for f in findings
                    if not is_suppressed(f, self.suppress)]
        return sort_findings(findings)


def analyze_graph(graph, config=None, **kwargs) -> list[Finding]:
    """One-call Tier A analysis: ``analyze_graph(eval_nodes) -> findings``."""
    return GraphAnalyzer(graph, config=config, **kwargs).run()
