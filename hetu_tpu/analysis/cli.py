"""hetulint: lint graph-builder callables from the command line / CI.

    hetulint [--json] [--suppress LINT]... [--fail-on error|warn|never]
             MODULE:CALLABLE [MODULE:CALLABLE ...]

A target is ``package.module:callable`` or ``path/to/file.py:callable``. The
callable takes no arguments and returns one of:

- an Op / list of Ops / ``{target: [ops]}`` dict (an Executor eval spec), or
- ``(graph, config_kwargs)`` where ``config_kwargs`` build an
  :class:`AnalysisConfig` (e.g. ``{"comm_mode": "PS"}``) so strategy lints
  apply without spawning any runtime.

Every op constructed by the builder is recorded, so dead subgraphs (built but
unreachable from the returned eval targets) are reported. Exit status: 0
clean, 1 findings at/above ``--fail-on`` (default ``error``), 2 usage or
builder-import failure.

``--plan`` switches to the hetuplan Tier C pass (docs/ANALYSIS.md "Tier C:
planning"): instead of linting the declared layout, choose one —

    hetulint --plan [--devices N] [--calibrate TEL_DIR] [--json] \\
             MODULE:CALLABLE ...
    hetulint --plan --check        # CI self-test of the planning contract
"""
from __future__ import annotations

import argparse
import importlib
import importlib.util
import json
import os
import sys

from .analyzer import AnalysisConfig, GraphAnalyzer, record_graph
from .findings import count_by_severity, sort_findings


def load_builder(spec: str):
    """Resolve ``module.path:callable`` or ``path/to/file.py:callable``."""
    if ":" not in spec:
        raise ValueError(
            f"target {spec!r} is not of the form module:callable")
    mod_spec, _, attr = spec.rpartition(":")
    if mod_spec.endswith(".py") or os.path.sep in mod_spec:
        path = os.path.abspath(mod_spec)
        name = os.path.splitext(os.path.basename(path))[0]
        spec_obj = importlib.util.spec_from_file_location(name, path)
        if spec_obj is None:
            raise ImportError(f"cannot load {path!r}")
        module = importlib.util.module_from_spec(spec_obj)
        sys.modules.setdefault(name, module)
        spec_obj.loader.exec_module(module)
    else:
        module = importlib.import_module(mod_spec)
    fn = getattr(module, attr, None)
    if not callable(fn):
        raise AttributeError(
            f"{mod_spec!r} has no callable {attr!r}")
    return fn


def _builder_result(builder):
    """Normalize one builder call: ``graph`` or ``(graph, config_kwargs)``
    -> ``(graph, config_kwargs)``."""
    result = builder()
    if isinstance(result, tuple) and len(result) == 2 \
            and isinstance(result[1], dict):
        return result
    return result, {}


def lint_target(spec: str, suppress=(), options=None, kernels=None):
    """Build one target's graph (recording the op universe) and run Tier A.
    Returns (findings, counts). ``kernels`` overrides the builder's
    hetukern mode so CI can ask "would kernels='force' fly on this
    graph?" without editing the builder (docs/KERNELS.md)."""
    builder = load_builder(spec)
    with record_graph() as universe:
        result = builder()
    config_kwargs = {}
    graph = result
    if isinstance(result, tuple) and len(result) == 2 \
            and isinstance(result[1], dict):
        graph, config_kwargs = result
    if kernels is not None:
        config_kwargs = dict(config_kwargs, kernels=kernels)
    config = AnalysisConfig(**config_kwargs)
    analyzer = GraphAnalyzer(
        graph, config=config, universe=universe, suppress=suppress,
        options=options, insert_comm=config.comm_mode is not None)
    findings = analyzer.run()
    return findings, count_by_severity(findings)


def plan_target(spec: str, devices=None, calibrate=None, suppress=()):
    """Build one target's graph and run the hetuplan Tier C pass
    (docs/ANALYSIS.md "Tier C: planning"). The builder's declared config
    is NEVER a hint — it is only diffed against the choice for the
    ``plan-divergence`` lint. Returns (plan, findings, counts)."""
    from .findings import is_suppressed
    from .planner import plan_graph

    builder = load_builder(spec)
    graph, config_kwargs = _builder_result(builder)
    config = AnalysisConfig(**config_kwargs)
    plan = plan_graph(graph, config=config, devices=devices,
                      calibrate=calibrate)
    findings = [f for f in plan.findings(config=config)
                if not is_suppressed(f, suppress)]
    findings = sort_findings(findings)
    return plan, findings, count_by_severity(findings)


def _plan_main(args) -> int:
    """The ``hetulint --plan`` mode: plan each target, print the chosen
    layout + predicted step time + per-decision rationale findings. Exit
    status follows the lint contract (0 clean under --fail-on, 1
    findings at/above it — a ``plan-infeasible`` error fails by default,
    a ``plan-divergence`` warn only under ``--fail-on warn``), 2 usage/
    builder failure."""
    if args.check:
        from .planner import plan_self_check
        return plan_self_check()
    if not args.targets:
        print("hetulint: --plan needs MODULE:CALLABLE target(s) "
              "(or --check)", file=sys.stderr)
        return 2
    devices = args.devices if args.devices is not None else 8

    def target_ok(counts) -> bool:
        if args.fail_on == "never":
            return True
        bad = counts["error"]
        if args.fail_on == "warn":
            bad += counts["warn"]
        return bad == 0

    results = []
    load_failed = False
    for spec in args.targets:
        try:
            plan, findings, counts = plan_target(
                spec, devices=devices, calibrate=args.calibrate,
                suppress=args.suppress)
        except Exception as e:  # noqa: BLE001 — builder errors are exit 2
            print(f"hetulint: cannot plan {spec!r}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            results.append({"target": spec, "plan": None, "findings": [],
                            "counts": None, "ok": False,
                            "error": f"{type(e).__name__}: {e}"})
            load_failed = True
            continue
        results.append({"target": spec, "plan": plan.as_dict(),
                        "findings": [f.as_dict() for f in findings],
                        "counts": counts, "ok": target_ok(counts)})
        if not args.as_json:
            print(f"{spec}:")
            print(plan.summary())
            for f in findings:
                print(f"  {f}")
    ok = all(r["ok"] for r in results)
    if args.as_json:
        print(json.dumps({"results": results, "ok": ok}, indent=2))
    if load_failed:
        return 2
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="hetulint",
        description="Define-time graph validation for hetu_tpu graphs.")
    ap.add_argument("targets", nargs="*", metavar="MODULE:CALLABLE",
                    help="graph-builder callable(s) to lint")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output for CI")
    ap.add_argument("--suppress", action="append", default=[],
                    metavar="LINT", help="silence a lint id (repeatable)")
    ap.add_argument("--fail-on", choices=["error", "warn", "never"],
                    default="error",
                    help="lowest severity that fails the run (default error)")
    ap.add_argument("--kernels", choices=["off", "auto", "force"],
                    default=None,
                    help="override the hetukern dispatch mode for the "
                         "kernels_pass lints (docs/KERNELS.md)")
    ap.add_argument("--plan", action="store_true",
                    help="run the hetuplan Tier C pass: choose comm-mode/"
                         "mesh/quantization/ZeRO-1/remat from the cost "
                         "model instead of linting a declared layout")
    ap.add_argument("--devices", type=int, default=None, metavar="N",
                    help="device budget for --plan (default 8, the bench "
                         "virtual-mesh size; pass 1 for single-chip)")
    ap.add_argument("--calibrate", metavar="TEL_DIR",
                    help="with --plan: telemetry dir (or hetuprof "
                         "--roofline --json file) whose measured residuals "
                         "and critical-path legs calibrate the cost model")
    ap.add_argument("--check", action="store_true",
                    help="with --plan: self-test the planning contract "
                         "over the bundled builders (CI smoke)")
    args = ap.parse_args(argv)

    if args.plan:
        return _plan_main(args)
    if not args.targets:
        ap.print_usage(sys.stderr)
        return 2

    def target_ok(counts) -> bool:
        """Does this target pass under --fail-on? Keeps the per-target
        ``ok`` field and the exit status telling the same story."""
        if args.fail_on == "never":
            return True
        bad = counts["error"]
        if args.fail_on == "warn":
            bad += counts["warn"]
        return bad == 0

    results = []
    load_failed = False
    for spec in args.targets:
        try:
            findings, counts = lint_target(spec, suppress=args.suppress,
                                           kernels=args.kernels)
        except Exception as e:  # noqa: BLE001 — builder errors are exit 2
            # report on stderr, but keep the --json stdout contract: CI
            # parsers get a well-formed report carrying the partial results
            print(f"hetulint: cannot lint {spec!r}: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            results.append({"target": spec, "findings": [], "counts": None,
                            "ok": False,
                            "error": f"{type(e).__name__}: {e}"})
            load_failed = True
            continue
        results.append({"target": spec,
                        "findings": [f.as_dict() for f in findings],
                        "counts": counts,
                        "ok": target_ok(counts)})
        if not args.as_json:
            total = sum(counts.values())
            print(f"{spec} — {total} finding(s) "
                  f"({counts['error']} error, {counts['warn']} warn, "
                  f"{counts['note']} note)")
            for f in sort_findings(findings):
                print(f"  {f}")

    ok = all(r["ok"] for r in results)
    if args.as_json:
        print(json.dumps({"results": results, "ok": ok}, indent=2))
    if load_failed:
        return 2
    return 0 if ok else 1
