"""Tier A passes: define-time lints over the Op graph.

Each pass is a function ``(ctx: AnalysisContext) -> list[Finding]``. The
default pipeline is :data:`TIER_A_PASSES`; ``GraphAnalyzer.run(passes=...)``
accepts any subset or user-written passes with the same signature.

Lint catalogue (see docs/ANALYSIS.md for examples and suppression):

structure  : graph-cycle(E) bad-input(E) duplicate-name(W/N)
shapes     : shape-mismatch(E) abstract-eval-failed(N) shape-unknown(N)
             f64-value(W) f64-upcast(W) int-float-mix(N)
comm       : ps-op-without-ps-mode(E) ps-push-ignored(W)
             ps-lookup-index-not-fed(E) allreduce-without-comm-mode(W)
             allreduce-degenerate(N) dispatch-rank-mismatch(E)
             dispatch-no-mp-axis(E) dispatch-grad-unpaired(W)
             pipeline-send-unconsumed(W) pipeline-recv-source(N)
             pipeline-stage-loop(W)
comm_quant : comm-quant-forced-small(W) comm-quant-no-error-feedback(N)
kernels    : kernels-force-ineligible(E) kernels-auto-fallback(N)
dce        : dead-subgraph(W) common-subexpression(N)
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..graph.node import Op, PlaceholderOp, FunctionalOp
from ..graph.gradients import GradientOp
from ..graph.ops.comm import (
    AllReduceCommunicateOp, DispatchOp, DispatchGradientOp,
    PipelineSendOp, PipelineReceiveOp,
)
from ..graph.ops.ps import (
    ParameterServerCommunicateOp, ParameterServerSparsePullOp,
)
from .findings import Finding, ERROR, WARN, NOTE


# ---------------------------------------------------------------------------
# structure
# ---------------------------------------------------------------------------

def structure_pass(ctx) -> list:
    """Cycles, malformed inputs, duplicate names."""
    out = []
    # -- malformed inputs ---------------------------------------------------
    for node in ctx.topo:
        for i, inp in enumerate(node.inputs):
            if not isinstance(inp, Op):
                out.append(Finding.at(
                    node, "bad-input", ERROR,
                    f"input {i} is {type(inp).__name__!s} ({inp!r}), not an "
                    "Op — the graph cannot be traced", "structure"))
    # -- cycle detection (iterative white/gray/black DFS) -------------------
    WHITE, GRAY, BLACK = 0, 1, 2
    color: dict[int, int] = {}
    reported: set[int] = set()
    for root in ctx.eval_nodes:
        stack = [(root, iter(getattr(root, "inputs", [])))]
        color.setdefault(id(root), WHITE)
        color[id(root)] = GRAY
        while stack:
            cur, it = stack[-1]
            advanced = False
            for child in it:
                if not isinstance(child, Op):
                    continue
                c = color.get(id(child), WHITE)
                if c == GRAY:
                    if id(child) not in reported:
                        reported.add(id(child))
                        out.append(Finding.at(
                            child, "graph-cycle", ERROR,
                            f"participates in a dependency cycle via "
                            f"{cur.name!r} — topological evaluation is "
                            "impossible", "structure"))
                elif c == WHITE:
                    color[id(child)] = GRAY
                    stack.append((child, iter(child.inputs)))
                    advanced = True
                    break
            if not advanced:
                color[id(cur)] = BLACK
                stack.pop()
    # -- duplicate names ----------------------------------------------------
    by_name: dict[str, list] = {}
    for node in ctx.topo:
        by_name.setdefault(node.name, []).append(node)
    for name, nodes in by_name.items():
        if len(nodes) < 2:
            continue
        trainable = [n for n in nodes
                     if isinstance(n, PlaceholderOp) and n.trainable]
        sev = WARN if len(trainable) >= 2 else NOTE
        what = ("trainable parameters share" if sev == WARN
                else "ops share")
        extra = (" — checkpoints disambiguate with __<k> suffixes tied to "
                 "construction order, which silently breaks reloading into a "
                 "reordered graph" if sev == WARN else "")
        out.append(Finding.at(
            nodes[1], "duplicate-name", sev,
            f"{len(nodes)} {what} the name {name!r}{extra}", "structure"))
    return out


# ---------------------------------------------------------------------------
# shapes / dtypes
# ---------------------------------------------------------------------------

_ELEMENTWISE_MIX_OPS = {"AddElewise", "MultiplyElewise", "Division",
                        "MatrixDot"}


def shapes_pass(ctx) -> list:
    """Whole-graph abstract shape/dtype inference with mismatch localization
    plus dtype-promotion lints."""
    out = []
    ag = ctx.abstract
    by_id = {id(n): n for n in ctx.topo}
    for nid, (kind, msg) in ag.failures.items():
        node = by_id.get(nid)
        sev = ERROR if kind == "shape-mismatch" else NOTE
        out.append(Finding.at(node, kind, sev, msg, "shapes"))
    for node in ag.unknown_roots:
        out.append(Finding.at(
            node, "shape-unknown", NOTE,
            "shape is not known at define time (fed placeholder / dynamic "
            "loader) — downstream shape checks are skipped; declare shapes "
            "via an initializer, a Dataloader, or feed_meta", "shapes"))
    # -- dtype lints --------------------------------------------------------
    for node in ctx.topo:
        m = ag.meta.get(id(node))
        dt = getattr(m, "dtype", None) if m is not None else None
        if node.is_placeholder:
            declared = getattr(node, "dtype", None)
            if declared is not None and np.dtype(declared) == np.float64:
                out.append(Finding.at(
                    node, "f64-value", WARN,
                    "declared float64 — the executor silently casts feeds to "
                    "f32 and x64-disabled jax truncates parameters; declare "
                    "f32 (or enable x64 deliberately)", "shapes"))
            continue
        if dt is not None and np.dtype(dt) == np.float64:
            in_dts = [ag.dtype_of(i) for i in node.inputs]
            if not any(d is not None and np.dtype(d) == np.float64
                       for d in in_dts):
                out.append(Finding.at(
                    node, "f64-upcast", WARN,
                    f"output silently widens to float64 from inputs "
                    f"{[str(d) for d in in_dts]} — doubles memory and "
                    "falls off the TPU fast path", "shapes"))
        if isinstance(node, FunctionalOp) \
                and node.opname in _ELEMENTWISE_MIX_OPS:
            in_dts = [ag.dtype_of(i) for i in node.inputs]
            if len(in_dts) >= 2 and all(d is not None for d in in_dts):
                has_int = any(jnp.issubdtype(d, jnp.integer) for d in in_dts)
                has_flt = any(jnp.issubdtype(d, jnp.floating) for d in in_dts)
                if has_int and has_flt:
                    out.append(Finding.at(
                        node, "int-float-mix", NOTE,
                        f"{node.opname} mixes integer and float inputs "
                        f"({[str(d) for d in in_dts]}) — the integer side is "
                        "silently promoted; cast explicitly if intended",
                        "shapes"))
    return out


# ---------------------------------------------------------------------------
# comm placement
# ---------------------------------------------------------------------------

def _is_fed(node) -> bool:
    return (node.is_dataloader
            or (node.is_placeholder and getattr(node, "is_feed", False)))


def _embed_grad_push_wired(push, grad_in, ctx, consumers) -> bool:
    """Mirror of the executor's rows-route rewire preconditions
    (``_rewire_ps_gradients``): would this explicit embedding-grad push
    actually be wired? The structural half (sole consumer, not an eval
    target, ps_id present, dense mode) is the SHARED predicate
    ``embed_grad_push_routable``; only the target-param resolution
    differs — the PS runtime isn't available at lint time, so the param
    resolves by name over the topo with the same sparse classification
    the runtime applies."""
    from ..graph.ops.embedding import embed_grad_push_routable
    eval_ids = {id(n) for n in ctx.eval_nodes}
    if not embed_grad_push_routable(push, grad_in, consumers, eval_ids):
        return False
    var = next((n for n in ctx.topo
                if isinstance(n, PlaceholderOp) and n.trainable
                and n.name == push.ps_id), None)
    if var is None:
        return False
    sparse = (getattr(var, "is_embed", False)
              or id(var) in getattr(ctx, "ps_embed_ids", ()))
    if not sparse:
        return False
    shape = getattr(var, "shape", None)
    return shape is None \
        or tuple(shape) == tuple(getattr(grad_in, "embed_shape", ()))


def comm_pass(ctx) -> list:
    """Comm-op placement: AllReduce vs DP context, PS ops vs comm_mode,
    dispatch pairing/rank, pipeline send/recv consistency."""
    out = []
    cfg = ctx.config
    comm_mode = getattr(cfg, "comm_mode", None) if cfg is not None else None
    mesh = getattr(cfg, "mesh", None) if cfg is not None else None
    dp_size = getattr(cfg, "dp_size", 1) if cfg is not None else 1
    mp_axis = getattr(cfg, "mp_axis", "tp") if cfg is not None else "tp"
    ag = ctx.abstract

    consumers: dict[int, list] = {}
    for node in ctx.topo:
        for i in node.inputs:
            consumers.setdefault(id(i), []).append(node)

    has_dispatch = any(isinstance(n, DispatchOp) for n in ctx.topo)

    for node in ctx.topo:
        # -- AllReduce ------------------------------------------------------
        if isinstance(node, AllReduceCommunicateOp):
            if cfg is not None and comm_mode is None:
                out.append(Finding.at(
                    node, "allreduce-without-comm-mode", WARN,
                    "AllReduce marker in a graph built without comm_mode — "
                    "it lowers to an identity and gradients are NOT reduced "
                    "across replicas", "comm"))
            elif cfg is not None and (mesh is None or dp_size <= 1):
                out.append(Finding.at(
                    node, "allreduce-degenerate", NOTE,
                    f"AllReduce over a degenerate data-parallel context "
                    f"(dp={dp_size}) lowers to an identity", "comm"))
        # -- PS ops ---------------------------------------------------------
        if getattr(node, "is_ps", False):
            if cfg is not None and comm_mode not in ("PS", "Hybrid"):
                out.append(Finding.at(
                    node, "ps-op-without-ps-mode", ERROR,
                    f"{type(node).__name__} requires comm_mode 'PS' or "
                    f"'Hybrid' (got {comm_mode!r}) — without a PS runtime "
                    "the push yields None and the optimizer silently skips "
                    "the parameter forever", "comm"))
            if isinstance(node, ParameterServerCommunicateOp):
                grad_in = node.inputs[0]
                # an explicit EmbeddingLookUpGradient push is a wired
                # route since hetukern ONLY under the executor's rewire
                # conditions (executor._rewire_ps_gradients): ps_id
                # resolves to a sparse-classified param of matching shape,
                # the grad op's sole consumer is this push, and it is not
                # itself an eval target. Anything short of that is still
                # silently dropped — keep warning.
                is_embed_grad = (isinstance(grad_in, FunctionalOp)
                                 and grad_in.opname
                                 == "EmbeddingLookUpGradient"
                                 and _embed_grad_push_wired(
                                     node, grad_in, ctx, consumers))
                if not getattr(grad_in, "is_gradient", False) \
                        and not is_embed_grad:
                    out.append(Finding.at(
                        node, "ps-push-ignored", WARN,
                        f"push input {grad_in.name!r} is not a gradient "
                        "node — the executor only wires gradient pushes, "
                        "this op's traffic is silently dropped", "comm"))
            if isinstance(node, ParameterServerSparsePullOp) \
                    and comm_mode in ("PS", "Hybrid") \
                    and not _is_fed(node.inputs[1]):
                out.append(Finding.at(
                    node, "ps-lookup-index-not-fed", ERROR,
                    f"index input {node.inputs[1].name!r} is not a feed or "
                    "dataloader node — PS row staging needs the indices "
                    "host-side before the step runs", "comm"))
        # PS-resident embedding lookups have the same staging contract
        # (is_embed may be declared, or inferred by the comm-insertion
        # replay and carried in ctx.ps_embed_ids — the replay's attribute
        # marks are rolled back to keep the graph pristine)
        embed = getattr(node, "embed_node", None)
        if embed is not None and comm_mode in ("PS", "Hybrid") \
                and (getattr(embed, "is_embed", False)
                     or id(embed) in getattr(ctx, "ps_embed_ids", ())) \
                and getattr(embed, "trainable", False) \
                and len(node.inputs) > 1 and not _is_fed(node.inputs[1]):
            out.append(Finding.at(
                node, "ps-lookup-index-not-fed", ERROR,
                f"index input {node.inputs[1].name!r} of this PS-hosted "
                "lookup is not a feed or dataloader node — the executor "
                "will reject the graph at build", "comm"))
        # -- dispatch -------------------------------------------------------
        if isinstance(node, DispatchOp):
            if cfg is not None and (
                    mesh is None
                    or mp_axis not in getattr(mesh, "axis_names", ())):
                out.append(Finding.at(
                    node, "dispatch-no-mp-axis", ERROR,
                    f"dispatch marker but no {mp_axis!r} mesh axis exists — "
                    "place the subgraph in a tuple DeviceGroup or pass a "
                    "mesh with a model-parallel axis", "comm"))
            in_shape = ag.shape_of(node.inputs[0])
            if in_shape is not None and len(node.parts) != len(in_shape):
                out.append(Finding.at(
                    node, "dispatch-rank-mismatch", ERROR,
                    f"parts {node.parts} has rank {len(node.parts)} but the "
                    f"input {node.inputs[0].name!r} has rank "
                    f"{len(in_shape)} (shape {in_shape})", "comm"))
        if isinstance(node, DispatchGradientOp) and not has_dispatch:
            out.append(Finding.at(
                node, "dispatch-grad-unpaired", WARN,
                "DispatchGradient without any forward Dispatch marker in "
                "the graph — the gradient passes through unconstrained",
                "comm"))
        # -- pipeline -------------------------------------------------------
        if isinstance(node, PipelineSendOp):
            # consumers in this topo, plus registered receivers living
            # outside it (a validate-target recv still pairs the send —
            # the backlink avoids a false unconsumed warning)
            recvs = [c for c in consumers.get(id(node), [])
                     if isinstance(c, PipelineReceiveOp)]
            recvs += [r for r in getattr(node, "receivers", [])
                      if r not in recvs]
            if not recvs:
                out.append(Finding.at(
                    node, "pipeline-send-unconsumed", WARN,
                    "no paired pipeline_receive_op consumes this send — the "
                    "stage boundary is declared but never crossed", "comm"))
            for r in recvs:
                s_ctx, r_ctx = node.raw_ctx, r.raw_ctx
                # DeviceGroup defines value equality — two `ht.cpu(0)` ctx
                # literals wrap into distinct but equal groups
                if s_ctx is not None and r_ctx is not None \
                        and s_ctx == r_ctx:
                    out.append(Finding.at(
                        r, "pipeline-stage-loop", WARN,
                        f"receive shares the sending stage's device context "
                        f"with {node.name!r} — a stage boundary to the same "
                        "stage is a no-op and usually a mis-scoped "
                        "ht.context block", "comm"))
        if isinstance(node, PipelineReceiveOp) \
                and not isinstance(node.source, PipelineSendOp):
            out.append(Finding.at(
                node, "pipeline-recv-source", NOTE,
                f"source {node.source.name!r} is not a pipeline_send_op — "
                "pairing by producer works, but an explicit send marker "
                "makes the stage cut visible to the partitioner", "comm"))
    return out


# ---------------------------------------------------------------------------
# comm quantization (hetuq, docs/COMM_QUANT.md)
# ---------------------------------------------------------------------------

def comm_quant_pass(ctx) -> list:
    """Quantized-communication placement lints: a forced override that
    quantizes a below-threshold param (the exemption exists to protect
    exactly those biases/norms — a force-listed one is usually a
    misconfiguration), and int8 AllReduce running without error feedback
    (compression error then accumulates in the params over a long run)."""
    out = []
    cfg = ctx.config
    pol = getattr(cfg, "comm_quant_policy", None) if cfg is not None else None
    if pol is None or not getattr(pol, "active", False):
        return out
    ag = ctx.abstract
    noted_ef = False
    for node in ctx.topo:
        if not isinstance(node, AllReduceCommunicateOp):
            continue
        pn = node.param_node
        if pn is None:
            continue
        # param_node is an association, not a graph input — fall back to
        # the placeholder's declared shape when abstract eval never saw it.
        # Unknown size => can't tell whether the policy quantizes this
        # param at all; skip rather than lint speculatively.
        shape = ag.shape_of(pn) or getattr(pn, "shape", None)
        size = int(np.prod(shape)) if shape else None
        if size is None or not pol.applies(pn, size):
            continue
        if size < pol.min_size:
            # applies() said yes on a below-threshold param => force-listed
            out.append(Finding.at(
                node, "comm-quant-forced-small", WARN,
                f"comm_quant force-quantizes {pn.name!r} ({size} elements, "
                f"below the {pol.min_size}-element exemption threshold) — "
                "small/sensitive params (biases, norm scales) are exempt by "
                "design; drop the override unless the wire saving was "
                "measured to matter", "comm_quant"))
        if pol.mode == "int8" and not pol.error_feedback and not noted_ef:
            noted_ef = True
            out.append(Finding.at(
                node, "comm-quant-no-error-feedback", NOTE,
                "int8 AllReduce with error feedback disabled — per-step "
                "quantization error accumulates in the parameters instead "
                "of being carried forward and cancelled; enable "
                "comm_quant_error_feedback unless A/B-verified harmless "
                "(docs/COMM_QUANT.md)", "comm_quant"))
    return out


# ---------------------------------------------------------------------------
# hetukern (docs/KERNELS.md): kernel-tier dispatch lints
# ---------------------------------------------------------------------------

def kernels_pass(ctx) -> list:
    """Kernel-tier placement lints. Under ``kernels="force"`` an
    ineligible shape raises KernelEligibilityError at trace time deep in a
    jit stack — this pass reports the same predicate at define time with
    op-level provenance (``kernels-force-ineligible``, error). Under
    ``auto``, a kernel whose dispatches mostly fell back (>50%) gets a
    note: the tier is configured but not serving (shape misalignment is
    the usual cause)."""
    import jax

    out = []
    cfg = ctx.config
    mode = getattr(cfg, "kernels", None) if cfg is not None else None
    if mode in (None, "off"):
        return out
    from ..kernels import registry as kreg
    ag = ctx.abstract

    def struct(shape, dtype=np.float32):
        return jax.ShapeDtypeStruct(tuple(int(s) for s in shape), dtype)

    if mode == "force":
        # force + a multi-device mesh can never be served: the executor
        # scopes every trace spmd=True and each dispatch raises (HetuConfig
        # rejects this combination at construction; surface the same
        # verdict for AnalysisConfig-driven lints)
        mesh = getattr(cfg, "mesh", None)
        dp = getattr(cfg, "dp_size", 1)
        if (mesh is not None and getattr(mesh, "size", 1) > 1) or dp > 1:
            out.append(Finding.at(
                next(iter(ctx.topo), None), "kernels-force-ineligible",
                ERROR,
                "kernels='force' on a multi-device (GSPMD) program: a "
                "bare pallas_call has no SPMD partitioning rule, so every "
                "kernel dispatch raises at trace time — use kernels="
                "'auto' (docs/KERNELS.md)", "kernels"))
            return out
        for node in ctx.topo:
            # fused embedding grad: flattened (n, dim) row gradients
            if isinstance(node, FunctionalOp) \
                    and node.opname == "EmbeddingLookUpGradient":
                vshape = ag.shape_of(node.inputs[0])
                if not vshape or len(vshape) < 2:
                    continue
                n = int(np.prod(vshape[:-1]))
                # the prep casts grads to f32 unconditionally before the
                # kernel (embed_grad._prep), so the lint mirrors that —
                # dtype can never disqualify this call at runtime
                sv = struct((n, int(vshape[-1])))
                seg = struct((n,), np.int32)
                ok, why = kreg.eligibility_of("fused_embed_grad", sv, seg)
                if not ok:
                    out.append(Finding.at(
                        node, "kernels-force-ineligible", ERROR,
                        f"kernels='force' but the fused_embed_grad kernel "
                        f"cannot take this call: {why}", "kernels"))
            # CSR spmm/spmv: route through the REAL eligibility predicate
            # so the lint cannot drift from the kernel's rules. The dense
            # operand shape/dtype and nrow (the op's output rows) are
            # static; nnz is runtime-fed — a block-aligned stand-in (the
            # predicate does not read it)
            if isinstance(node, FunctionalOp) \
                    and node.opname in ("CSRMatMat", "CSRMatVec") \
                    and len(node.inputs) > 1:
                bshape = ag.shape_of(node.inputs[1])
                bdt = ag.dtype_of(node.inputs[1]) or np.float32
                oshape = ag.shape_of(node)
                if bshape and oshape:
                    kern = ("csr_spmm" if node.opname == "CSRMatMat"
                            else "csr_spmv")
                    nnz = struct((256,), np.int32)
                    if kern == "csr_spmm" and len(bshape) == 2:
                        # trans_B transposes the operand before the kernel
                        # sees it — derive the EFFECTIVE (K, F) from the
                        # op's output (F = oshape[-1]); a square operand
                        # is orientation-agnostic anyway
                        f_eff = int(oshape[-1])
                        k_eff = (int(bshape[0]) if int(bshape[1]) == f_eff
                                 else int(bshape[1]))
                        b_eff = struct((k_eff, f_eff), bdt)
                    else:
                        b_eff = struct(bshape, bdt)
                    ok, why = kreg.eligibility_of(
                        kern, struct((256,)), nnz, nnz, b_eff,
                        nrow=int(oshape[0]))
                    if not ok:
                        out.append(Finding.at(
                            node, "kernels-force-ineligible", ERROR,
                            f"kernels='force' but the {kern} kernel "
                            f"cannot take this call: {why}", "kernels"))
            # fused optimizer apply: every locally-applied trainable param
            if node.is_optimizer:
                opt_name = type(node.optimizer).__name__
                kern = {"AdamOptimizer": "fused_adam",
                        "AdamWOptimizer": "fused_adam",
                        "SGDOptimizer": "fused_sgd"}.get(opt_name)
                if kern is None:
                    continue
                for var in node.vars:
                    shape = ag.shape_of(var) or getattr(var, "shape", None)
                    if not shape:
                        continue
                    p = struct(shape, getattr(var, "dtype", np.float32))
                    args = ((p, p, p, p, struct((), np.float32), 0.01)
                            if kern == "fused_adam" else (p, p, 0.01))
                    ok, why = kreg.eligibility_of(kern, *args)
                    if not ok:
                        out.append(Finding.at(
                            node, "kernels-force-ineligible", ERROR,
                            f"kernels='force' but {kern} cannot apply "
                            f"{var.name!r}: {why}", "kernels"))
    # fallback-ratio note: only meaningful on a TPU backend — off-TPU,
    # auto-mode fallback is the DESIGN (interpret-mode Pallas would be
    # slower), so noting it would spam every CPU test run
    if mode == "auto" and kreg._on_tpu():
        stats = kreg.dispatch_stats()
        kernels = {k for k, _path in stats}
        anchor = next(iter(ctx.topo), None)
        for k in sorted(kernels):
            ratio = kreg.fallback_ratio(k)
            total = (stats.get((k, "pallas"), 0)
                     + stats.get((k, "fallback"), 0))
            if ratio is not None and ratio > 0.5 and total >= 2:
                out.append(Finding.at(
                    anchor, "kernels-auto-fallback", NOTE,
                    f"kernel {k!r}: {ratio:.0%} of {total} auto-mode "
                    "dispatches fell back to XLA — the tier is configured "
                    "but mostly not serving (ineligible shapes or "
                    "partitioned programs). PROCESS-WIDE tallies: every "
                    "executor/trace in this process contributes, not just "
                    "the analyzed graph; hetuprof's dispatch counter shows "
                    "which call sites", "kernels"))
    return out


# ---------------------------------------------------------------------------
# dead subgraphs + common subexpressions
# ---------------------------------------------------------------------------

def dce_pass(ctx) -> list:
    """Dead-subgraph reporting (needs a recorded universe) and
    common-subexpression notes."""
    out = []
    live = {id(n) for n in ctx.topo}
    if ctx.universe:
        dead = [n for n in ctx.universe
                if id(n) not in live and not n.is_placeholder
                and not n.is_dataloader]
        # report only the FRONTIER of each dead cone (dead ops none of whose
        # consumers are also dead) so one abandoned tower = one finding
        consumed_by_dead: set = set()
        for n in dead:
            for i in n.inputs:
                if isinstance(i, Op):
                    consumed_by_dead.add(id(i))
        for n in dead:
            if id(n) not in consumed_by_dead:
                out.append(Finding.at(
                    n, "dead-subgraph", WARN,
                    "constructed but unreachable from every eval target — "
                    f"it will never execute ({len(dead)} dead op(s) total "
                    "in this graph)", "dce"))
    # -- CSE ----------------------------------------------------------------
    seen: dict[tuple, Op] = {}
    for node in ctx.topo:
        if not isinstance(node, FunctionalOp) or node.needs_rng \
                or node.stateful:
            continue
        key = (node.opname, tuple(id(i) for i in node.inputs),
               tuple(sorted((k, repr(v))
                            for k, v in node.export_attrs.items())))
        first = seen.get(key)
        if first is None:
            seen[key] = node
        elif node.export_attrs or not _has_closure_params(node):
            out.append(Finding.at(
                node, "common-subexpression", NOTE,
                f"computes the same value as {first.name!r} (same op, "
                "inputs and attributes) — XLA CSE dedupes it in-program, "
                "but the duplicate build code is usually unintended", "dce"))
    return out


def _has_closure_params(node) -> bool:
    """Ops whose constructors close over parameters we cannot compare
    (no export_attrs): only flag CSE when the fn carries no free variables
    beyond the module globals."""
    fn = getattr(node, "fn", None)
    closure = getattr(fn, "__closure__", None)
    defaults = getattr(fn, "__defaults__", None)
    return bool(closure) or bool(defaults)


TIER_A_PASSES = (structure_pass, shapes_pass, comm_pass, comm_quant_pass,
                 kernels_pass, dce_pass)
