"""The ONE fault-kind registry behind every fault grammar in the stack.

Three parsers used to carry their own copies of the catalogue — the
step-boundary injector (``resilience.FaultInjector``, driving the elastic
``worker_lost``/``ps_join`` transitions too), the message-level chaos
grammar (``chaos.parse_spec``, mirrored bit-for-bit by the C++ parser in
csrc/ps/chaos.h), and the coordinated-snapshot phase grammar
(``recovery.PHASES``). A kind added to one copy but not the others was a
silent no-op in the places that mattered. Now each parser imports its
vocabulary from here and rejects unknown entries with the shared
catalogue message; ``bin/hetucheck`` (docs/ANALYSIS.md, Tier D) asserts
this registry, the three parsers, the C++ chaos grammar and the
docs/FAULT_TOLERANCE.md fault-kind catalogue all agree.

jax-free on purpose: hetucheck imports this under plain CPython in CI.
"""
from __future__ import annotations

# ---------------------------------------------------------------------------
# Step-boundary kinds: HETU_FAULT_SPEC="kind@step[:arg],..." — the
# resilience.FaultInjector grammar. ``arg`` names how the optional
# suffix parses: a number, an op name (nan_op), or a snapshot phase
# (job_kill). Each kind's one-line role mirrors its row in the
# docs/FAULT_TOLERANCE.md "Fault-kind catalogue" table.
STEP_FAULT_KINDS = {
    "nan_grads":     {"arg": "float", "exercises": "anomaly guard"},
    "nan_op":        {"arg": "opname", "exercises": "hetuscope provenance"},
    "stall":         {"arg": "float", "exercises": "hang watchdog"},
    "sigterm":       {"arg": "float", "exercises": "preemption (exit 75)"},
    "sigint":        {"arg": "float", "exercises": "preemption (exit 75)"},
    "crash":         {"arg": "float", "exercises": "supervise() restarts"},
    "ps_kill":       {"arg": "float",
                      "exercises": "PS snapshot/respawn/failover"},
    "quant_corrupt": {"arg": "float",
                      "exercises": "server payload validation"},
    "worker_lost":   {"arg": "float", "exercises": "elastic scale-down"},
    "ps_join":       {"arg": "float", "exercises": "live key-range migration"},
    "ps_slow":       {"arg": "float", "exercises": "hetutrail attribution"},
    "plan_flap":     {"arg": "float",
                      "exercises": "hetupilot anti-oscillation governor"},
    "ps_partition":  {"arg": "float", "exercises": "retry-with-backoff"},
    "job_kill":      {"arg": "phase", "exercises": "hetusave epochs"},
}
STEP_FAULT_NAMES = tuple(STEP_FAULT_KINDS)

# Coordinated-snapshot crash phases (recovery.take_job_snapshot): the
# job_kill arg vocabulary, in snapshot-protocol order.
JOB_KILL_PHASES = ("pre_barrier", "server_write", "pre_commit",
                   "post_commit")

# ---------------------------------------------------------------------------
# Message-level chaos grammar: HETU_CHAOS_SPEC="key=value,..." — the
# chaos.parse_spec grammar, mirrored by hetups::ChaosEngine::parse in
# csrc/ps/chaos.h (the round-trip test pins the two parsers together).
CHAOS_PROB_KEYS = ("drop", "droprsp", "dup", "corrupt")
CHAOS_SPEC_KEYS = {
    "seed": "U64", "drop": "P", "droprsp": "P", "dup": "P", "corrupt": "P",
    "delay": "P[:MAX_MS]", "reorder": "P[:MAX_MS]",
    "partition": "SERVER:FROM:COUNT",
}

CATALOGUE_DOC = "docs/FAULT_TOLERANCE.md"


def chaos_catalogue() -> str:
    """The known-kinds line chaos.parse_spec rejects with."""
    return ("seed, drop, droprsp, dup, corrupt, delay[:ms], reorder[:ms], "
            f"partition=SERVER:FROM:COUNT ({CATALOGUE_DOC})")


def parse_step_entry(part: str) -> dict:
    """Parse one ``kind@step[:arg]`` entry against the registry, rejecting
    unknown kinds (and invalid job_kill phases) with the catalogue. Returns
    ``{"kind", "step", "arg"}``."""
    kind, sep, rest = part.partition("@")
    kind = kind.strip()
    if not sep or kind not in STEP_FAULT_KINDS:
        raise ValueError(
            f"bad fault entry {part!r}: expected kind@step[:arg] with "
            f"kind in {STEP_FAULT_NAMES} — see the fault-kind catalogue in "
            f"{CATALOGUE_DOC}")
    step_s, _, arg_s = rest.partition(":")
    arg = None
    if arg_s:
        arg_form = STEP_FAULT_KINDS[kind]["arg"]
        if arg_form == "phase":
            if arg_s not in JOB_KILL_PHASES:
                raise ValueError(
                    f"bad fault entry {part!r}: job_kill phase {arg_s!r} "
                    f"not in {JOB_KILL_PHASES}")
            arg = arg_s
        elif arg_form == "opname":
            arg = arg_s
        else:
            arg = float(arg_s)
    return {"kind": kind, "step": int(step_s), "arg": arg}
