"""Graph-level autodiff: ``ht.gradients(loss, node_list)``.

Reference: ``gpu_ops/executor.py:1096`` builds the gradient graph by calling
each op's symbolic ``gradient`` in reverse topo order. The TPU-native design
instead defers to ``jax.vjp`` *at trace time*: a ``GradientOp`` node is a
placeholder whose value is produced by differentiating the traced forward
subgraph. This gives exact gradients for every op (including fused Pallas
kernels with custom_vjp) with zero per-op gradient code, and XLA's CSE removes
the duplicated forward trace.

The returned nodes behave exactly like reference gradient nodes: they can be
evaluated by the executor, wrapped in AllReduce/PS communication ops by the
optimizer, or composed into further graph computation.
"""
from __future__ import annotations

from typing import Sequence

from .node import Op


class GradientContext:
    """Shared bookkeeping for one ``gradients(loss, xs)`` call."""

    def __init__(self, loss: Op, xs: list[Op]):
        self.loss = loss
        self.xs = xs

    def downstream_nodes(self, topo: Sequence[Op]) -> list[Op]:
        """Nodes in ``topo`` reachable from ``xs`` (forward direction) — the
        sub-graph that must be re-traced inside the vjp closure."""
        reachable = set(id(x) for x in self.xs)
        out = []
        for node in topo:
            if id(node) in reachable:
                continue
            if any(id(i) in reachable for i in node.inputs):
                reachable.add(id(node))
                out.append(node)
        return out


class GradientOp(Op):
    """d(loss)/d(x) for one x. Inputs = [loss, x] so topo ordering places the
    full forward graph before the gradient is needed.

    ``multi_x``: when the executor rewires a PS-table gradient onto SEVERAL
    lookup outputs (one shared table feeding k lookup ops, the reference's
    IndexedSlices accumulation — optimizer.py:64-82), the node produces a
    TUPLE of per-lookup row gradients instead of one array; the PS push path
    concatenates and dedup-sums them host-side."""

    is_gradient = True

    def __init__(self, gctx: GradientContext, x: Op):
        super().__init__([gctx.loss, x], ctx=x.raw_ctx)
        self.gctx = gctx
        self.x = x
        self.multi_x = None
        self.name = f"Gradient({x.name})"

    def compute(self, input_vals, tc):
        if self.multi_x is not None:
            return tuple(tc.gradient_of(self.gctx, x) for x in self.multi_x)
        return tc.gradient_of(self.gctx, self.x)


def gradients(loss: Op, node_list: Sequence[Op], insert_grad=None) -> list[Op]:
    """Return gradient nodes of ``loss`` w.r.t. each node in ``node_list``
    (reference executor.py:1096 signature)."""
    gctx = GradientContext(loss, list(node_list))
    return [GradientOp(gctx, x) for x in node_list]
