"""Shape/layout ops: reshape, transpose, slice, split, concat, pad, broadcast,
reductions, one-hot.

Replaces the reference's Reshape/Transpose/Slice/Split/Concat/Pad/Broadcast/
BroadcastShape/ReduceSum/ReduceMean/ReduceSumAxisZero/OneHot CUDA kernels
(``src/ops``). All of these are pure data-movement in XLA and usually fuse
away entirely (the reference's lazy no-copy reshape/broadcast trick,
ndarray.py:290-356, is XLA's default behavior).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..node import FunctionalOp


def array_reshape_op(node, output_shape, ctx=None):
    op = FunctionalOp("ArrayReshape",
                      lambda x, s=tuple(output_shape): jnp.reshape(x, s),
                      [node], ctx)
    op.export_attrs = {"output_shape": tuple(int(s) for s in output_shape)}
    return op


def array_reshape_gradient_op(node_in, node_out, ctx=None):
    """Reshape grad back to the forward input's shape."""
    return FunctionalOp("ArrayReshapeGradient",
                        lambda x_in, g: jnp.reshape(g, x_in.shape),
                        [node_in, node_out], ctx)


def transpose_op(node, perm=None, ctx=None):
    op = FunctionalOp("Transpose", lambda x, p=perm: jnp.transpose(x, p), [node], ctx)
    op.export_attrs = {"perm": None if perm is None else tuple(int(p) for p in perm)}
    return op


def slice_op(node, begin, size, ctx=None):
    begin = tuple(int(b) for b in begin)
    size = tuple(int(s) for s in size)

    def _slice(x):
        sz = tuple(x.shape[i] - begin[i] if size[i] == -1 else size[i]
                   for i in range(len(size)))
        return jax.lax.dynamic_slice(x, begin, sz)

    op = FunctionalOp("Slice", _slice, [node], ctx)
    op.export_attrs = {"begin": begin, "size": size}
    return op


def slice_gradient_op(node, begin, size=None, ctx=None):
    """Scatter the sliced grad back into zeros of the forward-input shape.

    ``size`` here is the forward input's full shape (the reference recovers it
    from the paired forward op at placement time, Slice.py).
    """
    begin = tuple(int(b) for b in begin)
    out_shape = None if size is None else tuple(int(s) for s in size)

    def _grad(g):
        assert out_shape is not None, "slice_gradient_op needs the input shape"
        out = jnp.zeros(out_shape, dtype=g.dtype)
        return jax.lax.dynamic_update_slice(out, g, begin)

    return FunctionalOp("SliceGradient", _grad, [node], ctx)


def split_op(node, axes, indices, splits, ctx=None):
    """Take partition ``indices[k]`` of ``splits[k]`` along each ``axes[k]``
    (reference Split.py — multi-axis block split used by model parallelism)."""
    axes = [int(a) for a in np.atleast_1d(axes)]
    indices = [int(i) for i in np.atleast_1d(indices)]
    splits = [int(s) for s in np.atleast_1d(splits)]

    def _split(x):
        out = x
        for ax, idx, sp in zip(axes, indices, splits):
            dim = out.shape[ax]
            assert dim % sp == 0, f"axis {ax} size {dim} not divisible by {sp}"
            part = dim // sp
            out = jax.lax.slice_in_dim(out, idx * part, (idx + 1) * part, axis=ax)
        return out

    return FunctionalOp("Split", _split, [node], ctx)


def split_gradient_op(node, axes, indices, splits, ctx=None):
    axes = [int(a) for a in np.atleast_1d(axes)]
    indices = [int(i) for i in np.atleast_1d(indices)]
    splits = [int(s) for s in np.atleast_1d(splits)]

    def _grad(g):
        shape = list(g.shape)
        starts = [0] * g.ndim
        for ax, idx, sp in zip(axes, indices, splits):
            shape[ax] = g.shape[ax] * sp
            starts[ax] = idx * g.shape[ax]
        out = jnp.zeros(tuple(shape), dtype=g.dtype)
        return jax.lax.dynamic_update_slice(out, g, tuple(starts))

    return FunctionalOp("SplitGradient", _grad, [node], ctx)


def concat_op(node_A, node_B, axis=0, ctx=None):
    op = FunctionalOp("Concat",
                      lambda a, b, ax=axis: jnp.concatenate([a, b], axis=ax),
                      [node_A, node_B], ctx)
    op.export_attrs = {"axis": int(axis)}
    return op


def concat_gradient_op(grad_node, input_node, axis, idx, ctx=None):
    """Slice the grad chunk belonging to input ``idx`` (0 or 1) back out."""

    def _grad(g, x_in, ax=int(axis), which=int(idx)):
        size = x_in.shape[ax]
        start = 0 if which == 0 else g.shape[ax] - size
        return jax.lax.slice_in_dim(g, start, start + size, axis=ax)

    return FunctionalOp("ConcatGradient", _grad, [grad_node, input_node], ctx)


def pad_op(node, paddings, mode="CONSTANT", constant_values=0, ctx=None):
    pads = [tuple(int(v) for v in p) for p in paddings]
    assert mode.upper() == "CONSTANT", "only CONSTANT pad supported (as reference)"

    def _pad(x):
        full = [(0, 0)] * (x.ndim - len(pads)) + pads
        return jnp.pad(x, full, constant_values=constant_values)

    op = FunctionalOp("Pad", _pad, [node], ctx)
    op.export_attrs = {"paddings": pads, "constant_values": constant_values}
    return op


def pad_gradient_op(node, paddings, mode="CONSTANT", ctx=None):
    pads = [tuple(int(v) for v in p) for p in paddings]

    def _grad(g):
        full = [(0, 0)] * (g.ndim - len(pads)) + pads
        idx = tuple(slice(lo, g.shape[i] - hi) for i, (lo, hi) in enumerate(full))
        return g[idx]

    return FunctionalOp("PadGradient", _grad, [node], ctx)


def broadcastto_op(node_A, node_B, ctx=None):
    """Broadcast A to B's shape with numpy trailing-dim alignment
    (reference Broadcast.py)."""

    def _bc(a, b):
        return jnp.broadcast_to(a, b.shape)

    return FunctionalOp("BroadcastTo", _bc, [node_A, node_B], ctx)


def broadcast_shape_op(node, shape, add_axes=(), ctx=None):
    shape = tuple(int(s) for s in shape)
    add_axes = tuple(int(a) for a in add_axes)

    def _bc(x):
        y = x
        for ax in sorted(add_axes):
            y = jnp.expand_dims(y, ax)
        return jnp.broadcast_to(y, shape)

    op = FunctionalOp("BroadcastShape", _bc, [node], ctx)
    op.export_attrs = {"shape": shape, "add_axes": add_axes}
    return op


def reduce_sum_op(node, axes, keepdims=False, ctx=None):
    axes = tuple(int(a) for a in np.atleast_1d(axes))
    op = FunctionalOp("ReduceSum",
                      lambda x: jnp.sum(x, axis=axes, keepdims=keepdims),
                      [node], ctx)
    op.export_attrs = {"axes": axes, "keepdims": bool(keepdims)}
    return op


def reduce_mean_op(node, axes, keepdims=False, ctx=None):
    axes = tuple(int(a) for a in np.atleast_1d(axes))
    op = FunctionalOp("ReduceMean",
                      lambda x: jnp.mean(x, axis=axes, keepdims=keepdims),
                      [node], ctx)
    op.export_attrs = {"axes": axes, "keepdims": bool(keepdims)}
    return op


def reducesumaxiszero_op(node, ctx=None):
    return FunctionalOp("ReduceSumAxisZero", lambda x: jnp.sum(x, axis=0), [node], ctx)


def one_hot_op(node, num_classes, ctx=None):
    op = FunctionalOp("OneHot",
                      lambda x, n=int(num_classes): jax.nn.one_hot(
                          x.astype(jnp.int32), n, dtype=jnp.float32),
                      [node], ctx)
    op.export_attrs = {"num_classes": int(num_classes)}
    return op
