"""Graph-level operator library (reference ``gpu_ops/__init__.py`` registry).

Every public ``*_op`` constructor from the reference is re-exported here so
reference model code imports unchanged.
"""
from .arith import (
    add_op, addbyconst_op, mul_op, mul_byconst_op, div_op, div_const_op,
    opposite_op, sqrt_op, rsqrt_op, oneslike_op, zeroslike_op, where_op,
    relu_op, relu_gradient_op, leaky_relu_op, leaky_relu_gradient_op,
    sigmoid_op, tanh_op, gelu_op, exp_op, log_op,
    softmax_func, softmax_op, softmax_gradient_op,
)
from .shape import (
    array_reshape_op, array_reshape_gradient_op, transpose_op,
    slice_op, slice_gradient_op, split_op, split_gradient_op,
    concat_op, concat_gradient_op, pad_op, pad_gradient_op,
    broadcastto_op, broadcast_shape_op,
    reduce_sum_op, reduce_mean_op, reducesumaxiszero_op, one_hot_op,
)
from .matmul import (
    matmul_op, batch_matmul_op, matrix_dot_op, csrmv_op, csrmm_op,
)
from .gnn import distgcn_15d_op
from .conv import (
    conv2d_op, conv2d_gradient_of_data_op, conv2d_gradient_of_filter_op,
    conv2d_broadcastto_op, conv2d_reducesum_op,
    max_pool2d_op, max_pool2d_gradient_op, avg_pool2d_op, avg_pool2d_gradient_op,
)
from .norm import (
    batch_normalization_op, layer_normalization_op, instance_normalization2d_op,
    BatchNormOp,
)
from .dropout import (
    dropout_op, dropout_gradient_op, dropout2d_op, dropout2d_gradient_op,
)
from .losses import (
    softmaxcrossentropy_op, softmaxcrossentropy_gradient_op,
    binarycrossentropy_op, binarycrossentropy_gradient_op,
)
from .embedding import (
    embedding_lookup_op, embedding_lookup_gradient_op, IndexedRows,
)
from .comm import (
    allreduceCommunicate_op, groupallreduceCommunicate_op,
    datah2d_op, datad2h_op,
    pipeline_send_op, pipeline_receive_op,
    dispatch, dispatch_gradient, DispatchOp,
    AllReduceCommunicateOp, GroupAllReduceCommunicateOp,
    PipelineSendOp, PipelineReceiveOp,
)
from .ps import (
    parameterServerCommunicate_op, parameterServerSparsePull_op,
    ParameterServerCommunicateOp, ParameterServerSparsePullOp,
)
from ..node import Variable, placeholder_op, Op, PlaceholderOp, find_topo_sort

# star-export only the op API, not the submodules themselves (the `ps`
# submodule would otherwise shadow the top-level hetu_tpu.ps package)
import types as _types

__all__ = [_k for _k, _v in list(globals().items())
           if not _k.startswith("_") and not isinstance(_v, _types.ModuleType)]
