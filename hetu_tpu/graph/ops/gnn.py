"""Graph-neural-network ops: the DistGCN 1.5D hybrid-parallel GCN matmul
(reference ``gpu_ops/DistGCN_15d.py``).

API parity wrapper: ``distgcn_15d_op(A, H, W, ...)`` computes
``Z = A @ H (@ W)``. The reference implements the 1.5D schedule imperatively
(staged NCCL broadcasts + csrmm accumulation + row-group allreduce) inside the
op's ``compute``; here the op is a pure sparse-matmul composition — on a
device mesh the 1.5D data movement lives in
:mod:`hetu_tpu.parallel.distgcn` (``shard_map`` all_gather/psum over a
``(gr, gc)`` mesh), which XLA lowers to the same collectives.
"""
from __future__ import annotations

from .matmul import csrmm_op, matmul_op


def distgcn_15d_op(node_A, node_B, node_C=None, node_Count_Self=None,
                   node_Count_All=None, size=1, replication=1, device_id=0,
                   comm=None, comm_groups=None, need_W=True, ctx=None):
    """``A`` sparse adjacency (fed as ND_Sparse_Array), ``B`` features,
    ``C`` weight. The process-topology arguments of the reference signature
    (size/replication/device_id/comm/comm_groups) are accepted for API
    compatibility; distribution is declared via the mesh, not per-op."""
    z = csrmm_op(node_A, node_B, ctx=ctx)
    if need_W and node_C is not None:
        z = matmul_op(z, node_C, ctx=ctx)
    return z
