"""Elementwise arithmetic + activation ops.

Covers the reference's AddElewise/AddConst/MultiplyElewise/MultiplyConst/
Division/Opposite/Sqrt/OnesLike/ZerosLike/Where/Relu/LeakyRelu/Sigmoid/Tanh/
Softmax CUDA kernels (``src/ops/*.cu``) as jax compositions — XLA fuses these
into surrounding matmuls/reductions on the VPU, so no hand-written kernels are
needed at this level.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..node import FunctionalOp, Op


def add_op(node_A, node_B, ctx=None):
    return FunctionalOp("AddElewise", jnp.add, [node_A, node_B], ctx)


def addbyconst_op(node, const_val, ctx=None):
    op = FunctionalOp("AddConst", lambda x, c=const_val: x + c, [node], ctx)
    op.export_attrs = {"const_val": const_val}
    return op


def mul_op(node_A, node_B, ctx=None):
    return FunctionalOp("MultiplyElewise", jnp.multiply, [node_A, node_B], ctx)


def mul_byconst_op(node, const_val, ctx=None):
    op = FunctionalOp("MultiplyConst", lambda x, c=const_val: x * c, [node], ctx)
    op.export_attrs = {"const_val": const_val}
    return op


def div_op(node_A, node_B, ctx=None):
    return FunctionalOp("Division", jnp.divide, [node_A, node_B], ctx)


def div_const_op(const_val, node_A, ctx=None):
    op = FunctionalOp("DivConst", lambda x, c=const_val: c / x, [node_A], ctx)
    op.export_attrs = {"const_val": const_val}
    return op


def opposite_op(node, ctx=None):
    return FunctionalOp("Opposite", jnp.negative, [node], ctx)


def sqrt_op(node, ctx=None):
    return FunctionalOp("Sqrt", jnp.sqrt, [node], ctx)


def rsqrt_op(node, ctx=None):
    return FunctionalOp("ReciprocalSqrt", jax.lax.rsqrt, [node], ctx)


def oneslike_op(node, ctx=None):
    return FunctionalOp("OnesLike", jnp.ones_like, [node], ctx)


def zeroslike_op(node, ctx=None):
    return FunctionalOp("ZerosLike", jnp.zeros_like, [node], ctx)


def where_op(cond, node_A, node_B, ctx=None):
    return FunctionalOp("Where", lambda c, a, b: jnp.where(c != 0, a, b),
                        [cond, node_A, node_B], ctx)


# ---------------------------------------------------------------------------
# activations
# ---------------------------------------------------------------------------

def relu_op(node, ctx=None):
    return FunctionalOp("Relu", lambda x: jnp.maximum(x, 0), [node], ctx)


def relu_gradient_op(node, grad_node, ctx=None):
    """dL/dx for relu given forward input (reference Relu.py ReluGradientOp)."""
    return FunctionalOp("ReluGradient", lambda x, g: jnp.where(x > 0, g, 0.0),
                        [node, grad_node], ctx)


def leaky_relu_op(node, alpha, ctx=None):
    op = FunctionalOp("LeakyRelu", lambda x, a=alpha: jnp.where(x > 0, x, a * x),
                      [node], ctx)
    op.export_attrs = {"alpha": float(alpha)}
    return op


def leaky_relu_gradient_op(node_A, node_B, alpha, ctx=None):
    return FunctionalOp("LeakyReluGradient",
                        lambda x, g, a=alpha: jnp.where(x > 0, g, a * g),
                        [node_A, node_B], ctx)


def sigmoid_op(node, ctx=None):
    return FunctionalOp("Sigmoid", jax.nn.sigmoid, [node], ctx)


def tanh_op(node, ctx=None):
    return FunctionalOp("Tanh", jnp.tanh, [node], ctx)


def gelu_op(node, ctx=None):
    return FunctionalOp("Gelu", jax.nn.gelu, [node], ctx)


def exp_op(node, ctx=None):
    return FunctionalOp("Exp", jnp.exp, [node], ctx)


def log_op(node, ctx=None):
    return FunctionalOp("Log", jnp.log, [node], ctx)


def softmax_func(y):
    """Numerically-stable softmax over the last axis (reference Softmax.py)."""
    return jax.nn.softmax(y, axis=-1)


def softmax_op(node, ctx=None):
    return FunctionalOp("Softmax", softmax_func, [node], ctx)


def softmax_gradient_op(node_y, grad, ctx=None):
    """Backward of softmax given forward *output* y (reference SoftmaxGradient)."""

    def _grad(y, dy):
        return y * (dy - jnp.sum(dy * y, axis=-1, keepdims=True))

    return FunctionalOp("SoftmaxGradient", _grad, [node_y, grad], ctx)
