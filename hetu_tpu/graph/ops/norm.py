"""Normalization ops: BatchNorm (stateful running stats), LayerNorm,
InstanceNorm2d.

Replaces the reference's hand-written reduction kernels
(``src/ops/LayerNorm.cu`` — a 387-line two-pass reduction — ``BatchNorm.cu``,
``InstanceNorm2d.cu``, and their cuDNN variants). On TPU these are small jnp
reductions that XLA fuses into one pass; the BatchNorm running-mean/var state
is threaded functionally by the executor (reference keeps it as hidden mutable
arrays inside the op, BatchNorm.py).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..node import FunctionalOp, Op


class BatchNormOp(Op):
    """Batch normalization over (N, C, H, W) with per-channel scale/bias.

    Reference gpu_ops/BatchNorm.py: inputs (x, scale, bias); running stats are
    op state, updated only in training.
    """

    stateful = True

    def __init__(self, node_in, bn_scale, bn_bias, momentum=0.99, eps=0.01, ctx=None):
        super().__init__([node_in, bn_scale, bn_bias], ctx)
        self.momentum = float(momentum)
        self.eps = float(eps)

    def state_init(self):
        shape = getattr(self.inputs[1], "shape", None)
        assert shape is not None, "BatchNorm scale must be a Variable with known shape"
        c = int(np.prod(shape))
        return {"mean": np.zeros((c,), np.float32), "var": np.ones((c,), np.float32)}

    def compute_stateful(self, input_vals, state, tc):
        x, scale, bias = input_vals
        scale = scale.reshape((1, -1) + (1,) * (x.ndim - 2))
        bias = bias.reshape((1, -1) + (1,) * (x.ndim - 2))
        axes = (0,) + tuple(range(2, x.ndim))
        if tc.training:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            m = self.momentum
            new_state = {
                "mean": m * state["mean"] + (1.0 - m) * mean,
                "var": m * state["var"] + (1.0 - m) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        shape = (1, -1) + (1,) * (x.ndim - 2)
        norm = (x - mean.reshape(shape)) / jnp.sqrt(var.reshape(shape) + self.eps)
        return norm * scale + bias, new_state


def batch_normalization_op(node_in, bn_scale, bn_bias, momentum=0.99, eps=0.01, ctx=None):
    return BatchNormOp(node_in, bn_scale, bn_bias, momentum, eps, ctx)


def _ln(x, scale, bias, eps):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * scale + bias


def layer_normalization_op(node_in, ln_scale, ln_bias, eps=0.01, ctx=None):
    return FunctionalOp("LayerNorm", lambda x, s, b, e=float(eps): _ln(x, s, b, e),
                        [node_in, ln_scale, ln_bias], ctx)


def instance_normalization2d_op(node_in, eps=0.01, ctx=None):
    def _in2d(x, e=float(eps)):
        mean = jnp.mean(x, axis=(2, 3), keepdims=True)
        var = jnp.var(x, axis=(2, 3), keepdims=True)
        return (x - mean) / jnp.sqrt(var + e)

    return FunctionalOp("InstanceNorm2d", _in2d, [node_in], ctx)
