"""Dropout / Dropout2d — RNG-consuming ops.

Replaces the reference's curand mask kernels (``src/ops/Dropout.cu``,
``Dropout2d.cu``). RNG is functional: the executor folds a per-step PRNGKey
with the node id (``tc.next_rng``), so repeated traces are deterministic and
the reference's hidden mask buffers (DropoutOp keeps the mask for the
backward pass) are unnecessary — autodiff differentiates through the mask.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..node import Op


class DropoutOp(Op):
    needs_rng = True

    def __init__(self, node_in, keep_prob, ctx=None, channelwise=False):
        super().__init__([node_in], ctx)
        self.keep_prob = float(keep_prob)
        self.channelwise = channelwise

    def compute(self, input_vals, tc):
        (x,) = input_vals
        if not tc.training or self.keep_prob >= 1.0:
            return x
        rng = tc.next_rng(self)
        if self.channelwise:
            mask_shape = x.shape[:2] + (1,) * (x.ndim - 2)
        else:
            mask_shape = x.shape
        mask = jax.random.bernoulli(rng, self.keep_prob, mask_shape)
        return jnp.where(mask, x / self.keep_prob, 0.0)


def dropout_op(node_in, keep_prob, ctx=None):
    return DropoutOp(node_in, keep_prob, ctx)


def dropout2d_op(node_in, keep_prob, ctx=None):
    """Drops whole channels of an (N, C, H, W) tensor (reference Dropout2d)."""
    return DropoutOp(node_in, keep_prob, ctx, channelwise=True)


class DropoutGradientOp(Op):
    """API-parity gradient op: regenerates the forward mask from the paired
    forward node's RNG and applies it to the incoming grad."""

    needs_rng = True

    def __init__(self, node_in, keep_prob, forward_node, ctx=None, channelwise=False):
        super().__init__([node_in], ctx)
        self.keep_prob = float(keep_prob)
        self.forward_node = forward_node
        self.channelwise = channelwise

    def compute(self, input_vals, tc):
        (g,) = input_vals
        if not tc.training or self.keep_prob >= 1.0:
            return g
        rng = tc.next_rng(self.forward_node)
        if self.channelwise:
            mask_shape = g.shape[:2] + (1,) * (g.ndim - 2)
        else:
            mask_shape = g.shape
        mask = jax.random.bernoulli(rng, self.keep_prob, mask_shape)
        return jnp.where(mask, g / self.keep_prob, 0.0)


def dropout_gradient_op(node_in, keep_prob, forward_node, ctx=None):
    return DropoutGradientOp(node_in, keep_prob, forward_node, ctx)


def dropout2d_gradient_op(node_in, keep_prob, forward_node, ctx=None):
    return DropoutGradientOp(node_in, keep_prob, forward_node, ctx, channelwise=True)
