"""Parameter-server communication ops (graph-level markers).

Reference: ``gpu_ops/ParameterServerCommunicate.py`` — push/pull of grads and
params to the ps-lite server, with an ASP/BSP x prefetch x dense/sparse/cache
strategy matrix. In the TPU build the server is ``hetu_tpu.ps`` (host-resident
C++ KV store); these ops bridge the jitted step to the host client via
``jax.experimental.io_callback`` at the step boundary — the executor splits
PS traffic out of the XLA program the same way the reference routes it to the
d2h stream.
"""
from __future__ import annotations

from ..node import Op


class ParameterServerCommunicateOp(Op):
    """Push a gradient to the PS (and pull back the fresh parameter)."""

    is_ps = True

    def __init__(self, node, ps_id=None, optimizer=None, ctx=None):
        super().__init__([node], ctx)
        self.ps_id = ps_id
        self.optimizer = optimizer
        # filled by the executor's PS wiring (declared here so graph-level
        # introspection — hetulint, graphboard — sees stable attributes):
        # the PS-hosted parameter this push serves, and for sparse tables
        # the lookup op(s) whose row gradients are concatenated host-side
        self.ps_param_node = None
        self.staged_lookups = None

    def compute(self, input_vals, tc):
        return tc.ps_push_pull(self, input_vals[0])


def parameterServerCommunicate_op(node, ps_id=None, optimizer=None, ctx=None):
    return ParameterServerCommunicateOp(node, ps_id, optimizer, ctx)


class ParameterServerSparsePullOp(Op):
    """Inference-time sparse pull of embedding rows (reference :236)."""

    is_ps = True

    def __init__(self, node_embed, node_index, ctx=None):
        super().__init__([node_embed, node_index], ctx)
        self.embed_node = node_embed  # staged like embedding_lookup_op

    def compute(self, input_vals, tc):
        return tc.ps_sparse_pull(self, input_vals)


def parameterServerSparsePull_op(node_embed, node_index, ctx=None):
    return ParameterServerSparsePullOp(node_embed, node_index, ctx)
