"""Matrix-multiply family: matmul, batched matmul, tensordot, CSR sparse.

Replaces the reference's cuBLAS-backed MatrixMult/BatchMatrixMult
(``src/ops/MatrixMult.cu``) and cuSPARSE csrmv/csrmm (``src/ops/CuSparse.cu``).
Dense matmuls are ``jnp.dot`` in bf16-accumulate-f32 — they land directly on
the MXU. The CSR products are expressed as gather + segment-sum, which XLA
lowers to sorted-scatter; rows ride the VPU, which is the right trade on TPU
where true sparse units don't exist.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..node import FunctionalOp, Op


def matmul_op(node_A, node_B, trans_A=False, trans_B=False, ctx=None):
    def _mm(a, b, ta=trans_A, tb=trans_B):
        if ta:
            a = a.T
        if tb:
            b = b.T
        return jnp.dot(a, b, preferred_element_type=jnp.float32)

    op = FunctionalOp("MatMul", _mm, [node_A, node_B], ctx)
    op.export_attrs = {"trans_A": bool(trans_A), "trans_B": bool(trans_B)}
    return op


def batch_matmul_op(node_A, node_B, trans_A=False, trans_B=False, ctx=None):
    def _bmm(a, b, ta=trans_A, tb=trans_B):
        if ta:
            a = jnp.swapaxes(a, -1, -2)
        if tb:
            b = jnp.swapaxes(b, -1, -2)
        return jnp.matmul(a, b, preferred_element_type=jnp.float32)

    op = FunctionalOp("BatchMatMul", _bmm, [node_A, node_B], ctx)
    op.export_attrs = {"trans_A": bool(trans_A), "trans_B": bool(trans_B)}
    return op


def matrix_dot_op(node_A, node_B, axes=0, ctx=None):
    """Elementwise multiply (reference MatrixDot.py — despite the name, its
    kernel is an elementwise product; kept for API parity)."""
    return FunctionalOp("MatrixDot", jnp.multiply, [node_A, node_B], ctx)


# ---------------------------------------------------------------------------
# CSR sparse products. The sparse operand is fed as a ``ND_Sparse_Array``
# (COO rows/cols + values); at trace time it arrives as three arrays.
# hetukern (docs/KERNELS.md): both products route through the ``csr_spmm``
# kernel-registry entry — the blocked rows-into-VMEM segment-MAC kernel on
# TPU (or forced), the gather + segment_sum expression below otherwise
# (``kernels="off"`` serves it verbatim, bit-identical to pre-hetukern).
# ---------------------------------------------------------------------------

class SparseInputOp(Op):
    """Adapter node whose runtime value is the (values, rows, cols, nrow, ncol)
    tuple of a fed ND_Sparse_Array."""

    is_placeholder = True

    def __init__(self, name=None, ctx=None):
        super().__init__([], ctx, name or "SparseInput")
        self.trainable = False
        self.is_feed = True


def _coo_matvec(values, rows, cols, nrow, x):
    from ...kernels import csr_spmm
    return csr_spmm.coo_matvec(values, rows, cols, nrow, x)


def _coo_matmat(values, rows, cols, nrow, B):
    from ...kernels import csr_spmm
    return csr_spmm.coo_matmat(values, rows, cols, nrow, B)


def csrmv_op(node_A, node_B, trans=False, ctx=None):
    """Sparse(A) @ dense-vector(B); ``trans`` multiplies by Aᵀ."""

    def _mv(a, x, t=trans):
        values, rows, cols, nrow, ncol = a
        if t:
            rows, cols, nrow = cols, rows, ncol
        return _coo_matvec(values, rows, cols, nrow, x)

    return FunctionalOp("CSRMatVec", _mv, [node_A, node_B], ctx)


def csrmm_op(node_A, node_B, trans_A=False, trans_B=False, ctx=None):
    """Sparse(A) @ dense-matrix(B)."""

    def _mm(a, B, ta=trans_A, tb=trans_B):
        values, rows, cols, nrow, ncol = a
        if tb:
            B = B.T
        if ta:
            rows, cols, nrow = cols, rows, ncol
        return _coo_matmat(values, rows, cols, nrow, B)

    return FunctionalOp("CSRMatMat", _mm, [node_A, node_B], ctx)
