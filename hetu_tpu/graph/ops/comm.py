"""Communication / placement ops: AllReduce, group AllReduce, host<->device
transfer markers, pipeline send/recv, and the ``dispatch`` tensor-parallel
marker.

The reference backs these with MPI+NCCL (``src/communication/
mpi_nccl_communication.cu``) driven per-op on dedicated streams. On TPU the
collectives are *compiled into the XLA program*: an AllReduce node lowers to a
sharding constraint (GSPMD inserts the psum over ICI), pipeline send/recv
lower to stage boundaries handled by the pipeline executor, and ``dispatch``
lowers to a PartitionSpec constraint. None of these move bytes from Python.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..node import Op, FunctionalOp


class AllReduceCommunicateOp(Op):
    """Gradient all-reduce marker (reference AllReduceCommunicate.py:8).

    Under GSPMD data parallelism the psum is inserted by XLA when the
    (batch-sharded) gradient meets the (replicated) parameter update; this op
    pins that contract with an explicit replication constraint. With tensor
    parallelism the target parameter may itself be sharded over the model
    axis, so the constraint is the parameter's own spec (reduce over dp,
    stay split over tp) — ``param_node`` carries that association.
    """

    # hetuq (docs/COMM_QUANT.md): the Executor flips this on per op when the
    # comm_quant policy quantizes the target parameter's gradient sync —
    # TraceContext.allreduce then lowers the marker as reduce-scatter(f32)
    # -> blockwise quantize -> all-gather(int8/fp8) -> dequantize
    comm_quant = False

    def __init__(self, node, comm=None, ctx=None, param_node=None):
        super().__init__([node], ctx)
        self.comm = comm
        self.param_node = param_node

    def compute(self, input_vals, tc):
        return tc.allreduce(input_vals[0], self.param_node, op=self)


def allreduceCommunicate_op(node, comm=None, ctx=None, param_node=None):
    return AllReduceCommunicateOp(node, comm, ctx, param_node)


class GroupAllReduceCommunicateOp(AllReduceCommunicateOp):
    """Sub-group allreduce used by pipeline+DP (reference :73). The group is a
    mesh-axis subset; under GSPMD it reduces over the 'dp' axis only."""

    def __init__(self, node, group=None, ctx=None):
        super().__init__(node, None, ctx)
        self.group = group


def groupallreduceCommunicate_op(node, group=None, ctx=None):
    return GroupAllReduceCommunicateOp(node, group, ctx)


def datah2d_op(node, ctx=None):
    """Host->device transfer marker (reference DataTransfer.py). XLA owns
    placement; this is an identity that documents the boundary."""
    return FunctionalOp("DataH2D", lambda x: x, [node], ctx)


def datad2h_op(node, ctx=None):
    return FunctionalOp("DataD2H", lambda x: x, [node], ctx)


class PipelineSendOp(Op):
    """Stage-boundary send marker (reference PipelineSend.py:19-44).

    Executable: an identity pinned to the sending stage's context. The
    reference issues a NCCL P2P send with a runtime shape handshake; here the
    gpipe executor partitions the graph at context boundaries and its generic
    boundary-edge machinery carries the value to the consuming stage via
    ``jax.device_put`` — shapes are static and known at placement, so no
    handshake exists. The marker's job is to make the stage cut explicit."""

    def __init__(self, node, destination=None, comm=None, stream=None, ctx=None):
        super().__init__([node], ctx)
        self.destination = destination
        # paired PipelineReceiveOps register themselves here; hetulint's
        # pairing lint consults it so a receiver on another eval target
        # (outside the analyzed topo) still counts as consuming this send
        self.receivers: list["PipelineReceiveOp"] = []

    def compute(self, input_vals, tc):
        return input_vals[0]


def pipeline_send_op(node, destination=None, comm=None, stream=None, ctx=None):
    return PipelineSendOp(node, destination, comm, stream, ctx)


class PipelineReceiveOp(Op):
    """Stage-boundary receive marker (reference PipelineReceive.py:20-48).

    Executable: pass the paired :class:`PipelineSendOp` node (or any producer
    node) as ``source`` — the pair forms a real graph edge, so topo sort,
    autodiff, and the gpipe executor's cross-stage boundary transfer all see
    it. The reference instead pairs send/recv by device rank at runtime with
    a dynamic shape handshake; XLA's static shapes make placement-time
    pairing the TPU-native design."""

    def __init__(self, source=None, comm=None, stream=None, ctx=None):
        if not isinstance(source, Op):
            raise TypeError(
                "pipeline_receive_op(source=...) takes the paired "
                "pipeline_send_op NODE (placement-time pairing); device-rank "
                "pairing with a runtime shape handshake is a NCCL-ism with no "
                "XLA equivalent")
        super().__init__([source], ctx)
        self.source = source
        if isinstance(source, PipelineSendOp):
            source.receivers.append(self)

    def compute(self, input_vals, tc):
        return input_vals[0]


def pipeline_receive_op(source=None, comm=None, stream=None, ctx=None):
    return PipelineReceiveOp(source, comm, stream, ctx)


class DispatchOp(Op):
    """Declarative tensor-partition marker: ``ht.dispatch(node, parts,
    duplicate)`` (reference Dispatch.py:5).

    The reference replaces these during placement with split/concat + P2P
    (context.py:184-274). Here the partition tuple maps directly onto a
    PartitionSpec over the mesh's model axes, and GSPMD materializes the
    (much cheaper) collectives.
    """

    def __init__(self, node, parts, duplicate=1, ctx=None):
        super().__init__([node], ctx)
        self.parts = tuple(int(p) for p in parts)
        self.duplicate = int(duplicate)
        split_dims = [i for i, p in enumerate(self.parts) if p > 1]
        if len(split_dims) > 1:
            raise NotImplementedError(
                f"dispatch parts {self.parts}: at most one partitioned "
                "dimension is supported (the reference restricts dispatch to "
                "1->N / N->1 transitions the same way, Dispatch.py:35-49)")
        self.split_dim = split_dims[0] if split_dims else None

    def partition_spec(self, mesh, dp_axis, mp_axis):
        """PartitionSpec this marker denotes on ``mesh``.

        The partitioned dim maps onto the model axis. For non-parameter
        inputs dim 0 is the (dp-sharded) batch dim, so it keeps the dp axis —
        reference semantics: dispatch splits *within* a worker's model-
        parallel group while data parallelism replicates across groups.
        """
        from jax.sharding import PartitionSpec as P
        # trainable (not is_placeholder): a fed placeholder IS batch data,
        # only a stored parameter has no batch dimension
        is_param = getattr(self.inputs[0], "trainable", False)
        ndim = len(self.parts)
        dims: list = [None] * ndim
        if self.split_dim is not None:
            tp_size = mesh.shape[mp_axis]
            if self.parts[self.split_dim] != tp_size:
                raise ValueError(
                    f"dispatch parts {self.parts} split {self.parts[self.split_dim]}-way "
                    f"but the model-parallel axis has {tp_size} devices")
            dims[self.split_dim] = mp_axis
        if not is_param and ndim >= 1 and dp_axis in mesh.axis_names:
            if dims[0] is None:
                dims[0] = dp_axis
            elif dims[0] == mp_axis:
                dims[0] = (dp_axis, mp_axis)
        return P(*dims)

    def compute(self, input_vals, tc):
        return tc.apply_dispatch(self, input_vals[0])


def dispatch(node, parts, duplicate=1):
    return DispatchOp(node, parts, duplicate)


class DispatchGradientOp(Op):
    """Gradient-side partition marker paired with a forward DispatchOp
    (``inputs[1]`` is the paired forward op or its input)."""

    def __init__(self, node, forward_input, ctx=None):
        super().__init__([node, forward_input], ctx)

    def compute(self, input_vals, tc):
        return input_vals[0]


def dispatch_gradient(node, forward_input):
    return DispatchGradientOp(node, forward_input)
