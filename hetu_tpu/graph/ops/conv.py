"""Conv2d + pooling ops (NCHW, matching the reference's layout).

Replaces the reference's im2col/cuDNN conv kernels (``src/ops/Conv2d.cu``,
``CudnnConv2d.cu``) and pooling kernels with ``lax.conv_general_dilated`` /
``lax.reduce_window`` — XLA tiles these directly onto the MXU; explicit
gradient ops are provided for API parity (reference conv2d_gradient_of_data/
filter, pool gradient ops) via jax.vjp of the forward.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..node import FunctionalOp

_DIMNUMS = ("NCHW", "OIHW", "NCHW")


def _conv2d(x, w, padding, stride):
    # No preferred_element_type: output dtype follows the inputs, so the conv
    # transpose rule under jax.grad sees matching dtypes in bf16 compute mode
    # (the MXU accumulates bf16 products in f32 internally either way).
    p, s = int(padding), int(stride)
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(s, s), padding=[(p, p), (p, p)],
        dimension_numbers=_DIMNUMS)


def conv2d_op(node_A, node_B, padding=0, stride=1, ctx=None):
    op = FunctionalOp("Conv2d", lambda x, w: _conv2d(x, w, padding, stride),
                      [node_A, node_B], ctx)
    op.export_attrs = {"padding": int(padding), "stride": int(stride)}
    return op


def conv2d_gradient_of_data_op(node_filter, node_grad_y, padding=0, stride=1, ctx=None):
    """d(conv)/d(input) given (filter, dY) — reference Conv2d_Gradient_of_DataOp.

    Needs the input spatial size; recovered from dY/filter/stride/padding
    (valid for the shapes the reference supports: H_in = (H_out-1)*s + kH - 2p).
    """

    def _grad(w, dy, p=int(padding), s=int(stride)):
        kh, kw = w.shape[2], w.shape[3]
        hin = (dy.shape[2] - 1) * s + kh - 2 * p
        win = (dy.shape[3] - 1) * s + kw - 2 * p
        n, cin = dy.shape[0], w.shape[1]
        x_shape = (n, cin, hin, win)
        _, vjp = jax.vjp(lambda x: _conv2d(x, w, p, s), jnp.zeros(x_shape, dy.dtype))
        return vjp(dy)[0]

    return FunctionalOp("Conv2dGradientOfData", _grad, [node_filter, node_grad_y], ctx)


def conv2d_gradient_of_filter_op(input_X, gradient_Y, padding=0, stride=1, ctx=None):
    def _grad(x, dy, p=int(padding), s=int(stride)):
        cout, cin = dy.shape[1], x.shape[1]
        kh = x.shape[2] + 2 * p - (dy.shape[2] - 1) * s
        kw = x.shape[3] + 2 * p - (dy.shape[3] - 1) * s
        w_shape = (cout, cin, kh, kw)
        _, vjp = jax.vjp(lambda w: _conv2d(x, w, p, s), jnp.zeros(w_shape, dy.dtype))
        return vjp(dy)[0]

    return FunctionalOp("Conv2dGradientOfFilter", _grad, [input_X, gradient_Y], ctx)


def conv2d_broadcastto_op(node_A, node_B, ctx=None):
    """Broadcast per-channel bias (C,) over (N,C,H,W) (reference Conv2dBroadcast)."""
    return FunctionalOp("Conv2dBroadcastTo",
                        lambda b, x: jnp.broadcast_to(b[None, :, None, None], x.shape),
                        [node_A, node_B], ctx)


def conv2d_reducesum_op(node_A, ctx=None):
    """Reduce (N,C,H,W) over N,H,W -> (C,) — gradient of the bias broadcast."""
    return FunctionalOp("Conv2dReduceSum", lambda x: jnp.sum(x, axis=(0, 2, 3)),
                        [node_A], ctx)


# ---------------------------------------------------------------------------
# pooling
# ---------------------------------------------------------------------------

def _max_pool(x, kh, kw, p, s):
    return jax.lax.reduce_window(
        x, -jnp.inf, jax.lax.max, (1, 1, kh, kw), (1, 1, s, s),
        [(0, 0), (0, 0), (p, p), (p, p)])


def _avg_pool(x, kh, kw, p, s):
    summed = jax.lax.reduce_window(
        x, 0.0, jax.lax.add, (1, 1, kh, kw), (1, 1, s, s),
        [(0, 0), (0, 0), (p, p), (p, p)])
    # count_include_pad=True, matching the reference's divide-by-kernel-area
    return summed / float(kh * kw)


def max_pool2d_op(node_A, kernel_H, kernel_W, padding, stride, ctx=None):
    kh, kw, p, s = int(kernel_H), int(kernel_W), int(padding), int(stride)
    op = FunctionalOp("MaxPool2d", lambda x: _max_pool(x, kh, kw, p, s),
                      [node_A], ctx)
    op.export_attrs = {"kernel_H": kh, "kernel_W": kw, "padding": p, "stride": s}
    return op


def max_pool2d_gradient_op(node_out, node_out_gradient, node_in,
                           kernel_H, kernel_W, padding, stride, ctx=None):
    kh, kw, p, s = int(kernel_H), int(kernel_W), int(padding), int(stride)

    def _grad(_y, dy, x):
        _, vjp = jax.vjp(lambda v: _max_pool(v, kh, kw, p, s), x)
        return vjp(dy)[0]

    return FunctionalOp("MaxPool2dGradient", _grad,
                        [node_out, node_out_gradient, node_in], ctx)


def avg_pool2d_op(node_A, kernel_H, kernel_W, padding, stride, ctx=None):
    kh, kw, p, s = int(kernel_H), int(kernel_W), int(padding), int(stride)
    op = FunctionalOp("AvgPool2d", lambda x: _avg_pool(x, kh, kw, p, s),
                      [node_A], ctx)
    op.export_attrs = {"kernel_H": kh, "kernel_W": kw, "padding": p, "stride": s}
    return op


def avg_pool2d_gradient_op(node_out, node_out_gradient, node_in,
                           kernel_H, kernel_W, padding, stride, ctx=None):
    kh, kw, p, s = int(kernel_H), int(kernel_W), int(padding), int(stride)

    def _grad(_y, dy, x):
        _, vjp = jax.vjp(lambda v: _avg_pool(v, kh, kw, p, s), x)
        return vjp(dy)[0]

    return FunctionalOp("AvgPool2dGradient", _grad,
                        [node_out, node_out_gradient, node_in], ctx)
