"""Embedding lookup + sparse gradient.

Replaces the reference's EmbeddingLookup gather kernel
(``src/ops/EmbeddingLookup.cu``) and the IndexedSlices scatter path
(``OptimizersSparse.cu``). ``jnp.take`` lowers to a TPU gather; its vjp is a
scatter-add, which XLA sorts/segments efficiently. When the embedding variable
is PS-hosted (comm_mode PS/Hybrid), the executor routes lookups through the
parameter-server client instead (see ops/ps.py).

hetukern (docs/KERNELS.md): ``embedding_lookup_gradient_op`` dispatches
through the kernel tier. With kernels active on TPU (or forced), the dense
table gradient is reconstructed from the fused sort/unique + segment-sum
kernel's compact ``(rows, grads)`` form — one unique-row scatter instead of
one scatter per occurrence; with ``kernels="off"`` (or auto off-TPU) it is
the pre-hetukern full-table scatter, bit for bit. When the consumer is a PS
gradient push the executor flips the op into ROWS mode (:meth:`to_rows`):
the traced output becomes an :class:`IndexedRows` pair and the ``(vocab,
dim)`` zeros table is never materialized — the rows leave the device anyway.
"""
from __future__ import annotations

from typing import Any, NamedTuple

import jax.numpy as jnp

from ..node import FunctionalOp


class IndexedRows(NamedTuple):
    """IndexedSlices-style sparse gradient: ``rows`` (n,) int32 unique row
    ids padded with the vocab-size sentinel, ``grads`` (n, dim) per-row
    sums (zeros past the valid prefix). The PS runtime trims the sentinel
    tail before the wire."""

    rows: Any
    grads: Any


def embed_grad_push_routable(push, grad_op, consumers, eval_ids) -> bool:
    """The STRUCTURAL half of the rows-route preconditions, shared by the
    executor's rewire (``_rewire_ps_gradients``) and hetulint's
    ``ps-push-ignored`` mirror so the two cannot drift: the grad op is in
    dense mode, its sole consumer is this push, and it is not itself an
    eval target. Each caller still resolves the target parameter its own
    way (live PS runtime vs static name match) and checks sparse/shape.

    ``consumers``: ``{id(node): [consumer, ...]}`` over the caller's
    topo; ``eval_ids``: ids of the eval targets."""
    if getattr(grad_op, "rows_mode", None) is not False:
        return False
    if getattr(push, "ps_id", None) is None:
        return False
    if any(c is not push for c in consumers.get(id(grad_op), ())):
        return False
    return id(grad_op) not in eval_ids


def embedding_lookup_op(embedding, index, ctx=None):
    def _lookup(table, idx):
        return jnp.take(table, idx.astype(jnp.int32), axis=0)

    op = FunctionalOp("EmbeddingLookUp", _lookup, [embedding, index], ctx)
    op.embed_node = embedding
    return op


def embedding_lookup_gradient_op(vectors, index, embed_shape, ctx=None):
    """Table-shaped scatter-add of lookup grads (the reference returns
    IndexedSlices; a dense consumer needs table shape either way). The
    executor may switch the op to the compact rows form via
    :meth:`to_rows` when the value only feeds a PS push."""
    shape = tuple(int(s) for s in embed_shape)

    def _grad_dense(vec, idx):
        from ...kernels import embed_grad, registry
        mode = registry.current_mode()
        # rows restructure only where the kernel will actually serve:
        # force takes it unconditionally (an ineligible shape raises, the
        # force contract); auto-on-TPU consults eligibility FIRST so an
        # ineligible shape keeps the pre-tier one-scatter expression
        # instead of paying sort + fallback-segment-sum + scatter
        if mode == "force" or (mode == "auto" and registry._on_tpu()
                               and embed_grad.rows_path_eligible(vec, idx)):
            return embed_grad.embed_grad_dense(vec, idx, shape)
        # pre-hetukern expression — bit-identical off/fallback path.
        # Tick the dispatch stat here too: this branch IS this kernel's
        # off/fallback route for dense consumers, and the fallback-ratio
        # lint + hetutop panel must see it
        registry._count("fused_embed_grad",
                        "off" if mode == "off" else "fallback")
        return embed_grad.embed_grad_dense_xla(vec, idx, shape)

    def _grad_rows(vec, idx):
        from ...kernels import embed_grad
        rows, grads, _count = embed_grad.embed_grad_rows(vec, idx, shape[0])
        return IndexedRows(rows, grads)

    op = FunctionalOp("EmbeddingLookUpGradient", _grad_dense,
                      [vectors, index], ctx)
    op.embed_shape = shape
    op.rows_mode = False
    op._dense_fn = _grad_dense
    op._rows_fn = _grad_rows

    def _infer_meta(inputs, training=False):
        # identity shape rule for abstract evaluation (hetulint/hetuplan):
        # dense mode is the table-shaped scatter; rows mode is the compact
        # IndexedRows pair whose row count equals the lookup's index
        # elements (embed_grad_rows pads unique rows to that length).
        # Skipping eval_shape through the kernel tier keeps lint-time
        # evaluation off the dispatch counters and off the sort/unique
        # trace entirely.
        import jax
        if not op.rows_mode:
            return jax.ShapeDtypeStruct(shape, jnp.float32)
        idx = inputs[1] if len(inputs) > 1 else None
        idx_shape = (tuple(idx.shape) if hasattr(idx, "shape")
                     else tuple(idx) if isinstance(idx, tuple) else ())
        n = 1
        for s in idx_shape:
            n *= int(s)
        return IndexedRows(jax.ShapeDtypeStruct((n,), jnp.int32),
                           jax.ShapeDtypeStruct((n, shape[-1]), jnp.float32))

    op.infer_meta = _infer_meta

    def to_rows():
        op.fn = op._rows_fn
        op.rows_mode = True
        return op

    def to_dense():
        op.fn = op._dense_fn
        op.rows_mode = False
        return op

    op.to_rows = to_rows
    op.to_dense = to_dense
    return op
