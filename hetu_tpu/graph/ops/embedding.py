"""Embedding lookup + sparse gradient.

Replaces the reference's EmbeddingLookup gather kernel
(``src/ops/EmbeddingLookup.cu``) and the IndexedSlices scatter path
(``OptimizersSparse.cu``). ``jnp.take`` lowers to a TPU gather; its vjp is a
scatter-add, which XLA sorts/segments efficiently. When the embedding variable
is PS-hosted (comm_mode PS/Hybrid), the executor routes lookups through the
parameter-server client instead (see ops/ps.py).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..node import FunctionalOp


def embedding_lookup_op(embedding, index, ctx=None):
    def _lookup(table, idx):
        return jnp.take(table, idx.astype(jnp.int32), axis=0)

    op = FunctionalOp("EmbeddingLookUp", _lookup, [embedding, index], ctx)
    op.embed_node = embedding
    return op


def embedding_lookup_gradient_op(vectors, index, embed_shape, ctx=None):
    """Dense scatter-add of lookup grads into a zeros table (the reference
    returns IndexedSlices; on TPU a fused scatter-add is preferred)."""
    shape = tuple(int(s) for s in embed_shape)

    def _grad(vec, idx):
        flat_idx = idx.astype(jnp.int32).reshape(-1)
        flat_vec = vec.reshape((-1, shape[-1]))
        return jnp.zeros(shape, vec.dtype).at[flat_idx].add(flat_vec)

    return FunctionalOp("EmbeddingLookUpGradient", _grad, [vectors, index], ctx)
