"""Loss ops: softmax cross-entropy (fused) and binary cross-entropy.

Replaces the reference's fused SoftmaxCrossEntropy kernel
(``src/ops/SoftmaxCrossEntropy.cu`` and the cuDNN variant). The
log-softmax + weighted-sum composition here fuses into a single XLA reduction
on TPU — numerically identical to the reference's max-subtracted form.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..node import FunctionalOp


def _softmax_ce(logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.sum(labels * logp, axis=-1)


def softmaxcrossentropy_op(node_A, node_B, use_cudnn=True, ctx=None):
    """Per-example CE between logits (N, C) and one-hot labels (N, C).

    ``use_cudnn`` is accepted and ignored (reference SoftmaxCrossEntropy.py).
    """
    return FunctionalOp("SoftmaxCrossEntropy", _softmax_ce, [node_A, node_B], ctx)


def softmaxcrossentropy_gradient_op(node_A, node_B, node_C, use_cudnn=True, ctx=None):
    """(softmax(logits) - labels) * dL — reference SoftmaxCrossEntropyGradient."""

    def _grad(logits, labels, dl):
        return (jax.nn.softmax(logits, axis=-1) - labels) * dl[..., None]

    return FunctionalOp("SoftmaxCrossEntropyGradient", _grad,
                        [node_A, node_B, node_C], ctx)


def binarycrossentropy_op(node_A, node_B, ctx=None):
    """Elementwise BCE between prediction probabilities and labels
    (reference BinaryCrossEntropy.py)."""

    def _bce(pred, label):
        # 1e-7, not the reference's 1e-12: in f32, 1.0 - 1e-12 rounds to
        # exactly 1.0, so a saturated sigmoid still reached log(0) and one
        # fully-confident wrong example NaN'd the whole training run
        eps = 1e-7
        pred = jnp.clip(pred, eps, 1.0 - eps)
        return -(label * jnp.log(pred) + (1.0 - label) * jnp.log(1.0 - pred))

    return FunctionalOp("BinaryCrossEntropy", _bce, [node_A, node_B], ctx)


def binarycrossentropy_gradient_op(node_A, node_B, node_C, ctx=None):
    def _grad(pred, label, dl):
        eps = 1e-7  # f32-meaningful clip (see binarycrossentropy_op)
        pred = jnp.clip(pred, eps, 1.0 - eps)
        return (pred - label) / (pred * (1.0 - pred)) * dl

    return FunctionalOp("BinaryCrossEntropyGradient", _grad,
                        [node_A, node_B, node_C], ctx)
