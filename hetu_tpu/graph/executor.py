"""The Executor: define-then-run semantics compiled to single XLA programs.

Capability parity with the reference's ``gpu_ops/executor.py`` (HetuConfig
:103, Executor :301, SubExecutor :769, gradients :1096), redesigned for TPU:

The reference interprets the graph node-by-node in Python (executor.py:1029),
hand-assigning each op to one of five CUDA streams and synchronizing events.
Here each (subexecutor, feed-shape-signature) pair is traced ONCE into a
single jitted XLA program: the whole forward+backward+optimizer step — params
in, params out, buffers donated — so the Python overhead per step is one
function call and XLA owns scheduling, fusion, memory planning and collective
insertion. The reference's memory planner (executor.py:912), stream dispatch
(:1045-1073) and transfer-op insertion have no equivalent because XLA subsumes
them.

Data parallelism: with ``comm_mode='AllReduce'`` the executor builds a 1-axis
``jax.sharding.Mesh`` over the device group, shards feeds/batches along the
batch axis and replicates parameters; GSPMD inserts the gradient psum over ICI
(the reference drives NCCL per-gradient from Python on a dedicated stream,
AllReduceCommunicate.py:15-34).
"""
from __future__ import annotations

import json
import os
import pickle
import re
import time
import zlib
from typing import Any, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..context import DeviceGroup, get_current_context
from ..telemetry.tracing import XlaTraceWindow as _XW
from ..ndarray import DLContext, NDArray, ND_Sparse_Array, SparseValue, cpu, tpu
from .node import Op, PlaceholderOp, find_topo_sort
from .gradients import gradients, GradientOp, GradientContext
from .ops.comm import AllReduceCommunicateOp, DispatchOp, PipelineSendOp, PipelineReceiveOp
from .ops.ps import ParameterServerCommunicateOp, ParameterServerSparsePullOp

_NO_OUTPUT = "<no-output>"
_PS_RESIDENT = "<ps-resident-parameter>"

# op-name -> jax.named_scope name: "/" would open a NESTED scope (one op
# must be one scope segment so the profiler's HLO-metadata join stays 1:1)
_SCOPE_BAD = re.compile(r"[/\s]+")


def _op_scope(node: Op) -> str:
    return _SCOPE_BAD.sub("_", node.name)


def _flight_crc(feed_dict, batch_host) -> int:
    """Cheap batch fingerprint for the flight recorder: a chained crc32
    over a bounded stride-sample (≤512 elements per array, first + spread)
    of every fed/loaded host array — identifies WHICH batch a recorded
    step saw without storing data or paying a full-array pass per step."""
    h = 0
    vals = list(batch_host.values())
    for v in (feed_dict or {}).values():
        if hasattr(v, "asnumpy"):
            v = v.asnumpy()
        vals.append(v)
    for v in vals:
        try:
            a = np.asarray(v).ravel()
            stride = max(1, a.size // 512)
            h = zlib.crc32(np.ascontiguousarray(a[::stride][:512]).tobytes(),
                           h)
        except (TypeError, ValueError):
            continue
    return h


def _device_live_bytes() -> Optional[float]:
    """Live allocated device memory (bytes_in_use), or None where the
    backend keeps no allocator stats (CPU)."""
    try:
        stats = jax.local_devices()[0].memory_stats()
        return float(stats["bytes_in_use"]) if stats else None
    except Exception:  # noqa: BLE001 — observability only
        return None


class HetuConfig:
    """Execution configuration (reference executor.py:103).

    Unused reference knobs that have no TPU meaning (stream counts, lazy
    memory planning) are accepted and ignored so call sites port unchanged.
    """

    def __init__(self, eval_node_list, train_name="*", val_name="*", ctx=None,
                 seed=None, comm_mode=None, mesh=None, use_sparse_pull=True,
                 cstable_policy=None, bsp=False, prefetch=True, enable_lazy=False,
                 cache_bound=100, log_path=None, gpipe=False,
                 gpipe_microbatches=None, dtype=np.float32,
                 dp_axis="dp", mp_axis="tp", anomaly_guard=False,
                 telemetry=None, introspect=None, comm_quant=None,
                 comm_quant_block=None, comm_quant_min_size=None,
                 comm_quant_error_feedback=None, comm_quant_force=(),
                 kernels=None, plan=None, watch=None, slo=None, **kwargs):
        self.eval_node_list = eval_node_list
        self.ctx = ctx
        self.seed = seed if seed is not None else np.random.randint(0, 2**31 - 1)
        self.comm_mode = comm_mode
        self.bsp = bsp
        self.prefetch = prefetch
        # accepted for API parity, no behavioral switch here: the PS path
        # ALWAYS stages sparse row pulls (the reference's False mode pulls
        # whole tables — strictly worse on TPU), and logging goes through
        # the standard logger rather than a file path
        self.use_sparse_pull = use_sparse_pull
        self.cstable_policy = cstable_policy
        self.cache_bound = cache_bound
        self.log_path = log_path
        self.gpipe = gpipe
        # microbatch count for dataloader-fed gpipe runs (run() without a
        # feed list); explicit feed lists carry their own M
        self.gpipe_microbatches = gpipe_microbatches
        # compute dtype: bf16 keeps the MXU fed at full rate; master params,
        # optimizer state and updates stay f32 (mixed precision — the
        # reference is f32-only, c_runtime_api.h GetDataSize :74-82)
        self.dtype = np.dtype(dtype)
        self.compute_dtype = self.dtype
        self.dp_axis = dp_axis
        self.mp_axis = mp_axis
        # resilience: in-trace finite-check gating the state commit (see
        # hetu_tpu/resilience.py). A NaN/Inf loss, parameter update or slot
        # leaves params/slots/op-state bit-identical to pre-step.
        from ..resilience import env_truthy
        self.anomaly_guard = bool(anomaly_guard) \
            or env_truthy("HETU_ANOMALY_GUARD")
        # observability: "off" (default, zero per-step overhead), "metrics"
        # (registry + per-step JSONL), or "trace" (+ Chrome-trace spans).
        # Env default: HETU_TELEMETRY; output dir: HETU_TELEMETRY_DIR.
        # See hetu_tpu/telemetry and docs/OBSERVABILITY.md.
        from ..telemetry import resolve_mode
        self.telemetry = resolve_mode(telemetry)
        # numeric-health introspection (docs/OBSERVABILITY.md "numeric
        # health"): 0 = off (default, zero per-step scope work — same
        # None-check-only contract as telemetry), N = fused in-graph stats
        # every N steps + flight recorder + NaN/Inf provenance on guard
        # trips. Env default: HETU_INTROSPECT (+ HETU_INTROSPECT_EVERY).
        from ..telemetry.scope import resolve_introspect
        self.introspect = resolve_introspect(introspect)
        # hetuwatch (docs/OBSERVABILITY.md pillar 6): runtime plan-
        # divergence sentinel. 0 = off (default, zero per-step watch work —
        # one attribute check, same contract as telemetry/introspect), N =
        # judge the measured critical-path legs against the adopted plan's
        # prediction every N steps, export residual gauges + kind:"watch"
        # JSONL, and latch plan_divergence / SLO-breach events. Env
        # default: HETU_WATCH (+ HETU_WATCH_EVERY). SLO budgets come from
        # slo= / HETU_SLO_SPEC (e.g. "step_ms<25,ps_pull_frac<0.3") and
        # are validated here so a bad spec fails at build, not mid-run.
        from ..telemetry.watch import parse_slo_spec, resolve_watch
        self.watch = resolve_watch(watch)
        self.slo = slo if slo is not None \
            else os.environ.get("HETU_SLO_SPEC", "")
        parse_slo_spec(self.slo)
        # hetuq (docs/COMM_QUANT.md): quantized communication policy. "off"
        # (default) leaves every comm path bit-identical to pre-hetuq
        # behavior; "int8"/"fp8" compresses the DP AllReduce broadcast half
        # in-trace (per-block scaling, optional error-feedback residual as
        # executor state, small params exempt by min_size) and arms the PS
        # worker's int8 wire container. Env default: HETU_COMM_QUANT (+
        # _BLOCK/_MIN/_EF).
        from ..comm_quant import resolve_policy
        self.comm_quant_policy = resolve_policy(
            comm_quant, comm_quant_block, comm_quant_min_size,
            comm_quant_error_feedback, comm_quant_force)
        self.comm_quant = self.comm_quant_policy.mode
        # hetukern (docs/KERNELS.md): Pallas kernel tier dispatch mode.
        # "off" = every call site serves its pre-hetukern XLA expression,
        # bit-identical; "auto" (default) = eligible shapes take the Pallas
        # kernel on real TPU backends and fall back per-shape elsewhere —
        # off-TPU auto IS the pre-hetukern path; "force" = kernels
        # everywhere (interpret mode off-TPU), ineligible shapes raise.
        # Env default: HETU_KERNELS. The executor scopes this mode around
        # every trace/lower so interleaved executors never leak settings.
        from ..kernels.registry import resolve_mode as _kresolve
        self.kernels = _kresolve(kernels)
        # hetuplan (docs/ANALYSIS.md "Tier C: planning"): "auto" asks the
        # Executor to run the cost-model planner over the graph at build
        # and adopt its comm_mode / comm_quant choice wherever this config
        # left them unset (an explicit declaration always wins — hetulint
        # --plan reports the divergence instead). A prebuilt analysis.Plan
        # is adopted as-is. Env default: HETU_PLAN=auto (off/0/false/none
        # disable — the HETU_KERNELS/HETU_COMM_QUANT convention).
        if plan is None:
            env_plan = os.environ.get("HETU_PLAN", "").strip().lower()
            if env_plan and env_plan not in ("off", "0", "false", "none",
                                             "no"):
                plan = env_plan
        if isinstance(plan, str) and plan not in ("auto",):
            raise ValueError(
                f"plan must be None, 'auto', or an analysis.Plan; "
                f"got {plan!r}")
        self.plan = plan
        self.plan_adopted = None   # set by Plan.apply at executor build
        if self.comm_quant != "off" and gpipe:
            raise ValueError(
                "comm_quant is not supported with gpipe=True: the pipeline "
                "executor owns its own cross-stage transfers")
        if self.anomaly_guard and comm_mode in ("PS", "Hybrid"):
            raise ValueError(
                "anomaly_guard gates the on-device state commit, but PS-"
                "hosted parameters update server-side per gradient push and "
                "cannot be skipped after the fact — run PS/Hybrid jobs "
                "without the guard")
        if mesh is not None and not isinstance(mesh, Mesh):
            raise ValueError(
                f"mesh must be a jax.sharding.Mesh, got {type(mesh).__name__}")
        self.mesh = mesh
        self.placeholder_to_arr_map = {}
        self.param_specs: dict[int, P] = {}  # placeholder id -> PartitionSpec
        self.has_dispatch = any(
            isinstance(n, DispatchOp)
            for n in find_topo_sort(self.eval_node_list))
        if self.mesh is None:
            self.mesh = self._deduce_mesh()
        if self.has_dispatch and (
                self.mesh is None or self.mp_axis not in self.mesh.axis_names):
            raise ValueError(
                "the graph contains ht.dispatch(...) tensor-parallel markers "
                "but no model-parallel mesh axis exists; place the model-"
                "parallel subgraph in a tuple DeviceGroup context (e.g. "
                "ctx=[(tpu(0), tpu(1)), (tpu(2), tpu(3))] for 2 workers x "
                f"2-way TP) or pass mesh= with a {self.mp_axis!r} axis")
        if self.kernels == "force" and self.mesh is not None \
                and self.mesh.size > 1:
            raise ValueError(
                "kernels='force' cannot serve a multi-device (GSPMD) "
                "program: a bare pallas_call has no SPMD partitioning "
                "rule, so every kernel would raise at trace time. Use "
                "kernels='auto' (partitioned programs keep their XLA "
                "fallbacks) — docs/KERNELS.md")
        self.device = self._deduce_device()

    # -- device & mesh deduction -------------------------------------------
    def _ctx_list(self):
        if isinstance(self.ctx, DeviceGroup):
            return self.ctx.flat()
        if isinstance(self.ctx, DLContext):
            return [self.ctx]
        if isinstance(self.ctx, (list, tuple)):
            return DeviceGroup(list(self.ctx)).flat()
        return []

    def _find_mp_group(self) -> Optional[DeviceGroup]:
        """Largest model-parallel (tuple-containing) DeviceGroup attached to
        the executor ctx or any graph node (reference context.py tuple syntax:
        ``[(d0, d1), (d2, d3)]`` = 2 workers x 2-way model parallel)."""
        best = None
        candidates = []
        if isinstance(self.ctx, DeviceGroup):
            candidates.append(self.ctx)
        for n in find_topo_sort(self.eval_node_list):
            if isinstance(n.raw_ctx, DeviceGroup):
                candidates.append(n.raw_ctx)
        for g in candidates:
            if g.is_mp and (best is None
                            or g.mp_device_num > best.mp_device_num):
                best = g
        return best

    def _deduce_mesh(self) -> Optional[Mesh]:
        mp_group = self._find_mp_group()
        if mp_group is not None:
            sizes = {len(c) for c in mp_group if isinstance(c, tuple)}
            if len(sizes) != 1 or not all(
                    isinstance(c, tuple) for c in mp_group):
                raise ValueError(
                    f"model-parallel DeviceGroup {mp_group} must consist of "
                    "uniform tuples: [(d0, d1), (d2, d3)] = 2 workers x 2-way")
            tp = sizes.pop()
            dp = mp_group.worker_num
            devs = [c.jax_device() for c in mp_group.flat()]
            if len(set(devs)) != dp * tp:
                raise ValueError(
                    f"model-parallel DeviceGroup {mp_group} resolves to "
                    f"{len(set(devs))} distinct devices, need {dp}x{tp}")
            return Mesh(np.array(devs).reshape(dp, tp),
                        (self.dp_axis, self.mp_axis))
        if self.comm_mode not in ("AllReduce", "Hybrid"):
            return None
        ctxs = self._ctx_list()
        if len(ctxs) > 1:
            devs = [c.jax_device() for c in ctxs]
        else:
            devs = [d for d in jax.devices() if d.platform != "cpu"] or jax.devices()
        if len(devs) <= 1:
            return None
        return Mesh(np.array(devs), (self.dp_axis,))

    @property
    def dp_size(self) -> int:
        if self.mesh is None or self.dp_axis not in self.mesh.axis_names:
            return 1
        return self.mesh.shape[self.dp_axis]

    def _deduce_device(self):
        ctxs = self._ctx_list()
        if ctxs:
            return ctxs[0].jax_device()
        return None


class TraceContext:
    """Per-trace services handed to ``Op.compute`` (replaces the reference's
    stream_handle/event plumbing)."""

    def __init__(self, config: HetuConfig, topo, training: bool, env: dict,
                 rng_key, step, op_state_in: dict):
        self.config = config
        self.topo = topo
        self.training = training
        self.env = env
        self.rng_key = rng_key
        self.step = step
        self.op_state_in = op_state_in
        self.op_state_updates: dict[int, Any] = {}
        self.param_updates: dict[int, Any] = {}
        self.slot_updates: dict[int, Any] = {}
        self.ps_grad_outputs: dict[int, Any] = {}
        # hetuq error-feedback residuals: executor-threaded state keyed by
        # quantized AllReduce op id (in: previous step's residual; updates:
        # this step's quantization error, committed like slots)
        self.qresid_in: dict[int, Any] = {}
        self.qresid_updates: dict[int, Any] = {}
        self.grad_cache: dict[int, dict[int, Any]] = {}
        self._in_grad_retrace = False
        # f32 master copies of params when compute_dtype is lower precision
        # (filled by the step builder; optimizer updates read these)
        self.master_params: dict[int, Any] = {}
        # hetuscope hooks: a clip_grad_norm optimizer publishes its fused
        # global-norm reduction here so the introspection stats reuse it
        # (one computation, two consumers); poison_scope is the nan_op
        # fault target — that op's output is NaN'd inside the trace
        self.grad_global_norm: Optional[Any] = None
        self.poison_scope: Optional[str] = None
        # Fold the node's position WITHIN this topo, not its process-global
        # id: global ids depend on how many nodes earlier code constructed,
        # which made RNG streams (dropout etc.) vary with test order.
        self._node_index = {id(n): i for i, n in enumerate(topo)}

    # -- RNG ---------------------------------------------------------------
    def next_rng(self, node: Op):
        return jax.random.fold_in(
            self.rng_key, self._node_index.get(id(node), node.id))

    # -- collectives (GSPMD) ----------------------------------------------
    def allreduce(self, x, param_node=None, op=None):
        mesh = self.config.mesh
        if mesh is None:
            return x
        # Constrain the gradient to the target parameter's own spec: GSPMD
        # inserts the psum over the dp axis (the MPI+NCCL module's job in the
        # reference); a tp-sharded parameter's gradient stays tp-sharded.
        spec = (self.config.param_specs.get(id(param_node), P())
                if param_node is not None else P())
        # hetuq: ops the Executor marked (comm_quant policy, eligibility by
        # size/override) lower as reduce-scatter(f32) -> blockwise quantize
        # -> all-gather(int8/fp8) -> dequantize, with the error-feedback
        # residual threaded through executor state (docs/COMM_QUANT.md)
        if op is not None and getattr(op, "comm_quant", False) \
                and self.config.comm_quant_policy.active \
                and hasattr(x, "dtype") \
                and jnp.issubdtype(x.dtype, jnp.floating) \
                and self.config.dp_axis in mesh.axis_names:
            from .. import comm_quant as _cq
            out, new_resid = _cq.quantized_allreduce(
                x, self.qresid_in.get(id(op)), mesh, self.config.dp_axis,
                NamedSharding(mesh, spec), self.config.comm_quant_policy)
            if new_resid is not None and not self._in_grad_retrace:
                self.qresid_updates[id(op)] = new_resid
            return out
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    def apply_dispatch(self, op: DispatchOp, x):
        mesh = self.config.mesh
        if mesh is None or self.config.mp_axis not in mesh.axis_names:
            raise ValueError(
                f"{op.name}: dispatch requires a mesh with a "
                f"{self.config.mp_axis!r} axis (HetuConfig should have "
                "raised at construction)")
        if len(op.parts) != x.ndim:
            raise ValueError(
                f"{op.name}: parts {op.parts} does not match input rank "
                f"{x.ndim}")
        spec = op.partition_spec(mesh, self.config.dp_axis,
                                 self.config.mp_axis)
        return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))

    # -- PS hooks (installed by their runtimes) -----------------------------
    def ps_push_pull(self, op, grad):
        """PS comm op inside the trace: capture the gradient as an extra
        program output; the host pushes it to the server post-step (the
        reference instead issues the RPC from the interpreter on the d2h
        stream, ParameterServerCommunicate.py:38-50)."""
        def f32(g):
            if hasattr(g, "dtype") and g.dtype != jnp.float32:
                return g.astype(jnp.float32)  # PS stores/accumulates f32
            return g

        from .ops.embedding import IndexedRows
        if isinstance(grad, IndexedRows):
            # hetukern rows-mode embedding grad: ids stay int, values f32
            self.ps_grad_outputs[id(op)] = IndexedRows(grad.rows,
                                                       f32(grad.grads))
            return None
        # a shared-table gradient arrives as a tuple of per-lookup row grads
        self.ps_grad_outputs[id(op)] = (
            tuple(f32(g) for g in grad) if isinstance(grad, tuple) else f32(grad))
        return None

    def ps_sparse_pull(self, op, vals):
        raise AssertionError(
            "ParameterServerSparsePullOp values are staged by the executor")

    # -- autodiff ----------------------------------------------------------
    def gradient_of(self, gctx: GradientContext, x: Op):
        key = id(gctx)
        if key not in self.grad_cache:
            xs = gctx.xs
            sub_topo = gctx.downstream_nodes(self.topo)
            base_env = self.env

            down_ids = {id(n) for n in sub_topo}

            def fwd(x_vals):
                # drop downstream nodes so they re-trace as functions of xs
                env2 = {k: v for k, v in base_env.items() if k not in down_ids}
                for n, v in zip(xs, x_vals):
                    env2[id(n)] = v
                sub_tc = TraceContext(self.config, self.topo, self.training,
                                      env2, self.rng_key, self.step,
                                      self.op_state_in)
                sub_tc._in_grad_retrace = True
                # the vjp re-trace must see the same poisoned op as the
                # primal trace, or grads would flow from clean values
                sub_tc.poison_scope = self.poison_scope
                for node in sub_topo:
                    # skip the gradient/comm/optimizer tail — only the forward
                    # path to the loss matters inside the vjp closure
                    if node.is_gradient or node.is_optimizer:
                        continue
                    if any(id(i) not in env2 for i in node.inputs):
                        continue
                    _eval_node(node, env2, sub_tc)
                loss_val = env2[id(gctx.loss)]
                return jnp.sum(loss_val)  # loss is scalar already in practice

            x_vals = [self.env[id(n)] for n in xs]
            grads = jax.grad(fwd)(x_vals)
            self.grad_cache[key] = {id(n): g for n, g in zip(xs, grads)}
        return self.grad_cache[key][id(x)]


def _eval_node(node: Op, env: dict, tc: TraceContext):
    """Evaluate one node into ``env`` (shared by main trace and vjp re-trace)."""
    if id(node) in env:
        return
    input_vals = [env[id(i)] for i in node.inputs]
    cdtype = tc.config.compute_dtype
    if cdtype != np.float32:
        # enforce the compute dtype at every op boundary: stateful ops
        # (batchnorm running stats) legitimately produce f32 and would
        # otherwise poison downstream matmuls back to full precision.
        # XLA elides the no-op casts.
        input_vals = [
            v.astype(cdtype)
            if (isinstance(v, jax.Array) or hasattr(v, "aval"))
            and jnp.issubdtype(getattr(v, "dtype", np.int32), jnp.floating)
            and v.dtype != cdtype else v
            for v in input_vals]
    if any(v is _PS_RESIDENT for v in input_vals):
        raise ValueError(
            f"{node.name} reads a PS-resident embedding table directly; only "
            "embedding_lookup_op / parameterServerSparsePull_op may touch "
            "PS-hosted tables (their rows are staged by the executor)")
    # every op's lowering runs under jax.named_scope(op.name): the HLO
    # metadata op_name path then carries graph-op identity, which is what
    # lets hetuprof attribute device-trace time back to Ops (and dump_hlo
    # readers navigate the fused program). Trace-time only — zero per-step
    # runtime cost, and backward ops inherit the scope through the vjp.
    if node.stateful:
        state_in = tc.op_state_in[id(node)]
        with jax.named_scope(_op_scope(node)):
            out, new_state = node.compute_stateful(input_vals, state_in, tc)
        # op state (running stats) keeps its own dtype across steps — under
        # bf16 compute the update must not silently downcast the f32 stats
        new_state = jax.tree.map(
            lambda new, old: new.astype(old.dtype)
            if hasattr(old, "dtype") and hasattr(new, "dtype")
            and new.dtype != old.dtype else new,
            new_state, state_in)
        if not tc._in_grad_retrace:
            tc.op_state_updates[id(node)] = new_state
        env[id(node)] = out
    else:
        with jax.named_scope(_op_scope(node)):
            env[id(node)] = node.compute(input_vals, tc)
    if tc.poison_scope is not None and _op_scope(node) == tc.poison_scope:
        # nan_op fault (HETU_FAULT_SPEC, test mode): poison exactly this
        # op's output so provenance can be proven to localize it
        out = env[id(node)]
        if hasattr(out, "dtype") and jnp.issubdtype(out.dtype,
                                                    jnp.floating):
            env[id(node)] = jnp.full_like(out, jnp.nan)


class SubExecutor:
    """One named evaluation target compiled into jitted programs
    (reference SubExecutor executor.py:769)."""

    def __init__(self, name: str, eval_nodes: list[Op], executor: "Executor"):
        self.name = name
        self.eval_nodes = eval_nodes
        self.executor = executor
        self.config = executor.config
        self.topo = find_topo_sort(eval_nodes)
        self.training = any(n.is_optimizer for n in self.topo)
        self.feed_nodes = [n for n in self.topo
                           if n.is_placeholder and getattr(n, "is_feed", False)]
        self.dataloader_nodes = [n for n in self.topo if n.is_dataloader]
        self.stateful_nodes = [n for n in self.topo if n.stateful]
        self.optimizer_nodes = [n for n in self.topo if n.is_optimizer]
        # hetuq: quantized-AllReduce ops appearing in this target's topo and
        # the subset carrying error-feedback residual state — the residuals
        # ride through the jitted step like optimizer slots
        _qids = {id(n) for n in getattr(executor, "qar_ops", ())}
        self.qar_nodes = [n for n in self.topo if id(n) in _qids]
        self.qresid_nodes = [n for n in self.qar_nodes
                             if id(n) in executor.state.get("qresid", {})]
        # finite-check + gated commit only makes sense where state commits
        self.anomaly_guard = self.training and self.config.anomaly_guard
        self._compiled: dict[tuple, Any] = {}
        self._last_call = None  # (jitted fn, args) of the latest run
        # hetuscope introspection (docs/OBSERVABILITY.md "numeric health"):
        # armed iff the Executor built an Introspector and this target
        # trains. Stats/poison variants of the step compile under distinct
        # cache keys; _base_sigs tracks the shape signatures alone so those
        # variants never read as recompile churn. _scope_meta is the
        # (topo-ordered scope keys, per-op input map) pair captured while
        # tracing a stats variant — what find_culprit walks.
        self.introspect = self.training and executor.introspector is not None
        self._base_sigs: set = set()
        self._replay_compiled: dict[tuple, Any] = {}
        self._scope_meta: Optional[tuple] = None
        # compiled-executable handles keyed by the jitted fn, so repeated
        # cost/memory/HLO queries re-lower once per signature, not per query
        self._exe_cache: dict[int, Any] = {}
        # device-side input double buffer: id(node) -> (host batch, device arr)
        self._dev_prefetch: dict[int, tuple] = {}
        # HETU_PROFILE=1: cumulative host-side phase timings + step count
        # (the reference's profiling surface is --timing walls + PS load
        # recording; this adds a per-phase breakdown, ``sub.profile_summary()``)
        self._profile = ({"prestep_s": 0.0, "trace_build_s": 0.0,
                          "dispatch_s": 0.0, "poststep_s": 0.0, "steps": 0}
                         if os.environ.get("HETU_PROFILE", "0")
                         not in ("", "0") else None)
        # telemetry (docs/OBSERVABILITY.md): PS server-health poll cadence
        # and the last recorded per-phase wall times (graphboard's
        # render(..., timings=True) overlay reads these)
        self._tel_ps_every = max(1, int(os.environ.get(
            "HETU_TELEMETRY_PS_EVERY", "20")))
        self.last_phases: Optional[dict] = None
        self._tel_cp_cache: dict = {}   # hetutrail critical-path gauges
        self._tel_watch_cache: dict = {}   # hetuwatch residual gauges

        # -- PS bookkeeping (comm_mode PS/Hybrid) --------------------------
        ps = executor.ps_runtime
        self.ps_staged_ops = []    # lookup/sparse-pull ops fed by host pulls
        self.ps_sparse_vars = []   # PS-resident tables appearing in the topo
        self.ps_dense_vars = []    # PS-hosted dense params fed per step
        self.ps_comm_ops = []      # gradient push ops, in topo order
        if ps is not None:
            for n in self.topo:
                embed = getattr(n, "embed_node", None)
                if embed is not None and id(embed) in ps.params \
                        and ps.params[id(embed)].sparse:
                    self.ps_staged_ops.append(n)
                if isinstance(n, ParameterServerCommunicateOp) \
                        and getattr(n, "ps_param_node", None) is not None:
                    self.ps_comm_ops.append(n)
                if n.is_placeholder and id(n) in ps.params:
                    if ps.params[id(n)].sparse:
                        self.ps_sparse_vars.append(n)
                    else:
                        self.ps_dense_vars.append(n)
            for op in self.ps_staged_ops:
                idx_node = op.inputs[1]
                if not (idx_node in self.feed_nodes
                        or idx_node in self.dataloader_nodes):
                    raise ValueError(
                        f"PS-hosted lookup {op.name!r}: the index input "
                        f"{idx_node.name!r} must be a feed or dataloader "
                        "node (its value is needed host-side to pull rows)")
        # staged lookups grouped by table: a shared table (several lookup
        # ops) pulls the union of its indices once per step
        self._staged_by_table: dict[int, list] = {}
        for op in self.ps_staged_ops:
            self._staged_by_table.setdefault(id(op.embed_node), []).append(op)

        # -- device-resident datasets (TPU infeed design) -------------------
        # A small, sequential (no shuffle/func, drop_last) dataset uploads to
        # the device ONCE; the jitted step slices its batch with a traced
        # cursor. Replaces the reference's 3-deep pinned-buffer H2D ring
        # (dataloader.py:26-55) with zero per-step host->device traffic.
        self.resident_dl: dict[int, Any] = {}
        self._dl_cursor: dict[int, int] = {}
        limit = float(os.environ.get("HETU_DEVICE_DATA_MB", "1024")) * 1e6
        if executor.config.mesh is None:
            ps_idx = {id(op.inputs[1]) for op in self.ps_staged_ops}
            for n in self.dataloader_nodes:
                dl = getattr(n, "dataloaders", {}).get(self.name)
                if (dl is not None and dl.func is None and not dl.shuffle
                        and dl.drop_last and id(n) not in ps_idx
                        and dl._data.nbytes <= limit):
                    self.resident_dl[id(n)] = (
                        executor._prepare_input(dl._data, batch=False),
                        dl.batch_size, dl.batch_num)
        self.host_dl_nodes = [n for n in self.dataloader_nodes
                              if id(n) not in self.resident_dl]
        self.res_dl_nodes = [n for n in self.dataloader_nodes
                             if id(n) in self.resident_dl]

    # ------------------------------------------------------------------
    def _signature(self, feed_vals, batch_vals):
        def sig(v):
            if isinstance(v, SparseValue):
                return ("sparse", tuple(v.data.shape), v.nrow, v.ncol)
            return (tuple(v.shape), str(v.dtype))

        # host-side optimizer state (e.g. ReduceOnPlateau's current lr) is
        # baked into the trace as constants — key the cache on it so host
        # lr changes retrace instead of being silently ignored
        opt_tokens = tuple(n.optimizer.cache_token() for n in self.optimizer_nodes)
        return (tuple(sig(v) for v in feed_vals),
                tuple(sig(v) for v in batch_vals), opt_tokens)

    @staticmethod
    def _push_idx(op, staged_idx):
        """Index argument for one PS grad push: None (dense), one array
        (single lookup), or a tuple of per-lookup arrays (shared table —
        the runtime concatenates and dedup-sums, matching the reference's
        IndexedSlices accumulation)."""
        lks = getattr(op, "staged_lookups", None)
        if not lks:
            return None
        if len(lks) == 1:
            return staged_idx[id(lks[0])]
        return tuple(staged_idx[id(lk)] for lk in lks)

    def _host_value(self, node, feed_dict, batch_host):
        """Host-side numpy value of a feed/dataloader node (pre device_put)."""
        if node in feed_dict:
            v = feed_dict[node]
            if hasattr(v, "asnumpy"):
                v = v.asnumpy()
            return np.asarray(v)
        if id(node) in batch_host:
            return batch_host[id(node)]
        raise ValueError(f"no host value for {node.name!r}")

    def _build(self, introspect_now=False, poison_scope=None,
               donate_ok=True):
        """Build one jitted step variant. ``introspect_now`` fuses the
        hetuscope per-op/per-param reductions into the program and returns
        them as one extra output; ``poison_scope`` NaN-poisons that op's
        output inside the trace (the ``nan_op`` fault); ``donate_ok=False``
        builds the no-donation debug variant the provenance replay uses
        (inputs must survive the call)."""
        from ..telemetry import scope as _scope
        ex = self.executor
        param_nodes = ex.param_nodes
        pf_names = {id(n): f for n, f in zip(ex.param_nodes,
                                             ex._param_file_names())}
        topo = self.topo
        eval_nodes = self.eval_nodes
        training = self.training
        feed_nodes = self.feed_nodes
        dl_nodes = self.dataloader_nodes
        stateful_nodes = self.stateful_nodes
        opt_nodes = self.optimizer_nodes
        config = self.config

        ps_staged_ops = self.ps_staged_ops
        ps_sparse_vars = self.ps_sparse_vars
        ps_dense_vars = self.ps_dense_vars
        ps_comm_ops = self.ps_comm_ops
        qresid_nodes = self.qresid_nodes

        host_dl_nodes = self.host_dl_nodes
        res_dl_specs = [(n,) + self.resident_dl[id(n)][1:]
                        for n in self.res_dl_nodes]

        compute_dtype = config.compute_dtype

        def cast_in(v):
            """Cast a float input to the compute dtype (bf16 mixed precision);
            master params stay f32 outside ``env``."""
            if compute_dtype == np.float32:
                return v
            if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating):
                return v.astype(compute_dtype)
            return v

        guard = self.anomaly_guard

        def step_fn(params_t, slots_t, opstate_t, rng_root, step, feeds_t,
                    batches_t, dl_cursors_t, res_data_t, ps_staged_t,
                    ps_dense_t, inject_nan_t, qresid_t):
            # fold the step into the rng INSIDE the trace: doing it eagerly
            # costs ~5 dispatched host ops per step (measured ~3ms over the
            # tunneled chip; free here)
            rng = jax.random.fold_in(rng_root, step)
            env: dict[int, Any] = {}
            masters: dict[int, Any] = {}
            for node, val in zip(param_nodes, params_t):
                env[id(node)] = cast_in(val)
                masters[id(node)] = val
            for node, val in zip(feed_nodes, feeds_t):
                env[id(node)] = cast_in(val)
            for node, val in zip(host_dl_nodes, batches_t):
                env[id(node)] = cast_in(val)
            # device-resident datasets: slice the batch on device. The data
            # rides in as an ARGUMENT, not a closure constant — constants are
            # serialized into the (size-limited) remote compile request.
            for (node, bs, bnum), data, cur in zip(res_dl_specs, res_data_t,
                                                   dl_cursors_t):
                # named like its dataloader node so hetuprof attributes the
                # on-device batch slice instead of an anonymous dynamic_slice
                with jax.named_scope(_op_scope(node)):
                    start = (cur % bnum) * bs
                    batch = jax.lax.dynamic_slice_in_dim(data, start, bs,
                                                         axis=0)
                    env[id(node)] = cast_in(batch)
            # PS-resident embeddings: staged rows stand in for the lookup
            # output; the table itself never exists on device
            for node, val in zip(ps_staged_ops, ps_staged_t):
                env[id(node)] = cast_in(val)
            for node in ps_sparse_vars:
                env[id(node)] = _PS_RESIDENT
            for node, val in zip(ps_dense_vars, ps_dense_t):
                env[id(node)] = cast_in(val)
            op_state_in = {id(n): s for n, s in zip(stateful_nodes, opstate_t)}
            tc = TraceContext(config, topo, training, env, rng, step, op_state_in)
            tc.master_params = masters
            tc.poison_scope = poison_scope
            tc.qresid_in = {id(n): v for n, v in zip(qresid_nodes, qresid_t)}
            slots_in = {id(n): s for n, s in zip(opt_nodes, slots_t)}
            for node in topo:
                if id(node) in env:
                    continue
                if node.is_placeholder:
                    raise ValueError(f"Placeholder {node.name} was not fed")
                if node.is_optimizer:
                    with jax.named_scope(_op_scope(node)):
                        node.apply_updates(env, slots_in[id(node)], tc)
                    env[id(node)] = _NO_OUTPUT
                    continue
                _eval_node(node, env, tc)
            outputs = tuple(
                jnp.zeros(()) if (env[id(n)] is _NO_OUTPUT or env[id(n)] is None)
                else env[id(n)]
                for n in eval_nodes)
            new_params = tuple(tc.param_updates.get(id(n), masters[id(n)])
                               for n in param_nodes)
            new_slots = tuple(tc.slot_updates.get(id(n), slots_in[id(n)])
                              for n in opt_nodes)
            new_opstate = tuple(tc.op_state_updates.get(id(n), op_state_in[id(n)])
                                for n in stateful_nodes)
            ps_grads = tuple(tc.ps_grad_outputs[id(op)] for op in ps_comm_ops)
            new_qresid = tuple(tc.qresid_updates.get(id(n), tc.qresid_in[id(n)])
                               for n in qresid_nodes)
            scope_stats = ()
            if introspect_now:
                # -- hetuscope in-graph stats (one extra fetch) ------------
                # Per-op activation stats for every float-typed value in
                # the env (activations, grads, fed inputs) keyed by the
                # same named_scope identity hetuprof joins on, plus
                # per-parameter grad norms and update/param ratios.
                # Computed BEFORE the guard gating so the table describes
                # the ATTEMPTED update — exactly what a NaN post-mortem
                # needs. XLA fuses the reductions into the step program.
                key_by_id: dict[int, str] = {}
                used: set[str] = set()
                op_entries = []
                for node in topo:
                    v = env.get(id(node))
                    if node.is_optimizer or v is None or v is _NO_OUTPUT \
                            or v is _PS_RESIDENT or isinstance(v, tuple):
                        continue
                    if not (hasattr(v, "dtype")
                            and jnp.issubdtype(v.dtype, jnp.floating)) \
                            or not getattr(v, "size", 0):
                        continue
                    k = _op_scope(node)
                    if k in used:   # duplicate user op names stay distinct
                        k = f"{k}__{node.id}"
                    used.add(k)
                    key_by_id[id(node)] = k
                    op_entries.append((k, v))
                param_entries = []
                for onode in opt_nodes:
                    for var, gnode in zip(onode.vars, onode.inputs):
                        g = env.get(id(gnode))
                        if g is None or isinstance(g, tuple) \
                                or not hasattr(g, "dtype"):
                            continue   # PS-managed: server owns the update
                        param_entries.append(
                            (pf_names.get(id(var), var.name), g,
                             masters.get(id(var)),
                             tc.param_updates.get(id(var))))
                loss_val = None
                for n, v in zip(eval_nodes, outputs):
                    if n.is_optimizer:
                        continue
                    if hasattr(v, "dtype") \
                            and jnp.issubdtype(v.dtype, jnp.floating) \
                            and getattr(v, "size", 0) == 1:
                        loss_val = v
                        break
                # stats pack into ONE stacked vector (the single extra
                # fetch); the slot spec + topo order + input map are
                # trace-time metadata, captured host-side for find_culprit
                spec, scope_stats = _scope.traced_stats(
                    op_entries, param_entries, loss_val,
                    tc.grad_global_norm)
                self._scope_meta = (
                    [key_by_id[id(n)] for n in topo if id(n) in key_by_id],
                    {key_by_id[id(n)]: [key_by_id[id(i)] for i in n.inputs
                                        if id(i) in key_by_id]
                     for n in topo if id(n) in key_by_id},
                    spec)
            finite = jnp.bool_(True)
            if guard:
                # -- anomaly guard (resilience layer) ----------------------
                # inject_nan_t is the deterministic fault hook: poison the
                # update BEFORE the finite-check, so the guard path is
                # exercised end to end (a scalar arg — no retrace per step)
                def is_float(v):
                    return (hasattr(v, "dtype")
                            and jnp.issubdtype(v.dtype, jnp.floating))

                new_params = tuple(
                    jnp.where(inject_nan_t, jnp.full_like(p, jnp.nan), p)
                    if is_float(p) else p for p in new_params)
                checks = [jnp.all(jnp.isfinite(v)) for v in outputs
                          if is_float(v)]
                checks += [jnp.all(jnp.isfinite(p)) for p in new_params
                           if is_float(p)]
                for s in new_slots + new_opstate:
                    checks += [jnp.all(jnp.isfinite(l))
                               for l in jax.tree.leaves(s) if is_float(l)]
                if checks:
                    finite = jnp.all(jnp.stack(checks))

                # gate the whole commit: an anomalous step leaves params,
                # slots and op state bit-identical to pre-step
                def keep(new, old):
                    return jax.tree.map(
                        lambda a, b: jnp.where(finite, a, b), new, old)

                new_params = tuple(
                    jnp.where(finite, p, masters[id(n)])
                    for p, n in zip(new_params, param_nodes))
                new_slots = tuple(keep(s, slots_in[id(n)])
                                  for s, n in zip(new_slots, opt_nodes))
                new_opstate = tuple(
                    keep(s, op_state_in[id(n)])
                    for s, n in zip(new_opstate, stateful_nodes))
                # error-feedback residuals roll back with the params: a
                # rolled-back step must not leave a phantom residual behind
                new_qresid = tuple(
                    jnp.where(finite, a, b)
                    for a, b in zip(new_qresid, qresid_t))
            return outputs, new_params, new_slots, new_opstate, ps_grads, \
                new_qresid, finite, scope_stats

        # HETU_NO_DONATE=1: bisect knob for the bench wedge harness
        # (tools/wedge_bisect.py) — donation changes XLA's buffer
        # assignment, one of the suspects for the bf16 bs>=256 hang.
        # qresid (arg 12) donates like the state it is: the hetuq residuals
        # are full-size param copies, and without donation each step would
        # transiently double their HBM footprint
        donate = ((0, 1, 2, 12) if training and donate_ok
                  and os.environ.get("HETU_NO_DONATE") != "1" else ())
        return jax.jit(step_fn, donate_argnums=donate)

    def _kern_spmd(self) -> bool:
        """Is this subexecutor's program a GSPMD multi-device program? A
        bare pallas_call inside one has no SPMD partitioning rule, so the
        kernel tier's eligibility declines under this scope
        (registry.in_spmd_scope; per-shard shard_map wrapping is the
        documented follow-up in docs/KERNELS.md)."""
        mesh = self.config.mesh
        return mesh is not None and mesh.size > 1

    def profile_summary(self):
        """Per-step host-phase breakdown (HETU_PROFILE=1), or None.

        prestep = feeds/batches/PS pulls staging; dispatch = the jit call
        (enqueue + any blocking transfers); poststep = PS push issue,
        prefetch issue, state bookkeeping; trace_build = tracing+compile.
        Host-side phases only: under async dispatch the device compute wait
        lands wherever the first output is materialized (often the caller's
        ``asnumpy``), so the phases need not sum to wall time per step.
        """
        p = self._profile
        if p is None or p["steps"] == 0:
            return None
        n = p["steps"]
        return {k.replace("_s", "_ms_per_step"): round(v / n * 1000, 3)
                for k, v in p.items() if k != "steps"} | {"steps": n}

    def _record_telemetry(self, tel, step, t0, t_pre, t_c0, t_c1, t_d0,
                          t_d1, t_end, compiled_now, feed_vals, batch_vals,
                          ps_comm_ms=None, ps_pull_ms=None,
                          ps_push_ms=None):
        """Per-step telemetry: phase spans (trace mode), step metrics and
        the JSONL step record; PS server health on its poll cadence. Runs
        only when telemetry is active — the hot path records raw
        ``perf_counter`` stamps and this emits everything post-hoc."""
        ex = self.executor
        step_ms = (t_end - t0) * 1e3
        phases = {"prestep_ms": (t_pre - t0) * 1e3,
                  "dispatch_ms": (t_d1 - t_d0) * 1e3,
                  "poststep_ms": (t_end - t_d1) * 1e3}
        if compiled_now:
            phases["compile_ms"] = (t_c1 - t_c0) * 1e3
        if ps_comm_ms is not None:
            phases["ps_comm_ms"] = ps_comm_ms
        if ps_pull_ms is not None:
            # the two PS legs separately (pull wait in prestep, push in
            # poststep): what hetutrail's critical path decomposes
            phases["ps_pull_ms"] = ps_pull_ms
            phases["ps_push_ms"] = ps_push_ms or 0.0
        self.last_phases = {"step_ms": step_ms, "step": int(step), **phases}
        tracer = tel.tracer
        label = "step" if self.training else "eval"
        if tracer is not None:
            tracer.complete(f"{label}:{self.name}", t0, t_end,
                            args={"step": int(step)})
            tracer.complete("feed", t0, t_pre)
            if compiled_now:
                tracer.complete("compile", t_c0, t_c1)
            # jax.jit compiles lazily: on a compiled_now step the first
            # dispatch below carries the actual XLA trace+compile, so the
            # "compile" span above is only the step-fn build
            tracer.complete("compute", t_d0, t_d1,
                            args={"includes_compile": True}
                            if compiled_now else None)
            tracer.complete("poststep", t_d1, t_end)
        tm = ex._tel_metrics
        if not self.training:
            tm["eval_ms"].observe(step_ms)
            return
        tm["step_ms"].observe(step_ms)
        tm["steps"].inc()
        bs = None
        for v in list(batch_vals) + list(feed_vals):
            shape = getattr(v, "shape", None)
            if shape:
                bs = int(shape[0])
                break
        if bs is None and self.res_dl_nodes:
            bs = self.resident_dl[id(self.res_dl_nodes[0])][1]
        if bs:
            tm["examples"].inc(bs)
        if ps_comm_ms is not None and step_ms > 0:
            # critical-path PS RPC share of the step (staging pulls + push
            # issue). The gauge exists only for PS/Hybrid runs; AllReduce
            # comm lives inside the XLA program — hetuprof --attr separates
            # it offline from the device trace (docs/PROFILING.md).
            tel.metrics.gauge("hetu_comm_fraction").set(
                min(1.0, ps_comm_ms / step_ms))
        # hetutrail critical path (docs/OBSERVABILITY.md pillar 5): the
        # blocking chain per step as hetu_critical_path_ms{leg=...} gauges
        # plus hetu_cp_fraction (dominant leg's share) — the cost-model
        # calibration signal hetuprof's cp_fraction column reads back
        from ..telemetry import trail as _trail_mod
        _trail_mod.export_critical_path(
            tel.metrics, _trail_mod.step_legs(phases),
            cache=self._tel_cp_cache)
        # hetuwatch (pillar 6): judge this step against the adopted plan's
        # stamped prediction on the watch cadence. None when unarmed — the
        # only cost the default run pays is this attribute check.
        # compile steps are excluded (the step_phase_means convention):
        # trace+compile wall time is warm-up, not plan divergence
        pw = ex.plan_watch
        if pw is not None and not compiled_now and step % pw.every == 0:
            self._watch_observe(tel, ex, pw, step, step_ms, phases)
        if compiled_now:
            tm["compiles"].inc()
            # recompile churn counts distinct SHAPE signatures, not the
            # hetuscope cadence/poison variants of the same signature
            if len(self._base_sigs) > 1:
                tm["recompiles"].inc()
            mon = ex._tel_recompile_mon
            if mon is not None:
                for f in mon.check():
                    # signature-churn diagnosis from the existing Tier B
                    # RecompileMonitor, surfaced as a telemetry event
                    tel.event("recompile_budget", sub=self.name,
                              message=f.message)
            cost = self.last_cost_analysis() or {}
            if cost.get("flops"):
                tm["flops"].set(float(cost["flops"]))
            # 6ND companion denominator (docs/ROOFLINE.md): 6·N·tokens,
            # tokens from the first integer-typed 2-D feed (token ids) or
            # the batch size. hetutop shows MFU under BOTH this and the
            # measured cost-analysis flops (which include attention).
            tokens = None
            for v in list(feed_vals) + list(batch_vals):
                shape = getattr(v, "shape", None)
                dt = getattr(v, "dtype", None)
                if shape is not None and len(shape) >= 2 and dt is not None \
                        and jnp.issubdtype(dt, jnp.integer):
                    tokens = int(shape[0]) * int(shape[1])
                    break
            if tokens is None:
                tokens = bs
            if tokens and ex.n_params_total:
                tel.metrics.gauge("hetu_flops_per_step_6nd").set(
                    6.0 * ex.n_params_total * tokens)
            # HBM accounting of the program just compiled, next to the live
            # allocator gauge polled below — predicted vs resident
            mem = self.last_memory_analysis()
            if mem:
                for k, v in mem.items():
                    tel.metrics.gauge(f"hetu_hbm_{k}").set(float(v))
        tel.step_record(self.name, step, step_ms, phases=phases)
        ps = ex.ps_runtime
        if step % self._tel_ps_every == 0:
            live = _device_live_bytes()
            if live is not None:
                tel.metrics.gauge("hetu_hbm_live_bytes").set(live)
        if ps is not None and step % self._tel_ps_every == 0:
            for row in ps.telemetry_stats():
                tel.record(**row)

    # -- hetuwatch (docs/OBSERVABILITY.md pillar 6) -------------------------
    def _watch_observe(self, tel, ex, pw, step, step_ms, phases):
        """One cadence observation of the plan-divergence sentinel: fold
        this step's measured legs into the residual windows, export the
        residual/divergence gauges, stream the kind:"watch" JSONL row
        (what ``hetulint --plan --calibrate`` and ``hetuprof --gate`` read
        back), and route any latched events through the resilience bus.
        Runs on the watch cadence only; never raises — the sentinel must
        not take the step down with it."""
        from ..resilience import _flight_flush, _incident, _tel_event
        from ..telemetry import trail as _trail_mod
        from ..telemetry import watch as _watch_mod
        try:
            if pw.families is None:
                # op-family -> leg identities (the roofline's op_family
                # naming): every traced family executes inside dispatch =
                # the compute leg; PS-staged pulls and gradient pushes own
                # the boundary legs. Built once, on the first observation.
                from ..telemetry.profiler import op_family
                fams = {}
                pull = {id(n) for n in self.ps_staged_ops}
                push = {id(n) for n in self.ps_comm_ops}
                for n in self.topo:
                    if not n.inputs:   # placeholders aren't a family
                        continue
                    leg = ("ps_pull" if id(n) in pull
                           else "ps_push" if id(n) in push else "compute")
                    fams.setdefault(op_family(n.name), leg)
                pw.families = fams
            wv = getattr(getattr(ex, "elastic", None), "world_version",
                         None)
            row, events = pw.observe(step, phases=phases, step_ms=step_ms,
                                     world_version=wv)
            _watch_mod.export_watch(tel.metrics, pw._ewma,
                                    row.get("divergence"),
                                    cache=self._tel_watch_cache)
            tel.record("watch", **row)
            # hetupilot rides the same residual stream the row exports —
            # the controller's measurement windows ARE the watch windows
            pilot = getattr(ex, "pilot", None)
            if pilot is not None:
                pilot.feed_row(row)
            for e in events:
                name = e.pop("name")
                if name == "plan_divergence":
                    # name the blocking server+param via hetutrail's span
                    # join (rare-event path; requires HETU_TRAIL_DIR).
                    # This step's own spans may still be in the native
                    # ring, so fall back one step — the breach is K
                    # windows old by the time the latch fires.
                    trail_dir = _trail_mod.armed()
                    if trail_dir and e.get("leg", "").startswith("ps_"):
                        loaded = _trail_mod.load_dir(trail_dir)
                        joined, _rate = _trail_mod.join_spans(
                            loaded["client"], loaded["server"])
                        for s in (int(step), int(step) - 1):
                            by_server, by_tensor = \
                                _trail_mod._ps_attribution(joined, s,
                                                           tel.rank)
                            if by_server:
                                e["server"] = max(by_server,
                                                  key=by_server.get)
                                if by_tensor:
                                    e["param"] = max(by_tensor,
                                                     key=by_tensor.get)
                                break
                    rec = _watch_mod.recommend(pw.plan, e.get("leg", ""),
                                               e.get("ratio", 0.0))
                    e["recommendation"] = rec["message"]
                    # the bounded plan delta as the suppressible finding
                    # shape hetulint emits (advisory — never actuated here;
                    # the pilot actuates at the NEXT step boundary, inside
                    # the elastic two-phase barrier)
                    tel.record("finding", **rec)
                    if pilot is not None and rec.get("delta") is not None:
                        pilot.feed_recommendation(rec["delta"], dict(e))
                _tel_event(name, sub=self.name, **e)
                if pilot is not None:
                    pilot.feed_event(name, e)
                if name == "slo_breach":
                    # the flight ring holds the steps AROUND the breach —
                    # flush it while they are still in the window
                    _flight_flush(f"slo_breach:{e.get('slo')}")
                    _incident("slo_breach", step=step, slo=e.get("slo"),
                              value=e.get("value"))
        except Exception:  # noqa: BLE001 — sentinel must never kill a step
            pass

    # -- hetuscope helpers --------------------------------------------------
    def _default_poison_scope(self) -> Optional[str]:
        """Target of a ``nan_op@step`` fault with no explicit op name: the
        first computing node in topological order."""
        for n in self.topo:
            if n.inputs and not n.is_optimizer:
                return _op_scope(n)
        return None

    def _host_lr(self) -> Optional[float]:
        """Best-effort host-visible learning rate for the flight record
        (None for purely traced schedules)."""
        for n in self.optimizer_nodes:
            lr = n.optimizer.learning_rate
            try:
                return float(lr.get()) if hasattr(lr, "get") else float(lr)
            except (TypeError, ValueError):
                continue
        return None

    def _flight_cursors(self) -> Optional[dict]:
        """Dataloader positions (host cursors + device-resident cursors)
        for the flight record — with the batch crc32 and the step's RNG
        fold, enough to re-point a replay at the failing batch."""
        out = {}
        for n in self.host_dl_nodes:
            dl = getattr(n, "dataloaders", {}).get(self.name)
            cur = getattr(dl, "_cursor", None)
            if cur is not None:
                out[n.name] = int(cur)
        for n in self.res_dl_nodes:
            out[n.name] = int(self._dl_cursor.get(id(n), 0))
        return out or None

    def _loss_at_trip(self, outputs) -> Optional[float]:
        """The first scalar float eval output (the loss, by convention) as
        a host float — read only on a guard trip, where the step already
        synced on the finite flag."""
        for n, v in zip(self.eval_nodes, outputs):
            if n.is_optimizer:
                continue
            if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating) \
                    and getattr(v, "size", 0) == 1:
                return float(np.asarray(v))
        return None

    def _provenance_replay(self, step, base_key, feed_vals, batch_vals,
                           dl_cursors, res_data, ps_staged_vals,
                           ps_dense_vals, inject_nan, poison_scope):
        """Debug sub-executor for NaN/Inf provenance: re-run the failing
        step bit-identically — the guard's gated commit left params/slots/
        op-state at their pre-step values, the step number re-seeds the
        same RNG fold, and the feed/batch device arrays were not donated —
        through a no-donation stats variant of the same program, then
        localize the first op (topological order) that emitted non-finite
        values. Compile cost is paid once per signature, only after a
        trip."""
        ex = self.executor
        rkey = base_key + (poison_scope,)
        fn = self._replay_compiled.get(rkey)
        if fn is None:
            fn = self._build(introspect_now=True, poison_scope=poison_scope,
                             donate_ok=False)
            self._replay_compiled[rkey] = fn
        params_t = tuple(ex.state["params"][id(n)] for n in ex.param_nodes)
        slots_t = tuple(ex.state["slots"][id(n)]
                        for n in self.optimizer_nodes)
        opstate_t = tuple(ex.state["op_state"][id(n)]
                          for n in self.stateful_nodes)
        args = (params_t, slots_t, opstate_t, ex.rng_root, np.int32(step),
                tuple(feed_vals), tuple(batch_vals), tuple(dl_cursors),
                res_data, tuple(ps_staged_vals), tuple(ps_dense_vals),
                np.bool_(inject_nan),
                tuple(ex.state["qresid"][id(n)] for n in self.qresid_nodes))
        from ..telemetry import scope as _scope
        from ..kernels import registry as _kreg
        with _kreg.active(self.config.kernels, spmd=self._kern_spmd()):
            *_rest, stats_t = fn(*args)
        order, inputs_map, spec = self._scope_meta
        stats = _scope.host_stats(spec, stats_t)
        return _scope.find_culprit(order, inputs_map, stats, step)

    def _lowered(self):
        """Re-lower the latest executed step (hits the compilation cache)."""
        if self._last_call is None:
            return None
        fn, args = self._last_call
        from ..kernels import registry as _kreg
        with _kreg.active(self.config.kernels, spmd=self._kern_spmd()):
            return fn.lower(*args)

    def _executable(self):
        """Compiled executable of the latest executed step, cached per
        jitted program: ``last_cost_analysis``/``last_memory_analysis``/
        ``dump_hlo(stage="optimized")`` used to re-lower + re-look-up the
        compile cache on EVERY query — cache-hitting but not free (a
        whole-program re-trace each time); now one fetch per signature."""
        if self._last_call is None:
            return None
        fn, args = self._last_call
        exe = self._exe_cache.get(id(fn))
        if exe is None:
            from ..kernels import registry as _kreg
            with _kreg.active(self.config.kernels, spmd=self._kern_spmd()):
                exe = fn.lower(*args).compile()
            self._exe_cache[id(fn)] = exe
        return exe

    def last_cost_analysis(self):
        """XLA cost analysis (flops etc.) of the latest executed step, for
        MFU reporting and the Tier B lints (reaches the compilation cache —
        no recompile). Normalized to a dict or None: jax 0.4.x returns a
        single-element LIST wrapping the dict, newer jax the dict itself."""
        try:
            exe = self._executable()
            ca = None if exe is None else exe.cost_analysis()
        except Exception:  # noqa: BLE001 — diagnostics only
            return None
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        return ca if isinstance(ca, dict) else None

    def last_memory_analysis(self) -> Optional[dict]:
        """HBM accounting of the latest executed step program as a plain
        dict (``argument/output/temp/alias/generated_code`` bytes plus the
        derived ``peak_bytes`` = args + out + temp − alias, the same formula
        as the AOT HBM gate in ``__graft_entry__.aot_memory_check``), from
        the same cached compiled handle as :meth:`last_cost_analysis`.
        None when nothing has run or the backend exposes no analysis."""
        try:
            exe = self._executable()
            ma = None if exe is None else exe.memory_analysis()
        except Exception:  # noqa: BLE001 — diagnostics only
            return None
        if ma is None:
            return None
        out = {}
        for field in ("argument_size_in_bytes", "output_size_in_bytes",
                      "temp_size_in_bytes", "alias_size_in_bytes",
                      "generated_code_size_in_bytes"):
            out[field.replace("_size_in_bytes", "_bytes")] = \
                int(getattr(ma, field, 0) or 0)
        out["peak_bytes"] = (out["argument_bytes"] + out["output_bytes"]
                             + out["temp_bytes"] - out["alias_bytes"])
        return out

    def dump_hlo(self, path=None, stage="stablehlo"):
        """The compiled program of the latest executed step as text — the
        whole subexecutor is ONE XLA program, so this is the full fused
        truth of what runs per step (the deep-debug complement to
        graphboard's op-level topo view). ``stage``: "stablehlo" (lowered,
        pre-optimization) or "optimized" (post-XLA-passes HLO, with fusion
        decisions and layouts). Returns the text; also writes it when
        ``path`` is given."""
        if stage not in ("stablehlo", "optimized"):
            raise ValueError(f"stage must be 'stablehlo' or 'optimized', "
                             f"got {stage!r}")
        if stage == "optimized":
            exe = self._executable()
            text = None if exe is None else exe.as_text()
        else:
            lowered = self._lowered()
            text = None if lowered is None else lowered.as_text()
        if text is None:
            return None
        if path is not None:
            with open(path, "w") as f:
                f.write(text)
        return text

    # ------------------------------------------------------------------
    def run(self, feed_dict=None, convert_to_numpy_ret_vals=False,
            eval_node_list=None):
        ex = self.executor
        prof = self._profile  # HETU_PROFILE=1: per-phase wall-time ledger
        tel = ex.telemetry   # None when telemetry is off (the only check)
        intro = ex.introspector if self.introspect else None
        timed = prof is not None or tel is not None or intro is not None
        t_run0 = time.perf_counter() if timed else 0.0
        step = ex.state["step"]
        # resilience supervisor (watchdog beat, host fault injection);
        # training targets only — an eval pass is not a supervised step
        sup = getattr(ex, "supervisor", None) if self.training else None
        if sup is not None:
            sup.pre_step(ex, self, step)
        # hetu-elastic: pending-resize check AFTER fault injection (a
        # ps_join fault proposes the resize this same boundary commits)
        ela = getattr(ex, "elastic", None) if self.training else None
        if ela is not None:
            ela.step_boundary(self, step)
        # hetupilot actuation/verdict point, AFTER the elastic agent's own
        # commit (a pilot barrier must never race a real pending resize).
        # An actuation rebuilds ex.subexecutors: this (stale) instance
        # delegates the step to its replacement, which re-enters this hook
        # idempotently at the same step.
        pil = getattr(ex, "pilot", None) if self.training else None
        if pil is not None:
            pil.step_boundary(self, step)
            fresh = ex.subexecutors.get(self.name)
            if fresh is not None and fresh is not self:
                return fresh.run(
                    feed_dict=feed_dict,
                    convert_to_numpy_ret_vals=convert_to_numpy_ret_vals,
                    eval_node_list=eval_node_list)
        feed_dict = feed_dict or {}
        feed_vals = []
        for node in self.feed_nodes:
            if node not in feed_dict:
                raise ValueError(f"Missing feed for placeholder {node.name!r}")
            feed_vals.append(ex._prepare_input(feed_dict[node],
                                               batch=getattr(node, "batch", True)))
        batch_host = {}
        batch_vals = []
        for n in self.host_dl_nodes:
            hv = n.get_batch(self.name)
            pf = self._dev_prefetch.pop(id(n), None)
            # identity check: get_batch returns the exact peeked object when
            # the prefetch ran, so a hit means the device_put already happened
            dv = pf[1] if pf is not None and pf[0] is hv \
                else ex._prepare_input(hv)
            batch_host[id(n)] = np.asarray(hv)
            batch_vals.append(dv)
        dl_cursors = []
        for n in self.res_dl_nodes:
            cur = self._dl_cursor.get(id(n), 0)
            dl_cursors.append(np.int32(cur))
            self._dl_cursor[id(n)] = cur + 1

        # -- PS pre-step: pull this batch's embedding rows ------------------
        # Lookups are grouped by table: a table feeding several lookup ops
        # (shared CTR embeddings) pulls the UNION of its row indices once,
        # then distributes rows to each lookup — one RPC instead of k.
        ps = ex.ps_runtime
        ps_timed = timed and ps is not None
        t_ps0 = time.perf_counter() if ps_timed else 0.0
        staged_idx: dict[int, np.ndarray] = {}
        staged_rows: dict[int, np.ndarray] = {}
        for tid, ops in self._staged_by_table.items():
            p = ps.params[tid]
            for op in ops:
                staged_idx[id(op)] = self._host_value(op.inputs[1], feed_dict,
                                                      batch_host)
            if len(ops) == 1:
                op = ops[0]
                idx = staged_idx[id(op)]
                rows = (ps.take_prefetched(id(op), idx)
                        if ps.async_enabled else None)
                if rows is None:
                    rows = ps.stage_lookup(p, idx)
                staged_rows[id(op)] = rows
            else:
                flat = [np.ascontiguousarray(staged_idx[id(op)],
                                             np.int64).ravel() for op in ops]
                union = np.unique(np.concatenate(flat))
                # union prefetch (keyed by table): issued post-step from the
                # peeked next batches, consumed here when they match
                urows = (ps.take_prefetched(tid, union)
                         if ps.async_enabled else None)
                if urows is None:
                    urows = ps.stage_lookup(p, union)      # (U, *tail)
                tail = tuple(p.shape[1:])
                for op, f in zip(ops, flat):
                    pos = np.searchsorted(union, f)
                    staged_rows[id(op)] = urows[pos].reshape(
                        tuple(np.shape(staged_idx[id(op)])) + tail)
        ps_staged_vals = [ex._prepare_input(staged_rows[id(op)])
                          for op in self.ps_staged_ops]
        ps_dense_vals = []
        for n in self.ps_dense_vars:
            p = ps.params[id(n)]
            ps.wait_dense(p)   # async DDPushPull updates host_value
            ps_dense_vals.append(ex._prepare_input(p.host_value, batch=False))
        # pull-wait vs push legs tracked separately: hetutrail's critical
        # path needs to know WHICH PS leg blocked, not just the total
        ps_pull_s = (time.perf_counter() - t_ps0) if ps_timed else 0.0
        ps_comm_s = ps_pull_s

        t_pre = time.perf_counter() if timed else 0.0
        if prof is not None:
            prof["prestep_s"] += t_pre - t_run0

        # hetuscope: cadence-gated stats variant + nan_op fault poisoning.
        # Variants key the compile cache alongside the shape signature;
        # _base_sigs keeps recompile accounting blind to them.
        introspect_now = intro is not None and step % intro.cadence == 0
        poison_scope = None
        if sup is not None and hasattr(sup, "poison_op"):
            p = sup.poison_op(step)
            if p is not None:
                poison_scope = p or self._default_poison_scope()

        base_key = self._signature(feed_vals, batch_vals) + (
            tuple(tuple(v.shape) for v in ps_staged_vals),)
        key = base_key + (introspect_now, poison_scope)
        fn = self._compiled.get(key)
        compiled_now = fn is None
        t_c0 = t_c1 = t_pre
        if fn is None:
            t_c0 = time.perf_counter() if timed else 0.0
            fn = self._build(introspect_now=introspect_now,
                             poison_scope=poison_scope)
            self._compiled[key] = fn
            t_c1 = time.perf_counter() if timed else 0.0
            if prof is not None:
                prof["trace_build_s"] += t_c1 - t_c0
        self._base_sigs.add(base_key)

        params_t = tuple(ex.state["params"][id(n)] for n in ex.param_nodes)
        slots_t = tuple(ex.state["slots"][id(n)] for n in self.optimizer_nodes)
        opstate_t = tuple(ex.state["op_state"][id(n)] for n in self.stateful_nodes)
        qresid_t = tuple(ex.state["qresid"][id(n)] for n in self.qresid_nodes)

        res_data = tuple(self.resident_dl[id(n)][0]
                         for n in self.res_dl_nodes)
        inject_nan = bool(self.anomaly_guard and sup is not None
                          and sup.inject_nan(step))
        args = (params_t, slots_t, opstate_t, ex.rng_root, np.int32(step),
                tuple(feed_vals), tuple(batch_vals), tuple(dl_cursors),
                res_data, tuple(ps_staged_vals), tuple(ps_dense_vals),
                np.bool_(inject_nan), qresid_t)
        self._last_call = (fn, args)
        if tel is not None and tel.xla_window is not None and self.training:
            # env-gated deep dive: HETU_XLA_TRACE=dir[:start[:n]] opens a
            # bounded jax.profiler window around the configured steps
            tel.xla_window.on_step(step)
        t_d0 = time.perf_counter() if timed else 0.0
        # hetukern: scope the kernel dispatch mode around the call — jit
        # traces lazily, so the trace (where dispatch decisions live) runs
        # under this scope; on cache-hit steps the context is a ~µs no-op
        from ..kernels import registry as _kreg
        if tel is not None and tel.tracer is not None:
            # named step regions in the device timeline when a jax profiler
            # trace is active (the XLA window above, or an external capture)
            with _XW.step_annotation(step), \
                    _kreg.active(self.config.kernels,
                                 spmd=self._kern_spmd()):
                outputs, new_params, new_slots, new_opstate, ps_grads, \
                    qresid_out, finite_t, scope_stats_t = fn(*args)
        else:
            with _kreg.active(self.config.kernels, spmd=self._kern_spmd()):
                outputs, new_params, new_slots, new_opstate, ps_grads, \
                    qresid_out, finite_t, scope_stats_t = fn(*args)
        t_d1 = time.perf_counter() if timed else 0.0
        if prof is not None:
            prof["dispatch_s"] += t_d1 - t_d0

        # -- device-side input prefetch: enqueue batch N+1's device_put now,
        # so its H2D transfer overlaps this step's compute (the reference's
        # 3-deep pinned ring + h2d stream, dataloader.py:26-55)
        for n in self.host_dl_nodes:
            if hasattr(n, "peek_batch"):
                nxt = n.peek_batch(self.name)
                self._dev_prefetch[id(n)] = (nxt, ex._prepare_input(nxt))

        # -- PS post-step: push gradients (reference push/pull, ASP/BSP) ----
        t_pu0 = time.perf_counter() if ps_timed else 0.0
        if ps is not None and ps.async_enabled:
            # async push: the device sync (np.asarray) happens on the push
            # thread, off the critical path
            items = []
            for op, grad in zip(self.ps_comm_ops, ps_grads):
                p = ps.params[id(op.ps_param_node)]
                idx = self._push_idx(op, staged_idx)
                items.append((p, grad, idx))
            if items:
                ps.push_grads_async(items, step)
            # prefetch pulls for batch N+1 (dataloader-fed lookups only):
            # issued now, so under ASP they overlap this step's compute and
            # its pushes — the reference's prefetch-stream semantics.
            # Single-lookup tables prefetch per op; a shared table
            # prefetches the UNION of its peeked next batches (keyed by
            # table id, matching the union pull in the pre-step).
            for tid, ops in self._staged_by_table.items():
                idx_nodes = [op.inputs[1] for op in ops]
                if not all(n in self.dataloader_nodes
                           and hasattr(n, "peek_batch") for n in idx_nodes):
                    continue
                if len(ops) == 1:
                    ps.prefetch_lookup(
                        id(ops[0]), ps.params[tid],
                        np.asarray(idx_nodes[0].peek_batch(self.name)))
                else:
                    nxt = np.unique(np.concatenate(
                        [np.ascontiguousarray(
                            np.asarray(n.peek_batch(self.name)),
                            np.int64).ravel() for n in idx_nodes]))
                    ps.prefetch_lookup(tid, ps.params[tid], nxt)
        else:
            for op, grad in zip(self.ps_comm_ops, ps_grads):
                p = ps.params[id(op.ps_param_node)]
                idx = self._push_idx(op, staged_idx)
                ps.push_grad(p, grad, idx, step=step)
        ps_push_s = 0.0
        if ps_timed:
            ps_push_s = time.perf_counter() - t_pu0
            ps_comm_s += ps_push_s

        if self.training:
            for node, val in zip(ex.param_nodes, new_params):
                ex.state["params"][id(node)] = val
            for node, val in zip(self.optimizer_nodes, new_slots):
                ex.state["slots"][id(node)] = val
            for node, val in zip(self.stateful_nodes, new_opstate):
                ex.state["op_state"][id(node)] = val
            for node, val in zip(self.qresid_nodes, qresid_out):
                ex.state["qresid"][id(node)] = val
            ex.state["step"] = step + 1

        finite = True
        if self.anomaly_guard:
            # materializing the scalar syncs on the step — the documented
            # cost of the guard (callers reading the loss sync anyway)
            finite = bool(np.asarray(finite_t))
            if finite:
                ex.state["anomaly_streak"] = 0
            else:
                ex.state["anomaly_streak"] += 1
                ex.state["anomaly_total"] += 1
                if tel is not None:
                    ex._tel_metrics["anomalies"].inc()
            ex.state["last_step_finite"] = finite

        # -- hetuscope: stats fetch, flight record, NaN/Inf provenance ------
        prov = None
        if intro is not None:
            from ..telemetry import scope as _scope
            stats_host = None
            if self.anomaly_guard and not finite:
                if introspect_now:
                    # the failing step WAS a stats step, and the guard's
                    # finite check already synced it: its own packed table
                    # localizes the culprit, no replay needed
                    stats_host = _scope.host_stats(self._scope_meta[2],
                                                   scope_stats_t)
                    order, inputs_map = self._scope_meta[:2]
                    prov = _scope.find_culprit(order, inputs_map,
                                               stats_host, step)
                else:
                    prov = self._provenance_replay(
                        step, base_key, feed_vals, batch_vals, dl_cursors,
                        res_data, ps_staged_vals, ps_dense_vals, inject_nan,
                        poison_scope)
            rec = {"sub": self.name, "step": int(step),
                   "step_ms": round((time.perf_counter() - t_run0) * 1e3, 4),
                   "finite": bool(finite), "seed": int(self.config.seed),
                   "lr": self._host_lr(),
                   "batch_crc32": _flight_crc(feed_dict, batch_host),
                   "cursors": self._flight_cursors()}
            intro.record_step(rec, stats=stats_host)
            if introspect_now and stats_host is None:
                # DEFER the cadence fetch: materializing the packed vector
                # now would block on this step's compute and stall the
                # dispatch pipeline (measured: the stall, not the fused
                # reductions, dominated the overhead). It resolves at the
                # next step boundary / flush / first read, mutating the
                # ring record in place and exporting the hetu_scope_*
                # gauges + scope JSONL row then.
                def _resolve(vec=scope_stats_t, spec=self._scope_meta[2],
                             name=self.name, s=int(step), tel=tel,
                             intro=intro):
                    stats = _scope.host_stats(spec, vec)
                    if tel is not None:
                        intro.export(tel, name, s, stats)
                    return stats

                intro.defer(rec, _resolve)
            elif tel is not None and stats_host is not None:
                intro.export(tel, self.name, step, stats_host)
            if prov is not None:
                intro.on_anomaly(prov, telemetry=tel)

        t_end = time.perf_counter() if timed else 0.0
        if prof is not None:
            prof["poststep_s"] += t_end - t_d1
            prof["steps"] += 1
        # hetutrail step boundary: drain this step's client RPC spans and
        # advance the span step stamp (None writer when off — one check)
        if ps is not None and self.training \
                and ps.trail_writer is not None:
            ps.trail_step_boundary(step)
        if tel is not None:
            # recorded BEFORE supervisor post-step: an emergency flush on
            # the preemption path must already contain this step's record
            self._record_telemetry(
                tel, step, t_run0, t_pre, t_c0, t_c1, t_d0, t_d1, t_end,
                compiled_now, feed_vals, batch_vals,
                ps_comm_ms=ps_comm_s * 1e3 if ps_timed else None,
                ps_pull_ms=ps_pull_s * 1e3 if ps_timed else None,
                ps_push_ms=ps_push_s * 1e3 if ps_timed else None)

        # post-step supervision LAST: a rollback rewrites ex.state, an
        # emergency save captures it, and Preempted aborts the return — all
        # only valid after the commit above. On a trip the anomaly event
        # carries the headline numbers (loss at trip; global grad norm when
        # provenance ran) so post-mortems don't need the flight recorder
        # for them.
        if sup is not None:
            extra = {}
            if self.anomaly_guard and not finite:
                # the provenance stats already carry the at-trip loss —
                # reuse them; the extra device fetch is only for guard-
                # without-introspection runs
                loss_v = prov.get("loss") if prov is not None else None
                extra["loss"] = (loss_v if loss_v is not None
                                 else self._loss_at_trip(outputs))
                if prov is not None:
                    extra["grad_norm"] = prov.get("grad_norm")
            sup.post_step(ex, self, step, finite=finite, **extra)

        results = []
        wanted = eval_node_list if eval_node_list is not None else self.eval_nodes
        out_by_node = {id(n): v for n, v in zip(self.eval_nodes, outputs)}
        for node in wanted:
            if node.is_optimizer:
                results.append(None)
            else:
                if id(node) not in out_by_node:
                    raise ValueError(
                        f"Node {node.name!r} is not among subexecutor "
                        f"{self.name!r}'s eval nodes; include it in the "
                        "eval_node_dict at Executor construction")
                v = out_by_node[id(node)]
                results.append(np.asarray(v) if convert_to_numpy_ret_vals
                               else NDArray(v))
        return results


class Executor:
    """User-facing executor (reference executor.py:301)."""

    def __init__(self, eval_node_dict, ctx=None, seed=None, comm_mode=None,
                 config=None, lint=None, **kwargs):
        if isinstance(eval_node_dict, (list, tuple)):
            eval_node_dict = {"default": list(eval_node_dict)}
        self.eval_node_dict = {k: list(v) for k, v in eval_node_dict.items()}
        all_nodes = [n for nodes in self.eval_node_dict.values() for n in nodes]
        if config is None:
            config = HetuConfig(eval_node_list=all_nodes, ctx=ctx, seed=seed,
                                comm_mode=comm_mode, **kwargs)
        self.config = config
        # -- hetuplan adoption (docs/ANALYSIS.md "Tier C: planning") --------
        # Runs BEFORE comm-op insertion so the adopted comm_mode drives the
        # same strategy rewrite a hand-declared one would. The planner only
        # fills fields the config left unset; a declared comm_mode is never
        # overridden (the plan-divergence lint reports the conflict).
        self.plan = None
        if getattr(config, "plan", None) is not None:
            from ..analysis.planner import Plan as _Plan, plan_graph
            if isinstance(config.plan, _Plan):
                self.plan = config.plan
            else:
                n_dev = (config.mesh.size if config.mesh is not None
                         else max(1, len(jax.devices())))
                self.plan = plan_graph(self.eval_node_dict, config=config,
                                       devices=n_dev)
            self.plan.apply(config)
        self.comm_mode = config.comm_mode

        # -- telemetry activation (docs/OBSERVABILITY.md) -------------------
        # Activated BEFORE the PS runtime spawns so its pull/push streams can
        # cache the handle. When off, self.telemetry is None and every
        # instrumented point in SubExecutor.run short-circuits on that one
        # None check — no timestamps, no allocations.
        from .. import telemetry as _tel_pkg
        self.telemetry = _tel_pkg.activate(config.telemetry)
        self._tel_metrics = None
        self._tel_recompile_mon = None
        if self.telemetry is not None:
            reg = self.telemetry.metrics
            self._tel_metrics = {
                "step_ms": reg.histogram("hetu_step_time_ms"),
                "eval_ms": reg.histogram("hetu_eval_time_ms"),
                "steps": reg.counter("hetu_steps_total"),
                "examples": reg.counter("hetu_examples_total"),
                "compiles": reg.counter("hetu_compiles_total"),
                "recompiles": reg.counter("hetu_recompiles_total"),
                "anomalies": reg.counter("hetu_anomaly_trips_total"),
                "flops": reg.gauge("hetu_flops_per_step"),
            }
            from ..analysis.lowered import RecompileMonitor
            self._tel_recompile_mon = RecompileMonitor(
                self, budget=int(os.environ.get("HETU_RECOMPILE_BUDGET",
                                                "3")))
            try:
                device_kind = str(jax.devices()[0].device_kind)
            except Exception:  # noqa: BLE001 — identity is best-effort
                device_kind = "unknown"
            # the peak is an ASSUMPTION (docs/ROOFLINE.md): record it next
            # to the device so every MFU number downstream is auditable
            self.telemetry.record(
                "run_info", device_kind=device_kind,
                peak_tflops_assumed=float(
                    os.environ.get("HETU_PEAK_TFLOPS", "197")),
                comm_mode=str(config.comm_mode))

        # -- numeric-health introspection (hetuscope) -----------------------
        # Armed by HetuConfig(introspect=...) / HETU_INTROSPECT; None when
        # off, and every scope point in SubExecutor.run gates on that one
        # None check. The flight recorder shares the telemetry directory
        # (flight/ subdir) so bin/hetuscope reads one place post-mortem.
        self.introspector = None
        if config.introspect:
            from ..telemetry import scope as _scope
            scope_dir = (self.telemetry.dir if self.telemetry is not None
                         else os.environ.get("HETU_TELEMETRY_DIR",
                                             "hetu_telemetry"))
            self.introspector = _scope.Introspector(config.introspect,
                                                    scope_dir)

        # -- hetuwatch: plan stamp + divergence sentinel (pillar 6) ---------
        # The adopted plan's per-leg prediction is stamped into telemetry
        # unconditionally (one kind:"plan" record — the judge's denominator
        # and the run's layout provenance, which heturun's run_summary and
        # hetulint --calibrate both read back). The live sentinel arms only
        # when the watch cadence is set AND there is something to judge: a
        # plan to diverge from, or SLO budgets to enforce. Off, plan_watch
        # is None and the step-boundary hook is one attribute check.
        self.plan_watch = None
        if self.telemetry is not None:
            from ..telemetry import watch as _watch_mod
            plan_dict = None
            if self.plan is not None:
                plan_dict = self.plan.as_dict()
                self.telemetry.record(
                    "plan", **_watch_mod.stamp_fields(plan_dict))
            if config.watch and (plan_dict is not None or config.slo):
                self.plan_watch = _watch_mod.PlanWatch(
                    predicted=(_watch_mod.predicted_legs(
                        plan_dict.get("breakdown") or {})
                        if plan_dict is not None else None),
                    predicted_step_ms=(plan_dict or {}).get(
                        "predicted_step_ms"),
                    every=config.watch,
                    window=int(os.environ.get(
                        "HETU_WATCH_WINDOW",
                        str(_watch_mod.DEFAULT_WINDOW))),
                    k=int(os.environ.get("HETU_WATCH_K",
                                         str(_watch_mod.DEFAULT_K))),
                    ratio=float(os.environ.get(
                        "HETU_WATCH_RATIO", str(_watch_mod.DEFAULT_RATIO))),
                    min_ms=float(os.environ.get(
                        "HETU_WATCH_MIN_MS",
                        str(_watch_mod.DEFAULT_MIN_MS))),
                    slo=config.slo, plan=plan_dict)

        full_topo = find_topo_sort(all_nodes)
        # any variable read through an embedding lookup is a sparse embedding
        # for comm-strategy purposes (keeps insert_comm_ops and PSRuntime's
        # classification in agreement)
        if config.comm_mode in ("PS", "Hybrid"):
            for node in full_topo:
                embed = getattr(node, "embed_node", None)
                if embed is not None and getattr(embed, "trainable", False):
                    embed.is_embed = True
        # comm-op insertion (the reference's OptimizerOp.backward_hook,
        # optimizer.py:125-139) — rewrite optimizer grad inputs per strategy.
        for node in full_topo:
            if node.is_optimizer:
                node.insert_comm_ops(config)
        full_topo = find_topo_sort(all_nodes)

        # hetukern rows-mode reset: graph nodes are shared between
        # executors (the comm_quant re-assert idiom) — a grad op a
        # PREVIOUS executor flipped to rows mode must come back dense
        # BEFORE lint runs and before this build's own PS wiring
        # re-flips eligible ops; likewise a push op's ps_param_node /
        # staged_lookups from a previous wiring must not survive into a
        # build whose conditions no longer hold (a stale ps_param_node
        # would enroll the push in ps_comm_ops with a dense grad and no
        # indices).
        from .ops.ps import ParameterServerCommunicateOp as _PSPush
        for node in full_topo:
            if getattr(node, "rows_mode", False):
                node.to_dense()
            if isinstance(node, _PSPush):
                node.ps_param_node = None
                node.staged_lookups = None

        # -- define-time validation (hetulint Tier A, docs/ANALYSIS.md) -----
        # Runs over the post-comm-insertion graph — the graph that will
        # actually trace — and BEFORE any PS server spawns or parameter
        # materializes, so an invalid graph fails fast with op-level
        # provenance instead of a deep jit traceback at run time.
        self._lint(lint)

        # -- PS/Hybrid runtime (reference ParameterServerCommunicate.py) ----
        self.ps_runtime = None
        if config.comm_mode in ("PS", "Hybrid"):
            from .ps_runtime import PSRuntime
            self.ps_runtime = PSRuntime(config, full_topo)
            self._rewire_ps_gradients(full_topo)

        ps_resident = (set(self.ps_runtime.params.keys())
                       if self.ps_runtime else set())
        self.param_nodes = [n for n in full_topo
                            if n.is_placeholder and not getattr(n, "is_feed", True)
                            and id(n) not in ps_resident]
        self.rng_root = jax.random.PRNGKey(config.seed)

        # -- tensor-parallel parameter shardings ----------------------------
        # a dispatch marker directly on a trainable Variable pins that
        # parameter's layout for its whole lifetime (init, updates, ckpt) —
        # the weight is *stored* split over the model axis, never gathered
        if config.mesh is not None \
                and config.mp_axis in config.mesh.axis_names:
            for node in full_topo:
                if isinstance(node, DispatchOp) \
                        and getattr(node.inputs[0], "trainable", False):
                    config.param_specs[id(node.inputs[0])] = \
                        node.partition_spec(config.mesh, config.dp_axis,
                                            config.mp_axis)

        # -- parameter initialization (reference initializers.py) ----------
        params = {}
        for i, node in enumerate(self.param_nodes):
            init_rng = jax.random.fold_in(self.rng_root, 2**20 + i)
            value = node.instantiate(init_rng)
            value = jnp.asarray(value, dtype=node.dtype)
            if config.mesh is not None:
                spec = config.param_specs.get(id(node), P())
                value = jax.device_put(value, NamedSharding(config.mesh, spec))
            elif config.device is not None:
                value = jax.device_put(value, config.device)
            params[id(node)] = value
            config.placeholder_to_arr_map[node] = value

        # -- hetuq: quantized DP AllReduce eligibility (docs/COMM_QUANT.md) -
        # Marks the AllReduce ops whose gradient sync the policy compresses:
        # device-resident f32 params at/above the size threshold (or force-
        # listed), pure-DP only — tp-sharded params keep the exact path, as
        # does everything when comm_quant="off" (the marked-op check in
        # TraceContext.allreduce is the single branch point, so off mode is
        # bit-identical to pre-hetuq behavior). Error-feedback residuals are
        # executor state, committed/rolled back like optimizer slots.
        qpol = config.comm_quant_policy
        self.qar_ops = []
        qresid = {}
        for node in full_topo:
            if not isinstance(node, AllReduceCommunicateOp):
                continue
            # ALWAYS reset first: graph nodes are shared between executors
            # (A/B legs reuse a built graph), and a stale mark from a
            # previous quantized executor must never leak into this one —
            # off mode re-asserts the exact path on every node
            node.comm_quant = False
            if not qpol.active or config.mesh is None:
                continue
            pn = node.param_node
            val = params.get(id(pn)) if pn is not None else None
            if val is None or id(pn) in config.param_specs:
                continue
            if not jnp.issubdtype(val.dtype, jnp.floating):
                continue
            if qpol.applies(pn, int(np.prod(val.shape))):
                node.comm_quant = True
                self.qar_ops.append(node)
                if qpol.error_feedback:
                    qresid[id(node)] = jnp.zeros_like(
                        val, dtype=jnp.float32)
        self.comm_quant_report = None
        if self.qar_ops:
            from .. import comm_quant as _cq
            sizes = {n.param_node.name: int(np.prod(params[id(n.param_node)].shape))
                     for n in self.qar_ops}
            self.comm_quant_report = _cq.allreduce_wire_report(
                sizes, qpol, config.dp_size)
            if self.telemetry is not None:
                g = self.telemetry.metrics.gauge
                g("hetu_comm_quant_raw_bytes").set(
                    float(self.comm_quant_report["raw_bytes"]))
                g("hetu_comm_quant_wire_bytes").set(
                    float(self.comm_quant_report["wire_bytes"]))

        slots = {}
        op_state = {}
        for node in full_topo:
            if node.is_optimizer:
                # PS-resident params keep their optimizer state server-side
                slots[id(node)] = node.init_slots(
                    {id(v): params[id(v)] for v in node.vars
                     if id(v) in params})
            if node.stateful:
                op_state[id(node)] = jax.tree.map(jnp.asarray, node.state_init())
        self.state = {"params": params, "slots": slots, "op_state": op_state,
                      "qresid": qresid, "step": 0,
                      # resilience counters (anomaly_guard):
                      "anomaly_streak": 0, "anomaly_total": 0,
                      "last_step_finite": True}
        # total trainable parameter count — the N in the 6ND MFU denominator
        # (docs/ROOFLINE.md). PS-resident tables count too: their lookup/
        # update flops run per step even though the arrays live server-side.
        self.n_params_total = sum(
            int(np.prod(v.shape)) for v in params.values())
        if self.ps_runtime is not None:
            self.n_params_total += sum(
                int(np.prod(p.shape))
                for p in self.ps_runtime.params.values())
        if self.telemetry is not None:
            self.telemetry.metrics.gauge("hetu_params_total").set(
                float(self.n_params_total))
        # resilience.Supervisor hook point (attach_supervisor)
        self.supervisor = None
        # hetu-elastic membership agent (docs/FAULT_TOLERANCE.md "Elastic
        # membership"): armed below for PS/Hybrid runs under HETU_ELASTIC;
        # None otherwise — SubExecutor.run pays one None check per step
        self.elastic = None
        # hetupilot self-tuning controller (docs/FAULT_TOLERANCE.md
        # "Self-tuning with guardrails"): armed below for PS/Hybrid runs
        # under HETU_PILOT when the plan-divergence sentinel is watching
        self.pilot = None

        self.subexecutors = {}
        for name, nodes in self.eval_node_dict.items():
            if config.gpipe:
                # every target pipelines (forward-only for validation
                # entries): params commit to per-stage devices, so a plain
                # single-device SubExecutor could not touch them anyway
                from .gpipe import SubExecutor4Gpipe
                self.subexecutors[name] = SubExecutor4Gpipe(name, nodes, self)
            else:
                self.subexecutors[name] = SubExecutor(name, nodes, self)

        if self.ps_runtime is not None:
            from ..resilience import env_truthy
            if env_truthy("HETU_ELASTIC"):
                from ..elastic import ElasticAgent
                self.elastic = ElasticAgent.from_env(self)
                # after subexecutors exist: a late joiner's bootstrap
                # re-partitions their dataloaders from the world log
                self.elastic.bootstrap()
            # heturun --restore (docs/FAULT_TOLERANCE.md "Coordinated job
            # snapshots"): re-impose this rank's persisted state from the
            # newest committed job epoch and verify the update-counter
            # algebra against the manifest BEFORE any training step runs
            restore_dir = os.environ.get("HETU_RESTORE_DIR", "")
            if restore_dir:
                from ..recovery import restore_executor_from_env
                restore_executor_from_env(self, restore_dir)
            # hetupilot (heturun --pilot / HETU_PILOT=1): acts on the
            # sentinel's recommendations, so it needs the sentinel — armed
            # AFTER any restore so interrupted-era sealing sees the state
            # the run will actually continue from
            if env_truthy("HETU_PILOT"):
                if self.plan_watch is not None:
                    from ..pilot import Pilot
                    self.pilot = Pilot.from_env(self)
                else:
                    import sys as _sys
                    print("# hetupilot: HETU_PILOT set but the plan watch "
                          "is not armed (need HETU_WATCH plus an adopted "
                          "plan or SLO) — controller disabled",
                          file=_sys.stderr, flush=True)

    # ------------------------------------------------------------------
    def _lint(self, lint):
        """Tier A graph validation at build: ``lint`` is "error" (raise
        ``GraphValidationError`` on error-severity findings), "warn" (report
        everything as warnings, build anyway) or "off". Defaults to the
        ``HETU_LINT`` env var, else off."""
        if lint is None:
            lint = os.environ.get("HETU_LINT", "off") or "off"
        if lint == "off":
            return
        if lint not in ("error", "warn"):
            raise ValueError(
                f"lint must be 'error', 'warn' or 'off', got {lint!r}")
        from ..analysis import (GraphAnalyzer, GraphValidationError,
                                format_findings, ERROR)
        findings = GraphAnalyzer(self.eval_node_dict,
                                 config=self.config).run()
        if not findings:
            return
        errors = [f for f in findings if f.severity == ERROR]
        if errors and lint == "error":
            raise GraphValidationError(findings)
        import warnings
        warnings.warn(
            f"hetulint: {len(findings)} finding(s) on this graph:\n"
            + format_findings(findings), stacklevel=3)

    def _rewire_ps_gradients(self, topo):
        """Point each PS comm op's gradient at the lookup OUTPUT rather than
        the table variable, so the traced grad is (batch_rows, width) instead
        of a full-table scatter (the reference's IndexedSlices analogue)."""
        loss_topo_ids: dict[int, set] = {}  # per-loss memo for this pass
        ps_by_name = {p.node.name: p for p in self.ps_runtime.params.values()}
        consumers: dict[int, list] = {}
        for n in topo:
            for i in n.inputs:
                consumers.setdefault(id(i), []).append(n)
        eval_ids = {id(n) for ns in self.eval_node_dict.values() for n in ns}
        for node in topo:
            if not isinstance(node, ParameterServerCommunicateOp):
                continue
            grad_node = node.inputs[0]
            if not getattr(grad_node, "is_gradient", False):
                # hetukern satellite (docs/KERNELS.md): an explicit
                # embedding_lookup_gradient_op whose ONLY consumer is this
                # PS push flips into ROWS mode — the rows leave the device
                # anyway, so the (vocab, dim) zeros-table scatter the dense
                # form pays is pure waste on this route. The runtime trims
                # the sentinel tail and pushes (rows, grads) directly.
                # Another consumer (or the op itself as an eval target)
                # needs the dense table shape, so the op stays dense then.
                # Structural preconditions shared with hetulint's
                # ps-push-ignored mirror (embed_grad_push_routable) so the
                # lint and this rewire cannot drift.
                from .ops.embedding import embed_grad_push_routable
                if embed_grad_push_routable(node, grad_node, consumers,
                                            eval_ids) \
                        and node.ps_id in ps_by_name:
                    p = ps_by_name[node.ps_id]
                    if p.sparse and tuple(grad_node.embed_shape) == p.shape:
                        grad_node.to_rows()
                        node.ps_param_node = p.node
                continue
            var = grad_node.x
            p = self.ps_runtime.params.get(id(var))
            if p is None:
                continue
            node.ps_param_node = var
            if not p.sparse:
                continue  # dense PS params are fed whole; grad wrt var is fine
            # Scope to lookups on THIS gradient's loss graph: the table may
            # also feed other eval targets (a validate head with its own
            # lookup node) whose rows are staged by their own subexecutor and
            # never produce gradients. Inference-only sparse pulls are not
            # differentiation targets either (their zero grads would corrupt
            # stateful server-optimizer rows).
            loss = grad_node.gctx.loss
            loss_ids = loss_topo_ids.get(id(loss))
            if loss_ids is None:
                loss_ids = {id(n) for n in find_topo_sort([loss])}
                loss_topo_ids[id(loss)] = loss_ids
            lookups = [lk for lk in p.lookup_ops
                       if id(lk) in loss_ids
                       and not isinstance(lk, ParameterServerSparsePullOp)]
            if not lookups:
                raise ValueError(
                    f"PS-hosted embedding {var.name!r} has a gradient but no "
                    "lookup op reads it on the loss graph — sparse PS tables "
                    "are only trainable through embedding_lookup_op")
            node.staged_lookups = lookups
            xs = grad_node.gctx.xs
            if len(lookups) == 1:
                lookup = lookups[0]
                grad_node.x = lookup
                grad_node.inputs = [grad_node.gctx.loss, lookup]
                for i, x in enumerate(xs):
                    if x is var:
                        xs[i] = lookup
            else:
                # one table, k lookups (the reference accumulates the grads
                # as IndexedSlices, optimizer.py:64-82): differentiate wrt
                # EACH lookup output; the push path concatenates the per-
                # lookup (rows, width) grads and dedup-sums before the RPC
                grad_node.x = lookups[0]
                grad_node.multi_x = lookups
                grad_node.inputs = [grad_node.gctx.loss] + lookups
                for i, x in enumerate(xs):
                    if x is var:
                        xs[i] = lookups[0]
                for lk in lookups[1:]:
                    if all(x is not lk for x in xs):
                        xs.append(lk)

    def _prepare_input(self, value, batch=True):
        """Stage one host value onto the device/mesh.

        ``batch`` says whether dim 0 is a batch dimension to shard over the
        dp axis (feeds/dataloader batches: yes by default, overridable per
        placeholder via ``ht.Variable(..., batch=False)``; whole parameters:
        no). An earlier divisibility heuristic sharded any conveniently-
        shaped feed, silently corrupting non-batch inputs.
        """
        if isinstance(value, NDArray):
            value = value.handle
        if isinstance(value, ND_Sparse_Array):
            return SparseValue(value.data, value.row, value.col,
                               value.nrow, value.ncol)
        arr = np.asarray(value)
        if arr.dtype == np.float64:
            arr = arr.astype(np.float32)
        mesh = self.config.mesh
        if mesh is not None:
            dp = self.config.dp_size
            if batch and arr.ndim >= 1 and dp > 1:
                if arr.shape[0] % dp == 0:
                    return jax.device_put(
                        arr, NamedSharding(mesh, P(self.config.dp_axis)))
                import warnings
                warnings.warn(
                    f"batch dim {arr.shape[0]} is not divisible by dp={dp}: "
                    "the feed is REPLICATED across the dp axis instead of "
                    "sharded (correct but slow) — pad the batch or use "
                    "drop_last", stacklevel=3)
            return jax.device_put(arr, NamedSharding(mesh, P()))
        if self.config.device is not None:
            return jax.device_put(arr, self.config.device)
        return jnp.asarray(arr)

    def remesh(self, new_mesh) -> dict:
        """hetu-elastic leg 2: LIVE dp re-mesh — rebuild the device world
        mid-run without losing a step. State round-trips through the
        existing checkpoint capture/restore machinery
        (``resilience.capture_executor_state`` — no new serialization
        format): params, optimizer slots, op state, and hetuq
        error-feedback residuals are captured to host, re-placed under the
        new mesh's shardings, and every compiled step program is
        invalidated (the shardings changed, so the old executables are
        wrong, not just stale). The step counter, RNG folds, and
        dataloader cursors survive, so training continues exactly where it
        left off — ``tests/test_elastic_executor.py`` pins loss parity
        against an uninterrupted run.

        Pure data-parallel meshes only: dispatch-pinned (tensor-parallel)
        parameter storage re-shards are not yet supported."""
        cfg = self.config
        if not isinstance(new_mesh, Mesh):
            raise ValueError(
                f"new_mesh must be a jax.sharding.Mesh, got "
                f"{type(new_mesh).__name__}")
        if cfg.gpipe:
            raise NotImplementedError(
                "remesh is not supported under gpipe: the pipeline "
                "executor owns per-stage placement")
        if cfg.mp_axis in new_mesh.axis_names or cfg.param_specs or (
                cfg.mesh is not None
                and cfg.mp_axis in cfg.mesh.axis_names):
            raise NotImplementedError(
                "remesh supports pure data-parallel meshes; model-parallel "
                "(dispatch-pinned) parameter storage does not re-shard yet")
        t0 = time.perf_counter()
        from ..resilience import capture_executor_state, load_executor_state
        state = capture_executor_state(self)
        qresid_host = {id(n): np.asarray(self.state["qresid"][id(n)])
                       for n in self._qresid_ordered()}
        cfg.mesh = new_mesh

        def place(x):
            return jax.device_put(jnp.asarray(x),
                                  NamedSharding(new_mesh, P()))

        # params re-place through the same path init/load use
        # (_place_param inside load_executor_state); slots/op-state/qresid
        # re-place replicated explicitly — like_current's bare jnp.asarray
        # would leave them on the default device, and donation across
        # mismatched placements is what a half-moved world trips over
        load_executor_state(self, state)
        for n in self._opt_nodes():
            self.state["slots"][id(n)] = jax.tree.map(
                place, self.state["slots"][id(n)])
        for n in self._stateful_nodes():
            self.state["op_state"][id(n)] = jax.tree.map(
                place, self.state["op_state"][id(n)])
        for nid, v in qresid_host.items():
            self.state["qresid"][nid] = place(v)
        for sub in self.subexecutors.values():
            sub._compiled.clear()
            sub._replay_compiled.clear()
            sub._exe_cache.clear()
            sub._base_sigs.clear()
            sub._last_call = None
            sub._dev_prefetch.clear()
            for nid in list(sub.resident_dl):
                node = next(n for n in sub.res_dl_nodes if id(n) == nid)
                dl = node.dataloaders.get(sub.name)
                # re-place the resident dataset (old-mesh arrays are no
                # longer addressable placements for the new programs) and
                # refresh geometry — an elastic repartition may have
                # changed it
                sub.resident_dl[nid] = (
                    self._prepare_input(dl._data, batch=False),
                    dl.batch_size, dl.batch_num)
        dur_ms = (time.perf_counter() - t0) * 1e3
        if self.telemetry is not None:
            g = self.telemetry.metrics.gauge
            g("hetu_dp_size").set(float(cfg.dp_size))
            g("hetu_resize_duration_ms").set(round(dur_ms, 2))
            self.telemetry.event("remesh", dp_size=cfg.dp_size,
                                 duration_ms=round(dur_ms, 1))
        return {"dp_size": cfg.dp_size, "duration_ms": round(dur_ms, 2),
                "step": int(self.state["step"])}

    def attach_supervisor(self, sup):
        """Attach a ``resilience.Supervisor``: its pre_step/post_step hooks
        then run at every training-step boundary (watchdog beat, fault
        injection, anomaly rollback, periodic + emergency checkpoints,
        preemption exit). Pass None to detach. Returns ``sup``."""
        self.supervisor = sup
        return sup

    @property
    def rank(self) -> int:
        """Reference examples gate printing on ``executor.rank``; the
        single-program TPU build is logically rank 0 of one process."""
        return jax.process_index()

    def run(self, name="default", eval_node_list=None, feed_dict=None,
            convert_to_numpy_ret_vals=False, **kwargs):
        if isinstance(name, (dict, list, tuple)):  # run(feed_dict) legacy form
            feed_dict, name = name, "default"
        sub = self.subexecutors[name]
        return sub.run(feed_dict=feed_dict,
                       convert_to_numpy_ret_vals=convert_to_numpy_ret_vals,
                       eval_node_list=eval_node_list)

    def get_batch_num(self, name="default"):
        """Batches per epoch for the target's dataloaders (min across
        them). Under dataloader-fed gpipe this counts STEPS per epoch:
        each gpipe run() consumes gpipe_microbatches batches per
        loader."""
        sub = self.subexecutors[name]
        dls = getattr(sub, "dataloader_nodes", None)
        if dls is None:
            dls = getattr(sub, "dl_nodes", [])
        nums = [n.get_batch_num(name) for n in dls]
        if not nums:
            return None
        num = min(nums)
        m = getattr(self.config, "gpipe_microbatches", None)
        if self.config.gpipe and m:
            if num < m:
                raise ValueError(
                    f"dataloader provides {num} batches/epoch but one "
                    f"gpipe step consumes gpipe_microbatches={m}; a "
                    f"0-step epoch loop would silently train nothing")
            num //= m
        return num

    def _param_file_names(self):
        """Stable, collision-free file name per parameter: duplicates get a
        deterministic __<k> suffix (construction order)."""
        counts: dict[str, int] = {}
        names = []
        for node in self.param_nodes:
            k = counts.get(node.name, 0)
            counts[node.name] = k + 1
            names.append(node.name if k == 0 else f"{node.name}__{k}")
        return names

    # -- checkpoint (reference executor.py:355-413; adds optimizer state) ---
    def save(self, file_path: str):
        tel = self.telemetry
        t0 = time.perf_counter() if tel is not None else 0.0
        self._save(file_path)
        if tel is not None:
            t1 = time.perf_counter()
            tel.metrics.histogram("hetu_checkpoint_save_ms").observe(
                (t1 - t0) * 1e3)
            if tel.tracer is not None:
                tel.tracer.complete("checkpoint_save", t0, t1, cat="ckpt")

    def _save(self, file_path: str):
        os.makedirs(file_path, exist_ok=True)
        if self.ps_runtime is not None:
            self.ps_runtime.save(file_path)
        for node, fname in zip(self.param_nodes, self._param_file_names()):
            np.save(os.path.join(file_path, fname + ".npy"),
                    np.asarray(self.state["params"][id(node)]))
        aux = {
            "step": self.state["step"],
            "slots": {str(i): jax.tree.map(np.asarray, self.state["slots"][id(n)])
                      for i, n in enumerate(self._opt_nodes())},
            "op_state": {str(i): jax.tree.map(np.asarray, self.state["op_state"][id(n)])
                         for i, n in enumerate(self._stateful_nodes())},
            # hetuq error-feedback residuals: without them a resumed run's
            # first quantized steps would re-pay the cold-start compression
            # error the residual had already absorbed
            "qresid": {str(i): np.asarray(self.state["qresid"][id(n)])
                       for i, n in enumerate(self._qresid_ordered())},
        }
        with open(os.path.join(file_path, "executor_state.pkl"), "wb") as f:
            pickle.dump(aux, f)

    def _place_param(self, node, value):
        """A host value as this parameter's device/mesh-resident array (the
        same placement rule as init/load; shared with resilience restore)."""
        value = jnp.asarray(value, dtype=node.dtype)
        if self.config.mesh is not None:
            spec = self.config.param_specs.get(id(node), P())
            value = jax.device_put(value, NamedSharding(self.config.mesh, spec))
        elif self.config.device is not None:
            value = jax.device_put(value, self.config.device)
        return value

    def load(self, file_path: str):
        if self.ps_runtime is not None:
            self.ps_runtime.load(file_path)
        for node, fname in zip(self.param_nodes, self._param_file_names()):
            path = os.path.join(file_path, fname + ".npy")
            if os.path.exists(path):
                self.state["params"][id(node)] = self._place_param(
                    node, np.load(path))
        aux_path = os.path.join(file_path, "executor_state.pkl")
        if os.path.exists(aux_path):
            with open(aux_path, "rb") as f:
                aux = pickle.load(f)
            self.state["step"] = aux.get("step", 0)
            for i, n in enumerate(self._opt_nodes()):
                if str(i) in aux.get("slots", {}):
                    self.state["slots"][id(n)] = jax.tree.map(
                        jnp.asarray, aux["slots"][str(i)])
            for i, n in enumerate(self._stateful_nodes()):
                if str(i) in aux.get("op_state", {}):
                    self.state["op_state"][id(n)] = jax.tree.map(
                        jnp.asarray, aux["op_state"][str(i)])
            for i, n in enumerate(self._qresid_ordered()):
                if str(i) in aux.get("qresid", {}):
                    v = jnp.asarray(aux["qresid"][str(i)], jnp.float32)
                    if self.config.mesh is not None:
                        v = jax.device_put(
                            v, NamedSharding(self.config.mesh, P()))
                    self.state["qresid"][id(n)] = v

    def _qresid_ordered(self):
        """Stable checkpoint order for the error-feedback residuals (the
        quantized-AllReduce op scan order)."""
        return [n for n in self.qar_ops if id(n) in self.state["qresid"]]

    def _opt_nodes(self):
        seen, out = set(), []
        for sub in self.subexecutors.values():
            for n in sub.optimizer_nodes:
                if id(n) not in seen:
                    seen.add(id(n))
                    out.append(n)
        return out

    def _stateful_nodes(self):
        seen, out = set(), []
        for sub in self.subexecutors.values():
            for n in sub.stateful_nodes:
                if id(n) not in seen:
                    seen.add(id(n))
                    out.append(n)
        return out

    def close(self):
        """Drain and stop the PS async I/O threads (reference worker
        Finalize). Safe to call more than once; training can resume on the
        synchronous path afterwards. Also detaches this executor's
        hetuscope introspector so later abort flushes don't rewrite a
        finished run's flight file."""
        if self.ps_runtime is not None:
            self.ps_runtime.drain()
            self.ps_runtime.shutdown()
        if self.introspector is not None:
            self.introspector.close()

    def fetch_dense_parameter_value(self, nodes):
        """Reference executor.py:1236 — current parameter values (PS-hosted
        dense params are pulled from the server)."""
        out = []
        for n in nodes:
            p = (self.ps_runtime.params.get(id(n))
                 if self.ps_runtime is not None else None)
            if p is not None:
                out.append(NDArray(self.ps_runtime.pull_dense_value(p)))
            else:
                out.append(NDArray(self.state["params"][id(n)]))
        return out


# ---------------------------------------------------------------------------
# distributed bootstrap shims (reference executor.py:38-100). Under JAX the
# runtime is initialized once per process via jax.distributed; these keep the
# reference's call sites working.
# ---------------------------------------------------------------------------

def wrapped_mpi_nccl_init(init_nccl=True, devices=None):
    import jax

    class _Comm:
        rank = jax.process_index()
        nrank = jax.process_count()

        def local_rank(self):
            return 0

    return _Comm()


def mpi_nccl_init():
    comm = wrapped_mpi_nccl_init()
    return comm, comm.rank


def mpi_nccl_finish(comm=None):
    return None


def new_group_comm(devices=None):
    return None


def scheduler_init():
    from .. import ps
    ps.scheduler_init()


def scheduler_finish():
    from .. import ps
    ps.scheduler_finish()


def server_init():
    from .. import ps
    ps.server_init()


def server_finish():
    from .. import ps
    ps.server_finish()


def worker_init():
    from .. import ps
    ps.worker_init()


def worker_finish():
    from .. import ps
    ps.worker_finish()


def get_worker_communicate():
    from .. import ps
    return ps.get_worker_communicate()
