from .node import Op, PlaceholderOp, Variable, placeholder_op, find_topo_sort
from .gradients import gradients, GradientOp
from .executor import Executor, HetuConfig, SubExecutor
