"""Graph-API pipeline parallelism: the GPipe subexecutor.

Capability parity with the reference's ``SubExecutor4Gpipe``
(``gpu_ops/executor.py:435-767``): per-stage ``ht.context(...)`` blocks
partition the graph into pipeline stages, ``Executor(..., gpipe=True)`` runs a
list of microbatch feed_dicts through all stage forwards, then all backwards
in reverse buffer order, and applies the optimizer ONCE after the last
microbatch (:675-742).

TPU-native redesign, not a translation:

- The reference splits its flat topo at the first PipelineSend/OnesLike into
  forward/backward halves (:469-482) and drives NCCL P2P ops per edge from
  Python. Here the graph is partitioned at *context boundaries* into stage
  subgraphs; each stage compiles to two jitted XLA programs (forward, and a
  ``jax.vjp`` backward that REMATERIALIZES the stage forward — the GPipe
  paper's activation-recomputation trade, which on TPU buys back HBM for
  FLOPs the MXU has to spare). Stage boundary values cross devices via
  explicit ``jax.device_put`` edges: shapes are static and known at
  placement, so the reference's runtime shape handshake
  (PipelineSend.py:30-44) has no equivalent.
- The fill/drain overlap comes from JAX's asynchronous dispatch: the Python
  scheduler issues stage programs in dependency order and returns before
  they execute, so different stage devices genuinely compute concurrently —
  the role the reference's per-stage processes + p2p stream play.
- Gradients accumulate across microbatches with the loss cotangent seeded at
  1/M, so the accumulated gradient equals the gradient of the full-batch
  mean loss — the pipeline run matches a single-device run on the
  concatenated batch exactly (the correctness oracle the reference lacks).
- Pipeline+DP: a multi-device stage context (``with ht.context([d0, d1])``)
  gives that stage a 1-axis dp mesh; microbatches shard over it and GSPMD
  inserts the per-stage gradient allreduce (the reference's per-group
  ``new_group_comm``, executor.py:248-256).
- Stateful ops (BatchNorm running stats) thread sequentially through the
  microbatch schedule — each microbatch's forward consumes the previous
  one's stats, matching the reference's in-op mutable arrays — and the
  remat backward reuses the exact state its forward saw.
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from jax.sharding import NamedSharding, PartitionSpec as P

from ..context import DeviceGroup
from ..ndarray import NDArray
from .node import Op, find_topo_sort


class _Stage:
    """One pipeline stage: its device(s) plus the forward subgraph placed on
    them. A multi-device stage group means pipeline+DP: the stage's
    microbatch is sharded over a per-stage 1-axis mesh and GSPMD inserts the
    per-group gradient allreduce (the reference's ``new_group_comm`` per
    param group, executor.py:248-256)."""

    def __init__(self, index: int, group: DeviceGroup):
        from jax.sharding import Mesh
        self.index = index
        self.group = group
        devices = [d.jax_device() for d in group.flat()]
        self.device = devices[0]
        self.mesh = (Mesh(np.asarray(devices), ("dp",))
                     if len(devices) > 1 else None)
        self.nodes: list[Op] = []        # compute nodes, topo order
        self.param_nodes: list[Op] = []
        self.feed_nodes: list[Op] = []
        self.state_nodes: list[Op] = []  # stateful ops (BatchNorm stats)
        self.in_nodes: list[Op] = []     # boundary inputs from earlier stages
        self.out_nodes: list[Op] = []    # values later stages / evals consume
        self.fwd = None                  # jitted (params, ins, feeds, rng, st) -> (outs, st')
        self.bwd = None                  # jitted (..., cts) -> (ct_params, ct_ins)
        self.apply = None                # jitted optimizer apply for this stage

    # -- placement helpers -------------------------------------------------
    def put_replicated(self, v):
        if self.mesh is not None:
            return jax.device_put(v, NamedSharding(self.mesh, P()))
        return jax.device_put(v, self.device)

    def put_batch(self, v):
        """Shard dim 0 over the stage's dp mesh (microbatch data)."""
        if self.mesh is not None:
            ndim = np.ndim(v)
            if ndim >= 1:
                dp = self.mesh.shape["dp"]
                if np.shape(v)[0] % dp:
                    raise ValueError(
                        f"stage {self.index}: microbatch dim 0 "
                        f"({np.shape(v)[0]}) must divide the stage's dp "
                        f"width ({dp}); size the microbatches accordingly")
            spec = P("dp") if ndim >= 1 else P()
            return jax.device_put(v, NamedSharding(self.mesh, spec))
        return jax.device_put(v, self.device)


class SubExecutor4Gpipe:
    """GPipe schedule over context-partitioned stages
    (reference executor.py:435)."""

    def __init__(self, name: str, eval_nodes: list[Op], executor):
        self.name = name
        self.eval_nodes = eval_nodes
        self.executor = executor
        self.config = executor.config

        topo = find_topo_sort(eval_nodes)
        opt_nodes = [n for n in topo if n.is_optimizer]
        if len(opt_nodes) > 1:
            raise ValueError(
                f"gpipe=True needs at most one optimizer in the graph, "
                f"found {len(opt_nodes)}")
        if self.config.comm_mode not in (None, "AllReduce"):
            raise NotImplementedError(
                f"gpipe=True with comm_mode={self.config.comm_mode!r}: "
                "PS/Hybrid embeddings cannot ride the pipeline schedule; "
                "pipeline+DP is expressed by multi-device stage contexts "
                "(comm_mode='AllReduce' or default)")
        # no optimizer = a forward-only (validation) target: it still runs
        # through the stage pipeline, because after a train step the params
        # are committed to their stage devices
        self.opt_node = opt_nodes[0] if opt_nodes else None
        self.loss = None
        self.opt_vars = []
        if self.opt_node is not None:
            grad0 = self.opt_node.inputs[0]
            # comm_mode='AllReduce' wraps grads in AllReduce markers
            # (optimizer.insert_comm_ops); under gpipe the dp reduction is
            # GSPMD's inside each stage program, so unwrap to the gradient
            from .ops.comm import AllReduceCommunicateOp
            if isinstance(grad0, AllReduceCommunicateOp):
                grad0 = grad0.inputs[0]
            if not getattr(grad0, "is_gradient", False):
                raise ValueError(
                    "gpipe optimizer inputs must be gradient nodes")
            self.loss = grad0.gctx.loss
            self.opt_vars = list(self.opt_node.vars)

        fwd_evals = [n for n in eval_nodes if not n.is_optimizer]
        if self.loss is not None and self.loss not in fwd_evals:
            fwd_evals.append(self.loss)
        self.fwd_evals = fwd_evals
        fwd_topo = [n for n in find_topo_sort(fwd_evals)
                    if not (n.is_gradient or n.is_optimizer)]
        # dataloader-fed gpipe (round 5; the reference's gpipe is
        # feed-list-only): dataloader nodes become per-stage feeds whose
        # values run() pulls host-side, M microbatches per step. Plain
        # DataloaderOp only — GNN double-buffered loaders have a
        # step-driven get_batch contract this schedule does not drive.
        from ..dataloader import DataloaderOp
        self.dl_nodes = [n for n in fwd_topo if n.is_dataloader]
        for n in self.dl_nodes:
            if not isinstance(n, DataloaderOp):
                raise NotImplementedError(
                    f"gpipe dataloader feeds support plain dataloader_op "
                    f"nodes; {type(n).__name__} must be fed explicitly")

        self.training = self.opt_node is not None
        self.stages = self._partition(fwd_topo)
        self._build_programs()

    # ------------------------------------------------------------------
    def _partition(self, fwd_topo: list[Op]) -> list[_Stage]:
        """Group forward nodes into stages by their context, in order of
        first appearance (reference context.py:369-387 infers the same
        stage chain before inserting send/recv pairs)."""
        stage_of: dict[int, int] = {}    # node id -> stage index
        stages: list[_Stage] = []
        group_index: dict[DeviceGroup, int] = {}

        def stage_for_group(g: DeviceGroup) -> int:
            if g not in group_index:
                group_index[g] = len(stages)
                stages.append(_Stage(len(stages), g))
            return group_index[g]

        for n in fwd_topo:
            if n.is_placeholder or n.is_dataloader:
                continue  # assigned to earliest consumer below
            if not isinstance(n.raw_ctx, DeviceGroup):
                raise ValueError(
                    f"gpipe=True but {n.name!r} has no placement context; "
                    "wrap each pipeline stage in `with ht.context(...)` "
                    "(reference examples/runner/parallel/gpipe.py)")
            s = stage_for_group(n.raw_ctx)
            # edges may only flow forward through the pipeline
            for i in n.inputs:
                if id(i) in stage_of and stage_of[id(i)] > s:
                    raise ValueError(
                        f"{n.name!r} (stage {s}) consumes {i.name!r} from a "
                        f"later stage {stage_of[id(i)]}; pipeline edges must "
                        "flow forward")
            stage_of[id(n)] = s
            stages[s].nodes.append(n)
        if len(stages) == 0:
            raise ValueError("gpipe=True but the graph has no stage contexts")

        for st in stages:
            st.state_nodes = [n for n in st.nodes if n.stateful]

        # placeholders (params and feeds) and dataloader nodes belong to
        # their earliest consumer; dataloaders join feed_nodes — the stage
        # program treats them as feeds, run() supplies their batches
        for n in fwd_topo:
            if not (n.is_placeholder or n.is_dataloader):
                continue
            consumers = [stage_of[id(c)] for c in fwd_topo
                         if not (c.is_placeholder or c.is_dataloader)
                         and any(i is n for i in c.inputs)]
            if not consumers:
                continue
            s = min(consumers)
            stage_of[id(n)] = s
            if n.is_dataloader or getattr(n, "is_feed", False):
                stages[s].feed_nodes.append(n)
            else:
                stages[s].param_nodes.append(n)

        # boundary edges: anything consumed by a LATER stage is an output of
        # its own stage and an input of every later consumer stage
        for n in fwd_topo:
            if id(n) not in stage_of:
                continue
            s = stage_of[id(n)]
            later = sorted({stage_of[id(c)] for c in fwd_topo
                            if not c.is_placeholder
                            and any(i is n for i in c.inputs)
                            and stage_of[id(c)] > s})
            is_eval = any(n is e for e in self.fwd_evals)
            if later or is_eval:
                stages[s].out_nodes.append(n)
            for t in later:
                stages[t].in_nodes.append(n)
        self._stage_of = stage_of
        return stages

    # ------------------------------------------------------------------
    def _build_programs(self):
        from .executor import TraceContext, _eval_node
        config = self.config
        training = self.training

        for stage in self.stages:
            def make_fwd(stage=stage):
                def fwd(params_t, ins_t, feeds_t, rng, opstate_t):
                    env: dict[int, Any] = {}
                    for node, v in zip(stage.param_nodes, params_t):
                        env[id(node)] = v
                    for node, v in zip(stage.in_nodes, ins_t):
                        env[id(node)] = v
                    for node, v in zip(stage.feed_nodes, feeds_t):
                        env[id(node)] = v
                    op_state_in = {id(n): s for n, s in
                                   zip(stage.state_nodes, opstate_t)}
                    tc = TraceContext(config, stage.nodes, training, env, rng,
                                      jnp.zeros((), jnp.int32), op_state_in)
                    for node in stage.nodes:
                        _eval_node(node, env, tc)
                    new_state = tuple(
                        tc.op_state_updates.get(id(n), op_state_in[id(n)])
                        for n in stage.state_nodes)
                    return tuple(env[id(n)] for n in stage.out_nodes), new_state
                return fwd

            fwd = make_fwd()
            stage.fwd = jax.jit(fwd)
            if not training:
                continue

            def make_bwd(fwd=fwd):
                def bwd(params_t, ins_t, feeds_t, rng, opstate_t, cts):
                    # rematerialize the stage forward inside the vjp: no
                    # activation stash survives the schedule (GPipe remat).
                    # op state (BN running stats) enters as a constant — the
                    # microbatch's own batch statistics ARE differentiated
                    # through; the running EMA is not.
                    _, vjp = jax.vjp(
                        lambda p, i: fwd(p, i, feeds_t, rng, opstate_t)[0],
                        params_t, ins_t)
                    return vjp(cts)
                return bwd

            stage.bwd = jax.jit(make_bwd())

            opt = self.opt_node.optimizer
            var_pos = {id(v): i for i, v in enumerate(self.opt_vars)}
            stage_var_idx = [var_pos[id(v)] for v in stage.param_nodes]

            def make_apply(stage=stage, opt=opt):
                def apply(params_t, grads_t, slots_t, step):
                    lr = opt.lr_value(step)
                    new_p, new_s = [], []
                    for p, g, s in zip(params_t, grads_t, slots_t):
                        np_, ns_ = opt.apply_dense(p, g, s, lr)
                        new_p.append(np_)
                        new_s.append(ns_)
                    return tuple(new_p), tuple(new_s)
                return apply

            stage.apply = jax.jit(make_apply(), donate_argnums=(0, 2))
            stage.var_idx = stage_var_idx

    # ------------------------------------------------------------------
    def _stage_params(self, stage: _Stage):
        ex = self.executor
        stage_devs = (set(stage.mesh.devices.flat) if stage.mesh is not None
                      else {stage.device})
        vals = []
        for node in stage.param_nodes:
            v = ex.state["params"][id(node)]
            if v.devices() != stage_devs:
                v = stage.put_replicated(v)
                ex.state["params"][id(node)] = v
            vals.append(v)
        return tuple(vals)

    def _stage_opstate(self, stage: _Stage):
        ex = self.executor
        return tuple(stage.put_replicated(ex.state["op_state"][id(n)])
                     for n in stage.state_nodes)

    def run(self, feed_dict=None, convert_to_numpy_ret_vals=False,
            eval_node_list=None):
        """Run one GPipe step over a LIST of microbatch feed_dicts
        (reference executor.py:592: ``run(feed_dicts_list)``). Returns, per
        eval node, the list of per-microbatch values (None for the
        optimizer node)."""
        ex = self.executor
        if not feed_dict:
            # {} / [] mean the same as None: nothing fed by hand — the
            # dataloader path must not silently run a 1-microbatch step
            feed_dict = None
        if isinstance(feed_dict, dict):
            feed_dict = [feed_dict]
        if feed_dict is None and self.dl_nodes:
            # dataloader-fed step: M comes from the config (explicit feed
            # lists carry their own M)
            M = self.config.gpipe_microbatches
            if not M:
                raise ValueError(
                    "gpipe with dataloader feeds and no feed_dicts list "
                    "needs Executor(..., gpipe_microbatches=M)")
            feed_dict = [{} for _ in range(M)]
        if not isinstance(feed_dict, (list, tuple)) or not feed_dict:
            raise ValueError(
                "gpipe run() takes a non-empty list of microbatch feed_dicts")
        if self.dl_nodes:
            # pull M batches per dataloader, injected per microbatch (a
            # user-supplied value for the same node would be ambiguous)
            feed_dict = [dict(fd) for fd in feed_dict]
            for fd in feed_dict:
                for n in self.dl_nodes:
                    if n in fd:
                        raise ValueError(
                            f"{n.name!r} is a dataloader node; its batches "
                            "come from the loader, not the feed list")
                    fd[n] = np.asarray(n.get_batch(self.name))
        M = len(feed_dict)
        step = ex.state["step"]
        rng_step = jax.random.fold_in(ex.rng_root, step)

        # validate BEFORE building feeds — the comprehension below indexes
        # fd[n] eagerly, and a bare KeyError names the Op repr, not the
        # microbatch/feed the user forgot
        for m, fd in enumerate(feed_dict):
            for st in self.stages:
                for n in st.feed_nodes:
                    if n not in fd:
                        raise ValueError(
                            f"microbatch {m}: missing feed for {n.name!r}")
        # stage feeds per microbatch, batch-sharded over the stage devices
        feeds = [[tuple(st.put_batch(np.asarray(fd[n]))
                        for n in st.feed_nodes)
                  for st in self.stages] for fd in feed_dict]

        params = [self._stage_params(st) for st in self.stages]
        # op state (BN running stats) threads sequentially through the
        # microbatches of each stage; state_store holds the rolling value,
        # state_in_store the per-(m, stage) input for the remat backward
        state_store = [self._stage_opstate(st) for st in self.stages]
        state_in_store: list[list[tuple]] = [[None] * len(self.stages)
                                             for _ in range(M)]
        # per-(microbatch, stage) keys: stages index their nodes locally, so
        # without the stage fold two stages' dropout masks would coincide
        rngs = [[jax.random.fold_in(jax.random.fold_in(rng_step, m), s)
                 for s in range(len(self.stages))] for m in range(M)]

        # ---- forward fill: all microbatches through all stages ----------
        # (async dispatch overlaps stage m on device s with m+1 on s-1)
        boundary: list[dict[int, Any]] = [dict() for _ in range(M)]
        ins_store: list[list[tuple]] = [[None] * len(self.stages)
                                        for _ in range(M)]
        for m in range(M):
            for s, st in enumerate(self.stages):
                ins = tuple(st.put_batch(boundary[m][id(n)])
                            for n in st.in_nodes)
                ins_store[m][s] = ins
                state_in_store[m][s] = state_store[s]
                outs, new_state = st.fwd(params[s], ins, feeds[m][s],
                                         rngs[m][s], state_store[s])
                state_store[s] = new_state
                for n, v in zip(st.out_nodes, outs):
                    boundary[m][id(n)] = v

        if self.training:
            # commit the post-schedule running stats (training mode only —
            # eval traces return state unchanged anyway)
            for s, st in enumerate(self.stages):
                for n, v in zip(st.state_nodes, state_store[s]):
                    ex.state["op_state"][id(n)] = v

        if not self.training:
            return self._collect(boundary, M, eval_node_list,
                                 convert_to_numpy_ret_vals)

        # ---- backward drain: reverse microbatch, reverse stage ----------
        grads_acc: list[Optional[list]] = [None] * len(self.stages)
        for m in reversed(range(M)):
            cts: dict[int, Any] = {}
            seed = jnp.ones(np.shape(boundary[m][id(self.loss)]),
                            jnp.float32) / M
            cts[id(self.loss)] = self.stages[-1].put_replicated(seed)
            for s in reversed(range(len(self.stages))):
                st = self.stages[s]
                ct_out = tuple(
                    st.put_batch(cts[id(n)])
                    if id(n) in cts else jnp.zeros_like(boundary[m][id(n)])
                    for n in st.out_nodes)
                ct_params, ct_ins = st.bwd(params[s], ins_store[m][s],
                                           feeds[m][s], rngs[m][s],
                                           state_in_store[m][s], ct_out)
                if grads_acc[s] is None:
                    grads_acc[s] = list(ct_params)
                else:
                    grads_acc[s] = [a + g for a, g in
                                    zip(grads_acc[s], ct_params)]
                for n, ct in zip(st.in_nodes, ct_ins):
                    prev = cts.get(id(n))
                    if prev is not None:
                        ct = ct + st.put_batch(prev)
                    cts[id(n)] = ct

        # ---- single optimizer apply after all microbatches --------------
        # (reference executor.py:734-742)
        slots_all = list(ex.state["slots"][id(self.opt_node)])
        step_arr = jnp.asarray(step, jnp.int32)
        for s, st in enumerate(self.stages):
            if not st.param_nodes:
                continue
            slots_t = tuple(st.put_replicated(slots_all[i])
                            for i in st.var_idx)
            new_p, new_s = st.apply(params[s], tuple(grads_acc[s]),
                                    slots_t, step_arr)
            for node, v in zip(st.param_nodes, new_p):
                ex.state["params"][id(node)] = v
            for i, v in zip(st.var_idx, new_s):
                slots_all[i] = v
        ex.state["slots"][id(self.opt_node)] = tuple(slots_all)
        ex.state["step"] = step + 1
        return self._collect(boundary, M, eval_node_list,
                             convert_to_numpy_ret_vals)

    def _collect(self, boundary, M, eval_node_list, convert_to_numpy):
        """Per-microbatch eval values, per eval node (optimizer -> None)."""
        results = []
        wanted = eval_node_list if eval_node_list is not None else self.eval_nodes
        for node in wanted:
            if node.is_optimizer:
                results.append(None)
                continue
            vals = [boundary[m][id(node)] for m in range(M)]
            results.append([np.asarray(v) if convert_to_numpy
                            else NDArray(v) for v in vals])
        return results
