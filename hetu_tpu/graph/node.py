"""Graph node (Op) base classes for the define-then-run frontend.

Capability parity with the reference's ``gpu_ops/Node.py`` (Op :9, compute :73,
gradient :83, infer_shape :95), redesigned for XLA:

- ``compute`` is a *pure jax function* of traced arrays — it is called once per
  (subexecutor, shape-signature) while tracing the whole subgraph into a single
  jitted XLA program. The reference's per-node interpreter dispatch, stream
  assignment, event sync and transfer-op insertion (Node.py:111-163) do not
  exist here: XLA schedules, fuses and places everything.
- autodiff is graph-level via ``hetu_tpu.graph.gradients`` (jax.vjp at trace
  time), so ops do not each carry a symbolic ``gradient`` method; explicit
  ``*_gradient_op`` constructors are still provided for API parity.
- stateful ops (BatchNorm running stats, Dropout RNG) declare state through
  ``stateful``/``state_init`` and are threaded functionally by the executor.
"""
from __future__ import annotations

import itertools
from typing import Any, Callable, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ..context import get_current_context, DeviceGroup

_id_counter = itertools.count()

# Active graph recorders (see hetu_tpu.analysis.record_graph): every Op
# constructed while a recorder is on the stack is appended to it, giving the
# analyzer a *universe* of constructed nodes so it can report subgraphs that
# are dead w.r.t. the eval targets. Empty in normal operation — the per-Op
# cost is iterating an empty list.
_graph_recorders: list[list] = []


def _as_struct(x) -> jax.ShapeDtypeStruct:
    """Normalize a shape tuple / array / ShapeDtypeStruct into a struct.

    Bare shape tuples keep the historical ``infer_shape`` contract of
    assuming float32 inputs (reference Node.py:95 is shape-only). A tuple
    whose elements are themselves array-like (the IndexedRows sparse-grad
    pair from the PR-12 rows route) is a pytree of values, not a shape —
    it maps elementwise, preserving the NamedTuple type so downstream
    abstract evaluation sees the same container the trace would."""
    if isinstance(x, jax.ShapeDtypeStruct):
        return x
    if hasattr(x, "shape") and hasattr(x, "dtype"):
        return jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
    if isinstance(x, tuple) and x and all(
            hasattr(e, "shape") and hasattr(e, "dtype") for e in x):
        mapped = [_as_struct(e) for e in x]
        return type(x)(*mapped) if hasattr(x, "_fields") else tuple(mapped)
    return jax.ShapeDtypeStruct(tuple(int(s) for s in x), np.float32)


class Op:
    """Base graph node. Users compose these via the ``*_op`` constructors."""

    # class-level flags the executor dispatches on
    is_placeholder = False   # fed via feed_dict or a Variable
    is_dataloader = False
    is_optimizer = False
    is_gradient = False
    stateful = False         # has functional state threaded by the executor
    needs_rng = False        # wants a PRNGKey during training trace

    def __init__(self, inputs: Sequence["Op"], ctx=None, name: Optional[str] = None):
        self.id = next(_id_counter)
        self.inputs = list(inputs)
        if ctx is None:
            ctx = get_current_context()
        self.raw_ctx = ctx if (ctx is None or isinstance(ctx, DeviceGroup)) else DeviceGroup(ctx)
        self.name = name or f"{type(self).__name__}_{self.id}"
        self.desc = self.name
        for rec in _graph_recorders:
            rec.append(self)

    # ------------------------------------------------------------------
    def compute(self, input_vals, tc):
        """Pure computation: list of jax arrays -> jax array (traced)."""
        raise NotImplementedError(type(self).__name__)

    def compute_stateful(self, input_vals, state, tc):
        """Stateful computation -> (output, new_state)."""
        raise NotImplementedError(type(self).__name__)

    def state_init(self):
        """Initial state pytree for stateful ops."""
        raise NotImplementedError(type(self).__name__)

    def infer_meta(self, inputs, training: bool = False):
        """Abstract-evaluate this op: input shapes/dtypes -> output
        ``jax.ShapeDtypeStruct`` without running any computation.

        ``inputs`` items may be bare shape tuples (assumed float32, the
        historical ``infer_shape`` contract), ``jax.ShapeDtypeStruct``\\ s, or
        arrays — so integer-indexed ops (embedding lookup, one-hot, sparse
        pulls) infer correctly when given real dtypes. Works for stateful ops
        (BatchNorm) by abstract-evaluating ``compute_stateful`` over a fresh
        ``state_init``. Comm/PS ops evaluate through the abstract trace
        context's collective identities.
        """
        structs = [_as_struct(s) for s in inputs]
        tc = _AbstractTraceContext(training=training)
        if self.stateful:
            state = jax.tree.map(np.asarray, self.state_init())

            def fn(*xs):
                out, _ = self.compute_stateful(list(xs), state, tc)
                return out
        else:
            def fn(*xs):
                return self.compute(list(xs), tc)
        return jax.eval_shape(fn, *structs)

    def infer_shape(self, input_shapes):
        """Shape inference via abstract evaluation (reference Node.py:95).

        The executor does not need this (XLA infers shapes); it exists for
        user introspection, the analysis passes, and tests. Accepts shape
        tuples (float32 assumed, API parity) or ``ShapeDtypeStruct``\\ s.
        """
        out = self.infer_meta(input_shapes)
        return tuple(out.shape) if hasattr(out, "shape") else None

    # -- operator overloads (reference Node.py:33-71) -------------------
    def __add__(self, other):
        from .ops import add_op, addbyconst_op
        if isinstance(other, Op):
            return add_op(self, other)
        return addbyconst_op(self, other)

    __radd__ = __add__

    def __mul__(self, other):
        from .ops import mul_op, mul_byconst_op
        if isinstance(other, Op):
            return mul_op(self, other)
        return mul_byconst_op(self, other)

    __rmul__ = __mul__

    def __sub__(self, other):
        from .ops import add_op, addbyconst_op, opposite_op
        if isinstance(other, Op):
            return add_op(self, opposite_op(other))
        return addbyconst_op(self, -other)

    def __rsub__(self, other):
        from .ops import addbyconst_op, opposite_op
        return addbyconst_op(opposite_op(self), other)

    def __neg__(self):
        from .ops import opposite_op
        return opposite_op(self)

    def __truediv__(self, other):
        from .ops import div_op, div_const_op, mul_byconst_op
        if isinstance(other, Op):
            return div_op(self, other)
        return mul_byconst_op(self, 1.0 / other)

    def __rtruediv__(self, other):
        from .ops import div_const_op
        return div_const_op(other, self)

    def __lt__(self, other):  # stable ordering for pytree-dict keys
        return self.id < other.id

    def __repr__(self):
        return self.name


class _AbstractTraceContext:
    """Trace context for abstract evaluation (``infer_shape``/``infer_meta``
    and the analysis subsystem's whole-graph shape pass).

    Comm and PS ops call collective/RPC hooks on the trace context; during
    abstract evaluation these reduce to their shape-level identities, so a
    graph containing AllReduce/Dispatch/pipeline/PS nodes abstract-evaluates
    end to end instead of crashing on the missing executor services:

    - ``allreduce``/``apply_dispatch``: sharding constraints — value identity.
    - ``ps_push_pull``: the real hook captures the gradient host-side and the
      op yields no in-graph value — abstractly ``None``.
    - ``ps_sparse_pull``: staged row pull — abstractly a gather, giving the
      (batch..., width) row block the executor would stage.
    """

    training = False
    config = None

    def __init__(self, training: bool = False):
        self.training = bool(training)

    def next_rng(self, node):
        return jax.random.PRNGKey(0)

    def allreduce(self, x, param_node=None, op=None):
        return x

    def apply_dispatch(self, op, x):
        return x

    def ps_push_pull(self, op, grad):
        return None

    def ps_sparse_pull(self, op, vals):
        table, idx = vals
        return jnp.take(table, idx.astype(jnp.int32), axis=0)


class FunctionalOp(Op):
    """An op whose compute is a closed-over pure function — the workhorse.

    Most of the reference's 55 ``gpu_ops/*`` classes (each pairing a CUDA
    kernel with shims) become one of these wrapping a jax/lax composition.
    """

    def __init__(self, opname: str, fn: Callable, inputs: Sequence[Op], ctx=None,
                 name: Optional[str] = None, **attrs):
        super().__init__(inputs, ctx, name or f"{opname}_{next(_id_counter)}")
        self.opname = opname
        self.fn = fn
        self.attrs = attrs
        # introspection-only metadata (ONNX export, graphboard); never passed
        # to ``fn`` — constructors close over the actual values
        self.export_attrs: dict = {}

    def compute(self, input_vals, tc):
        return self.fn(*input_vals, **self.attrs)


class PlaceholderOp(Op):
    """Leaf node: a trainable Variable, a constant, or a fed placeholder.

    Reference ``gpu_ops/Variable.py`` — ``Variable(name, value=...)`` with an
    initializer produces a parameter; with neither it is fed via feed_dict.
    """

    is_placeholder = True

    def __init__(self, name, value=None, initializer=None, trainable=None,
                 dtype=np.float32, ctx=None, batch=None, **kwargs):
        super().__init__([], ctx, name)
        self.initializer = initializer
        self.dtype = np.dtype(dtype)
        self.is_embed = bool(kwargs.get("is_embed", False))
        # is dim 0 a batch dimension (shardable over dp)? Fed placeholders
        # default to yes (reference: each DP worker feeds its own shard);
        # pass batch=False for non-batch feeds like constant masks.
        self.batch = True if batch is None else bool(batch)
        if value is not None and not isinstance(value, np.ndarray):
            value = np.asarray(value, dtype=self.dtype)
        self.value = value
        has_data = value is not None or initializer is not None
        if trainable is None:
            trainable = has_data
        if trainable and not has_data:
            raise ValueError(
                f"Variable {name!r} is trainable=True but has neither a value "
                "nor an initializer; fed placeholders must be trainable=False")
        self.trainable = trainable
        self.shape = None
        if value is not None:
            self.shape = tuple(value.shape)
        elif initializer is not None:
            self.shape = tuple(initializer.shape)

    @property
    def is_feed(self) -> bool:
        return self.value is None and self.initializer is None

    def instantiate(self, rng_key) -> np.ndarray | jax.Array:
        """Produce the initial parameter value (host-side, executor init)."""
        if self.value is not None:
            return np.asarray(self.value, dtype=self.dtype)
        if self.initializer is not None:
            return self.initializer.init(rng_key, self.dtype)
        raise ValueError(f"Placeholder {self.name} has no value; feed it via feed_dict")

    def compute(self, input_vals, tc):
        raise AssertionError("PlaceholderOp values are supplied by the executor")


def Variable(name, value=None, initializer=None, trainable=None, dtype=np.float32,
             ctx=None, batch=None, **kwargs):
    """Create a variable/placeholder node (reference gpu_ops/Variable.py)."""
    return PlaceholderOp(name, value=value, initializer=initializer,
                         trainable=trainable, dtype=dtype, ctx=ctx,
                         batch=batch, **kwargs)


placeholder_op = Variable


def find_topo_sort(node_list: Sequence[Op]) -> list[Op]:
    """Post-order DFS topological sort (reference executor.py:1175)."""
    visited: set[int] = set()
    order: list[Op] = []

    def dfs(node: Op):
        stack = [(node, iter(node.inputs))]
        if id(node) in visited:
            return
        visited.add(id(node))
        while stack:
            cur, it = stack[-1]
            advanced = False
            for child in it:
                if id(child) not in visited:
                    visited.add(id(child))
                    stack.append((child, iter(child.inputs)))
                    advanced = True
                    break
            if not advanced:
                order.append(cur)
                stack.pop()

    for n in node_list:
        dfs(n)
    return order
