"""PS/Hybrid execution: bridges the jitted XLA step to the host-resident
parameter server.

Reference behavior being matched (``gpu_ops/ParameterServerCommunicate.py``,
``EmbeddingLookUp.py``):
  - sparse embedding tables live on the PS, never on the accelerator; each
    step pulls only the batch's rows (SparsePull / cache lookup, forward_hook
    :122-231) and pushes only their gradients (SSPushPull / cache push-pull)
  - dense params under comm_mode='PS' live on the PS; workers push lr-scaled
    gradients and pull fresh values (DDPushPull, worker-side ``_mult_lr``
    :24-25, :52-60)
  - ASP by default; BSP adds a worker barrier per step (:42-46)
  - optional bounded-staleness client cache (``cstable_policy``)

TPU-native redesign: the reference interleaves PS RPCs *inside* the op
interpreter via a d2h stream + events. Here the jitted step is a pure XLA
program; PS traffic happens at its boundary:
  - pre-step (host): pull batch rows for every PS-hosted embedding lookup,
    feed them as extra inputs
  - in-trace: the lookup op returns the staged rows; gradient nodes are
    rewired from the table variable to the lookup output, so the grad leaves
    the program as a (batch_rows, width) tensor, not a full-table scatter
  - post-step (host): push row gradients (and dense grads) to the PS
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np

from .node import Op, PlaceholderOp, find_topo_sort
from .ops.ps import ParameterServerCommunicateOp, ParameterServerSparsePullOp


_INIT_SPEC_BY_CLASS = {
    # initializer class name -> (ps init_type, (a_attr, b_attr))
    "ConstantInit": ("constant", ("constant", None)),
    "ZerosInit": ("constant", ("constant", None)),
    "OnesInit": ("constant", ("constant", None)),
    "UniformInit": ("uniform", ("low", "high")),
    "NormalInit": ("normal", ("mean", "stddev")),
    "TruncatedNormalInit": ("truncated_normal", ("mean", "stddev")),
}


def _ps_init_spec(node: PlaceholderOp):
    """Map a Variable's initializer onto the server-side init RPC
    (reference initializers.py:28-39 init_on_ps). Returns None when the value
    must be computed host-side and pushed instead (e.g. Xavier variants)."""
    init = node.initializer
    if init is None:
        return None
    spec = _INIT_SPEC_BY_CLASS.get(type(init).__name__)
    if spec is None:
        return None
    itype, (a_attr, b_attr) = spec
    a = float(getattr(init, a_attr, 0.0)) if a_attr else 0.0
    b = float(getattr(init, b_attr, 1.0)) if b_attr else 1.0
    return itype, a, b


class PSParam:
    """One PS-hosted parameter."""

    def __init__(self, node: PlaceholderOp, ps_id: int, sparse: bool):
        self.node = node
        self.ps_id = ps_id
        self.sparse = sparse
        self.shape = tuple(node.shape)
        self.cache = None            # CacheSparseTable when cstable_policy set
        self.lookup_ops: list[Op] = []
        self.host_value: Optional[np.ndarray] = None  # dense params only


class PSRuntime:
    """Owns the PS-hosted parameters of one Executor."""

    def __init__(self, config, topo: list[Op]):
        import os
        from .. import ps as ps_pkg
        self.config = config
        if ps_pkg._worker is None and os.environ.get("DMLC_PS_ROOT_URI"):
            # auto-bootstrap like the reference HetuConfig (executor.py:69)
            ps_pkg.worker_init()
        self.comm = ps_pkg.get_worker_communicate()
        self.bsp = bool(config.bsp)

        # -- identify PS-hosted params (reference context.py:146-148) -------
        embed_vars = set()
        lookups_by_var: dict[int, list[Op]] = {}
        for op in topo:
            embed = getattr(op, "embed_node", None)
            if embed is not None and isinstance(embed, PlaceholderOp):
                embed_vars.add(id(embed))
                lookups_by_var.setdefault(id(embed), []).append(op)
        self.params: dict[int, PSParam] = {}
        next_id = 0
        for op in topo:
            if not (isinstance(op, PlaceholderOp) and op.trainable):
                continue
            sparse = getattr(op, "is_embed", False) or id(op) in embed_vars
            if config.comm_mode == "Hybrid" and not sparse:
                continue  # dense params ride AllReduce in Hybrid
            if config.comm_mode == "PS" or sparse:
                p = PSParam(op, next_id, sparse)
                p.lookup_ops = lookups_by_var.get(id(op), [])
                self.params[id(op)] = p
                next_id += 1

        # optimizer config for the server (worker-side lr pre-scaling is used
        # for SGD, like the reference; stateful optimizers run server-side)
        self._opt_nodes = [n for n in topo if n.is_optimizer]
        self._server_opt = self._deduce_server_opt()
        self._init_params()

    # ------------------------------------------------------------------
    def _deduce_server_opt(self):
        import warnings
        for opt_node in self._opt_nodes:
            o = opt_node.optimizer
            name = type(o).__name__
            scheduled = hasattr(o.learning_rate, "get") or hasattr(
                o.learning_rate, "get_traced")
            lr = float(o.lr_value(0))
            if getattr(o, "l2reg", 0.0):
                raise NotImplementedError(
                    "l2reg is not applied server-side; PS-hosted params would "
                    "silently skip regularization — use l2reg=0 with "
                    "comm_mode PS/Hybrid or keep the param device-resident")
            if name == "SGDOptimizer":
                # prescale: the worker multiplies by -lr(step) each push, so
                # lr schedules are honored (reference _mult_lr)
                return {"otype": "sgd", "lrs": (lr,), "prescale": True,
                        "opt": o}
            if scheduled:
                raise NotImplementedError(
                    f"{name} with an lr scheduler: server-side optimizer "
                    "state is configured once at init, so the schedule would "
                    "be silently frozen — use SGDOptimizer (worker-side lr) "
                    "for PS-hosted params or a fixed lr")
            if name == "MomentumOptimizer":
                return {"otype": "nesterov" if o.nesterov else "momentum",
                        "lrs": (lr, o.momentum), "prescale": False, "opt": o}
            if name == "AdaGradOptimizer":
                return {"otype": "adagrad", "lrs": (lr, o.eps),
                        "prescale": False, "opt": o}
            if name in ("AdamOptimizer", "AdamWOptimizer"):
                return {"otype": "adam",
                        "lrs": (lr, o.beta1, o.beta2, o.epsilon),
                        "prescale": False, "opt": o}
        return {"otype": "sgd", "lrs": (0.01,), "prescale": True, "opt": None}

    def _prescale_lr(self, step: int) -> float:
        o = self._server_opt.get("opt")
        if o is None:
            return 0.01
        return float(o.lr_value(step))

    def _init_params(self):
        cfg = self.config
        if cfg.cstable_policy and not self._server_opt["prescale"]:
            raise NotImplementedError(
                "cstable_policy requires worker-side lr-scaled SGD: the "
                "cache applies raw pushed grads to its local rows, which "
                "diverges from a stateful server optimizer (the reference "
                "has the same restriction, ParameterServerCommunicate.py)")
        for p in self.params.values():
            opt = self._server_opt
            if p.sparse:
                rows, width = int(p.shape[0]), int(np.prod(p.shape[1:]))
                kind = 2 if cfg.cstable_policy else 1
            else:
                rows, width = int(np.prod(p.shape)), 1
                kind = 0
            spec = _ps_init_spec(p.node)
            if spec is not None:
                itype, a, b = spec
                self.comm.InitTensor(p.ps_id, kind, rows, width, itype, a, b,
                                     seed=cfg.seed + p.ps_id,
                                     opt_type=opt["otype"], lrs=opt["lrs"])
            else:
                # host-side init (explicit value or derived initializer like
                # Xavier): init zeros on the server, rank 0 pushes the value
                self.comm.InitTensor(p.ps_id, kind, rows, width, "constant",
                                     0.0, 1.0, seed=cfg.seed,
                                     opt_type=opt["otype"], lrs=opt["lrs"])
                if self.comm.rank == 0:
                    import jax
                    # per-param key (fold in ps_id): same-shape derived-init
                    # params must not share initial values, matching the
                    # device path's per-param fold_in (executor.py)
                    value = np.asarray(
                        p.node.instantiate(jax.random.fold_in(
                            jax.random.PRNGKey(cfg.seed), p.ps_id)),
                        dtype=np.float32)
                    # raw assignment: the value must not pass through the
                    # server optimizer (Adam would treat it as a gradient)
                    if p.sparse:
                        self.comm.SparseAssign(
                            p.ps_id, np.arange(rows, dtype=np.int64),
                            value.reshape(rows, width))
                    else:
                        self.comm.Assign(p.ps_id, value.ravel())
                self.comm.BarrierWorker()
            if p.sparse and cfg.cstable_policy:
                from ..cstable import CacheSparseTable
                limit = max(1, int(rows * 0.1))
                p.cache = CacheSparseTable(limit, rows, width, p.ps_id,
                                           policy=cfg.cstable_policy,
                                           bound=cfg.cache_bound)
            if not p.sparse:
                buf = np.zeros(rows, np.float32)
                self.comm.Pull(p.ps_id, buf)
                self.comm.Wait(p.ps_id)
                p.host_value = buf.reshape(p.shape)

    # ------------------------------------------------------------------
    # pre-step: stage embedding rows / dense values
    # ------------------------------------------------------------------
    def stage_lookup(self, p: PSParam, idx: np.ndarray) -> np.ndarray:
        """Pull the batch's rows (reference EmbeddingLookUp.py:27-40)."""
        width = int(np.prod(p.shape[1:]))
        flat = np.ascontiguousarray(idx, dtype=np.int64).ravel()
        dest = np.zeros((flat.size, width), np.float32)
        if p.cache is not None:
            p.cache.embedding_lookup(flat.astype(np.uint64), dest, sync=True)
        else:
            self.comm.SparsePull(p.ps_id, flat, dest)
            self.comm.Wait(p.ps_id)
        return dest.reshape(tuple(idx.shape) + tuple(p.shape[1:]))

    # ------------------------------------------------------------------
    # post-step: push gradients
    # ------------------------------------------------------------------
    def push_grad(self, p: PSParam, grad: np.ndarray,
                  idx: Optional[np.ndarray], step: int = 0):
        opt = self._server_opt
        if p.sparse:
            width = int(np.prod(p.shape[1:]))
            flat_idx = np.ascontiguousarray(idx, dtype=np.int64).ravel()
            g = np.asarray(grad, np.float32).reshape(flat_idx.size, width)
            if opt["prescale"]:
                g = -self._prescale_lr(step) * g
            if p.cache is not None:
                p.cache.embedding_update(flat_idx.astype(np.uint64), g,
                                         sync=True)
            else:
                self.comm.SparsePush(p.ps_id, flat_idx, g)
                self.comm.Wait(p.ps_id)
        else:
            g = np.asarray(grad, np.float32).ravel()
            if opt["prescale"]:
                g = -self._prescale_lr(step) * g
            out = np.empty_like(p.host_value).ravel()
            self.comm.DDPushPull(p.ps_id, g, out)
            self.comm.Wait(p.ps_id)
            p.host_value = out.reshape(p.shape)
        if self.bsp:
            self.comm.BarrierWorker()

    # ------------------------------------------------------------------
    def save(self, directory: str):
        """Server-side checkpoint of PS params (reference executor.py:355)."""
        if self.comm.rank == 0:
            for p in self.params.values():
                self.comm.SaveParam(p.ps_id, directory)
        self.comm.BarrierWorker()

    def load(self, directory: str):
        if self.comm.rank == 0:
            for p in self.params.values():
                self.comm.LoadParam(p.ps_id, directory)
        self.comm.BarrierWorker()
        for p in self.params.values():
            if not p.sparse:
                buf = np.zeros(int(np.prod(p.shape)), np.float32)
                self.comm.Pull(p.ps_id, buf)
                self.comm.Wait(p.ps_id)
                p.host_value = buf.reshape(p.shape)

    def pull_dense_value(self, p: PSParam) -> np.ndarray:
        buf = np.zeros(int(np.prod(p.shape)), np.float32)
        self.comm.Pull(p.ps_id, buf)
        self.comm.Wait(p.ps_id)
        return buf.reshape(p.shape)

    def pull_sparse_rows(self, p: PSParam, idx: np.ndarray) -> np.ndarray:
        return self.stage_lookup(p, idx)
