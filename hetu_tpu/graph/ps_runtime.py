"""PS/Hybrid execution: bridges the jitted XLA step to the host-resident
parameter server.

Reference behavior being matched (``gpu_ops/ParameterServerCommunicate.py``,
``EmbeddingLookUp.py``):
  - sparse embedding tables live on the PS, never on the accelerator; each
    step pulls only the batch's rows (SparsePull / cache lookup, forward_hook
    :122-231) and pushes only their gradients (SSPushPull / cache push-pull)
  - dense params under comm_mode='PS' live on the PS; workers push lr-scaled
    gradients and pull fresh values (DDPushPull, worker-side ``_mult_lr``
    :24-25, :52-60)
  - ASP by default; BSP adds a worker barrier per step (:42-46)
  - optional bounded-staleness client cache (``cstable_policy``)

TPU-native redesign: the reference interleaves PS RPCs *inside* the op
interpreter via a d2h stream + events. Here the jitted step is a pure XLA
program; PS traffic happens at its boundary:
  - pre-step (host): pull batch rows for every PS-hosted embedding lookup,
    feed them as extra inputs
  - in-trace: the lookup op returns the staged rows; gradient nodes are
    rewired from the table variable to the lookup output, so the grad leaves
    the program as a (batch_rows, width) tensor, not a full-table scatter
  - post-step (host): push row gradients (and dense grads) to the PS
"""
from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Optional

import numpy as np

from .. import telemetry as _telemetry

from .node import Op, PlaceholderOp, find_topo_sort
from .ops.ps import ParameterServerCommunicateOp, ParameterServerSparsePullOp


class _SerialIO:
    """A dedicated thread running submitted closures in order.

    The PS worker agent's C++ side is thread-safe (its own pool + per-tensor
    tickets), but the Python client keeps shared staging state, so all client
    calls from one logical stream go through one of these; cross-stream calls
    are guarded by the runtime's rpc lock around the issue phase."""

    def __init__(self, name: str):
        self._q: "queue.Queue" = queue.Queue()
        self._t = threading.Thread(target=self._loop, name=name, daemon=True)
        self._t.start()

    def _loop(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, fn = item
            if not fut.set_running_or_notify_cancel():
                continue
            try:
                fut.set_result(fn())
            except BaseException as e:  # noqa: BLE001 — delivered via future
                fut.set_exception(e)

    def submit(self, fn) -> Future:
        fut: Future = Future()
        self._q.put((fut, fn))
        return fut

    def drain(self):
        """Block until everything submitted so far has completed."""
        self.submit(lambda: None).result()

    def stop(self):
        self.drain()
        self._q.put(None)
        self._t.join(timeout=10)


def _dedup_sum_rows(flat_idx: np.ndarray, g: np.ndarray):
    """Sum duplicate rows before the wire: a stateful server optimizer
    (momentum/adagrad/adam) must see ONE summed grad per row per step, not
    one state update per occurrence; for prescaled SGD this is equivalent
    and just shrinks the RPC.

    Vectorized sort + ``np.add.reduceat`` over contiguous runs — the
    previous ``np.add.at(acc, inv, g)`` was a single-threaded Python-rate
    scatter loop sitting in every PS sparse push (the CTR inner loop).
    ``reduceat`` sums each run with numpy's pairwise reduction, which is
    at least as accurate as the scatter loop's strictly-sequential f32
    adds (regression-tested within f32 rounding against both the old
    path and a float64 oracle on duplicate-heavy indices)."""
    uniq, inv = np.unique(flat_idx, return_inverse=True)
    if uniq.size == flat_idx.size:
        return flat_idx, g
    order = np.argsort(inv, kind="stable")
    starts = np.searchsorted(inv[order], np.arange(uniq.size))
    acc = np.add.reduceat(g[order], starts, axis=0)
    return uniq, np.ascontiguousarray(acc, np.float32)


_INIT_SPEC_BY_CLASS = {
    # initializer class name -> (ps init_type, (a_attr, b_attr))
    "ConstantInit": ("constant", ("constant", None)),
    "ZerosInit": ("constant", ("constant", None)),
    "OnesInit": ("constant", ("constant", None)),
    "UniformInit": ("uniform", ("low", "high")),
    "NormalInit": ("normal", ("mean", "stddev")),
    "TruncatedNormalInit": ("truncated_normal", ("mean", "stddev")),
}


def _ps_init_spec(node: PlaceholderOp):
    """Map a Variable's initializer onto the server-side init RPC
    (reference initializers.py:28-39 init_on_ps). Returns None when the value
    must be computed host-side and pushed instead (e.g. Xavier variants)."""
    init = node.initializer
    if init is None:
        return None
    spec = _INIT_SPEC_BY_CLASS.get(type(init).__name__)
    if spec is None:
        return None
    itype, (a_attr, b_attr) = spec
    a = float(getattr(init, a_attr, 0.0)) if a_attr else 0.0
    b = float(getattr(init, b_attr, 1.0)) if b_attr else 1.0
    return itype, a, b


class PSParam:
    """One PS-hosted parameter."""

    def __init__(self, node: PlaceholderOp, ps_id: int, sparse: bool):
        self.node = node
        self.ps_id = ps_id
        self.sparse = sparse
        self.shape = tuple(node.shape)
        self.cache = None            # CacheSparseTable when cstable_policy set
        self.lookup_ops: list[Op] = []
        self.host_value: Optional[np.ndarray] = None  # dense params only


class PSRuntime:
    """Owns the PS-hosted parameters of one Executor."""

    def __init__(self, config, topo: list[Op]):
        import os
        from .. import ps as ps_pkg
        self.config = config
        if ps_pkg._worker is None and os.environ.get("DMLC_PS_ROOT_URI"):
            # auto-bootstrap like the reference HetuConfig (executor.py:69)
            ps_pkg.worker_init()
        self.comm = ps_pkg.get_worker_communicate()
        self.bsp = bool(config.bsp)
        # hetuq (docs/COMM_QUANT.md): arm/disarm the worker's quantized wire
        # explicitly — the communicator is a process singleton, so an A/B of
        # two executors must not inherit the other leg's setting. The PS
        # wire container is int8 either way (fp8 is an AllReduce-only mode).
        self.comm_quant = getattr(config, "comm_quant", "off") or "off"
        if hasattr(self.comm, "SetCommQuant"):
            self.comm.SetCommQuant(self.comm_quant != "off")

        # -- identify PS-hosted params (reference context.py:146-148) -------
        embed_vars = set()
        lookups_by_var: dict[int, list[Op]] = {}
        for op in topo:
            embed = getattr(op, "embed_node", None)
            if embed is not None and isinstance(embed, PlaceholderOp):
                embed_vars.add(id(embed))
                lookups_by_var.setdefault(id(embed), []).append(op)
        self.params: dict[int, PSParam] = {}
        # id base lets multiple Executors in one process address disjoint
        # server tensors (e.g. A/B runs against one live cluster)
        next_id = int(os.environ.get("HETU_PS_ID_BASE", "0"))
        for op in topo:
            if not (isinstance(op, PlaceholderOp) and op.trainable):
                continue
            sparse = getattr(op, "is_embed", False) or id(op) in embed_vars
            if config.comm_mode == "Hybrid" and not sparse:
                continue  # dense params ride AllReduce in Hybrid
            if config.comm_mode == "PS" or sparse:
                p = PSParam(op, next_id, sparse)
                p.lookup_ops = lookups_by_var.get(id(op), [])
                self.params[id(op)] = p
                next_id += 1

        # optimizer config for the server (worker-side lr pre-scaling is used
        # for SGD, like the reference; stateful optimizers run server-side)
        self._opt_nodes = [n for n in topo if n.is_optimizer]
        self._server_opt = self._deduce_server_opt()
        self._init_params()

        # -- async I/O (reference prefetch x ASP/BSP matrix,
        #    ParameterServerCommunicate.py:122-231) ------------------------
        # push stream: syncs the device grads (off the critical path) then
        # pushes; pull stream: issues batch N+1's row pulls while step N
        # computes. Under BSP the pull stream IS the push stream, so the
        # ordering push -> barrier -> pull is exact; under ASP the streams
        # race, giving the reference's staleness-by-one-step semantics.
        self.async_enabled = bool(config.prefetch)
        self._rpc_lock = threading.Lock()
        self._io_push: Optional[_SerialIO] = None
        self._io_pull: Optional[_SerialIO] = None
        if self.async_enabled:
            self._io_push = _SerialIO("hetu-ps-push")
            self._io_pull = (self._io_push if self.bsp
                             else _SerialIO("hetu-ps-pull"))
        self._prefetched: dict[int, tuple[np.ndarray, Future]] = {}
        self._pending_pushes: list[Future] = []
        self._dense_push_fut: dict[int, Future] = {}
        self.perf = {"sync_pulls": 0, "prefetch_issued": 0,
                     "prefetch_hits": 0, "prefetch_misses": 0,
                     "async_pushes": 0}
        # telemetry (docs/OBSERVABILITY.md): RPC latency/bytes observed from
        # the push/pull stream threads; None when off — handles cached here
        # so the streams pay one attribute read per RPC, not a registry walk
        self.tel = _telemetry.get()
        if self.tel is not None:
            reg = self.tel.metrics
            self._m_pull_ms = reg.histogram("hetu_ps_pull_ms")
            self._m_push_ms = reg.histogram("hetu_ps_push_ms")
            self._m_pull_bytes = reg.counter("hetu_ps_pull_bytes_total")
            self._m_push_bytes = reg.counter("hetu_ps_push_bytes_total")
            self._m_pref_hits = reg.counter("hetu_ps_prefetch_hits_total")
            self._m_pref_miss = reg.counter("hetu_ps_prefetch_misses_total")
        # hetutrail (docs/OBSERVABILITY.md pillar 5): the native worker's
        # client-span ring (armed by the same HETU_TRAIL_DIR the C++ side
        # checks) is drained at every step boundary into
        # trail-client-r<rank>.jsonl. None when off — the executor's
        # boundary hook pays one attribute check and nothing else.
        from ..telemetry import trail as _trail
        self._trail_mod = _trail
        self.trail_writer = None
        # the span ring is drained on a cadence, not per step: one drain
        # amortizes the ctypes round trip + JSON serialization over N
        # steps (the ring holds HETU_TRAIL_RING spans — with ~a dozen RPCs
        # per step that is thousands of steps of headroom), keeping
        # always-on cost inside the <2% budget
        self._trail_every = max(1, int(os.environ.get(
            "HETU_TRAIL_DRAIN_EVERY", "64")))
        trail_dir = _trail.armed()
        if trail_dir is not None and hasattr(self.comm, "SetTrailStep"):
            try:
                self.trail_writer = _trail.TrailWriter(
                    os.path.join(trail_dir,
                                 f"trail-client-r{self.comm.rank}.jsonl"),
                    self.comm.rank)
                self.comm.SetTrailStep(0)
            except OSError:
                self.trail_writer = None  # unwritable dir: trail off
        if hasattr(self.comm, "SetTrail"):
            # explicit arm/disarm (the SetCommQuant pattern): the worker is
            # a process singleton — an A/B of two executors must not
            # inherit the other leg's ring state
            self.comm.SetTrail(self.trail_writer is not None)
        ps_pkg._register_runtime(self)  # drained at worker_finish

    # ------------------------------------------------------------------
    def _deduce_server_opt(self):
        """Map the graph optimizer onto the server-side optimizer config.

        lr schedules, l2reg, and decoupled weight decay are honored through
        PER-STEP push opts: before each step's pushes the worker refreshes
        [lr(step), l2reg, weight_decay] on the tensor (SetPushOpts), carried
        as a trailing arg on the push RPC and applied under the param lock
        (store.h UpdateOpts) — reference behavior is the server applying
        whatever lr arrives with the push (optimizer.h:15-75)."""
        for opt_node in self._opt_nodes:
            o = opt_node.optimizer
            name = type(o).__name__
            scheduled = hasattr(o.learning_rate, "get") or hasattr(
                o.learning_rate, "get_traced")
            lr = float(o.lr_value(0))
            l2reg = float(getattr(o, "l2reg", 0.0) or 0.0)
            wd = float(getattr(o, "weight_decay", 0.0) or 0.0)
            if name == "SGDOptimizer":
                # prescale: the worker multiplies by -lr(step) each push, so
                # lr schedules are honored (reference _mult_lr); the l2 term
                # additionally needs the raw lr server-side, so l2reg rides
                # the push opts (server: w += grad - lr*l2reg*w)
                return {"otype": "sgd", "lrs": (lr,), "prescale": True,
                        "opt": o, "l2reg": l2reg, "wd": 0.0,
                        "per_step": l2reg > 0.0}
            base = None
            if name == "MomentumOptimizer":
                base = {"otype": "nesterov" if o.nesterov else "momentum",
                        "lrs": (lr, o.momentum)}
            elif name == "AdaGradOptimizer":
                base = {"otype": "adagrad", "lrs": (lr, o.eps)}
            elif name in ("AdamOptimizer", "AdamWOptimizer"):
                base = {"otype": "adam",
                        "lrs": (lr, o.beta1, o.beta2, o.epsilon)}
            if base is not None:
                base.update(prescale=False, opt=o, l2reg=l2reg, wd=wd,
                            per_step=scheduled or l2reg > 0.0 or wd > 0.0)
                if (l2reg > 0.0 or wd > 0.0) and any(
                        p.sparse for p in self.params.values()):
                    # lazy regularization: the server shrinks only the rows a
                    # step pushes. Standard for sparse training, but it is a
                    # semantic difference from a device-resident table (dense
                    # grads regularize every row every step) — say so once.
                    import warnings
                    warnings.warn(
                        "l2reg/weight_decay on PS-hosted sparse embeddings "
                        "is LAZY: only rows present in a batch are "
                        "regularized (device-resident tables shrink all rows "
                        "every step)", stacklevel=3)
                return base
        return {"otype": "sgd", "lrs": (0.01,), "prescale": True, "opt": None,
                "l2reg": 0.0, "wd": 0.0, "per_step": False}

    def _prescale_lr(self, step: int) -> float:
        o = self._server_opt.get("opt")
        if o is None:
            return 0.01
        return float(o.lr_value(step))

    def _init_params(self):
        import os
        cfg = self.config
        # hetu-elastic late joiner: the PS tables already hold TRAINED
        # state (InitTensor is idempotent server-side so declaring them is
        # safe), but the host-side value push would DESTROY it, and the
        # init barrier would park forever — the peers are training, not
        # bootstrapping. Skip both; dense host_value pulls below fetch the
        # live values.
        joiner = bool(os.environ.get("HETU_ELASTIC_JOIN"))
        if cfg.cstable_policy and (not self._server_opt["prescale"]
                                   or self._server_opt["l2reg"] > 0.0):
            raise NotImplementedError(
                "cstable_policy requires worker-side lr-scaled SGD without "
                "l2reg: the cache applies raw pushed grads to its local "
                "rows, which diverges from a stateful/regularizing server "
                "optimizer (the reference has the same restriction, "
                "ParameterServerCommunicate.py)")
        for p in self.params.values():
            opt = self._server_opt
            if p.sparse:
                rows, width = int(p.shape[0]), int(np.prod(p.shape[1:]))
                kind = 2 if cfg.cstable_policy else 1
            else:
                rows, width = int(np.prod(p.shape)), 1
                kind = 0
            spec = _ps_init_spec(p.node)
            if spec is not None:
                itype, a, b = spec
                self.comm.InitTensor(p.ps_id, kind, rows, width, itype, a, b,
                                     seed=cfg.seed + p.ps_id,
                                     opt_type=opt["otype"], lrs=opt["lrs"])
            else:
                # host-side init (explicit value or derived initializer like
                # Xavier): init zeros on the server, rank 0 pushes the value
                self.comm.InitTensor(p.ps_id, kind, rows, width, "constant",
                                     0.0, 1.0, seed=cfg.seed,
                                     opt_type=opt["otype"], lrs=opt["lrs"])
                if not joiner and self.comm.rank == 0:
                    import jax
                    # per-param key (fold in ps_id): same-shape derived-init
                    # params must not share initial values, matching the
                    # device path's per-param fold_in (executor.py)
                    value = np.asarray(
                        p.node.instantiate(jax.random.fold_in(
                            jax.random.PRNGKey(cfg.seed), p.ps_id)),
                        dtype=np.float32)
                    # raw assignment: the value must not pass through the
                    # server optimizer (Adam would treat it as a gradient)
                    if p.sparse:
                        self.comm.SparseAssign(
                            p.ps_id, np.arange(rows, dtype=np.int64),
                            value.reshape(rows, width))
                    else:
                        self.comm.Assign(p.ps_id, value.ravel())
                if not joiner:
                    self.comm.BarrierWorker()
            if p.sparse and cfg.cstable_policy:
                from ..cstable import CacheSparseTable
                limit = max(1, int(rows * 0.1))
                p.cache = CacheSparseTable(limit, rows, width, p.ps_id,
                                           policy=cfg.cstable_policy,
                                           bound=cfg.cache_bound)
                if _telemetry.get() is not None:
                    # arm the C++ perf counters the telemetry poll reads;
                    # rollup-only — the per-batch log would grow unbounded
                    # over a long run
                    p.cache.perf_enabled(True, rollup_only=True)
            if not p.sparse:
                buf = np.zeros(rows, np.float32)
                self.comm.Pull(p.ps_id, buf)
                self.comm.Wait(p.ps_id)
                p.host_value = buf.reshape(p.shape)

    # ------------------------------------------------------------------
    # pre-step: stage embedding rows / dense values
    # ------------------------------------------------------------------
    def _pull_rows(self, p: PSParam, idx: np.ndarray) -> np.ndarray:
        tel = self.tel
        t0 = time.perf_counter() if tel is not None else 0.0
        width = int(np.prod(p.shape[1:]))
        flat = np.ascontiguousarray(idx, dtype=np.int64).ravel()
        dest = np.zeros((flat.size, width), np.float32)
        if p.cache is not None:
            with self._rpc_lock:
                p.cache.embedding_lookup(flat.astype(np.uint64), dest,
                                         sync=True)
        else:
            with self._rpc_lock:
                self.comm.SparsePull(p.ps_id, flat, dest)
            self.comm.Wait(p.ps_id)
        if tel is not None:
            t1 = time.perf_counter()
            self._m_pull_ms.observe((t1 - t0) * 1e3)
            self._m_pull_bytes.inc(dest.nbytes)
            if tel.tracer is not None:
                tel.tracer.complete("ps_pull", t0, t1, cat="ps",
                                    args={"rows": int(flat.size),
                                          "tensor": p.ps_id})
        return dest.reshape(tuple(idx.shape) + tuple(p.shape[1:]))

    def stage_lookup(self, p: PSParam, idx: np.ndarray) -> np.ndarray:
        """Pull the batch's rows (reference EmbeddingLookUp.py:27-40).

        When async I/O is on, the pull rides the pull STREAM instead of
        running inline: under BSP the pull stream is the push stream, so the
        pull queues behind this worker's in-flight pushes (and the barrier) —
        a direct inline pull could read rows the step-N pushes haven't
        reached yet. Covers prefetch misses, feed-fed lookups, and the
        shared-table union pull alike."""
        self.perf["sync_pulls"] += 1
        if self.async_enabled:
            return self._io_pull.submit(
                lambda: self._pull_rows(p, idx)).result()
        return self._pull_rows(p, idx)

    def prefetch_lookup(self, key: int, p: PSParam, idx: np.ndarray):
        """Issue batch N+1's row pull on the pull stream (reference prefetch,
        ParameterServerCommunicate.py:122-231). Under ASP the pull races this
        step's push — staleness bounded by one step, like the reference;
        under BSP the pull stream is the push stream, so ordering is exact."""
        idx = np.array(idx, copy=True)
        self.perf["prefetch_issued"] += 1
        self._prefetched[key] = (idx, self._io_pull.submit(
            lambda: self._pull_rows(p, idx)))

    def take_prefetched(self, key: int, idx) -> Optional[np.ndarray]:
        ent = self._prefetched.pop(key, None)
        if ent is None:
            return None
        expected, fut = ent
        if np.array_equal(expected, np.asarray(idx)):
            self.perf["prefetch_hits"] += 1
            if self.tel is not None:
                self._m_pref_hits.inc()
            return fut.result()
        self.perf["prefetch_misses"] += 1
        if self.tel is not None:
            self._m_pref_miss.inc()
        fut.result()  # let it finish; the pulled rows are simply unused
        return None

    def wait_dense(self, p: PSParam):
        """Block until the latest async DDPushPull for ``p`` has refreshed
        ``host_value``."""
        fut = self._dense_push_fut.get(id(p.node))
        if fut is not None:
            fut.result()

    # ------------------------------------------------------------------
    # post-step: push gradients
    # ------------------------------------------------------------------
    def _refresh_push_opts(self, p: PSParam, step: int):
        """Refresh this tensor's per-step [lr(step), l2reg, weight_decay]
        push opts before the step's pushes (no-op unless the optimizer needs
        them: schedule on a stateful server optimizer, l2reg, or AdamW wd)."""
        opt = self._server_opt
        if not opt.get("per_step"):
            return
        o = opt.get("opt")
        lr = float(o.lr_value(step)) if o is not None else float(opt["lrs"][0])
        self.comm.SetPushOpts(p.ps_id, lr, opt["l2reg"], opt["wd"])

    def _push_one(self, p: PSParam, grad, idx, step: int) -> None:
        tel = self.tel
        if tel is None:
            self._push_one_body(p, grad, idx, step)
            return
        t0 = time.perf_counter()
        pushed = self._push_one_body(p, grad, idx, step)
        t1 = time.perf_counter()
        self._m_push_ms.observe((t1 - t0) * 1e3)
        self._m_push_bytes.inc(pushed)
        if tel.tracer is not None:
            tel.tracer.complete("ps_push", t0, t1, cat="ps",
                                args={"tensor": p.ps_id,
                                      "bytes": int(pushed)})

    def _push_one_body(self, p: PSParam, grad, idx, step: int) -> int:
        """Returns the pushed payload size in bytes (grad values; the
        timing around it includes the device sync np.asarray implies)."""
        opt = self._server_opt
        self._refresh_push_opts(p, step)
        if p.sparse:
            from .ops.embedding import IndexedRows
            width = int(np.prod(p.shape[1:]))
            if isinstance(grad, IndexedRows):
                # hetukern rows-mode push: the device already emitted
                # unique sorted (rows, grads); trim the vocab-sentinel
                # padding tail and skip the host dedup entirely. Ids
                # outside [0, vocab) — negative padding ids included —
                # are DROPPED, never wrapped: a padding slot must not
                # update a real row (documented divergence from the dense
                # scatter's numpy-style negative wrap, docs/KERNELS.md)
                flat_idx = np.asarray(grad.rows, np.int64).ravel()
                g = np.asarray(grad.grads,
                               np.float32).reshape(flat_idx.size, width)
                keep = (flat_idx >= 0) & (flat_idx < int(p.shape[0]))
                if not keep.all():
                    flat_idx, g = flat_idx[keep], np.ascontiguousarray(
                        g[keep])
            else:
                if isinstance(grad, (tuple, list)):
                    # shared table: concatenate the per-lookup row grads/
                    # indices (the reference's IndexedSlices accumulation)
                    flat_idx = np.concatenate(
                        [np.ascontiguousarray(i, np.int64).ravel()
                         for i in idx])
                    g = np.concatenate(
                        [np.asarray(gi, np.float32).reshape(-1, width)
                         for gi in grad], axis=0)
                else:
                    flat_idx = np.ascontiguousarray(idx,
                                                    dtype=np.int64).ravel()
                    g = np.asarray(grad,
                                   np.float32).reshape(flat_idx.size, width)
                flat_idx, g = _dedup_sum_rows(flat_idx, g)
            if opt["prescale"]:
                g = -self._prescale_lr(step) * g
            if p.cache is not None:
                with self._rpc_lock:
                    p.cache.embedding_update(flat_idx.astype(np.uint64), g,
                                             sync=True)
            else:
                with self._rpc_lock:
                    self.comm.SparsePush(p.ps_id, flat_idx, g)
                self.comm.Wait(p.ps_id)
            return g.nbytes + flat_idx.nbytes
        else:
            g = np.asarray(grad, np.float32).ravel()
            if opt["prescale"]:
                g = -self._prescale_lr(step) * g
            out = np.empty_like(p.host_value).ravel()
            with self._rpc_lock:
                self.comm.DDPushPull(p.ps_id, g, out)
            self.comm.Wait(p.ps_id)
            p.host_value = out.reshape(p.shape)
            return g.nbytes

    def push_grad(self, p: PSParam, grad: np.ndarray,
                  idx: Optional[np.ndarray], step: int = 0):
        """Synchronous push (prefetch=False path)."""
        self._push_one(p, grad, idx, step)
        if self.bsp:
            self.comm.BarrierWorker()

    def push_grads_async(self, items, step: int):
        """Enqueue one step's pushes on the push stream. ``items`` is
        ``[(PSParam, device_grad, idx_or_None), ...]`` — the device sync
        (np.asarray of a possibly-unfinished jax array) happens on the push
        thread, so the caller returns before the step has even finished on
        the accelerator."""

        def _do():
            for p, grad, idx in items:
                self._push_one(p, grad, idx, step)
            if self.bsp:
                self.comm.BarrierWorker()
            self.perf["async_pushes"] += len(items)

        fut = self._io_push.submit(_do)
        self._pending_pushes.append(fut)
        if len(self._pending_pushes) > 64:
            # bound the backlog: the oldest push must land before we pile on
            self._pending_pushes.pop(0).result()
        for p, _, _ in items:
            if not p.sparse:
                self._dense_push_fut[id(p.node)] = fut
        return fut

    def trail_step_boundary(self, step: int) -> None:
        """hetutrail: drain the step's client spans into the trail file and
        stamp the NEXT step id onto subsequent RPCs. Spans issued by async
        pushes that land after the boundary carry the next step's stamp —
        a documented one-step skew, matching the prefetch overlap they ride
        with. Never raises."""
        w = self.trail_writer
        if w is None:
            return
        try:
            self.comm.SetTrailStep(int(step) + 1)
            if (int(step) + 1) % self._trail_every:
                return   # off-cadence boundary: stamp only
            with self._rpc_lock:
                self._trail_mod.drain_client_spans(self.comm, w)
        except Exception:  # noqa: BLE001 — observability only
            pass

    def drain(self):
        """Complete all in-flight async PS traffic (checkpoint/fetch/shutdown
        boundaries)."""
        if self._io_push is not None:
            self._io_push.drain()
        if self._io_pull is not None and self._io_pull is not self._io_push:
            self._io_pull.drain()
        for fut in self._pending_pushes:
            fut.result()
        self._pending_pushes.clear()

    def shutdown(self):
        """Stop the async I/O threads (after draining)."""
        if self._io_push is not None:
            self._io_push.stop()
        if self._io_pull is not None and self._io_pull is not self._io_push:
            self._io_pull.stop()
        self._io_push = self._io_pull = None
        self.async_enabled = False
        if self.trail_writer is not None:
            # final drain: the last (partial) step's spans, post-streams
            try:
                with self._rpc_lock:
                    self._trail_mod.drain_client_spans(self.comm,
                                                       self.trail_writer)
            except Exception:  # noqa: BLE001
                pass
            self.trail_writer.close()
            self.trail_writer = None

    # ------------------------------------------------------------------
    def save(self, directory: str):
        """Server-side checkpoint of PS params (reference executor.py:355)."""
        self.drain()
        if self.comm.rank == 0:
            for p in self.params.values():
                self.comm.SaveParam(p.ps_id, directory)
        self.comm.BarrierWorker()

    def load(self, directory: str):
        self.drain()
        self._prefetched.clear()  # prefetched rows predate the restore
        if self.comm.rank == 0:
            for p in self.params.values():
                self.comm.LoadParam(p.ps_id, directory)
        self.comm.BarrierWorker()
        for p in self.params.values():
            if not p.sparse:
                buf = np.zeros(int(np.prod(p.shape)), np.float32)
                self.comm.Pull(p.ps_id, buf)
                self.comm.Wait(p.ps_id)
                p.host_value = buf.reshape(p.shape)

    def pull_dense_value(self, p: PSParam) -> np.ndarray:
        self.drain()
        buf = np.zeros(int(np.prod(p.shape)), np.float32)
        self.comm.Pull(p.ps_id, buf)
        self.comm.Wait(p.ps_id)
        return buf.reshape(p.shape)

    def pull_sparse_rows(self, p: PSParam, idx: np.ndarray) -> np.ndarray:
        self.drain()
        return self._pull_rows(p, idx)

    # ------------------------------------------------------------------
    def telemetry_stats(self) -> list[dict]:
        """PS-tier health rows for the telemetry JSONL (polled by the
        executor on its HETU_TELEMETRY_PS_EVERY cadence): one ``ps_server``
        row per server (the extended kServerStats: updates, snapshot
        coverage/age/version, request count, apply latency, dedup-ledger
        occupancy), plus worker-side retry/failover counters and embedding-
        cache hit/data rates as registry metrics. Never raises — a health
        poll must not take training down with it."""
        rows: list[dict] = []
        if self.tel is None:
            return rows
        reg = self.tel.metrics
        try:
            for s in range(self.comm.num_servers):
                with self._rpc_lock:
                    st = self.comm.ServerStats(s)
                rows.append({"kind": "ps_server", "server": s, **st})
        except Exception:  # noqa: BLE001 — diagnostics only
            pass
        try:
            with self._rpc_lock:
                cs = self.comm.ClientStats()
            reg.gauge("hetu_ps_rpcs_total").set(cs["rpcs"])
            reg.gauge("hetu_ps_retries_total").set(cs["retries"])
            reg.gauge("hetu_ps_failovers_total").set(cs["failovers"])
            # acknowledged pushes: the client-side half of hetustory's
            # push-accounting audit (== Σ server updates − restored)
            reg.gauge("hetu_ps_pushes_ok_total").set(cs.get("pushes_ok", 0))
            # hetuchaos transport hardening (docs/FAULT_TOLERANCE.md):
            # recv/deadline timeouts, total retry backoff slept, CRC
            # rejects observed (server + response-leg), and faults an
            # armed chaos schedule injected (0 in production — arming is
            # HETU_TEST_MODE-gated)
            reg.gauge("hetu_rpc_timeouts_total").set(cs.get("timeouts", 0))
            reg.gauge("hetu_rpc_backoff_ms").set(cs.get("backoff_ms", 0))
            reg.gauge("hetu_crc_rejects_total").set(
                cs.get("crc_rejects", 0))
            reg.gauge("hetu_chaos_faults_total").set(
                cs.get("chaos_faults", 0))
            # hetuq raw-vs-wire accounting (worker.h value payloads; with
            # quantization off raw == wire) — what hetutop's PS panel shows
            # as the measured compression ratio
            raw = cs.get("quant_raw_bytes", 0)
            wire = cs.get("quant_wire_bytes", 0)
            if raw or wire:
                reg.gauge("hetu_comm_quant_raw_bytes_total").set(raw)
                reg.gauge("hetu_comm_quant_wire_bytes_total").set(wire)
                if wire:
                    reg.gauge("hetu_comm_quant_ratio").set(
                        round(raw / wire, 4))
        except Exception:  # noqa: BLE001
            pass
        for p in self.params.values():
            if p.cache is None:
                continue
            try:
                s = p.cache.telemetry_summary()
            except Exception:  # noqa: BLE001
                continue
            labels = {"tensor": str(p.ps_id)}
            if s["miss_rate"] >= 0:
                reg.gauge("hetu_cache_hit_rate", labels).set(
                    1.0 - s["miss_rate"])
            if s["data_rate"] >= 0:
                reg.gauge("hetu_cache_data_rate", labels).set(s["data_rate"])
            reg.gauge("hetu_cache_evictions_total", labels).set(
                s["evictions"])
        return rows
