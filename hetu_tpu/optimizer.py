"""Optimizers: SGD / Momentum(+Nesterov) / AdaGrad / Adam (+AdamW).

Capability parity with the reference's ``python/hetu/optimizer.py``
(Optimizer :13, OptimizerOp :85, minimize :64). The reference applies updates
with fused CUDA kernels (``src/ops/Optimizers.cu``) and rewrites gradient
inputs into AllReduce/PS communication ops in ``backward_hook`` (:125-139).
Here the update rules are pure jax expressions traced into the same XLA
program as the step (XLA fuses them into the gradient epilogue), and the
comm-op rewrite happens once in ``OptimizerOp.insert_comm_ops`` at executor
construction.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax.numpy as jnp

from .graph.node import Op, PlaceholderOp, find_topo_sort
from .graph.gradients import gradients


class Optimizer:
    """Base optimizer holding the lr (float or an ``lr_scheduler``).

    ``clip_grad_norm`` clips the GLOBAL gradient norm (all trainable vars
    together, torch ``clip_grad_norm_`` semantics) before the update rule;
    the fused norm reduction it computes is published to the trace context
    so the hetuscope introspection pass reuses it instead of re-reducing
    (one computation, two consumers). PS-resident parameters update
    server-side per gradient push and are NOT clipped (their grads never
    reach ``apply_dense``); the norm is taken over the locally-applied
    gradients only.
    """

    def __init__(self, learning_rate, l2reg=0.0, clip_grad_norm=None):
        self.learning_rate = learning_rate
        self.l2reg = float(l2reg)
        if clip_grad_norm is not None and float(clip_grad_norm) <= 0:
            raise ValueError(
                f"clip_grad_norm must be > 0, got {clip_grad_norm}")
        self.clip_grad_norm = (None if clip_grad_norm is None
                               else float(clip_grad_norm))

    # -- graph construction -------------------------------------------------
    def minimize(self, loss, var_list: Optional[Sequence[Op]] = None):
        if var_list is None:
            var_list = [n for n in find_topo_sort([loss])
                        if isinstance(n, PlaceholderOp) and n.trainable]
        grads = gradients(loss, var_list)
        return OptimizerOp(grads, self, var_list)

    def get_gradients(self, loss, var_list=None):
        if var_list is None:
            var_list = [n for n in find_topo_sort([loss])
                        if isinstance(n, PlaceholderOp) and n.trainable]
        return gradients(loss, var_list), var_list

    # -- traced update rules -------------------------------------------------
    def lr_value(self, step):
        lr = self.learning_rate
        if hasattr(lr, "get_traced"):
            return lr.get_traced(step)
        if hasattr(lr, "get"):
            return lr.get()
        return lr

    def _regularized(self, param, grad):
        if self.l2reg > 0:
            return grad + self.l2reg * param
        return grad

    def slot_init(self, param):
        return ()

    def cache_token(self):
        """Host-side state that gets baked into the traced step as constants
        (e.g. ReduceOnPlateau's current lr) — part of the compile-cache key."""
        lr = self.learning_rate
        if hasattr(lr, "host_token"):
            return lr.host_token()
        return None

    def apply_dense(self, param, grad, slot, lr):
        raise NotImplementedError


class SGDOptimizer(Optimizer):
    def __init__(self, learning_rate=0.01, l2reg=0.0, clip_grad_norm=None):
        super().__init__(learning_rate, l2reg, clip_grad_norm)

    def apply_dense(self, param, grad, slot, lr):
        # hetukern (docs/KERNELS.md): one registry dispatch in EVERY mode
        # — "off" serves fused_opt._sgd_xla, which is the pre-hetukern
        # expression (incl. the l2 fold) verbatim, so the update rule has
        # exactly one copy and off stays bit-identical
        from .kernels import fused_opt
        return fused_opt.sgd_step(self, param, grad, lr), slot


class MomentumOptimizer(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, nesterov=False,
                 l2reg=0.0, clip_grad_norm=None):
        super().__init__(learning_rate, l2reg, clip_grad_norm)
        self.momentum = float(momentum)
        self.nesterov = nesterov

    def slot_init(self, param):
        return {"velocity": jnp.zeros_like(param)}

    def apply_dense(self, param, grad, slot, lr):
        grad = self._regularized(param, grad)
        v = self.momentum * slot["velocity"] - lr * grad
        if self.nesterov:
            new_param = param + self.momentum * v - lr * grad
        else:
            new_param = param + v
        return new_param, {"velocity": v}


class AdaGradOptimizer(Optimizer):
    def __init__(self, learning_rate=0.01, initial_accumulator_value=0.0,
                 eps=1e-7, l2reg=0.0, clip_grad_norm=None):
        super().__init__(learning_rate, l2reg, clip_grad_norm)
        self.initial_accumulator_value = float(initial_accumulator_value)
        self.eps = float(eps)

    def slot_init(self, param):
        return {"accum": jnp.full_like(param, self.initial_accumulator_value)}

    def apply_dense(self, param, grad, slot, lr):
        grad = self._regularized(param, grad)
        accum = slot["accum"] + grad * grad
        new_param = param - lr * grad / (jnp.sqrt(accum) + self.eps)
        return new_param, {"accum": accum}


class AdamOptimizer(Optimizer):
    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-7, l2reg=0.0, weight_decay=0.0,
                 clip_grad_norm=None):
        super().__init__(learning_rate, l2reg, clip_grad_norm)
        self.beta1 = float(beta1)
        self.beta2 = float(beta2)
        self.epsilon = float(epsilon)
        self.weight_decay = float(weight_decay)

    def slot_init(self, param):
        return {"m": jnp.zeros_like(param), "v": jnp.zeros_like(param),
                "t": jnp.zeros((), jnp.float32)}

    def apply_dense(self, param, grad, slot, lr):
        grad = self._regularized(param, grad)
        # hetukern (docs/KERNELS.md): one registry dispatch in EVERY mode
        # — "off" serves fused_opt._adam_xla, the bias-corrected rule as
        # ONE copy (previously duplicated here); the kernel path is the
        # same expression sequence in one VMEM pass
        from .kernels import fused_opt
        return fused_opt.adam_step(self, param, grad, slot, lr)


class AdamWOptimizer(AdamOptimizer):
    def __init__(self, learning_rate=0.01, beta1=0.9, beta2=0.999,
                 epsilon=1e-7, weight_decay=0.01, clip_grad_norm=None):
        super().__init__(learning_rate, beta1, beta2, epsilon,
                         l2reg=0.0, weight_decay=weight_decay,
                         clip_grad_norm=clip_grad_norm)


class OptimizerOp(Op):
    """The graph node applying updates to every trainable var
    (reference optimizer.py:85)."""

    is_optimizer = True

    def __init__(self, grads, optimizer: Optimizer, var_list):
        super().__init__(list(grads), None)
        self.optimizer = optimizer
        self.vars = list(var_list)
        self.name = f"Optimizer_{type(optimizer).__name__}_{self.id}"
        self._comm_inserted = False

    # -- comm strategy rewrite (reference backward_hook optimizer.py:125) ---
    def insert_comm_ops(self, config):
        if self._comm_inserted:
            return
        self._comm_inserted = True
        mode = config.comm_mode
        if mode is None:
            return
        from .graph.ops.comm import allreduceCommunicate_op
        from .graph.ops.ps import parameterServerCommunicate_op
        new_inputs = []
        for var, grad in zip(self.vars, self.inputs):
            sparse = getattr(var, "is_embed", False)
            if mode == "AllReduce" or (mode == "Hybrid" and not sparse):
                new_inputs.append(allreduceCommunicate_op(grad,
                                                          param_node=var))
            elif mode == "PS" or (mode == "Hybrid" and sparse):
                new_inputs.append(parameterServerCommunicate_op(
                    grad, ps_id=var.name, optimizer=self.optimizer))
            else:
                new_inputs.append(grad)
        self.inputs = new_inputs

    # -- executor protocol --------------------------------------------------
    def init_slots(self, params_by_id):
        # vars missing from the map are PS-resident: the server owns their
        # optimizer slots (reference ps/server/optimizer.h)
        return tuple(self.optimizer.slot_init(params_by_id[id(v)])
                     if id(v) in params_by_id else ()
                     for v in self.vars)

    def apply_updates(self, env, slots, tc):
        lr = self.optimizer.lr_value(tc.step)
        clip = self.optimizer.clip_grad_norm
        scale = None
        if clip is not None:
            # global-norm clipping over every locally-applied gradient —
            # ONE fused reduction, published on the trace context so the
            # hetuscope introspection stats reuse it instead of
            # re-reducing (scope.traced_stats' grad_global_norm input)
            sq = []
            for grad_node in self.inputs:
                g = env[id(grad_node)]
                if g is None or isinstance(g, tuple):
                    continue  # PS-managed: the server applies the update
                gf = g.astype(jnp.float32) if g.dtype != jnp.float32 else g
                sq.append(jnp.sum(gf * gf))
            if sq:
                gnorm = jnp.sqrt(sum(sq))
                tc.grad_global_norm = gnorm
                scale = jnp.minimum(1.0, clip / (gnorm + 1e-12))
        new_slots = []
        for var, grad_node, slot in zip(self.vars, self.inputs, slots):
            # mixed precision: update the f32 master copy, not the (possibly
            # bf16) compute-side value in env
            param = tc.master_params.get(id(var), env[id(var)])
            grad = env[id(grad_node)]
            if grad is None:  # PS-managed parameter: server applied the update
                new_slots.append(slot)
                continue
            if hasattr(grad, "dtype") and grad.dtype != param.dtype:
                grad = grad.astype(param.dtype)
            if scale is not None:
                grad = grad * scale.astype(param.dtype)
            new_param, new_slot = self.optimizer.apply_dense(param, grad, slot, lr)
            tc.param_updates[id(var)] = new_param
            new_slots.append(new_slot)
        tc.slot_updates[id(self)] = tuple(new_slots)

    def compute(self, input_vals, tc):
        raise AssertionError("OptimizerOp is applied by the executor")
