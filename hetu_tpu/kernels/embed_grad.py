"""Fused sparse embedding gradient: sort/unique + segment-sum into
IndexedSlices-style ``(rows, grads)`` pairs (docs/KERNELS.md).

The pre-hetukern ``embedding_lookup_gradient_op`` scatters the batch's
row gradients into a ``(vocab, dim)`` zeros table
(``jnp.zeros(shape).at[idx].add(vec)``) — for a CTR table that is a
table-sized HBM intermediate written per step to carry a few thousand
live rows (the reference pays the same shape with a hand-written
``EmbeddingLookup.cu`` scatter kernel). This module computes the compact
form instead:

    rows, grads, count = embed_grad_rows(vec, idx, vocab)

``rows`` is ``(n,)`` int32 — the sorted unique row ids, padded with the
``vocab`` sentinel past ``count``; ``grads`` is ``(n, dim)`` with the
per-unique-row gradient sums in the first ``count`` slots and zeros
after. The pair feeds the PS push path directly (rows leave the device
anyway) and reconstructs the dense table gradient with ONE
unique-index scatter when a consumer genuinely needs table shape.

Split of labor: the sort + segment-id prep is XLA either way (XLA's sort
is already good; a Pallas sort would be re-deriving it); the kernel tier
covers the segment-sum — a blocked mask-matmul (``out[k] = Σ_j
[seg_j = k]·g_j``) whose per-block compare-and-MAC rides the MXU with
row blocks streamed through VMEM, versus the fallback's
``jax.ops.segment_sum`` scatter-adds. Note the jax.grad path through
``embedding_lookup_op`` cannot use the compact form — a vjp cotangent
must match the primal's (table) shape — so this tier serves the explicit
gradient op and the PS push route, and the dense reconstruction keeps
the scatter unique-rows-only.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import registry

# MXU-friendly tile for the mask-matmul; eligibility asks the padded row
# count to divide it and the trailing dim to be lane-aligned. Tiling and
# VMEM-budget constants are the registry's shared ones: the kernel holds
# the full (n, d) sorted-grad array + (n,) seg ids + one (BLOCK_ROWS, d)
# output block in VMEM per grid step, and oversized CTR batches must
# fall back under auto instead of dying in a Mosaic VMEM-exhausted
# compile.
BLOCK_ROWS = 128
_LANE = registry.LANE
VMEM_BUDGET_BYTES = registry.VMEM_BUDGET_BYTES


# ---------------------------------------------------------------------------
# shared prep (XLA both paths): sort, segment ids, unique-row vector
# ---------------------------------------------------------------------------

def _prep(vec, idx, vocab: int):
    """Flatten + stable-sort the row gradients by row id.

    Returns ``(sorted_grads (n, d) f32, seg (n,) i32, rows (n,) i32,
    count () i32)`` — ``seg`` maps each sorted slot to its unique-row
    rank, ``rows[k]`` is unique row k's id (``vocab`` sentinel past
    ``count``)."""
    d = vec.shape[-1]
    flat_idx = idx.astype(jnp.int32).reshape(-1)
    flat_vec = vec.reshape(-1, d).astype(jnp.float32)
    order = jnp.argsort(flat_idx)   # jnp.argsort is stable by default
    sidx = flat_idx[order]
    sv = flat_vec[order]
    n = sidx.shape[0]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), sidx[1:] != sidx[:-1]])
    seg = jnp.cumsum(first.astype(jnp.int32)) - 1          # (n,) 0..count-1
    count = seg[-1] + 1
    rows = jnp.full((n,), vocab, jnp.int32).at[seg].set(sidx)
    return sv, seg, rows, count


# ---------------------------------------------------------------------------
# segment-sum implementations (the registered kernel)
# ---------------------------------------------------------------------------

def _segsum_xla(sv, seg):
    """The fallback: XLA's sorted-scatter segment sum."""
    return jax.ops.segment_sum(sv, seg, num_segments=sv.shape[0])


def _segsum_kernel(seg_ref, g_ref, o_ref, *, block_rows, n):
    """One output row-block: mask-matmul segment MAC. ``out[k] = Σ_j
    [seg_j = k] g_j`` — the (block, block) compare mask against a g block
    is one MXU dot; the fori_loop streams g blocks through VMEM."""
    i = pl.program_id(0)
    k0 = i * block_rows

    def body(jb, acc):
        seg = seg_ref[pl.ds(jb * block_rows, block_rows)]
        g = g_ref[pl.ds(jb * block_rows, block_rows), :]
        krow = k0 + jax.lax.broadcasted_iota(
            jnp.int32, (block_rows, block_rows), 0)
        m = (krow == seg[None, :]).astype(jnp.float32)
        return acc + jax.lax.dot(m, g, preferred_element_type=jnp.float32)

    acc0 = jnp.zeros((block_rows, g_ref.shape[1]), jnp.float32)
    o_ref[:] = jax.lax.fori_loop(0, n // block_rows, body, acc0)


def _segsum_pallas(sv, seg):
    n, d = sv.shape
    out = pl.pallas_call(
        functools.partial(_segsum_kernel, block_rows=BLOCK_ROWS, n=n),
        grid=(n // BLOCK_ROWS,),
        in_specs=[
            pl.BlockSpec((n,), lambda i: (0,)),
            pl.BlockSpec((n, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((BLOCK_ROWS, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, d), jnp.float32),
        interpret=not registry._on_tpu(),
    )(seg, sv)
    return out


def _segsum_eligible(sv, seg):
    n = sv.shape[0]
    d = sv.shape[1] if sv.ndim == 2 else None
    if sv.ndim != 2:
        return False, f"grads must be (n, dim), got rank {sv.ndim}"
    if jnp.dtype(sv.dtype) not in (jnp.dtype(jnp.float32),):
        return False, f"grads must be f32 on the wire, got {sv.dtype}"
    if n == 0 or n % BLOCK_ROWS:
        return False, (f"row count {n} must be a positive multiple of the "
                       f"{BLOCK_ROWS}-row mask-matmul tile")
    if d % _LANE:
        return False, f"embedding dim {d} must be a multiple of {_LANE}"
    if (n * (d + 1) + BLOCK_ROWS * d) * 4 > VMEM_BUDGET_BYTES:
        return False, (f"{n} rows x dim {d} exceed the "
                       f"{VMEM_BUDGET_BYTES >> 20} MiB VMEM residency "
                       "budget for the mask-matmul sweep")
    return True, None


registry.register_kernel(
    "fused_embed_grad",
    pallas_fn=_segsum_pallas,
    xla_fallback=_segsum_xla,
    eligibility=_segsum_eligible,
)


# ---------------------------------------------------------------------------
# public forms
# ---------------------------------------------------------------------------

def rows_path_eligible(vec, idx) -> bool:
    """Would the fused segment-sum kernel serve this call? The dense-grad
    op consults this BEFORE restructuring into the rows form, so an
    ineligible shape under ``auto`` keeps the pre-tier one-scatter
    expression instead of paying sort + segment-sum + scatter on the XLA
    fallback."""
    n = 1
    for s in idx.shape:
        n *= int(s)
    d = int(vec.shape[-1])
    ok, _why = registry.eligibility_of(
        "fused_embed_grad",
        jax.ShapeDtypeStruct((n, d), jnp.float32),
        jax.ShapeDtypeStruct((n,), jnp.int32))
    return ok


def embed_grad_rows(vec, idx, vocab: int):
    """Compact embedding gradient: ``(rows, grads, count)`` (see module
    docstring for the layout contract). Dispatches the segment-sum through
    the kernel registry."""
    d = int(vec.shape[-1])
    n = 1
    for s in idx.shape:
        n *= int(s)
    if n == 0:
        # empty batch: the sort/segment prep's first-occurrence flag is
        # minimum length 1 and would shape-error; the compact form of
        # nothing is just nothing (the off-mode dense scatter handles
        # n=0 natively, so this route must too)
        return (jnp.zeros((0,), jnp.int32), jnp.zeros((0, d), jnp.float32),
                jnp.zeros((), jnp.int32))
    sv, seg, rows, count = _prep(vec, idx, vocab)
    grads = registry.dispatch("fused_embed_grad", sv, seg)
    return rows, grads, count


def embed_grad_dense(vec, idx, shape):
    """Dense ``(vocab, dim)`` gradient via the compact form: one scatter
    over UNIQUE rows (duplicates were already summed), versus the
    fallback's scatter over every occurrence. The sentinel row (``vocab``)
    is dropped by XLA's out-of-bounds-scatter semantics and carries zero
    grads regardless."""
    shape = tuple(int(s) for s in shape)
    rows, grads, _count = embed_grad_rows(vec, idx, shape[0])
    return jnp.zeros(shape, vec.dtype).at[rows].add(
        grads.astype(vec.dtype), mode="drop")


def embed_grad_dense_xla(vec, idx, shape):
    """The pre-hetukern expression, verbatim — what ``kernels='off'``
    must reproduce bit-for-bit and what equality tests compare against."""
    shape = tuple(int(s) for s in shape)
    flat_idx = idx.astype(jnp.int32).reshape(-1)
    flat_vec = vec.reshape((-1, shape[-1]))
    return jnp.zeros(shape, vec.dtype).at[flat_idx].add(flat_vec)
