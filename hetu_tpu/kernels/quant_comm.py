"""Quant-fused collective legs for the hetuq AllReduce (docs/KERNELS.md,
docs/COMM_QUANT.md).

PR 8's quantized DP AllReduce lowers as reduce-scatter(f32) → blockwise
quantize → all-gather(int8/fp8 + scales) → dequantize. The quantize half
under XLA's default codegen is three passes over the shard (abs-max
reduce, scale divide, round/clip/cast) with the ``(nb, block)`` reshape
materialized between them; the dequantize half is another two. These
kernels fuse each half into ONE pass over the shard resident in VMEM —
the EQuARX move (PAPERS.md arXiv:2506.17615) of pushing the quantization
work below the collective boundary, expressed at the Pallas level since
GSPMD owns the collective itself.

Wire-format contract: the kernel output must be BIT-IDENTICAL to
``comm_quant.quantize_blocks`` — same abs-max, same ``/Q`` scale, same
round-half-even, same all-zero-block convention — because the payload
crosses the wire to peers that may dequantize with the unfused path
(and because the error-feedback residual algebra assumes one quantizer).
``tests/test_kernels.py`` asserts exact equality of ``(q, scales)`` for
both int8 and fp8.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from . import registry

_INT8_Q = 127.0
_FP8_Q = 448.0
_LANE = registry.LANE
# one-pass residency: the whole (nb, block) shard view sits in VMEM
# (the registry's shared budget constant)
VMEM_BUDGET_BYTES = registry.VMEM_BUDGET_BYTES


def _fp8():
    return getattr(jnp, "float8_e4m3fn", None)


# -- fallbacks: the comm_quant (jnp) implementations, re-used not copied ----

def _quant_xla(x, *, block: int, mode: str):
    from .. import comm_quant
    return comm_quant.quantize_blocks(x, block, mode)


def _dequant_xla(q, scales, *, n: int, block: int):
    from .. import comm_quant
    return comm_quant.dequantize_blocks(q, scales, n, block)


# -- pallas: one pass over the shard ----------------------------------------

def _quant_kernel(x_ref, q_ref, s_ref, *, mode):
    blocks = x_ref[:]                                   # (nb, block) f32
    amax = jnp.max(jnp.abs(blocks), axis=1, keepdims=True)
    if mode == "fp8":
        scales = amax / _FP8_Q
        safe = jnp.where(scales > 0, scales, 1.0)
        q_ref[:] = (blocks / safe).astype(q_ref.dtype)
    else:
        scales = amax / _INT8_Q
        safe = jnp.where(scales > 0, scales, 1.0)
        q_ref[:] = jnp.clip(jnp.round(blocks / safe),
                            -127, 127).astype(jnp.int8)
    s_ref[:] = scales


def _quant_pallas(x, *, block: int, mode: str):
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.size
    nb = -(-n // block)
    pad = nb * block - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    blocks = flat.reshape(nb, block)
    wire_dtype = _fp8() if mode == "fp8" else jnp.int8
    q, scales = pl.pallas_call(
        functools.partial(_quant_kernel, mode=mode),
        out_shape=[
            jax.ShapeDtypeStruct((nb, block), wire_dtype),
            jax.ShapeDtypeStruct((nb, 1), jnp.float32),
        ],
        interpret=not registry._on_tpu(),
    )(blocks)
    return q.reshape(-1), scales.reshape(-1), n


def _quant_eligible(x, *, block: int, mode: str):
    if mode not in ("int8", "fp8"):
        return False, f"mode must be int8/fp8, got {mode!r}"
    if mode == "fp8" and _fp8() is None:
        return False, "this jax build has no float8_e4m3fn"
    if not jnp.issubdtype(jnp.dtype(x.dtype), jnp.floating):
        return False, f"payload must be float, got {x.dtype}"
    if block % _LANE:
        return False, f"block {block} must be a multiple of {_LANE}"
    n = 1
    for s in x.shape:
        n *= int(s)
    nb = -(-n // block)
    if nb * block * 5 > VMEM_BUDGET_BYTES:   # f32 in + 1-byte out
        return False, (f"shard of {nb * block} elements exceeds the "
                       f"{VMEM_BUDGET_BYTES >> 20} MiB one-pass VMEM budget")
    return True, None


def _dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[:] = q_ref[:].astype(jnp.float32) * s_ref[:]


def _dequant_pallas(q, scales, *, n: int, block: int):
    nb = scales.size
    out = pl.pallas_call(
        _dequant_kernel,
        out_shape=jax.ShapeDtypeStruct((nb, block), jnp.float32),
        interpret=not registry._on_tpu(),
    )(q.reshape(nb, block), scales.reshape(nb, 1))
    return out.reshape(-1)[:n]


def _dequant_eligible(q, scales, *, n: int, block: int):
    if block % _LANE:
        return False, f"block {block} must be a multiple of {_LANE}"
    nb = 1
    for s in scales.shape:
        nb *= int(s)
    if nb * block * 5 > VMEM_BUDGET_BYTES:
        return False, (f"shard of {nb * block} elements exceeds the "
                       f"{VMEM_BUDGET_BYTES >> 20} MiB one-pass VMEM budget")
    return True, None


registry.register_kernel(
    "quant_blocks",
    pallas_fn=_quant_pallas,
    xla_fallback=_quant_xla,
    eligibility=_quant_eligible,
)

registry.register_kernel(
    "dequant_blocks",
    pallas_fn=_dequant_pallas,
    xla_fallback=_dequant_xla,
    eligibility=_dequant_eligible,
)


def quantize_blocks(x, block: int, mode: str = "int8"):
    """Registry-dispatched blockwise quantize — same signature and
    bit-identical output contract as ``comm_quant.quantize_blocks``."""
    return registry.dispatch("quant_blocks", x, block=block, mode=mode)


def dequantize_blocks(q, scales, n: int, block: int):
    return registry.dispatch("dequant_blocks", q, scales, n=n, block=block)
