"""CSR/COO sparse-times-dense matmul kernel (docs/KERNELS.md).

The graph-side sparse products (``csrmm_op``/``csrmv_op``,
``graph/ops/matmul.py``) and DistGCN's 1.5D local block product
(``parallel/distgcn.py``) all reduce to one primitive:

    Z[r, :] = Σ_j [rows_j = r] · values_j · B[cols_j, :]

The XLA fallback expresses it as gather + ``jax.ops.segment_sum`` —
correct, but the segment sum lowers to a SORT of the contributions
before the scatter, and the gather materializes an ``(nnz, F)``
intermediate in HBM. The Pallas kernel instead streams nnz blocks
through SMEM (ids/values) and does a rows-into-VMEM segment MAC: for
each entry, one dynamic-row read of ``B`` and one dynamic-row
accumulate into the output block resident in VMEM — no ``(nnz, F)``
intermediate, no sort. The TPU grid is sequential, so cross-block
accumulation into the same output ref is exact and deterministic.

Zero-padded entries (DistGCN pads blocks to the max nnz) contribute
``0 · B[0]`` and are harmless, same as in the fallback.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import registry

BLOCK_NNZ = 256
_LANE = registry.LANE
_SUBLANE = registry.SUBLANE
# the whole (nrow, F) output block plus the (K, F) dense operand live in
# VMEM for the kernel's lifetime — stay well under the ~16 MB/core
# budget (the registry's shared constant)
VMEM_BUDGET_BYTES = registry.VMEM_BUDGET_BYTES


def _spmm_xla(values, rows, cols, b, *, nrow: int):
    """The pre-hetukern expression (graph/ops/matmul.py ``_coo_matmat``),
    verbatim — the ``off``-mode path and the equality oracle."""
    contrib = values[:, None] * jnp.take(b, cols, axis=0)
    return jax.ops.segment_sum(contrib, rows, num_segments=nrow)


def _spmm_kernel(vals_ref, rows_ref, cols_ref, b_ref, o_ref, *, block_nnz):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        o_ref[:] = jnp.zeros_like(o_ref)

    def body(j, _):
        r = rows_ref[j]
        c = cols_ref[j]
        v = vals_ref[j]
        o_ref[pl.ds(r, 1), :] = (o_ref[pl.ds(r, 1), :]
                                 + v * b_ref[pl.ds(c, 1), :])
        return 0

    jax.lax.fori_loop(0, block_nnz, body, 0)


def _pad_nnz(values, rows, cols):
    nnz = values.shape[0]
    pad = (-nnz) % BLOCK_NNZ
    if pad:
        # value-0 padding: contributes 0 * B[0] to row 0, a no-op
        values = jnp.pad(values, (0, pad))
        rows = jnp.pad(rows, (0, pad))
        cols = jnp.pad(cols, (0, pad))
    return values, rows, cols


def _spmm_pallas(values, rows, cols, b, *, nrow: int):
    k, f = b.shape
    values, rows, cols = _pad_nnz(
        values.astype(jnp.float32), rows.astype(jnp.int32),
        cols.astype(jnp.int32))
    nnz = values.shape[0]
    out = pl.pallas_call(
        functools.partial(_spmm_kernel, block_nnz=BLOCK_NNZ),
        grid=(nnz // BLOCK_NNZ,),
        in_specs=[
            pl.BlockSpec((BLOCK_NNZ,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((BLOCK_NNZ,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((BLOCK_NNZ,), lambda i: (i,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((k, f), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((nrow, f), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((nrow, f), jnp.float32),
        interpret=not registry._on_tpu(),
    )(values, rows, cols, b)
    return out


def _spmm_eligible(values, rows, cols, b, *, nrow: int):
    if b.ndim != 2:
        return False, f"dense operand must be (K, F), got rank {b.ndim}"
    k, f = int(b.shape[0]), int(b.shape[1])
    if jnp.dtype(b.dtype) != jnp.dtype(jnp.float32):
        return False, f"dense operand must be f32, got {b.dtype}"
    if jnp.dtype(values.dtype) != jnp.dtype(jnp.float32):
        # the kernel casts to f32; the fallback computes in the input
        # dtype — declining keeps the force-vs-off dtype contract honest
        return False, f"values must be f32, got {values.dtype}"
    if f % _LANE:
        return False, f"feature dim {f} must be a multiple of {_LANE}"
    if int(nrow) % _SUBLANE or k % _SUBLANE:
        return False, (f"row counts (nrow={nrow}, K={k}) must be multiples "
                       f"of {_SUBLANE} (f32 sublane tile)")
    if (int(nrow) + k) * f * 4 > VMEM_BUDGET_BYTES:
        return False, (f"output ({nrow}x{f}) + dense operand ({k}x{f}) "
                       f"exceed the {VMEM_BUDGET_BYTES >> 20} MiB VMEM "
                       "residency budget")
    return True, None


registry.register_kernel(
    "csr_spmm",
    pallas_fn=_spmm_pallas,
    xla_fallback=_spmm_xla,
    eligibility=_spmm_eligible,
)


# -- matvec: its own KernelSpec so the registry gate (mode semantics,
# counting, force errors) is defined in exactly one place ----------------

def _spmv_xla(values, rows, cols, x, *, nrow: int):
    """The pre-hetukern ``_coo_matvec`` expression, verbatim."""
    contrib = values * jnp.take(x, cols, axis=0)
    return jax.ops.segment_sum(contrib, rows, num_segments=nrow)


def _spmv_pallas(values, rows, cols, x, *, nrow: int):
    # ride the spmm kernel with the vector lane-padded to (K, 128)
    b = jnp.zeros((x.shape[0], _LANE), jnp.float32).at[:, 0].set(
        x.astype(jnp.float32))
    return _spmm_pallas(values, rows, cols, b, nrow=nrow)[:, 0]


def _spmv_eligible(values, rows, cols, x, *, nrow: int):
    if x.ndim != 1:
        return False, f"dense operand must be a vector, got rank {x.ndim}"
    if jnp.dtype(x.dtype) != jnp.dtype(jnp.float32):
        return False, f"vector must be f32, got {x.dtype}"
    return _spmm_eligible(
        values, rows, cols,
        jax.ShapeDtypeStruct((int(x.shape[0]), _LANE), jnp.float32),
        nrow=nrow)


registry.register_kernel(
    "csr_spmv",
    pallas_fn=_spmv_pallas,
    xla_fallback=_spmv_xla,
    eligibility=_spmv_eligible,
)


def coo_matmat(values, rows, cols, nrow: int, b):
    """``sparse(values, rows, cols) @ B`` through the kernel registry —
    the shared entry for ``csrmm_op`` and DistGCN."""
    return registry.dispatch("csr_spmm", values, rows, cols, b,
                             nrow=int(nrow))


def coo_matvec(values, rows, cols, nrow: int, x):
    """``sparse @ x`` through the registry (``csrmv_op``)."""
    return registry.dispatch("csr_spmv", values, rows, cols, x,
                             nrow=int(nrow))
