"""Fused causal attention for TPU.

The reference's attention is unfused BatchMatMul + Softmax + BatchMatMul
(examples/nlp/hetu_transformer.py:56+), materializing the (S, S) score matrix
in HBM. This module computes attention blockwise with an online softmax so
only (block_q, block_k) tiles ever exist:

- forward: a Pallas kernel — q/k/v tiles stream HBM->VMEM, scores hit the
  MXU, the running (max, sum) rescale keeps the softmax exact. Falls back to
  interpreter mode off-TPU so the same code runs in CPU-mesh tests.
- backward: Pallas kernels both directions on TPU (a dq kernel over q blocks
  and a fused dk+dv kernel over k blocks, each recomputing its probability
  tile from (q, k, lse) — no (S,S) materialization); off-TPU, a blockwise
  `lax.scan` recomputation in XLA serves as fallback and numerical oracle.

Public entry: ``flash_attention(q, k, v, causal=True)`` with shapes
(batch, heads, seq, head_dim), differentiable via custom_vjp. An optional
``k_bias`` (batch, seq) float is ADDED to every score column — the key-
padding mask form (0 valid / -1e9 padded) the BERT encoder uses — so masked
batches keep the fused kernel instead of falling back to the unfused path.
All-padded rows degenerate to a uniform softmax, exactly like the unfused
form (softmax is shift-invariant), so the semantics match the dot path.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def _causal_mask(s, q_start, k_start, block_q, block_k):
    """Mask scores above the diagonal for one (q block, k block) tile."""
    q_pos = q_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = k_start + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_INF)


def _causal_upper_kb(q_start, block_q, block_k):
    """First key block strictly above the diagonal, by CEIL division —
    flooring would drop the diagonal block whenever block_q < block_k
    (regression guard: test_flash_causal_uneven_blocks). Shared by the
    forward and dq kernels so the bound cannot drift between them."""
    return (q_start + block_q + block_k - 1) // block_k


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, lse_ref, *, scale,
                causal, use_bias, block_k, seq_len):
    # grid: (batch*heads, q_blocks); refs carry one q block and the full k/v
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * scale          # (block_q, d)
    block_q = q.shape[0]
    q_start = qi * block_q

    num_kb = seq_len // block_k

    def body(kj, carry):
        acc, m_prev, l_prev = carry
        k_blk = k_ref[0, pl.ds(kj * block_k, block_k)].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kj * block_k, block_k)].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if use_bias:
            s = s + bias_ref[0, pl.ds(kj * block_k, block_k), 0][None, :]
        if causal:
            s = _causal_mask(s, q_start, kj * block_k, block_q, block_k)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new[:, None])
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1)
        acc = acc * alpha[:, None] + jax.lax.dot(
            p, v_blk, preferred_element_type=jnp.float32)
        return acc, m_new, l_new

    # causal: skip key blocks entirely above the diagonal
    upper = (num_kb if not causal
             else _causal_upper_kb(q_start, block_q, block_k))
    acc0 = jnp.zeros((block_q, q.shape[1]), jnp.float32)
    m0 = jnp.full((block_q,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, upper, body, (acc0, m0, l0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)
    lse_ref[0, :, 0] = m + jnp.log(l)


def _expand_bias(k_bias, b, h, s):
    """(b, s) per-key bias -> (b*h, s, 1) column blocks for the kernels."""
    kb = jnp.broadcast_to(k_bias.astype(jnp.float32)[:, None, :], (b, h, s))
    return kb.reshape(b * h, s, 1)


def _fwd_pallas(q, k, v, k_bias, scale, causal, block_q, block_k, interpret):
    b, h, s, d = q.shape
    bh = b * h
    qf = q.reshape(bh, s, d)
    kf = k.reshape(bh, s, d)
    vf = v.reshape(bh, s, d)
    grid = (bh, s // block_q)
    use_bias = k_bias is not None
    kern = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                             use_bias=use_bias, block_k=block_k, seq_len=s)
    if not use_bias:
        def kern(q_ref, k_ref, v_ref, o_ref, lse_ref):  # noqa: F811
            return _fwd_kernel(q_ref, k_ref, v_ref, None, o_ref, lse_ref,
                               scale=scale, causal=causal, use_bias=False,
                               block_k=block_k, seq_len=s)
    in_specs = [
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0)),
    ]
    ops = [qf, kf, vf]
    if use_bias:
        in_specs.append(pl.BlockSpec((1, s, 1), lambda i, j: (i, 0, 0)))
        ops.append(_expand_bias(k_bias, b, h, s))
    out, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            # trailing singleton keeps the block's last-two dims TPU-tileable
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), q.dtype),
            jax.ShapeDtypeStruct((bh, s, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*ops)
    return out.reshape(b, h, s, d), lse.reshape(b, h, s)


# ---------------------------------------------------------------------------
# backward Pallas kernels (dq; dk+dv) — flash backward both directions:
# each tile recomputes its probability block from (q, k, lse), so nothing
# (S, S)-shaped ever exists. delta = rowsum(dO * O) is precomputed in XLA.
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   bias_ref, dq_ref, *, scale, causal, use_bias, block_k,
                   seq_len):
    # grid: (batch*heads, q_blocks); owns one q block, loops over k blocks
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)                  # (block_q, d)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]                            # (block_q,)
    delta = delta_ref[0, :, 0]
    block_q = q.shape[0]
    q_start = qi * block_q
    num_kb = seq_len // block_k

    def body(kj, dq):
        k_blk = k_ref[0, pl.ds(kj * block_k, block_k)].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(kj * block_k, block_k)].astype(jnp.float32)
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if use_bias:
            s = s + bias_ref[0, pl.ds(kj * block_k, block_k), 0][None, :]
        if causal:
            s = _causal_mask(s, q_start, kj * block_k, block_q, block_k)
        p = jnp.exp(s - lse[:, None])
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq + jax.lax.dot(ds, k_blk,
                                preferred_element_type=jnp.float32)

    upper = (num_kb if not causal
             else _causal_upper_kb(q_start, block_q, block_k))
    dq = jax.lax.fori_loop(0, upper, body,
                           jnp.zeros((block_q, q.shape[1]), jnp.float32))
    dq_ref[0] = dq.astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    bias_ref, dk_ref, dv_ref, *, scale, causal, use_bias,
                    block_q, seq_len):
    # grid: (batch*heads, k_blocks); owns one k/v block, loops over q blocks
    ki = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)              # (block_k, d)
    v_blk = v_ref[0].astype(jnp.float32)
    block_k = k_blk.shape[0]
    k_start = ki * block_k
    num_qb = seq_len // block_q

    def body(qi, carry):
        dk, dv = carry
        q = q_ref[0, pl.ds(qi * block_q, block_q)].astype(jnp.float32)
        do = do_ref[0, pl.ds(qi * block_q, block_q)].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), 0]
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), 0]
        s = jax.lax.dot_general(q, k_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if use_bias:
            # this kernel owns ONE k block: its bias column is constant
            s = s + bias_ref[0, :, 0][None, :]
        if causal:
            s = _causal_mask(s, qi * block_q, k_start, block_q, block_k)
        p = jnp.exp(s - lse[:, None])                 # (block_q, block_k)
        dv = dv + jax.lax.dot_general(p, do, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v_blk, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        dk = dk + jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        return dk, dv

    # causal: q blocks strictly before this k block contribute nothing
    lower = (k_start // block_q) if causal else 0
    d = k_blk.shape[1]
    dk, dv = jax.lax.fori_loop(
        lower, num_qb, body,
        (jnp.zeros((block_k, d), jnp.float32),
         jnp.zeros((block_k, d), jnp.float32)))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_pallas(res, do, *, scale, causal, block_q, block_k, interpret):
    q, k, v, o, lse, k_bias = res
    b, h, s, d = q.shape
    bh = b * h
    use_bias = k_bias is not None
    biasf = _expand_bias(k_bias, b, h, s) if use_bias else None
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)                           # (b, h, s)
    qf, kf, vf = (x.reshape(bh, s, d) for x in (q, k, v))
    dof = do.reshape(bh, s, d)
    lsef = lse.reshape(bh, s, 1)
    deltaf = delta.reshape(bh, s, 1)

    full = pl.BlockSpec((1, s, d), lambda i, j: (i, 0, 0))
    col = pl.BlockSpec((1, s, 1), lambda i, j: (i, 0, 0))

    dq_kern = functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                                use_bias=use_bias, block_k=block_k,
                                seq_len=s)
    if not use_bias:
        def dq_kern(q_ref, k_ref, v_ref, do_ref, lse_ref,  # noqa: F811
                    delta_ref, dq_ref):
            return _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                  delta_ref, None, dq_ref, scale=scale,
                                  causal=causal, use_bias=False,
                                  block_k=block_k, seq_len=s)
    dq_specs = [
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            full, full,
            pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_q, 1), lambda i, j: (i, j, 0)),
    ]
    dq_ops = [qf, kf, vf, dof, lsef, deltaf]
    if use_bias:
        dq_specs.append(col)
        dq_ops.append(biasf)
    dq = pl.pallas_call(
        dq_kern,
        grid=(bh, s // block_q),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, s, d), q.dtype),
        interpret=interpret,
    )(*dq_ops)

    dkv_kern = functools.partial(_bwd_dkv_kernel, scale=scale,
                                 causal=causal, use_bias=use_bias,
                                 block_q=block_q, seq_len=s)
    if not use_bias:
        def dkv_kern(q_ref, k_ref, v_ref, do_ref, lse_ref,  # noqa: F811
                     delta_ref, dk_ref, dv_ref):
            return _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref,
                                   delta_ref, None, dk_ref, dv_ref,
                                   scale=scale, causal=causal,
                                   use_bias=False, block_q=block_q,
                                   seq_len=s)
    dkv_specs = [
            full,
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            full, col, col,
    ]
    dkv_ops = [qf, kf, vf, dof, lsef, deltaf]
    if use_bias:
        dkv_specs.append(
            pl.BlockSpec((1, block_k, 1), lambda i, j: (i, j, 0)))
        dkv_ops.append(biasf)
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(bh, s // block_k),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, s, d), k.dtype),
            jax.ShapeDtypeStruct((bh, s, d), v.dtype),
        ],
        interpret=interpret,
    )(*dkv_ops)

    return (dq.reshape(b, h, s, d), dk.reshape(b, h, s, d),
            dv.reshape(b, h, s, d))


# ---------------------------------------------------------------------------
# blockwise backward (XLA): flash-style recomputation, no (S, S) tensor
# (off-TPU fallback and the Pallas backward's numerical oracle)
# ---------------------------------------------------------------------------

def _bwd_blockwise(res, do, *, scale, causal, block_k):
    q, k, v, o, lse, k_bias = res
    b, h, s, d = q.shape
    nkb = s // block_k
    do_f = do.astype(jnp.float32)
    q_f = q.astype(jnp.float32)
    # delta_i = sum_j dO_ij O_ij  (rowwise), standard flash backward
    delta = jnp.sum(do_f * o.astype(jnp.float32), axis=-1)  # (b,h,s)

    q_pos = jnp.arange(s)

    def one_kblock(kj):
        ks = kj * block_k
        k_blk = jax.lax.dynamic_slice_in_dim(k, ks, block_k, 2)
        v_blk = jax.lax.dynamic_slice_in_dim(v, ks, block_k, 2)
        s_blk = jnp.einsum("bhqd,bhkd->bhqk", q_f,
                           k_blk.astype(jnp.float32)) * scale
        if k_bias is not None:
            kb = jax.lax.dynamic_slice_in_dim(
                k_bias.astype(jnp.float32), ks, block_k, 1)
            s_blk = s_blk + kb[:, None, None, :]
        if causal:
            mask = q_pos[:, None] >= (ks + jnp.arange(block_k))[None, :]
            s_blk = jnp.where(mask, s_blk, _NEG_INF)
        p = jnp.exp(s_blk - lse[..., None])                    # (b,h,s,bk)
        dv_blk = jnp.einsum("bhqk,bhqd->bhkd", p, do_f)
        dp = jnp.einsum("bhqd,bhkd->bhqk", do_f, v_blk.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * scale
        dq_part = jnp.einsum("bhqk,bhkd->bhqd", ds, k_blk.astype(jnp.float32))
        dk_blk = jnp.einsum("bhqk,bhqd->bhkd", ds, q_f)
        return dq_part, dk_blk, dv_blk

    def scan_body(dq_acc, kj):
        dq_part, dk_blk, dv_blk = one_kblock(kj)
        return dq_acc + dq_part, (dk_blk, dv_blk)

    dq, (dk_blocks, dv_blocks) = jax.lax.scan(
        scan_body, jnp.zeros(q.shape, jnp.float32), jnp.arange(nkb))
    dk = jnp.moveaxis(dk_blocks, 0, 2).reshape(b, h, s, d)
    dv = jnp.moveaxis(dv_blocks, 0, 2).reshape(b, h, s, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash(q, k, v, k_bias, causal, scale, block_q, block_k):
    out, _ = _flash_fwd(q, k, v, k_bias, causal, scale, block_q, block_k)
    return out


def flash_attention(q, k, v, causal=True, scale=None,
                    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K,
                    k_bias=None):
    """Fused attention. q/k/v: (batch, heads, seq, head_dim).

    ``k_bias``: optional (batch, seq) float added to every score column —
    the key-padding mask form (0 valid / -1e9 padded). Non-trainable: its
    cotangent is zero."""
    return _flash(q, k, v, k_bias, causal, scale, block_q, block_k)


def _resolve(q, scale, block_q, block_k):
    s = q.shape[2]
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q or s % block_k:
        raise ValueError(f"seq_len {s} must divide blocks ({block_q},{block_k})")
    return scale, block_q, block_k


def _flash_fwd(q, k, v, k_bias, causal, scale, block_q, block_k):
    scale, block_q, block_k = _resolve(q, scale, block_q, block_k)
    out, lse = _fwd_pallas(q, k, v, k_bias, scale, causal, block_q, block_k,
                           interpret=not _on_tpu())
    return out, (q, k, v, out, lse, k_bias)


def _flash_bwd(causal, scale, block_q, block_k, res, do):
    q = res[0]
    scale, block_q, block_k = _resolve(q, scale, block_q, block_k)
    if _on_tpu():
        grads = _bwd_pallas(res, do, scale=scale, causal=causal,
                            block_q=block_q, block_k=block_k,
                            interpret=False)
    else:
        grads = _bwd_blockwise(res, do, scale=scale, causal=causal,
                               block_k=block_k)
    k_bias = res[5]
    dbias = None if k_bias is None else jnp.zeros_like(k_bias)
    return grads + (dbias,)


_flash.defvjp(_flash_fwd, _flash_bwd)


def mha_reference(q, k, v, causal=True, scale=None, k_bias=None):
    """Unfused reference (the reference framework's BatchMatMul+Softmax
    attention) — used as the numerical oracle in tests."""
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if k_bias is not None:
        s = s + k_bias.astype(jnp.float32)[:, None, None, :]
    if causal:
        n = q.shape[2]
        mask = jnp.tril(jnp.ones((n, n), bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
