"""Fused optimizer step kernels: one VMEM pass over (grad, m, v, param)
(docs/KERNELS.md).

The reference applies sparse/dense updates with hand-fused CUDA kernels
(``src/ops/Optimizers.cu`` / ``OptimizersSparse.cu``); under XLA the
update rule is a chain of elementwise HLOs that the fusion pass USUALLY
melts into the gradient epilogue — but for the large-parameter ZeRO-ish
step the measured behavior (hetuprof roofline: optimizer families sit on
the HBM roof) is several full passes over param-sized tensors. The Adam
kernel here reads grad + m + v + param once each and writes the three
outputs in the same pass — arithmetic intensity goes from ~1 flop/byte
per HLO to the full rule per element loaded.

Numerical contract: the kernel body is the SAME expression sequence as
``Optimizer.apply_dense`` (bias-corrected Adam, SGD with fused l2), so
off/auto/force agree to f32 rounding; the equality tests pin it.

Layout: parameters arrive in their natural shapes; the kernel views them
as lane-shaped ``(rows, 128)`` blocks, zero-padded up to the 8x128 f32
tile and sliced back — elementwise kernels can always be tiled by
padding, so only dtype (f32 master precision) disqualifies a call, and
the whole parameter set of a real model (odd biases included) rides the
fused pass. An optional extra addend (e.g. a decoded error-feedback
residual folded into the grad) rides the same pass.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import registry

_LANE = registry.LANE
_SUBLANE = registry.SUBLANE
_TILE = _LANE * _SUBLANE


def _lane_view(x):
    """Flat lane-shaped view, zero-padded up to the 8x128 f32 tile —
    elementwise kernels can always be tiled by padding (the pad rows are
    computed and sliced away; XLA fuses the pad/slice into the call's
    edges), unlike the gather/matmul kernels whose alignment is load-
    bearing. Returns (view, n_elements)."""
    n = x.size
    pad = (-n) % _TILE
    flat = x.reshape(-1)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, _LANE), n


def _unview(view, n, shape):
    return view.reshape(-1)[:n].reshape(shape)


# ---------------------------------------------------------------------------
# Adam (bias-corrected; optional decoupled weight decay)
# ---------------------------------------------------------------------------

def _adam_xla(param, grad, m, v, t, lr, *, beta1, beta2, eps, weight_decay):
    """The Optimizer.apply_dense expression sequence, verbatim."""
    t = t + 1.0
    m = beta1 * m + (1.0 - beta1) * grad
    v = beta2 * v + (1.0 - beta2) * grad * grad
    m_hat = m / (1.0 - beta1 ** t)
    v_hat = v / (1.0 - beta2 ** t)
    new_param = param - lr * m_hat / (jnp.sqrt(v_hat) + eps)
    if weight_decay > 0:
        new_param = new_param - lr * weight_decay * param
    return new_param, m, v, t


def _adam_kernel(p_ref, g_ref, m_ref, v_ref, t_ref, lr_ref,
                 po_ref, mo_ref, vo_ref, *, beta1, beta2, eps, weight_decay):
    t = t_ref[0, 0] + 1.0
    lr = lr_ref[0, 0]
    g = g_ref[:]
    p = p_ref[:]
    m = beta1 * m_ref[:] + (1.0 - beta1) * g
    v = beta2 * v_ref[:] + (1.0 - beta2) * g * g
    m_hat = m / (1.0 - beta1 ** t)
    v_hat = v / (1.0 - beta2 ** t)
    new_p = p - lr * m_hat / (jnp.sqrt(v_hat) + eps)
    if weight_decay > 0:
        new_p = new_p - lr * weight_decay * p
    po_ref[:] = new_p
    mo_ref[:] = m
    vo_ref[:] = v


def _adam_pallas(param, grad, m, v, t, lr, *, beta1, beta2, eps,
                 weight_decay):
    shape = param.shape
    (pv, n), (gv, _), (mv, _), (vv, _) = (
        _lane_view(x) for x in (param, grad, m, v))
    t_in = jnp.asarray(t, jnp.float32).reshape(1, 1)
    lr_in = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    vec = pl.BlockSpec(memory_space=pltpu.VMEM)
    sca = pl.BlockSpec(memory_space=pltpu.SMEM)
    new_p, new_m, new_v = pl.pallas_call(
        functools.partial(_adam_kernel, beta1=beta1, beta2=beta2, eps=eps,
                          weight_decay=weight_decay),
        in_specs=[vec, vec, vec, vec, sca, sca],
        out_specs=[vec, vec, vec],
        out_shape=[jax.ShapeDtypeStruct(pv.shape, jnp.float32)] * 3,
        interpret=not registry._on_tpu(),
    )(pv, gv, mv, vv, t_in, lr_in)
    return (_unview(new_p, n, shape), _unview(new_m, n, shape),
            _unview(new_v, n, shape), jnp.asarray(t, jnp.float32) + 1.0)


def _sized_f32(name, x):
    """Elementwise kernels pad to the tile internally, so alignment is
    never disqualifying — only dtype (f32 master precision) and emptiness
    are."""
    if jnp.dtype(x.dtype) != jnp.dtype(jnp.float32):
        return False, f"{name} must be f32 (master precision), got {x.dtype}"
    n = 1
    for s in x.shape:
        n *= int(s)
    if n == 0:
        return False, f"{name} is empty"
    return True, None


def _adam_eligible(param, grad, m, v, t, lr, **_kw):
    for name, x in (("param", param), ("grad", grad), ("m", m), ("v", v)):
        ok, why = _sized_f32(name, x)
        if not ok:
            return ok, why
    return True, None


registry.register_kernel(
    "fused_adam",
    pallas_fn=_adam_pallas,
    xla_fallback=_adam_xla,
    eligibility=_adam_eligible,
)


# ---------------------------------------------------------------------------
# SGD (l2 folded into the same pass)
# ---------------------------------------------------------------------------

def _sgd_xla(param, grad, lr, *, l2reg):
    if l2reg > 0:
        grad = grad + l2reg * param
    return param - lr * grad


def _sgd_kernel(p_ref, g_ref, lr_ref, o_ref, *, l2reg):
    g = g_ref[:]
    p = p_ref[:]
    if l2reg > 0:
        g = g + l2reg * p
    o_ref[:] = p - lr_ref[0, 0] * g


def _sgd_pallas(param, grad, lr, *, l2reg):
    shape = param.shape
    pv, n = _lane_view(param)
    gv, _ = _lane_view(grad)
    lr_in = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    vec = pl.BlockSpec(memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        functools.partial(_sgd_kernel, l2reg=l2reg),
        in_specs=[vec, vec, pl.BlockSpec(memory_space=pltpu.SMEM)],
        out_specs=vec,
        out_shape=jax.ShapeDtypeStruct(pv.shape, jnp.float32),
        interpret=not registry._on_tpu(),
    )(pv, gv, lr_in)
    return _unview(out, n, shape)


def _sgd_eligible(param, grad, lr, **_kw):
    for name, x in (("param", param), ("grad", grad)):
        ok, why = _sized_f32(name, x)
        if not ok:
            return ok, why
    return True, None


registry.register_kernel(
    "fused_sgd",
    pallas_fn=_sgd_pallas,
    xla_fallback=_sgd_xla,
    eligibility=_sgd_eligible,
)


# ---------------------------------------------------------------------------
# optimizer.py entry points
# ---------------------------------------------------------------------------

def adam_step(opt, param, grad, slot, lr):
    """Registry-dispatched Adam apply for one parameter. ``opt`` is the
    AdamOptimizer (hyperparameters are trace-time constants)."""
    new_p, m, v, t = registry.dispatch(
        "fused_adam", param, grad, slot["m"], slot["v"], slot["t"], lr,
        beta1=opt.beta1, beta2=opt.beta2, eps=opt.epsilon,
        weight_decay=opt.weight_decay)
    return new_p, {"m": m, "v": v, "t": t}


def sgd_step(opt, param, grad, lr):
    return registry.dispatch("fused_sgd", param, grad, lr, l2reg=opt.l2reg)
