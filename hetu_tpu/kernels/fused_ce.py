"""Fused linear + softmax cross-entropy ("cut cross-entropy") for TPU.

The standard path materializes the full (N, V) logits tensor in HBM twice
(forward + backward) — for BERT-base's MLM head that is N=B·P rows against
V≈30k vocab, ~300 MB of f32 per direction per step, pure bandwidth. This
kernel never materializes logits: vocab TILES stream through VMEM with an
online (max, sum) logsumexp — exactly the flash-attention recurrence with
the vocabulary playing the key axis — and the backward recomputes each
probability tile from the saved per-row lse (no residual bigger than (N,)).

    nll = fused_linear_nll(h, W, b, targets)   # (N,) per-row -log p[target]

with ``logits = h @ W^T + b`` implied, differentiable wrt h, W, b via
custom_vjp (targets are integers; their cotangent is None). Reference
accounting: SURVEY §7 names softmax-CE a Pallas fusion candidate; the
technique is the public "cut your losses" formulation re-derived for the
Pallas TPU programming model.

Interpret mode off-TPU (same code runs in the CPU-mesh tests); an XLA
einsum fallback (`linear_nll_reference`) is the numerical oracle.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

DEFAULT_BLOCK_N = 128
DEFAULT_BLOCK_V = 512
_NEG_INF = -1e30


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


# ---------------------------------------------------------------------------
# forward: per-row (lse, target_logit)
# ---------------------------------------------------------------------------

def _fwd_kernel(h_ref, w_ref, b_ref, tgt_ref, lse_ref, tl_ref, *,
                block_v, vocab, n_vb):
    h = h_ref[0].astype(jnp.float32)                  # (Bn, D)
    tgt = tgt_ref[0, :, 0]                            # (Bn,)
    Bn = h.shape[0]

    def body(vj, carry):
        m_prev, l_prev, tl = carry
        w_blk = w_ref[0, pl.ds(vj * block_v, block_v)].astype(jnp.float32)
        b_blk = b_ref[0, pl.ds(vj * block_v, block_v), 0].astype(jnp.float32)
        s = jax.lax.dot_general(h, w_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) + b_blk
        # vocab tail: positions past V never participate
        vpos = vj * block_v + jax.lax.broadcasted_iota(
            jnp.int32, (Bn, block_v), 1)
        s = jnp.where(vpos < vocab, s, _NEG_INF)
        # the target logit lives in exactly one tile per row
        hit = vpos == tgt[:, None]
        tl = tl + jnp.sum(jnp.where(hit, s, 0.0), axis=1)
        m_cur = jnp.max(s, axis=1)
        m_new = jnp.maximum(m_prev, m_cur)
        l_new = (l_prev * jnp.exp(m_prev - m_new)
                 + jnp.sum(jnp.exp(s - m_new[:, None]), axis=1))
        return m_new, l_new, tl

    m0 = jnp.full((Bn,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((Bn,), jnp.float32)
    tl0 = jnp.zeros((Bn,), jnp.float32)
    m, l, tl = jax.lax.fori_loop(0, n_vb, body, (m0, l0, tl0))
    lse_ref[0, :, 0] = m + jnp.log(jnp.maximum(l, 1e-30))
    tl_ref[0, :, 0] = tl


# ---------------------------------------------------------------------------
# backward: dh over row blocks; dW/db over vocab blocks — both recompute
# their probability tile from (h, W, lse), flash-style
# ---------------------------------------------------------------------------

def _bwd_dh_kernel(h_ref, w_ref, b_ref, tgt_ref, lse_ref, ct_ref, dh_ref, *,
                   block_v, vocab, n_vb):
    h = h_ref[0].astype(jnp.float32)
    tgt = tgt_ref[0, :, 0]
    lse = lse_ref[0, :, 0]
    ct = ct_ref[0, :, 0]                              # dloss per row
    Bn = h.shape[0]

    def body(vj, dh):
        w_blk = w_ref[0, pl.ds(vj * block_v, block_v)].astype(jnp.float32)
        b_blk = b_ref[0, pl.ds(vj * block_v, block_v), 0].astype(jnp.float32)
        s = jax.lax.dot_general(h, w_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) + b_blk
        vpos = vj * block_v + jax.lax.broadcasted_iota(
            jnp.int32, (Bn, block_v), 1)
        p = jnp.where(vpos < vocab, jnp.exp(s - lse[:, None]), 0.0)
        g = (p - (vpos == tgt[:, None]).astype(jnp.float32)) * ct[:, None]
        return dh + jax.lax.dot(g, w_blk,
                                preferred_element_type=jnp.float32)

    dh = jax.lax.fori_loop(0, n_vb, body,
                           jnp.zeros(h.shape, jnp.float32))
    dh_ref[0] = dh.astype(dh_ref.dtype)


def _bwd_dw_kernel(h_ref, w_ref, b_ref, tgt_ref, lse_ref, ct_ref,
                   dw_ref, db_ref, *, block_n, vocab, n_nb):
    w_blk = w_ref[0].astype(jnp.float32)              # (Bv, D)
    b_blk = b_ref[0, :, 0].astype(jnp.float32)
    Bv = w_blk.shape[0]
    vj = pl.program_id(1)
    vpos = vj * Bv + jax.lax.broadcasted_iota(jnp.int32, (1, Bv), 1)

    def body(nj, carry):
        dw, db = carry
        h = h_ref[0, pl.ds(nj * block_n, block_n)].astype(jnp.float32)
        tgt = tgt_ref[0, pl.ds(nj * block_n, block_n), 0]
        lse = lse_ref[0, pl.ds(nj * block_n, block_n), 0]
        ct = ct_ref[0, pl.ds(nj * block_n, block_n), 0]
        s = jax.lax.dot_general(h, w_blk, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) + b_blk
        p = jnp.where(vpos < vocab, jnp.exp(s - lse[:, None]), 0.0)
        g = (p - (vpos == tgt[:, None]).astype(jnp.float32)) * ct[:, None]
        dw = dw + jax.lax.dot_general(g, h, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
        db = db + jnp.sum(g, axis=0)
        return dw, db

    dw, db = jax.lax.fori_loop(
        0, n_nb, body,
        (jnp.zeros(w_blk.shape, jnp.float32), jnp.zeros((Bv,), jnp.float32)))
    dw_ref[0] = dw.astype(dw_ref.dtype)
    db_ref[0, :, 0] = db.astype(db_ref.dtype)


# ---------------------------------------------------------------------------
# host-side plumbing
# ---------------------------------------------------------------------------

def _pad_to(x, mult, axis, value=0):
    n = x.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad, constant_values=value)


def _resolve_blocks(n, v, block_n, block_v):
    return min(block_n, max(n, 1)), min(block_v, max(v, 1))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5))
def _fused(h, w, b, targets, block_n, block_v):
    out, _ = _fused_fwd(h, w, b, targets, block_n, block_v)
    return out


def fused_linear_nll(h, w, b, targets, block_n=DEFAULT_BLOCK_N,
                     block_v=DEFAULT_BLOCK_V):
    """Per-row ``-log softmax(h @ w^T + b)[target]`` without materializing
    the (N, V) logits. h: (N, D); w: (V, D); b: (V,); targets: (N,) int32.
    Returns (N,) f32. Differentiable wrt h, w, b."""
    return _fused(h, w, b, targets, block_n, block_v)


def _stage(h, w, b, targets, block_n, block_v):
    """Pad to block multiples and reshape for the kernels' (1, ·, ·) refs."""
    N, V = h.shape[0], w.shape[0]
    block_n, block_v = _resolve_blocks(N, V, block_n, block_v)
    hp = _pad_to(h, block_n, 0)
    tp = _pad_to(targets.astype(jnp.int32), block_n, 0)
    wp = _pad_to(w, block_v, 0)
    bp = _pad_to(b, block_v, 0)
    return hp, wp, bp, tp, N, V, block_n, block_v


def _fused_fwd(h, w, b, targets, block_n, block_v):
    hp, wp, bp, tp, N, V, block_n, block_v = _stage(
        h, w, b, targets, block_n, block_v)
    Np, Vp, D = hp.shape[0], wp.shape[0], hp.shape[1]
    n_vb = Vp // block_v
    lse, tl = pl.pallas_call(
        functools.partial(_fwd_kernel, block_v=block_v, vocab=V, n_vb=n_vb),
        grid=(Np // block_n,),
        in_specs=[
            pl.BlockSpec((1, block_n, D), lambda i: (0, i, 0)),
            pl.BlockSpec((1, Vp, D), lambda i: (0, 0, 0)),
            pl.BlockSpec((1, Vp, 1), lambda i: (0, 0, 0)),
            pl.BlockSpec((1, block_n, 1), lambda i: (0, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_n, 1), lambda i: (0, i, 0)),
            pl.BlockSpec((1, block_n, 1), lambda i: (0, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, Np, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, Np, 1), jnp.float32),
        ],
        interpret=not _on_tpu(),
    )(hp[None], wp[None], bp[None, :, None], tp[None, :, None])
    nll = (lse[0, :N, 0] - tl[0, :N, 0])
    return nll, (h, w, b, targets, lse[0, :, 0])


def _fused_bwd(block_n, block_v, res, ct):
    h, w, b, targets, lse_p = res
    hp, wp, bp, tp, N, V, block_n, block_v = _stage(
        h, w, b, targets, block_n, block_v)
    Np, Vp, D = hp.shape[0], wp.shape[0], hp.shape[1]
    ctp = _pad_to(ct.astype(jnp.float32), block_n, 0)  # padded rows: ct = 0
    lsep = lse_p[None, :, None]

    dh = pl.pallas_call(
        functools.partial(_bwd_dh_kernel, block_v=block_v, vocab=V,
                          n_vb=Vp // block_v),
        grid=(Np // block_n,),
        in_specs=[
            pl.BlockSpec((1, block_n, D), lambda i: (0, i, 0)),
            pl.BlockSpec((1, Vp, D), lambda i: (0, 0, 0)),
            pl.BlockSpec((1, Vp, 1), lambda i: (0, 0, 0)),
            pl.BlockSpec((1, block_n, 1), lambda i: (0, i, 0)),
            pl.BlockSpec((1, block_n, 1), lambda i: (0, i, 0)),
            pl.BlockSpec((1, block_n, 1), lambda i: (0, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_n, D), lambda i: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((1, Np, D), h.dtype),
        interpret=not _on_tpu(),
    )(hp[None], wp[None], bp[None, :, None], tp[None, :, None], lsep,
      ctp[None, :, None])

    dw, db = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, block_n=block_n, vocab=V,
                          n_nb=Np // block_n),
        grid=(1, Vp // block_v),
        in_specs=[
            pl.BlockSpec((1, Np, D), lambda i, j: (0, 0, 0)),
            pl.BlockSpec((1, block_v, D), lambda i, j: (0, j, 0)),
            pl.BlockSpec((1, block_v, 1), lambda i, j: (0, j, 0)),
            pl.BlockSpec((1, Np, 1), lambda i, j: (0, 0, 0)),
            pl.BlockSpec((1, Np, 1), lambda i, j: (0, 0, 0)),
            pl.BlockSpec((1, Np, 1), lambda i, j: (0, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_v, D), lambda i, j: (0, j, 0)),
            pl.BlockSpec((1, block_v, 1), lambda i, j: (0, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, Vp, D), w.dtype),
            jax.ShapeDtypeStruct((1, Vp, 1), jnp.float32),
        ],
        interpret=not _on_tpu(),
    )(hp[None], wp[None], bp[None, :, None], tp[None, :, None], lsep,
      ctp[None, :, None])

    return (dh[0, :N].astype(h.dtype), dw[0, :V].astype(w.dtype),
            db[0, :V, 0].astype(b.dtype), None)


_fused.defvjp(_fused_fwd, _fused_bwd)


def linear_nll_reference(h, w, b, targets):
    """Unfused oracle: materializes the full logits."""
    logits = (h.astype(jnp.float32) @ w.astype(jnp.float32).T
              + b.astype(jnp.float32))
    logp = jax.nn.log_softmax(logits, -1)
    return -jnp.take_along_axis(logp, targets[:, None].astype(jnp.int32),
                                -1)[:, 0]
