"""Fused linear + softmax cross-entropy ("cut cross-entropy") for TPU.

The standard path materializes the full (N, V) logits tensor in HBM twice
(forward + backward) — for BERT-base's MLM head that is N=B·P rows against
V≈30k vocab, ~300 MB of f32 per direction per step, pure bandwidth. This
kernel never materializes logits: the VOCABULARY is a grid axis, so weight
TILES stream HBM->VMEM one (block_v, D) slab at a time while per-row online
(max, sum) logsumexp state lives in VMEM scratch — the flash-attention
recurrence with the vocabulary playing the key axis. The backward recomputes
each probability tile from the saved per-row lse (no residual bigger than
(N,)).

    nll = fused_linear_nll(h, W, b, targets)   # (N,) per-row -log p[target]

with ``logits = h @ W^T + b`` implied (``w_layout="vd"``, W is (V, D) — the
tied-embedding orientation) or ``logits = h @ W + b`` (``w_layout="dv"``,
W is (D, V) — the LM-head orientation). Both layouts are native: no caller
ever transposes a vocab-sized matrix. Differentiable wrt h, W, b via
custom_vjp (targets are integers; their cotangent is None).

Reference accounting: SURVEY §7 names softmax-CE a Pallas fusion candidate;
the technique is the public "cut your losses" formulation re-derived for
the Pallas TPU programming model. Interpret mode off-TPU (same code runs in
the CPU-mesh tests); ``linear_nll_reference`` is the numerical oracle.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

DEFAULT_BLOCK_N = 128
DEFAULT_BLOCK_V = 512
_NEG_INF = -1e30


def _on_tpu() -> bool:
    return jax.default_backend() == "tpu"


def should_fuse(flag, mesh=None) -> bool:
    """The ONE gating rule for config flags ('auto' | True | False): fused
    CE runs on the single-program TPU path. Under a mesh the einsum form
    stays (GSPMD cannot partition the custom kernel); off-TPU interpret
    mode would be slower than the einsum."""
    if mesh is not None:
        return False
    return flag is True or (flag == "auto" and _on_tpu())


def _dot_hw(h, w_blk, w_dv):
    """(Bn, D) x W tile -> (Bn, block_v) logits tile for either layout."""
    if w_dv:   # w_blk (D, block_v)
        return jax.lax.dot(h, w_blk, preferred_element_type=jnp.float32)
    # w_blk (block_v, D)
    return jax.lax.dot_general(h, w_blk, (((1,), (1,)), ((), ())),
                               preferred_element_type=jnp.float32)


# ---------------------------------------------------------------------------
# forward: grid (row_blocks, vocab_blocks) — vocab innermost; the online
# (m, l, target-logit) state lives in scratch across the vocab sweep
# ---------------------------------------------------------------------------

def _fwd_kernel(h_ref, w_ref, b_ref, tgt_ref, lse_ref, tl_ref,
                m_sc, l_sc, tl_sc, *, block_v, vocab, n_vb, w_dv):
    vj = pl.program_id(1)

    @pl.when(vj == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc[:], _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc[:])
        tl_sc[:] = jnp.zeros_like(tl_sc[:])

    h = h_ref[0].astype(jnp.float32)                  # (Bn, D)
    tgt = tgt_ref[0, :, 0]                            # (Bn,)
    w_blk = w_ref[0].astype(jnp.float32)
    b_blk = b_ref[0, :, 0].astype(jnp.float32)
    Bn = h.shape[0]
    s = _dot_hw(h, w_blk, w_dv) + b_blk
    vpos = vj * block_v + jax.lax.broadcasted_iota(
        jnp.int32, (Bn, block_v), 1)
    s = jnp.where(vpos < vocab, s, _NEG_INF)          # vocab tail mask
    hit = vpos == tgt[:, None]
    tl_sc[:] = tl_sc[:] + jnp.sum(jnp.where(hit, s, 0.0), axis=1)
    m_prev, l_prev = m_sc[:], l_sc[:]
    m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
    l_new = (l_prev * jnp.exp(m_prev - m_new)
             + jnp.sum(jnp.exp(s - m_new[:, None]), axis=1))
    m_sc[:] = m_new
    l_sc[:] = l_new

    @pl.when(vj == n_vb - 1)
    def _emit():
        lse_ref[0, :, 0] = m_sc[:] + jnp.log(jnp.maximum(l_sc[:], 1e-30))
        tl_ref[0, :, 0] = tl_sc[:]


# ---------------------------------------------------------------------------
# backward: dh over (row_blocks, vocab_blocks) accumulating in scratch;
# dW/db over (vocab_blocks, row_blocks) — each recomputes its probability
# tile from (h, W, lse), flash-style
# ---------------------------------------------------------------------------

def _prob_grad_tile(h, w_blk, b_blk, tgt, lse, ct, v0, block_v, vocab, w_dv):
    """(softmax - onehot) * ct for one (row_block, vocab_block) tile."""
    Bn = h.shape[0]
    s = _dot_hw(h, w_blk, w_dv) + b_blk
    vpos = v0 + jax.lax.broadcasted_iota(jnp.int32, (Bn, block_v), 1)
    p = jnp.where(vpos < vocab, jnp.exp(s - lse[:, None]), 0.0)
    return (p - (vpos == tgt[:, None]).astype(jnp.float32)) * ct[:, None]


def _bwd_dh_kernel(h_ref, w_ref, b_ref, tgt_ref, lse_ref, ct_ref, dh_ref,
                   acc_sc, *, block_v, vocab, n_vb, w_dv):
    vj = pl.program_id(1)

    @pl.when(vj == 0)
    def _init():
        acc_sc[:] = jnp.zeros_like(acc_sc[:])

    h = h_ref[0].astype(jnp.float32)
    w_blk = w_ref[0].astype(jnp.float32)
    g = _prob_grad_tile(h, w_blk, b_ref[0, :, 0].astype(jnp.float32),
                        tgt_ref[0, :, 0], lse_ref[0, :, 0], ct_ref[0, :, 0],
                        vj * block_v, block_v, vocab, w_dv)
    if w_dv:   # w_blk (D, block_v): dh += g @ w_blk^T
        acc_sc[:] = acc_sc[:] + jax.lax.dot_general(
            g, w_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:      # w_blk (block_v, D): dh += g @ w_blk
        acc_sc[:] = acc_sc[:] + jax.lax.dot(
            g, w_blk, preferred_element_type=jnp.float32)

    @pl.when(vj == n_vb - 1)
    def _emit():
        dh_ref[0] = acc_sc[:].astype(dh_ref.dtype)


def _bwd_dw_kernel(h_ref, w_ref, b_ref, tgt_ref, lse_ref, ct_ref,
                   dw_ref, db_ref, dw_sc, db_sc, *, block_n, block_v,
                   vocab, n_nb, w_dv):
    vj, nj = pl.program_id(0), pl.program_id(1)

    @pl.when(nj == 0)
    def _init():
        dw_sc[:] = jnp.zeros_like(dw_sc[:])
        db_sc[:] = jnp.zeros_like(db_sc[:])

    h = h_ref[0].astype(jnp.float32)                  # (Bn, D)
    w_blk = w_ref[0].astype(jnp.float32)
    g = _prob_grad_tile(h, w_blk, b_ref[0, :, 0].astype(jnp.float32),
                        tgt_ref[0, :, 0], lse_ref[0, :, 0], ct_ref[0, :, 0],
                        vj * block_v, block_v, vocab, w_dv)
    if w_dv:   # dw tile (D, block_v) += h^T @ g
        dw_sc[:] = dw_sc[:] + jax.lax.dot_general(
            h, g, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    else:      # dw tile (block_v, D) += g^T @ h
        dw_sc[:] = dw_sc[:] + jax.lax.dot_general(
            g, h, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    db_sc[:] = db_sc[:] + jnp.sum(g, axis=0)

    @pl.when(nj == n_nb - 1)
    def _emit():
        dw_ref[0] = dw_sc[:].astype(dw_ref.dtype)
        db_ref[0, :, 0] = db_sc[:].astype(db_ref.dtype)


# ---------------------------------------------------------------------------
# host-side plumbing
# ---------------------------------------------------------------------------

def _pad_to(x, mult, axis):
    n = x.shape[axis]
    rem = (-n) % mult
    if rem == 0:
        return x
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, rem)
    return jnp.pad(x, pad)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _fused(h, w, b, targets, block_n, block_v, w_dv):
    out, _ = _fused_fwd(h, w, b, targets, block_n, block_v, w_dv)
    return out


def fused_linear_nll(h, w, b, targets, block_n=DEFAULT_BLOCK_N,
                     block_v=DEFAULT_BLOCK_V, w_layout="vd"):
    """Per-row NLL of ``softmax(linear(h))`` without materializing the
    (N, V) logits. h: (N, D); b: (V,); targets: (N,) int32; w: (V, D) with
    ``w_layout="vd"`` (tied-embedding orientation, logits = h @ w^T + b) or
    (D, V) with ``w_layout="dv"`` (LM-head orientation, logits = h @ w + b).
    Returns (N,) f32. Differentiable wrt h, w, b."""
    assert w_layout in ("vd", "dv"), w_layout
    return _fused(h, w, b, targets, block_n, block_v, w_layout == "dv")


def _stage(h, w, b, targets, block_n, block_v, w_dv):
    N = h.shape[0]
    V = w.shape[1] if w_dv else w.shape[0]
    block_n = min(block_n, max(N, 1))
    block_v = min(block_v, max(V, 1))
    hp = _pad_to(h, block_n, 0)
    tp = _pad_to(targets.astype(jnp.int32), block_n, 0)
    wp = _pad_to(w, block_v, 1 if w_dv else 0)
    bp = _pad_to(b, block_v, 0)
    return hp, wp, bp, tp, N, V, block_n, block_v


def _w_spec(block_v, D, w_dv):
    if w_dv:
        return pl.BlockSpec((1, D, block_v), lambda i, j: (0, 0, j))
    return pl.BlockSpec((1, block_v, D), lambda i, j: (0, j, 0))


def _fused_fwd(h, w, b, targets, block_n, block_v, w_dv):
    hp, wp, bp, tp, N, V, block_n, block_v = _stage(
        h, w, b, targets, block_n, block_v, w_dv)
    Np, D = hp.shape
    Vp = wp.shape[1] if w_dv else wp.shape[0]
    n_vb = Vp // block_v
    row = pl.BlockSpec((1, block_n, 1), lambda i, j: (0, i, 0))
    lse, tl = pl.pallas_call(
        functools.partial(_fwd_kernel, block_v=block_v, vocab=V, n_vb=n_vb,
                          w_dv=w_dv),
        grid=(Np // block_n, n_vb),   # vocab innermost: W tiles stream
        in_specs=[
            pl.BlockSpec((1, block_n, D), lambda i, j: (0, i, 0)),
            _w_spec(block_v, D, w_dv),
            pl.BlockSpec((1, block_v, 1), lambda i, j: (0, j, 0)),
            row,
        ],
        out_specs=[row, row],
        out_shape=[
            jax.ShapeDtypeStruct((1, Np, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, Np, 1), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((block_n,), jnp.float32)] * 3,
        interpret=not _on_tpu(),
    )(hp[None], wp[None], bp[None, :, None], tp[None, :, None])
    nll = (lse[0, :N, 0] - tl[0, :N, 0])
    return nll, (h, w, b, targets, lse[0, :, 0])


def _fused_bwd(block_n, block_v, w_dv, res, ct):
    h, w, b, targets, lse_p = res
    hp, wp, bp, tp, N, V, block_n, block_v = _stage(
        h, w, b, targets, block_n, block_v, w_dv)
    Np, D = hp.shape
    Vp = wp.shape[1] if w_dv else wp.shape[0]
    n_vb, n_nb = Vp // block_v, Np // block_n
    ctp = _pad_to(ct.astype(jnp.float32), block_n, 0)  # padded rows: ct = 0
    lsep = lse_p[None, :, None]
    row_i = pl.BlockSpec((1, block_n, 1), lambda i, j: (0, i, 0))

    dh = pl.pallas_call(
        functools.partial(_bwd_dh_kernel, block_v=block_v, vocab=V,
                          n_vb=n_vb, w_dv=w_dv),
        grid=(n_nb, n_vb),
        in_specs=[
            pl.BlockSpec((1, block_n, D), lambda i, j: (0, i, 0)),
            _w_spec(block_v, D, w_dv),
            pl.BlockSpec((1, block_v, 1), lambda i, j: (0, j, 0)),
            row_i, row_i, row_i,
        ],
        out_specs=pl.BlockSpec((1, block_n, D), lambda i, j: (0, i, 0)),
        out_shape=jax.ShapeDtypeStruct((1, Np, D), h.dtype),
        scratch_shapes=[pltpu.VMEM((block_n, D), jnp.float32)],
        interpret=not _on_tpu(),
    )(hp[None], wp[None], bp[None, :, None], tp[None, :, None], lsep,
      ctp[None, :, None])

    # dW/db: vocab blocks OUTER, row blocks inner (each W tile revisits its
    # accumulator across the row sweep)
    row_j = pl.BlockSpec((1, block_n, 1), lambda i, j: (0, j, 0))
    wspec = (pl.BlockSpec((1, D, block_v), lambda i, j: (0, 0, i)) if w_dv
             else pl.BlockSpec((1, block_v, D), lambda i, j: (0, i, 0)))
    dw_shape = (1, D, Vp) if w_dv else (1, Vp, D)
    dw_out = (pl.BlockSpec((1, D, block_v), lambda i, j: (0, 0, i)) if w_dv
              else pl.BlockSpec((1, block_v, D), lambda i, j: (0, i, 0)))
    dw_sc = (pltpu.VMEM((D, block_v), jnp.float32) if w_dv
             else pltpu.VMEM((block_v, D), jnp.float32))
    dw, db = pl.pallas_call(
        functools.partial(_bwd_dw_kernel, block_n=block_n, block_v=block_v,
                          vocab=V, n_nb=n_nb, w_dv=w_dv),
        grid=(n_vb, n_nb),
        in_specs=[
            pl.BlockSpec((1, block_n, D), lambda i, j: (0, j, 0)),
            wspec,
            pl.BlockSpec((1, block_v, 1), lambda i, j: (0, i, 0)),
            row_j, row_j, row_j,
        ],
        out_specs=[
            dw_out,
            pl.BlockSpec((1, block_v, 1), lambda i, j: (0, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(dw_shape, w.dtype),
            jax.ShapeDtypeStruct((1, Vp, 1), jnp.float32),
        ],
        scratch_shapes=[dw_sc, pltpu.VMEM((block_v,), jnp.float32)],
        interpret=not _on_tpu(),
    )(hp[None], wp[None], bp[None, :, None], tp[None, :, None], lsep,
      ctp[None, :, None])

    dw_full = dw[0, :, :V] if w_dv else dw[0, :V]
    return (dh[0, :N].astype(h.dtype), dw_full.astype(w.dtype),
            db[0, :V, 0].astype(b.dtype), None)


_fused.defvjp(_fused_fwd, _fused_bwd)


def linear_nll_reference(h, w, b, targets, w_layout="vd"):
    """Unfused oracle: materializes the full logits."""
    wf = w.astype(jnp.float32)
    if w_layout == "vd":
        wf = wf.T
    logits = h.astype(jnp.float32) @ wf + b.astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, -1)
    return -jnp.take_along_axis(logp, targets[:, None].astype(jnp.int32),
                                -1)[:, 0]
