"""hetukern: the Pallas kernel tier (docs/KERNELS.md).

Layout:

- :mod:`registry` — the dispatch gate every kernel call goes through
  (``HetuConfig(kernels="off"|"auto"|"force")`` / ``HETU_KERNELS``,
  per-call eligibility, ``hetu_kernel_dispatch_total{kernel,path}``).
- :mod:`embed_grad` — fused sparse embedding gradient: sort/unique +
  segment-sum into IndexedSlices-style ``(rows, grads)``.
- :mod:`csr_spmm` — blocked rows-into-VMEM segment-MAC for the
  CSR/COO sparse products (csrmm/csrmv, DistGCN 1.5D).
- :mod:`quant_comm` — one-pass blockwise quantize/dequantize fused into
  the hetuq AllReduce legs (bit-identical wire payloads).
- :mod:`fused_opt` — multi-tensor Adam/SGD apply in one VMEM pass.
- :mod:`flash_attention` / :mod:`fused_ce` — the two pre-tier kernels
  (their ``should_fuse``-style gating predates the registry and is
  documented in docs/KERNELS.md).

Importing this package registers the four tier kernels; the graph ops
import it lazily inside their compute fns so jax-free tools never pay
for it.
"""
from . import registry                            # noqa: F401
from .registry import (                           # noqa: F401
    KernelEligibilityError, KernelSpec, active, current_mode, dispatch,
    dispatch_stats, eligibility_of, fallback_ratio, register_kernel,
    registered_kernels, reset_stats, resolve_mode,
)
from . import embed_grad, csr_spmm, quant_comm, fused_opt  # noqa: F401
