"""hetukern dispatch registry: the one gate between graph ops and the
Pallas kernel tier (docs/KERNELS.md).

Every kernel in ``hetu_tpu/kernels`` registers itself here as a
:class:`KernelSpec` — ``{name, pallas_fn, xla_fallback, eligibility}`` —
and every call site goes through :func:`dispatch`, never straight at the
``pallas_fn``. The mode knob (``HetuConfig(kernels="off"|"auto"|"force")``
/ ``HETU_KERNELS``) decides which implementation serves a call:

- ``off``   — the XLA fallback, unconditionally. Bit-identical to the
  pre-hetukern tree: the fallback IS the expression the op used before
  the tier existed.
- ``auto``  — the Pallas kernel when the shape/dtype eligibility
  predicate passes AND the backend is a real TPU; the fallback otherwise
  (per call, per shape — a 100-row lookup falls back while the 1M-row
  one next to it takes the kernel). Off-TPU, ``auto`` always falls back:
  interpret-mode Pallas is a *testing* vehicle, slower than the XLA
  fallback it mirrors.
- ``force`` — the Pallas kernel, interpret-mode off-TPU (how the CPU
  equality tests drive the kernel path); an ineligible shape raises
  :class:`KernelEligibilityError` instead of silently falling back —
  hetulint's ``kernels_pass`` catches this at define time.

Dispatch decisions happen at TRACE time (the call sites live inside the
jitted step), so the ``hetu_kernel_dispatch_total{kernel,path}`` counter
ticks once per compiled program per call site, not once per step — it
answers "which tier serves this op family in the programs now running",
which is what hetutop's ``kernels:`` panel shows. A process-local mirror
(:func:`dispatch_stats`) backs the hetulint fallback-ratio note when
telemetry is off.

The mode is scoped, not global: the Executor wraps every step
trace/lower in ``with active(config.kernels):`` so two executors with
different settings interleave correctly; bare calls outside any scope
resolve from ``HETU_KERNELS`` (default ``auto``).
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Optional

MODES = ("off", "auto", "force")

# shared TPU tiling/budget constants for the kernel modules (one home so
# a budget or tile change cannot silently drift between kernels)
LANE = 128
SUBLANE = 8
VMEM_BUDGET_BYTES = 8 * 1024 * 1024


class KernelEligibilityError(ValueError):
    """kernels="force" met a shape/dtype the Pallas kernel cannot take."""

    def __init__(self, kernel: str, reason: str):
        super().__init__(
            f"kernels='force': {kernel} is ineligible for this call — "
            f"{reason}. Use kernels='auto' to fall back per-shape, or fix "
            "the shape (docs/KERNELS.md lists each kernel's eligibility "
            "rules)")
        self.kernel = kernel
        self.reason = reason


class KernelSpec:
    """One registered kernel: the Pallas implementation, the XLA expression
    it must match, and the predicate deciding per-call eligibility.

    ``eligibility(*args, **kwargs) -> (ok, reason)`` sees the same
    arguments as the implementations; it must only read shapes/dtypes (it
    is also called by hetulint with ``ShapeDtypeStruct`` stand-ins)."""

    def __init__(self, name: str, pallas_fn: Callable, xla_fallback: Callable,
                 eligibility: Callable):
        self.name = name
        self.pallas_fn = pallas_fn
        self.xla_fallback = xla_fallback
        self.eligibility = eligibility


_REGISTRY: dict[str, KernelSpec] = {}

# process-local dispatch tallies: {(kernel, path): count}. Mirrors the
# telemetry counter so the hetulint fallback-ratio note works without an
# active telemetry session.
_stats: dict[tuple, int] = {}
_stats_lock = threading.Lock()

# scoped-mode stack (executor traces push config.kernels here); thread-local
# because PS stream threads must not see a trace's scope
_tls = threading.local()


def register_kernel(name: str, *, pallas_fn: Callable, xla_fallback: Callable,
                    eligibility: Callable) -> KernelSpec:
    spec = KernelSpec(name, pallas_fn, xla_fallback, eligibility)
    _REGISTRY[name] = spec
    return spec


def get_kernel(name: str) -> Optional[KernelSpec]:
    return _REGISTRY.get(name)


def registered_kernels() -> dict[str, KernelSpec]:
    return dict(_REGISTRY)


def resolve_mode(mode: Optional[str] = None) -> str:
    """Config-or-env resolution (the telemetry convention): explicit wins,
    then ``HETU_KERNELS``, then ``auto`` (which changes nothing off-TPU —
    eligibility gates the kernel path to real TPU backends)."""
    if mode is None:
        mode = os.environ.get("HETU_KERNELS") or "auto"
    if mode not in MODES:
        raise ValueError(f"kernels must be one of {MODES}, got {mode!r}")
    return mode


class active:
    """``with active("force"): ...`` — scope the dispatch mode for the
    enclosed trace. Re-entrant; the innermost scope wins.

    ``spmd=True`` marks the enclosed trace as a GSPMD multi-device
    program (the executor passes ``mesh is not None and mesh.size > 1``):
    a bare ``pallas_call`` inside such a program has no SPMD partitioning
    rule — GSPMD would fail to lower it or replicate the operand — so
    every kernel's eligibility declines under this flag. Per-shard
    ``shard_map`` wrapping of the kernels is the documented follow-up
    (docs/KERNELS.md); until then the tier serves single-device programs.
    """

    def __init__(self, mode: Optional[str], spmd: bool = False):
        self.mode = resolve_mode(mode)
        self.spmd = bool(spmd)

    def __enter__(self):
        stack = getattr(_tls, "stack", None)
        if stack is None:
            stack = _tls.stack = []
        stack.append((self.mode, self.spmd))
        return self

    def __exit__(self, *exc):
        _tls.stack.pop()
        return False


def current_mode() -> str:
    stack = getattr(_tls, "stack", None)
    if stack:
        return stack[-1][0]
    return resolve_mode(None)


def in_spmd_scope() -> bool:
    """Is the current trace scoped as a GSPMD multi-device program?"""
    stack = getattr(_tls, "stack", None)
    return bool(stack) and stack[-1][1]


def _on_tpu() -> bool:
    import jax
    return jax.default_backend() == "tpu"


def _count(kernel: str, path: str) -> None:
    with _stats_lock:
        key = (kernel, path)
        _stats[key] = _stats.get(key, 0) + 1
    from .. import telemetry as _tel
    t = _tel.get()
    if t is not None:
        t.metrics.counter("hetu_kernel_dispatch_total",
                          {"kernel": kernel, "path": path}).inc()


def dispatch_stats() -> dict:
    """``{(kernel, path): count}`` snapshot of every dispatch decision this
    process made (trace-time tallies — see the module docstring)."""
    with _stats_lock:
        return dict(_stats)


def reset_stats() -> None:
    with _stats_lock:
        _stats.clear()


def fallback_ratio(kernel: str) -> Optional[float]:
    """Share of this kernel's AUTO-mode dispatches served by the fallback,
    or None when it was never dispatched under auto. Force-mode servings
    count under the distinct ``forced`` path, so an equality smoke run
    before linting cannot dilute this ratio."""
    s = dispatch_stats()
    pallas = s.get((kernel, "pallas"), 0)
    fb = s.get((kernel, "fallback"), 0)
    total = pallas + fb
    return (fb / total) if total else None


def dispatch(name: str, *args, **kwargs):
    """Serve one kernel call through the mode/eligibility gate.

    Paths counted: ``pallas`` (kernel served under auto), ``forced``
    (kernel served under force), ``fallback`` (auto declined — ineligible
    shape or non-TPU backend), ``off`` (mode off). ``force`` raises on
    ineligibility rather than counting a fallback."""
    spec = _REGISTRY.get(name)
    if spec is None:
        raise KeyError(f"no kernel {name!r} registered "
                       f"(have: {sorted(_REGISTRY)})")
    mode = current_mode()
    if mode == "off":
        _count(name, "off")
        return spec.xla_fallback(*args, **kwargs)
    ok, reason = _check_eligibility(spec, args, kwargs)
    if mode == "force":
        if not ok:
            raise KernelEligibilityError(name, reason or "ineligible")
        _count(name, "forced")
        return spec.pallas_fn(*args, **kwargs)
    # auto: Pallas only where it can win — an eligible shape on a real TPU
    if ok and _on_tpu():
        _count(name, "pallas")
        return spec.pallas_fn(*args, **kwargs)
    _count(name, "fallback")
    return spec.xla_fallback(*args, **kwargs)


def _check_eligibility(spec: KernelSpec, args, kwargs):
    """Shared pre-check + per-kernel predicate: the partitioned-context
    decline lives HERE (once), not copy-pasted into every predicate."""
    if _partitioned_context():
        return False, ("inside a partitioned trace (shard_map named axis "
                       "or GSPMD multi-device scope)")
    return spec.eligibility(*args, **kwargs)


def eligibility_of(name: str, *args, **kwargs):
    """(ok, reason) for a hypothetical call — what hetulint's
    ``kernels_pass`` evaluates against abstract shapes."""
    spec = _REGISTRY.get(name)
    if spec is None:
        return False, f"no kernel {name!r} registered"
    return _check_eligibility(spec, args, kwargs)


def _partitioned_context() -> bool:
    """True when a bare ``pallas_call`` would face partitioning the
    kernels do not implement: a GSPMD multi-device scope (the executor's
    ``active(..., spmd=True)``) or a named-axis (shard_map/pmap) trace.
    Eligibility predicates decline here so ``auto`` keeps partitioned
    programs on their XLA fallbacks."""
    return in_spmd_scope() or _in_named_axis_trace()


def _in_named_axis_trace() -> bool:
    """True inside a shard_map/pmap named-axis trace, where a pallas_call
    cannot be partitioned by GSPMD — eligibility predicates use this to
    decline (the DistGCN call site lives inside shard_map).

    The probes read private jax internals, so version drift can make both
    unusable. That failure FAILS CLOSED for ``auto`` (report 'inside', so
    auto declines — the safe direction: a wrongly-attempted pallas_call
    inside shard_map is a trace-time crash) but open for ``force`` — the
    user explicitly demanded kernels, and a closed answer would turn every
    forced call into a misleading 'inside a named-axis trace' error."""
    probed = False
    try:
        import jax.core as jc
        frame = getattr(jc, "thread_local_state", None)
        if frame is not None:
            env = getattr(frame.trace_state, "axis_env", None)
            probed = True
            if env:
                return True
    except Exception:  # noqa: BLE001 — version drift must not break dispatch
        pass
    try:
        from jax._src.core import get_axis_env
        env = get_axis_env()
        names = getattr(env, "axis_names", None)
        probed = True
        if callable(names):
            return bool(names())
        return bool(getattr(env, "axis_sizes", None))
    except Exception:  # noqa: BLE001
        pass
    if probed:
        return False
    return current_mode() != "force"
