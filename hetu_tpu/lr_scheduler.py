"""Learning-rate schedulers (reference ``python/hetu/lr_scheduler.py``).

Same classes and stateful ``step()/get()`` surface as the reference, plus a
``get_traced(step)`` form used inside the jitted training step so schedules
compile into the XLA program (no retrace per LR change).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp


class FixedScheduler:
    def __init__(self, learning_rate):
        assert learning_rate >= 0
        self.learning_rate = learning_rate
        self.step_count = 0

    def step(self):
        self.step_count += 1
        return self.get()

    def get(self):
        return self.learning_rate

    def get_traced(self, step):
        return jnp.asarray(self.learning_rate, jnp.float32)

    def host_token(self):
        """Host-side state baked into the traced program as a constant; the
        executor includes this in its compile-cache key so host-driven lr
        changes trigger a retrace."""
        return None


class StepScheduler(FixedScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, ending=1e-8):
        super().__init__(learning_rate)
        assert step_size > 0
        self.step_size = int(step_size)
        self.gamma = float(gamma)
        self.ending = float(ending)

    def get(self):
        lr = self.learning_rate * self.gamma ** (self.step_count // self.step_size)
        return max(lr, self.ending)

    def get_traced(self, step):
        lr = self.learning_rate * self.gamma ** jnp.floor_divide(step, self.step_size)
        return jnp.maximum(lr, self.ending).astype(jnp.float32)


class MultiStepScheduler(FixedScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1):
        super().__init__(learning_rate)
        self.milestones = sorted(int(m) for m in milestones)
        self.gamma = float(gamma)

    def get(self):
        k = sum(1 for m in self.milestones if self.step_count >= m)
        return self.learning_rate * self.gamma ** k

    def get_traced(self, step):
        ms = jnp.asarray(self.milestones, jnp.int32)
        k = jnp.sum(step >= ms)
        return (self.learning_rate * self.gamma ** k).astype(jnp.float32)


class ExponentialScheduler(FixedScheduler):
    def __init__(self, learning_rate, gamma=0.9, ending=1e-8):
        super().__init__(learning_rate)
        self.gamma = float(gamma)
        self.ending = float(ending)

    def get(self):
        return max(self.learning_rate * self.gamma ** self.step_count, self.ending)

    def get_traced(self, step):
        lr = self.learning_rate * self.gamma ** step.astype(jnp.float32)
        return jnp.maximum(lr, self.ending).astype(jnp.float32)


class CosineScheduler(FixedScheduler):
    """Cosine decay to ``ending`` over ``decay_steps`` (a TPU-build addition —
    the reference ships ReduceOnPlateau instead; both are provided)."""

    def __init__(self, learning_rate, decay_steps, ending=0.0, warmup_steps=0):
        super().__init__(learning_rate)
        self.decay_steps = int(decay_steps)
        self.ending = float(ending)
        self.warmup_steps = int(warmup_steps)

    def get(self):
        return float(self.get_traced(jnp.asarray(self.step_count)))

    def get_traced(self, step):
        step_f = step.astype(jnp.float32) if hasattr(step, "astype") else float(step)
        warm = jnp.minimum(step_f / max(self.warmup_steps, 1), 1.0) \
            if self.warmup_steps > 0 else 1.0
        frac = jnp.clip(step_f / max(self.decay_steps, 1), 0.0, 1.0)
        cos = 0.5 * (1.0 + jnp.cos(np.pi * frac))
        lr = self.ending + (self.learning_rate - self.ending) * cos
        return (warm * lr).astype(jnp.float32)


class ReduceOnPlateauScheduler(FixedScheduler):
    """Host-driven plateau scheduler (reference lr_scheduler.py:83). Being
    value-driven it cannot be traced; ``get_traced`` returns the current lr as
    a constant, so each reduction triggers one retrace — acceptable because
    reductions are rare."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, ending=1e-8):
        super().__init__(learning_rate)
        assert mode in ("min", "max")
        assert threshold_mode in ("rel", "abs")
        self.mode = mode
        self.factor = float(factor)
        self.patience = int(patience)
        self.threshold = float(threshold)
        self.threshold_mode = threshold_mode
        self.cooldown = int(cooldown)
        self.ending = float(ending)
        self.best = None
        self.num_bad = 0
        self.cooldown_count = 0
        self.cur_lr = learning_rate

    def _better(self, value):
        if self.best is None:
            return True
        if self.threshold_mode == "rel":
            delta = self.threshold * abs(self.best)
        else:
            delta = self.threshold
        return value < self.best - delta if self.mode == "min" \
            else value > self.best + delta

    def step(self, value):
        self.step_count += 1
        if self._better(value):
            self.best = value
            self.num_bad = 0
        elif self.cooldown_count > 0:
            self.cooldown_count -= 1
        else:
            self.num_bad += 1
            if self.num_bad > self.patience:
                self.cur_lr = max(self.cur_lr * self.factor, self.ending)
                self.num_bad = 0
                self.cooldown_count = self.cooldown
        return self.cur_lr

    def get(self):
        return self.cur_lr

    def get_traced(self, step):
        return jnp.asarray(self.cur_lr, jnp.float32)

    def host_token(self):
        return self.cur_lr
