// extern "C" surface of the hetu_tpu parameter server, consumed via ctypes.
//
// Capability parity with the reference's ps-lite/src/python_binding.cc
// (Init/Finalize :8-16, Push/Pull/DDPushPull :18-30, Sparse*/S*PushPull
// :32-66, PushData/PullData :72-88, Wait/WaitData/BarrierWorker :82-92,
// InitTensor :94, Clear/ClearOnServer/SaveParam/LoadParam :104-119,
// startRecord/getLoads :121-127, StartServer :129, rank/nrank :134-140).
// Arrays cross the boundary as raw pointers + lengths instead of DLArray
// structs: the TPU frontend's NDArray is a jax.Array, so the Python client
// stages through pinned numpy buffers (hetu_tpu/ps/client.py).
//
// Role selection via DMLC_ROLE / DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT /
// DMLC_NUM_WORKER / DMLC_NUM_SERVER / WORKER_ID / SERVER_ID /
// DMLC_PS_SERVER_PORT, matching the reference launcher's env plumbing
// (python/runner.py:186-190, tests/pstests/local_s2_w2.yml).

#include <cstdlib>
#include <cstring>
#include <memory>
#include <atomic>
#include <chrono>
#include <string>
#include <thread>

#include "ring.h"
#include "scheduler.h"
#include "server.h"
#include "worker.h"

namespace {

std::unique_ptr<hetups::Scheduler> g_scheduler;
std::unique_ptr<hetups::PsServer> g_server;
std::unique_ptr<hetups::RingComm> g_ring;
std::shared_ptr<hetups::Conn> g_server_sched_conn;  // server's scheduler link
std::shared_ptr<std::atomic<bool>> g_server_hb_stop;  // keepalive kill switch
std::unique_ptr<hetups::PsWorker> g_worker;
std::string g_last_error;
std::string g_loads;

const char* env_or(const char* k, const char* dflt) {
  const char* v = std::getenv(k);
  return v ? v : dflt;
}

using hetups::env_int_or;  // shared with net.h (empty value -> default)

template <typename F>
void guard(F&& f) {
  try {
    f();
  } catch (const std::exception& e) {
    g_last_error = e.what();
    std::fprintf(stderr, "[hetups] %s\n", e.what());
  }
}

hetups::PsWorker& worker() {
  if (!g_worker)
    throw std::runtime_error(
        "no worker agent: Init() not called with DMLC_ROLE=worker, or "
        "already finalized");
  return *g_worker;
}

}  // namespace

namespace hetups {
// Shared with the embedding cache (cache/cache_capi.cc).
PsWorker* global_worker() { return g_worker.get(); }
}  // namespace hetups

extern "C" {

// Returns-and-clears: the caller observes each failure once.
const char* LastError() {
  static std::string report;
  report = g_last_error;
  g_last_error.clear();
  return report.c_str();
}

void Init() {
  guard([] {
    std::string role = env_or("DMLC_ROLE", "worker");
    std::string root = env_or("DMLC_PS_ROOT_URI", "127.0.0.1");
    int root_port = env_int_or("DMLC_PS_ROOT_PORT", 13200);
    int n_workers = env_int_or("DMLC_NUM_WORKER", 1);
    int n_servers = env_int_or("DMLC_NUM_SERVER", 1);
    if (role == "scheduler") {
      if (g_scheduler) return;
      g_scheduler = std::make_unique<hetups::Scheduler>(root_port, n_servers,
                                                        n_workers);
      g_scheduler->start();
    } else if (role == "server") {
      if (g_server) return;
      int id = env_int_or("SERVER_ID", 0);
      // default 0 = OS-assigned: the server binds before anyone learns the
      // number and registers the ACTUAL port with the scheduler, so stale
      // clusters can never wedge a new launch on a port collision
      int port = env_int_or("DMLC_PS_SERVER_PORT", 0);
      std::string host = env_or("DMLC_PS_SERVER_URI", "127.0.0.1");
      g_server = std::make_unique<hetups::PsServer>(id, host, port);
      // recovery-restores-state: a replacement server rebuilds its store
      // from the last ParamSave directory BEFORE it starts serving — a
      // reconnecting worker (racing via the scheduler's address book or a
      // pinned port) must never observe the empty pre-restore store (the
      // worker does NOT re-init; see server.h load_param_file)
      const char* restore_dir = std::getenv("DMLC_PS_RESTORE_DIR");
      if (restore_dir && *restore_dir) g_server->restore_from(restore_dir);
      g_server->start();
      port = g_server->port();  // actual bound port when OS-assigned
      // register the listen address with the scheduler
      g_server_sched_conn = std::make_shared<hetups::Conn>(
          hetups::connect_to(root, root_port));
      hetups::Message reg;
      reg.head.type = static_cast<int32_t>(hetups::PsfType::kRegister);
      int32_t meta[3] = {0, id, port};
      reg.args.push_back(hetups::Arg::i32(meta, 3));
      reg.args.push_back(hetups::Arg::str(host));
      g_server_sched_conn->send(reg);
      hetups::Message book;
      if (!g_server_sched_conn->recv(&book))
        throw std::runtime_error("scheduler closed during server registration");
      // periodic keepalive so the scheduler can report this server dead to
      // workers when it stops arriving (reference van.cc:27,569). Detached,
      // with shared ownership of the conn and stop flag: a server process
      // that exits without Finalize must not std::terminate in a joinable
      // thread's destructor.
      int hb_ms = env_int_or("DMLC_PS_HEARTBEAT_MS", 1000);
      g_server_hb_stop = std::make_shared<std::atomic<bool>>(false);
      std::thread([id, hb_ms, conn = g_server_sched_conn,
                   stop = g_server_hb_stop] {
        while (!*stop) {
          std::this_thread::sleep_for(std::chrono::milliseconds(hb_ms));
          if (*stop) break;
          hetups::Message hb;
          hb.head.type = static_cast<int32_t>(hetups::PsfType::kHeartbeat);
          int32_t meta[2] = {0, id};
          hb.args.push_back(hetups::Arg::i32(meta, 2));
          try {
            conn->send(hb);
          } catch (...) {
            break;  // scheduler gone; nothing to keep alive for
          }
        }
      }).detach();
    } else {  // worker
      if (g_worker) return;
      int id = env_int_or("WORKER_ID", 0);
      g_worker = std::make_unique<hetups::PsWorker>(id, n_workers, root,
                                                    root_port);
    }
  });
}

void StartServer() { /* folded into Init() by role; kept for API parity */ }

void SchedulerWait() {
  guard([] {
    if (g_scheduler) g_scheduler->wait();
  });
}

void Finalize() {
  guard([] {
    if (g_worker) {
      g_worker->finalize();
      g_worker.reset();
    }
    if (g_server) {
      if (g_server_hb_stop) *g_server_hb_stop = true;
      if (g_server_sched_conn) {
        hetups::Message bye;
        bye.head.type = static_cast<int32_t>(hetups::PsfType::kShutdown);
        // identity-tagged checkout (scheduler wait() diagnostics)
        int32_t who[2] = {0, g_server->rank()};
        bye.args.push_back(hetups::Arg::i32(who, 2));
        try {
          g_server_sched_conn->send(bye);
        } catch (...) {
        }
        g_server_sched_conn->close();
        g_server_sched_conn.reset();
      }
      g_server->stop();
      g_server.reset();
    }
    if (g_scheduler) {
      // a timed-out SchedulerWait() already gave up (wait() returns
      // immediately then); a first-time timeout here must still tear down
      try {
        g_scheduler->wait();
      } catch (const std::exception& e) {
        g_last_error = e.what();
        std::fprintf(stderr, "[hetups] %s\n", e.what());
      }
      g_scheduler->stop();
      g_scheduler.reset();
    }
  });
}

// -- dense ------------------------------------------------------------------
void Push(int node, const float* grad, long len) {
  guard([&] { worker().push(node, grad, static_cast<size_t>(len)); });
}

// Per-step optimizer overrides for subsequent pushes of `node`:
// lr(step) schedule value, l2 regularization, decoupled weight decay.
// lr < 0 with l2reg == wd == 0 clears the override.
void SetPushOpts(int node, float lr, float l2reg, float weight_decay) {
  guard([&] { worker().set_push_opts(node, lr, l2reg, weight_decay); });
}

void Pull(int node, float* out, long len) {
  guard([&] { worker().pull(node, out, static_cast<size_t>(len)); });
}

void DDPushPull(int node, const float* grad, float* out, long len) {
  guard([&] { worker().dd_pushpull(node, grad, out, static_cast<size_t>(len)); });
}

// -- sparse -----------------------------------------------------------------
void SparsePush(int node, const long* idx, const float* vals, long nidx) {
  guard([&] {
    worker().sparse_push(node, reinterpret_cast<const int64_t*>(idx), vals,
                          static_cast<size_t>(nidx));
  });
}

void SparsePull(int node, const long* idx, float* vals, long nidx) {
  guard([&] {
    worker().sparse_pull(node, reinterpret_cast<const int64_t*>(idx), vals,
                          static_cast<size_t>(nidx));
  });
}

void SDPushPull(int node, const long* idx, const float* vals, long nidx,
                float* out) {
  guard([&] {
    worker().sd_pushpull(node, reinterpret_cast<const int64_t*>(idx), vals,
                          static_cast<size_t>(nidx), out);
  });
}

void SSPushPull(int node, const long* in_idx, const float* vals,
                const long* out_idx, float* out, long nidx) {
  guard([&] {
    worker().ss_pushpull(node, reinterpret_cast<const int64_t*>(in_idx), vals,
                          reinterpret_cast<const int64_t*>(out_idx), out,
                          static_cast<size_t>(nidx));
  });
}

void AssignDense(int node, const float* data, long len) {
  guard([&] { worker().assign_dense(node, data, static_cast<size_t>(len)); });
}

void AssignRows(int node, const long* idx, const float* vals, long nidx) {
  guard([&] {
    worker().assign_rows(node, reinterpret_cast<const int64_t*>(idx), vals,
                         static_cast<size_t>(nidx));
  });
}

// -- data blobs -------------------------------------------------------------
long PushData(int node, const unsigned long long* ids, int n,
              const float* vals, const long* lens) {
  long q = -1;
  guard([&] {
    q = worker().push_data(node, reinterpret_cast<const uint64_t*>(ids),
                            static_cast<size_t>(n), vals,
                            reinterpret_cast<const int64_t*>(lens));
  });
  return q;
}

long PullData(int node, const unsigned long long* ids, int n, float* vals,
              const long* lens) {
  long q = -1;
  guard([&] {
    q = worker().pull_data(node, reinterpret_cast<const uint64_t*>(ids),
                            static_cast<size_t>(n), vals,
                            reinterpret_cast<const int64_t*>(lens));
  });
  return q;
}

void WaitData(long query) {
  guard([&] { worker().wait_data(query); });
}

// -- control ----------------------------------------------------------------
void Wait(int node) {
  guard([&] { worker().wait(node); });
}

void BarrierWorker() {
  guard([] { worker().barrier(); });
}

void InitTensor(int node, int ptype, long len, long width, int init_type,
                double init_a, double init_b, unsigned long long seed,
                int otype, float* lrs, int nlr) {
  guard([&] {
    worker().parameter_init(
        node, static_cast<hetups::ParamKind>(ptype), static_cast<size_t>(len),
        static_cast<size_t>(width), static_cast<hetups::InitType>(init_type),
        init_a, init_b, seed, static_cast<hetups::OptType>(otype), lrs,
        static_cast<size_t>(nlr));
  });
}

void Clear(int node) {
  guard([&] { worker().clear(node); });
}

void ClearOnServer(int node) {
  guard([&] { worker().clear_on_server(node); });
}

void SaveParam(int node, const char* dir) {
  guard([&] { worker().parameter_save(node, dir); });
}

void LoadParam(int node, const char* dir) {
  guard([&] { worker().parameter_load(node, dir); });
}

void startRecord(const char* dir) {
  guard([&] { worker().start_record(dir); });
}

const char* getLoads() {
  guard([] { g_loads = worker().get_loads(); });
  return g_loads.c_str();
}

// Per-server HA + health counters: fills up to n of [updates,
// snapshot_updates, restored_updates (-1 = fresh), snapshot_version,
// n_params, requests, apply_ns, apply_count, snapshot_age_ms (-1 = none),
// dedup_clients, crc_rejects] (server.h kServerStats).
void QueryServerStats(int server, long long* out, int n) {
  guard([&] {
    auto v = worker().server_stats(static_cast<size_t>(server));
    for (int i = 0; i < n && i < static_cast<int>(v.size()); ++i)
      out[i] = static_cast<long long>(v[i]);
  });
}

// hetusave (docs/FAULT_TOLERANCE.md "Coordinated job snapshots"): drive one
// server's epoch-stamped snapshot NOW; fills out with up to n of
// [snapshot_version, covered_update_counter, update_count, epoch].
// Synchronous — returns only after the snapshot is on disk and its LATEST
// pointer flipped. A production checkpoint primitive: NOT test-gated.
void ServerSnapshotNow(int server, long long epoch, long long* out, int n) {
  guard([&] {
    auto v = worker().snapshot_now(static_cast<size_t>(server),
                                   static_cast<int64_t>(epoch));
    for (int i = 0; i < n && i < static_cast<int>(v.size()); ++i)
      out[i] = static_cast<long long>(v[i]);
  });
}

// -- hetu-elastic membership (docs/FAULT_TOLERANCE.md) ----------------------

// Stamp this worker's committed membership epoch onto every subsequent
// request (servers armed via kSetWorldVersion reject mismatches).
void SetWorldVersion(unsigned long long v) {
  guard([&] { worker().set_world_version(static_cast<uint64_t>(v)); });
}

unsigned long long GetWorldVersion() {
  return g_worker ? worker().world_version() : 0ull;
}

// Re-sync the server connection set + partitioner denominator with the
// scheduler's address book after a committed resize (caller must have
// drained all in-flight traffic). Returns the new server count, -1 on
// error (stashed in LastError).
int RefreshServers() {
  int n = -1;
  guard([&] { n = static_cast<int>(worker().refresh_servers()); });
  return n;
}

// hetuq: toggle quantized value payloads (ArgType::kQI8) for this worker's
// push/pull traffic. mode != 0 enables; the env default is HETU_COMM_QUANT.
void SetCommQuant(int mode) {
  guard([&] { worker().set_quant(mode != 0); });
}

// -- hetuchaos (docs/FAULT_TOLERANCE.md "Chaos testing") --------------------

// CRC32C payload checksums on this worker's PS traffic (default ON; the
// env default is HETU_PS_CRC at Init — 0 disables). The server side needs
// no knob: it verifies and checksums per request via the kFlagCrc
// negotiation, so a live A/B toggles both legs from the client alone.
void SetPsCrc(int on) {
  guard([&] { worker().set_crc(on != 0); });
}

// Arm a seeded chaos schedule on this worker's transport (empty/NULL spec
// disarms). Destructive by design, so arming requires HETU_TEST_MODE —
// the HETU_CHAOS_SPEC env arming in the worker ctor is gated the same way.
// Grammar: csrc/ps/chaos.h / hetu_tpu.chaos.parse_spec.
void SetChaos(const char* spec) {
  guard([&] {
    const std::string s = spec ? spec : "";
    if (!s.empty() && !hetups::env_test_mode())
      throw std::runtime_error("SetChaos requires HETU_TEST_MODE");
    worker().set_chaos(s);
  });
}

// Drain up to max_rows injected-fault events (oldest first) into out as
// 6-wide i64 rows: [kind, server, psf, tensor, seq, arg] — kind ids in
// csrc/ps/chaos.h (mirrored by hetu_tpu.chaos.KIND_NAMES). Deterministic
// given the spec's seed and the workload: the SORTED log of a replay is
// identical. Returns the row count (0 when chaos was never armed).
long DrainChaosEvents(long long* out, int max_rows) {
  long n = 0;
  guard([&] {
    n = static_cast<long>(worker().drain_chaos(
        reinterpret_cast<int64_t*>(out),
        max_rows > 0 ? static_cast<size_t>(max_rows) : 0));
  });
  return n;
}

// hetuq test hook (inert without HETU_TEST_MODE): corrupt the scale bytes
// of the next quantized payload (node < 0 = any tensor) to prove the
// server's validation rejects malformed quantized args.
void TestCorruptNextQuant(int node) {
  guard([&] {
    if (!hetups::env_test_mode())
      throw std::runtime_error(
          "TestCorruptNextQuant requires HETU_TEST_MODE");
    worker().arm_quant_corrupt(node);
  });
}

// -- hetutrail (docs/OBSERVABILITY.md pillar 5) -----------------------------

// Stamp the worker's current training step onto subsequent client RPC spans
// (the span context riding the wire stays the existing client_id/req_id).
void SetTrailStep(long long step) {
  guard([&] { worker().set_trail_step(static_cast<int64_t>(step)); });
}

// Arm/disarm the client span ring at runtime (the env default is
// HETU_TRAIL_DIR at Init; an A/B of two executors on one live worker needs
// the explicit toggle, like SetCommQuant). Disarming clears the ring.
void SetTrail(int on) {
  guard([&] { worker().set_trail(on != 0); });
}

// Drain up to max_rows client spans (oldest first) into out as 10-wide i64
// rows: [req_id, client_id, server, psf, tensor, step, t0_us, dur_us,
// req_bytes, rsp_bytes]. t0_us is CLOCK_MONOTONIC µs (net.h trail_mono_us),
// directly comparable with server-side spans on the same host. Returns the
// row count (0 when the ring is empty or trail is off).
long DrainTrailSpans(long long* out, int max_rows) {
  long n = 0;
  guard([&] {
    n = static_cast<long>(worker().drain_trail(
        reinterpret_cast<int64_t*>(out),
        max_rows > 0 ? static_cast<size_t>(max_rows) : 0));
  });
  return n;
}

// Spans dropped because the bounded ring was full (monotonic counter).
long long TrailDropped() {
  return g_worker ? static_cast<long long>(worker().trail_dropped()) : 0;
}

// hetutrail test lever (inert without HETU_TEST_MODE): delay server
// `server`'s NEXT optimizer apply by `ms` — the deterministic slow leg the
// critical-path and straggler tests attribute.
void TestSlowApply(int server, int ms) {
  guard([&] {
    if (!hetups::env_test_mode())
      throw std::runtime_error("TestSlowApply requires HETU_TEST_MODE");
    worker().test_slow_apply(static_cast<size_t>(server), ms);
  });
}

// Worker-side RPC counters: fills up to n of [rpcs, retries, failovers,
// quant raw value bytes, quant wire value bytes, rpc timeouts, backoff ms
// slept, crc rejects observed, chaos faults injected, write RPCs landed]
// (worker.h client_stats — the telemetry twin of QueryServerStats).
void QueryClientStats(long long* out, int n) {
  guard([&] {
    auto v = worker().client_stats();
    for (int i = 0; i < n && i < static_cast<int>(v.size()); ++i)
      out[i] = static_cast<long long>(v[i]);
  });
}

int rank() { return g_worker ? worker().rank() : 0; }
int nrank() { return g_worker ? worker().nrank() : 1; }
int num_servers() {
  return g_worker ? static_cast<int>(worker().num_servers()) : 0;
}

// -- ring collectives (reference c_communication_nthread.cc legacy path) ----

void RingInit(int rank, int nranks, const char* host, int base_port) {
  guard([&] {
    g_ring = std::make_unique<hetups::RingComm>(rank, nranks, host,
                                                base_port);
  });
}

void RingAllReduce(float* data, long n) {
  guard([&] {
    if (!g_ring) throw std::runtime_error("RingInit not called");
    g_ring->allreduce_sum(data, static_cast<size_t>(n));
  });
}

void RingAllGather(const float* in, float* out, long n_per) {
  guard([&] {
    if (!g_ring) throw std::runtime_error("RingInit not called");
    g_ring->allgather(in, out, static_cast<size_t>(n_per));
  });
}

void RingBarrier() {
  guard([&] {
    if (!g_ring) throw std::runtime_error("RingInit not called");
    g_ring->barrier();
  });
}

void RingFinalize() {
  guard([] { g_ring.reset(); });
}

}  // extern "C"
