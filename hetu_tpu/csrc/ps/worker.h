// Worker-side PS agent: key-range partitioning, async push/pull on a thread
// pool, per-tensor completion tracking.
//
// Capability parity with the reference's PSAgent/Worker
// (ps-lite/include/ps/worker/PSAgent.h: registerTensor key-range partitioning
// :104-122, dedup-by-key sparse push/pull :124-160; src/worker.cc: thread-pool
// push :27-36, rank-0 parameter_init + barrier :6-17) and the partitioner
// (include/ps/partitioner.h: dense average split, sparse row-wise split).
#pragma once

#include <algorithm>
#include <array>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <sstream>
#include <thread>
#include <unordered_map>
#include <vector>

#include "chaos.h"
#include "net.h"
#include "store.h"

namespace hetups {

class ThreadPool {
 public:
  explicit ThreadPool(size_t n) {
    for (size_t i = 0; i < n; ++i)
      threads_.emplace_back([this] { loop(); });
  }
  ~ThreadPool() { shutdown(); }

  void submit(std::function<void()> f) {
    {
      std::lock_guard<std::mutex> g(mu_);
      q_.push_back(std::move(f));
    }
    cv_.notify_one();
  }

  void shutdown() {
    {
      std::lock_guard<std::mutex> g(mu_);
      if (stop_) return;
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_)
      if (t.joinable()) t.join();
  }

 private:
  void loop() {
    for (;;) {
      std::function<void()> f;
      {
        std::unique_lock<std::mutex> g(mu_);
        cv_.wait(g, [this] { return stop_ || !q_.empty(); });
        if (q_.empty()) {
          if (stop_) return;
          continue;
        }
        f = std::move(q_.front());
        q_.pop_front();
      }
      f();
    }
  }

  std::vector<std::thread> threads_;
  std::deque<std::function<void()>> q_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_ = false;
};

// Tracks outstanding async operations per tensor id (reference Worker::wait
// per node_name) and per data-query id (wait_data).
class PendingTracker {
 public:
  void add(int32_t key, int n = 1) {
    std::lock_guard<std::mutex> g(mu_);
    pending_[key] += n;
  }
  void done(int32_t key) {
    std::lock_guard<std::mutex> g(mu_);
    if (--pending_[key] <= 0) cv_.notify_all();
  }
  void wait(int32_t key) {
    std::unique_lock<std::mutex> g(mu_);
    cv_.wait(g, [&] { return pending_[key] <= 0; });
    // surface async worker errors at the Wait() call site
    auto it = errors_.find(key);
    if (it != errors_.end()) {
      std::string e = it->second;
      errors_.erase(it);
      throw std::runtime_error(e);
    }
  }
  void fail(int32_t key, const std::string& what) {
    std::lock_guard<std::mutex> g(mu_);
    errors_[key] = what;
    if (--pending_[key] <= 0) cv_.notify_all();
  }

 private:
  std::mutex mu_;
  std::condition_variable cv_;
  std::unordered_map<int32_t, int> pending_;
  std::unordered_map<int32_t, std::string> errors_;
};

struct TensorMeta {
  ParamKind kind = ParamKind::kDense;
  size_t len = 0;    // dense total length
  size_t rows = 0;   // sparse rows
  size_t width = 0;  // sparse width
};

class PsWorker {
 public:
  PsWorker(int rank, int num_workers, const std::string& sched_host,
           int sched_port, int n_threads = 4)
      : rank_(rank), num_workers_(num_workers), sched_host_(sched_host),
        sched_port_(sched_port), pool_(n_threads) {
    recv_timeout_ms_ = env_int_or("DMLC_PS_RECV_TIMEOUT_MS", 15000);
    max_retry_ = env_int_or("DMLC_PS_MAX_RETRY", 3);
    // hetutrail: client-side RPC spans into a bounded ring, drained by the
    // Python runtime (DrainTrailSpans) into trail-client-r<rank>.jsonl.
    // Armed by HETU_TRAIL_DIR like the server side; when off the rpc path
    // pays one relaxed atomic load and nothing else.
    if (const char* td = std::getenv("HETU_TRAIL_DIR"))
      trail_on_.store(td[0] != '\0');
    trail_cap_ = static_cast<size_t>(
        env_int_or("HETU_TRAIL_RING", 65536));
    // hetuq: quantize push/pull value payloads (ArgType::kQI8 — row-wise
    // int8 for sparse, kQuantWireBlock blocks for dense). Env default so a
    // bare PSClient inherits the run's knob; SetCommQuant overrides.
    if (const char* q = std::getenv("HETU_COMM_QUANT"))
      quant_ = (std::string(q) == "int8" || std::string(q) == "fp8" ||
                std::string(q) == "1");
    // opt-in failover: after the fast retries exhaust, block-with-deadline
    // for a replacement server to register instead of throwing (0 = off)
    failover_ms_ = env_int_or("DMLC_PS_FAILOVER_DEADLINE_MS", 0);
    failover_poll_ms_ = env_int_or("DMLC_PS_FAILOVER_POLL_MS", 500);
    // hetuchaos transport hardening (docs/FAULT_TOLERANCE.md): retries
    // back off exponentially with deterministic jitter instead of
    // hammering a struggling server in a tight loop, and an optional
    // per-RPC wall deadline bounds the whole retry phase (0 = the retry
    // count alone bounds it, the pre-chaos semantics).
    backoff_base_ms_ = env_int_or("DMLC_PS_BACKOFF_BASE_MS", 10);
    backoff_cap_ms_ = env_int_or("DMLC_PS_BACKOFF_CAP_MS", 2000);
    rpc_timeout_ms_ = env_int_or("DMLC_PS_RPC_TIMEOUT_MS", 0);
    // CRC32C end-to-end payload checksums, default ON (HETU_PS_CRC=0 opts
    // out): requests checksum their args and ask the server (kFlagCrc) to
    // reject mismatches before any apply and to checksum its response.
    {
      const char* c = std::getenv("HETU_PS_CRC");
      crc_on_.store(!(c && *c == '0'));
    }
    // chaos engine env arming (SetChaos is the runtime path). Doubly
    // gated: a leaked HETU_CHAOS_SPEC is inert without HETU_TEST_MODE.
    if (const char* cs = std::getenv("HETU_CHAOS_SPEC"))
      if (*cs && env_test_mode()) set_chaos(cs);
    sched_ = std::make_unique<Conn>(connect_to(sched_host, sched_port));
    // register with the scheduler, receive the server address book
    Message reg;
    reg.head.type = static_cast<int32_t>(PsfType::kRegister);
    int32_t meta[3] = {1, rank, 0};
    reg.args.push_back(Arg::i32(meta, 3));
    reg.args.push_back(Arg::str("127.0.0.1"));
    sched_->send(reg);
    Message book;
    if (!sched_->recv(&book))
      throw std::runtime_error("scheduler closed during registration");
    if (book.args.size() > 1 && book.args[1].as_i32()[0] > 0) {
      // scheduler-issued incarnation epoch in the high bits: strictly
      // increasing per rank across worker restarts regardless of clock
      // steps, and (epoch >= 1) always above the pure-wall-clock ids a
      // pre-epoch snapshot's ledger may hold (wall-µs stays < 2^51
      // until ~2041)
      next_req_id_ = boot_req_id() +
                     (static_cast<uint64_t>(book.args[1].as_i32()[0]) << 51);
    }
    std::istringstream ss(book.args[0].as_str());
    std::string line;
    while (std::getline(ss, line)) {
      if (line.empty()) continue;
      server_addrs_.push_back(line);
      // TWO connections per server — a BULK channel for gradient-payload
      // messages and a FAST channel for pulls/control — so a small pull is
      // never head-of-line-blocked behind a megabyte push on the same
      // socket. TPU-native equivalent of the reference's priority p3 van
      // (ps-lite/src/p3_van.h:1-71, selected at van.cc:29-42): instead of
      // slicing big messages into priority-scheduled chunks, the two
      // classes ride separate TCP streams served by separate server
      // threads (per-param shared_mutex still orders conflicting applies).
      servers_.push_back(std::make_unique<Conn>(connect_addr(line)));
      servers_fast_.push_back(std::make_unique<Conn>(connect_addr(line)));
    }
    if (servers_.empty()) throw std::runtime_error("no servers in address book");
  }

  ~PsWorker() { finalize(); }

  void finalize() {
    if (finalized_) return;
    finalized_ = true;
    pool_.shutdown();
    Message bye;
    bye.head.type = static_cast<int32_t>(PsfType::kShutdown);
    for (auto* chan : {&servers_, &servers_fast_}) {
      for (auto& s : *chan) {
        try {
          s->send(bye);
        } catch (...) {
        }
        s->close();
      }
    }
    // identity-tagged checkout: the scheduler's bounded teardown wait can
    // then name the ranks that never made it here
    int32_t who[2] = {1, rank_};
    bye.args.push_back(Arg::i32(who, 2));
    try {
      sched_->send(bye);
    } catch (...) {
    }
    sched_->close();
  }

  int rank() const { return rank_; }
  int nrank() const { return num_workers_; }
  size_t num_servers() const { return servers_.size(); }

  // -- partitioner (reference partitioner.h:18-24) -----------------------
  // dense: average split of [0, len); sparse: row-wise average split.
  std::pair<size_t, size_t> dense_range(size_t len, size_t s) const {
    size_t S = servers_.size();
    return {s * len / S, (s + 1) * len / S};
  }
  std::pair<size_t, size_t> row_range(size_t rows, size_t s) const {
    size_t S = servers_.size();
    return {s * rows / S, (s + 1) * rows / S};
  }
  size_t row_owner(size_t rows, size_t r) const {
    size_t S = servers_.size();
    // inverse of row_range: smallest s with (s+1)*rows/S > r
    size_t s = (r * S) / rows;
    while ((s + 1) * rows / S <= r) ++s;
    while (s > 0 && s * rows / S > r) --s;
    return s;
  }

  // -- tensor registration / init (reference worker.cc:6-17) -------------
  void parameter_init(int32_t key, ParamKind kind, size_t len, size_t width,
                      InitType itype, double a, double b, uint64_t seed,
                      OptType otype, const float* lrs, size_t n_lr) {
    {
      std::lock_guard<std::mutex> g(meta_mu_);
      TensorMeta& m = metas_[key];
      m.kind = kind;
      if (kind == ParamKind::kDense) {
        m.len = len;
        m.width = 1;
      } else {
        m.rows = len;
        m.width = width;
        m.len = len * width;
      }
    }
    // synchronous init on every server shard (idempotent server-side, so no
    // rank-0-only dance is needed; the reference barriers instead)
    for (size_t s = 0; s < servers_.size(); ++s) {
      size_t shard = (kind == ParamKind::kDense)
                         ? dense_range(len, s).second - dense_range(len, s).first
                         : row_range(len, s).second - row_range(len, s).first;
      Message req;
      req.head.type = static_cast<int32_t>(PsfType::kParamInit);
      req.head.tensor_id = key;
      int64_t meta[6] = {static_cast<int64_t>(kind),
                         static_cast<int64_t>(shard),
                         static_cast<int64_t>(width),
                         static_cast<int64_t>(itype),
                         static_cast<int64_t>(otype),
                         static_cast<int64_t>(n_lr)};
      double ab[2] = {a, b};
      uint64_t sd = seed + s * 131071u;
      req.args.push_back(Arg::i64(meta, 6));
      req.args.push_back(Arg::f64(ab, 2));
      req.args.push_back(Arg::u64(&sd, 1));
      req.args.push_back(Arg::f32(lrs, n_lr));
      rpc(s, req);
    }
  }

  // -- per-step optimizer overrides --------------------------------------
  // [lr, l2reg, weight_decay] attached as a trailing f32 arg to this
  // tensor's subsequent push RPCs (server parse_opts -> store.h UpdateOpts).
  // How lr schedules + regularization reach stateful SERVER-side optimizers:
  // the worker refreshes lr(step) before each step's pushes. lr < 0 with
  // zero l2/wd clears the override.
  void set_push_opts(int32_t key, float lr, float l2reg, float wd) {
    std::lock_guard<std::mutex> g(opts_mu_);
    if (lr < 0.0f && l2reg == 0.0f && wd == 0.0f)
      push_opts_.erase(key);
    else
      push_opts_[key] = {lr, l2reg, wd};
  }

  bool get_push_opts(int32_t key, std::array<float, 3>* out) {
    std::lock_guard<std::mutex> g(opts_mu_);
    auto it = push_opts_.find(key);
    if (it == push_opts_.end()) return false;
    *out = it->second;
    return true;
  }

  // -- hetuq quantized wire (docs/COMM_QUANT.md) --------------------------
  void set_quant(bool on) { quant_.store(on); }
  bool quant_enabled() const { return quant_.load(); }

  // -- hetu-elastic membership (docs/FAULT_TOLERANCE.md) ------------------
  void set_world_version(uint64_t v) { world_version_.store(v); }
  uint64_t world_version() const { return world_version_.load(); }

  // -- hetuchaos (docs/FAULT_TOLERANCE.md "Chaos testing") ----------------
  // Arm a seeded fault schedule ("" disarms). Gating on HETU_TEST_MODE
  // lives in capi.cc / the env-arming ctor path; this setter is the
  // mechanism. Retired engines are kept until finalize so a concurrent
  // RPC that loaded the old pointer never dereferences freed memory.
  void set_chaos(const std::string& spec) {
    if (spec.empty()) {
      chaos_.store(nullptr, std::memory_order_release);
      return;
    }
    auto eng = ChaosEngine::parse(spec);
    ChaosEngine* raw = eng.get();
    {
      std::lock_guard<std::mutex> g(chaos_mu_);
      chaos_owned_.push_back(std::move(eng));
    }
    chaos_.store(raw, std::memory_order_release);
  }

  // Drain injected-fault events (6-wide i64 rows, oldest first) across
  // EVERY engine armed this session, in arming order — a test that
  // re-arms per phase (or disarms before reading) still gets the full
  // log. Returns 0 when no engine was ever armed.
  size_t drain_chaos(int64_t* out, size_t max_rows) {
    std::lock_guard<std::mutex> g(chaos_mu_);
    size_t n = 0;
    for (auto& eng : chaos_owned_) {
      if (n >= max_rows) break;
      n += eng->drain(out + n * ChaosEngine::kEventCols, max_rows - n);
    }
    return n;
  }

  uint64_t chaos_faults() const {
    // injected-fault total across every engine armed this session (0
    // with none armed): reading through chaos_ alone would go blind the
    // moment a test disarms or re-arms
    std::lock_guard<std::mutex> g(chaos_mu_);
    uint64_t n = 0;
    for (const auto& eng : chaos_owned_) n += eng->fault_count();
    return n;
  }

  // CRC32C payload checksums on/off for this worker's traffic (the env
  // default is HETU_PS_CRC at Init; the bench A/B toggles it live).
  void set_crc(bool on) { crc_on_.store(on); }
  bool crc_enabled() const { return crc_on_.load(); }

  // Re-sync the server set with the scheduler's address book after a
  // committed resize: joined servers get fresh bulk+fast connections and
  // the partitioner denominator (servers_.size()) grows to match.
  // PRECONDITION: the caller drained — no RPCs in flight on any channel
  // (the ElasticAgent calls this between kCommitResize returning and the
  // first post-resize push). Relocated servers reconnect lazily via the
  // existing retry path, so only NEW entries connect here.
  size_t refresh_servers() {
    Conn c(connect_to(sched_host_, sched_port_, /*retries=*/50,
                      /*wait_ms=*/100));
    set_recv_timeout(c.fd(), recv_timeout_ms_);
    Message q;
    q.head.type = static_cast<int32_t>(PsfType::kQueryServers);
    c.send(q);
    Message rsp;
    if (!c.recv(&rsp) || rsp.args.empty())
      throw std::runtime_error(
          "refresh_servers: scheduler at " + sched_host_ + ":" +
          std::to_string(sched_port_) + " returned no address book");
    std::vector<std::string> addrs;
    std::istringstream ss(rsp.args[0].as_str());
    std::string line;
    while (std::getline(ss, line))
      if (!line.empty()) addrs.push_back(line);
    if (addrs.size() > kMaxServers)
      throw std::runtime_error(
          "refresh_servers: " + std::to_string(addrs.size()) +
          " servers exceed the per-worker connection table (" +
          std::to_string(kMaxServers) + ")");
    std::lock_guard<std::mutex> g(addr_mu_);
    if (addrs.size() < server_addrs_.size())
      throw std::runtime_error(
          "refresh_servers: the address book shrank (" +
          std::to_string(addrs.size()) + " < " +
          std::to_string(server_addrs_.size()) +
          ") — server scale-down is not supported");
    for (size_t i = 0; i < addrs.size(); ++i) {
      if (i < server_addrs_.size()) {
        server_addrs_[i] = addrs[i];  // relocations reconnect on retry
      } else {
        server_addrs_.push_back(addrs[i]);
        servers_.push_back(std::make_unique<Conn>(connect_addr(addrs[i])));
        servers_fast_.push_back(
            std::make_unique<Conn>(connect_addr(addrs[i])));
      }
    }
    return servers_.size();
  }

  // test hook (capi gates it on HETU_TEST_MODE): corrupt the scale bytes of
  // the NEXT quantized value payload (optionally only for `tensor`), to
  // prove the server's length/scale validation rejects the message instead
  // of applying garbage. One-shot.
  void arm_quant_corrupt(int32_t tensor) {
    corrupt_tensor_.store(tensor);
    corrupt_armed_.store(true);
  }

  const TensorMeta& meta(int32_t key) {
    std::lock_guard<std::mutex> g(meta_mu_);
    auto it = metas_.find(key);
    if (it == metas_.end())
      throw std::runtime_error("tensor " + std::to_string(key) +
                               " not registered (InitTensor first)");
    return it->second;
  }

  // -- dense ops ---------------------------------------------------------
  // Async: returns immediately; caller's buffers must stay alive until
  // wait(key) (same contract as the reference's Push/Pull + Wait).
  void check_len(const TensorMeta& m, int32_t key, size_t len) const {
    if (len != m.len)
      throw std::runtime_error(
          "tensor " + std::to_string(key) + ": buffer has " +
          std::to_string(len) + " f32s but " + std::to_string(m.len) +
          " were registered via InitTensor");
  }

  void push(int32_t key, const float* grad, size_t len) {
    auto m = meta(key);
    check_len(m, key, len);
    std::array<float, 3> uo;
    const bool has_uo = get_push_opts(key, &uo);  // snapshot in caller thread
    pending_.add(key, static_cast<int>(servers_.size()));
    for (size_t s = 0; s < servers_.size(); ++s) {
      auto [lo, hi] = dense_range(m.len, s);
      pool_.submit([=] {
        guarded(key, [&] {
          Message req;
          req.head.type = static_cast<int32_t>(PsfType::kDensePush);
          req.head.tensor_id = key;
          req.args.push_back(value_arg(key, grad + lo, hi - lo,
                                       kQuantWireBlock));
          if (has_uo) req.args.push_back(Arg::f32(uo.data(), 3));
          rpc(s, req);
          record("push", (hi - lo) * 4);
        });
      });
    }
  }

  void pull(int32_t key, float* out, size_t len) {
    auto m = meta(key);
    check_len(m, key, len);
    pending_.add(key, static_cast<int>(servers_.size()));
    for (size_t s = 0; s < servers_.size(); ++s) {
      auto [lo, hi] = dense_range(m.len, s);
      pool_.submit([=] {
        guarded(key, [&] {
          Message req;
          req.head.type = static_cast<int32_t>(PsfType::kDensePull);
          req.head.tensor_id = key;
          Message rsp = rpc(s, req);
          std::memcpy(out + lo, rsp.args[0].as_f32(), (hi - lo) * 4);
          record("pull", (hi - lo) * 4);
        });
      });
    }
  }

  void dd_pushpull(int32_t key, const float* grad, float* out, size_t len) {
    auto m = meta(key);
    check_len(m, key, len);
    std::array<float, 3> uo;
    const bool has_uo = get_push_opts(key, &uo);
    pending_.add(key, static_cast<int>(servers_.size()));
    for (size_t s = 0; s < servers_.size(); ++s) {
      auto [lo, hi] = dense_range(m.len, s);
      pool_.submit([=] {
        guarded(key, [&] {
          Message req;
          req.head.type = static_cast<int32_t>(PsfType::kDDPushPull);
          req.head.tensor_id = key;
          mark_quant_rsp(&req);
          req.args.push_back(value_arg(key, grad + lo, hi - lo,
                                       kQuantWireBlock));
          if (has_uo) req.args.push_back(Arg::f32(uo.data(), 3));
          Message rsp = rpc(s, req);
          std::vector<float> scratch;
          std::memcpy(out + lo, rsp_view(rsp.args[0], &scratch),
                      (hi - lo) * 4);
          record("ddpushpull", (hi - lo) * 8);
        });
      });
    }
  }

  // -- sparse ops --------------------------------------------------------
  // Dedup-by-key then split per server (reference PSAgent.h:124-160).
  struct ShardedKeys {
    std::vector<std::vector<int64_t>> local;     // per-server local row ids
    std::vector<std::vector<size_t>> positions;  // per-server original slots
  };

  ShardedKeys shard_rows(const TensorMeta& m, const int64_t* keys, size_t n,
                         std::vector<int64_t>* uniq_out = nullptr,
                         std::vector<size_t>* inv_out = nullptr) {
    // dedup: uniq keys + inverse map original position -> uniq slot
    std::unordered_map<int64_t, size_t> first;
    std::vector<int64_t> uniq;
    std::vector<size_t> inv(n);
    for (size_t i = 0; i < n; ++i) {
      auto it = first.find(keys[i]);
      if (it == first.end()) {
        first[keys[i]] = uniq.size();
        inv[i] = uniq.size();
        uniq.push_back(keys[i]);
      } else {
        inv[i] = it->second;
      }
    }
    ShardedKeys sk;
    sk.local.resize(servers_.size());
    sk.positions.resize(servers_.size());
    for (size_t u = 0; u < uniq.size(); ++u) {
      // ids come straight from user data; an out-of-range id would index
      // past sk.local below (row_owner returns an invalid server slot)
      if (uniq[u] < 0 || static_cast<size_t>(uniq[u]) >= m.rows)
        throw std::runtime_error(
            "row id " + std::to_string(uniq[u]) + " out of range [0, " +
            std::to_string(m.rows) + ")");
      size_t s = row_owner(m.rows, static_cast<size_t>(uniq[u]));
      sk.local[s].push_back(uniq[u] -
                            static_cast<int64_t>(row_range(m.rows, s).first));
      sk.positions[s].push_back(u);
    }
    if (uniq_out) *uniq_out = std::move(uniq);
    if (inv_out) *inv_out = std::move(inv);
    return sk;
  }

  void sparse_push(int32_t key, const int64_t* keys, const float* vals,
                   size_t n) {
    auto m = meta(key);
    // dedup with accumulation: duplicate rows in one push sum their grads
    std::vector<int64_t> uniq;
    std::vector<size_t> inv;
    auto sk = shard_rows(m, keys, n, &uniq, &inv);
    auto acc = std::make_shared<std::vector<float>>(uniq.size() * m.width, 0.0f);
    for (size_t i = 0; i < n; ++i) {
      float* dst = acc->data() + inv[i] * m.width;
      const float* src = vals + i * m.width;
      for (size_t j = 0; j < m.width; ++j) dst[j] += src[j];
    }
    std::array<float, 3> uo;
    const bool has_uo = get_push_opts(key, &uo);
    pending_.add(key, static_cast<int>(servers_.size()));
    auto sk_p = std::make_shared<ShardedKeys>(std::move(sk));
    for (size_t s = 0; s < servers_.size(); ++s) {
      pool_.submit([=] {
        guarded(key, [&] {
          const auto& loc = sk_p->local[s];
          if (loc.empty()) return;
          std::vector<float> shard_vals(loc.size() * m.width);
          for (size_t i = 0; i < loc.size(); ++i)
            std::memcpy(shard_vals.data() + i * m.width,
                        acc->data() + sk_p->positions[s][i] * m.width,
                        m.width * 4);
          Message req;
          req.head.type = static_cast<int32_t>(PsfType::kSparsePush);
          req.head.tensor_id = key;
          req.args.push_back(Arg::i64(loc.data(), loc.size()));
          req.args.push_back(value_arg(key, shard_vals.data(),
                                       shard_vals.size(), m.width));
          if (has_uo) req.args.push_back(Arg::f32(uo.data(), 3));
          rpc(s, req);
          record("sparse_push", shard_vals.size() * 4);
        });
      });
    }
  }

  void sparse_pull(int32_t key, const int64_t* keys, float* out, size_t n) {
    auto m = meta(key);
    std::vector<int64_t> uniq;
    auto inv = std::make_shared<std::vector<size_t>>();
    auto sk = shard_rows(m, keys, n, &uniq, inv.get());
    auto uniq_vals = std::make_shared<std::vector<float>>(uniq.size() * m.width);
    auto sk_p = std::make_shared<ShardedKeys>(std::move(sk));
    auto remain = std::make_shared<std::atomic<int>>(
        static_cast<int>(servers_.size()));
    pending_.add(key, static_cast<int>(servers_.size()));
    for (size_t s = 0; s < servers_.size(); ++s) {
      pool_.submit([=] {
        guarded(key, [&] {
          const auto& loc = sk_p->local[s];
          if (!loc.empty()) {
            Message req;
            req.head.type = static_cast<int32_t>(PsfType::kSparsePull);
            req.head.tensor_id = key;
            mark_quant_rsp(&req);
            req.args.push_back(Arg::i64(loc.data(), loc.size()));
            Message rsp = rpc(s, req);
            std::vector<float> scratch;
            const float* rows = rsp_view(rsp.args[0], &scratch);
            for (size_t i = 0; i < loc.size(); ++i)
              std::memcpy(uniq_vals->data() + sk_p->positions[s][i] * m.width,
                          rows + i * m.width, m.width * 4);
            record("sparse_pull", loc.size() * m.width * 4);
          }
          // last shard scatters uniq -> caller positions
          if (remain->fetch_sub(1) == 1) {
            for (size_t i = 0; i < n; ++i)
              std::memcpy(out + i * m.width,
                          uniq_vals->data() + (*inv)[i] * m.width, m.width * 4);
          }
        });
      });
    }
  }

  void sd_pushpull(int32_t key, const int64_t* keys, const float* vals,
                   size_t n, float* out_dense) {
    sparse_push(key, keys, vals, n);
    wait(key);
    // dense view of a sparse table: pull all rows in order
    auto m = meta(key);
    pending_.add(key, static_cast<int>(servers_.size()));
    for (size_t s = 0; s < servers_.size(); ++s) {
      auto [lo, hi] = row_range(m.rows, s);
      pool_.submit([=] {
        guarded(key, [&] {
          Message req;
          req.head.type = static_cast<int32_t>(PsfType::kDensePull);
          req.head.tensor_id = key;
          Message rsp = rpc(s, req);
          std::memcpy(out_dense + lo * m.width, rsp.args[0].as_f32(),
                      (hi - lo) * m.width * 4);
        });
      });
    }
  }

  void ss_pushpull(int32_t key, const int64_t* push_keys, const float* vals,
                   const int64_t* pull_keys, float* out, size_t n) {
    // BSP-correct ordering: apply the push, then pull (possibly different)
    // rows. The reference overlaps these per-server (SSPushPull PSF); we
    // conservatively order globally, which also avoids cross-server skew.
    sparse_push(key, push_keys, vals, n);
    wait(key);
    sparse_pull(key, pull_keys, out, n);
  }

  // -- raw assignment (host-side init values; reference initializers push
  // through InitTensor's server-side init — here explicit values bypass the
  // optimizer entirely) -------------------------------------------------
  void assign_dense(int32_t key, const float* data, size_t len) {
    auto m = meta(key);
    check_len(m, key, len);
    for (size_t s = 0; s < servers_.size(); ++s) {
      auto [lo, hi] = (m.kind == ParamKind::kDense)
                          ? dense_range(m.len, s)
                          : std::pair<size_t, size_t>(
                                row_range(m.rows, s).first * m.width,
                                row_range(m.rows, s).second * m.width);
      Message req;
      req.head.type = static_cast<int32_t>(PsfType::kParamAssign);
      req.head.tensor_id = key;
      req.args.push_back(Arg::f32(data + lo, hi - lo));
      rpc(s, req);
    }
  }

  void assign_rows(int32_t key, const int64_t* keys, const float* vals,
                   size_t n) {
    auto m = meta(key);
    auto sk = shard_rows(m, keys, n);
    for (size_t s = 0; s < servers_.size(); ++s) {
      const auto& loc = sk.local[s];
      if (loc.empty()) continue;
      std::vector<float> shard_vals(loc.size() * m.width);
      for (size_t i = 0; i < loc.size(); ++i)
        std::memcpy(shard_vals.data() + i * m.width,
                    vals + sk.positions[s][i] * m.width, m.width * 4);
      Message req;
      req.head.type = static_cast<int32_t>(PsfType::kParamAssignRows);
      req.head.tensor_id = key;
      req.args.push_back(Arg::i64(loc.data(), loc.size()));
      req.args.push_back(Arg::f32(shard_vals.data(), shard_vals.size()));
      rpc(s, req);
    }
  }

  // -- cache-table ops (used by the C++ embedding cache) ------------------
  // Bounded-staleness pull (reference hetu_client.cc:6-37): returns rows of
  // `keys` the client has never seen (cver == -1) or whose server version ran
  // more than `bound` updates ahead. out_* are filled synchronously (callers
  // run on the cache's own worker thread).
  void sync_embedding(int32_t key, const uint64_t* keys, const int64_t* cvers,
                      size_t n, int64_t bound, std::vector<size_t>* out_pos,
                      std::vector<float>* out_rows,
                      std::vector<int64_t>* out_vers) {
    auto m = meta(key);
    std::vector<int64_t> ikeys(keys, keys + n);
    auto sk = shard_rows(m, ikeys.data(), n);
    out_pos->clear();
    out_rows->clear();
    out_vers->clear();
    for (size_t s = 0; s < servers_.size(); ++s) {
      const auto& loc = sk.local[s];
      if (loc.empty()) continue;
      std::vector<int64_t> shard_vers(loc.size());
      for (size_t i = 0; i < loc.size(); ++i)
        shard_vers[i] = cvers[sk.positions[s][i]];
      Message req;
      req.head.type = static_cast<int32_t>(PsfType::kSyncEmbedding);
      req.head.tensor_id = key;
      mark_quant_rsp(&req);
      req.args.push_back(Arg::i64(loc.data(), loc.size()));
      req.args.push_back(Arg::i64(shard_vers.data(), shard_vers.size()));
      req.args.push_back(Arg::i64(&bound, 1));
      Message rsp = rpc(s, req);
      const int32_t* sel = rsp.args[0].as_i32();
      size_t nsel = rsp.args[0].size() / 4;
      std::vector<float> scratch;
      const float* rows = rsp_view(rsp.args[1], &scratch);
      const int64_t* vers = rsp.args[2].as_i64();
      for (size_t i = 0; i < nsel; ++i) {
        out_pos->push_back(sk.positions[s][sel[i]]);
        out_rows->insert(out_rows->end(), rows + i * m.width,
                         rows + (i + 1) * m.width);
        out_vers->push_back(vers[i]);
      }
      record("sync_embedding", nsel * m.width * 4);
    }
  }

  void push_embedding(int32_t key, const uint64_t* keys, const float* grads,
                      const int64_t* updates, size_t n) {
    auto m = meta(key);
    std::vector<int64_t> ikeys(keys, keys + n);
    auto sk = shard_rows(m, ikeys.data(), n);
    for (size_t s = 0; s < servers_.size(); ++s) {
      const auto& loc = sk.local[s];
      if (loc.empty()) continue;
      std::vector<float> shard_grads(loc.size() * m.width);
      std::vector<int64_t> shard_ups(loc.size());
      for (size_t i = 0; i < loc.size(); ++i) {
        std::memcpy(shard_grads.data() + i * m.width,
                    grads + sk.positions[s][i] * m.width, m.width * 4);
        shard_ups[i] = updates[sk.positions[s][i]];
      }
      Message req;
      req.head.type = static_cast<int32_t>(PsfType::kPushEmbedding);
      req.head.tensor_id = key;
      req.args.push_back(Arg::i64(loc.data(), loc.size()));
      req.args.push_back(value_arg(key, shard_grads.data(),
                                   shard_grads.size(), m.width));
      req.args.push_back(Arg::i64(shard_ups.data(), shard_ups.size()));
      rpc(s, req);
      record("push_embedding", shard_grads.size() * 4);
    }
  }

  // Combined push+sync in ONE round trip per server (reference
  // kPushSyncEmbedding, PSFhandle_embedding.cc:67-81).
  void push_sync_embedding(int32_t key, const uint64_t* push_keys,
                           const float* grads, const int64_t* updates,
                           size_t n_push, const uint64_t* sync_keys,
                           const int64_t* cvers, size_t n_sync, int64_t bound,
                           std::vector<size_t>* out_pos,
                           std::vector<float>* out_rows,
                           std::vector<int64_t>* out_vers) {
    auto m = meta(key);
    std::vector<int64_t> ipush(push_keys, push_keys + n_push);
    std::vector<int64_t> isync(sync_keys, sync_keys + n_sync);
    auto skp = shard_rows(m, ipush.data(), n_push);
    auto sks = shard_rows(m, isync.data(), n_sync);
    out_pos->clear();
    out_rows->clear();
    out_vers->clear();
    for (size_t s = 0; s < servers_.size(); ++s) {
      const auto& locp = skp.local[s];
      const auto& locs = sks.local[s];
      if (locp.empty() && locs.empty()) continue;
      std::vector<float> shard_grads(locp.size() * m.width);
      std::vector<int64_t> shard_ups(locp.size());
      for (size_t i = 0; i < locp.size(); ++i) {
        std::memcpy(shard_grads.data() + i * m.width,
                    grads + skp.positions[s][i] * m.width, m.width * 4);
        shard_ups[i] = updates[skp.positions[s][i]];
      }
      std::vector<int64_t> shard_vers(locs.size());
      for (size_t i = 0; i < locs.size(); ++i)
        shard_vers[i] = cvers[sks.positions[s][i]];
      Message req;
      req.head.type = static_cast<int32_t>(PsfType::kPushSyncEmbedding);
      req.head.tensor_id = key;
      mark_quant_rsp(&req);
      req.args.push_back(Arg::i64(locp.data(), locp.size()));
      req.args.push_back(value_arg(key, shard_grads.data(),
                                   shard_grads.size(), m.width));
      req.args.push_back(Arg::i64(shard_ups.data(), shard_ups.size()));
      req.args.push_back(Arg::i64(locs.data(), locs.size()));
      req.args.push_back(Arg::i64(shard_vers.data(), shard_vers.size()));
      req.args.push_back(Arg::i64(&bound, 1));
      Message rsp = rpc(s, req);
      const int32_t* sel = rsp.args[0].as_i32();
      size_t nsel = rsp.args[0].size() / 4;
      std::vector<float> scratch;
      const float* rows = rsp_view(rsp.args[1], &scratch);
      const int64_t* vers = rsp.args[2].as_i64();
      for (size_t i = 0; i < nsel; ++i) {
        out_pos->push_back(sks.positions[s][sel[i]]);
        out_rows->insert(out_rows->end(), rows + i * m.width,
                         rows + (i + 1) * m.width);
        out_vers->push_back(vers[i]);
      }
      record("push_sync_embedding", (shard_grads.size() + nsel * m.width) * 4);
    }
  }

  // -- data blobs (reference PushData/PullData) ---------------------------
  using query_t = int64_t;

  query_t push_data(int32_t key, const uint64_t* ids, size_t n,
                    const float* vals, const int64_t* lens) {
    return data_op(PsfType::kDataPush, key, ids, n, const_cast<float*>(vals),
                   lens);
  }

  query_t pull_data(int32_t key, const uint64_t* ids, size_t n, float* vals,
                    const int64_t* lens) {
    return data_op(PsfType::kDataPull, key, ids, n, vals, lens);
  }

  void wait_data(query_t q) { pending_.wait(query_key(q)); }

  // -- control -----------------------------------------------------------
  void wait(int32_t key) { pending_.wait(key); }

  // -- hetutrail client spans (docs/OBSERVABILITY.md pillar 5) ------------
  // One span per successful RPC round trip, stamped with the worker's
  // current step (SetTrailStep) — the span context riding the wire is the
  // existing (client_id, req_id) pair, so server spans join back without
  // any wire-format change.
  struct TrailSpan {
    uint64_t req_id;
    int32_t client_id, server, psf, tensor;
    int64_t step;
    int64_t t0_us, dur_us;      // trail_mono_us at send / round-trip span
    int64_t req_bytes, rsp_bytes;
  };
  static constexpr size_t kTrailCols = 10;  // i64 row width for the drain

  void set_trail_step(int64_t step) {
    trail_step_.store(step, std::memory_order_relaxed);
  }

  // Explicit arm/disarm (the SetCommQuant pattern): the worker is a
  // process singleton, so an A/B of two executors must not inherit the
  // other leg's ring state. Disarming clears the ring.
  void set_trail(bool on) {
    trail_on_.store(on);
    if (!on) {
      std::lock_guard<std::mutex> g(trail_mu_);
      trail_ring_.clear();
    }
  }

  // Copy up to max_rows spans (oldest first) into out as kTrailCols-wide
  // i64 rows, removing them from the ring. Returns the row count.
  size_t drain_trail(int64_t* out, size_t max_rows) {
    std::lock_guard<std::mutex> g(trail_mu_);
    size_t n = std::min(max_rows, trail_ring_.size());
    for (size_t i = 0; i < n; ++i) {
      const TrailSpan& s = trail_ring_[i];
      int64_t* r = out + i * kTrailCols;
      r[0] = static_cast<int64_t>(s.req_id);
      r[1] = s.client_id;
      r[2] = s.server;
      r[3] = s.psf;
      r[4] = s.tensor;
      r[5] = s.step;
      r[6] = s.t0_us;
      r[7] = s.dur_us;
      r[8] = s.req_bytes;
      r[9] = s.rsp_bytes;
    }
    trail_ring_.erase(trail_ring_.begin(), trail_ring_.begin() + n);
    return n;
  }

  uint64_t trail_dropped() const { return trail_dropped_.load(); }

  // hetutrail test lever (capi gates on HETU_TEST_MODE, the server gates
  // again): delay the target server's NEXT optimizer apply by `ms`.
  void test_slow_apply(size_t server, int ms) {
    if (server >= servers_.size())
      throw std::runtime_error("test_slow_apply: server index " +
                               std::to_string(server) + " out of range");
    Message req;
    req.head.type = static_cast<int32_t>(PsfType::kTestSlowApply);
    req.head.tensor_id = -1;
    int64_t v = ms;
    req.args.push_back(Arg::i64(&v, 1));
    rpc(server, req);
  }

  // Worker-side RPC counters (telemetry: kServerStats' client-side twin):
  // [rpc round trips issued, fast-retry attempts, successful failover
  // re-issues, raw value-payload bytes, wire value-payload bytes,
  // recv/deadline timeouts, total backoff slept (ms), CRC rejects
  // observed (server rejections + local response-verify failures),
  // chaos faults injected, successful write-RPC round trips]. The two
  // byte counters cover every quantizable payload leg in BOTH modes
  // (raw == wire with quantization off), so raw/wire is the measured
  // compression ratio. `pushes_ok` counts each LOGICAL write RPC once no
  // matter how many retries/duplicates it took — with a fresh single-
  // worker cluster it must equal the sum of the servers' update counters
  // EXACTLY (the no-double-apply / no-lost-update accounting invariant
  // hetu_tpu.chaos checks). Relaxed atomics bumped on the rpc path —
  // counting costs nothing whether or not anyone ever reads them.
  std::vector<int64_t> client_stats() const {
    return {static_cast<int64_t>(rpc_count_.load()),
            static_cast<int64_t>(retry_count_.load()),
            static_cast<int64_t>(failover_count_.load()),
            static_cast<int64_t>(val_raw_bytes_.load()),
            static_cast<int64_t>(val_wire_bytes_.load()),
            static_cast<int64_t>(timeout_count_.load()),
            static_cast<int64_t>(backoff_ms_total_.load()),
            static_cast<int64_t>(crc_reject_count_.load()),
            static_cast<int64_t>(chaos_faults()),
            static_cast<int64_t>(push_ok_count_.load())};
  }

  // hetusave coordinated-snapshot trigger: ask one server to write an
  // epoch-stamped full-state snapshot NOW (synchronous — returns after the
  // snapshot is published and its LATEST pointer flipped). Reply:
  // [snapshot_version, covered_update_counter, update_count, epoch].
  std::vector<int64_t> snapshot_now(size_t server, int64_t epoch) {
    if (server >= servers_.size())
      throw std::runtime_error("snapshot_now: server index " +
                               std::to_string(server) + " out of range");
    Message req;
    req.head.type = static_cast<int32_t>(PsfType::kSnapshotNow);
    req.head.tensor_id = -1;
    req.args.push_back(Arg::i64(&epoch, 1));
    Message rsp = rpc(server, req);
    const int64_t* s = rsp.args[0].as_i64();
    return std::vector<int64_t>(s, s + rsp.args[0].n_i64());
  }

  // Per-server HA counters (kServerStats; rides the fast channel):
  // [updates, snapshot_updates, restored_updates(-1 fresh), snapshot_version,
  // n_params]. After a recovery, `updates acked before death -
  // restored_updates` is the exact lost-update count for that shard.
  std::vector<int64_t> server_stats(size_t server) {
    if (server >= servers_.size())
      throw std::runtime_error("server_stats: server index " +
                               std::to_string(server) + " out of range");
    Message req;
    req.head.type = static_cast<int32_t>(PsfType::kServerStats);
    req.head.tensor_id = -1;
    Message rsp = rpc(server, req);
    const int64_t* s = rsp.args[0].as_i64();
    return std::vector<int64_t>(s, s + rsp.args[0].n_i64());
  }

  void barrier() {
    std::lock_guard<std::mutex> g(sched_mu_);
    Message req;
    req.head.type = static_cast<int32_t>(PsfType::kBarrier);
    sched_->send(req);
    Message rsp;
    if (!sched_->recv(&rsp)) throw std::runtime_error("scheduler lost in barrier");
  }

  void clear(int32_t key) {
    std::lock_guard<std::mutex> g(meta_mu_);
    metas_.erase(key);
  }

  void clear_on_server(int32_t key) {
    for (size_t s = 0; s < servers_.size(); ++s) {
      Message req;
      req.head.type = static_cast<int32_t>(PsfType::kParamClear);
      req.head.tensor_id = key;
      rpc(s, req);
    }
  }

  void parameter_save(int32_t key, const std::string& dir) {
    for (size_t s = 0; s < servers_.size(); ++s) {
      Message req;
      req.head.type = static_cast<int32_t>(PsfType::kParamSave);
      req.head.tensor_id = key;
      req.args.push_back(Arg::str(dir));
      rpc(s, req);
    }
  }

  void parameter_load(int32_t key, const std::string& dir) {
    for (size_t s = 0; s < servers_.size(); ++s) {
      Message req;
      req.head.type = static_cast<int32_t>(PsfType::kParamLoad);
      req.head.tensor_id = key;
      req.args.push_back(Arg::str(dir));
      rpc(s, req);
    }
  }

  // -- load recording (reference PSAgent::startRecord/getLoads) ----------
  void start_record(const std::string& dir) {
    std::lock_guard<std::mutex> g(loads_mu_);
    record_dir_ = dir;
    loads_.clear();
  }

  std::string get_loads() {
    std::lock_guard<std::mutex> g(loads_mu_);
    std::ostringstream os;
    os << "{";
    bool fst = true;
    for (auto& kv : loads_) {
      if (!fst) os << ", ";
      fst = false;
      os << "\"" << kv.first << "\": " << kv.second;
    }
    os << "}";
    if (!record_dir_.empty()) {
      FILE* f = std::fopen((record_dir_ + "/ps_loads_w" +
                            std::to_string(rank_) + ".json").c_str(), "w");
      if (f) {
        std::string s = os.str();
        std::fwrite(s.data(), 1, s.size(), f);
        std::fclose(f);
      }
    }
    return os.str();
  }

 private:
  // One value payload of a push-side RPC: quantized (kQI8) when the knob is
  // on, plain f32 otherwise — with raw-vs-wire byte accounting either way,
  // so an off-vs-int8 A/B reads its compression ratio straight from
  // client_stats. `block` is the scale granularity (row width for sparse
  // payloads, kQuantWireBlock for dense).
  Arg value_arg(int32_t key, const float* vals, size_t n, size_t block) {
    val_raw_bytes_.fetch_add(n * 4, std::memory_order_relaxed);
    if (!quant_.load(std::memory_order_relaxed)) {
      val_wire_bytes_.fetch_add(n * 4, std::memory_order_relaxed);
      return Arg::f32(vals, n);
    }
    Arg a = make_qi8_arg(vals, n, block);
    if (corrupt_armed_.load(std::memory_order_relaxed)) {
      const int32_t t = corrupt_tensor_.load();
      bool mine = t < 0 || t == key;
      bool expected = true;
      if (mine && corrupt_armed_.compare_exchange_strong(expected, false) &&
          a.buf.size() >= sizeof(QI8Header) + 4) {
        // 0xFF-fill the first block's scale -> NaN: must be REJECTED by
        // the server's scale validation (see net.h dequant_qi8)
        std::memset(a.buf.data() + sizeof(QI8Header), 0xFF, 4);
      }
    }
    val_wire_bytes_.fetch_add(a.buf.size(), std::memory_order_relaxed);
    return a;
  }

  // f32 view of a response value payload (dequantizes kQI8 into `scratch`
  // — the bounded-staleness cache and every pull consumer see plain f32
  // rows, so caching/staleness semantics are untouched), with the same
  // raw/wire accounting as value_arg.
  const float* rsp_view(const Arg& a, std::vector<float>* scratch) {
    if (a.dtype == ArgType::kQI8) {
      dequant_qi8(a, scratch, 0);
      val_raw_bytes_.fetch_add(scratch->size() * 4,
                               std::memory_order_relaxed);
      val_wire_bytes_.fetch_add(a.buf.size(), std::memory_order_relaxed);
      return scratch->data();
    }
    val_raw_bytes_.fetch_add(a.buf.size(), std::memory_order_relaxed);
    val_wire_bytes_.fetch_add(a.buf.size(), std::memory_order_relaxed);
    return a.as_f32();
  }

  // request flag asking the server to quantize ITS response value payloads
  void mark_quant_rsp(Message* req) {
    if (quant_.load(std::memory_order_relaxed))
      req->head.flags |= kFlagQuantRsp;
  }

  int connect_addr(const std::string& addr, int retries = 600,
                   int wait_ms = 100) {
    auto colon = addr.rfind(':');
    int fd = connect_to(addr.substr(0, colon),
                        std::stoi(addr.substr(colon + 1)), retries, wait_ms);
    set_recv_timeout(fd, recv_timeout_ms_);
    return fd;
  }

  // Current address + liveness of one server, per the scheduler's heartbeat
  // ledger. Uses a fresh short-lived connection (the registered scheduler
  // connection may be parked inside a barrier).
  std::string cached_addr(size_t server) {
    std::lock_guard<std::mutex> g(addr_mu_);
    return server_addrs_[server];
  }

  // One liveness probe of `server` via the scheduler's heartbeat ledger.
  // `sched_ok` distinguishes the two unreachability shapes the escalation
  // logic must tell apart: scheduler reachable + heartbeat fresh + RPCs
  // failing = a DIRECTED PARTITION between this worker and that server;
  // scheduler unreachable = this worker may be the isolated one.
  struct ServerStatus {
    std::string addr;
    bool alive = true;
    bool sched_ok = false;
  };

  ServerStatus query_server_status(size_t server) {
    try {
      Conn c(connect_to(sched_host_, sched_port_, /*retries=*/20,
                        /*wait_ms=*/100));
      set_recv_timeout(c.fd(), recv_timeout_ms_);
      Message q;
      q.head.type = static_cast<int32_t>(PsfType::kQueryServers);
      c.send(q);
      Message rsp;
      if (!c.recv(&rsp) || rsp.args.size() < 2)
        return {cached_addr(server), true, false};
      std::vector<std::string> addrs;
      std::istringstream ss(rsp.args[0].as_str());
      std::string line;
      while (std::getline(ss, line))
        if (!line.empty()) addrs.push_back(line);
      const int32_t* alive = rsp.args[1].as_i32();
      if (server < addrs.size())
        return {addrs[server], alive[server] != 0, true};
      // beyond the scheduler's address book: the scheduler answered but
      // has NO heartbeat for this server — report not-alive, or the
      // partition diagnosis would claim a fresh heartbeat that does not
      // exist and steer recovery away from the departure path
      return {cached_addr(server), false, true};
    } catch (...) {
      // scheduler unreachable: fall back to the cached address and let the
      // reconnect below decide
    }
    return {cached_addr(server), true, false};
  }

  // One reliable request/response round trip (the role of the reference's
  // resender.h ack+timeout+resend): recv timeouts bound every wait, a dead
  // connection triggers reconnect (to the scheduler's current address for
  // that rank, so a recovered server is picked up) and a RESEND — servers
  // dedup on (client_id, req_id) so a request that executed but whose
  // response was lost is not applied twice.
  // Channel classification is by the size of EITHER leg: anything that can
  // carry a whole-tensor payload — in the request (pushes, assigns) or in
  // the response (kDensePull/kDataPull return full shards, kDDPushPull
  // both) — rides the bulk channel. The fast channel carries the latency-
  // critical per-batch row pulls (kSparsePull, kSyncEmbedding) and small
  // control messages, so they are never stuck behind a multi-MB transfer
  // (see the p3-van note in the constructor).
  static bool is_bulk(PsfType t) {
    switch (t) {
      case PsfType::kDensePush:
      case PsfType::kDensePull:
      case PsfType::kDDPushPull:
      case PsfType::kSparsePush:
      case PsfType::kSDPushPull:    // never sent by this worker (decomposed
      case PsfType::kSSPushPull:    // into push+pull) — kept bulk for any
                                    // external client of the wire protocol
      case PsfType::kPushEmbedding:
      case PsfType::kPushSyncEmbedding:
      case PsfType::kDataPush:
      case PsfType::kDataPull:
      case PsfType::kParamAssign:
      case PsfType::kParamAssignRows:
        return true;
      default:
        return false;
    }
  }

  // A server-side rejection that is SAFE to retry: the request was never
  // applied (CRC reject happens before any dedup/handle work) and the
  // stream is still in sync, so the client resends instead of surfacing
  // an application error. Distinguished from "server error:" (app-level,
  // no retry) by the server's "retryable:" message prefix.
  struct RetryableReject : std::runtime_error {
    using std::runtime_error::runtime_error;
  };

  // One send/recv over the current connection. Returns true with *rsp
  // filled on success; false (error recorded) on a transport failure or a
  // retryable server reject (the connection is closed only in the former
  // — a reject leaves a healthy, in-sync stream, and sets *rejected so
  // the retry loop resends immediately on it instead of paying backoff +
  // scheduler query + reconnect); rethrows app-level server errors (no
  // retry).
  bool try_roundtrip(std::vector<std::unique_ptr<Conn>>& conns, size_t server,
                     Message& req, Message* rsp, std::string* last_err,
                     size_t corrupt_arg = static_cast<size_t>(-1),
                     size_t corrupt_off = 0, bool* rejected = nullptr) {
    try {
      auto& conn = *conns[server];
      conn.send(req, corrupt_arg, corrupt_off);
      // cleared first: a clean peer close (recv() == 0) returns false
      // WITHOUT touching errno, and a stale EAGAIN from an earlier
      // timeout would misclassify a dead server as a timing-out one
      errno = 0;
      if (!conn.recv(rsp)) {
        // SO_RCVTIMEO expiry surfaces as EAGAIN/EWOULDBLOCK; anything else
        // is a closed/error'd peer. Counted apart (hetu_rpc_timeouts_total)
        // because a timing-out server and a dead one are different faults.
        const bool to = errno == EAGAIN || errno == EWOULDBLOCK;
        if (to) timeout_count_.fetch_add(1, std::memory_order_relaxed);
        throw std::runtime_error("server " + std::to_string(server) +
                                 (to ? " timed out" : " closed connection"));
      }
      if (rsp->head.flags == -1) {
        const std::string msg =
            rsp->args.empty() ? "(no diagnostic)" : rsp->args[0].as_str();
        if (msg.rfind("retryable:", 0) == 0) {
          if (msg.find("CRC") != std::string::npos)
            crc_reject_count_.fetch_add(1, std::memory_order_relaxed);
          throw RetryableReject("server " + std::to_string(server) +
                                " rejected: " + msg);
        }
        throw std::runtime_error("server error: " + msg);
      }
      // response integrity: a payload corrupted on the return leg must be
      // re-pulled, never handed to the caller (dedup makes resend safe)
      if (crc_on_.load(std::memory_order_relaxed) &&
          (rsp->head.flags & kFlagCrc)) {
        std::string cerr;
        if (!verify_msg_crc(*rsp, &cerr)) {
          crc_reject_count_.fetch_add(1, std::memory_order_relaxed);
          throw std::runtime_error("server " + std::to_string(server) +
                                   " response CRC mismatch: " + cerr);
        }
      }
      return true;
    } catch (const RetryableReject& e) {
      *last_err = e.what();
      if (rejected) *rejected = true;
      return false;  // stream intact — no close, just resend
    } catch (const std::exception& e) {
      std::string what = e.what();
      if (what.rfind("server error:", 0) == 0) throw;  // app-level: no retry
      *last_err = what;
      conns[server]->close();
      return false;
    }
  }

  // try_roundtrip plus the chaos engine's faults: `cd` is this MESSAGE's
  // scheduled fault (applied on the first attempt only — retries go
  // clean, like a real network where the fault hit one packet), while the
  // directed-partition check applies to EVERY attempt (a real partition
  // blocks retries too, until its window closes).
  bool try_roundtrip_chaos(std::vector<std::unique_ptr<Conn>>& conns,
                           size_t server, int ch, Message& req, Message* rsp,
                           std::string* last_err, const ChaosDecision& cd,
                           ChaosEngine* ce, bool* rejected = nullptr) {
    if (ce && ce->partition_blocked(static_cast<int32_t>(server), ch,
                                    req.head.type, req.head.tensor_id)) {
      *last_err = "chaos: directed partition to server " +
                  std::to_string(server) + " (injected)";
      conns[server]->close();  // a real partition kills the stream too
      return false;
    }
    // events are recorded HERE, when a fault actually fires — a decision
    // preempted by the partition block above (or a corrupt that degrades)
    // leaves no event, so the drained log never over-claims
    const auto applied = [&](ChaosKind k, int64_t arg) {
      ce->record_applied(k, static_cast<int32_t>(server), req.head.type,
                         req.head.tensor_id, cd.seq, arg);
    };
    switch (cd.kind) {
      case ChaosKind::kNone:
        return try_roundtrip(conns, server, req, rsp, last_err,
                             static_cast<size_t>(-1), 0, rejected);
      case ChaosKind::kDelay:
      case ChaosKind::kReorder:
        // the held request lets sibling RPCs (other servers, the other
        // channel) overtake it — delivery reordering at message level
        applied(cd.kind, cd.arg);
        std::this_thread::sleep_for(std::chrono::milliseconds(cd.arg));
        return try_roundtrip(conns, server, req, rsp, last_err,
                             static_cast<size_t>(-1), 0, rejected);
      case ChaosKind::kDrop:
        // request lost on the wire: never sent, stream untouched
        applied(cd.kind, cd.arg);
        *last_err = "chaos: request dropped (injected)";
        return false;
      case ChaosKind::kDropRsp: {
        // the applied-but-unacked window: the server executes, the
        // response is lost. The retry resends the SAME req_id and must be
        // answered from the dedup slot without a second apply. Recorded
        // only when the server actually executed (a transport failure
        // here means no response existed to drop).
        if (!try_roundtrip(conns, server, req, rsp, last_err,
                           static_cast<size_t>(-1), 0, rejected))
          return false;
        applied(cd.kind, cd.arg);
        *rsp = Message();
        *last_err = "chaos: response dropped after execution (injected)";
        return false;
      }
      case ChaosKind::kDup: {
        // duplicate delivery: the same req_id arrives twice back-to-back;
        // the second copy must be served from the dedup slot (we return
        // ITS response, so a divergence would surface immediately)
        if (!try_roundtrip(conns, server, req, rsp, last_err,
                           static_cast<size_t>(-1), 0, rejected))
          return false;
        applied(cd.kind, cd.arg);
        Message second;
        if (!try_roundtrip(conns, server, req, &second, last_err,
                           static_cast<size_t>(-1), 0, rejected))
          return false;
        *rsp = std::move(second);
        return true;
      }
      case ChaosKind::kCorrupt: {
        // flip one payload byte ON THE WIRE — after the checksums are
        // computed (net.h send_msg), exactly where a real bit-flip lands,
        // so the server's CRC verify is what must catch it; the clean
        // retry must then apply exactly once. Requires the CRC leg
        // (without it the corruption would be APPLIED, which is the
        // disease, not the test); with CRC off or no payload the fault
        // degrades to a clean send.
        size_t ai = 0, best = 0;
        for (size_t i = 0; i < req.args.size(); ++i)
          if (req.args[i].buf.size() > best) {
            best = req.args[i].buf.size();
            ai = i;
          }
        if (best == 0 || !crc_on_.load(std::memory_order_relaxed))
          return try_roundtrip(conns, server, req, rsp, last_err,
                               static_cast<size_t>(-1), 0, rejected);
        bool rej = false;
        const bool ok = try_roundtrip(conns, server, req, rsp, last_err, ai,
                                      static_cast<size_t>(cd.arg), &rej);
        // recorded only when the corrupted bytes actually REACHED a
        // receiver — a reject (the expected path) or, hypothetically, a
        // CRC collision that got through. A send that failed at the
        // transport (peer closed first) put nothing on the wire, and
        // logging it would over-claim; the clean retry resends anyway
        // (the corruption lived only in the wire buffer).
        if (ok || rej)
          ce->record_applied(ChaosKind::kCorrupt,
                             static_cast<int32_t>(server), req.head.type,
                             req.head.tensor_id, cd.seq,
                             static_cast<int64_t>(
                                 static_cast<uint64_t>(cd.arg) % best));
        if (rej && rejected) *rejected = true;
        return ok;
      }
      case ChaosKind::kPartition:
        break;  // never scheduled by decide(); handled per-attempt above
    }
    return try_roundtrip(conns, server, req, rsp, last_err,
                         static_cast<size_t>(-1), 0, rejected);
  }

  Message rpc(size_t server, Message& req) {
    // serialize the whole round trip per (server, channel) connection:
    // concurrency comes from the pool issuing to different servers — and
    // from fast-channel requests overtaking bulk transfers
    const int ch = is_bulk(static_cast<PsfType>(req.head.type)) ? 0 : 1;
    auto& conns = ch == 0 ? servers_ : servers_fast_;
    std::lock_guard<std::mutex> g(server_mu_[ch][server % kMaxServers]);
    rpc_count_.fetch_add(1, std::memory_order_relaxed);
    // hetutrail: span start AFTER the per-(server, channel) lock — the span
    // measures wire + server time, not local queueing behind a sibling RPC
    const bool trail = trail_on_.load(std::memory_order_relaxed);
    const int64_t tr0 = trail ? trail_mono_us() : 0;
    req.head.req_id = next_req_id_.fetch_add(1);
    // per-channel client identity: the server's resend-dedup slot assumes
    // monotonic req_ids per client, which holds per channel but not across
    // the two interleaved channels
    req.head.client_id = rank_ * 2 + ch;
    // hetu-elastic membership stamp: an armed server rejects a mismatched
    // non-zero epoch (a straggler that missed a resize commit); 0 (the
    // default, non-elastic runs) is always accepted
    req.head.world_ver = static_cast<int32_t>(
        world_version_.load(std::memory_order_relaxed));
    // hetuchaos hardening: checksum the payload and ask the server to
    // verify + checksum its response (net.h kFlagCrc)
    if (crc_on_.load(std::memory_order_relaxed)) req.head.flags |= kFlagCrc;
    // one scheduled-fault roll per logical RPC (off-mode: one relaxed load)
    ChaosEngine* ce = chaos_.load(std::memory_order_acquire);
    ChaosDecision cd;
    if (ce) cd = ce->decide(static_cast<int32_t>(server), req.head.type,
                            req.head.tensor_id);
    using Clock = std::chrono::steady_clock;
    const auto rpc_deadline =
        rpc_timeout_ms_ > 0
            ? Clock::now() + std::chrono::milliseconds(rpc_timeout_ms_)
            : Clock::time_point::max();
    std::string last_err;
    bool sched_saw_alive = false;  // partition-vs-dead classification
    Message rsp;
    // phase 1: bounded retries with exponential backoff + jitter. The
    // resend rides the (client_id, req_id) dedup ledger, so a request
    // that EXECUTED but whose response was lost is answered from the
    // slot, never applied twice — PR 4's re-issue proof generalized from
    // failover-only to every retry.
    bool was_reject = false;  // last failure was a retryable server reject
    for (int attempt = 0; attempt <= max_retry_; ++attempt) {
      if (attempt > 0) {
        retry_count_.fetch_add(1, std::memory_order_relaxed);
        // a retryable reject (CRC mismatch) came from a HEALTHY server
        // over an in-sync stream: resend immediately on the live socket —
        // backoff is a congestion/death signal, and the scheduler query +
        // reconnect would throw away the intact connection for nothing
        if (!was_reject) {
          const int64_t bo = backoff_ms(attempt, backoff_base_ms_,
                                        backoff_cap_ms_, req.head.req_id);
          backoff_ms_total_.fetch_add(static_cast<uint64_t>(bo),
                                      std::memory_order_relaxed);
          std::this_thread::sleep_for(std::chrono::milliseconds(bo));
          if (Clock::now() >= rpc_deadline) {
            timeout_count_.fetch_add(1, std::memory_order_relaxed);
            last_err += " (DMLC_PS_RPC_TIMEOUT_MS=" +
                        std::to_string(rpc_timeout_ms_) + " exhausted)";
            break;
          }
          auto st = query_server_status(server);
          sched_saw_alive = st.sched_ok && st.alive;
          {
            // both channels' retry paths may relocate the same server
            // concurrently (they hold different per-channel mutexes)
            std::lock_guard<std::mutex> ag(addr_mu_);
            server_addrs_[server] = st.addr;
          }
          if (!st.alive && attempt == max_retry_) break;  // declared dead
          try {
            conns[server] = std::make_unique<Conn>(
                connect_addr(st.addr, /*retries=*/30, /*wait_ms=*/100));
          } catch (const std::exception& e) {
            last_err = e.what();
            continue;
          }
        }
      }
      was_reject = false;
      if (try_roundtrip_chaos(conns, server, ch, req, &rsp, &last_err,
                              attempt == 0 ? cd : ChaosDecision(), ce,
                              &was_reject)) {
        if (trail) trail_record(req, rsp, server, tr0);
        if (is_write_apply(static_cast<PsfType>(req.head.type)))
          push_ok_count_.fetch_add(1, std::memory_order_relaxed);
        return rsp;
      }
    }
    // phase 2 (opt-in): the server is gone OR partitioned from this
    // worker — block-with-deadline until the supervisor's replacement
    // registers (or the partition heals), then re-issue the SAME request
    // (unchanged req_id: the server's (client_id, req_id) dedup — live
    // slot or snapshot-restored ledger — makes re-issue safe). On
    // deadline, fall through to the same error the non-failover path
    // raises, so supervise() still catches the unrecoverable case.
    if (failover_ms_ > 0) {
      const auto deadline =
          Clock::now() + std::chrono::milliseconds(failover_ms_);
      std::fprintf(stderr,
                   "[hetups worker %d] server %zu unreachable (%s); failover:"
                   " waiting up to %d ms for a replacement\n",
                   rank_, server, last_err.c_str(), failover_ms_);
      while (Clock::now() < deadline) {
        auto st = query_server_status(server);
        sched_saw_alive = st.sched_ok && st.alive;
        {
          std::lock_guard<std::mutex> ag(addr_mu_);
          server_addrs_[server] = st.addr;
        }
        if (st.alive) {  // heartbeat fresh: replacement or healed partition
          bool connected = false;
          try {
            conns[server] = std::make_unique<Conn>(
                connect_addr(st.addr, /*retries=*/5, /*wait_ms=*/100));
            connected = true;
          } catch (const std::exception& e) {
            last_err = e.what();
          }
          if (connected &&
              try_roundtrip_chaos(conns, server, ch, req, &rsp, &last_err,
                                  ChaosDecision(), ce)) {
            if (trail) trail_record(req, rsp, server, tr0);
            if (is_write_apply(static_cast<PsfType>(req.head.type)))
              push_ok_count_.fetch_add(1, std::memory_order_relaxed);
            failover_count_.fetch_add(1, std::memory_order_relaxed);
            std::fprintf(stderr,
                         "[hetups worker %d] server %zu recovered at %s; "
                         "request re-issued\n",
                         rank_, server, st.addr.c_str());
            return rsp;
          }
        }
        std::this_thread::sleep_for(
            std::chrono::milliseconds(failover_poll_ms_));
      }
      throw std::runtime_error(
          "PS server " + std::to_string(server) +
          " unreachable: no replacement within the failover deadline (" +
          std::to_string(failover_ms_) + " ms; " + last_err + ")" +
          partition_diag(server, sched_saw_alive));
    }
    throw std::runtime_error(
        "PS server " + std::to_string(server) + " unreachable after " +
        std::to_string(max_retry_ + 1) + " attempts (" + last_err + ")" +
        partition_diag(server, sched_saw_alive));
  }

  // Partial-partition escalation diagnosis: when the scheduler is
  // reachable and reports the server's heartbeat FRESH while this
  // worker's RPCs keep failing, the fault is a directed client<->server
  // partition, not a dead server — the caller should take the PR 4
  // failover / PR 11 departure path instead of blocking on a respawn
  // that will never come (the server isn't down). Scheduler-unreachable
  // keeps the plain error (the Python side's typed SchedulerUnreachable
  // owns that case).
  static std::string partition_diag(size_t server, bool sched_saw_alive) {
    if (!sched_saw_alive) return "";
    return " — directed partition suspected: the scheduler is reachable "
           "and server " +
           std::to_string(server) +
           "'s heartbeat is fresh, but this worker cannot complete an RPC "
           "to it; escalate via the failover/departure path "
           "(DMLC_PS_FAILOVER_DEADLINE_MS / hetu-elastic) instead of "
           "waiting for a respawn";
  }

  // PSF types whose success ticks the server's optimizer update counter
  // exactly once (begin_req) — the client-side half of the update-counter
  // accounting invariant (see client_stats).
  static bool is_write_apply(PsfType t) {
    switch (t) {
      case PsfType::kDensePush:
      case PsfType::kDDPushPull:
      case PsfType::kSparsePush:
      case PsfType::kSDPushPull:
      case PsfType::kSSPushPull:
      case PsfType::kPushEmbedding:
      case PsfType::kPushSyncEmbedding:
        return true;
      default:
        return false;
    }
  }

  // hetutrail: bounded ring append (drop-new + counter when full — the
  // always-on cost contract is a fixed memory ceiling, like the flight
  // recorder, never an unbounded buffer).
  void trail_record(const Message& req, const Message& rsp, size_t server,
                    int64_t t0_us) {
    TrailSpan s;
    s.req_id = req.head.req_id;
    s.client_id = req.head.client_id;
    s.server = static_cast<int32_t>(server);
    s.psf = req.head.type;
    s.tensor = req.head.tensor_id;
    s.step = trail_step_.load(std::memory_order_relaxed);
    s.t0_us = t0_us;
    s.dur_us = trail_mono_us() - t0_us;
    s.req_bytes = 0;
    for (const auto& a : req.args)
      s.req_bytes += static_cast<int64_t>(a.buf.size());
    s.rsp_bytes = 0;
    for (const auto& a : rsp.args)
      s.rsp_bytes += static_cast<int64_t>(a.buf.size());
    std::lock_guard<std::mutex> g(trail_mu_);
    if (trail_ring_.size() >= trail_cap_) {
      trail_dropped_.fetch_add(1, std::memory_order_relaxed);
      return;
    }
    trail_ring_.push_back(s);
  }

  template <typename F>
  void guarded(int32_t key, F&& f) {
    try {
      f();
      pending_.done(key);
    } catch (const std::exception& e) {
      pending_.fail(key, e.what());
    }
  }

  static int32_t query_key(query_t q) {
    return static_cast<int32_t>(q % 1000000) + 1000000000;
  }

  query_t data_op(PsfType type, int32_t key, const uint64_t* ids, size_t n,
                  float* vals, const int64_t* lens) {
    query_t q = next_query_++;
    // shard by id hash across servers
    struct Shard {
      std::vector<uint64_t> ids;
      std::vector<int64_t> lens;
      std::vector<size_t> offs;  // offsets into vals
    };
    auto shards = std::make_shared<std::vector<Shard>>(servers_.size());
    size_t off = 0;
    for (size_t i = 0; i < n; ++i) {
      size_t s = ids[i] % servers_.size();
      (*shards)[s].ids.push_back(ids[i]);
      (*shards)[s].lens.push_back(lens[i]);
      (*shards)[s].offs.push_back(off);
      off += static_cast<size_t>(lens[i]);
    }
    pending_.add(query_key(q), static_cast<int>(servers_.size()));
    for (size_t s = 0; s < servers_.size(); ++s) {
      pool_.submit([=] {
        guarded(query_key(q), [&] {
          auto& sh = (*shards)[s];
          if (sh.ids.empty()) return;
          Message req;
          req.head.type = static_cast<int32_t>(type);
          req.head.tensor_id = key;
          req.args.push_back(Arg::u64(sh.ids.data(), sh.ids.size()));
          req.args.push_back(Arg::i64(sh.lens.data(), sh.lens.size()));
          if (type == PsfType::kDataPush) {
            std::vector<float> payload;
            for (size_t i = 0; i < sh.ids.size(); ++i)
              payload.insert(payload.end(), vals + sh.offs[i],
                             vals + sh.offs[i] + sh.lens[i]);
            req.args.push_back(Arg::f32(payload.data(), payload.size()));
            rpc(s, req);
          } else {
            Message rsp = rpc(s, req);
            const float* rows = rsp.args[0].as_f32();
            size_t roff = 0;
            for (size_t i = 0; i < sh.ids.size(); ++i) {
              std::memcpy(vals + sh.offs[i], rows + roff, sh.lens[i] * 4);
              roff += static_cast<size_t>(sh.lens[i]);
            }
          }
        });
      });
    }
    return q;
  }

  static constexpr size_t kMaxServers = 64;

  int rank_, num_workers_;
  bool finalized_ = false;
  std::string sched_host_;
  int sched_port_ = 0;
  int recv_timeout_ms_ = 15000;
  int max_retry_ = 3;
  int failover_ms_ = 0;        // DMLC_PS_FAILOVER_DEADLINE_MS (0 = off)
  int failover_poll_ms_ = 500;
  // Seeded from the wall clock, not 1: servers keep a per-client_id dedup
  // slot (live, and persisted across server restarts in the snapshot
  // ledger), and a RESTARTED worker process reuses its rank's client_id.
  // If its ids restarted at 1 they would sit below the slot's last_id and
  // every request would be dropped as a pre-reconnect straggler. The wall
  // clock alone is NOT monotonic across incarnations (NTP step-back), so
  // registration folds the scheduler's per-rank incarnation epoch into
  // bits 51+ — the scheduler observes every incarnation in order, making
  // the seed strictly increasing per rank no matter what the clock does.
  static uint64_t boot_req_id() {
    return static_cast<uint64_t>(
        std::chrono::duration_cast<std::chrono::microseconds>(
            std::chrono::system_clock::now().time_since_epoch())
            .count());
  }
  std::atomic<uint64_t> next_req_id_{boot_req_id()};
  std::atomic<uint64_t> rpc_count_{0};       // telemetry (client_stats)
  std::atomic<uint64_t> retry_count_{0};
  std::atomic<uint64_t> failover_count_{0};
  // hetuchaos hardening counters + engine (docs/FAULT_TOLERANCE.md)
  std::atomic<uint64_t> timeout_count_{0};     // recv/deadline timeouts
  std::atomic<uint64_t> backoff_ms_total_{0};  // retry backoff slept
  std::atomic<uint64_t> crc_reject_count_{0};  // server rejects + rsp fails
  std::atomic<uint64_t> push_ok_count_{0};     // logical write RPCs landed
  std::atomic<bool> crc_on_{true};             // HETU_PS_CRC / SetPsCrc
  int backoff_base_ms_ = 10;                   // DMLC_PS_BACKOFF_BASE_MS
  int backoff_cap_ms_ = 2000;                  // DMLC_PS_BACKOFF_CAP_MS
  int rpc_timeout_ms_ = 0;                     // DMLC_PS_RPC_TIMEOUT_MS
  std::atomic<ChaosEngine*> chaos_{nullptr};
  mutable std::mutex chaos_mu_;                // guards chaos_owned_
  std::vector<std::unique_ptr<ChaosEngine>> chaos_owned_;
  // hetuq: quantized-wire state + raw-vs-wire accounting over every
  // quantizable value payload (pushes and pull responses; counted in BOTH
  // modes so off==raw is the A/B denominator)
  std::atomic<bool> quant_{false};
  std::atomic<bool> corrupt_armed_{false};
  // hetu-elastic: this worker's committed membership epoch (stamped onto
  // every request header; 0 until an ElasticAgent arms it)
  std::atomic<uint64_t> world_version_{0};
  std::atomic<int32_t> corrupt_tensor_{-1};
  std::atomic<uint64_t> val_raw_bytes_{0};
  std::atomic<uint64_t> val_wire_bytes_{0};
  std::unique_ptr<Conn> sched_;
  std::mutex sched_mu_;
  std::mutex addr_mu_;   // guards server_addrs_ (both channels' retries)
  std::vector<std::string> server_addrs_;
  std::vector<std::unique_ptr<Conn>> servers_;       // bulk channel
  std::vector<std::unique_ptr<Conn>> servers_fast_;  // pulls/control channel
  std::mutex server_mu_[2][kMaxServers];
  ThreadPool pool_;
  PendingTracker pending_;
  std::mutex meta_mu_;
  std::unordered_map<int32_t, TensorMeta> metas_;
  std::mutex opts_mu_;
  std::unordered_map<int32_t, std::array<float, 3>> push_opts_;
  // hetutrail client-span ring (armed by HETU_TRAIL_DIR)
  std::atomic<bool> trail_on_{false};
  std::atomic<int64_t> trail_step_{0};
  std::atomic<uint64_t> trail_dropped_{0};
  size_t trail_cap_ = 65536;
  std::mutex trail_mu_;
  // deque, not vector: the drain erases from the FRONT in 4096-row
  // batches while trail_mu_ blocks concurrent rpc records — a vector
  // would memmove the whole remaining ring per batch
  std::deque<TrailSpan> trail_ring_;
  std::atomic<query_t> next_query_{1};
  std::mutex loads_mu_;
  std::string record_dir_;
  std::unordered_map<std::string, uint64_t> loads_;

  void record(const char* op, size_t bytes) {
    std::lock_guard<std::mutex> g(loads_mu_);
    loads_[op] += bytes;
  }
};

}  // namespace hetups
