// Scheduler: node registry + address-book broadcast + worker barriers.
//
// Capability parity with the reference's ps-lite Postoffice/scheduler role
// (src/postoffice.cc, van.cc ProcessAddNodeCommandAtScheduler :47): nodes
// join, the scheduler assembles the cluster view and broadcasts it; workers
// use the scheduler for group barriers (Postoffice::Barrier).
#pragma once

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <utility>
#include <vector>

#include "net.h"

namespace hetups {

class Scheduler {
 public:
  Scheduler(int port, int num_servers, int num_workers)
      : port_(port), num_servers_(num_servers), num_workers_(num_workers) {}

  ~Scheduler() { stop(); }

  void start() {
    listen_fd_ = listen_on("", port_);
    running_ = true;
    accept_thread_ = std::thread([this] { accept_loop(); });
  }

  void stop() {
    running_ = false;
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    {
      // release any workers parked in the kCommitResize drain barrier —
      // their conn threads otherwise wait on resize_cv_ forever and
      // join_all() below never returns
      std::lock_guard<std::mutex> g(mu_);
      ++resize_gen_;
      resize_cv_.notify_all();
    }
    {
      std::lock_guard<std::mutex> g(fds_mu_);
      for (int fd : live_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    conn_threads_.join_all();
  }

  // Blocks until every node has sent kShutdown (clean cluster teardown) —
  // bounded by DMLC_PS_SCHED_WAIT_TIMEOUT_MS (default 5 min; <= 0 waits
  // forever). The clock arms when teardown BEGINS (the first kShutdown
  // arrives) and re-arms on every further checkout: wait() is entered at
  // cluster STARTUP, so a timeout measured from entry would kill any
  // healthy run longer than the knob mid-training. A rank that died before
  // checkout shows up as no progress within one window once the others
  // check out, and the timeout throws a diagnostic naming it. (A cluster
  // where NOBODY checks out is the launcher's reap path — workers send no
  // heartbeats, so the scheduler cannot tell that from a long quiet run.)
  // A second call after a timeout returns immediately so Finalize() can
  // still tear the scheduler down.
  void wait() {
    std::unique_lock<std::mutex> g(mu_);
    if (gave_up_) return;
    auto pred = [this] { return shutdowns_ >= num_servers_ + num_workers_; };
    if (wait_timeout_ms_ <= 0) {
      done_cv_.wait(g, pred);
      return;
    }
    done_cv_.wait(g, [this] { return shutdowns_ > 0; });
    int last = shutdowns_;
    while (!pred()) {
      done_cv_.wait_for(g, std::chrono::milliseconds(wait_timeout_ms_),
                        [&] { return pred() || shutdowns_ != last; });
      if (shutdowns_ == last && !pred()) break;  // window expired, no progress
      last = shutdowns_;
    }
    if (pred()) return;
    gave_up_ = true;
    auto seen = [this](int role, int id) {
      for (auto& p : checked_out_)
        if (p.first == role && p.second == id) return true;
      return false;
    };
    std::string sv, wk;
    for (int i = 0; i < num_servers_; ++i)
      if (!seen(0, i)) sv += (sv.empty() ? "" : ",") + std::to_string(i);
    // after an elastic resize the live worker ranks are members_, not
    // necessarily 0..num_workers_-1
    ensure_members_locked();
    for (int32_t i : members_)
      if (!seen(1, i)) wk += (wk.empty() ? "" : ",") + std::to_string(i);
    throw std::runtime_error(
        "hetups scheduler: teardown wait timed out after " +
        std::to_string(wait_timeout_ms_) + " ms (" +
        std::to_string(shutdowns_) + "/" +
        std::to_string(num_servers_ + num_workers_) +
        " shutdowns received); never checked out: servers [" + sv +
        "] workers [" + wk + "] — those ranks likely died before teardown");
  }

 private:
  void accept_loop() {
    while (running_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      conn_threads_.spawn([this, fd] { serve_conn(fd); });
    }
  }

  void serve_conn(int fd) {
    {
      std::lock_guard<std::mutex> g(fds_mu_);
      live_fds_.push_back(fd);
    }
    Message req;
    while (recv_msg(fd, &req)) {
      switch (static_cast<PsfType>(req.head.type)) {
        case PsfType::kRegister: {
          // args: i32[role(0=server,1=worker), id, port], str host
          const int32_t* meta = req.args[0].as_i32();
          std::string host = req.args[1].as_str();
          std::unique_lock<std::mutex> g(mu_);
          int32_t epoch = 0;
          if (meta[0] == 0) {
            // capacity may exceed num_servers_ while a grow is pending
            // (kProposeResize resizes the book so joining servers can
            // register before the world flips)
            const int cap = std::max(
                num_servers_, pending_version_ ? pending_ns_ : 0);
            if (meta[1] < 0 || meta[1] >= cap) {
              std::fprintf(stderr,
                           "[hetups scheduler] SERVER_ID %d out of range "
                           "[0, %d) — check DMLC_NUM_SERVER\n",
                           meta[1], cap);
              break;
            }
            if (server_addrs_.size() < static_cast<size_t>(cap)) {
              server_addrs_.resize(cap);
              last_hb_.resize(cap);
            }
            bool readd = !server_addrs_[meta[1]].empty();
            server_addrs_[meta[1]] = host + ":" + std::to_string(meta[2]);
            last_hb_[meta[1]] = Clock::now();
            if (readd) {
              // recovery re-add (reference van.cc:47's recovery-node path):
              // the cluster is already assembled, answer immediately so the
              // replacement can start serving
              std::fprintf(stderr,
                           "[hetups scheduler] server %d re-registered "
                           "(recovery) at %s\n",
                           meta[1], server_addrs_[meta[1]].c_str());
              Message rsp;
              rsp.head.type = static_cast<int32_t>(PsfType::kAddressBook);
              rsp.head.req_id = req.head.req_id;
              std::string book;
              for (auto& a : server_addrs_) book += a + "\n";
              rsp.args.push_back(Arg::str(book));
              g.unlock();
              try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;  // peer vanished; drop the connection, not the scheduler
          }
              break;
            }
            ++servers_seen_;
          } else {
            ++workers_seen_;
            // per-rank incarnation epoch: a RESTARTED worker reuses its
            // rank's client_id, and the servers' dedup slots (live or
            // snapshot-restored) outlive it. The scheduler is the one
            // party that observes every incarnation in order, so its
            // counter — not the worker's wall clock, which NTP can step
            // backwards — is what guarantees each incarnation's req_ids
            // start above the previous one's.
            if (meta[1] >= 0 && meta[1] < num_workers_) {
              if (worker_incarnations_.size() <
                  static_cast<size_t>(num_workers_))
                worker_incarnations_.resize(num_workers_, 0);
              epoch = ++worker_incarnations_[meta[1]];
            }
          }
          reg_cv_.notify_all();
          reg_cv_.wait(g, [this] {
            return servers_seen_ >= num_servers_ && workers_seen_ >= num_workers_;
          });
          std::string book;
          for (auto& a : server_addrs_) book += a + "\n";
          Message rsp;
          rsp.head.type = static_cast<int32_t>(PsfType::kAddressBook);
          rsp.head.req_id = req.head.req_id;
          rsp.args.push_back(Arg::str(book));
          rsp.args.push_back(Arg::i32(&epoch, 1));  // 0 for servers
          g.unlock();
          try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;  // peer vanished; drop the connection, not the scheduler
          }
          break;
        }
        case PsfType::kHeartbeat: {
          // args: i32[role, id] — one-way keepalive (reference van.cc:569)
          const int32_t* meta = req.args[0].as_i32();
          std::lock_guard<std::mutex> g(mu_);
          if (meta[0] == 0 && meta[1] >= 0 &&
              static_cast<size_t>(meta[1]) < last_hb_.size())
            last_hb_[meta[1]] = Clock::now();
          break;
        }
        case PsfType::kQueryServers: {
          // reply: str book, i32 alive[num_servers] (1 = heartbeat fresh)
          std::unique_lock<std::mutex> g(mu_);
          std::string book;
          for (auto& a : server_addrs_) book += a + "\n";
          std::vector<int32_t> alive(server_addrs_.size(), 0);
          auto now = Clock::now();
          for (size_t i = 0; i < server_addrs_.size(); ++i) {
            auto age = std::chrono::duration_cast<std::chrono::milliseconds>(
                           now - last_hb_[i])
                           .count();
            alive[i] = (!server_addrs_[i].empty() && age <= hb_timeout_ms_)
                           ? 1
                           : 0;
          }
          Message rsp;
          rsp.head.type = static_cast<int32_t>(PsfType::kAddressBook);
          rsp.head.req_id = req.head.req_id;
          rsp.args.push_back(Arg::str(book));
          rsp.args.push_back(Arg::i32(alive.data(), alive.size()));
          g.unlock();
          try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;  // peer vanished; drop the connection, not the scheduler
          }
          break;
        }
        case PsfType::kBarrier: {
          std::unique_lock<std::mutex> g(mu_);
          uint64_t my_gen = barrier_gen_;
          ++barrier_count_;
          if (barrier_count_ >= num_workers_) {
            barrier_count_ = 0;
            ++barrier_gen_;
            barrier_cv_.notify_all();
          } else {
            barrier_cv_.wait(g, [this, my_gen] { return barrier_gen_ > my_gen; });
          }
          Message rsp;
          rsp.head.type = static_cast<int32_t>(PsfType::kAck);
          rsp.head.req_id = req.head.req_id;
          g.unlock();
          try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;  // peer vanished; drop the connection, not the scheduler
          }
          break;
        }
        case PsfType::kProposeResize: {
          // phase 1 (hetu-elastic): record the pending world and grow the
          // registry CAPACITY so joining servers can register/restore —
          // nothing else changes until kFinishResize.
          // args: i32[new_nw, new_ns, removed_ranks...],
          //       optional i64 removed_last_steps (-1 = unknown progress)
          // (size-guarded: these PSFs are reachable from hand-packed raw
          // sockets, and a short frame must not index past empty args)
          if (req.args.empty() || req.args[0].size() < 8) {
            Message rsp = error_reply(req.head.req_id,
                                      "kProposeResize needs at least "
                                      "[new_n_workers, new_n_servers]");
            try {
              send_msg(fd, rsp);
            } catch (...) {
              goto out;
            }
            break;
          }
          const int32_t* a = req.args[0].as_i32();
          const size_t n = req.args[0].size() / 4;
          std::unique_lock<std::mutex> g(mu_);
          ensure_members_locked();
          Message rsp;
          rsp.head.type = static_cast<int32_t>(PsfType::kAck);
          rsp.head.req_id = req.head.req_id;
          if (n < 2) {
            rsp = error_reply(req.head.req_id, "kProposeResize needs at "
                              "least [new_n_workers, new_n_servers]");
          } else {
            const int nw = a[0], ns = a[1];
            std::vector<int32_t> removed(a + 2, a + n);
            if (pending_version_ != 0) {
              if (nw == pending_nw_ && ns == pending_ns_ &&
                  removed == pending_removed_) {
                // idempotent re-propose of the identical resize
                int64_t v = static_cast<int64_t>(pending_version_);
                rsp.args.push_back(Arg::i64(&v, 1));
              } else {
                rsp = error_reply(
                    req.head.req_id,
                    "a different resize (world v" +
                    std::to_string(pending_version_) +
                    ") is already pending — finish or abort it first");
              }
            } else if (ns < num_servers_) {
              rsp = error_reply(
                  req.head.req_id,
                  "server scale-down is not supported (a lost server is a "
                  "FAULT — the HA snapshot/respawn path owns it)");
            } else if (nw < 1) {
              rsp = error_reply(req.head.req_id,
                                "a world needs at least one worker");
            } else {
              pending_version_ = world_version_ + 1;
              pending_nw_ = nw;
              pending_ns_ = ns;
              pending_removed_ = std::move(removed);
              pending_removed_steps_.assign(pending_removed_.size(), -1);
              if (req.args.size() > 1) {
                const int64_t* st = req.args[1].as_i64();
                const size_t ns_ = req.args[1].n_i64();
                for (size_t i = 0;
                     i < ns_ && i < pending_removed_steps_.size(); ++i)
                  pending_removed_steps_[i] = st[i];
              }
              drained_.clear();
              if (server_addrs_.size() < static_cast<size_t>(ns)) {
                server_addrs_.resize(ns);
                last_hb_.resize(ns);
              }
              if (worker_incarnations_.size() < static_cast<size_t>(nw))
                worker_incarnations_.resize(nw, 0);
              std::fprintf(stderr,
                           "[hetups scheduler] resize proposed: world v%llu "
                           "-> %dw/%ds\n",
                           (unsigned long long)pending_version_, nw, ns);
              int64_t v = static_cast<int64_t>(pending_version_);
              rsp.args.push_back(Arg::i64(&v, 1));
            }
          }
          g.unlock();
          try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;
          }
          break;
        }
        case PsfType::kResizeState: {
          std::unique_lock<std::mutex> g(mu_);
          ensure_members_locked();
          const auto survivors = survivors_locked();
          int64_t vals[13] = {
              static_cast<int64_t>(world_version_),
              static_cast<int64_t>(pending_version_),
              num_workers_,
              num_servers_,
              pending_nw_,
              pending_ns_,
              static_cast<int64_t>(drained_survivors_locked(survivors)),
              pending_version_ ? static_cast<int64_t>(survivors.size()) : 0,
              new_servers_ready_locked() ? 1 : 0,
              static_cast<int64_t>(members_.size()),
              // slot 10 (hetusave): completed coordinated-snapshot epochs
              // this scheduler incarnation — a pure suffix extension, so
              // pre-hetusave clients reading 10 slots stay valid
              static_cast<int64_t>(snapshot_epochs_),
              // slots 11-12 (hetupilot): actuation eras sealed with a
              // commit / rollback verdict tag — the same suffix-extension
              // discipline, so hetusave-era clients reading 11 stay valid
              static_cast<int64_t>(pilot_commit_epochs_),
              static_cast<int64_t>(pilot_rollback_epochs_)};
          Message rsp;
          rsp.head.type = static_cast<int32_t>(PsfType::kAck);
          rsp.head.req_id = req.head.req_id;
          rsp.args.push_back(Arg::i64(vals, 13));
          rsp.args.push_back(Arg::i32(members_.data(), members_.size()));
          g.unlock();
          try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;
          }
          break;
        }
        case PsfType::kCommitResize: {
          // the drain barrier: a surviving worker reports its current step
          // and PARKS here until the coordinator finishes (or aborts) the
          // pending resize. With no resize pending it returns the current
          // world immediately (covers retried commits after a finish).
          if (req.args.empty() || req.args[0].size() < 8) {
            Message rsp = error_reply(req.head.req_id,
                                      "kCommitResize needs [role, rank]");
            try {
              send_msg(fd, rsp);
            } catch (...) {
              goto out;
            }
            break;
          }
          const int32_t* who = req.args[0].as_i32();
          const int32_t rank = who[1];
          const int64_t step =
              (req.args.size() > 1 && req.args[1].n_i64() >= 1)
                  ? req.args[1].as_i64()[0]
                  : 0;
          std::unique_lock<std::mutex> g(mu_);
          ensure_members_locked();
          if (pending_version_ != 0) {
            drained_[rank] = step;
            const uint64_t my_gen = resize_gen_;
            resize_cv_.wait(g, [this, my_gen] {
              return resize_gen_ > my_gen;
            });
          }
          Message rsp = world_reply_locked(req.head.req_id, rank);
          g.unlock();
          try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;
          }
          break;
        }
        case PsfType::kFinishResize: {
          // phase 2: flip the world atomically (or abort — the safety
          // valve after a failed migration / drain timeout: the pending
          // proposal clears and every parked worker is released under the
          // OLD world, state untouched).
          const bool abort =
              !req.args.empty() && req.args[0].size() >= 4 &&
              req.args[0].as_i32()[0] != 0;
          // optional second i32 (suffix extension): the actuation tag —
          // WHY the coordinator ran this identity-resize barrier era.
          // 0/absent: plain resize or untagged abort (counted nowhere);
          // 1: hetusave committed a snapshot epoch; 2/3: hetupilot sealed
          // an actuation era with a commit/rollback verdict. Only tagged
          // aborts advance an era counter — shape inference (identical
          // world, nobody removed) would miscount a genuine same-size
          // resize aborted after a drain timeout, or a failed snapshot's
          // best-effort release, as a completed epoch.
          const int32_t actuation_tag =
              (abort && req.args[0].size() >= 8)
                  ? req.args[0].as_i32()[1]
                  : 0;
          std::unique_lock<std::mutex> g(mu_);
          ensure_members_locked();
          Message rsp;
          if (pending_version_ == 0) {
            rsp = error_reply(req.head.req_id, "no resize is pending");
          } else if (abort) {
            // hetusave and hetupilot both ride propose-identical-world ->
            // drain-park -> abort as their quiesce barrier; when the
            // coordinator tagged this abort as the release AFTER its
            // outcome durably committed (job manifest / actuation
            // verdict), stamp the matching era counter so kResizeState
            // exposes monotonic, cause-attributed counters.
            if (actuation_tag == 1) ++snapshot_epochs_;
            else if (actuation_tag == 2) ++pilot_commit_epochs_;
            else if (actuation_tag == 3) ++pilot_rollback_epochs_;
            std::fprintf(stderr,
                         "[hetups scheduler] resize v%llu ABORTED; world "
                         "v%llu continues\n",
                         (unsigned long long)pending_version_,
                         (unsigned long long)world_version_);
            pending_version_ = 0;
            pending_removed_.clear();
            pending_removed_steps_.clear();
            drained_.clear();
            ++resize_gen_;
            resize_cv_.notify_all();
            rsp.head.type = static_cast<int32_t>(PsfType::kAck);
            rsp.head.req_id = req.head.req_id;
            int64_t v = static_cast<int64_t>(world_version_);
            rsp.args.push_back(Arg::i64(&v, 1));
          } else {
            const auto survivors = survivors_locked();
            const size_t sdrained = drained_survivors_locked(survivors);
            if (sdrained < survivors.size()) {
              rsp = error_reply(
                  req.head.req_id,
                  "drain barrier incomplete (" +
                  std::to_string(sdrained) + "/" +
                  std::to_string(survivors.size()) + " survivors parked)");
            } else if (!new_servers_ready_locked()) {
              rsp = error_reply(req.head.req_id,
                                "joining server(s) not yet registered");
            } else {
              // close the open era with per-member end steps: survivors
              // reported theirs at drain; removed ranks ride the
              // proposal's progress records (-1 = unknown -> max survivor
              // step, which may LOSE the dead rank's in-era tail but
              // never double-applies it)
              int64_t max_step = 0;
              for (auto& kv : drained_) max_step = std::max(max_step,
                                                            kv.second);
              if (!world_log_.empty()) {
                for (auto& m : world_log_.back().members) {
                  auto it = drained_.find(m.rank);
                  if (it != drained_.end()) {
                    m.end_step = it->second;
                    continue;
                  }
                  // a rank that never drained (removed, or vanished):
                  // with a progress record its exact tail redistributes;
                  // WITHOUT one the only end step that can never
                  // double-apply is "assume it consumed its whole chunk"
                  // (-2 sentinel; era_partitions treats the chunk as
                  // fully consumed) — its unconsumed tail is LOST, which
                  // is the documented at-most-once fallback. Guessing the
                  // max survivor step would replay batches a fast dead
                  // rank already pushed.
                  m.end_step = -2;
                  for (size_t i = 0; i < pending_removed_.size(); ++i)
                    if (pending_removed_[i] == m.rank &&
                        pending_removed_steps_[i] >= 0)
                      m.end_step = pending_removed_steps_[i];
                }
              }
              members_ = survivors;
              // joiners take the lowest free ranks (dedup-safe: the
              // per-rank incarnation epoch covers rank reuse)
              while (static_cast<int>(members_.size()) < pending_nw_) {
                int32_t cand = 0;
                while (std::find(members_.begin(), members_.end(), cand) !=
                       members_.end())
                  ++cand;
                members_.push_back(cand);
              }
              std::sort(members_.begin(), members_.end());
              if (static_cast<int>(members_.size()) > pending_nw_)
                members_.resize(pending_nw_);  // unnamed shrink: drop
                                               // the highest ranks
              num_workers_ = pending_nw_;
              num_servers_ = pending_ns_;
              world_version_ = pending_version_;
              Era e{world_version_, num_workers_, num_servers_, {}};
              for (int32_t r : members_) {
                auto it = drained_.find(r);
                e.members.push_back(
                    {r, it != drained_.end() ? it->second : max_step, -1});
              }
              world_log_.push_back(std::move(e));
              pending_version_ = 0;
              pending_removed_.clear();
              pending_removed_steps_.clear();
              drained_.clear();
              ++resize_gen_;
              resize_cv_.notify_all();
              std::fprintf(stderr,
                           "[hetups scheduler] world v%llu committed: "
                           "%dw/%ds\n",
                           (unsigned long long)world_version_, num_workers_,
                           num_servers_);
              rsp.head.type = static_cast<int32_t>(PsfType::kAck);
              rsp.head.req_id = req.head.req_id;
              int64_t v = static_cast<int64_t>(world_version_);
              rsp.args.push_back(Arg::i64(&v, 1));
            }
          }
          g.unlock();
          try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;
          }
          break;
        }
        case PsfType::kResizeLog: {
          // flat i64 rows: per era {version, nw, ns, n_members,
          // (rank, start_step, end_step) * n_members}
          std::unique_lock<std::mutex> g(mu_);
          ensure_members_locked();
          std::vector<int64_t> flat;
          for (const auto& e : world_log_) {
            flat.push_back(static_cast<int64_t>(e.version));
            flat.push_back(e.nw);
            flat.push_back(e.ns);
            flat.push_back(static_cast<int64_t>(e.members.size()));
            for (const auto& m : e.members) {
              flat.push_back(m.rank);
              flat.push_back(m.start_step);
              flat.push_back(m.end_step);
            }
          }
          Message rsp;
          rsp.head.type = static_cast<int32_t>(PsfType::kAck);
          rsp.head.req_id = req.head.req_id;
          rsp.args.push_back(Arg::i64(flat.data(), flat.size()));
          g.unlock();
          try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;
          }
          break;
        }
        case PsfType::kShutdown: {
          // optional args: i32[role, id] — who is checking out (lets the
          // bounded wait() name the ranks that never did)
          std::unique_lock<std::mutex> g(mu_);
          ++shutdowns_;
          if (!req.args.empty() && req.args[0].size() >= 8) {
            const int32_t* m = req.args[0].as_i32();
            checked_out_.push_back({m[0], m[1]});
          }
          done_cv_.notify_all();
          goto out;
        }
        default:
          break;
      }
    }
  out:
    {
      std::lock_guard<std::mutex> g(fds_mu_);
      live_fds_.erase(std::remove(live_fds_.begin(), live_fds_.end(), fd),
                      live_fds_.end());
    }
    ::close(fd);
  }

  int port_;
  int num_servers_;
  int num_workers_;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  ConnThreads conn_threads_;

  std::mutex fds_mu_;
  std::vector<int> live_fds_;
  using Clock = std::chrono::steady_clock;
  std::mutex mu_;
  std::condition_variable reg_cv_, barrier_cv_, done_cv_;
  std::vector<std::string> server_addrs_;
  std::vector<Clock::time_point> last_hb_;
  // a server whose last heartbeat is older than this is reported dead to
  // kQueryServers clients (reference heartbeat_timeout, van.cc:27)
  int hb_timeout_ms_ = env_int_or("DMLC_PS_HEARTBEAT_TIMEOUT_MS", 10000);
  int servers_seen_ = 0, workers_seen_ = 0;
  std::vector<uint32_t> worker_incarnations_;  // per-rank kRegister count

  // -- hetu-elastic membership registry (guarded by mu_) ------------------
  // The world log: one era per committed membership, with PER-MEMBER
  // start/end steps — survivors drain at different local steps, and the
  // per-member bounds are what keep the exactly-once dataloader
  // accounting honest (hetu_tpu/elastic.py era_partitions).
  struct EraMember {
    int32_t rank;
    int64_t start_step;
    int64_t end_step;  // -1 while the era is open
  };
  struct Era {
    uint64_t version;
    int32_t nw, ns;
    std::vector<EraMember> members;
  };
  uint64_t world_version_ = 1;
  std::vector<int32_t> members_;  // current worker ranks (sorted)
  std::vector<Era> world_log_;
  uint64_t pending_version_ = 0;  // 0 = no resize pending
  int pending_nw_ = 0, pending_ns_ = 0;
  std::vector<int32_t> pending_removed_;
  std::vector<int64_t> pending_removed_steps_;  // -1 = unknown progress
  std::map<int32_t, int64_t> drained_;  // rank -> step at drain commit
  uint64_t resize_gen_ = 0;             // bumps at finish/abort
  std::condition_variable resize_cv_;   // parks kCommitResize callers
  uint64_t snapshot_epochs_ = 0;        // hetusave: completed coordinated
                                        // snapshot epochs (snapshot-tagged
                                        // kFinishResize aborts only)
  uint64_t pilot_commit_epochs_ = 0;    // hetupilot: actuation eras sealed
  uint64_t pilot_rollback_epochs_ = 0;  // with a commit/rollback verdict
                                        // (tag 2/3 kFinishResize aborts)

  // members_/world_log_ materialize lazily — the launch world is fixed by
  // config, so this is valid whether it runs before or after assembly
  void ensure_members_locked() {
    if (members_.empty() && num_workers_ > 0)
      for (int i = 0; i < num_workers_; ++i) members_.push_back(i);
    if (world_log_.empty() && !members_.empty()) {
      Era e{1, num_workers_, num_servers_, {}};
      for (int32_t r : members_) e.members.push_back({r, 0, -1});
      world_log_.push_back(std::move(e));
    }
  }

  std::vector<int32_t> survivors_locked() {
    ensure_members_locked();
    std::vector<int32_t> out;
    for (int32_t r : members_)
      if (std::find(pending_removed_.begin(), pending_removed_.end(), r) ==
          pending_removed_.end())
        out.push_back(r);
    return out;
  }

  // drained SURVIVORS only: a removed-but-alive rank that parks must not
  // satisfy the barrier while a true survivor still has traffic in flight
  size_t drained_survivors_locked(const std::vector<int32_t>& survivors) {
    size_t n = 0;
    for (int32_t r : survivors)
      if (drained_.count(r)) ++n;
    return n;
  }

  bool new_servers_ready_locked() const {
    if (pending_version_ == 0) return true;
    for (int i = num_servers_;
         i < pending_ns_ && i < static_cast<int>(server_addrs_.size()); ++i)
      if (server_addrs_[i].empty()) return false;
    return pending_ns_ <= static_cast<int>(server_addrs_.size());
  }

  // shared reply body for kCommitResize (and its no-pending fast path):
  // the released worker learns the now-current world in one message
  Message world_reply_locked(uint64_t req_id, int32_t rank) {
    Message rsp;
    rsp.head.type = static_cast<int32_t>(PsfType::kAck);
    rsp.head.req_id = req_id;
    int64_t dp_rank = -1, start_step = 0;
    if (!world_log_.empty()) {
      const Era& cur = world_log_.back();
      for (size_t j = 0; j < cur.members.size(); ++j)
        if (cur.members[j].rank == rank) {
          dp_rank = static_cast<int64_t>(j);
          start_step = cur.members[j].start_step;
        }
    }
    int64_t vals[5] = {static_cast<int64_t>(world_version_), num_workers_,
                       num_servers_, dp_rank, start_step};
    rsp.args.push_back(Arg::i64(vals, 5));
    rsp.args.push_back(Arg::i32(members_.data(), members_.size()));
    std::string book;
    for (auto& a : server_addrs_) book += a + "\n";
    rsp.args.push_back(Arg::str(book));
    return rsp;
  }

  static Message error_reply(uint64_t req_id, const std::string& what) {
    Message rsp;
    rsp.head.type = static_cast<int32_t>(PsfType::kAck);
    rsp.head.req_id = req_id;
    rsp.head.flags = -1;
    rsp.args.push_back(Arg::str(what));
    return rsp;
  }
  int barrier_count_ = 0;
  uint64_t barrier_gen_ = 0;
  int shutdowns_ = 0;
  std::vector<std::pair<int, int>> checked_out_;  // (role, id) per kShutdown
  int wait_timeout_ms_ = env_int_or("DMLC_PS_SCHED_WAIT_TIMEOUT_MS", 300000);
  bool gave_up_ = false;
};

}  // namespace hetups
