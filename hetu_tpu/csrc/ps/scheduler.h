// Scheduler: node registry + address-book broadcast + worker barriers.
//
// Capability parity with the reference's ps-lite Postoffice/scheduler role
// (src/postoffice.cc, van.cc ProcessAddNodeCommandAtScheduler :47): nodes
// join, the scheduler assembles the cluster view and broadcasts it; workers
// use the scheduler for group barriers (Postoffice::Barrier).
#pragma once

#include <algorithm>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <mutex>
#include <thread>
#include <utility>
#include <vector>

#include "net.h"

namespace hetups {

class Scheduler {
 public:
  Scheduler(int port, int num_servers, int num_workers)
      : port_(port), num_servers_(num_servers), num_workers_(num_workers) {}

  ~Scheduler() { stop(); }

  void start() {
    listen_fd_ = listen_on("", port_);
    running_ = true;
    accept_thread_ = std::thread([this] { accept_loop(); });
  }

  void stop() {
    running_ = false;
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    {
      std::lock_guard<std::mutex> g(fds_mu_);
      for (int fd : live_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    conn_threads_.join_all();
  }

  // Blocks until every node has sent kShutdown (clean cluster teardown) —
  // bounded by DMLC_PS_SCHED_WAIT_TIMEOUT_MS (default 5 min; <= 0 waits
  // forever). The clock arms when teardown BEGINS (the first kShutdown
  // arrives) and re-arms on every further checkout: wait() is entered at
  // cluster STARTUP, so a timeout measured from entry would kill any
  // healthy run longer than the knob mid-training. A rank that died before
  // checkout shows up as no progress within one window once the others
  // check out, and the timeout throws a diagnostic naming it. (A cluster
  // where NOBODY checks out is the launcher's reap path — workers send no
  // heartbeats, so the scheduler cannot tell that from a long quiet run.)
  // A second call after a timeout returns immediately so Finalize() can
  // still tear the scheduler down.
  void wait() {
    std::unique_lock<std::mutex> g(mu_);
    if (gave_up_) return;
    auto pred = [this] { return shutdowns_ >= num_servers_ + num_workers_; };
    if (wait_timeout_ms_ <= 0) {
      done_cv_.wait(g, pred);
      return;
    }
    done_cv_.wait(g, [this] { return shutdowns_ > 0; });
    int last = shutdowns_;
    while (!pred()) {
      done_cv_.wait_for(g, std::chrono::milliseconds(wait_timeout_ms_),
                        [&] { return pred() || shutdowns_ != last; });
      if (shutdowns_ == last && !pred()) break;  // window expired, no progress
      last = shutdowns_;
    }
    if (pred()) return;
    gave_up_ = true;
    auto seen = [this](int role, int id) {
      for (auto& p : checked_out_)
        if (p.first == role && p.second == id) return true;
      return false;
    };
    std::string sv, wk;
    for (int i = 0; i < num_servers_; ++i)
      if (!seen(0, i)) sv += (sv.empty() ? "" : ",") + std::to_string(i);
    for (int i = 0; i < num_workers_; ++i)
      if (!seen(1, i)) wk += (wk.empty() ? "" : ",") + std::to_string(i);
    throw std::runtime_error(
        "hetups scheduler: teardown wait timed out after " +
        std::to_string(wait_timeout_ms_) + " ms (" +
        std::to_string(shutdowns_) + "/" +
        std::to_string(num_servers_ + num_workers_) +
        " shutdowns received); never checked out: servers [" + sv +
        "] workers [" + wk + "] — those ranks likely died before teardown");
  }

 private:
  void accept_loop() {
    while (running_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      conn_threads_.spawn([this, fd] { serve_conn(fd); });
    }
  }

  void serve_conn(int fd) {
    {
      std::lock_guard<std::mutex> g(fds_mu_);
      live_fds_.push_back(fd);
    }
    Message req;
    while (recv_msg(fd, &req)) {
      switch (static_cast<PsfType>(req.head.type)) {
        case PsfType::kRegister: {
          // args: i32[role(0=server,1=worker), id, port], str host
          const int32_t* meta = req.args[0].as_i32();
          std::string host = req.args[1].as_str();
          std::unique_lock<std::mutex> g(mu_);
          int32_t epoch = 0;
          if (meta[0] == 0) {
            if (meta[1] < 0 || meta[1] >= num_servers_) {
              std::fprintf(stderr,
                           "[hetups scheduler] SERVER_ID %d out of range "
                           "[0, %d) — check DMLC_NUM_SERVER\n",
                           meta[1], num_servers_);
              break;
            }
            if (server_addrs_.size() <
                static_cast<size_t>(num_servers_)) {
              server_addrs_.resize(num_servers_);
              last_hb_.resize(num_servers_);
            }
            bool readd = !server_addrs_[meta[1]].empty();
            server_addrs_[meta[1]] = host + ":" + std::to_string(meta[2]);
            last_hb_[meta[1]] = Clock::now();
            if (readd) {
              // recovery re-add (reference van.cc:47's recovery-node path):
              // the cluster is already assembled, answer immediately so the
              // replacement can start serving
              std::fprintf(stderr,
                           "[hetups scheduler] server %d re-registered "
                           "(recovery) at %s\n",
                           meta[1], server_addrs_[meta[1]].c_str());
              Message rsp;
              rsp.head.type = static_cast<int32_t>(PsfType::kAddressBook);
              rsp.head.req_id = req.head.req_id;
              std::string book;
              for (auto& a : server_addrs_) book += a + "\n";
              rsp.args.push_back(Arg::str(book));
              g.unlock();
              try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;  // peer vanished; drop the connection, not the scheduler
          }
              break;
            }
            ++servers_seen_;
          } else {
            ++workers_seen_;
            // per-rank incarnation epoch: a RESTARTED worker reuses its
            // rank's client_id, and the servers' dedup slots (live or
            // snapshot-restored) outlive it. The scheduler is the one
            // party that observes every incarnation in order, so its
            // counter — not the worker's wall clock, which NTP can step
            // backwards — is what guarantees each incarnation's req_ids
            // start above the previous one's.
            if (meta[1] >= 0 && meta[1] < num_workers_) {
              if (worker_incarnations_.size() <
                  static_cast<size_t>(num_workers_))
                worker_incarnations_.resize(num_workers_, 0);
              epoch = ++worker_incarnations_[meta[1]];
            }
          }
          reg_cv_.notify_all();
          reg_cv_.wait(g, [this] {
            return servers_seen_ >= num_servers_ && workers_seen_ >= num_workers_;
          });
          std::string book;
          for (auto& a : server_addrs_) book += a + "\n";
          Message rsp;
          rsp.head.type = static_cast<int32_t>(PsfType::kAddressBook);
          rsp.head.req_id = req.head.req_id;
          rsp.args.push_back(Arg::str(book));
          rsp.args.push_back(Arg::i32(&epoch, 1));  // 0 for servers
          g.unlock();
          try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;  // peer vanished; drop the connection, not the scheduler
          }
          break;
        }
        case PsfType::kHeartbeat: {
          // args: i32[role, id] — one-way keepalive (reference van.cc:569)
          const int32_t* meta = req.args[0].as_i32();
          std::lock_guard<std::mutex> g(mu_);
          if (meta[0] == 0 && meta[1] >= 0 &&
              static_cast<size_t>(meta[1]) < last_hb_.size())
            last_hb_[meta[1]] = Clock::now();
          break;
        }
        case PsfType::kQueryServers: {
          // reply: str book, i32 alive[num_servers] (1 = heartbeat fresh)
          std::unique_lock<std::mutex> g(mu_);
          std::string book;
          for (auto& a : server_addrs_) book += a + "\n";
          std::vector<int32_t> alive(server_addrs_.size(), 0);
          auto now = Clock::now();
          for (size_t i = 0; i < server_addrs_.size(); ++i) {
            auto age = std::chrono::duration_cast<std::chrono::milliseconds>(
                           now - last_hb_[i])
                           .count();
            alive[i] = (!server_addrs_[i].empty() && age <= hb_timeout_ms_)
                           ? 1
                           : 0;
          }
          Message rsp;
          rsp.head.type = static_cast<int32_t>(PsfType::kAddressBook);
          rsp.head.req_id = req.head.req_id;
          rsp.args.push_back(Arg::str(book));
          rsp.args.push_back(Arg::i32(alive.data(), alive.size()));
          g.unlock();
          try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;  // peer vanished; drop the connection, not the scheduler
          }
          break;
        }
        case PsfType::kBarrier: {
          std::unique_lock<std::mutex> g(mu_);
          uint64_t my_gen = barrier_gen_;
          ++barrier_count_;
          if (barrier_count_ >= num_workers_) {
            barrier_count_ = 0;
            ++barrier_gen_;
            barrier_cv_.notify_all();
          } else {
            barrier_cv_.wait(g, [this, my_gen] { return barrier_gen_ > my_gen; });
          }
          Message rsp;
          rsp.head.type = static_cast<int32_t>(PsfType::kAck);
          rsp.head.req_id = req.head.req_id;
          g.unlock();
          try {
            send_msg(fd, rsp);
          } catch (...) {
            goto out;  // peer vanished; drop the connection, not the scheduler
          }
          break;
        }
        case PsfType::kShutdown: {
          // optional args: i32[role, id] — who is checking out (lets the
          // bounded wait() name the ranks that never did)
          std::unique_lock<std::mutex> g(mu_);
          ++shutdowns_;
          if (!req.args.empty() && req.args[0].size() >= 8) {
            const int32_t* m = req.args[0].as_i32();
            checked_out_.push_back({m[0], m[1]});
          }
          done_cv_.notify_all();
          goto out;
        }
        default:
          break;
      }
    }
  out:
    {
      std::lock_guard<std::mutex> g(fds_mu_);
      live_fds_.erase(std::remove(live_fds_.begin(), live_fds_.end(), fd),
                      live_fds_.end());
    }
    ::close(fd);
  }

  int port_;
  int num_servers_;
  int num_workers_;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  ConnThreads conn_threads_;

  std::mutex fds_mu_;
  std::vector<int> live_fds_;
  using Clock = std::chrono::steady_clock;
  std::mutex mu_;
  std::condition_variable reg_cv_, barrier_cv_, done_cv_;
  std::vector<std::string> server_addrs_;
  std::vector<Clock::time_point> last_hb_;
  // a server whose last heartbeat is older than this is reported dead to
  // kQueryServers clients (reference heartbeat_timeout, van.cc:27)
  int hb_timeout_ms_ = env_int_or("DMLC_PS_HEARTBEAT_TIMEOUT_MS", 10000);
  int servers_seen_ = 0, workers_seen_ = 0;
  std::vector<uint32_t> worker_incarnations_;  // per-rank kRegister count
  int barrier_count_ = 0;
  uint64_t barrier_gen_ = 0;
  int shutdowns_ = 0;
  std::vector<std::pair<int, int>> checked_out_;  // (role, id) per kShutdown
  int wait_timeout_ms_ = env_int_or("DMLC_PS_SCHED_WAIT_TIMEOUT_MS", 300000);
  bool gave_up_ = false;
};

}  // namespace hetups
