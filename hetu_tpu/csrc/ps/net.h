// TCP transport for the hetu_tpu parameter server.
//
// Capability parity with the reference's ps-lite "van" layer
// (ps-lite/src/van.cc:29-42, zmq_van.h): a message-framed, connection-oriented
// transport. Redesigned: raw POSIX TCP with length-prefixed frames instead of
// ZMQ — no external dependency, same loopback/process-cluster test story
// (reference tests/pstests/local_s2_w2.yml).
#pragma once

#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cctype>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

namespace hetups {

// ---------------------------------------------------------------------------
// Wire format: fixed header + n_args payload arrays.
//   MsgHeader | {ArgHeader | bytes} * n_args
// Same-architecture cluster assumed (host byte order), like the reference van.
// ---------------------------------------------------------------------------

enum class PsfType : int32_t {
  // control plane
  kRegister = 0,       // node -> scheduler: {role, id, listen addr}
  kAddressBook = 1,    // scheduler -> node: server addresses
  kBarrier = 2,        // worker -> scheduler -> worker
  kShutdown = 3,
  kAck = 4,
  kHeartbeat = 5,      // server -> scheduler keepalive (reference van.cc:27,569)
  kQueryServers = 6,   // any -> scheduler: current address book + liveness
  kServerStats = 7,    // worker -> server: update/snapshot/restore counters
  // dense
  kDensePush = 10,
  kDensePull = 11,
  kDDPushPull = 12,
  // sparse (2D row-partitioned)
  kSparsePush = 20,
  kSparsePull = 21,
  kSDPushPull = 22,
  kSSPushPull = 23,
  // param management
  kParamInit = 30,
  kParamClear = 31,
  kParamSave = 32,
  kParamLoad = 33,
  kParamAssign = 34,       // raw value assignment (init push, no optimizer)
  kParamAssignRows = 35,
  // bounded-staleness cache table (reference ps-lite psf/cachetable.h:22-43)
  kSyncEmbedding = 40,
  kPushEmbedding = 41,
  kPushSyncEmbedding = 42,
  // arbitrary-length data blobs (reference PushData/PullData)
  kDataPush = 50,
  kDataPull = 51,
  // hetu-elastic: live membership changes (docs/FAULT_TOLERANCE.md
  // "Elastic membership"). Scheduler-side two-phase resize handshake:
  kProposeResize = 60,  // coordinator -> scheduler: pending world + capacity
  kResizeState = 61,    // any -> scheduler: world/pending/drain progress
  kCommitResize = 62,   // worker -> scheduler: drain barrier (parks until
                        // the coordinator finishes or aborts)
  kFinishResize = 63,   // coordinator -> scheduler: flip/abort the world
  kResizeLog = 64,      // any -> scheduler: committed era history
  // server-side membership surface:
  kListParams = 65,       // any -> server: param key/meta inventory
  kSetWorldVersion = 66,  // coordinator -> server: arm stale-epoch rejection
  // hetusave (docs/FAULT_TOLERANCE.md "Coordinated job snapshots"):
  // coordinator -> server inside the drain window: write one epoch-stamped
  // full-state snapshot NOW and reply {version, counter, updates, epoch}
  kSnapshotNow = 67,
  // hetutrail (docs/OBSERVABILITY.md pillar 5): deterministic test lever —
  // delay the server's NEXT optimizer apply by i64[ms] (inert without
  // HETU_TEST_MODE), so critical-path and straggler tests have a knowable
  // slow leg to attribute
  kTestSlowApply = 70,
};

struct MsgHeader {
  int32_t type = 0;       // PsfType
  int32_t tensor_id = 0;  // node_name in the reference C API
  uint64_t req_id = 0;    // per-client monotonic; servers dedup resends on it
  int32_t n_args = 0;
  int32_t flags = 0;
  int32_t client_id = -1; // rank*2 + channel (bulk=0/fast=1) — the server's
                          // resend-dedup slot key; ids must be monotonic
                          // PER client_id stream. -1 = untracked
  int32_t world_ver = 0;  // hetu-elastic membership epoch stamp: servers
                          // armed via kSetWorldVersion reject a mismatched
                          // non-zero stamp (a straggler that missed a
                          // resize commit). 0 = unversioned legacy
                          // traffic, always accepted. Occupies the former
                          // pad slot — the wire layout is unchanged.
};

enum class ArgType : int32_t { kF32 = 0, kI64 = 1, kF64 = 2, kBytes = 3, kI32 = 4, kU64 = 5,
                               // hetuq: blockwise-quantized f32 payload
                               // (int8 + one f32 scale per block)
                               kQI8 = 6 };

struct ArgHeader {
  int32_t dtype = 0;
  int32_t pad = 0;   // CRC32C of the arg bytes when the message carries
                     // kFlagCrc (crc_field below; wire layout unchanged —
                     // the slot was always there, always zero before)
  uint64_t nbytes = 0;
};

// One payload argument: a typed, sized view (owning buffer on receive).
struct Arg {
  ArgType dtype = ArgType::kBytes;
  uint32_t wire_crc = 0;  // ArgHeader.pad as received (never serialized
                          // from here; send_msg recomputes from buf)
  std::vector<uint8_t> buf;

  Arg() = default;
  Arg(ArgType t, const void* data, size_t nbytes) : dtype(t) {
    buf.resize(nbytes);
    if (nbytes) std::memcpy(buf.data(), data, nbytes);
  }
  static Arg f32(const float* p, size_t n) { return Arg(ArgType::kF32, p, n * 4); }
  static Arg i64(const int64_t* p, size_t n) { return Arg(ArgType::kI64, p, n * 8); }
  static Arg u64(const uint64_t* p, size_t n) { return Arg(ArgType::kU64, p, n * 8); }
  static Arg i32(const int32_t* p, size_t n) { return Arg(ArgType::kI32, p, n * 4); }
  static Arg f64(const double* p, size_t n) { return Arg(ArgType::kF64, p, n * 8); }
  static Arg str(const std::string& s) { return Arg(ArgType::kBytes, s.data(), s.size()); }

  const float* as_f32() const { return reinterpret_cast<const float*>(buf.data()); }
  const int64_t* as_i64() const { return reinterpret_cast<const int64_t*>(buf.data()); }
  const uint64_t* as_u64() const { return reinterpret_cast<const uint64_t*>(buf.data()); }
  const int32_t* as_i32() const { return reinterpret_cast<const int32_t*>(buf.data()); }
  const double* as_f64() const { return reinterpret_cast<const double*>(buf.data()); }
  float* mut_f32() { return reinterpret_cast<float*>(buf.data()); }
  std::string as_str() const { return std::string(buf.begin(), buf.end()); }
  size_t n_f32() const { return buf.size() / 4; }
  size_t n_i64() const { return buf.size() / 8; }
  size_t size() const { return buf.size(); }
};

struct Message {
  MsgHeader head;
  std::vector<Arg> args;
};

// ---------------------------------------------------------------------------
// hetuq wire container (ArgType::kQI8): a quantized stand-in for an f32
// value arg. Layout: u64 n_values | u64 block | f32 scales[ceil(n/block)]
// | int8 q[n]. Sparse row payloads use block == row width (one scale per
// row); dense payloads use a fixed block (kQuantWireBlock). Scheme:
// symmetric linear — scale = max(|block|)/127, q = lrintf(v/scale) clipped
// to [-127,127]; an all-zero block stores scale 0 (exact zeros). Matched
// bit-for-bit by hetu_tpu.comm_quant.np_quantize_blocks.
// ---------------------------------------------------------------------------

constexpr size_t kQuantWireBlock = 256;
// request-header flag: "quantize the value payloads of YOUR response"
// (pull rows / push-pull return legs). Responses self-describe via the
// arg dtype, so no response-side flag exists; flags == -1 stays the error
// marker.
constexpr int32_t kFlagQuantRsp = 1;
// hetuchaos transport hardening (docs/FAULT_TOLERANCE.md "Chaos testing &
// transport hardening"): "my payload args carry CRC32C checksums in their
// ArgHeader.pad slot — verify them, and checksum your response the same
// way". Per-request negotiation instead of a process knob so (a) a CRC-off
// client against a new server costs the server nothing, and (b) a bench
// A/B toggles it live on the singleton worker (SetPsCrc). Every flags
// check must exclude the -1 error marker first (it has all bits set).
constexpr int32_t kFlagCrc = 2;

struct QI8Header {
  uint64_t n = 0;
  uint64_t block = 0;
};

inline Arg make_qi8_arg(const float* vals, size_t n, size_t block) {
  if (block == 0) block = 1;
  const size_t nb = (n + block - 1) / block;
  Arg a;
  a.dtype = ArgType::kQI8;
  a.buf.resize(sizeof(QI8Header) + nb * 4 + n);
  QI8Header h{n, block};
  std::memcpy(a.buf.data(), &h, sizeof(h));
  float* scales = reinterpret_cast<float*>(a.buf.data() + sizeof(h));
  int8_t* q = reinterpret_cast<int8_t*>(a.buf.data() + sizeof(h) + nb * 4);
  for (size_t b = 0; b < nb; ++b) {
    const size_t lo = b * block, hi = std::min(n, lo + block);
    float amax = 0.0f;
    for (size_t i = lo; i < hi; ++i) {
      // per-element: NaN compares false against everything, so a plain
      // running max would silently drop it and quantize garbage. Fail at
      // the SENDER with a numeric diagnosis instead — letting a NaN/Inf
      // through would either corrupt the scale (receiver rejects it as
      // "malformed scale", a misleading wire-corruption error for what is
      // a numeric-gradient problem) or quantize NaN to an arbitrary int.
      if (!std::isfinite(vals[i]))
        throw std::runtime_error(
            "hetuq: non-finite value at element " + std::to_string(i) +
            " of quantized payload — the gradient/value itself is NaN/Inf");
      const float av = std::fabs(vals[i]);
      if (av > amax) amax = av;
    }
    const float scale = amax / 127.0f;
    scales[b] = scale;
    const float inv = scale > 0.0f ? scale : 1.0f;
    for (size_t i = lo; i < hi; ++i) {
      long v = lrintf(vals[i] / inv);
      if (v > 127) v = 127;
      if (v < -127) v = -127;
      q[i] = static_cast<int8_t>(v);
    }
  }
  return a;
}

// Validate + dequantize a kQI8 arg into `out`. `expect_n` > 0 enforces the
// element count the handler derived from its OTHER args (row count x
// width, shard length): a mismatch, a torn container, or a non-finite /
// negative scale is a protocol error — the server answers with an error
// response instead of applying garbage.
inline void dequant_qi8(const Arg& a, std::vector<float>* out,
                        size_t expect_n) {
  if (a.buf.size() < sizeof(QI8Header))
    throw std::runtime_error("quantized arg: truncated header");
  QI8Header h;
  std::memcpy(&h, a.buf.data(), sizeof(h));
  if (h.block == 0 || h.block > (1u << 20))
    throw std::runtime_error("quantized arg: bad block size " +
                             std::to_string(h.block));
  const size_t nb = (h.n + h.block - 1) / h.block;
  if (a.buf.size() != sizeof(QI8Header) + nb * 4 + h.n)
    throw std::runtime_error(
        "quantized arg: length mismatch (" + std::to_string(a.buf.size()) +
        " bytes for " + std::to_string(h.n) + " values x block " +
        std::to_string(h.block) + ")");
  if (expect_n > 0 && h.n != expect_n)
    throw std::runtime_error(
        "quantized arg: carries " + std::to_string(h.n) + " values, " +
        std::to_string(expect_n) + " expected");
  const float* scales =
      reinterpret_cast<const float*>(a.buf.data() + sizeof(h));
  const int8_t* q =
      reinterpret_cast<const int8_t*>(a.buf.data() + sizeof(h) + nb * 4);
  for (size_t b = 0; b < nb; ++b)
    if (!(scales[b] >= 0.0f) || !std::isfinite(scales[b]))
      throw std::runtime_error(
          "quantized arg: malformed scale in block " + std::to_string(b));
  out->resize(h.n);
  for (size_t i = 0; i < h.n; ++i)
    (*out)[i] = static_cast<float>(q[i]) * scales[i / h.block];
}

// Element count of an f32-or-quantized value arg (what n_f32 is to kF32).
inline size_t value_count(const Arg& a) {
  if (a.dtype != ArgType::kQI8) return a.n_f32();
  if (a.buf.size() < sizeof(QI8Header)) return 0;
  QI8Header h;
  std::memcpy(&h, a.buf.data(), sizeof(h));
  return h.n;
}

// ---------------------------------------------------------------------------
// End-to-end payload integrity: CRC32C (Castagnoli) over every arg's bytes,
// carried in the ArgHeader.pad slot when the message's kFlagCrc is set.
// Covers the path TCP's 16-bit checksum does not meaningfully protect —
// multi-MB gradient payloads through proxies/userland copies — and gives the
// chaos engine's corrupt-bytes fault a detector to prove. The 32-byte
// MsgHeader itself is NOT covered (that would change the wire layout); a
// corrupted header surfaces as an unknown-psf/length error instead.
// ---------------------------------------------------------------------------

// Shared Castagnoli byte/slicing tables: t[0] is the classic byte-at-a-
// time table (also the seed for the interleave shift tables below),
// t[1..7] extend it to slicing-by-8.
inline const uint32_t (*crc32c_tables())[256] {
  static const auto* tables = [] {
    static uint32_t t[8][256];
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c >> 1) ^ (0x82F63B78u & (~(c & 1u) + 1u));
      t[0][i] = c;
    }
    for (uint32_t i = 0; i < 256; ++i)
      for (int s = 1; s < 8; ++s)
        t[s][i] = (t[s - 1][i] >> 8) ^ t[0][t[s - 1][i] & 0xFF];
    return &t;
  }();
  return *tables;
}

// Software path: slicing-by-8 (8 x 256 tables, 8 bytes per iteration,
// ~GB/s) — a plain byte-at-a-time table loop measured 35%/step on the
// bench cell, blowing the <= 2% hardening budget by itself.
inline uint32_t crc32c_sw(const uint8_t* p, size_t n, uint32_t crc) {
  const auto* t = crc32c_tables();
  crc = ~crc;
  while (n >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    v ^= crc;
    crc = t[7][v & 0xFF] ^ t[6][(v >> 8) & 0xFF] ^ t[5][(v >> 16) & 0xFF] ^
          t[4][(v >> 24) & 0xFF] ^ t[3][(v >> 32) & 0xFF] ^
          t[2][(v >> 40) & 0xFF] ^ t[1][(v >> 48) & 0xFF] ^
          t[0][(v >> 56) & 0xFF];
    p += 8;
    n -= 8;
  }
  while (n--) crc = t[0][(crc ^ *p++) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// Zero-extension operator for the interleaved hardware path below:
// shift[i][b] tables such that 4 lookups advance a raw (un-inverted) CRC
// register past kCrcBlk zero bytes. Feeding one zero byte to the raw
// register is linear in the register (crc' = t0[crc & 0xFF] ^ (crc >> 8)),
// so the shift-by-N operator is built from the 1-byte table by doubling —
// log2(kCrcBlk) squarings of a 4x256 table, a one-time lazy init.
constexpr size_t kCrcBlk = 1024;  // bytes per interleave stream segment

inline uint32_t crc32c_shift_blk(uint32_t x);

inline const uint32_t (*crc32c_shift_tables())[256] {
  static const auto* tables = [] {
    static uint32_t t[4][256];
    const auto* byte_t = crc32c_tables();
    // shift-by-1-byte operator applied to each basis byte of the register
    for (uint32_t b = 0; b < 256; ++b)
      for (int i = 0; i < 4; ++i) {
        uint32_t x = b << (8 * i);
        t[i][b] = byte_t[0][x & 0xFF] ^ (x >> 8);
      }
    auto apply = [](uint32_t x) {
      return t[0][x & 0xFF] ^ t[1][(x >> 8) & 0xFF] ^
             t[2][(x >> 16) & 0xFF] ^ t[3][(x >> 24) & 0xFF];
    };
    for (size_t len = 1; len < kCrcBlk; len *= 2) {   // double: N -> 2N
      uint32_t sq[4][256];
      for (uint32_t b = 0; b < 256; ++b)
        for (int i = 0; i < 4; ++i) sq[i][b] = apply(apply(b << (8 * i)));
      std::memcpy(t, sq, sizeof(sq));
    }
    return &t;
  }();
  return *tables;
}

// Advance a raw CRC register past kCrcBlk zero bytes (4 table lookups).
inline uint32_t crc32c_shift_blk(uint32_t x) {
  const auto* t = crc32c_shift_tables();
  return t[0][x & 0xFF] ^ t[1][(x >> 8) & 0xFF] ^ t[2][(x >> 16) & 0xFF] ^
         t[3][(x >> 24) & 0xFF];
}

#if defined(__x86_64__)
// Hardware path (x86-64 only: __builtin_ia32_crc32di does not exist in
// 32-bit mode, where the software path below serves instead): the
// SSE4.2 crc32 instruction implements exactly the
// Castagnoli polynomial, but its 3-cycle latency serializes a single
// register chain at ~6 GB/s — still ~3%/step on the bench cell. Three
// independent streams hide that latency (~3x); each 3*kCrcBlk block is
// merged with the zero-extension tables (crc(A||B) = shift(crcA) ^ crcB
// by linearity). Runtime-selected so the same .so runs on older CPUs.
__attribute__((target("sse4.2"))) inline uint32_t crc32c_hw(
    const uint8_t* p, size_t n, uint32_t crc) {
  crc = ~crc;
  while (n >= 3 * kCrcBlk) {
    uint32_t a = crc, b = 0, c = 0;
    const uint8_t* pb = p + kCrcBlk;
    const uint8_t* pc = p + 2 * kCrcBlk;
    for (size_t i = 0; i < kCrcBlk; i += 8) {
      uint64_t va, vb, vc;
      std::memcpy(&va, p + i, 8);
      std::memcpy(&vb, pb + i, 8);
      std::memcpy(&vc, pc + i, 8);
      a = static_cast<uint32_t>(__builtin_ia32_crc32di(a, va));
      b = static_cast<uint32_t>(__builtin_ia32_crc32di(b, vb));
      c = static_cast<uint32_t>(__builtin_ia32_crc32di(c, vc));
    }
    crc = crc32c_shift_blk(crc32c_shift_blk(a) ^ b) ^ c;
    p += 3 * kCrcBlk;
    n -= 3 * kCrcBlk;
  }
  while (n >= 8) {
    uint64_t v;
    std::memcpy(&v, p, 8);
    crc = static_cast<uint32_t>(__builtin_ia32_crc32di(crc, v));
    p += 8;
    n -= 8;
  }
  while (n--) crc = __builtin_ia32_crc32qi(crc, *p++);
  return ~crc;
}

inline bool crc32c_has_hw() {
  static const bool ok = __builtin_cpu_supports("sse4.2");
  return ok;
}
#endif

inline uint32_t crc32c(const uint8_t* p, size_t n, uint32_t crc = 0) {
#if defined(__x86_64__)
  if (crc32c_has_hw()) return crc32c_hw(p, n, crc);
#endif
  return crc32c_sw(p, n, crc);
}

// The on-wire CRC field: 0 means "sender did not checksum" (every pre-CRC
// message — pad was always written as 0), so a genuinely-zero CRC maps to 1.
// Collides 0 and 1 onto one value; detection probability is unchanged at
// the 2^-32 scale.
inline uint32_t crc_field(const uint8_t* p, size_t n) {
  const uint32_t c = crc32c(p, n);
  return c ? c : 1u;
}

// Verify every arg of a kFlagCrc message against its carried checksum.
// Returns true when all match; fills *err with a diagnosis otherwise.
inline bool verify_msg_crc(const Message& m, std::string* err) {
  for (size_t i = 0; i < m.args.size(); ++i) {
    const Arg& a = m.args[i];
    if (a.wire_crc == 0) continue;  // sender predates CRC / disabled leg
    const uint32_t got = crc_field(a.buf.data(), a.buf.size());
    if (got != a.wire_crc) {
      if (err)
        *err = "arg " + std::to_string(i) + " (" +
               std::to_string(a.buf.size()) + " bytes) checksum " +
               std::to_string(got) + " != carried " +
               std::to_string(a.wire_crc);
      return false;
    }
  }
  return true;
}

// The single truthy-env convention shared with the Python side
// (resilience.env_truthy): destructive test hooks are inert without it.
// Lives here (not server.h) so the worker's chaos arming shares it.
inline bool env_test_mode() {
  const char* v = std::getenv("HETU_TEST_MODE");
  if (!v) return false;
  std::string s(v);
  for (auto& c : s) c = static_cast<char>(std::tolower(c));
  return s == "1" || s == "true" || s == "yes" || s == "on";
}

// ---------------------------------------------------------------------------
// Socket helpers
// ---------------------------------------------------------------------------

inline void send_all(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t k = ::send(fd, p, n, MSG_NOSIGNAL);
    if (k <= 0) throw std::runtime_error("hetups: send failed (peer closed?)");
    p += k;
    n -= static_cast<size_t>(k);
  }
}

inline bool recv_all(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t k = ::recv(fd, p, n, 0);
    if (k <= 0) return false;  // closed or error
    p += k;
    n -= static_cast<size_t>(k);
  }
  return true;
}

// Sends header+args as one buffered write (one syscall for small messages).
// kFlagCrc messages (and only those — flags == -1 error responses never
// carry it) get a CRC32C per arg in the ArgHeader.pad slot.
// `corrupt_arg`/`corrupt_off` are the chaos engine's wire-corruption
// lever: flip one byte of that arg's payload AFTER the checksums are
// computed — i.e. on the wire, exactly where a real bit-flip lands, so
// the receiver's CRC is what must catch it (csrc/ps/chaos.h kCorrupt).
inline void send_msg(int fd, const Message& m,
                     size_t corrupt_arg = static_cast<size_t>(-1),
                     size_t corrupt_off = 0) {
  MsgHeader h = m.head;
  h.n_args = static_cast<int32_t>(m.args.size());
  const bool crc = h.flags != -1 && (h.flags & kFlagCrc);
  size_t total = sizeof(MsgHeader);
  for (const auto& a : m.args) total += sizeof(ArgHeader) + a.buf.size();
  std::vector<uint8_t> out(total);
  uint8_t* p = out.data();
  std::memcpy(p, &h, sizeof(h));
  p += sizeof(h);
  for (size_t i = 0; i < m.args.size(); ++i) {
    const Arg& a = m.args[i];
    ArgHeader ah{static_cast<int32_t>(a.dtype), 0, a.buf.size()};
    if (crc)
      ah.pad = static_cast<int32_t>(crc_field(a.buf.data(), a.buf.size()));
    std::memcpy(p, &ah, sizeof(ah));
    p += sizeof(ah);
    if (!a.buf.empty()) std::memcpy(p, a.buf.data(), a.buf.size());
    if (i == corrupt_arg && !a.buf.empty())
      p[corrupt_off % a.buf.size()] ^= 0xFF;
    p += a.buf.size();
  }
  send_all(fd, out.data(), out.size());
}

inline bool recv_msg(int fd, Message* m) {
  if (!recv_all(fd, &m->head, sizeof(MsgHeader))) return false;
  m->args.clear();
  m->args.resize(m->head.n_args);
  for (auto& a : m->args) {
    ArgHeader ah;
    if (!recv_all(fd, &ah, sizeof(ah))) return false;
    a.dtype = static_cast<ArgType>(ah.dtype);
    a.wire_crc = static_cast<uint32_t>(ah.pad);
    a.buf.resize(ah.nbytes);
    if (ah.nbytes && !recv_all(fd, a.buf.data(), ah.nbytes)) return false;
  }
  return true;
}

// Bound every blocking recv so a dead peer surfaces as an error instead of a
// hang (the role of the reference's resender timeouts, resender.h:116).
inline void set_recv_timeout(int fd, int ms) {
  if (ms <= 0) return;
  timeval tv{ms / 1000, (ms % 1000) * 1000};
  ::setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
}

inline int env_int_or(const char* name, int dflt) {
  const char* v = ::getenv(name);
  return v && *v ? std::atoi(v) : dflt;
}

// hetutrail: ONE monotonic-µs clock for every trail span on both sides of
// the wire. CLOCK_MONOTONIC (what steady_clock reads on Linux) counts from
// boot and is shared by every process on a host, so client and server spans
// are directly comparable without wall-clock re-anchoring — immune to the
// NTP steps that motivated the PR 4 req_id epoch machinery.
inline int64_t trail_mono_us() {
  return std::chrono::duration_cast<std::chrono::microseconds>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

inline int listen_on(const std::string& host, int port, int backlog = 128) {
  int fd = ::socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) throw std::runtime_error("hetups: socket() failed");
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(static_cast<uint16_t>(port));
  addr.sin_addr.s_addr = host.empty() ? INADDR_ANY : ::inet_addr(host.c_str());
  if (::bind(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) != 0)
    throw std::runtime_error("hetups: bind failed on port " + std::to_string(port));
  if (::listen(fd, backlog) != 0) throw std::runtime_error("hetups: listen failed");
  return fd;
}

// Resolve a dotted-quad IP or hostname (reference vans resolve via
// network_utils.h; DMLC_PS_ROOT_URI may be a hostname in cluster ymls).
inline in_addr_t resolve_host(const std::string& host) {
  in_addr_t ip = ::inet_addr(host.c_str());
  if (ip != INADDR_NONE) return ip;
  addrinfo hints{}, *res = nullptr;
  hints.ai_family = AF_INET;
  hints.ai_socktype = SOCK_STREAM;
  if (::getaddrinfo(host.c_str(), nullptr, &hints, &res) != 0 || !res)
    throw std::runtime_error("hetups: cannot resolve host '" + host + "'");
  in_addr_t out =
      reinterpret_cast<sockaddr_in*>(res->ai_addr)->sin_addr.s_addr;
  ::freeaddrinfo(res);
  return out;
}

// Connect with retry — nodes race the scheduler/servers at startup
// (the reference's van retries similarly via resender.h timeouts).
inline int connect_to(const std::string& host, int port, int retries = 600,
                      int wait_ms = 100) {
  in_addr_t ip = resolve_host(host);
  for (int i = 0; i < retries; ++i) {
    int fd = ::socket(AF_INET, SOCK_STREAM, 0);
    if (fd < 0) throw std::runtime_error("hetups: socket() failed");
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_port = htons(static_cast<uint16_t>(port));
    addr.sin_addr.s_addr = ip;
    if (::connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) == 0) {
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      return fd;
    }
    ::close(fd);
    struct timespec ts = {wait_ms / 1000, (wait_ms % 1000) * 1000000L};
    nanosleep(&ts, nullptr);
  }
  throw std::runtime_error("hetups: connect to " + host + ":" +
                           std::to_string(port) + " timed out");
}

// Connection-thread registry that reaps finished threads as new connections
// arrive: short-lived connections (scheduler liveness queries, worker
// reconnects) would otherwise accumulate joinable thread handles for the
// life of the process.
class ConnThreads {
 public:
  template <typename F>
  void spawn(F&& f) {
    reap();
    auto done = std::make_shared<std::atomic<bool>>(false);
    std::lock_guard<std::mutex> g(mu_);
    threads_.push_back(
        {std::thread([fn = std::forward<F>(f), done]() mutable {
           fn();
           *done = true;
         }),
         done});
  }

  void reap() {
    std::lock_guard<std::mutex> g(mu_);
    for (auto it = threads_.begin(); it != threads_.end();) {
      if (it->done->load()) {
        it->t.join();
        it = threads_.erase(it);
      } else {
        ++it;
      }
    }
  }

  void join_all() {
    std::lock_guard<std::mutex> g(mu_);
    for (auto& e : threads_)
      if (e.t.joinable()) e.t.join();
    threads_.clear();
  }

 private:
  struct Entry {
    std::thread t;
    std::shared_ptr<std::atomic<bool>> done;
  };
  std::mutex mu_;
  std::vector<Entry> threads_;
};

// A connection whose requests may be issued from many threads: writes are
// serialized by a mutex; responses are matched by req_id by a reader thread.
class Conn {
 public:
  explicit Conn(int fd) : fd_(fd) {}
  ~Conn() {
    if (fd_ >= 0) ::close(fd_);
  }
  Conn(const Conn&) = delete;

  void send(const Message& m, size_t corrupt_arg = static_cast<size_t>(-1),
            size_t corrupt_off = 0) {
    std::lock_guard<std::mutex> g(send_mu_);
    send_msg(fd_, m, corrupt_arg, corrupt_off);
  }
  bool recv(Message* m) { return recv_msg(fd_, m); }
  int fd() const { return fd_; }
  void close() {
    if (fd_ >= 0) {
      ::shutdown(fd_, SHUT_RDWR);
      ::close(fd_);
      fd_ = -1;
    }
  }

 private:
  int fd_;
  std::mutex send_mu_;
};

}  // namespace hetups
