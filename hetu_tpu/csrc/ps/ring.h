// Host-side ring allreduce/allgather over raw TCP (reference
// src/communication/c_communication_nthread.cc:32,145-506 — the legacy
// multi-threaded ZMQ REQ/REP ring used for CPU data parallelism without
// NCCL). Same capability, redesigned on this van's socket helpers: each rank
// listens at base_port+rank, connects to its right neighbor, and runs the
// classic 2-phase chunked ring (N-1 scatter-reduce steps, N-1 allgather
// steps). Every step sends on a helper thread while receiving on the caller
// thread, so a full ring of simultaneous large sends cannot deadlock on
// socket buffers (the role the reference's worker threads play).
//
// On TPU the real DP path is GSPMD psum over ICI; this exists for API/
// capability parity and for host-only (accelerator-less) workers.
#ifndef HETUPS_RING_H_
#define HETUPS_RING_H_

#include <poll.h>
#include <sys/socket.h>

#include <cstring>
#include <stdexcept>
#include <string>
#include <thread>
#include <vector>

#include "net.h"

namespace hetups {

class RingComm {
 public:
  RingComm(int rank, int nranks, const std::string& host, int base_port)
      : rank_(rank), n_(nranks) {
    if (n_ < 1) throw std::runtime_error("ring: nranks must be >= 1");
    if (n_ == 1) return;
    // every blocking socket op is bounded so a dead peer surfaces as an
    // error, never a hang (same policy as the PS van, net.h:183)
    const int timeout_ms = env_int_or("DMLC_PS_RING_TIMEOUT_MS", 60000);
    try {
      listen_fd_ = listen_on("", base_port + rank_);
      // accept the left neighbor while connecting to the right one: the
      // ring is a cycle, so doing either first on every rank would deadlock
      std::exception_ptr acc_err;
      std::thread acc([&] {
        try {
          recv_fd_ = accept_with_timeout(listen_fd_, timeout_ms);
        } catch (...) {
          acc_err = std::current_exception();
        }
      });
      try {
        send_fd_ = connect_to(host, base_port + (rank_ + 1) % n_);
      } catch (...) {
        acc.join();  // bounded: accept_with_timeout gives up on its own
        throw;
      }
      acc.join();
      if (acc_err) std::rethrow_exception(acc_err);
      set_recv_timeout(recv_fd_, timeout_ms);
      timeval tv{timeout_ms / 1000, (timeout_ms % 1000) * 1000};
      ::setsockopt(send_fd_, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
    } catch (...) {
      close_all();
      throw;
    }
  }

  ~RingComm() { close_all(); }
  RingComm(const RingComm&) = delete;

  int rank() const { return rank_; }
  int nranks() const { return n_; }

  // In-place sum-allreduce (reference _RingAllreduce_*_nthread :217/:388).
  void allreduce_sum(float* data, size_t n) {
    if (n_ == 1 || n == 0) return;
    std::vector<size_t> start(n_ + 1);
    for (int i = 0; i <= n_; ++i)
      start[i] = n * static_cast<size_t>(i) / n_;
    auto seg_len = [&](int s) { return start[s + 1] - start[s]; };
    auto mod = [&](int x) { return ((x % n_) + n_) % n_; };
    std::vector<float> buf((n + n_ - 1) / n_);  // ceil: the largest segment

    // phase 1: scatter-reduce — after step s, segment (rank-s-1) holds the
    // partial sum of s+2 ranks; after n-1 steps each rank owns the full sum
    // of segment (rank+1)
    for (int s = 0; s < n_ - 1; ++s) {
      int snd = mod(rank_ - s), rcv = mod(rank_ - s - 1);
      exchange(data + start[snd], seg_len(snd) * 4,
               buf.data(), seg_len(rcv) * 4);
      float* dst = data + start[rcv];
      for (size_t i = 0; i < seg_len(rcv); ++i) dst[i] += buf[i];
    }
    // phase 2: allgather — circulate the completed segments
    for (int s = 0; s < n_ - 1; ++s) {
      int snd = mod(rank_ + 1 - s), rcv = mod(rank_ - s);
      exchange(data + start[snd], seg_len(snd) * 4,
               data + start[rcv], seg_len(rcv) * 4);
    }
  }

  // out[(r*n_per) .. ] = rank r's in (reference DL_Communicate allgather).
  void allgather(const float* in, float* out, size_t n_per) {
    std::memcpy(out + static_cast<size_t>(rank_) * n_per, in, n_per * 4);
    if (n_ == 1) return;
    auto mod = [&](int x) { return ((x % n_) + n_) % n_; };
    for (int s = 0; s < n_ - 1; ++s) {
      int snd = mod(rank_ - s), rcv = mod(rank_ - s - 1);
      exchange(out + static_cast<size_t>(snd) * n_per, n_per * 4,
               out + static_cast<size_t>(rcv) * n_per, n_per * 4);
    }
  }

  void barrier() {
    float token = 0.0f;
    allreduce_sum(&token, 1);
  }

 private:
  static int accept_with_timeout(int listen_fd, int timeout_ms) {
    pollfd pfd{listen_fd, POLLIN, 0};
    int r = ::poll(&pfd, 1, timeout_ms);
    if (r == 0)
      throw std::runtime_error("ring: timed out waiting for left neighbor");
    if (r < 0) throw std::runtime_error("ring: poll failed");
    int fd = ::accept(listen_fd, nullptr, nullptr);
    if (fd < 0) throw std::runtime_error("ring: accept failed");
    return fd;
  }

  void close_all() {
    if (send_fd_ >= 0) ::close(send_fd_);
    if (recv_fd_ >= 0) ::close(recv_fd_);
    if (listen_fd_ >= 0) ::close(listen_fd_);
    send_fd_ = recv_fd_ = listen_fd_ = -1;
  }

  // Concurrent send-to-right / recv-from-left: the send rides a helper
  // thread so a ring of blocking sends can't wedge on full socket buffers.
  // Both directions carry SO_SNDTIMEO/SO_RCVTIMEO, so a collapsed ring
  // (dead or wedged neighbor) errors out instead of hanging the join.
  void exchange(const void* send_buf, size_t send_bytes,
                void* recv_buf, size_t recv_bytes) {
    std::exception_ptr send_err;
    std::thread t([&] {
      try {
        send_all(send_fd_, send_buf, send_bytes);
      } catch (...) {
        send_err = std::current_exception();
      }
    });
    bool ok = recv_all(recv_fd_, recv_buf, recv_bytes);
    t.join();
    if (send_err) std::rethrow_exception(send_err);
    if (!ok)
      throw std::runtime_error("ring: left neighbor closed or timed out");
  }

  int rank_;
  int n_;
  int listen_fd_ = -1;
  int send_fd_ = -1;
  int recv_fd_ = -1;
};

}  // namespace hetups

#endif  // HETUPS_RING_H_
