// Server-side parameter store + optimizers.
//
// Capability parity with the reference's ps-lite server:
//  - Key -> Param/Param2D/CacheTable store with shared-mutex read/write guards
//    (reference include/ps/server/PSFHandle.h:24, param.h).
//  - Server-side optimizers SGD/Momentum/Nesterov/AdaGrad/Adam with
//    ApplyDense/ApplySparse/ApplyCache and version increment on cache apply
//    (reference include/ps/server/optimizer.h:15-75).
//  - Initializers evaluated ON the server (reference initializers.py:28-39
//    init_on_ps -> InitTensor RPC).
#pragma once

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <memory>
#include <mutex>
#include <random>
#include <shared_mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace hetups {

enum class ParamKind : int32_t { kDense = 0, kSparse = 1, kCacheTable = 2 };
enum class InitType : int32_t { kConstant = 0, kUniform = 1, kNormal = 2, kTruncatedNormal = 3 };
enum class OptType : int32_t { kSGD = 0, kMomentum = 1, kNesterov = 2, kAdaGrad = 3, kAdam = 4 };

// One stored parameter shard. Dense params are (len) vectors; sparse params
// and cache tables are (rows x width) row-major matrices, where `rows` is
// this server's row range after partitioning.
struct Param {
  ParamKind kind = ParamKind::kDense;
  size_t len = 0;    // dense: total f32s on this shard; sparse: rows*width
  size_t rows = 0;   // sparse/cache only
  size_t width = 0;  // sparse/cache only
  std::vector<float> data;

  // optimizer config + slots
  OptType otype = OptType::kSGD;
  std::vector<float> lrs;     // lrs[0] = lr; adam: lr,beta1,beta2,eps
  std::vector<float> accum;   // momentum buffer / adagrad accum / adam m
  std::vector<float> accum2;  // adam v
  uint64_t step = 0;          // adam bias-correction step

  // cache-table row versions (reference embedding.h:19-40 Line::version);
  // signed: the CLIENT uses -1 as the "never synced, always pull" sentinel
  // (reference PSFhandle_embedding.cc:49); server rows start at 0
  std::vector<int64_t> versions;

  // seq of the last applied write (guarded by mu, stamped by server.h's
  // mark lambda): take_snapshot compares it against the seq each shard
  // file was saved at to decide whether a client's last write made it
  // into the snapshot — the dedup-ledger provenance filter
  uint64_t last_write_seq = 0;

  mutable std::shared_mutex mu;
};

inline void init_values(std::vector<float>* out, InitType itype, double a,
                        double b, uint64_t seed) {
  std::mt19937_64 gen(seed);
  switch (itype) {
    case InitType::kConstant:
      std::fill(out->begin(), out->end(), static_cast<float>(a));
      break;
    case InitType::kUniform: {
      std::uniform_real_distribution<float> d(static_cast<float>(a),
                                              static_cast<float>(b));
      for (auto& v : *out) v = d(gen);
      break;
    }
    case InitType::kNormal: {
      std::normal_distribution<float> d(static_cast<float>(a),
                                        static_cast<float>(b));
      for (auto& v : *out) v = d(gen);
      break;
    }
    case InitType::kTruncatedNormal: {
      std::normal_distribution<float> d(static_cast<float>(a),
                                        static_cast<float>(b));
      for (auto& v : *out) {
        float x;
        do {
          x = d(gen);
        } while (std::fabs(x - a) > 2.0f * b);
        v = x;
      }
      break;
    }
  }
}

// ---------------------------------------------------------------------------
// Optimizer application. `grad` covers `n` contiguous f32s starting at
// parameter offset `off` (dense) or one row (sparse/cache).
// Reference semantics (optimizer.h): SGD on the server applies raw `+= grad`
// because the worker pre-scales by -lr (ParameterServerCommunicate.py:24-25);
// stateful optimizers keep slots server-side.
//
// begin_update() MUST be called once per logical request before one-or-more
// apply_update() calls: it advances Adam's bias-correction step once per
// request (not once per row — a sparse push of N rows is ONE update).
// ---------------------------------------------------------------------------
inline void begin_update(Param& p) {
  if (p.otype == OptType::kAdam) p.step += 1;
}

// Per-REQUEST optimizer overrides, carried as an optional trailing f32 arg
// [lr, l2reg, weight_decay] on push messages. Lets workers honor lr
// schedules on stateful server optimizers (the init-time p.lrs[0] is only a
// fallback) and apply l2 regularization / decoupled weight decay against
// the CURRENT server value under the param lock — matching the device
// path's grad + l2reg*w (optimizer.py apply_gradient) and AdamW's
// w -= lr*wd*w. lr < 0 means "not provided".
struct UpdateOpts {
  float lr = -1.0f;
  float l2reg = 0.0f;
  float weight_decay = 0.0f;
};

inline void apply_update(Param& p, size_t off, const float* grad, size_t n,
                         const UpdateOpts& uo = {}) {
  float* w = p.data.data() + off;
  const float l2 = uo.l2reg;
  switch (p.otype) {
    case OptType::kSGD: {
      // grads arrive pre-scaled by -lr (worker-side schedule); the l2 term
      // needs an explicit lr — the per-request one if provided, else the
      // init-time fallback (consistent with the stateful optimizers below)
      if (l2 != 0.0f) {
        const float lr = uo.lr >= 0.0f ? uo.lr
                                       : (p.lrs.empty() ? 0.01f : p.lrs[0]);
        const float s = lr * l2;
        for (size_t i = 0; i < n; ++i) w[i] += grad[i] - s * w[i];
      } else {
        for (size_t i = 0; i < n; ++i) w[i] += grad[i];
      }
      break;
    }
    case OptType::kMomentum:
    case OptType::kNesterov: {
      const float lr = uo.lr >= 0.0f ? uo.lr
                                     : (p.lrs.empty() ? 0.01f : p.lrs[0]);
      const float mom = p.lrs.size() > 1 ? p.lrs[1] : 0.9f;
      float* v = p.accum.data() + off;
      if (p.otype == OptType::kMomentum) {
        for (size_t i = 0; i < n; ++i) {
          v[i] = mom * v[i] - lr * (grad[i] + l2 * w[i]);
          w[i] += v[i];
        }
      } else {
        for (size_t i = 0; i < n; ++i) {
          float prev = v[i];
          v[i] = mom * v[i] - lr * (grad[i] + l2 * w[i]);
          w[i] += -mom * prev + (1.0f + mom) * v[i];
        }
      }
      break;
    }
    case OptType::kAdaGrad: {
      const float lr = uo.lr >= 0.0f ? uo.lr
                                     : (p.lrs.empty() ? 0.01f : p.lrs[0]);
      const float eps = p.lrs.size() > 1 ? p.lrs[1] : 1e-7f;
      float* a = p.accum.data() + off;
      for (size_t i = 0; i < n; ++i) {
        const float g = grad[i] + l2 * w[i];
        a[i] += g * g;
        w[i] -= lr * g / (std::sqrt(a[i]) + eps);
      }
      break;
    }
    case OptType::kAdam: {
      const float lr = uo.lr >= 0.0f ? uo.lr
                                     : (p.lrs.empty() ? 0.01f : p.lrs[0]);
      const float b1 = p.lrs.size() > 1 ? p.lrs[1] : 0.9f;
      const float b2 = p.lrs.size() > 2 ? p.lrs[2] : 0.999f;
      const float eps = p.lrs.size() > 3 ? p.lrs[3] : 1e-7f;
      const float bc1 = 1.0f - std::pow(b1, static_cast<float>(p.step));
      const float bc2 = 1.0f - std::pow(b2, static_cast<float>(p.step));
      float* m = p.accum.data() + off;
      float* v = p.accum2.data() + off;
      for (size_t i = 0; i < n; ++i) {
        const float w_old = w[i];
        const float g = grad[i] + l2 * w_old;
        m[i] = b1 * m[i] + (1.0f - b1) * g;
        v[i] = b2 * v[i] + (1.0f - b2) * g * g;
        w[i] -= lr * (m[i] / bc1) / (std::sqrt(v[i] / bc2) + eps);
        // decoupled weight decay (AdamW) against the PRE-update value —
        // mirrors optimizer.py's new_param -= lr * weight_decay * param
        if (uo.weight_decay != 0.0f) w[i] -= lr * uo.weight_decay * w_old;
      }
      break;
    }
  }
}

inline void alloc_slots(Param& p) {
  switch (p.otype) {
    case OptType::kSGD:
      break;
    case OptType::kMomentum:
    case OptType::kNesterov:
    case OptType::kAdaGrad:
      p.accum.assign(p.data.size(), 0.0f);
      break;
    case OptType::kAdam:
      p.accum.assign(p.data.size(), 0.0f);
      p.accum2.assign(p.data.size(), 0.0f);
      break;
  }
}

// The store: key -> Param, concurrent-safe (reference thread_safe_hash_map.h
// + per-param shared_mutex in PSFHandle.h:44-95).
class Store {
 public:
  Param* get(int32_t key) {
    std::shared_lock<std::shared_mutex> g(mu_);
    auto it = map_.find(key);
    return it == map_.end() ? nullptr : it->second.get();
  }

  Param* get_or_create(int32_t key) {
    {
      std::shared_lock<std::shared_mutex> g(mu_);
      auto it = map_.find(key);
      if (it != map_.end()) return it->second.get();
    }
    std::unique_lock<std::shared_mutex> g(mu_);
    auto& slot = map_[key];
    if (!slot) slot = std::make_unique<Param>();
    return slot.get();
  }

  void erase(int32_t key) {
    std::unique_lock<std::shared_mutex> g(mu_);
    map_.erase(key);
  }

  template <typename F>
  void for_each(F&& f) {
    std::shared_lock<std::shared_mutex> g(mu_);
    for (auto& kv : map_) f(kv.first, *kv.second);
  }

 private:
  std::shared_mutex mu_;
  std::unordered_map<int32_t, std::unique_ptr<Param>> map_;
};

}  // namespace hetups
