// hetuchaos: deterministic message-level fault injection for the PS
// transport (docs/FAULT_TOLERANCE.md "Chaos testing & transport hardening").
//
// The engine sits INSIDE the worker's rpc path and injects the faults a
// real network inflicts — drop, delay, duplicate, reorder, corrupt-bytes,
// directed partitions — so the hardening that survives them (retry with
// backoff riding the req_id dedup ledger, CRC32C payload rejection,
// partition escalation) is proven by the same machinery that will face
// them in production. Three contracts:
//
//  - DETERMINISM. Every decision is a pure function of (seed, server, psf,
//    tensor, per-triple sequence number) — never of wall time or thread
//    interleaving — so a failing schedule replays bit-identically from its
//    seed: the canonical (sorted) chaos event log of two runs of the same
//    workload under the same spec is EQUAL (tests/test_chaos.py pins it).
//    The per-triple counters are deterministic because each tensor's RPC
//    stream to each server is issued in program order.
//  - OFF-MODE ZERO COST. With no spec armed the worker pays one relaxed
//    atomic pointer load per RPC and nothing else (the telemetry/scope
//    off-mode convention).
//  - GATED. Arming requires HETU_TEST_MODE (enforced in capi.cc AND at the
//    worker's env-arming path), like every destructive hook in this repo.
//
// Spec grammar (HETU_CHAOS_SPEC / SetChaos; mirrored by
// hetu_tpu.chaos.parse_spec):
//
//   spec      := entry ("," entry)*
//   entry     := "seed=" u64
//              | "drop=" p          # request never sent; client retries
//              | "droprsp=" p       # response discarded after the server
//                                   # executed — the applied-but-unacked
//                                   # window; retry must dedup-replay
//              | "dup=" p           # request sent twice; the second copy
//                                   # must be answered from the dedup slot
//              | "corrupt=" p       # one payload byte flipped on the wire;
//                                   # the receiver's CRC must reject it
//                                   # (skipped when the client runs CRC-off)
//              | "delay=" p [":" max_ms]    # sleep 1..max_ms before send
//              | "reorder=" p [":" max_ms]  # same mechanics, logged as
//                                   # reorder: the held request lets sibling
//                                   # RPCs (other servers / the other
//                                   # channel) overtake it
//              | "partition=" server ":" from ":" count
//                                   # every attempt (initial or retry) to
//                                   # `server` while the per-(server,
//                                   # channel) attempt counter is in
//                                   # [from, from+count) fails — a directed
//                                   # client<->server partition that heals
//                                   # deterministically, or escalates to
//                                   # the failover/departure path if it
//                                   # outlives the retry budget
//
// Probabilities are cumulative-walked in a fixed order (drop, droprsp, dup,
// corrupt, delay, reorder); at most ONE scheduled fault per message.
#pragma once

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <deque>
#include <memory>
#include <mutex>
#include <stdexcept>
#include <string>
#include <unordered_map>
#include <vector>

namespace hetups {

// Mirrored by hetu_tpu.chaos.splitmix64 (the backoff-jitter tests pin both
// sides to the same values).
inline uint64_t splitmix64(uint64_t x) {
  x += 0x9e3779b97f4a7c15ull;
  x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
  x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
  return x ^ (x >> 31);
}

// Numeric kind ids are the wire/drain contract (hetu_tpu.chaos.KIND_NAMES).
enum class ChaosKind : int32_t {
  kNone = 0,
  kDrop = 1,
  kDelay = 2,
  kDup = 3,
  kReorder = 4,
  kCorrupt = 5,
  kPartition = 6,
  kDropRsp = 7,
};

struct ChaosDecision {
  ChaosKind kind = ChaosKind::kNone;
  int64_t arg = 0;  // delay/reorder: ms; corrupt: byte-offset selector
  int64_t seq = 0;  // the deciding per-triple sequence number
};

// One injected fault, drained as a 6-wide i64 row:
// [kind, server, psf, tensor, seq, arg].
struct ChaosEvent {
  int32_t kind, server, psf, tensor;
  int64_t seq, arg;
};

class ChaosEngine {
 public:
  static constexpr size_t kEventCols = 6;

  // Throws std::runtime_error naming the bad entry + the grammar on any
  // unknown key (the HETU_FAULT_SPEC reject-unknown-kinds convention).
  static std::unique_ptr<ChaosEngine> parse(const std::string& spec) {
    auto eng = std::unique_ptr<ChaosEngine>(new ChaosEngine());
    size_t pos = 0;
    while (pos <= spec.size()) {
      size_t comma = spec.find(',', pos);
      if (comma == std::string::npos) comma = spec.size();
      std::string ent = spec.substr(pos, comma - pos);
      pos = comma + 1;
      // trim
      while (!ent.empty() && (ent.front() == ' ')) ent.erase(0, 1);
      while (!ent.empty() && (ent.back() == ' ')) ent.pop_back();
      if (ent.empty()) continue;
      const size_t eq = ent.find('=');
      if (eq == std::string::npos)
        throw std::runtime_error("chaos spec entry '" + ent +
                                 "': expected key=value");
      const std::string key = ent.substr(0, eq);
      const std::string val = ent.substr(eq + 1);
      if (key == "seed") {
        char* end = nullptr;
        eng->seed_ = std::strtoull(val.c_str(), &end, 10);
        if (val.empty() || !end || *end != '\0')
          throw std::runtime_error("chaos spec entry '" + ent +
                                   "': seed must be an unsigned integer");
      } else if (key == "drop") {
        eng->p_drop_ = parse_p(ent, val);
      } else if (key == "droprsp") {
        eng->p_droprsp_ = parse_p(ent, val);
      } else if (key == "dup") {
        eng->p_dup_ = parse_p(ent, val);
      } else if (key == "corrupt") {
        eng->p_corrupt_ = parse_p(ent, val);
      } else if (key == "delay" || key == "reorder") {
        const size_t colon = val.find(':');
        const double p = parse_p(ent, val.substr(0, colon));
        // per-kind defaults match the member initializers AND the Python
        // mirror (ChaosSpec.delay_ms / .reorder_ms; a trailing ':' keeps
        // the default there too, a non-numeric ms raises on both sides)
        int64_t ms = key == "delay" ? 20 : 10;
        if (colon != std::string::npos && colon + 1 < val.size()) {
          char* end = nullptr;
          ms = std::strtoll(val.c_str() + colon + 1, &end, 10);
          if (!end || *end != '\0')
            throw std::runtime_error("chaos spec entry '" + ent +
                                     "': ms must be an integer");
        }
        if (ms < 1) ms = 1;
        if (key == "delay") {
          eng->p_delay_ = p;
          eng->delay_ms_ = ms;
        } else {
          eng->p_reorder_ = p;
          eng->reorder_ms_ = ms;
        }
      } else if (key == "partition") {
        // server:from:count
        Window w;
        char* end = nullptr;
        w.server = static_cast<int32_t>(std::strtol(val.c_str(), &end, 10));
        if (!end || *end != ':')
          throw std::runtime_error("chaos spec entry '" + ent +
                                   "': partition=SERVER:FROM:COUNT");
        w.from = std::strtoull(end + 1, &end, 10);
        if (!end || *end != ':')
          throw std::runtime_error("chaos spec entry '" + ent +
                                   "': partition=SERVER:FROM:COUNT");
        w.count = std::strtoull(end + 1, nullptr, 10);
        eng->partitions_.push_back(w);
      } else {
        throw std::runtime_error(
            "chaos spec entry '" + ent + "': unknown kind '" + key +
            "' — known: seed, drop, droprsp, dup, corrupt, delay[:ms], "
            "reorder[:ms], partition=SERVER:FROM:COUNT "
            "(docs/FAULT_TOLERANCE.md)");
      }
    }
    return eng;
  }

  // One scheduled-fault roll per logical RPC (retries of the same RPC do
  // NOT re-roll — the decision belongs to the message, not the attempt).
  // Decisions are NOT recorded here: the applier (worker.h
  // try_roundtrip_chaos) calls record_applied for the faults that
  // actually fire, so the event log never over-claims — a scheduled
  // fault preempted by a directed-partition block, or a corrupt that
  // degrades on a payload-less/CRC-off message, leaves no event. Every
  // degrade condition is itself deterministic (partition windows walk
  // per-(server, channel) attempt counters in program order; message
  // shape and the CRC setting are fixed per run), so replay equality
  // still holds.
  ChaosDecision decide(int32_t server, int32_t psf, int32_t tensor) {
    const uint64_t k = triple_key(server, psf, tensor);
    uint64_t seq;
    {
      std::lock_guard<std::mutex> g(mu_);
      seq = ++seq_[k];
    }
    const uint64_t h =
        splitmix64(seed_ ^ splitmix64(k) ^ (seq * 0x2545F4914F6CDD1Dull));
    // 53-bit uniform in [0, 1)
    const double u = static_cast<double>(h >> 11) / 9007199254740992.0;
    ChaosDecision d;
    d.seq = static_cast<int64_t>(seq);
    double c = 0.0;
    if (u < (c += p_drop_)) {
      d.kind = ChaosKind::kDrop;
    } else if (u < (c += p_droprsp_)) {
      d.kind = ChaosKind::kDropRsp;
    } else if (u < (c += p_dup_)) {
      d.kind = ChaosKind::kDup;
    } else if (u < (c += p_corrupt_)) {
      d.kind = ChaosKind::kCorrupt;
      d.arg = static_cast<int64_t>(splitmix64(h) >> 1);  // offset selector
    } else if (u < (c += p_delay_)) {
      d.kind = ChaosKind::kDelay;
      d.arg = 1 + static_cast<int64_t>(splitmix64(h) %
                                       static_cast<uint64_t>(delay_ms_));
    } else if (u < (c += p_reorder_)) {
      d.kind = ChaosKind::kReorder;
      d.arg = 1 + static_cast<int64_t>(splitmix64(h) %
                                       static_cast<uint64_t>(reorder_ms_));
    }
    return d;
  }

  // The applier's log entry for a fault that actually fired (see the
  // decide() contract above).
  void record_applied(ChaosKind kind, int32_t server, int32_t psf,
                      int32_t tensor, int64_t seq, int64_t arg) {
    record(kind, server, psf, tensor, seq, arg);
  }

  // Per-ATTEMPT partition check (unlike decide's per-message roll): a real
  // partition blocks retries too. The counter is per (server, channel) so
  // the WINDOW [from, from+count) is deterministic; WHICH message lands
  // in it depends on pool-thread interleaving when several tensors share
  // the channel — so the event records the deterministic fact (window
  // hit at attempt `a` on `channel`, carried in seq/arg) with psf/tensor
  // zeroed, keeping the canonical replay-log contract for partition
  // faults too (the racy victim identity is in last_err, not the log).
  bool partition_blocked(int32_t server, int32_t channel, int32_t psf,
                         int32_t tensor) {
    (void)psf;
    (void)tensor;
    if (partitions_.empty()) return false;
    bool targets = false;
    for (const Window& w : partitions_)
      if (w.server == server) targets = true;
    if (!targets) return false;
    uint64_t a;
    {
      std::lock_guard<std::mutex> g(mu_);
      a = att_[static_cast<uint64_t>(server) * 2 +
               static_cast<uint64_t>(channel)]++;
    }
    for (const Window& w : partitions_) {
      if (w.server == server && a >= w.from && a < w.from + w.count) {
        record(ChaosKind::kPartition, server, /*psf=*/0, /*tensor=*/0,
               static_cast<int64_t>(a), channel);
        return true;
      }
    }
    return false;
  }

  // Copy up to max_rows events (oldest first) out as kEventCols-wide i64
  // rows, removing them from the ring. Returns the row count.
  size_t drain(int64_t* out, size_t max_rows) {
    std::lock_guard<std::mutex> g(mu_);
    const size_t n = std::min(max_rows, ring_.size());
    for (size_t i = 0; i < n; ++i) {
      const ChaosEvent& e = ring_[i];
      int64_t* r = out + i * kEventCols;
      r[0] = e.kind;
      r[1] = e.server;
      r[2] = e.psf;
      r[3] = e.tensor;
      r[4] = e.seq;
      r[5] = e.arg;
    }
    ring_.erase(ring_.begin(), ring_.begin() + n);
    return n;
  }

  uint64_t fault_count() const {
    return fault_count_.load(std::memory_order_relaxed);
  }
  uint64_t dropped_events() const {
    return dropped_.load(std::memory_order_relaxed);
  }
  uint64_t seed() const { return seed_; }

 private:
  ChaosEngine() = default;

  static double parse_p(const std::string& ent, const std::string& val) {
    char* end = nullptr;
    const double p = std::strtod(val.c_str(), &end);
    // val.empty()/no-digits check: strtod("") "succeeds" at 0.0, which
    // the Python mirror rejects — the grammars must agree on rejection.
    // The negated range form also rejects NaN (every comparison with NaN
    // is false, so `p < 0 || p > 1` would let it through).
    if (val.empty() || end == val.c_str() || !end || *end != '\0' ||
        !(p >= 0.0 && p <= 1.0))
      throw std::runtime_error("chaos spec entry '" + ent +
                               "': probability must be in [0, 1]");
    return p;
  }

  static uint64_t triple_key(int32_t server, int32_t psf, int32_t tensor) {
    return static_cast<uint64_t>(static_cast<uint32_t>(server)) |
           (static_cast<uint64_t>(static_cast<uint32_t>(psf)) << 16) |
           (static_cast<uint64_t>(static_cast<uint32_t>(tensor)) << 32);
  }

  void record(ChaosKind kind, int32_t server, int32_t psf, int32_t tensor,
              int64_t seq, int64_t arg) {
    fault_count_.fetch_add(1, std::memory_order_relaxed);
    std::lock_guard<std::mutex> g(mu_);
    if (ring_.size() >= kRingCap) {
      dropped_.fetch_add(1, std::memory_order_relaxed);
      return;
    }
    ring_.push_back({static_cast<int32_t>(kind), server, psf, tensor, seq,
                     arg});
  }

  struct Window {
    int32_t server = 0;
    uint64_t from = 0, count = 0;
  };

  static constexpr size_t kRingCap = 65536;

  uint64_t seed_ = 0;
  double p_drop_ = 0, p_droprsp_ = 0, p_dup_ = 0, p_corrupt_ = 0,
         p_delay_ = 0, p_reorder_ = 0;
  int64_t delay_ms_ = 20, reorder_ms_ = 10;
  std::vector<Window> partitions_;
  std::mutex mu_;
  std::unordered_map<uint64_t, uint64_t> seq_;  // triple -> message seq
  std::unordered_map<uint64_t, uint64_t> att_;  // (server, ch) -> attempts
  std::deque<ChaosEvent> ring_;
  std::atomic<uint64_t> fault_count_{0};
  std::atomic<uint64_t> dropped_{0};
};

// Deterministic retry backoff: exponential base<<(attempt-1) capped at
// `cap`, scaled by a jitter in [0.5, 1.0) derived from splitmix64 — pure
// integer math, mirrored bit-for-bit by hetu_tpu.chaos.backoff_ms (the
// fake-clock schedule tests pin both sides).
inline int64_t backoff_ms(int attempt, int64_t base, int64_t cap,
                          uint64_t key) {
  if (attempt < 1) attempt = 1;
  int64_t exp = base << std::min(attempt - 1, 20);
  if (exp > cap) exp = cap;
  const int64_t j =
      static_cast<int64_t>(splitmix64(key ^ static_cast<uint64_t>(attempt)) %
                           500ull);
  return exp * (500 + j) / 1000;
}

}  // namespace hetups
