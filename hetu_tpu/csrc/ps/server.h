// The parameter server process: TCP accept loop + per-connection handler
// threads serving PSF requests against the Store.
//
// Capability parity with the reference's KVServer + PSFHandle
// (ps-lite/include/ps/server/PSFHandle.h: DensePull :31, DensePush :51
// (+= accumulate), DDPushPull :78, SparsePull :106, cachetable.h kSync*).
// Concurrency: connections are handled in parallel; per-param shared_mutex
// guards give the reference's ASP lock-granularity (PSFHandle.h:44-95).
#pragma once

#include <algorithm>
#include <atomic>
#include <cctype>
#include <chrono>
#include <cmath>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <filesystem>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

#include "net.h"
#include "store.h"

namespace hetups {

// hetutrail per-request apply timing: begin_req/mark run on the SAME serve
// thread as serve_conn's span record, so a thread_local pair carries the
// true apply window (optimizer math only — param-lock wait and response
// serialization excluded) out of handle() without threading a context
// through every PSF case. Zeroed per request in serve_conn; stays 0 for
// reads and when trail is off.
inline thread_local int64_t g_trail_apply_t0 = 0;
inline thread_local int64_t g_trail_apply_us = 0;

// The dedup slot this dispatch thread holds locked while executing the
// current request. take_snapshot's ledger walk locks EVERY client slot,
// so no caller may enter it while holding one: serve_conn drops the
// requester's slot BEFORE kSnapshotNow's handle() (holding it while
// take_snapshot waits on snap_take_mu_ would ABBA-deadlock against the
// periodic snapshot_loop thread, which holds snap_take_mu_ and then
// locks slots during the ledger walk). This thread_local remains as
// belt-and-braces: if a future caller does reach take_snapshot with a
// slot held, the walk reads that one slot lock-free instead of
// self-deadlocking.
inline thread_local const void* g_dedup_slot_held = nullptr;

// env_test_mode (the single truthy-env gate for destructive test hooks)
// moved to net.h so the worker's chaos arming shares it.

class PsServer {
 public:
  PsServer(int rank, const std::string& host, int port)
      : rank_(rank), host_(host), port_(port) {
    const char* v = std::getenv("DMLC_PS_VALIDATE");
    validate_ = v && *v && *v != '0';
    const char* sd = std::getenv("DMLC_PS_SNAPSHOT_DIR");
    if (sd && *sd) snapshot_dir_ = sd;
    snapshot_ms_ = env_int_or("DMLC_PS_SNAPSHOT_MS", 5000);
    // deterministic fault hook for the dedup-proof tests: _Exit right after
    // the Nth optimizer update completes but BEFORE its response is sent
    // (the applied-but-unacked window resend dedup exists for). Optional
    // ":snap" takes a final synchronous snapshot first, so the apply AND its
    // dedup-ledger entry are on disk for the replacement. Inert without
    // HETU_TEST_MODE (same gate as resolve_test_kill_index).
    const char* tx = std::getenv("HETU_PS_TEST_EXIT_AFTER_UPDATES");
    if (tx && *tx && env_test_mode()) {
      std::string spec(tx);
      auto colon = spec.find(':');
      test_exit_snap_ = colon != std::string::npos &&
                        spec.substr(colon + 1) == "snap";
      test_exit_after_updates_ = std::atol(spec.c_str());
    }
    // hetutrail (docs/OBSERVABILITY.md pillar 5): per-request timelines
    // into a bounded ring, flushed as JSONL the offline analyzer joins to
    // client spans by (client_id, req_id). Armed by HETU_TRAIL_DIR — the
    // server is a light ctypes process with no Python telemetry, so the
    // C++ side owns the file.
    const char* td = std::getenv("HETU_TRAIL_DIR");
    if (td && *td) {
      trail_path_ = std::string(td) + "/trail-server-s" +
                    std::to_string(rank_) + ".jsonl";
      trail_cap_ = static_cast<size_t>(env_int_or("HETU_TRAIL_RING", 65536));
      // bounded file growth, like the Python TrailWriter: rotate to one
      // .1 backup past the cap (0 disables)
      trail_max_bytes_ = static_cast<int64_t>(
          env_int_or("HETU_TRAIL_MAX_MB", 512)) * 1000000;
    }
  }

  ~PsServer() { stop(); }

  void start() {
    listen_fd_ = listen_on("", port_);
    if (port_ == 0) {
      // OS-assigned port (race-free: bound before anyone learns it; the
      // actual number reaches workers via the scheduler's address book)
      sockaddr_in addr{};
      socklen_t len = sizeof(addr);
      if (::getsockname(listen_fd_, reinterpret_cast<sockaddr*>(&addr),
                        &len) != 0)
        throw std::runtime_error("hetups: getsockname failed");
      port_ = ntohs(addr.sin_port);
    }
    running_ = true;
    accept_thread_ = std::thread([this] { accept_loop(); });
    if (!snapshot_dir_.empty() && snapshot_ms_ > 0)
      snapshot_thread_ = std::thread([this] { snapshot_loop(); });
  }

  int port() const { return port_; }

  void stop() {
    running_ = false;
    trail_flush(/*force=*/true);
    {
      std::lock_guard<std::mutex> g(snap_mu_);
      snap_stop_ = true;
    }
    snap_cv_.notify_all();
    if (snapshot_thread_.joinable()) snapshot_thread_.join();
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    if (accept_thread_.joinable()) accept_thread_.join();
    {
      std::lock_guard<std::mutex> g(fds_mu_);
      for (int fd : live_fds_) ::shutdown(fd, SHUT_RDWR);
    }
    conn_threads_.join_all();
    trail_flush(/*force=*/true);  // spans the serve threads added late
    {
      std::lock_guard<std::mutex> g(trail_mu_);
      if (trail_f_) {
        std::fclose(trail_f_);
        trail_f_ = nullptr;
      }
    }
  }

  int rank() const { return rank_; }

 private:
  void accept_loop() {
    while (running_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      conn_threads_.spawn([this, fd] { serve_conn(fd); });
    }
  }

  // Per-client resend dedup (the server half of the reference's resender.h
  // contract): a worker that resends after a lost response must not have the
  // request applied twice. One slot per client_id suffices because each
  // worker CHANNEL serializes its requests to one server (client_id encodes
  // rank*2 + channel — the bulk and fast channels are independent streams
  // with independently monotonic req_ids).
  struct ClientSlot {
    std::mutex mu;
    uint64_t last_id = 0;
    Message rsp;
    // false when last_id was restored from a snapshot's dedup ledger: the
    // request already APPLIED (it is inside the restored state) but the
    // response payload was never persisted — a resend re-executes with
    // skip_apply so reads are answered without double-applying the write.
    bool has_rsp = false;
    // provenance of the last request's applied write (0 = read-only or
    // restored-from-snapshot): take_snapshot's ledger filter compares
    // write_seq against the seq its target param's file was saved at, so
    // a write that landed AFTER the file was written is left out of the
    // ledger (re-issue re-applies it) instead of being silently acked as
    // a skip_apply duplicate — see the kManifestMagic comment
    uint64_t write_seq = 0;
    int32_t write_key = -1;
  };

  ClientSlot* client_slot(int32_t client_id) {
    std::lock_guard<std::mutex> g(clients_mu_);
    auto& p = clients_[client_id];
    if (!p) p = std::make_unique<ClientSlot>();
    return p.get();
  }

  void serve_conn(int fd) {
    {
      std::lock_guard<std::mutex> g(fds_mu_);
      live_fds_.push_back(fd);
    }
    Message req;
    const bool trail = !trail_path_.empty();
    while (recv_msg(fd, &req)) {
      if (static_cast<PsfType>(req.head.type) == PsfType::kShutdown) break;
      req_count_.fetch_add(1, std::memory_order_relaxed);
      const int64_t tr_recv = trail ? trail_mono_us() : 0;
      // hetu-elastic stale-epoch rejection: once armed (kSetWorldVersion),
      // a request stamped with a DIFFERENT non-zero world version comes
      // from a worker that missed a resize commit — its view of the key
      // ranges is stale, so applying it would scatter updates across the
      // old partition. Rejected the same way resend-dedup rejects
      // duplicates: an error response, counters and params untouched.
      // world_ver == 0 is unversioned legacy traffic, always accepted.
      {
        const uint64_t wv = world_version_.load(std::memory_order_relaxed);
        const uint64_t rv = static_cast<uint64_t>(
            static_cast<uint32_t>(req.head.world_ver));
        if (wv != 0 && rv != 0 && rv != wv &&
            static_cast<PsfType>(req.head.type) !=
                PsfType::kSetWorldVersion) {
          Message rej;
          rej.head.type = static_cast<int32_t>(PsfType::kAck);
          rej.head.tensor_id = req.head.tensor_id;
          rej.head.req_id = req.head.req_id;
          rej.head.flags = -1;
          rej.args.push_back(Arg::str(
              "stale world epoch " + std::to_string(rv) +
              " (server at world v" + std::to_string(wv) +
              ") — re-sync membership before issuing traffic"));
          try {
            send_msg(fd, rej);
          } catch (...) {
            break;
          }
          continue;
        }
      }
      // hetuchaos transport hardening: verify payload CRCs BEFORE the
      // dedup slot and BEFORE any handling — a corrupted request must
      // leave params, update counters, AND the dedup ledger untouched
      // (advancing slot->last_id on garbage would make the clean resend
      // look like a stale straggler and silently drop it). The reject is
      // an error response marked "retryable:" so the client resends
      // instead of surfacing an app-level failure — exactly the malformed
      // kQI8 contract, applied to every payload.
      if (req.head.flags != -1 && (req.head.flags & kFlagCrc)) {
        std::string cerr;
        if (!verify_msg_crc(req, &cerr)) {
          crc_reject_count_.fetch_add(1, std::memory_order_relaxed);
          Message rej;
          rej.head.type = static_cast<int32_t>(PsfType::kAck);
          rej.head.tensor_id = req.head.tensor_id;
          rej.head.req_id = req.head.req_id;
          rej.head.flags = -1;
          rej.args.push_back(Arg::str(
              "retryable: payload CRC mismatch on psf " +
              std::to_string(req.head.type) + " tensor " +
              std::to_string(req.head.tensor_id) + " (" + cerr +
              ") — request not applied; resend"));
          try {
            send_msg(fd, rej);
          } catch (...) {
            break;
          }
          continue;
        }
      }
      ClientSlot* slot =
          (req.head.client_id >= 0 && req.head.req_id > 0)
              ? client_slot(req.head.client_id)
              : nullptr;
      std::unique_lock<std::mutex> slot_g;
      bool skip_apply = false;
      if (slot) {
        slot_g = std::unique_lock<std::mutex>(slot->mu);
        if (req.head.req_id == slot->last_id && slot->last_id > 0) {
          if (slot->has_rsp) {
            // duplicate of the last executed request: replay the response
            try {
              send_msg(fd, slot->rsp);
            } catch (...) {
              break;
            }
            continue;
          }
          // restored-ledger duplicate: the write already landed before the
          // snapshot — re-execute read-only (fall through with skip_apply)
          skip_apply = true;
        }
        if (req.head.req_id < slot->last_id) {
          // stale straggler from a pre-reconnect stream (a newer request
          // already executed): applying it now would double-apply — drop;
          // the worker stopped waiting on that stream long ago
          continue;
        }
      }
      Message rsp;
      rsp.head.type = static_cast<int32_t>(PsfType::kAck);
      rsp.head.tensor_id = req.head.tensor_id;
      rsp.head.req_id = req.head.req_id;
      uint64_t wseq = 0;
      // trail timeline: recv -> (queue + dedup-slot lock wait) -> handle
      // (param lock wait + apply + serialize) -> respond; the apply
      // window alone rides the begin_req/mark thread_locals
      if (trail) {
        g_trail_apply_t0 = 0;   // clear any stale window (error paths)
        g_trail_apply_us = 0;
      }
      const int64_t tr_h0 = trail ? trail_mono_us() : 0;
      const auto handle_t0 = std::chrono::steady_clock::now();
      // kSnapshotNow's handle() acquires snap_take_mu_ and then walks
      // every dedup slot; the periodic snapshot_loop thread takes those
      // same locks in that order. Holding this requester's slot across
      // handle() would close an ABBA cycle (dispatch: slot ->
      // snap_take_mu_; periodic: snap_take_mu_ -> slot), so the snapshot
      // path releases the slot for the handle() window and re-locks it to
      // record the response. A concurrent resend executing meanwhile is
      // harmless: take_snapshot serializes on snap_take_mu_, both
      // snapshots are complete, and the last recorded response wins.
      const bool drop_slot_for_snapshot =
          slot != nullptr &&
          req.head.type == static_cast<int32_t>(PsfType::kSnapshotNow);
      if (drop_slot_for_snapshot) slot_g.unlock();
      g_dedup_slot_held = drop_slot_for_snapshot ? nullptr : slot;
      try {
        handle(req, &rsp, skip_apply, &wseq);
      } catch (const std::exception& e) {
        std::fprintf(stderr, "[hetups server %d] error on psf %d tensor %d: %s\n",
                     rank_, req.head.type, req.head.tensor_id, e.what());
        rsp.head.flags = -1;
        rsp.args.clear();
        rsp.args.push_back(Arg::str(e.what()));
      }
      g_dedup_slot_held = nullptr;
      if (drop_slot_for_snapshot) slot_g.lock();
      // answer a CRC-speaking client in kind: send_msg checksums the
      // response args so the client can reject a corrupted return leg
      // (error responses stay flags == -1, never checksummed)
      if (req.head.flags != -1 && (req.head.flags & kFlagCrc) &&
          rsp.head.flags != -1)
        rsp.head.flags |= kFlagCrc;
      if (wseq != 0) {
        // apply latency (kServerStats): wall time of requests that applied
        // a write, accumulated as ns + count so the client derives the avg
        apply_ns_.fetch_add(
            std::chrono::duration_cast<std::chrono::nanoseconds>(
                std::chrono::steady_clock::now() - handle_t0)
                .count(),
            std::memory_order_relaxed);
        apply_count_.fetch_add(1, std::memory_order_relaxed);
      }
      // req_id >= last_id always holds on the normal path (the lock was
      // held since the dedup check); on the snapshot path a newer request
      // may have executed while the slot was dropped — never regress the
      // ledger below it (the reply still goes out from rsp directly).
      bool recorded = false;
      if (slot && req.head.req_id >= slot->last_id) {
        slot->last_id = req.head.req_id;
        slot->rsp = std::move(rsp);  // no payload copy; slot mutex still held
        slot->has_rsp = true;
        slot->write_seq = wseq;
        slot->write_key = req.head.tensor_id;
        recorded = true;
      }
      if (test_exit_after_updates_ >= 0 &&
          update_count_.load() >=
              static_cast<uint64_t>(test_exit_after_updates_)) {
        // fault hook: die applied-but-unacked (see constructor). The slot
        // lock must drop first — the final snapshot reads the dedup ledger.
        if (slot_g.owns_lock()) slot_g.unlock();
        if (test_exit_snap_) {
          try {
            take_snapshot();
          } catch (...) {
          }
        }
        std::fprintf(stderr,
                     "[hetups server %d] TEST exit after %ld updates "
                     "(response for req %llu never sent)\n",
                     rank_, test_exit_after_updates_,
                     (unsigned long long)req.head.req_id);
        std::_Exit(137);
      }
      const int64_t tr_h1 = trail ? trail_mono_us() : 0;
      bool sent = true;
      try {
        send_msg(fd, recorded ? slot->rsp : rsp);
      } catch (...) {
        sent = false;  // peer gone mid-reply
      }
      if (trail) {
        SrvSpan s;
        s.client_id = req.head.client_id;
        s.req_id = req.head.req_id;
        s.psf = req.head.type;
        s.tensor = req.head.tensor_id;
        s.t0_us = tr_recv;
        s.q_us = tr_h0 - tr_recv;
        s.handle_us = tr_h1 - tr_h0;
        s.apply_us = g_trail_apply_us;   // optimizer math only; 0 = read
        s.send_us = trail_mono_us() - tr_h1;
        trail_record(s);
      }
      if (!sent) break;
    }
    {
      std::lock_guard<std::mutex> g(fds_mu_);
      live_fds_.erase(std::remove(live_fds_.begin(), live_fds_.end(), fd),
                      live_fds_.end());
    }
    ::close(fd);
  }

  // ---------------------------------------------------------------------
  // Optional per-request optimizer overrides: push messages may carry a
  // trailing f32 [lr, l2reg, weight_decay] arg beyond their base arg count
  // (store.h UpdateOpts) — how workers honor lr schedules and l2/weight
  // decay on stateful server-side optimizers.
  static UpdateOpts parse_opts(const Message& req, size_t base_args) {
    UpdateOpts uo;
    if (req.args.size() > base_args) {
      const Arg& a = req.args[base_args];
      if (a.dtype == ArgType::kF32 && a.n_f32() >= 3) {
        const float* f = a.as_f32();
        uo.lr = f[0];
        uo.l2reg = f[1];
        uo.weight_decay = f[2];
      }
    }
    return uo;
  }

  // One logical optimizer update is ONE counter tick (a sparse push of N
  // rows is one update, matching begin_update's Adam-step contract). The
  // counter is what snapshot manifests stamp — recovery reports exactly how
  // many updates the restored state is behind.
  void begin_req(Param& p) {
    // hetutrail ps_slow fault (kTestSlowApply, HETU_TEST_MODE-gated):
    // one-shot delay of the next apply, taken while the param's exclusive
    // lock is held — exactly the lock-wait shape a genuinely slow apply
    // inflicts on concurrent requests, which is what the critical-path
    // and straggler tests must attribute.
    // apply-window start BEFORE the slow hook's sleep: the injected delay
    // stands in for a genuinely slow apply, so it must read as apply time
    if (!trail_path_.empty()) g_trail_apply_t0 = trail_mono_us();
    const int64_t slow = test_slow_ms_.exchange(0, std::memory_order_relaxed);
    if (slow > 0)
      std::this_thread::sleep_for(std::chrono::milliseconds(slow));
    begin_update(p);
    update_count_.fetch_add(1, std::memory_order_relaxed);
  }

  // -- hetutrail span ring (bounded; see the flight-recorder precedent) ---
  struct SrvSpan {
    uint64_t req_id;
    int32_t client_id, psf, tensor;
    int64_t t0_us, q_us, handle_us, apply_us, send_us;
  };

  void trail_record(const SrvSpan& s) {
    bool do_flush = false;
    {
      std::lock_guard<std::mutex> g(trail_mu_);
      if (trail_ring_.size() >= trail_cap_) {
        ++trail_dropped_;
        do_flush = true;  // drain to disk so the ring frees up
      } else {
        trail_ring_.push_back(s);
        do_flush = trail_ring_.size() >= kTrailFlushEvery;
      }
    }
    if (do_flush) trail_flush(false);
  }

  // Append the ring to trail-server-s<rank>.jsonl. The first write of each
  // file handle emits an anchor record pairing this host's monotonic clock
  // with the wall clock, so offline tools can place spans in absolute time
  // without trusting wall-clock stamps taken mid-run (NTP steps).
  void trail_flush(bool force) {
    if (trail_path_.empty()) return;
    std::lock_guard<std::mutex> g(trail_mu_);
    if (trail_ring_.empty() && !force) return;
    if (!trail_f_) {
      trail_f_ = std::fopen(trail_path_.c_str(), "a");
      if (!trail_f_) {
        trail_ring_.clear();  // unwritable dir must not grow the ring
        return;
      }
      // count what a predecessor incarnation already wrote, so the size
      // bound holds across restarts too
      if (std::fseek(trail_f_, 0, SEEK_END) == 0)
        trail_file_bytes_ = std::ftell(trail_f_);
      const double wall = std::chrono::duration_cast<std::chrono::duration<
          double>>(std::chrono::system_clock::now().time_since_epoch())
          .count();
      std::fprintf(trail_f_,
                   "{\"kind\":\"anchor\",\"server\":%d,\"mono_us\":%lld,"
                   "\"wall_s\":%.3f}\n",
                   rank_, (long long)trail_mono_us(), wall);
    }
    for (const SrvSpan& s : trail_ring_) {
      int k = std::fprintf(
          trail_f_,
          "{\"kind\":\"srv\",\"server\":%d,\"client\":%d,"
          "\"req_id\":%llu,\"psf\":%d,\"tensor\":%d,"
          "\"t0_us\":%lld,\"q_us\":%lld,\"handle_us\":%lld,"
          "\"apply_us\":%lld,\"send_us\":%lld}\n",
          rank_, s.client_id, (unsigned long long)s.req_id, s.psf,
          s.tensor, (long long)s.t0_us, (long long)s.q_us,
          (long long)s.handle_us, (long long)s.apply_us,
          (long long)s.send_us);
      if (k > 0) trail_file_bytes_ += k;
    }
    if (trail_dropped_) {
      std::fprintf(trail_f_,
                   "{\"kind\":\"dropped\",\"server\":%d,\"n\":%llu}\n",
                   rank_, (unsigned long long)trail_dropped_);
      trail_dropped_ = 0;
    }
    trail_ring_.clear();
    std::fflush(trail_f_);
    if (trail_max_bytes_ > 0 && trail_file_bytes_ >= trail_max_bytes_) {
      // rotate to ONE .1 backup (bounded growth, the TrailWriter/JsonlSink
      // convention); the next flush reopens and writes a fresh anchor
      std::fclose(trail_f_);
      trail_f_ = nullptr;
      std::rename(trail_path_.c_str(), (trail_path_ + ".1").c_str());
      trail_file_bytes_ = 0;
    }
  }

  // hetuq: f32 view of a value arg that may ride the wire quantized
  // (ArgType::kQI8). Dequantizes into `scratch` with full length/scale
  // validation — a malformed quantized payload becomes an error response
  // (the param untouched), never an applied-garbage write. `expect_n` > 0
  // pins the element count the handler derived from its other args.
  static const float* value_f32(const Arg& a, std::vector<float>* scratch,
                                size_t expect_n) {
    if (a.dtype == ArgType::kQI8) {
      dequant_qi8(a, scratch, expect_n);
      return scratch->data();
    }
    if (expect_n > 0 && a.n_f32() != expect_n)
      throw std::runtime_error(
          "value arg carries " + std::to_string(a.n_f32()) + " f32s, " +
          std::to_string(expect_n) + " expected");
    return a.as_f32();
  }

  // hetuq: response value payload, quantized iff the request asked for it
  // (kFlagQuantRsp). `block` is the scale granularity — row width for
  // sparse rows, kQuantWireBlock for dense payloads.
  static Arg rsp_value(const Message& req, const float* vals, size_t n,
                       size_t block) {
    if (req.head.flags & kFlagQuantRsp)
      return make_qi8_arg(vals, n, block ? block : kQuantWireBlock);
    return Arg::f32(vals, n);
  }

  // `skip_apply`: re-execution of a request whose write already landed in
  // the restored snapshot (dedup-ledger duplicate) — perform reads, answer
  // normally, but never mutate. `write_seq` (when non-null) receives the
  // seq stamped on this request's applied write, 0 for read-only requests.
  void handle(Message& req, Message* rsp, bool skip_apply = false,
              uint64_t* write_seq = nullptr) {
    const auto type = static_cast<PsfType>(req.head.type);
    const int32_t key = req.head.tensor_id;
    std::vector<float> qscratch;  // dequant buffer for quantized value args
    // stamp an applied write while the param's exclusive lock is held —
    // the lock is what orders the stamp against save_param_file's read of
    // last_write_seq, making the snapshot's ledger filter race-free
    auto mark = [&](Param& pm) {
      pm.last_write_seq =
          write_seq_gen_.fetch_add(1, std::memory_order_relaxed) + 1;
      if (write_seq) *write_seq = pm.last_write_seq;
      // close the hetutrail apply window opened by begin_req (mark runs
      // right after the case's apply loop); cases that mark without
      // begin_req (init/assign/clear/load) leave t0 at 0 — no apply span
      if (g_trail_apply_t0) {
        g_trail_apply_us = trail_mono_us() - g_trail_apply_t0;
        g_trail_apply_t0 = 0;
      }
    };
    switch (type) {
      case PsfType::kParamInit: {
        // deliberately NOT skip_apply-gated: init is idempotent (re-init of
        // a sized param is a no-op below), and a param created between the
        // snapshot's key scan and its ledger capture exists in the ledger
        // but not on disk — suppressing the re-issued init would make that
        // key permanently uninitializable on the replacement
        // args: i64[kind, len, width, init_type, otype, n_lr],
        //       f64[a, b], u64[seed], f32 lrs
        const int64_t* meta = req.args[0].as_i64();
        const double* ab = req.args[1].as_f64();
        uint64_t seed = req.args[2].as_u64()[0];
        const float* lrs = req.args[3].as_f32();
        size_t n_lr = req.args[3].n_f32();
        Param* p = store_.get_or_create(key);
        std::unique_lock<std::shared_mutex> g(p->mu);
        size_t want = static_cast<size_t>(meta[1]) *
                      (meta[0] == 0 ? 1 : static_cast<size_t>(meta[2]));
        if (p->data.size() == want && want > 0) break;  // idempotent re-init
        p->kind = static_cast<ParamKind>(meta[0]);
        if (p->kind == ParamKind::kDense) {
          p->len = static_cast<size_t>(meta[1]);
          p->rows = 0;
          p->width = 1;
        } else {
          p->rows = static_cast<size_t>(meta[1]);
          p->width = static_cast<size_t>(meta[2]);
          p->len = p->rows * p->width;
        }
        p->otype = static_cast<OptType>(meta[4]);
        p->lrs.assign(lrs, lrs + n_lr);
        p->data.assign(p->len, 0.0f);
        init_values(&p->data, static_cast<InitType>(meta[3]), ab[0], ab[1],
                    seed + static_cast<uint64_t>(rank_) * 0x9e3779b9u);
        alloc_slots(*p);
        if (p->kind == ParamKind::kCacheTable)
          p->versions.assign(p->rows, 0);
        mark(*p);
        break;
      }
      case PsfType::kDensePush: {
        Param* p = store_.get(key);
        check(p, key);
        std::unique_lock<std::shared_mutex> g(p->mu);
        if (skip_apply) break;
        const size_t n = value_count(req.args[0]);
        if (n > p->data.size())
          throw std::runtime_error(
              "DensePush carries " + std::to_string(n) + " values for a " +
              std::to_string(p->data.size()) + "-element shard");
        const float* v = value_f32(req.args[0], &qscratch, n);
        begin_req(*p);
        apply_update(*p, 0, v, n, parse_opts(req, 1));
        mark(*p);
        break;
      }
      case PsfType::kDensePull: {
        Param* p = store_.get(key);
        check(p, key);
        std::shared_lock<std::shared_mutex> g(p->mu);
        rsp->args.push_back(Arg::f32(p->data.data(), p->data.size()));
        break;
      }
      case PsfType::kDDPushPull: {
        Param* p = store_.get(key);
        check(p, key);
        std::unique_lock<std::shared_mutex> g(p->mu);
        if (!skip_apply) {
          const size_t n = value_count(req.args[0]);
          if (n > p->data.size())
            throw std::runtime_error(
                "DDPushPull carries " + std::to_string(n) + " values for a " +
                std::to_string(p->data.size()) + "-element shard");
          const float* v = value_f32(req.args[0], &qscratch, n);
          begin_req(*p);
          apply_update(*p, 0, v, n, parse_opts(req, 1));
          mark(*p);
        }
        rsp->args.push_back(rsp_value(req, p->data.data(), p->data.size(),
                                      kQuantWireBlock));
        break;
      }
      case PsfType::kSparsePush: {
        // args: i64 local row ids (deduped), f32 vals (nidx x width)
        Param* p = store_.get(key);
        check(p, key);
        std::unique_lock<std::shared_mutex> g(p->mu);
        const int64_t* idx = req.args[0].as_i64();
        size_t nidx = req.args[0].n_i64();
        check_rows(*p, idx, nidx);  // before any mutation
        if (skip_apply) break;
        // length/scale validation BEFORE begin_req: a rejected quantized
        // payload must leave the param (and the update counter) untouched
        const float* vals = value_f32(req.args[1], &qscratch,
                                      nidx * p->width);
        begin_req(*p);
        const UpdateOpts uo = parse_opts(req, 2);
        for (size_t i = 0; i < nidx; ++i)
          apply_update(*p, static_cast<size_t>(idx[i]) * p->width,
                       vals + i * p->width, p->width, uo);
        mark(*p);
        break;
      }
      case PsfType::kSparsePull: {
        Param* p = store_.get(key);
        check(p, key);
        std::shared_lock<std::shared_mutex> g(p->mu);
        const int64_t* idx = req.args[0].as_i64();
        size_t nidx = req.args[0].n_i64();
        check_rows(*p, idx, nidx);
        std::vector<float> out(nidx * p->width);
        for (size_t i = 0; i < nidx; ++i)
          std::memcpy(out.data() + i * p->width,
                      p->data.data() + static_cast<size_t>(idx[i]) * p->width,
                      p->width * 4);
        rsp->args.push_back(rsp_value(req, out.data(), out.size(),
                                      p->width));
        break;
      }
      case PsfType::kSDPushPull: {
        // sparse push + dense pull (grads are sparse, want full table back)
        Param* p = store_.get(key);
        check(p, key);
        std::unique_lock<std::shared_mutex> g(p->mu);
        const int64_t* idx = req.args[0].as_i64();
        size_t nidx = req.args[0].n_i64();
        check_rows(*p, idx, nidx);  // before any mutation
        if (!skip_apply) {
          const float* vals = value_f32(req.args[1], &qscratch,
                                        nidx * p->width);
          begin_req(*p);
          const UpdateOpts uo = parse_opts(req, 2);
          for (size_t i = 0; i < nidx; ++i)
            apply_update(*p, static_cast<size_t>(idx[i]) * p->width,
                         vals + i * p->width, p->width, uo);
          mark(*p);
        }
        rsp->args.push_back(rsp_value(req, p->data.data(), p->data.size(),
                                      kQuantWireBlock));
        break;
      }
      case PsfType::kSSPushPull: {
        // sparse push + sparse pull of (possibly different) rows
        Param* p = store_.get(key);
        check(p, key);
        std::unique_lock<std::shared_mutex> g(p->mu);
        const int64_t* idx = req.args[0].as_i64();
        size_t nidx = req.args[0].n_i64();
        const int64_t* oidx = req.args[2].as_i64();
        size_t no = req.args[2].n_i64();
        // validate BOTH sides before any mutation: a rejected request must
        // leave the param untouched or a client retry double-applies
        check_rows(*p, idx, nidx);
        check_rows(*p, oidx, no);
        if (!skip_apply) {
          const float* vals = value_f32(req.args[1], &qscratch,
                                        nidx * p->width);
          begin_req(*p);
          const UpdateOpts uo = parse_opts(req, 3);
          for (size_t i = 0; i < nidx; ++i)
            apply_update(*p, static_cast<size_t>(idx[i]) * p->width,
                         vals + i * p->width, p->width, uo);
          mark(*p);
        }
        std::vector<float> out(no * p->width);
        for (size_t i = 0; i < no; ++i)
          std::memcpy(out.data() + i * p->width,
                      p->data.data() + static_cast<size_t>(oidx[i]) * p->width,
                      p->width * 4);
        rsp->args.push_back(rsp_value(req, out.data(), out.size(),
                                      p->width));
        break;
      }
      case PsfType::kParamAssign: {
        // raw overwrite of this shard (host-side initializers push values
        // through here so server optimizers never see them as gradients)
        Param* p = store_.get(key);
        check(p, key);
        std::unique_lock<std::shared_mutex> g(p->mu);
        if (req.args[0].n_f32() != p->data.size())
          throw std::runtime_error("ParamAssign size mismatch");
        if (skip_apply) break;
        std::memcpy(p->data.data(), req.args[0].as_f32(),
                    p->data.size() * 4);
        mark(*p);
        break;
      }
      case PsfType::kParamAssignRows: {
        Param* p = store_.get(key);
        check(p, key);
        std::unique_lock<std::shared_mutex> g(p->mu);
        const int64_t* idx = req.args[0].as_i64();
        size_t nidx = req.args[0].n_i64();
        check_rows(*p, idx, nidx);
        if (skip_apply) break;
        const float* vals = req.args[1].as_f32();
        for (size_t i = 0; i < nidx; ++i)
          std::memcpy(p->data.data() + static_cast<size_t>(idx[i]) * p->width,
                      vals + i * p->width, p->width * 4);
        mark(*p);
        break;
      }
      case PsfType::kParamClear: {
        Param* p = store_.get(key);
        if (!p || skip_apply) break;
        std::unique_lock<std::shared_mutex> g(p->mu);
        std::fill(p->data.begin(), p->data.end(), 0.0f);
        std::fill(p->accum.begin(), p->accum.end(), 0.0f);
        std::fill(p->accum2.begin(), p->accum2.end(), 0.0f);
        p->step = 0;
        if (!p->versions.empty()) std::fill(p->versions.begin(), p->versions.end(), 0);
        mark(*p);
        break;
      }
      case PsfType::kParamSave: {
        Param* p = store_.get(key);
        check(p, key);
        save_param_file(*p, shard_path(req.args[0].as_str(), key));
        break;
      }
      case PsfType::kParamLoad: {
        // unlike the reference's LoadParam, the param need not pre-exist:
        // the shard file carries full meta (+optimizer slots), so a blank
        // replacement server restores state without any worker-side re-init
        load_param_file(key, shard_path(req.args[0].as_str(), key));
        if (Param* lp = store_.get(key)) {
          std::unique_lock<std::shared_mutex> g(lp->mu);
          mark(*lp);
        }
        break;
      }
      case PsfType::kSyncEmbedding: {
        // Bounded-staleness pull (reference PSFhandle_embedding.cc:30-65):
        // return rows never seen by the client (cver == -1) or whose server
        // version ran more than `bound` updates ahead of the client's.
        // args: i64 local rows, i64 client versions, i64[bound]
        Param* p = store_.get(key);
        check(p, key);
        std::shared_lock<std::shared_mutex> g(p->mu);
        const int64_t* idx = req.args[0].as_i64();
        const int64_t* cver = req.args[1].as_i64();
        int64_t bound = req.args[2].as_i64()[0];
        size_t nidx = req.args[0].n_i64();
        check_rows(*p, idx, nidx);
        std::vector<int32_t> sel;
        std::vector<float> rows;
        std::vector<int64_t> vers;
        for (size_t i = 0; i < nidx; ++i) {
          size_t r = static_cast<size_t>(idx[i]);
          if (cver[i] == -1 || p->versions[r] - cver[i] > bound) {
            sel.push_back(static_cast<int32_t>(i));
            rows.insert(rows.end(), p->data.begin() + r * p->width,
                        p->data.begin() + (r + 1) * p->width);
            vers.push_back(p->versions[r]);
          }
        }
        rsp->args.push_back(Arg::i32(sel.data(), sel.size()));
        rsp->args.push_back(rsp_value(req, rows.data(), rows.size(),
                                      p->width));
        rsp->args.push_back(Arg::i64(vers.data(), vers.size()));
        break;
      }
      case PsfType::kPushEmbedding: {
        // args: i64 local rows, f32 grads, i64 per-row update counts
        // (reference PSFhandle_embedding.cc:5-28: accumulate + ver += updates)
        Param* p = store_.get(key);
        check(p, key);
        std::unique_lock<std::shared_mutex> g(p->mu);
        const int64_t* idx = req.args[0].as_i64();
        size_t nidx = req.args[0].n_i64();
        check_rows(*p, idx, nidx);  // before any mutation
        if (value_count(req.args[1]) != nidx * p->width ||
            req.args[2].n_i64() != nidx)
          throw std::runtime_error(
              "kPushEmbedding arg length mismatch: " +
              std::to_string(value_count(req.args[1])) + " grads / " +
              std::to_string(req.args[2].n_i64()) + " ups for " +
              std::to_string(nidx) + " rows x width " +
              std::to_string(p->width));
        if (skip_apply) break;
        const float* grads = value_f32(req.args[1], &qscratch,
                                       nidx * p->width);
        begin_req(*p);
        const int64_t* ups = req.args[2].as_i64();
        for (size_t i = 0; i < nidx; ++i) {
          size_t r = static_cast<size_t>(idx[i]);
          if (validate_)
            for (size_t j = 0; j < p->width; ++j)
              if (!(std::fabs(grads[i * p->width + j]) < 1e3f))
                std::fprintf(stderr,
                             "[hetups VALIDATE] push tensor %d row %lld "
                             "grad[%zu]=%g nidx=%zu ups=%lld\n",
                             key, (long long)idx[i], j,
                             (double)grads[i * p->width + j], nidx,
                             (long long)ups[i]);
          apply_update(*p, r * p->width, grads + i * p->width, p->width);
          p->versions[r] += ups[i];
        }
        mark(*p);
        break;
      }
      case PsfType::kPushSyncEmbedding: {
        // push grads for rows A, then bounded-staleness sync rows B.
        // args: i64 pushA, f32 gradsA, u64 upsA, i64 syncB, u64 cverB, u64[bound]
        Param* p = store_.get(key);
        check(p, key);
        std::unique_lock<std::shared_mutex> g(p->mu);
        const int64_t* idx = req.args[0].as_i64();
        size_t nidx = req.args[0].n_i64();
        const int64_t* sidx = req.args[3].as_i64();
        const int64_t* cver = req.args[4].as_i64();
        int64_t bound = req.args[5].as_i64()[0];
        size_t ns = req.args[3].n_i64();
        // validate BOTH sides before any mutation (rejected => untouched)
        check_rows(*p, idx, nidx);
        check_rows(*p, sidx, ns);
        if (value_count(req.args[1]) != nidx * p->width ||
            req.args[2].n_i64() != nidx)
          throw std::runtime_error(
              "kPushSyncEmbedding arg length mismatch: " +
              std::to_string(value_count(req.args[1])) + " grads / " +
              std::to_string(req.args[2].n_i64()) + " ups for " +
              std::to_string(nidx) + " rows x width " +
              std::to_string(p->width));
        if (!skip_apply) {
          const float* grads = value_f32(req.args[1], &qscratch,
                                         nidx * p->width);
          begin_req(*p);
          const int64_t* ups = req.args[2].as_i64();
          for (size_t i = 0; i < nidx; ++i) {
            size_t r = static_cast<size_t>(idx[i]);
            if (validate_)
              for (size_t j = 0; j < p->width; ++j)
                if (!(std::fabs(grads[i * p->width + j]) < 1e3f))
                  std::fprintf(stderr,
                               "[hetups VALIDATE] push_sync tensor %d row "
                               "%lld grad[%zu]=%g nidx=%zu ups=%lld\n",
                               key, (long long)idx[i], j,
                               (double)grads[i * p->width + j], nidx,
                               (long long)ups[i]);
            apply_update(*p, r * p->width, grads + i * p->width, p->width);
            p->versions[r] += ups[i];
          }
          mark(*p);
        }
        std::vector<int32_t> sel;
        std::vector<float> rows;
        std::vector<int64_t> vers;
        for (size_t i = 0; i < ns; ++i) {
          size_t r = static_cast<size_t>(sidx[i]);
          if (cver[i] == -1 || p->versions[r] - cver[i] > bound) {
            sel.push_back(static_cast<int32_t>(i));
            rows.insert(rows.end(), p->data.begin() + r * p->width,
                        p->data.begin() + (r + 1) * p->width);
            vers.push_back(p->versions[r]);
          }
        }
        rsp->args.push_back(Arg::i32(sel.data(), sel.size()));
        rsp->args.push_back(rsp_value(req, rows.data(), rows.size(),
                                      p->width));
        rsp->args.push_back(Arg::i64(vers.data(), vers.size()));
        break;
      }
      case PsfType::kDataPush: {
        // arbitrary-length blob rows keyed by u64 (reference PushData — used
        // for GNN graph data). args: u64 keys, i64 lens, f32 concat values
        if (skip_apply) break;
        std::unique_lock<std::shared_mutex> g(data_mu_);
        const uint64_t* keys = req.args[0].as_u64();
        size_t nk = req.args[0].n_i64();
        const int64_t* lens = req.args[1].as_i64();
        const float* vals = req.args[2].as_f32();
        size_t off = 0;
        for (size_t i = 0; i < nk; ++i) {
          auto& blob = data_store_[{key, keys[i]}];
          blob.assign(vals + off, vals + off + lens[i]);
          off += static_cast<size_t>(lens[i]);
        }
        // data blobs are never snapshotted: flag the write as absent from
        // every snapshot so a failover re-issue re-applies it
        if (write_seq) *write_seq = ~0ull;
        break;
      }
      case PsfType::kDataPull: {
        std::shared_lock<std::shared_mutex> g(data_mu_);
        const uint64_t* keys = req.args[0].as_u64();
        size_t nk = req.args[0].n_i64();
        std::vector<float> out;
        for (size_t i = 0; i < nk; ++i) {
          auto it = data_store_.find({key, keys[i]});
          if (it == data_store_.end())
            throw std::runtime_error("DataPull: missing key");
          out.insert(out.end(), it->second.begin(), it->second.end());
        }
        rsp->args.push_back(Arg::f32(out.data(), out.size()));
        break;
      }
      case PsfType::kListParams: {
        // hetu-elastic migration inventory: flat i64 rows of
        // {key, kind, rows|len, width, otype} per stored param — what the
        // coordinator iterates to kParamSave/kParamLoad every key across
        // a key-range move
        std::vector<int64_t> flat;
        store_.for_each([&](int32_t k, Param& p) {
          std::shared_lock<std::shared_mutex> pg(p.mu);
          flat.push_back(k);
          flat.push_back(static_cast<int64_t>(p.kind));
          flat.push_back(static_cast<int64_t>(
              p.kind == ParamKind::kDense ? p.len : p.rows));
          flat.push_back(static_cast<int64_t>(p.width));
          flat.push_back(static_cast<int64_t>(p.otype));
        });
        rsp->args.push_back(Arg::i64(flat.data(), flat.size()));
        break;
      }
      case PsfType::kSetWorldVersion: {
        // arm/advance stale-epoch rejection (see serve_conn): the
        // coordinator stamps every server inside the drain window, before
        // workers resume traffic under the new membership
        if (req.args.empty() || req.args[0].size() < 8)
          throw std::runtime_error("kSetWorldVersion needs i64[version]");
        world_version_.store(
            static_cast<uint64_t>(req.args[0].as_i64()[0]),
            std::memory_order_relaxed);
        break;
      }
      case PsfType::kTestSlowApply: {
        // hetutrail fault lever (ps_slow@step[:ms]): arm a one-shot delay
        // of the next optimizer apply. Doubly gated — capi refuses to send
        // without HETU_TEST_MODE, and this server refuses to arm without
        // it, so a stray message can never slow a production server.
        if (!env_test_mode())
          throw std::runtime_error("kTestSlowApply requires HETU_TEST_MODE");
        if (req.args.empty() || req.args[0].size() < 8)
          throw std::runtime_error("kTestSlowApply needs i64[ms]");
        test_slow_ms_.store(req.args[0].as_i64()[0],
                            std::memory_order_relaxed);
        break;
      }
      case PsfType::kSnapshotNow: {
        // hetusave coordinated snapshot epoch: inside the drain window
        // (workers parked, pushes_ok == updates proven by the coordinator)
        // write one full-state snapshot NOW and report exactly which
        // version the job manifest should pin. The optional i64[epoch]
        // stamp is recorded for telemetry/ServerStats cross-checks. NOT
        // test-gated — this is the production checkpoint path.
        if (snapshot_dir_.empty())
          throw std::runtime_error(
              "kSnapshotNow: server has no DMLC_PS_SNAPSHOT_DIR");
        const int64_t epoch =
            (!req.args.empty() && req.args[0].size() >= 8)
                ? req.args[0].as_i64()[0]
                : -1;
        const uint64_t version = take_snapshot();
        last_snapshot_epoch_.store(epoch, std::memory_order_relaxed);
        const int64_t out[4] = {
            static_cast<int64_t>(version),
            static_cast<int64_t>(last_snapshot_counter_.load()),
            static_cast<int64_t>(update_count_.load()),
            epoch};
        rsp->args.push_back(Arg::i64(out, 4));
        break;
      }
      case PsfType::kServerStats: {
        // reply: i64[updates applied, updates covered by latest snapshot,
        // update counter restored from (-1 = fresh start), snapshot version,
        // live param count, requests served, apply ns total, apply count,
        // snapshot age ms (-1 = none taken by THIS incarnation), dedup-
        // ledger occupancy, CRC-rejected requests]. Slots 0-4 are the PR-4
        // lost-update accounting surface; 5-9 the telemetry health
        // extension; 10 the hetuchaos transport-hardening counter (clients
        // that ask for fewer slots still get a valid prefix — the reply is
        // length-prefixed and QueryServerStats copies min(n, len)).
        int64_t n_params = 0;
        store_.for_each([&](int32_t, Param&) { ++n_params; });
        int64_t dedup_clients;
        {
          std::lock_guard<std::mutex> cg(clients_mu_);
          dedup_clients = static_cast<int64_t>(clients_.size());
        }
        const int64_t snap_at = last_snapshot_steady_ms_.load();
        const int64_t age_ms = snap_at ? steady_now_ms() - snap_at : -1;
        int64_t stats[11] = {
            static_cast<int64_t>(update_count_.load()),
            static_cast<int64_t>(last_snapshot_counter_.load()),
            restored_counter_.load(),
            static_cast<int64_t>(snapshot_version_.load()),
            n_params,
            static_cast<int64_t>(req_count_.load()),
            static_cast<int64_t>(apply_ns_.load()),
            static_cast<int64_t>(apply_count_.load()),
            age_ms,
            dedup_clients,
            static_cast<int64_t>(crc_reject_count_.load())};
        rsp->args.push_back(Arg::i64(stats, 11));
        break;
      }
      default:
        throw std::runtime_error("server: unknown psf type " +
                                 std::to_string(req.head.type));
    }
  }

  static int64_t steady_now_ms() {
    return std::chrono::duration_cast<std::chrono::milliseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  static void check(Param* p, int32_t key) {
    if (!p)
      throw std::runtime_error("param " + std::to_string(key) +
                               " not initialized (call InitTensor first)");
  }

  // Client-supplied row ids come straight from user data; an out-of-range id
  // must become an error response to the worker, not an OOB read/write here.
  static void check_rows(const Param& p, const int64_t* idx, size_t nidx) {
    for (size_t i = 0; i < nidx; ++i)
      if (idx[i] < 0 || static_cast<size_t>(idx[i]) >= p.rows)
        throw std::runtime_error(
            "row id " + std::to_string(idx[i]) + " out of range [0, " +
            std::to_string(p.rows) + ")");
  }

  std::string shard_path(const std::string& dir, int32_t key) const {
    return dir + "/param_" + std::to_string(key) + "_shard" +
           std::to_string(rank_) + ".bin";
  }

  // Full-state shard format (v2): a dead server's replacement can rebuild
  // its store from disk with no worker cooperation (recovery-restores-state;
  // the intent of reference van.cc:47 recovery + psf/PSFunc.h:25-28
  // ParamSave/Load). Layout: i64 meta[8] = {MAGIC(-2), kind, rows|len,
  // width, otype, step, n_lrs, n_versions}, f32 lrs[], f32 data[],
  // f32 accum[], f32 accum2[], i64 versions[].
  static constexpr int64_t kShardMagicV2 = -2;

  // Returns the param's last_write_seq as of the save (read under the same
  // shared lock as the data): every write stamped <= that seq is inside the
  // file, every later one is not — take_snapshot's ledger filter key.
  uint64_t save_param_file(Param& p, const std::string& path) {
    std::shared_lock<std::shared_mutex> g(p.mu);
    // tmp + rename: a crash mid-save (the very fault this recovers from)
    // must not destroy the previous good checkpoint
    const std::string tmp = path + ".tmp";
    FILE* f = std::fopen(tmp.c_str(), "wb");
    if (!f) throw std::runtime_error("cannot open " + tmp);
    int64_t meta[8] = {kShardMagicV2,
                       static_cast<int64_t>(p.kind),
                       static_cast<int64_t>(p.rows ? p.rows : p.len),
                       static_cast<int64_t>(p.width),
                       static_cast<int64_t>(p.otype),
                       static_cast<int64_t>(p.step),
                       static_cast<int64_t>(p.lrs.size()),
                       static_cast<int64_t>(p.versions.size())};
    std::fwrite(meta, sizeof(meta), 1, f);
    std::fwrite(p.lrs.data(), 4, p.lrs.size(), f);
    std::fwrite(p.data.data(), 4, p.data.size(), f);
    std::fwrite(p.accum.data(), 4, p.accum.size(), f);
    std::fwrite(p.accum2.data(), 4, p.accum2.size(), f);
    std::fwrite(p.versions.data(), 8, p.versions.size(), f);
    std::fclose(f);
    if (std::rename(tmp.c_str(), path.c_str()) != 0)
      throw std::runtime_error("cannot rename " + tmp + " -> " + path);
    return p.last_write_seq;
  }

  void load_param_file(int32_t key, const std::string& path) {
    FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) throw std::runtime_error("cannot open " + path);
    struct Closer { FILE* f; ~Closer() { std::fclose(f); } } closer{f};
    int64_t head;
    if (std::fread(&head, sizeof(head), 1, f) != 1)
      throw std::runtime_error("truncated " + path);
    if (head != kShardMagicV2) {
      // v1 layout: {kind, rows|len, width} + data only, into an existing
      // param (pre-v2 checkpoints)
      int64_t rest[2];
      if (std::fread(rest, sizeof(rest), 1, f) != 1)
        throw std::runtime_error("truncated " + path);
      Param* p1 = store_.get(key);
      if (!p1 || p1->data.empty())
        throw std::runtime_error(
            "v1 shard " + path + " cannot restore an uninitialized param");
      std::unique_lock<std::shared_mutex> g(p1->mu);
      std::vector<float> data(p1->data.size());
      if (std::fread(data.data(), 4, data.size(), f) != data.size())
        throw std::runtime_error("size mismatch loading " + path);
      p1->data = std::move(data);
      return;
    }
    // parse EVERYTHING into locals first: a truncated file must not leave a
    // phantom half-restored param in the store (check() would then pass and
    // pushes would write through empty buffers)
    int64_t meta[7];
    if (std::fread(meta, sizeof(meta), 1, f) != 1)
      throw std::runtime_error("truncated " + path);
    Param tmp;
    tmp.kind = static_cast<ParamKind>(meta[0]);
    if (tmp.kind == ParamKind::kDense) {
      tmp.len = static_cast<size_t>(meta[1]);
      tmp.rows = 0;
      tmp.width = 1;
    } else {
      tmp.rows = static_cast<size_t>(meta[1]);
      tmp.width = static_cast<size_t>(meta[2]);
      tmp.len = tmp.rows * tmp.width;
    }
    tmp.otype = static_cast<OptType>(meta[3]);
    tmp.step = static_cast<uint64_t>(meta[4]);
    tmp.lrs.assign(static_cast<size_t>(meta[5]), 0.0f);
    tmp.data.assign(tmp.len, 0.0f);
    auto read_f32 = [&](std::vector<float>& v) {
      if (!v.empty() && std::fread(v.data(), 4, v.size(), f) != v.size())
        throw std::runtime_error("size mismatch loading " + path);
    };
    read_f32(tmp.lrs);
    read_f32(tmp.data);
    alloc_slots(tmp);
    read_f32(tmp.accum);
    read_f32(tmp.accum2);
    tmp.versions.assign(static_cast<size_t>(meta[6]), 0);
    if (!tmp.versions.empty() &&
        std::fread(tmp.versions.data(), 8, tmp.versions.size(), f) !=
            tmp.versions.size())
      throw std::runtime_error("size mismatch loading " + path);
    Param* p = store_.get_or_create(key);
    std::unique_lock<std::shared_mutex> g(p->mu);
    p->kind = tmp.kind;
    p->len = tmp.len;
    p->rows = tmp.rows;
    p->width = tmp.width;
    p->otype = tmp.otype;
    p->step = tmp.step;
    p->lrs = std::move(tmp.lrs);
    p->data = std::move(tmp.data);
    p->accum = std::move(tmp.accum);
    p->accum2 = std::move(tmp.accum2);
    p->versions = std::move(tmp.versions);
  }

 public:
  // Restore this rank's state from `dir` (invoked at startup when
  // DMLC_PS_RESTORE_DIR is set). Two layouts:
  //  - a continuous-snapshot root (this server's LATEST_s<rank> pointer
  //    exists): follow it to the freshest COMPLETE snapshot — params +
  //    optimizer slots + row versions + the update-counter stamp + the
  //    per-client dedup ledger (so an in-flight resend of an already-
  //    snapshotted request is not double-applied);
  //  - a plain ParamSave directory: scan for shard files (legacy path).
  int restore_from(const std::string& dir) {
    namespace fs = std::filesystem;
    const fs::path ptr = fs::path(dir) / ("LATEST_s" + std::to_string(rank_));
    std::error_code ec;
    if (!fs::exists(ptr, ec)) return restore_scan_dir(dir);
    std::string name;
    {
      FILE* f = std::fopen(ptr.string().c_str(), "rb");
      if (!f) return restore_scan_dir(dir);
      char buf[256] = {0};
      size_t k = std::fread(buf, 1, sizeof(buf) - 1, f);
      std::fclose(f);
      name.assign(buf, k);
      while (!name.empty() && (name.back() == '\n' || name.back() == ' '))
        name.pop_back();
    }
    const fs::path snap = fs::path(dir) / name;
    if (!fs::exists(snap, ec)) {
      std::fprintf(stderr,
                   "[hetups] server %d: LATEST pointer names missing "
                   "snapshot %s; falling back to directory scan\n",
                   rank_, name.c_str());
      return restore_scan_dir(dir);
    }
    int n = restore_scan_dir(snap.string());
    load_manifest((snap / "manifest.bin").string());
    std::fprintf(stderr,
                 "[hetups] server %d restored %d param shard(s) from "
                 "snapshot %s (version %llu, update counter %lld)\n",
                 rank_, n, name.c_str(),
                 (unsigned long long)snapshot_version_.load(),
                 (long long)restored_counter_.load());
    return n;
  }

 private:
  int restore_scan_dir(const std::string& dir) {
    namespace fs = std::filesystem;
    const std::string suffix = "_shard" + std::to_string(rank_) + ".bin";
    int n = 0;
    std::error_code ec;
    for (const auto& ent : fs::directory_iterator(dir, ec)) {
      const std::string name = ent.path().filename().string();
      if (name.rfind("param_", 0) != 0) continue;
      if (name.size() <= suffix.size() + 6 ||
          name.compare(name.size() - suffix.size(), suffix.size(), suffix))
        continue;
      const std::string key_str =
          name.substr(6, name.size() - suffix.size() - 6);
      if (key_str.empty() ||
          key_str.find_first_not_of("0123456789") != std::string::npos)
        continue;  // stray file; not one of ours
      try {
        load_param_file(std::stoi(key_str), ent.path().string());
        ++n;
      } catch (const std::exception& e) {
        // one bad shard must not keep the replacement out of the cluster;
        // the affected param surfaces as "not initialized" to workers
        std::fprintf(stderr, "[hetups] server %d: skipping shard %s: %s\n",
                     rank_, name.c_str(), e.what());
      }
    }
    return n;
  }

  // Snapshot manifest (binary): i64 magic, u64 version, u64 update counter,
  // u64 n_params, u64 n_clients, then {i64 client_id, u64 last_req_id} per
  // client. The counter stamp is the lost-update ledger; the client map is
  // the resend-dedup ledger, captured AFTER the param files and filtered by
  // write provenance: a client whose last applied write is provably absent
  // from the saved shard files (its ClientSlot::write_seq is newer than the
  // seq its param's file was saved at) is left OUT, so a failover re-issue
  // re-applies the write; every entry that IS present implies its write is
  // inside the files, so a re-issue can skip_apply safely. Net: never a
  // double-apply, and never a silently-acked lost write.
  static constexpr int64_t kManifestMagic = -7001;

  void load_manifest(const std::string& path) {
    FILE* f = std::fopen(path.c_str(), "rb");
    if (!f) {
      std::fprintf(stderr, "[hetups] server %d: snapshot has no manifest %s"
                   " (counters start at 0)\n", rank_, path.c_str());
      return;
    }
    struct Closer { FILE* f; ~Closer() { std::fclose(f); } } closer{f};
    int64_t magic;
    uint64_t head[4];
    if (std::fread(&magic, 8, 1, f) != 1 || magic != kManifestMagic ||
        std::fread(head, sizeof(head), 1, f) != 1) {
      std::fprintf(stderr, "[hetups] server %d: bad manifest %s\n", rank_,
                   path.c_str());
      return;
    }
    snapshot_version_.store(head[0]);
    update_count_.store(head[1]);
    last_snapshot_counter_.store(head[1]);
    restored_counter_.store(static_cast<int64_t>(head[1]));
    for (uint64_t i = 0; i < head[3]; ++i) {
      int64_t cid;
      uint64_t last_id;
      if (std::fread(&cid, 8, 1, f) != 1 || std::fread(&last_id, 8, 1, f) != 1)
        break;
      ClientSlot* slot = client_slot(static_cast<int32_t>(cid));
      std::lock_guard<std::mutex> g(slot->mu);
      slot->last_id = last_id;
      slot->has_rsp = false;  // payload not persisted; resend => skip_apply
      slot->write_seq = 0;    // the write is inside the restored params
    }
  }

  void snapshot_loop() {
    using Clock = std::chrono::steady_clock;
    // wake faster than the snapshot cadence: a param-SET change (Executor
    // init, late sparse-table registration) must reach disk promptly —
    // with a plain snapshot_ms_ wait, a server killed inside the first
    // interval after init would hand its replacement a snapshot with
    // whole tensors missing (or none at all), an unrecoverable
    // unknown-tensor failover instead of interval-bounded lost updates
    const auto poll = std::chrono::milliseconds(std::min(snapshot_ms_, 250));
    auto last_tick = Clock::now();
    std::unique_lock<std::mutex> g(snap_mu_);
    while (!snap_cv_.wait_for(g, poll, [this] { return snap_stop_; })) {
      g.unlock();
      const auto now = Clock::now();
      const bool interval_elapsed =
          now - last_tick >= std::chrono::milliseconds(snapshot_ms_);
      if (interval_elapsed) last_tick = now;
      try {
        maybe_snapshot(interval_elapsed);
      } catch (const std::exception& e) {
        // snapshotting must never take the serving path down with it
        std::fprintf(stderr, "[hetups] server %d: snapshot failed: %s\n",
                     rank_, e.what());
      }
      g.lock();
    }
  }

  void maybe_snapshot(bool interval_elapsed) {
    uint64_t counter = update_count_.load();
    size_t n_params = 0;
    store_.for_each([&](int32_t, Param&) { ++n_params; });
    // a changed param set snapshots NOW (between interval ticks); pure
    // update traffic keeps the configured DMLC_PS_SNAPSHOT_MS cadence
    const bool params_changed =
        n_params != last_snapshot_params_.load() ||
        (snapshot_version_.load() == 0 && n_params > 0);
    if (!params_changed && !interval_elapsed)
      return;
    // idle skip: nothing new since the last complete snapshot. The write
    // generation is what catches mutations that do NOT tick the update
    // counter (ParamAssign/AssignRows/Clear/Load) — keying on the counter
    // alone would leave an acked assign unsnapshotted forever, a silently
    // lost write on failover. Param-count change alone (init-only, zero
    // updates) still snapshots, so a replacement never comes up without
    // the tables' init state.
    if (!params_changed &&
        counter == last_snapshot_counter_.load() &&
        write_seq_gen_.load() == last_snapshot_write_seq_ &&
        snapshot_version_.load() > 0)
      return;
    take_snapshot();
  }

  // One atomic, versioned snapshot: write everything into a hidden tmp dir,
  // rename it into place, then flip the LATEST pointer (tmp+rename as well).
  // A crash at ANY point leaves either the previous complete snapshot or a
  // garbage .tmp dir that restore never looks at. Runs entirely under the
  // per-param shared locks — the serving path is never paused. Returns the
  // published version (hetusave's kSnapshotNow reports it to the
  // coordinator so the job manifest can pin this exact snapshot).
  uint64_t take_snapshot() {
    namespace fs = std::filesystem;
    // serializes the periodic thread against the test hook's final snapshot
    std::lock_guard<std::mutex> take_g(snap_take_mu_);
    const uint64_t counter = update_count_.load();  // BEFORE params: the
    // stamp may under-claim coverage (updates landing mid-snapshot) but
    // never over-claim — reported lost-update counts never understate.
    const uint64_t wseq_at_start = write_seq_gen_.load();  // same logic:
    // a write landing mid-snapshot bumps the gen past this sample, so the
    // next idle check sees it and snapshots again
    const uint64_t version = snapshot_version_.fetch_add(1) + 1;
    const std::string name = "snap_s" + std::to_string(rank_) + "_v" +
                             std::to_string(version);
    const fs::path root(snapshot_dir_);
    const fs::path tmp = root / ("." + name + ".tmp");
    std::error_code ec;
    // a predecessor that died mid-cycle may have left this very tmp dir
    // (it restored from the same LATEST and picked the same next version);
    // stale shard files mixed into the fresh dump would corrupt it
    fs::remove_all(tmp, ec);
    fs::create_directories(tmp, ec);
    if (ec)
      throw std::runtime_error("cannot create snapshot dir " + tmp.string());
    std::vector<int32_t> keys;
    store_.for_each([&](int32_t k, Param&) { keys.push_back(k); });
    std::unordered_map<int32_t, uint64_t> file_seq;  // key -> seq-at-save
    for (int32_t k : keys) {
      Param* p = store_.get(k);
      if (p && !p->data.empty())
        file_seq[k] = save_param_file(
            *p, (tmp / ("param_" + std::to_string(k) + "_shard" +
                        std::to_string(rank_) + ".bin"))
                    .string());
    }
    // dedup ledger AFTER params (see kManifestMagic comment for why)
    std::vector<std::pair<int64_t, uint64_t>> ledger;
    {
      std::vector<std::pair<int32_t, ClientSlot*>> slots;
      {
        std::lock_guard<std::mutex> g(clients_mu_);
        for (auto& kv : clients_) slots.push_back({kv.first, kv.second.get()});
      }
      for (auto& [cid, slot] : slots) {
        // No live caller reaches here holding a slot mutex (serve_conn
        // drops the kSnapshotNow requester's slot before handle() — the
        // ABBA-deadlock fix against the periodic snapshot thread), so
        // every slot locks normally; the in-flight requester's last_id
        // still names the last RECORDED request, which is exactly right.
        // g_dedup_slot_held stays as same-thread self-deadlock defense
        // for any future caller that does hold one: read that slot
        // lock-free instead of re-locking.
        std::unique_lock<std::mutex> g;
        if (static_cast<const void*>(slot) != g_dedup_slot_held)
          g = std::unique_lock<std::mutex>(slot->mu);
        if (slot->last_id == 0) continue;
        if (slot->write_seq > 0) {
          // provenance filter: the client's last write landed AFTER its
          // param's file was saved (or the param was never saved) — it is
          // provably NOT in this snapshot, so leave the client out of the
          // ledger and let a failover re-issue RE-APPLY it. Including it
          // would make the re-issue a skip_apply duplicate: a silently
          // acked lost update.
          auto it = file_seq.find(slot->write_key);
          if (it == file_seq.end() || slot->write_seq > it->second) continue;
        }
        ledger.push_back({cid, slot->last_id});
      }
    }
    {
      FILE* f = std::fopen((tmp / "manifest.bin").string().c_str(), "wb");
      if (!f) throw std::runtime_error("cannot write snapshot manifest");
      int64_t magic = kManifestMagic;
      uint64_t head[4] = {version, counter, keys.size(), ledger.size()};
      std::fwrite(&magic, 8, 1, f);
      std::fwrite(head, sizeof(head), 1, f);
      for (auto& [cid, last_id] : ledger) {
        std::fwrite(&cid, 8, 1, f);
        std::fwrite(&last_id, 8, 1, f);
      }
      std::fclose(f);
    }
    // a predecessor may have published this version but died before
    // flipping LATEST — no reader ever saw it, and renaming onto a
    // non-empty directory fails
    fs::remove_all(root / name, ec);
    fs::rename(tmp, root / name, ec);
    if (ec) throw std::runtime_error("cannot publish snapshot " + name);
    // crash-window fault hook pinning the pointer-flip atomicity contract:
    // die AFTER the snapshot dir is published but BEFORE the pointer moves,
    // exactly when the matching version lands. Restore must then follow the
    // still-pointing-at-the-predecessor LATEST to a COMPLETE snapshot —
    // tests/test_recovery.py holds this. Inert without HETU_TEST_MODE.
    if (env_test_mode()) {
      const char* kill_v = std::getenv("HETU_PS_TEST_KILL_BEFORE_POINTER");
      if (kill_v && std::strtoull(kill_v, nullptr, 10) == version)
        std::_Exit(137);
    }
    // flip the pointer
    const fs::path ptr_tmp = root / (".LATEST_s" + std::to_string(rank_) +
                                     ".tmp");
    {
      FILE* f = std::fopen(ptr_tmp.string().c_str(), "wb");
      if (!f) throw std::runtime_error("cannot write snapshot pointer");
      std::fwrite(name.data(), 1, name.size(), f);
      std::fclose(f);
    }
    fs::rename(ptr_tmp, root / ("LATEST_s" + std::to_string(rank_)), ec);
    if (ec) throw std::runtime_error("cannot flip snapshot pointer");
    last_snapshot_counter_.store(counter);
    last_snapshot_params_ = keys.size();
    last_snapshot_write_seq_ = wseq_at_start;
    last_snapshot_steady_ms_.store(steady_now_ms());
    // prune: keep this snapshot and its predecessor (the pointer flip and a
    // racing reader of the old snapshot both stay safe); also sweep stale
    // .tmp dirs a crashed predecessor abandoned — each holds a full copy of
    // PS state and nothing else ever cleans them
    const std::string prefix = "snap_s" + std::to_string(rank_) + "_v";
    const std::string tprefix = "." + prefix;
    for (const auto& ent : fs::directory_iterator(root, ec)) {
      const std::string n = ent.path().filename().string();
      const bool is_tmp = n.size() > tprefix.size() + 4 &&
                          n.rfind(tprefix, 0) == 0 &&
                          n.compare(n.size() - 4, 4, ".tmp") == 0;
      const std::string v =
          is_tmp ? n.substr(tprefix.size(), n.size() - tprefix.size() - 4)
          : n.rfind(prefix, 0) == 0 ? n.substr(prefix.size())
                                    : std::string();
      if (v.empty() || v.find_first_not_of("0123456789") != std::string::npos)
        continue;
      if (is_tmp ? std::stoull(v) < version : std::stoull(v) + 1 < version)
        fs::remove_all(ent.path(), ec);
    }
    return version;
  }

  struct PairHash {
    size_t operator()(const std::pair<int32_t, uint64_t>& p) const {
      return std::hash<uint64_t>()(p.second * 1315423911u ^
                                   static_cast<uint64_t>(p.first));
    }
  };

  int rank_;
  std::string host_;
  int port_;
  bool validate_ = false;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;

  // -- continuous snapshots / HA bookkeeping ------------------------------
  std::string snapshot_dir_;             // DMLC_PS_SNAPSHOT_DIR ("" = off)
  int snapshot_ms_ = 5000;               // DMLC_PS_SNAPSHOT_MS
  std::thread snapshot_thread_;
  std::mutex snap_mu_;
  std::condition_variable snap_cv_;
  std::mutex snap_take_mu_;
  bool snap_stop_ = false;
  std::atomic<uint64_t> update_count_{0};          // optimizer updates applied
  std::atomic<uint64_t> last_snapshot_counter_{0}; // covered by latest snap
  std::atomic<uint64_t> snapshot_version_{0};
  std::atomic<uint64_t> write_seq_gen_{0};         // write-provenance stamps
  std::atomic<int64_t> restored_counter_{-1};      // -1 = fresh start
  // atomics, not snapshot-thread-private: the HETU_PS_TEST_EXIT hook runs
  // take_snapshot on a serve thread concurrently with maybe_snapshot's
  // idle-check reads (take_snapshot itself serializes via snap_take_mu_)
  std::atomic<size_t> last_snapshot_params_{0};
  std::atomic<uint64_t> last_snapshot_write_seq_{0};
  // -- telemetry health counters (kServerStats slots 5-10) -----------------
  std::atomic<uint64_t> req_count_{0};      // requests served (all types)
  std::atomic<uint64_t> crc_reject_count_{0};  // hetuchaos: CRC rejects
  std::atomic<uint64_t> apply_ns_{0};       // wall ns spent in write applies
  std::atomic<uint64_t> apply_count_{0};
  std::atomic<int64_t> last_snapshot_steady_ms_{0};  // 0 = none yet
  std::atomic<int64_t> last_snapshot_epoch_{-1};  // hetusave epoch stamp on
  // the latest kSnapshotNow-driven snapshot; -1 = none this incarnation
  long test_exit_after_updates_ = -1;              // test hook (gated)
  bool test_exit_snap_ = false;
  // hetutrail: per-request span ring + ps_slow fault state
  static constexpr size_t kTrailFlushEvery = 256;
  std::string trail_path_;                         // "" = trail off
  size_t trail_cap_ = 65536;
  int64_t trail_max_bytes_ = 0;                    // HETU_TRAIL_MAX_MB
  int64_t trail_file_bytes_ = 0;                   // guarded by trail_mu_
  std::mutex trail_mu_;
  std::vector<SrvSpan> trail_ring_;
  uint64_t trail_dropped_ = 0;                     // guarded by trail_mu_
  FILE* trail_f_ = nullptr;                        // guarded by trail_mu_
  std::atomic<int64_t> test_slow_ms_{0};           // kTestSlowApply (gated)
  // hetu-elastic membership epoch (0 = rejection unarmed); set via
  // kSetWorldVersion, compared against MsgHeader::world_ver in serve_conn
  std::atomic<uint64_t> world_version_{0};
  ConnThreads conn_threads_;
  std::mutex fds_mu_;
  std::vector<int> live_fds_;
  std::mutex clients_mu_;
  std::unordered_map<int32_t, std::unique_ptr<ClientSlot>> clients_;
  Store store_;
  std::shared_mutex data_mu_;
  std::unordered_map<std::pair<int32_t, uint64_t>, std::vector<float>, PairHash>
      data_store_;
};

}  // namespace hetups
