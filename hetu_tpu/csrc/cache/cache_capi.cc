// extern "C" surface of the embedding cache, consumed via ctypes by
// hetu_tpu/cstable.py (reference: pybind11 module defined in
// src/hetu_cache/src/python_api.cc, consumed by python/hetu/cstable.py).
//
// Handles are opaque pointers; async ops return tickets redeemed by
// CacheWait. Compiled into libhetu_ps.so so the cache shares the process's
// PS worker agent (the reference links hetu_cache against ps-lite the same
// way).

#include <cstring>
#include <sstream>
#include <string>

#include "cache/cache.h"

namespace hetups {
PsWorker* global_worker();  // defined in ps/capi.cc
}

namespace {
thread_local std::string t_cache_error;
}

extern "C" {

const char* CacheLastError() {
  static thread_local std::string report;
  report = t_cache_error;
  t_cache_error.clear();
  return report.c_str();
}

// policy: 0=LRU 1=LFU 2=LFUOpt
void* CacheCreate(int policy, long limit, long length, long width,
                  int node_id) {
  try {
    hetups::PsWorker* ps = hetups::global_worker();
    if (!ps) throw std::runtime_error("cache requires a PS worker (Init first)");
    switch (policy) {
      case 0:
        return new hetucache::LRUCache(limit, length, width, node_id, ps);
      case 1:
        return new hetucache::LFUCache(limit, length, width, node_id, ps);
      case 2:
        return new hetucache::LFUOptCache(limit, length, width, node_id, ps);
      default:
        throw std::runtime_error("unknown cache policy " +
                                 std::to_string(policy));
    }
  } catch (const std::exception& e) {
    t_cache_error = e.what();
    return nullptr;
  }
}

void CacheDestroy(void* h) {
  delete static_cast<hetucache::CacheBase*>(h);
}

void CacheSetBounds(void* h, long pull_bound, long push_bound) {
  auto* c = static_cast<hetucache::CacheBase*>(h);
  c->pull_bound = pull_bound;
  c->push_bound = push_bound;
}

long CacheEmbeddingLookup(void* h, const unsigned long long* keys, long n,
                          float* dest) {
  return static_cast<hetucache::CacheBase*>(h)->lookup_async(
      reinterpret_cast<const hetucache::cache_key_t*>(keys),
      static_cast<size_t>(n), dest);
}

long CacheEmbeddingUpdate(void* h, const unsigned long long* keys,
                          const float* grads, long n) {
  return static_cast<hetucache::CacheBase*>(h)->update_async(
      reinterpret_cast<const hetucache::cache_key_t*>(keys), grads,
      static_cast<size_t>(n));
}

long CacheEmbeddingPushPull(void* h, const unsigned long long* pull_keys,
                            long n_pull, float* dest,
                            const unsigned long long* push_keys,
                            const float* grads, long n_push) {
  return static_cast<hetucache::CacheBase*>(h)->push_pull_async(
      reinterpret_cast<const hetucache::cache_key_t*>(pull_keys),
      static_cast<size_t>(n_pull), dest,
      reinterpret_cast<const hetucache::cache_key_t*>(push_keys), grads,
      static_cast<size_t>(n_push));
}

// returns 0 on success, sets CacheLastError otherwise
int CacheWait(void* h, long ticket) {
  std::string err = static_cast<hetucache::CacheBase*>(h)->wait(ticket);
  if (err.empty()) return 0;
  t_cache_error = err;
  return -1;
}

long CacheSize(void* h) {
  auto* c = static_cast<hetucache::CacheBase*>(h);
  std::lock_guard<std::mutex> g(c->mtx);
  return static_cast<long>(c->size());
}

long CacheLimit(void* h) {
  return static_cast<long>(static_cast<hetucache::CacheBase*>(h)->limit());
}

void CacheBypass(void* h, int enable) {
  static_cast<hetucache::CacheBase*>(h)->set_bypass(enable != 0);
}

// enable: 0 = off, 1 = full per-batch log + rollup (the reference perf
// surface), 2 = rollup-only (bounded memory; what telemetry arms)
void CachePerfEnabled(void* h, int enable) {
  auto* c = static_cast<hetucache::CacheBase*>(h);
  c->set_perf_enabled(enable != 0);
  c->set_perf_log(enable != 2);
}

// O(1) cumulative perf rollup: fills up to n of [batches, evictions,
// pull_miss, pull_uniq, transfered, num_all] — the telemetry poll's
// cheap alternative to re-serializing the whole per-batch log below
void CachePerfRollup(void* h, long long* out, int n) {
  auto v = static_cast<hetucache::CacheBase*>(h)->perf_rollup();
  for (int i = 0; i < n && i < static_cast<int>(v.size()); ++i) out[i] = v[i];
}

// JSON array of per-batch perf dicts (reference cstable.py perf property)
const char* CachePerfJson(void* h) {
  static thread_local std::string out;
  auto perf = static_cast<hetucache::CacheBase*>(h)->perf();
  std::ostringstream os;
  os << "[";
  for (size_t i = 0; i < perf.size(); ++i) {
    const auto& p = perf[i];
    if (i) os << ",";
    os << "{\"type\":\"" << p.type << "\",\"is_full\":"
       << (p.is_full ? "true" : "false") << ",\"num_all\":" << p.num_all
       << ",\"num_unique\":" << p.num_unique << ",\"num_miss\":" << p.num_miss
       << ",\"num_evict\":" << p.num_evict
       << ",\"num_transfered\":" << p.num_transfered
       << ",\"time\":" << p.time_ms << "}";
  }
  os << "]";
  out = os.str();
  return out.c_str();
}

int CacheCount(void* h, unsigned long long key) {
  auto* c = static_cast<hetucache::CacheBase*>(h);
  std::lock_guard<std::mutex> g(c->mtx);
  return c->count(key);
}

// returns 1 if present; fills out/version/updates (each nullable)
int CacheLookupOne(void* h, unsigned long long key, float* out, long* version,
                   long* updates) {
  hetucache::version_t v, u;
  bool found = static_cast<hetucache::CacheBase*>(h)->lookup_one(key, out, &v,
                                                                &u);
  if (!found) return 0;
  if (version) *version = v;
  if (updates) *updates = u;
  return 1;
}

void CacheInsertOne(void* h, unsigned long long key, const float* data) {
  static_cast<hetucache::CacheBase*>(h)->insert_one(key, data);
}

// fills up to cap keys, returns the total count
long CacheKeys(void* h, unsigned long long* out, long cap) {
  auto* c = static_cast<hetucache::CacheBase*>(h);
  std::lock_guard<std::mutex> g(c->mtx);
  auto ks = c->keys();
  long n = static_cast<long>(ks.size());
  for (long i = 0; i < n && i < cap; ++i) out[i] = ks[i];
  return n;
}

const char* CacheRepr(void* h) {
  static thread_local std::string out;
  out = static_cast<hetucache::CacheBase*>(h)->repr();
  return out.c_str();
}

}  // extern "C"
